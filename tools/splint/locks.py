"""splint lock-set analysis — the engine under SPL014/SPL015/SPL017.

PRs 6 and 11 turned this codebase into a genuinely concurrent system:
worker threads, a heartbeat thread, flock + atomic-rename lease and
journal protocols.  The review-stage bug class that kept surfacing
(fsync under the server lock, the zombie-commit fence, the held-lease
leak) is *lock discipline* — which structure is guarded by which lock,
which locks nest in which order, and what may NOT happen while one is
held.  This module derives that discipline statically:

Lock discovery
    A lock is (a) a module-level or ``self.``-attribute binding whose
    initializer contains a ``threading.Lock/RLock/Condition/
    Semaphore/BoundedSemaphore`` call (wrapping helpers like
    ``lockcheck.guard_lock(threading.Lock())`` are seen through — the
    factory call is found anywhere inside the assignment value), or
    (b) a ``@contextlib.contextmanager`` method whose body calls
    ``fcntl.flock`` (the flock-sidecar wrappers: ``FleetMember.
    _locked``), or (c) an inline ``fcntl.flock(fd, LOCK_EX)`` call.
    Canonical ids are file- and class-qualified
    (``splatt_tpu/serve.py::Server._lock``,
    ``splatt_tpu/fleet.py::FleetMember._locked()``,
    ``...::flock@append_line``) so two classes' ``self._lock`` never
    alias.

Lock-set walk (:func:`lock_walk`)
    A must-hold analysis over one function body: ``with lock:`` holds
    for exactly the with-body (AST nesting is the ground truth —
    no CFG approximation needed), ``lock.acquire()``/``release()``
    holds between the calls within one statement sequence, and flock
    LOCK_EX/LOCK_UN likewise.  Nested ``def``/``class`` bodies start
    EMPTY (a closure runs later, not under the enclosing lock).
    Acquire/release effects inside a branch do not escape the branch
    (documented imprecision — a conditional release is treated as
    balanced).

Call summaries (:class:`ProjectLocks`)
    Per-function "locks acquired somewhere inside" and "contains a
    blocking verb", closed transitively over a deliberately
    conservative call resolution: ``self.f()`` resolves within the
    class, ``self.attr.f()`` resolves only when ``self.attr =
    ClassName(...)`` is visible in the same file, ``module.f()``
    through the import alias map, and bare ``f()`` within the file.
    Unresolvable receivers (``self._queue.append``) contribute
    nothing — a list's ``append`` must never inherit
    ``Journal.append``'s fsync.

SPL014 consumes the walk + the configured shared-state map; SPL015
consumes the acquisition-order edges (project-wide cycle check);
SPL017 consumes the blocking summaries on configured hot paths.  The
known imprecision is documented in docs/static-analysis.md: aliases
(``j = self._jobs[jid]``) are not tracked, containers hide their
elements, and caller-holds-the-lock helpers are exempted by the
``_locked``-suffix naming convention rather than interprocedural
lock-context inference.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Set, Tuple

_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}

#: call dotted-name tails that BLOCK the calling thread (SPL017's
#: direct verbs); `.join`/`.wait` are handled shape-sensitively below
_BLOCKING_FNS = {
    "os.fsync": "fsync", "fcntl.flock": "flock", "time.sleep": "sleep",
    "subprocess.run": "subprocess", "subprocess.Popen": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
}


#: factories whose locks may be re-taken by the holding thread — a
#: self-edge on these is not a deadlock
_REENTRANT_FACTORIES = {"threading.RLock", "threading.Condition"}


def _contains_lock_factory(ctx, expr) -> Optional[str]:
    """The lock-factory dotted name found anywhere inside `expr`
    (``lockcheck.guard_lock(threading.Lock())`` is seen through), or
    None when the expression builds no lock."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and \
                (ctx.resolve(n.func) or "") in _LOCK_FACTORIES:
            return ctx.resolve(n.func)
    return None


def _is_flock_call(ctx, call) -> Optional[str]:
    """'acquire'/'release' when `call` is an ``fcntl.flock`` with a
    recognizable LOCK_EX/LOCK_SH vs LOCK_UN flag, else None."""
    if not isinstance(call, ast.Call):
        return None
    if (ctx.resolve(call.func) or "") != "fcntl.flock":
        return None
    if len(call.args) < 2:
        return None
    names = {getattr(n, "attr", getattr(n, "id", None))
             for n in ast.walk(call.args[1])}
    if "LOCK_UN" in names:
        return "release"
    return "acquire"


def iter_scope_functions(tree):
    """Yield ``(fn, class_name)`` for every module-level function and
    class method (class_name None for module level).  Function-nested
    defs are reached by :func:`lock_walk`'s own recursion."""
    def visit(body, cls):
        for s in body:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield s, cls
            elif isinstance(s, ast.ClassDef):
                yield from visit(s.body, s.name)

    yield from visit(tree.body, None)


class FileLocks:
    """Lock discovery for one analyzed file (see module docstring)."""

    def __init__(self, ctx):
        self.ctx = ctx
        #: module-global lock name -> canonical id
        self.module_locks: Dict[str, str] = {}
        #: (class, attr) -> canonical id for ``self.attr`` locks
        self.attr_locks: Dict[Tuple[str, str], str] = {}
        #: (class, fname) -> canonical id for flock-wrapper
        #: contextmanager methods
        self.flock_wrappers: Dict[Tuple[Optional[str], str], str] = {}
        #: (class, attr) -> ClassName for ``self.attr = ClassName(...)``
        #: bindings (call-summary receiver resolution)
        self.attr_classes: Dict[Tuple[str, str], str] = {}
        #: canonical ids built from a re-entrant factory (RLock,
        #: Condition) — a self-edge on these is legal
        self.reentrant: set = set()
        rel = ctx.relpath
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                factory = _contains_lock_factory(ctx, node.value)
                if factory is not None:
                    name = node.targets[0].id
                    self.module_locks[name] = f"{rel}::{name}"
                    if factory in _REENTRANT_FACTORIES:
                        self.reentrant.add(f"{rel}::{name}")
        for fn, cls in iter_scope_functions(ctx.tree):
            if self._is_flock_wrapper(fn):
                tag = f"{cls}.{fn.name}()" if cls else f"{fn.name}()"
                self.flock_wrappers[(cls, fn.name)] = f"{rel}::{tag}"
            if cls is None:
                continue
            for s in ast.walk(fn):
                if not (isinstance(s, ast.Assign) and len(s.targets) == 1):
                    continue
                t = s.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                factory = _contains_lock_factory(ctx, s.value)
                if factory is not None:
                    self.attr_locks[(cls, t.attr)] = \
                        f"{rel}::{cls}.{t.attr}"
                    if factory in _REENTRANT_FACTORIES:
                        self.reentrant.add(f"{rel}::{cls}.{t.attr}")
                elif isinstance(s.value, ast.Call):
                    dotted = ctx.resolve(s.value.func) or ""
                    tail = dotted.split(".")[-1]
                    if tail and tail[:1].isupper():
                        self.attr_classes[(cls, t.attr)] = tail

    def _is_flock_wrapper(self, fn) -> bool:
        decorated = any("contextmanager" in ast.dump(d)
                        for d in fn.decorator_list)
        if not decorated:
            return False
        return any(_is_flock_call(self.ctx, n) == "acquire"
                   for n in ast.walk(fn))

    def lock_of(self, expr, cls: Optional[str]) -> Optional[str]:
        """Canonical lock id of a with-item / acquire-receiver
        expression, or None when it is not a known lock."""
        if isinstance(expr, ast.Name):
            return self.module_locks.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and cls is not None:
            return self.attr_locks.get((cls, expr.attr))
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and f.value.id == "self":
                return self.flock_wrappers.get((cls, f.attr)) \
                    or (self.flock_wrappers.get((None, f.attr))
                        if cls is None else None)
            if isinstance(f, ast.Name):
                return self.flock_wrappers.get((None, f.id))
        return None


def is_flock_id(lock_id: str) -> bool:
    """Whether a canonical id names an inter-process flock (these are
    excluded from SPL017's "in-process lock held" precondition)."""
    return lock_id.endswith("()") or "flock@" in lock_id


class LockWalkResult:
    def __init__(self):
        #: id(ast stmt) -> frozenset of held lock ids BEFORE the stmt
        #: executes its own acquisitions
        self.held_at: Dict[int, frozenset] = {}
        #: (lock_id, line, held-before frozenset) per acquisition site
        self.acquisitions: List[Tuple[str, int, frozenset]] = []


def lock_walk(ctx, fn, cls: Optional[str], locks: FileLocks,
              on_nested: Optional[Callable] = None) -> LockWalkResult:
    """Must-hold lock sets over `fn`'s body (module docstring).  With
    `on_nested`, nested function defs are reported (and NOT descended
    into) instead of walked with an empty held set."""
    res = LockWalkResult()

    def acquire_from_stmt(stmt) -> Optional[Tuple[str, str]]:
        """(verb, lock_id) for ``x.acquire()``/``x.release()`` or an
        inline flock statement, else None."""
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)):
            return None
        call = stmt.value
        fl = _is_flock_call(ctx, call)
        if fl is not None:
            return fl, f"{ctx.relpath}::flock@{fn.name}"
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in ("acquire",
                                                       "release"):
            lid = locks.lock_of(f.value, cls)
            if lid is not None:
                return f.attr, lid
        return None

    def walk(body, held: Set[str]):
        held = set(held)
        for stmt in body:
            res.held_at[id(stmt)] = frozenset(held)
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                if on_nested is not None:
                    on_nested(stmt, frozenset(held))
                elif isinstance(stmt, ast.ClassDef):
                    walk(stmt.body, set())
                else:
                    walk(stmt.body, set())  # a closure runs later
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                entered = []
                for item in stmt.items:
                    lid = locks.lock_of(item.context_expr, cls)
                    if lid is not None:
                        res.acquisitions.append(
                            (lid, stmt.lineno, frozenset(held)))
                        held.add(lid)
                        entered.append(lid)
                walk(stmt.body, held)
                for lid in entered:
                    held.discard(lid)
                continue
            if isinstance(stmt, ast.If):
                walk(stmt.body, held)
                walk(stmt.orelse, held)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                walk(stmt.body, held)
                walk(stmt.orelse, held)
                continue
            if isinstance(stmt, ast.Try):
                walk(stmt.body, held)
                for h in stmt.handlers:
                    walk(h.body, held)
                walk(stmt.orelse, held)
                walk(stmt.finalbody, held)
                continue
            verb = acquire_from_stmt(stmt)
            if verb is not None:
                kind, lid = verb
                if kind == "acquire":
                    res.acquisitions.append(
                        (lid, stmt.lineno, frozenset(held)))
                    held.add(lid)
                else:
                    held.discard(lid)
    walk(fn.body, set())
    return res


# -- project-wide summaries (SPL015 edges, SPL017 blocking) ------------------

def _blocking_verb(ctx, call) -> Optional[str]:
    """The blocking-verb label of one direct call, or None.  ``.join``
    is flagged only in the thread-join shape (no args, or a single
    numeric/keyword timeout) so ``", ".join(parts)`` never matches;
    ``.wait`` only as a bare attribute call (Event/Condition wait)."""
    dotted = ctx.resolve(call.func) or ""
    if dotted in _BLOCKING_FNS:
        return _BLOCKING_FNS[dotted]
    if dotted.split(".")[0] == "subprocess":
        return "subprocess"
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "join":
        if not call.args and not call.keywords:
            return "join"
        if len(call.args) == 1 and not call.keywords and \
                isinstance(call.args[0], ast.Constant) and \
                isinstance(call.args[0].value, (int, float)):
            return "join"
        if not call.args and all(k.arg == "timeout"
                                 for k in call.keywords):
            return "join"
        return None
    if isinstance(f, ast.Attribute) and f.attr == "wait":
        return "wait"
    return None


class ProjectLocks:
    """Cross-file lock model: per-file discovery, per-function
    acquisition/blocking summaries closed over conservative call
    resolution, and the project-wide lock acquisition graph."""

    def __init__(self, project):
        self.project = project
        self.files: Dict[str, FileLocks] = {}
        #: function key -> set of lock ids acquired anywhere inside
        self._acquires: Dict[str, Set[str]] = {}
        #: function key -> set of blocking verbs anywhere inside
        self._blocks: Dict[str, Set[str]] = {}
        #: function key -> list of callee keys (resolved)
        self._calls: Dict[str, List[str]] = {}
        #: function key -> (ctx, fn, cls)
        self.functions: Dict[str, Tuple[object, object, Optional[str]]] = {}
        #: function key -> LockWalkResult (the walks are the dominant
        #: cost of a full-tree run, and every consumer — summaries,
        #: order_edges, SPL017 — needs the same on_nested-free walk)
        self._walks: Dict[str, LockWalkResult] = {}
        for ctx in project.files:
            self.files[ctx.relpath] = FileLocks(ctx)
        for ctx in project.files:
            for fn, cls in iter_scope_functions(ctx.tree):
                self._summarize(ctx, fn, cls)
        self._close()

    @staticmethod
    def key(relpath: str, cls: Optional[str], name: str) -> str:
        return f"{relpath}::{cls + '.' if cls else ''}{name}"

    def _summarize(self, ctx, fn, cls) -> None:
        fl = self.files[ctx.relpath]
        key = self.key(ctx.relpath, cls, fn.name)
        self.functions[key] = (ctx, fn, cls)
        acq: Set[str] = set()
        blocks: Set[str] = set()
        callees: List[str] = []
        walk = self._walks[key] = lock_walk(ctx, fn, cls, fl)
        for lid, _line, _held in walk.acquisitions:
            acq.add(lid)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            verb = _blocking_verb(ctx, node)
            if verb is not None:
                blocks.add(verb)
            callees.extend(self._resolve_call(ctx, fl, cls, node))
        # entering a flock-wrapper contextmanager IS a flock
        for lid in acq:
            if is_flock_id(lid):
                blocks.add("flock")
        self._acquires[key] = acq
        self._blocks[key] = blocks
        self._calls[key] = callees

    def _resolve_call(self, ctx, fl: FileLocks, cls, call) -> List[str]:
        """Callee keys of one call — deliberately conservative (see
        module docstring); unresolvable receivers contribute nothing."""
        f = call.func
        rel = ctx.relpath
        out = []
        if isinstance(f, ast.Name):
            # bare name: a function in this file (module level or the
            # alias map's import target)
            dotted = ctx.resolve(f) or f.id
            if "." in dotted:
                out.extend(self._module_fn(dotted))
            else:
                key = self.key(rel, None, f.id)
                if key in self._calls or self._defined(rel, None, f.id):
                    out.append(key)
        elif isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and cls is not None:
                out.append(self.key(rel, cls, f.attr))
            elif isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and cls is not None:
                # self.attr.f(): resolve attr's class when the file
                # binds self.attr = ClassName(...)
                owner = fl.attr_classes.get((cls, base.attr))
                if owner is not None:
                    for frel, fls in self.files.items():
                        if self._defined(frel, owner, f.attr):
                            out.append(self.key(frel, owner, f.attr))
            elif isinstance(base, ast.Name):
                dotted = ctx.resolve(f) or ""
                if dotted:
                    out.extend(self._module_fn(dotted))
        return out

    def _defined(self, rel: str, cls: Optional[str], name: str) -> bool:
        ctx = self.files.get(rel)
        if ctx is None:
            return False
        fctx = next((c for c in self.project.files if c.relpath == rel),
                    None)
        if fctx is None:
            return False
        return any(fn.name == name and fcls == cls
                   for fn, fcls in iter_scope_functions(fctx.tree))

    def _module_fn(self, dotted: str) -> List[str]:
        """Keys for a module-qualified call (``trace.metric_inc``,
        ``splatt_tpu.utils.durable.append_line``): match analyzed files
        whose module path ends with the dotted prefix."""
        parts = dotted.split(".")
        name = parts[-1]
        modpath = "/".join(parts[:-1])
        out = []
        for rel in self.files:
            stem = rel[:-3] if rel.endswith(".py") else rel
            if stem.endswith(modpath) and self._defined(rel, None, name):
                out.append(self.key(rel, None, name))
        return out

    def _close(self) -> None:
        """Transitive closure of acquisition/blocking summaries over
        the call graph (fixpoint; recursion-safe)."""
        changed = True
        while changed:
            changed = False
            for key, callees in self._calls.items():
                for callee in callees:
                    if callee == key:
                        continue
                    extra_a = self._acquires.get(callee, set()) \
                        - self._acquires[key]
                    extra_b = self._blocks.get(callee, set()) \
                        - self._blocks[key]
                    if extra_a:
                        self._acquires[key] |= extra_a
                        changed = True
                    if extra_b:
                        self._blocks[key] |= extra_b
                        changed = True

    def walk_of(self, key: str) -> "LockWalkResult":
        """The memoized on_nested-free walk for one known function."""
        walk = self._walks.get(key)
        if walk is None:
            ctx, fn, cls = self.functions[key]
            walk = self._walks[key] = lock_walk(
                ctx, fn, cls, self.files[ctx.relpath])
        return walk

    def acquires(self, key: str) -> Set[str]:
        return self._acquires.get(key, set())

    def blocks(self, key: str) -> Set[str]:
        return self._blocks.get(key, set())

    def call_targets(self, ctx, cls, call) -> List[str]:
        return self._resolve_call(ctx, self.files[ctx.relpath], cls, call)

    # -- the project-wide lock acquisition graph (SPL015) --------------------

    def order_edges(self) -> Dict[Tuple[str, str], Tuple[str, int]]:
        """(held, acquired) -> (relpath, line) of one witness site.
        Direct edges come from acquisition sites with a non-empty held
        set; interprocedural edges from call sites under a held lock to
        every lock in the callee's transitive acquisition summary.
        Memoized — SPL015 needs it twice (witness sites + the cycle
        search) and the underlying lock walks are the dominant cost of
        the perf-gated full-tree run."""
        if getattr(self, "_order_edges", None) is not None:
            return self._order_edges
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        reentrant = set()
        for fl in self.files.values():
            reentrant |= fl.reentrant

        def add(a: str, b: str, rel: str, line: int):
            # a self-edge on a NON-reentrant lock is the degenerate
            # deadlock (the thread waits on itself); re-entrant locks
            # may legally nest under themselves
            if a == b and b in reentrant:
                return
            if (a, b) not in edges:
                edges[(a, b)] = (rel, line)

        for key, (ctx, fn, cls) in self.functions.items():
            fl = self.files[ctx.relpath]
            walk = self.walk_of(key)
            for lid, line, held in walk.acquisitions:
                for h in held:
                    add(h, lid, ctx.relpath, line)
            # call sites under a held lock
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.stmt):
                    continue
                held = walk.held_at.get(id(stmt))
                if not held:
                    continue
                for call in ast.walk(stmt):
                    if not isinstance(call, ast.Call):
                        continue
                    for callee in self._resolve_call(
                            ctx, fl, cls, call):
                        for lid in self._acquires.get(callee, set()):
                            for h in held:
                                add(h, lid, ctx.relpath,
                                    getattr(call, "lineno", fn.lineno))
        self._order_edges = edges
        return edges

    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the acquisition graph (including
        self-loops from re-acquiring a non-reentrant lock under
        itself), shortest first."""
        edges = self.order_edges()
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        out: List[List[str]] = []
        seen: Set[frozenset] = set()
        for start in sorted(graph):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(graph.get(node, ())):
                    if nxt == start:
                        key = frozenset(path)
                        if key not in seen:
                            seen.add(key)
                            out.append(path + [start])
                    elif nxt not in path and len(path) < 6:
                        stack.append((nxt, path + [nxt]))
        out.sort(key=len)
        return out


def project_locks(project) -> ProjectLocks:
    """The (cached per run) cross-file lock model."""
    if getattr(project, "_locks", None) is None:
        project._locks = ProjectLocks(project)
    return project._locks
