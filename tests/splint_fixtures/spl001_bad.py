"""SPL001 bad: raw os.environ access outside utils/env.py."""

import os
from os import environ, getenv

A = os.environ.get("SPLATT_ENGINE_FALLBACK", "1")
B = os.environ["SPLATT_ENGINE_FALLBACK"]
C = os.getenv("SPLATT_ENGINE_FALLBACK")
D = environ.get("SPLATT_ENGINE_FALLBACK")
E = getenv("SPLATT_ENGINE_FALLBACK")
