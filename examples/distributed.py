"""Distributed CPD across all available devices.

Run on any device count (simulate a mesh on CPU with:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/distributed.py
).  Exercises all three decompositions; each reproduces the
single-device factors for the same seed.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from splatt_tpu.utils.env import apply_env_platform

apply_env_platform()

import jax

import splatt_tpu
from splatt_tpu.config import CommPattern, Decomposition, Options, Verbosity
from splatt_tpu.parallel import distributed_cpd_als


def main() -> None:
    tt = splatt_tpu.SparseTensor.random((300, 240, 180), 50_000, seed=3)
    print(f"devices: {len(jax.devices())}  tensor: {tt.dims}, {tt.nnz} nnz")

    for decomp in Decomposition:
        opts = Options(random_seed=7, max_iterations=10,
                       verbosity=Verbosity.NONE, decomposition=decomp)
        out = distributed_cpd_als(tt, rank=8, opts=opts)
        print(f"{decomp.value:8s} fit = {float(out.fit):.5f}")

    # the memory-lean ppermute-ring variant (for modes whose factors
    # don't fit on one device)
    opts = Options(random_seed=7, max_iterations=10,
                   verbosity=Verbosity.NONE,
                   decomposition=Decomposition.FINE,
                   comm_pattern=CommPattern.POINT2POINT)
    out = distributed_cpd_als(tt, rank=8, opts=opts)
    print(f"ring     fit = {float(out.fit):.5f}")


if __name__ == "__main__":
    main()
