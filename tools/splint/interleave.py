"""Bounded-exhaustive interleaving checker for the fleet lease protocol.

The fleet chaos soak (``splatt chaos --fleet``) SIGKILLs one replica in
one schedule per run — a sampled point in the interleaving space.  This
harness enumerates the space: it drives the REAL lease state machine
(:class:`splatt_tpu.fleet.FleetMember` — actual lease files, actual
flock sidecars, actual ``acquire``/``renew``/``adopt``/``release``
code) across 2–3 virtual replicas under a **virtual clock**, running
every interleaving of fixed per-replica programs and asserting the
protocol invariants after every step of every schedule:

exactly-one-owner
    At every instant, at most one replica both believes it holds the
    job AND matches the published lease (replica and generation, not
    expired).

generation-fence monotonicity
    The published ``gen`` never decreases; a takeover always bumps it,
    so a stale owner's state can never compare equal to the current
    lease again.

no terminal append after expiry (the zombie-commit fence)
    A terminal journal record may only be appended under a live lease
    whose generation matches — modeled exactly like serve.py's
    ``_run_job``: a last-gate :meth:`renew` immediately before the
    append, abandon on refusal.  At most one terminal append per job.

The clock is a schedule step (``tick``), not a race: lease expiry
happens exactly when a schedule says it does, so the
expire-mid-run/adopt/zombie-commit orderings the soak can only
occasionally hit are all visited, every run.

**Mutants** re-introduce the bug classes PR 11's review caught, and
the checker must fail on them (tests/test_interleave.py pins this):

- ``no_fence`` — the zombie-commit bug: commit whenever the replica
  still *believes* it owns the job, skipping the last-gate renew.
- ``no_gen_bump`` — adoption without the generation fence: the old
  owner's renew matches the adopter's lease and revives it.

Run ``python -m tools.splint.interleave [--replicas N] [--mutant M]``
for the CLI form; the module API is :func:`check`.

Unlike the static side of splint, this module imports ``splatt_tpu``
(it executes the protocol, it does not parse it) — keep it out of the
analyzer's import path.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

JOB = "j1"
LEASE_S = 10.0


class VirtualClock:
    """The schedule-controlled time source injected into every
    :class:`FleetMember` — expiry becomes a deterministic step."""

    def __init__(self, t0: float = 1_000.0):
        self.t = t0

    def time(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@dataclasses.dataclass
class Violation:
    """One invariant breach: which schedule, after which step."""

    scenario: str
    schedule: Tuple[str, ...]
    step: str
    invariant: str
    detail: str

    def __str__(self):
        return (f"[{self.scenario}] after {self.step} in "
                f"{' '.join(self.schedule)}: {self.invariant} — "
                f"{self.detail}")


@dataclasses.dataclass
class CheckResult:
    replicas: int
    mutant: Optional[str]
    scenarios: int
    schedules: int
    steps: int
    violations: List[Violation]

    @property
    def ok(self) -> bool:
        return not self.violations


def interleavings(programs: Dict[str, Sequence[str]]):
    """Every merge of the per-actor op sequences that preserves each
    actor's internal order — the bounded-exhaustive schedule set.
    Yields tuples of ``"actor:op"`` steps."""
    actors = sorted(programs)
    counts = [len(programs[a]) for a in actors]

    def gen(idx):
        if all(i == c for i, c in zip(idx, counts)):
            yield ()
            return
        for k, a in enumerate(actors):
            if idx[k] < counts[k]:
                step = f"{a}:{programs[a][idx[k]]}"
                nxt = list(idx)
                nxt[k] += 1
                for rest in gen(tuple(nxt)):
                    yield (step,) + rest

    yield from gen(tuple(0 for _ in actors))


class _Run:
    """One schedule execution over a fresh spool root.

    The model mirrors serve.py's claim/commit coupling exactly:

    - a claim (acquire or adopt) that succeeds immediately re-reads
      the shared journal; a terminal record found there means a peer
      already finished the job — release and never run it
      (serve._next's post-claim re-check).  The lease's total order
      makes this airtight: the terminal append happens UNDER the
      lease, before release, so it is always visible to the next
      holder.
    - a commit renews at the last gate before its terminal append and
      abandons on refusal (serve._run_job's zombie-commit fence).
    """

    def __init__(self, root: str, actors: Dict[str, str],
                 mutant: Optional[str]):
        from splatt_tpu.fleet import FleetMember

        self.clock = VirtualClock()
        self.mutant = mutant
        #: actor name -> replica id.  Distinct actors may SHARE a
        #: replica id — that is the restarted-replica-under-a-pinned-
        #: SPLATT_FLEET_REPLICA scenario the generation fence exists
        #: for (a zombie twin's stale renew must never match the
        #: restarted instance's lease).
        self.actors = dict(actors)
        self.members: Dict[str, object] = {
            actor: FleetMember(root, replica=rid, lease_s=LEASE_S,
                               heartbeat_s=LEASE_S,
                               clock=self.clock.time)
            for actor, rid in actors.items()}
        #: terminal journal: (replica, gen) per append, in order
        self.journal: List[Tuple[str, int]] = []
        #: replicas that have SEEN a terminal record (their job table
        #: says terminal; they never claim or commit again)
        self.done: set = set()
        #: replicas whose lease was adopted away from them — the gen
        #: fence's contract is that their renew can NEVER succeed
        #: again for that era (cleared by a fresh successful claim)
        self.adopted_away: set = set()
        #: (invariant, detail) breaches raised by the ops themselves
        #: (drained by the schedule loop alongside the polled checks)
        self.step_violations: List[Tuple[str, str]] = []
        self.max_gen = 0

    # - ops -

    def op(self, actor: str, name: str) -> None:
        if actor == "clock":
            self.clock.advance(LEASE_S + 1.0 if name == "tick"
                               else LEASE_S / 2.0)
            return
        if name == "acquire":
            self._claim(actor, adopt=False)
        elif name == "renew":
            self._renew(actor)
        elif name == "release":
            self.members[actor].release(JOB)
        elif name == "adopt":
            self._claim(actor, adopt=True)
        elif name == "commit":
            self._commit(actor)
        else:
            raise ValueError(f"unknown op {name!r}")

    def _claim(self, actor: str, adopt: bool) -> None:
        """serve._next's claim: skip jobs known terminal, take the
        lease through the real protocol, then re-read the shared
        journal — a terminal record that landed before our claim means
        the job is finished; release and remember."""
        m = self.members[actor]
        if actor in self.done:
            return  # serve never queues/picks a terminal job
        if adopt:
            ok = m.adopt(JOB)
            if ok:
                # every OTHER actor's era on this job ended here: the
                # gen fence must refuse their every later renew, even
                # (especially) a zombie twin sharing our replica id
                for other, om in self.members.items():
                    if other != actor and JOB in dict(om._held):
                        self.adopted_away.add(other)
                if self.mutant == "no_gen_bump" and \
                        JOB in dict(m._held):
                    self._unbump_gen(m)
        else:
            ok = m.acquire(JOB)
        if ok:
            self.adopted_away.discard(actor)  # a fresh era
        if ok and self.journal:
            # the post-claim journal re-check (serve._next): the
            # terminal append happened under the lease we now hold,
            # so it is necessarily visible here
            self.done.add(actor)
            m.release(JOB)

    def _commit(self, actor: str) -> None:
        """serve._run_job's terminal-commit protocol: last-gate renew,
        then the terminal journal append; abandon on refusal.  The
        ``no_fence`` mutant is the PR 11 zombie-commit bug — append
        whenever the replica still believes it owns the job."""
        m = self.members[actor]
        if actor in self.done:
            return
        held = dict(m._held).get(JOB)
        if held is None:
            return
        if self.mutant != "no_fence":
            if not self._renew(actor):
                return  # fenced: ownership moved on, abandon
            held = dict(m._held).get(JOB)
        self.journal.append((m.replica, held.gen))
        self.done.add(actor)
        m.release(JOB)

    def _renew(self, actor: str) -> bool:
        """renew with the gen-fence contract checked: a renew that
        SUCCEEDS for an actor whose lease was adopted away revives a
        dead era — exactly what the adopt-time gen bump exists to make
        impossible (the zombie twin sharing a restarted replica's
        pinned id is the case the replica check alone cannot stop)."""
        ok = self.members[actor].renew(JOB)
        if ok and actor in self.adopted_away:
            self.step_violations.append((
                "gen-fence",
                f"{actor}'s renew succeeded after its lease was "
                f"adopted away — the takeover did not fence the old "
                f"owner's generation"))
        return ok

    def _unbump_gen(self, m) -> None:
        """The ``no_gen_bump`` mutant: republish the adopted lease at
        the PREVIOUS generation (an adopt that forgot the fence), in
        both the file and the adopter's belief."""
        import dataclasses as dc

        lease = m.lease_of(JOB)
        if lease is None or lease.gen <= 1:
            return
        stale = dc.replace(lease, gen=lease.gen - 1)
        m._write_lease(stale)
        with m._lock:
            if JOB in m._held:
                m._held[JOB] = stale

    # - invariants -

    def believed_owners(self) -> List[str]:
        """Replica IDS whose belief matches the published lease: held,
        same replica, same gen, unexpired at the virtual now.  The
        protocol's ownership unit is the replica id (two processes
        under one pinned id are, to the protocol, one owner — the gen
        fence distinguishes their ERAS, checked by :meth:`_renew`)."""
        now = self.clock.time()
        out = set()
        for actor, m in sorted(self.members.items()):
            held = dict(m._held).get(JOB)
            if held is None:
                continue
            cur = m.lease_of(JOB)
            if cur is not None and cur.replica == m.replica \
                    and cur.gen == held.gen and not cur.expired(now):
                out.add(m.replica)
        return sorted(out)

    def check_invariants(self) -> List[Tuple[str, str]]:
        """(invariant, detail) breaches at the current instant."""
        out = []
        owners = self.believed_owners()
        if len(owners) > 1:
            out.append(("exactly-one-owner",
                        f"two live matching owners: {owners}"))
        any_m = next(iter(self.members.values()))
        cur = any_m.lease_of(JOB)
        if cur is not None:
            if cur.gen < self.max_gen:
                out.append(("gen-monotonic",
                            f"published gen {cur.gen} < previously "
                            f"seen {self.max_gen}"))
            self.max_gen = max(self.max_gen, cur.gen)
        if len(self.journal) > 1:
            out.append(("single-terminal",
                        f"{len(self.journal)} terminal appends: "
                        f"{self.journal}"))
        return out

    def check_append_ownership(self) -> Optional[str]:
        """Called right after a commit op: the newest terminal append
        must have been made by the then-current lease holder.  With
        the fence on this holds by construction; the zombie mutant
        appends under a lease a peer already re-owns."""
        if not self.journal:
            return None
        rid, gen = self.journal[-1]
        if gen < self.max_gen:
            return (f"terminal append by {rid} at gen {gen} after the "
                    f"lease moved to gen {self.max_gen} (zombie "
                    f"commit)")
        return None


# -- the scenario programs ---------------------------------------------------

def _rid(actor: str) -> str:
    """Actor -> replica id: a trailing digit marks a twin instance
    sharing the base id (``A1``/``A2`` are two processes under the
    pinned replica id ``A`` — the restart scenario)."""
    return actor.rstrip("0123456789")


def scenarios(replicas: int) -> Dict[str, Dict[str, Sequence[str]]]:
    """Per-actor op programs whose interleavings cover the protocol's
    hazard surface: contention, expiry+failover, renew-after-expiry,
    release/reclaim, the restarted-replica zombie twin — and with
    three replicas, chained adoption."""
    base = {
        "contention": {"A": ("acquire", "commit"),
                       "B": ("acquire", "commit")},
        "failover": {"A": ("acquire", "commit"),
                     "B": ("adopt", "commit"),
                     "clock": ("tick",)},
        "renew-refusal": {"A": ("acquire", "renew", "commit"),
                          "B": ("adopt",),
                          "clock": ("tick",)},
        "release-reclaim": {"A": ("acquire", "release"),
                            "B": ("acquire", "commit"),
                            "clock": ("half",)},
        # the gen fence's home turf: A1 is a paused/zombie process, A2
        # a restarted replica under the SAME pinned id; after B's
        # adoption moved the lease on, A1's stale renew must never
        # match again — even once A2 (same replica id!) re-adopts
        "twin-revival": {"A1": ("acquire", "renew", "commit"),
                         "A2": ("adopt",),
                         "B": ("adopt",),
                         "clock": ("tick", "tick")},
    }
    if replicas >= 3:
        base["chained-adoption"] = {"A": ("acquire", "commit"),
                                    "B": ("adopt", "commit"),
                                    "C": ("adopt", "commit"),
                                    "clock": ("tick", "tick")}
    return base


def check(replicas: int = 2, mutant: Optional[str] = None,
          root: Optional[str] = None) -> CheckResult:
    """Run every scenario's every interleaving; collect violations.
    `mutant` in {None, "no_fence", "no_gen_bump"}."""
    schedules = 0
    steps = 0
    violations: List[Violation] = []
    scen = scenarios(replicas)
    with tempfile.TemporaryDirectory(dir=root) as tmp:
        for name, programs in sorted(scen.items()):
            actors = {a: _rid(a) for a in programs if a != "clock"}
            for i, sched in enumerate(interleavings(programs)):
                schedules += 1
                run = _Run(os.path.join(tmp, f"{name}-{i}"), actors,
                           mutant)
                for step in sched:
                    steps += 1
                    actor, op = step.split(":", 1)
                    run.op(actor, op)
                    raised = run.step_violations
                    run.step_violations = []
                    for inv, detail in raised + run.check_invariants():
                        violations.append(Violation(
                            name, sched, step, inv, detail))
                    if op == "commit":
                        zombie = run.check_append_ownership()
                        if zombie:
                            violations.append(Violation(
                                name, sched, step,
                                "no-append-after-expiry", zombie))
    return CheckResult(replicas=replicas, mutant=mutant,
                       scenarios=len(scen), schedules=schedules,
                       steps=steps, violations=violations)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m tools.splint.interleave",
        description="bounded-exhaustive lease-protocol interleaving "
                    "checker (docs/fleet.md)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="virtual replicas (2 or 3)")
    ap.add_argument("--mutant", default=None,
                    choices=["no_fence", "no_gen_bump"],
                    help="re-introduce a known bug class; the checker "
                         "must FAIL (exit 1) on it")
    args = ap.parse_args(argv)
    res = check(replicas=args.replicas, mutant=args.mutant)
    print(f"interleave: {res.scenarios} scenario(s), "
          f"{res.schedules} schedule(s), {res.steps} step(s), "
          f"{len(res.violations)} violation(s)"
          + (f" [mutant={res.mutant}]" if res.mutant else ""))
    for v in res.violations[:10]:
        print(f"  {v}")
    if len(res.violations) > 10:
        print(f"  ... {len(res.violations) - 10} more")
    return 0 if res.ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
