"""splint — the project-native static-analysis pass (tools/splint).

Tier-1 wiring: the analyzer runs over splatt_tpu/ and the build fails
on any non-baselined finding, so the dispatch/resilience/recompilation
invariants (docs/static-analysis.md) are machine-checked on every test
run, not re-litigated in review.  Per-rule fixtures under
tests/splint_fixtures/ pin each rule's detection with one known-bad
and one known-good example.
"""

import ast
import json
import shutil
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "splint_fixtures"

sys.path.insert(0, str(REPO))  # `tools` is importable from the root

from tools.splint import (Config, load_baseline, load_config, run,  # noqa: E402
                          update_baseline)
from tools.splint.config import _parse_table  # noqa: E402


def _cfg(**overrides) -> Config:
    cfg = load_config(REPO)
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


def _rule_findings(report, rule: str, relpath: str):
    return [f for f in report.findings
            if f.rule == rule and f.path == relpath]


# -- the tier-1 gate --------------------------------------------------------

def test_package_has_zero_nonbaselined_findings():
    """The acceptance invariant: splint over splatt_tpu/ is clean
    modulo the justified baseline."""
    baseline = load_baseline(REPO / "tools" / "splint" / "baseline.json")
    report = run(_cfg(), baseline=baseline)
    msg = "\n".join(f"{f.path}:{f.line}: {f.rule} {f.message}"
                    for f in report.new)
    assert report.ok, f"new splint findings:\n{msg}"


def test_zero_budget_rules_are_clean():
    """The [tool.splint] zero-rules budgets: these rules are fixed in
    code — never grandfathered, never pragma'd away wholesale.  Covers
    the PR 2 burn-down commitment (SPL001/SPL002) and the dataflow
    rules (SPL008-SPL012), whose real findings — the phased sweep's
    donated-M re-read, the inline cache opens, the undocumented
    env_platform_error event — were fixed, not baselined."""
    cfg = _cfg()
    assert {"SPL001", "SPL002", "SPL008", "SPL011"} <= set(cfg.zero_rules)
    report = run(cfg, baseline={})
    by_rule = {}
    for f in report.findings:
        by_rule.setdefault(f.rule, []).append(f)
    for rule in cfg.zero_rules:
        hits = ["{0.path}:{0.line}: {0.message}".format(f)
                for f in by_rule.get(rule, [])]
        assert not hits, f"{rule} must stay at zero findings:\n" \
                         + "\n".join(hits)


def test_baseline_never_contains_zero_budget_rules():
    """Baseline honesty for the zero-rules: the grandfathering ledger
    may not quietly absorb a rule whose budget is hard zero."""
    baseline = load_baseline(REPO / "tools" / "splint" / "baseline.json")
    zero = set(_cfg().zero_rules)
    offending = [k for k in baseline if k.split(":")[0] in zero]
    assert not offending, offending


def test_baseline_entries_are_justified():
    """The v5 burn-down emptied the baseline — every rule is a
    zero-rule now.  Any entry that ever reappears must carry a
    human-written reason and a live count."""
    baseline = load_baseline(REPO / "tools" / "splint" / "baseline.json")
    assert baseline == {}, \
        "the baseline was burned down to empty; do not grandfather " \
        "new findings — fix them or add a reasoned inline pragma"
    for key, entry in baseline.items():
        reason = entry.get("reason", "")
        assert reason and not reason.startswith("UNJUSTIFIED"), \
            f"baseline entry {key} lacks a human-written reason"
        assert entry["count"] > 0, f"stale baseline entry {key}"


def test_baseline_has_no_stale_or_overcounted_entries():
    """Every baseline entry matches reality: no stale groups (0
    findings) and no padded counts (fewer findings than baselined) —
    the ledger may only record what the code actually contains."""
    baseline = load_baseline(REPO / "tools" / "splint" / "baseline.json")
    report = run(_cfg(), baseline=baseline)
    assert not report.stale, f"stale baseline entries: {report.stale}"
    assert not report.shrunk, \
        f"baseline counts exceed current findings: {report.shrunk}"


# -- per-rule fixtures ------------------------------------------------------

RULE_IDS = ["SPL000", "SPL001", "SPL002", "SPL003", "SPL004", "SPL005",
            "SPL006", "SPL007", "SPL008", "SPL009", "SPL010", "SPL011",
            "SPL012", "SPL013", "SPL014", "SPL015", "SPL016", "SPL017",
            "SPL018", "SPL019", "SPL020", "SPL021", "SPL022",
            "SPL023", "SPL024", "SPL025", "SPL026", "SPL027",
            "SPL028", "SPL029"]


@pytest.mark.parametrize("rule", RULE_IDS)
def test_rule_flags_bad_fixture(rule):
    rel = f"tests/splint_fixtures/{rule.lower()}_bad.py"
    report = run(_cfg(paths=[rel]), baseline={})
    assert _rule_findings(report, rule, rel), \
        f"{rule} found nothing in its known-bad fixture"


@pytest.mark.parametrize("rule", RULE_IDS)
def test_rule_passes_good_fixture(rule):
    rel = f"tests/splint_fixtures/{rule.lower()}_good.py"
    report = run(_cfg(paths=[rel]), baseline={})
    hits = _rule_findings(report, rule, rel)
    assert not hits, f"{rule} false positives: " + "\n".join(
        f"{f.path}:{f.line} {f.message}" for f in hits)


def test_good_fixtures_are_fully_clean():
    """The good fixtures are clean under EVERY rule, not only their
    own (cross-rule noise in an exemplar would teach the wrong idiom)."""
    rels = [f"tests/splint_fixtures/{r.lower()}_good.py"
            for r in RULE_IDS]
    report = run(_cfg(paths=rels), baseline={})
    hits = [f for f in report.findings if f.path in rels]
    assert not hits, "\n".join(
        f"{f.path}:{f.line}: {f.rule} {f.message}" for f in hits)


def test_hot_function_config_extends_spl003():
    rel = "tests/splint_fixtures/spl003_bad.py"
    plain = run(_cfg(paths=[rel]), baseline={})
    assert not any(f.line == 24 for f in
                   _rule_findings(plain, "SPL003", rel))
    hot = run(_cfg(paths=[rel],
                   hot_functions=[f"{rel}::hot_sweep"]), baseline={})
    assert any("hot path" in f.message for f in
               _rule_findings(hot, "SPL003", rel))


# -- pragma / baseline workflow --------------------------------------------

def test_reasonless_pragma_is_spl000_and_still_suppresses():
    rel = "tests/splint_fixtures/spl000_bad.py"
    report = run(_cfg(paths=[rel]), baseline={})
    assert _rule_findings(report, "SPL000", rel)
    assert not _rule_findings(report, "SPL005", rel)
    assert report.suppressed == 1


def test_baseline_workflow_roundtrip(tmp_path):
    """update-baseline grandfathers today's findings; a new violation
    fails; burning one down is detected as shrinkage."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    mod = pkg / "m.py"
    mod.write_text("import jax.numpy as jnp\n"
                   "A = jnp.zeros(2, jnp.float32)\n"
                   "B = jnp.zeros(2, jnp.float64)\n")
    cfg = Config(root=tmp_path, paths=["pkg"])
    bl_path = tmp_path / "baseline.json"

    first = run(cfg, baseline={})
    assert len(first.findings) == 2 and not first.ok
    entries = update_baseline(bl_path, first)
    assert entries["SPL005:pkg/m.py"]["count"] == 2
    assert "UNJUSTIFIED" in entries["SPL005:pkg/m.py"]["reason"]

    clean = run(cfg, baseline=load_baseline(bl_path))
    assert clean.ok and len(clean.findings) == 2

    mod.write_text(mod.read_text()
                   + "C = jnp.zeros(2, jnp.bfloat16)\n")
    over = run(cfg, baseline=load_baseline(bl_path))
    assert not over.ok and len(over.new) == 3  # whole group surfaces

    mod.write_text("import jax.numpy as jnp\n"
                   "A = jnp.zeros(2, jnp.float32)\n")
    shrunk = run(cfg, baseline=load_baseline(bl_path))
    assert shrunk.ok and shrunk.shrunk["SPL005:pkg/m.py"] == (1, 2)
    # reasons survive a baseline rewrite
    entries["SPL005:pkg/m.py"]["reason"] = "fixture justification"
    bl_path.write_text(json.dumps({"version": 1, "entries": entries}))
    rewritten = update_baseline(bl_path, shrunk)
    assert rewritten["SPL005:pkg/m.py"] == {
        "count": 1, "reason": "fixture justification"}


def test_spl013_declaration_drift(tmp_path):
    """Both span-drift directions, on a mini-project: an undeclared
    opened name fires at the call site, a declared-but-never-opened
    name fires at the registry, and a declared ``x.*`` family matches
    f-string opens."""
    (tmp_path / "pkg").mkdir()
    trace_mod = tmp_path / "pkg" / "trace.py"
    trace_mod.write_text(
        "SPANS = {'used.span': 'doc', 'fam.*': 'doc', "
        "'dead.span': 'doc'}\n"
        "def span(name, **attrs): ...\n"
        "def begin(name, **attrs): ...\n")
    (tmp_path / "pkg" / "prod.py").write_text(
        "from pkg import trace\n"
        "def f(k):\n"
        "    with trace.span('used.span'):\n"
        "        pass\n"
        "    trace.begin(f'fam.{k}')\n"
        "    with trace.span('rogue.span'):\n"
        "        pass\n")
    cfg = Config(root=tmp_path, paths=["pkg"],
                 trace_module="pkg/trace.py")
    msgs = [f.message for f in run(cfg, baseline={}).findings
            if f.rule == "SPL013"]
    assert any("rogue.span" in m and "not declared" in m for m in msgs)
    assert any("dead.span" in m and "never opened" in m for m in msgs)
    assert not any("used.span" in m or "fam." in m for m in msgs)
    # opening the dead span and declaring the rogue one clears the drift
    trace_mod.write_text(
        "SPANS = {'used.span': 'doc', 'fam.*': 'doc', "
        "'dead.span': 'doc', 'rogue.span': 'doc'}\n"
        "def span(name, **attrs): ...\n"
        "def begin(name, **attrs): ...\n")
    (tmp_path / "pkg" / "prod.py").write_text(
        "from pkg import trace\n"
        "def f(k):\n"
        "    with trace.span('used.span'):\n"
        "        pass\n"
        "    trace.begin(f'fam.{k}')\n"
        "    with trace.span('rogue.span'):\n"
        "        pass\n"
        "    with trace.span('dead.span'):\n"
        "        pass\n")
    assert not [f for f in run(cfg, baseline={}).findings
                if f.rule == "SPL013"]


def test_spl013_span_registry_matches_runtime():
    """The SPANS registry is importable, documented, and every name the
    summarizer special-cases (roots, iteration spans, the guard family)
    is declared — the static check and the runtime summary read the
    same surface."""
    from splatt_tpu.trace import METRICS, SPANS

    assert {"cpd.als", "cpd.iter", "dist.als", "dist.step",
            "cpd.guard.health_pack", "cpd.guard.snapshot",
            "cpd.guard.rollback", "serve.job", "trace.export",
            "timer.*"} <= set(SPANS)
    for name, doc in SPANS.items():
        assert isinstance(doc, str) and len(doc) > 10, name
    for name, (typ, doc) in METRICS.items():
        assert typ in ("counter", "gauge", "histogram"), name
        assert isinstance(doc, str) and len(doc) > 10, name


def _spl029_project(tmp_path, docs: str = None):
    (tmp_path / "pkg").mkdir(exist_ok=True)
    (tmp_path / "pkg" / "trace.py").write_text(
        "METRICS = {'splatt_used_total': ('counter', 'doc'),\n"
        "           'splatt_dead_total': ('counter', 'doc'),\n"
        "           'splatt_depth': ('gauge', 'doc')}\n"
        "def metric_inc(name, value=1.0, **labels): ...\n"
        "def metric_set(name, value, **labels): ...\n"
        "def metric_observe(name, value, **labels): ...\n")
    (tmp_path / "pkg" / "prod.py").write_text(
        "from pkg import trace\n"
        "def f():\n"
        "    trace.metric_inc('splatt_used_total')\n"
        "    trace.metric_set('splatt_depth', 1.0)\n"
        "    trace.metric_inc('splatt_rogue_total')\n"
        "    trace.metric_inc('splatt_depth')\n")
    kw = {}
    if docs is not None:
        (tmp_path / "docs").mkdir(exist_ok=True)
        (tmp_path / "docs" / "obs.md").write_text(docs)
        kw["metrics_doc"] = "docs/obs.md"
    return Config(root=tmp_path, paths=["pkg"],
                  trace_module="pkg/trace.py", **kw)


def test_spl029_metric_drift(tmp_path):
    """Both registry directions plus the type check, on a
    mini-project: an undeclared recorded name fires at the call site,
    a declared-but-never-recorded name fires at the registry, and a
    counter recorded through the gauge verb (a runtime raise) is a
    finding before anything runs."""
    cfg = _spl029_project(tmp_path)
    msgs = [f.message for f in run(cfg, baseline={}).findings
            if f.rule == "SPL029"]
    assert any("splatt_rogue_total" in m and "not declared" in m
               for m in msgs)
    assert any("splatt_dead_total" in m and "never recorded" in m
               for m in msgs)
    assert any("splatt_depth" in m and "declared as a gauge" in m
               and "metric_inc" in m for m in msgs)
    assert not any("splatt_used_total" in m for m in msgs)


def test_spl029_docs_table_both_directions(tmp_path):
    """The docs legs: a declared metric missing from the configured
    metrics doc fires at the registry, and a doc-table metric the
    registry never declares is a dead promise."""
    docs = ("# metrics\n"
            "| metric | type |\n|---|---|\n"
            "| `splatt_used_total` | counter |\n"
            "| `splatt_ghost_total{x=y}` | counter |\n"
            "| `splatt_depth` | gauge |\n")
    cfg = _spl029_project(tmp_path, docs=docs)
    msgs = [f.message for f in run(cfg, baseline={}).findings
            if f.rule == "SPL029"]
    assert any("splatt_dead_total" in m and "no row" in m
               for m in msgs)
    assert any("splatt_ghost_total" in m and "never declares" in m
               for m in msgs)
    # documented + declared names are clean on the docs legs
    assert not any("splatt_used_total" in m and "row" in m
                   for m in msgs)
    # completing the table and dropping the ghost clears the docs legs
    (tmp_path / "docs" / "obs.md").write_text(
        docs.replace("| `splatt_ghost_total{x=y}` | counter |\n", "")
        + "| `splatt_dead_total` | counter |\n")
    msgs2 = [f.message for f in run(cfg, baseline={}).findings
             if f.rule == "SPL029"]
    assert not any("row" in m or "never declares" in m for m in msgs2)


def test_spl029_registry_matches_runtime_and_docs():
    """The real registry is importable and the real docs table is in
    sync (the full-tree zero gate enforces this too; this pins the
    wiring: metrics-doc configured, every metric typed + documented)."""
    cfg = _cfg()
    assert cfg.metrics_doc == "docs/observability.md"
    from splatt_tpu.trace import METRICS

    text = (REPO / "docs" / "observability.md").read_text()
    for name in METRICS:
        assert name in text, f"{name} missing from the docs table"


def test_spl006_declaration_drift(tmp_path):
    """Both drift directions: a declared-but-never-called site and a
    declared-but-untested site are findings at the registry."""
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "prod.py").write_text(
        "from pkg import faults\n"
        "faults.maybe_fail('used_site')\n")
    faults_mod = tmp_path / "pkg" / "faults.py"
    faults_mod.write_text(
        "SITES = {'used_site': 'doc', 'dead_site': 'doc'}\n"
        "def maybe_fail(site): ...\n")
    tdir = tmp_path / "tests"
    tdir.mkdir()
    (tdir / "test_x.py").write_text(
        "from pkg import faults\n"
        "def test_x():\n    faults.maybe_fail('other')\n")
    cfg = Config(root=tmp_path, paths=["pkg"],
                 faults_module="pkg/faults.py", tests_path="tests")
    report = run(cfg, baseline={})
    msgs = [f.message for f in report.findings if f.rule == "SPL006"]
    assert any("dead_site" in m and "no production call" in m
               for m in msgs)
    assert any("used_site" in m and "not exercised" in m for m in msgs)
    # exercising + calling both sites clears the drift
    (tdir / "test_x.py").write_text(
        "from pkg import faults\n"
        "def test_x():\n"
        "    faults.maybe_fail('used_site')\n"
        "    faults.maybe_fail('dead_site')\n")
    (tmp_path / "pkg" / "prod.py").write_text(
        "from pkg import faults\n"
        "faults.maybe_fail('used_site')\n"
        "faults.maybe_fail('dead_site')\n")
    assert not [f for f in run(cfg, baseline={}).findings
                if f.rule == "SPL006"]


# -- dataflow engine (CFG / def-use / jit-boundary map) ---------------------

from tools.splint.core import (FileCtx, FunctionCFG,  # noqa: E402
                               def_use_chains, jit_boundary)


def _cfg_of(src: str) -> FunctionCFG:
    fn = ast.parse(textwrap.dedent(src).strip()).body[0]
    return FunctionCFG(fn)


def _use_defs_lines(cfg: FunctionCFG, name: str, kind=None):
    """{use line: sorted def lines} for every use of `name`."""
    chains = def_use_chains(cfg)
    out = {}
    for node in cfg.nodes:
        if kind is not None and node.kind != kind:
            continue
        if any(n == name for n, _ in node.uses):
            defs = chains.get((node.idx, name), set())
            out[node.line] = sorted(cfg.nodes[d].line for d in defs)
    return out


def test_cfg_branch_defs_merge_at_join():
    cfg = _cfg_of("""
        def f(c):
            if c:
                x = 1
            else:
                x = 2
            return x
    """)
    assert _use_defs_lines(cfg, "x") == {6: [3, 5]}


def test_cfg_loop_carried_defs_reach_header_and_exit():
    cfg = _cfg_of("""
        def f(xs):
            total = 0
            for x in xs:
                total = total + x
            return total
    """)
    uses = _use_defs_lines(cfg, "total")
    assert uses[4] == [2, 4]   # in-loop use: initial AND loop-carried
    assert uses[5] == [2, 4]   # after the loop: both reach the return


def test_cfg_except_handler_sees_mid_try_defs():
    """Exception edges carry defs WITHOUT the kill: the raise may have
    happened before or after the rebind, so both defs reach."""
    cfg = _cfg_of("""
        def f(boom):
            x = 1
            try:
                x = 2
                boom()
            except ValueError:
                y = x
            return x
    """)
    uses = _use_defs_lines(cfg, "x")
    assert uses[7] == [2, 4]   # the handler sees pre- and mid-try defs
    assert uses[8] == [2, 4]


def test_cfg_tuple_unpacking_defines_and_kills():
    cfg = _cfg_of("""
        def f(pair):
            a, b = pair
            b, a = a, b
            return a + b
    """)
    uses_a = _use_defs_lines(cfg, "a")
    assert uses_a[3] == [2]    # swap reads the unpacked def
    assert uses_a[4] == [3]    # return reads ONLY the re-bind (killed)
    # function parameters are definitions at the entry node
    chains = def_use_chains(cfg)
    pair_use = next(k for k in chains if k[1] == "pair")
    assert chains[pair_use] == {cfg.entry.idx}


def test_cfg_while_break_paths():
    cfg = _cfg_of("""
        def f(xs):
            y = 0
            while True:
                y = xs.pop()
                if not xs:
                    break
            return y
    """)
    assert _use_defs_lines(cfg, "y")[7] == [2, 4]


def _ctx_of(src: str) -> FileCtx:
    src = textwrap.dedent(src).strip() + "\n"
    return FileCtx(Path("mem.py"), "mem.py", src, ast.parse(src))


def test_jit_boundary_factory_chain_and_conditional_union():
    """The interprocedural map follows a factory chain and unions
    conditional donate specs — the build_sweep/_make_sweep shape."""
    ctx = _ctx_of("""
        import jax

        def _make(donate):
            def sweep(factors, grams, first):
                return factors
            return jax.jit(sweep, static_argnames=("first",),
                           donate_argnums=(0, 1) if donate else ())

        def _make_other():
            def sweep(factors, grams, first):
                return factors
            return sweep

        def build(phased, donate):
            return (_make_other if phased else _make)(donate)
    """)
    jb = jit_boundary(ctx)
    assert jb.factories["_make"].donate_argnums == {0, 1}
    assert jb.factories["_make"].static_argnames == {"first"}
    assert jb.factories["build"].donate_argnums == {0, 1}
    assert "_make_other" not in jb.factories


def test_jit_boundary_wrapped_and_traced():
    ctx = _ctx_of("""
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("mode",))
        def decorated(x, mode):
            return x

        def plain(a, b):
            return a + b

        wrapped = jax.jit(plain, donate_argnums=(0,))
    """)
    jb = jit_boundary(ctx)
    assert jb.wrapped["decorated"].static_argnames == {"mode"}
    assert jb.wrapped["wrapped"].donate_argnums == {0}
    traced_names = {fn.name for fn, _ in jb.traced}
    assert traced_names == {"decorated", "plain"}


# -- analyzer coverage: class methods, direct calls, loop headers -----------

from tools.splint.core import Project  # noqa: E402
from tools.splint.rules import (CacheLockDiscipline,  # noqa: E402
                                RecompileTrigger, RunReportEventDrift,
                                UseAfterDonate)


def _rule_hits(rule, src: str):
    ctx = _ctx_of(src)
    project = Project(_cfg())
    project.files.append(ctx)
    return rule.check(ctx, project) + rule.finalize(project)


_DONATING_FACTORY = """
    import jax

    def make_step(reg):
        def step(state, grad):
            return state - reg * grad
        return jax.jit(step, donate_argnums=(0,))
"""


def test_spl008_covers_class_methods():
    hits = _rule_hits(UseAfterDonate(), _DONATING_FACTORY + """
    class Driver:
        def run(self, state, grad, reg):
            step = make_step(reg)
            new = step(state, grad)
            return state + new
""")
    assert hits and "state" in hits[0].message


def test_spl008_covers_unbound_factory_invocation():
    """A donating factory invoked without ever binding the wrapper —
    make_step(reg)(state, grad) — still donates its argnums."""
    hits = _rule_hits(UseAfterDonate(), _DONATING_FACTORY + """
    def run(state, grad, reg):
        new = make_step(reg)(state, grad)
        return state + new
""")
    assert hits and "state" in hits[0].message


def test_spl010_loop_header_is_not_in_the_loop():
    """A jit call in a for-statement's ITERABLE evaluates once per
    loop entry — flagging it would hard-fail the zero-budget gate on
    correct code.  The body (and a while test) re-run per iteration."""
    clean = _rule_hits(RecompileTrigger(), """
    import jax

    def f(g, xs):
        out = []
        for step in (jax.jit(g), jax.jit(g)):
            out.append(step(xs))
        return out
""")
    assert not clean
    dirty = _rule_hits(RecompileTrigger(), """
    import jax

    def f(g, xs):
        n = 0
        while jax.jit(g)(xs) > 0:
            n += 1
        return n
""")
    assert any("inside a loop" in h.message for h in dirty)


def test_spl010_covers_class_methods():
    hits = _rule_hits(RecompileTrigger(), """
    import jax

    class Driver:
        def run(self, x):
            f = jax.jit(lambda a, cfg: a, static_argnums=(1,))
            return f(x, [1, 2, 3])
""")
    assert any("unhashable" in h.message for h in hits)


def test_spl011_covers_class_methods():
    hits = _rule_hits(CacheLockDiscipline(), """
    import json
    import pathlib

    def cache_path():
        return pathlib.Path("/tmp/c.json")

    class Store:
        def flush(self, data):
            with open(cache_path(), "w") as f:
                json.dump(data, f)
""")
    assert any("bypasses the locked" in h.message for h in hits)


def test_spl012_covers_aliased_report():
    """rr = run_report(); rr.add(...) is the same emission surface."""
    hits = _rule_hits(RunReportEventDrift(), """
    from splatt_tpu import resilience

    def emit(err):
        rr = resilience.run_report()
        rr.add("spl012_alias_undeclared_event", error=str(err))
""")
    assert any("spl012_alias_undeclared_event" in h.message
               for h in hits)


# -- the concurrency family (SPL014-SPL018, tools/splint/locks.py) ----------

from tools.splint.locks import (FileLocks,  # noqa: E402
                                iter_scope_functions, lock_walk)
from tools.splint.rules import (BlockingCallUnderLock,  # noqa: E402
                                ContextvarLeak, LockOrderCycle,
                                SharedStateWithoutLock)


def _lock_walk_of(src: str):
    ctx = _ctx_of(src)
    fl = FileLocks(ctx)
    fns = list(iter_scope_functions(ctx.tree))
    fn, cls = fns[-1]
    return ctx, lock_walk(ctx, fn, cls, fl)


def test_lock_walk_with_nesting_and_restore():
    src = """
        import threading

        _A = threading.Lock()
        _B = threading.Lock()

        def f(x):
            before = 1
            with _A:
                inside_a = 2
                with _B:
                    inside_ab = 3
                after_b = 4
            after_a = 5
    """
    ctx, walk = _lock_walk_of(src)
    held_by_line = {}
    fn = [s for s in ast.walk(ctx.tree)
          if isinstance(s, ast.FunctionDef)][0]
    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.stmt) and id(stmt) in walk.held_at:
            held_by_line[stmt.lineno] = {
                h.split("::")[-1] for h in walk.held_at[id(stmt)]}
    assert held_by_line[7] == set()          # before
    assert held_by_line[9] == {"_A"}         # inside_a
    assert held_by_line[11] == {"_A", "_B"}  # inside_ab
    assert held_by_line[12] == {"_A"}        # after_b: _B restored
    assert held_by_line[13] == set()         # after_a: both restored
    # acquisition sites record the held-before sets (SPL015's edges)
    acq = {(lid.split("::")[-1], tuple(sorted(
        h.split("::")[-1] for h in held)))
        for lid, _line, held in walk.acquisitions}
    assert acq == {("_A", ()), ("_B", ("_A",))}


def test_lock_walk_acquire_release_pairs_and_closures():
    src = """
        import threading

        _A = threading.Lock()

        def f(xs):
            _A.acquire()
            xs.append(1)
            _A.release()
            xs.append(2)
            def closure():
                xs.append(3)  # runs later: NOT under _A
    """
    ctx, walk = _lock_walk_of(src)
    fn = [s for s in ast.walk(ctx.tree)
          if isinstance(s, ast.FunctionDef) and s.name == "f"][0]
    held = {s.lineno: walk.held_at[id(s)] for s in fn.body
            if id(s) in walk.held_at}
    assert not held[6]                      # before acquire
    assert any(held[7])                     # between the pair
    assert not held[9]                      # after release


def test_spl015_cross_function_cycle_and_self_loop():
    hits = _rule_hits(LockOrderCycle(), """
    import threading

    _A = threading.Lock()
    _B = threading.Lock()

    def ab():
        with _A:
            with _B:
                pass

    def ba():
        with _B:
            with _A:
                pass
""")
    assert any("cycle" in h.message and "_A" in h.message
               and "_B" in h.message for h in hits)
    # self-loop: re-acquiring a non-reentrant lock under itself
    hits = _rule_hits(LockOrderCycle(), """
    import threading

    _A = threading.Lock()

    def helper():
        with _A:
            pass

    def outer():
        with _A:
            helper()
""")
    assert any("cycle" in h.message for h in hits)


def test_spl015_interprocedural_edge_through_method_call():
    """An edge discovered through a call under a held lock: outer holds
    Server's lock while calling a helper that takes the metrics lock —
    plus the reverse nesting elsewhere closes the cycle."""
    hits = _rule_hits(LockOrderCycle(), """
    import threading

    _MET = threading.Lock()

    def record():
        with _MET:
            pass

    class Server:
        def __init__(self):
            self._lock = threading.Lock()

        def poll(self):
            with self._lock:
                record()

        def backwards(self):
            with _MET:
                with self._lock:
                    pass
""")
    assert any("cycle" in h.message for h in hits)


def test_spl017_flags_transitive_blocking_and_exempts_str_join():
    cfg = _cfg(hot_lock_paths=["mem.py::submit"])
    src = """
    import os
    import threading

    class Journal:
        def append(self, rec):
            with open("/tmp/j", "ab") as f:
                f.write(rec)
                os.fsync(f.fileno())

    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self.journal = Journal()

        def submit(self, jid, parts):
            with self._lock:
                label = ", ".join(parts)   # str.join: NOT blocking
                self.journal.append(label.encode())
            return jid
"""
    ctx = _ctx_of(src)
    project = Project(cfg)
    project.files.append(ctx)
    rule = BlockingCallUnderLock()
    hits = rule.check(ctx, project) + rule.finalize(project)
    assert len(hits) == 1, [h.message for h in hits]
    assert "via Journal.append" in hits[0].message
    assert "fsync" in hits[0].message or "flock" in hits[0].message


def test_spl018_enter_exit_pairs_are_exempt():
    hits = _rule_hits(ContextvarLeak(), """
    import contextvars

    _STACK = contextvars.ContextVar("stack", default=())

    class Handle:
        def __enter__(self):
            _STACK.set(_STACK.get() + (self,))
            return self

        def __exit__(self, *exc):
            _STACK.set(tuple(s for s in _STACK.get() if s is not self))
            return False
""")
    assert not hits


def test_spl014_flags_mutators_outside_bare_expressions():
    """A mutator call is a write wherever it appears — assigned
    (`jid = self._queue.pop(0)`), in a test position, in a return —
    not only as a bare expression statement (review-found gap)."""
    cfg = _cfg(shared_state=["mem.py::self._queue=self._lock"])
    src = """
    import threading

    class S:
        def __init__(self):
            self._lock = threading.Lock()
            self._queue = []

        def bad_pick(self):
            jid = self._queue.pop(0)
            return jid

        def bad_test(self):
            if self._queue.pop(0):
                return True

        def good_pick(self):
            with self._lock:
                return self._queue.pop(0)
"""
    ctx = _ctx_of(src)
    project = Project(cfg)
    project.files.append(ctx)
    hits = SharedStateWithoutLock().check(ctx, project)
    assert sorted(f.line for f in hits) == [9, 13]


def test_spl014_alias_imprecision_is_documented_not_flagged():
    """Mutation through an alias is the documented blind spot — the
    SPLATT_LOCKCHECK runtime sanitizer covers it dynamically."""
    cfg = _cfg(shared_state=["mem.py::self._jobs=self._lock"])
    src = """
    import threading

    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self._jobs = {}

        def touch(self, jid):
            j = self._jobs[jid]
            j["state"] = "started"   # alias write: not seen
"""
    ctx = _ctx_of(src)
    project = Project(cfg)
    project.files.append(ctx)
    rule = SharedStateWithoutLock()
    assert not rule.check(ctx, project)


def _copy_serve_tree(tmp_path, mutate):
    """A tmp mini-tree holding the REAL serve.py (+ its durable-write
    helper, preserving the package layout the call summaries resolve
    against), with `mutate(src) -> src` applied to serve.py."""
    pkg = tmp_path / "splatt_tpu"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "serve.py").write_text(
        mutate((REPO / "splatt_tpu" / "serve.py").read_text()))
    (pkg / "utils" / "durable.py").write_text(
        (REPO / "splatt_tpu" / "utils" / "durable.py").read_text())
    cfg = _cfg()
    cfg.root = tmp_path
    cfg.paths = ["splatt_tpu"]
    return cfg


def test_spl017_fires_when_submit_journals_under_the_lock(tmp_path):
    """Re-introducing the PR 11 submit bug — the durable accept append
    moved INSIDE the server lock — must trip SPL017 through the
    interprocedural summary (the fsync is two calls down, in the
    shared durable-write helper).  The unmutated file is clean (also
    covered by the tree gate)."""
    anchor = ("self._jobs[jid] = "
              "self._new_job_locked(spec, ACCEPTING)")

    def mutate(src):
        assert anchor in src, "serve.py submit anchor drifted"
        return src.replace(
            anchor,
            anchor + "\n                self.journal.append("
                     "self._rec(ACCEPTED, jid, spec=spec))")

    cfg = _copy_serve_tree(tmp_path, mutate)
    hits = [f for f in run(cfg, baseline={}).findings
            if f.rule == "SPL017"]
    assert hits and any("Journal.append" in f.message for f in hits)


def test_spl014_fires_when_replay_drops_the_lock(tmp_path):
    """Deleting _replay's server-lock region (the pre-PR-12 shape)
    must trip SPL014 on the queue/job-table mutations — proof the
    shared-state map guards the real file, not a fixture."""
    def mutate(src):
        anchor = ("        resumed: List[tuple] = []\n"
                  "        with self._lock:")
        assert anchor in src, "serve.py _replay anchor drifted"
        return src.replace(
            anchor, "        resumed: List[tuple] = []\n"
                    "        if True:")

    cfg = _copy_serve_tree(tmp_path, mutate)
    hits = [f for f in run(cfg, baseline={}).findings
            if f.rule == "SPL014"]
    assert hits and any("_queue" in f.message or "_jobs" in f.message
                        for f in hits)


def test_spl020_fires_when_backstop_fence_reverted(tmp_path):
    """Reverting the PR 17 fix — _backstop_fail's lease fence before
    its terminal FAILED append — must trip SPL020: the append is then
    reachable without a dominating renew, the exact zombie-commit
    shape the fence exists to kill."""
    anchor = "        if not self._renew_fence(jid):"

    def mutate(src):
        assert anchor in src, "serve.py _backstop_fail anchor drifted"
        return src.replace(anchor, "        if jid is None:", 1)

    cfg = _copy_serve_tree(tmp_path, mutate)
    hits = [f for f in run(cfg, baseline={}).findings
            if f.rule == "SPL020"]
    assert hits and any("_backstop_fail" in f.message for f in hits)


def test_spl022_fires_when_replay_gate_reverted(tmp_path):
    """Reverting the PR 17 forward-compat gate — _apply_rec_locked's
    KNOWN_KINDS membership check — must trip SPL022's never-consulted
    leg: a declared vocabulary replay no longer reads is exactly the
    drift the rule polices."""
    anchor = "if kind not in KNOWN_KINDS:"

    def mutate(src):
        assert anchor in src, "serve.py replay-gate anchor drifted"
        return src.replace(anchor, "if not isinstance(kind, str):", 1)

    cfg = _copy_serve_tree(tmp_path, mutate)
    hits = [f for f in run(cfg, baseline={}).findings
            if f.rule == "SPL022"]
    assert hits and any("KNOWN_KINDS" in f.message for f in hits)


def test_spl019_fires_when_publish_dir_fsync_reverted(tmp_path):
    """Reverting the PR 17 durability fix — publish_bytes' post-rename
    directory fsync — must trip SPL019 on the helper itself: without
    the barrier the rename can be lost on power failure after the
    caller was acknowledged (the crash-point checker's rename-lost
    states show the resulting data loss dynamically)."""
    pkg = tmp_path / "splatt_tpu"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "serve.py").write_text(
        (REPO / "splatt_tpu" / "serve.py").read_text())
    src = (REPO / "splatt_tpu" / "utils" / "durable.py").read_text()
    anchor = ("        os.replace(tmp, path)\n"
              "        if fsync:\n"
              "            _fsync_dir(path)")
    assert anchor in src, "durable.py publish_bytes anchor drifted"
    (pkg / "utils" / "durable.py").write_text(
        src.replace(anchor, "        os.replace(tmp, path)", 1))
    cfg = _cfg()
    cfg.root = tmp_path
    cfg.paths = ["splatt_tpu"]
    hits = [f for f in run(cfg, baseline={}).findings
            if f.rule == "SPL019"
            and f.path.endswith("durable.py")]
    assert hits and any("publish_bytes" in f.message for f in hits)


def test_shared_state_config_is_well_formed():
    """Every [tool.splint] shared-state / hot-lock-paths entry parses
    and points at a real file (a typo'd map silently unguards)."""
    from tools.splint.rules import _parse_shared_state

    cfg = _cfg()
    by_file = _parse_shared_state(cfg.shared_state)
    assert "splatt_tpu/serve.py" in by_file
    assert ("self._jobs", "self._lock") in by_file["splatt_tpu/serve.py"]
    for rel in by_file:
        assert (REPO / rel).is_file(), rel
    for entry in cfg.hot_lock_paths:
        rel, name = entry.split("::")
        assert (REPO / rel).is_file(), rel
    with pytest.raises(ValueError):
        _parse_shared_state(["no-separator"])


# -- the SPL008 guard: cpd.py's re-materialization is load-bearing ----------

def test_spl008_fires_when_cpd_rematerialization_deleted(tmp_path):
    """Deleting the engine-rescue re-materialization lines from cpd.py
    must make SPL008 fire — proof the analyzer actually guards the
    donated-sweep contract rather than pattern-matching today's file."""
    src = (REPO / "splatt_tpu" / "cpd.py").read_text()
    targets = ["factors = [jnp.asarray(u) for u in snap[0]]",
               "grams = [jnp.asarray(g) for g in snap[1]]"]
    mutated = src
    for t in targets:
        assert t in mutated, f"cpd.py no longer contains {t!r}"
        mutated = mutated.replace(t, "pass")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "cpd.py").write_text(mutated)
    report = run(Config(root=tmp_path, paths=["pkg"]), baseline={})
    hits = [f for f in report.findings if f.rule == "SPL008"]
    assert hits, "SPL008 must fire once the re-materialization is gone"
    assert any("factors" in f.message or "grams" in f.message
               for f in hits)
    # the unmutated file is clean (also covered by the tree gate)
    (pkg / "cpd.py").write_text(src)
    report = run(Config(root=tmp_path, paths=["pkg"]), baseline={})
    assert not [f for f in report.findings if f.rule == "SPL008"]


# -- entry points stay in lockstep ------------------------------------------

def test_cli_json_matches_pytest_wiring():
    """`python -m tools.splint --json` (the CLI/CI entry) agrees with
    the in-process run the tests use."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.splint", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    baseline = load_baseline(REPO / "tools" / "splint" / "baseline.json")
    report = run(_cfg(), baseline=baseline)
    assert len(payload["findings"]) == len(report.findings)


def test_cli_focus_analyzes_full_tree():
    """Positional paths focus the report only: no false SPL006 drift
    from a partial view, and a focused --update-baseline still rewrites
    from the full tree instead of destroying unanalyzed files' entries."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.splint", "splatt_tpu/ops"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no production call" not in proc.stdout
    assert "focused on splatt_tpu/ops" in proc.stdout


def test_cli_focused_update_baseline_keeps_all_groups(tmp_path):
    bl = tmp_path / "bl.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.splint", "splatt_tpu/ops",
         "--baseline", str(bl), "--update-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    repo_groups = set(load_baseline(
        REPO / "tools" / "splint" / "baseline.json"))
    assert set(load_baseline(bl)) == repo_groups


def test_cli_json_lockstep_for_dataflow_rules():
    """CLI --json findings for the SPL008-SPL012 family agree exactly
    (rule, path, line) with the in-process run pytest gates on."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.splint", "--json", "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    payload = json.loads(proc.stdout)
    new_rules = {"SPL008", "SPL009", "SPL010", "SPL011", "SPL012"}
    cli = sorted((f["rule"], f["path"], f["line"])
                 for f in payload["findings"] if f["rule"] in new_rules)
    report = run(_cfg(), baseline={})
    mine = sorted((f.rule, f.path, f.line)
                  for f in report.findings if f.rule in new_rules)
    assert cli == mine


def test_cli_json_lockstep_for_concurrency_rules(tmp_path):
    """CLI --json ≡ in-process for the SPL014-SPL018 family, on a
    mini-project holding the bad fixtures (the production tree is
    clean for them by the zero-budget gate, so lockstep there would
    compare empty sets).  Same pyproject, same analyzer, same
    findings — the CI entry point cannot drift from the pytest gate."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    for n in ("014", "015", "016", "017", "018"):
        name = f"spl{n}_bad.py"
        (pkg / name).write_text((FIXTURES / name).read_text())
    (tmp_path / "pyproject.toml").write_text(
        '[tool.splint]\n'
        'paths = ["pkg"]\n'
        'shared-state = ["pkg/spl014_bad.py::self._jobs=self._lock",\n'
        '               "pkg/spl014_bad.py::_TABLE=_TABLE_LOCK"]\n'
        'durable-write-helpers = ["publish_bytes"]\n'
        'hot-lock-paths = ["pkg/spl017_bad.py::submit_hot"]\n')
    proc = subprocess.run(
        [sys.executable, "-m", "tools.splint", "--root", str(tmp_path),
         "--json", "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    payload = json.loads(proc.stdout)
    fam = {"SPL014", "SPL015", "SPL016", "SPL017", "SPL018"}
    cli = sorted((f["rule"], f["path"], f["line"])
                 for f in payload["findings"] if f["rule"] in fam)
    report = run(load_config(tmp_path), baseline={})
    mine = sorted((f.rule, f.path, f.line)
                  for f in report.findings if f.rule in fam)
    assert cli and cli == mine
    assert {r for r, _, _ in cli} == fam  # every rule fires somewhere


def test_cli_sarif_structure(tmp_path):
    """`--sarif` writes a SARIF 2.1.0 log whose results agree with the
    --json findings — the CI code-scanning upload cannot drift from
    the gate.  Checked on a mini-project where SPL024 actually fires
    (the production tree is clean, so its results array is empty)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "spl024_bad.py").write_text(
        (FIXTURES / "spl024_bad.py").read_text())
    (tmp_path / "pyproject.toml").write_text(
        '[tool.splint]\n'
        'paths = ["pkg"]\n'
        'numerics-modules = ["pkg/spl024_bad.py"]\n')
    sarif_path = tmp_path / "out.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.splint", "--root", str(tmp_path),
         "--sarif", str(sarif_path), "--json", "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    sarif = json.loads(sarif_path.read_text())
    assert sarif["version"] == "2.1.0"
    driver = sarif["runs"][0]["tool"]["driver"]
    assert driver["name"] == "splint"
    by_id = {r["id"]: r for r in driver["rules"]}
    assert "SPL024" in by_id
    assert len(by_id["SPL024"]["shortDescription"]["text"]) > 10
    results = sarif["runs"][0]["results"]
    got = sorted(
        (r["ruleId"],
         r["locations"][0]["physicalLocation"]["artifactLocation"]["uri"],
         r["locations"][0]["physicalLocation"]["region"]["startLine"])
        for r in results)
    want = sorted((f["rule"], f["path"], f["line"])
                  for f in payload["findings"])
    assert got and got == want
    assert all(r["ruleId"] in by_id for r in results)
    # new findings carry no suppression; none are baselined here
    assert not any("suppressions" in r for r in results)
    # the clean production tree writes an empty results array
    clean_path = tmp_path / "clean.sarif"
    clean = subprocess.run(
        [sys.executable, "-m", "tools.splint",
         "--sarif", str(clean_path)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert json.loads(clean_path.read_text())["runs"][0]["results"] == []


def test_cli_list_rules_covers_new_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.splint", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for rid in ("SPL008", "SPL009", "SPL010", "SPL011", "SPL012",
                "SPL014", "SPL015", "SPL016", "SPL017", "SPL018",
                "SPL024", "SPL025", "SPL026", "SPL027", "SPL028",
                "SPL029"):
        line = next((ln for ln in proc.stdout.splitlines()
                     if ln.startswith(rid)), "")
        assert line and len(line.split(None, 1)[1]) > 10, \
            f"--list-rules lacks a one-line summary for {rid}"


def test_cli_explain_prints_doc_and_fixtures():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.splint", "--explain", "SPL008"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "SPL008" in proc.stdout
    assert "donate" in proc.stdout          # the rule doc
    assert "known-bad fixture" in proc.stdout
    assert "known-good fixture" in proc.stdout
    assert "spl008_bad.py" in proc.stdout
    bad = subprocess.run(
        [sys.executable, "-m", "tools.splint", "--explain", "SPL999"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert bad.returncode == 2
    assert "unknown rule" in bad.stderr


def test_full_tree_run_stays_fast():
    """The splint pass rides in tier-1 on every pytest run: a full-tree
    analysis (all rules, the dataflow passes, AND the v4 durability
    rules) plus one full crash-point enumeration must stay well under
    12 s or the gate starts costing more than it protects.  The
    crash-state count is bounded here too: the checker's cost is
    linear in enumerated states, so an accidental combinatorial
    blow-up (a new init x op product) fails this gate before it
    swamps CI."""
    from tools.splint.crashpoint import run_crash_check

    baseline = load_baseline(REPO / "tools" / "splint" / "baseline.json")
    t0 = time.perf_counter()
    run(_cfg(), baseline=baseline)
    crash = run_crash_check()
    elapsed = time.perf_counter() - t0
    assert crash.states <= 64, (
        f"crash-point enumeration grew to {crash.states} states — "
        f"bound it or move the new protocol to the slow tier")
    assert elapsed < 12.0, (
        f"full-tree splint + crash-point run took {elapsed:.1f}s")


def test_env_docs_render():
    from tools.splint.__main__ import _env_docs

    table = _env_docs(_cfg())
    assert "SPLATT_ENGINE_FALLBACK" in table
    assert "SPLATT_PROBE_CACHE_TTL_S" in table
    assert "| variable |" in table


def test_pyproject_table_parser():
    text = ('[tool.other]\nx = 1\n[tool.splint]\npaths = ["a",\n'
            '  "b"]\nbaseline = "bl.json"\n[tool.after]\ny = 2\n')
    table = _parse_table(text, "tool.splint")
    assert table == {"paths": ["a", "b"], "baseline": "bl.json"}


def test_config_matches_pyproject():
    cfg = load_config(REPO)
    assert cfg.paths == ["splatt_tpu"]
    assert cfg.resolve(cfg.baseline).exists()
    assert "_cache_io_error" in cfg.resilience_routers
    assert cfg.resilience_module == "splatt_tpu/resilience.py"
    assert cfg.trace_module == "splatt_tpu/trace.py"
    assert "SPL013" in cfg.zero_rules
    assert set(cfg.cache_path_functions) == {"_cache_path", "cache_path"}
    assert "_json_cache_update" in cfg.cache_io_helpers
    assert "_json_cache_load" in cfg.cache_io_helpers
    # the concurrency family (SPL014-SPL018) is zero-budget and its
    # three config keys are populated
    assert {"SPL014", "SPL015", "SPL016", "SPL017", "SPL018"} \
        <= set(cfg.zero_rules)
    assert any(e.startswith("splatt_tpu/serve.py::self._jobs=")
               for e in cfg.shared_state)
    assert any(e.startswith("splatt_tpu/tune.py::_MEM=")
               for e in cfg.shared_state)
    assert {"publish_bytes", "publish_json", "publish_file",
            "append_line"} <= set(cfg.durable_write_helpers)
    assert "splatt_tpu/serve.py::submit" in cfg.hot_lock_paths
    # the v5 numerics/tiling family (SPL024-SPL028) is zero-budget and
    # its config surface is populated
    assert {"SPL024", "SPL025", "SPL026", "SPL027", "SPL028"} \
        <= set(cfg.zero_rules)
    assert "splatt_tpu/ops/linalg.py" in cfg.numerics_modules
    assert "acc_dtype" in cfg.acc_dtype_helpers
    assert "splatt_tpu/cpd.py::_zz_inner" in cfg.hot_stream_functions
    assert any(e.startswith("splatt_tpu/cpd.py::_zz_inner::U_last=")
               for e in cfg.hot_stream_param_dtypes)
    assert "splatt_tpu/ops/pallas_kernels.py" in cfg.pallas_modules
    assert "tile_packing" in cfg.tile_pack_helpers
    assert int(cfg.vmem_budget_mib) > 0
    gate_map = dict(e.split("=") for e in cfg.vmem_gate_map)
    assert gate_map["fused_mttkrp"] == "fused_vmem_ok"
    assert "_tuned_plan_for" in cfg.plan_match_functions
    # SPL005 joined the zero-rules in the v5 burn-down
    assert "SPL005" in cfg.zero_rules


# -- the v5 guards: the numerics/tiling fixes are load-bearing --------------
#
# Each test re-introduces one production bug the v5 pass fixed (or a
# regression the rules exist to catch) into a tmp copy of the REAL
# package tree and asserts the matching rule fires.  The unmutated
# tree is clean (the tree gate above), so these prove the rules guard
# the real files, not just the fixtures.

def _copy_package_tree(tmp_path, rel, mutate):
    """A tmp copy of the full splatt_tpu package (+ the docs the
    registry rules read) with `mutate(src) -> src` applied to `rel`."""
    shutil.copytree(REPO / "splatt_tpu", tmp_path / "splatt_tpu")
    (tmp_path / "docs").mkdir()
    shutil.copy(REPO / "docs" / "observability.md", tmp_path / "docs")
    target = tmp_path / rel
    target.write_text(mutate(target.read_text()))
    cfg = _cfg()
    cfg.root = tmp_path
    cfg.paths = ["splatt_tpu"]
    return cfg


def test_spl024_fires_when_gram_pin_reverted(tmp_path):
    """Dropping gram's preferred_element_type pin — the exact shape
    the reference port had before the v5 fix — must trip SPL024: a
    bf16 factor would then accumulate its Gram matrix at bf16 and feed
    the error straight into the normal equations."""
    anchor = ("    return jnp.matmul(U.T, U, "
              "preferred_element_type=acc_dtype(U.dtype),\n"
              "                      precision=mxu_precision(U.dtype))")

    def mutate(src):
        assert anchor in src, "linalg.py gram anchor drifted"
        return src.replace(anchor, "    return jnp.matmul(U.T, U)")

    cfg = _copy_package_tree(tmp_path, "splatt_tpu/ops/linalg.py", mutate)
    hits = [f for f in run(cfg, baseline={}).findings
            if f.rule == "SPL024" and f.path.endswith("linalg.py")]
    assert hits and any("matmul" in f.message for f in hits)


def test_spl025_fires_when_rank_pad_reverted(tmp_path):
    """Reverting a kernel's rank padding to the dtype-blind
    ``ceil_to(R, 8)`` (the pre-v5 shape: correct for f32, half the
    sublane tile for bf16) must trip SPL025 on the block position the
    padded value certifies."""
    anchor = ("    R8 = _rank_pad(R, dtype)\n"
              "    others = [k for k in range(layout.nmodes) "
              "if k != mode]\n"
              "    grid = (nb,)\n")

    def mutate(src):
        assert anchor in src, "pallas_kernels.py rank-pad anchor drifted"
        return src.replace(
            anchor,
            "    R8 = ceil_to(R, 8)\n"
            "    others = [k for k in range(layout.nmodes) "
            "if k != mode]\n"
            "    grid = (nb,)\n", 1)

    cfg = _copy_package_tree(
        tmp_path, "splatt_tpu/ops/pallas_kernels.py", mutate)
    hits = [f for f in run(cfg, baseline={}).findings
            if f.rule == "SPL025"]
    assert hits and any("R8" in f.message for f in hits)


def test_spl026_fires_when_gate_consult_dropped(tmp_path):
    """Short-circuiting the fused_t dispatch gate — the kernel runs
    whether or not its block plan fits VMEM — must trip SPL026's
    registry leg: the declared gate is never consulted."""
    anchor = ('    if pallas and live("fused_t") and '
              "fused_t_vmem_ok(factors, mode,")

    def mutate(src):
        assert anchor in src, "mttkrp.py fused_t gate anchor drifted"
        return src.replace(
            anchor,
            '    if pallas and live("fused_t") and '
            "(lambda *a: True)(factors, mode,", 1)

    cfg = _copy_package_tree(tmp_path, "splatt_tpu/ops/mttkrp.py", mutate)
    hits = [f for f in run(cfg, baseline={}).findings
            if f.rule == "SPL026"]
    assert hits and any("fused_t_vmem_ok" in f.message
                        and "consulted" in f.message for f in hits)


def test_spl027_fires_when_match_comparison_dropped(tmp_path):
    """Deleting one strict-match comparison from _tuned_plan_for (a
    plan measured for another nnz block would then steer this
    dispatch) must trip SPL027's dispatch leg."""
    anchor = "            or plan.nnz_block != layout.block\n"

    def mutate(src):
        assert anchor in src, "mttkrp.py plan-match anchor drifted"
        return src.replace(anchor, "", 1)

    cfg = _copy_package_tree(tmp_path, "splatt_tpu/ops/mttkrp.py", mutate)
    hits = [f for f in run(cfg, baseline={}).findings
            if f.rule == "SPL027"]
    assert hits and any("nnz_block" in f.message for f in hits)


def test_spl028_fires_when_zz_inner_product_reverted(tmp_path):
    """Reverting _zz_inner's pinned einsum to the elementwise
    ``M * U_last`` product must trip SPL028 under the declared storage
    contract (M wide, U_last narrow): the product materializes a wide
    (dim, R) intermediate ahead of the reduce — the doubled hot-loop
    bytes the rule exists to catch."""
    anchor = ('    inner = jnp.einsum("dr,dr,r->", M, U_last, lam,\n'
              "                       preferred_element_type=acc)")

    def mutate(src):
        assert anchor in src, "cpd.py _zz_inner anchor drifted"
        return src.replace(
            anchor,
            "    inner = jnp.sum(M * U_last * lam[None, :], dtype=acc)")

    cfg = _copy_package_tree(tmp_path, "splatt_tpu/cpd.py", mutate)
    hits = [f for f in run(cfg, baseline={}).findings
            if f.rule == "SPL028" and f.path.endswith("cpd.py")]
    assert hits


def test_run_report_registry_matches_runtime():
    """The RUN_REPORT_EVENTS registry is importable and every kind the
    RunReport summary formatter special-cases is declared — the static
    SPL012 check and the runtime reporting read the same surface."""
    from splatt_tpu.resilience import RUN_REPORT_EVENTS

    assert set(RUN_REPORT_EVENTS) >= {
        "transient_retry", "engine_demotion", "checkpoint_recovery",
        "probe_downgrade", "probe_cache_io_error", "tune_cache_io_error",
        "tuned_plan", "tuner_negative", "tuner_degraded", "block_clamp"}
    for kind, doc in RUN_REPORT_EVENTS.items():
        assert isinstance(doc, str) and len(doc) > 10, kind
