"""Staged TPU health probe: claim → small compile → bulk transfer →
single big MTTKRP compile+run → full-sweep compile.  Each stage prints
its wall time; run under `timeout` so a wedged stage is attributable.

Usage: python tools/stage_probe.py [stage...]   (default: all stages)
"""
from __future__ import annotations

import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

from splatt_tpu.utils.env import apply_env_platform

apply_env_platform()

T0 = time.perf_counter()


def note(msg):
    print(f"[t+{time.perf_counter() - T0:7.1f}s] {msg}", flush=True)


def main():
    stages = sys.argv[1:] or ["claim", "small", "xfer", "one_mttkrp",
                              "phased_sweep"]
    import numpy as np

    import jax
    import jax.numpy as jnp

    if "claim" in stages:
        d = jax.devices()[0]
        note(f"claimed {d.device_kind} ({d.platform})")

    if "small" in stages:
        x = jnp.ones((1024, 1024), jnp.bfloat16)
        (x @ x).block_until_ready()
        note("small matmul compiled+ran")

    if "xfer" in stages:
        a = np.random.default_rng(0).random((40_000_000,), np.float32)
        t = time.perf_counter()
        da = jax.device_put(a)
        da.block_until_ready()
        dt = time.perf_counter() - t
        note(f"160MB host->device in {dt:.1f}s ({160 / max(dt, 1e-9):.0f} MB/s)")
        t = time.perf_counter()
        float(jnp.sum(da))
        note(f"reduce+fetch in {time.perf_counter() - t:.1f}s")

    nnz = int(os.environ.get("PROBE_NNZ", 20_000_000))
    rank = 50
    if {"one_mttkrp", "sweep", "phased_sweep"} & set(stages):
        from bench import synthetic_nell2_like

        tt = synthetic_nell2_like(nnz)
        note(f"synthesized {nnz} nnz")

    if "one_mttkrp" in stages:
        from splatt_tpu.blocked import build_layout
        from splatt_tpu.ops.mttkrp import mttkrp_blocked

        rng = np.random.default_rng(0)
        lay = build_layout(tt, 0, block=4096, val_dtype=np.float32)
        fac = [jnp.asarray(rng.random((d, rank)), jnp.float32)
               for d in tt.dims]
        note("layout built")
        from splatt_tpu.utils.env import host_fence

        t = time.perf_counter()
        host_fence(mttkrp_blocked(lay, fac, 0, path="sorted_onehot",
                                  impl="xla"))
        note(f"single sorted_onehot xla compile+run in "
             f"{time.perf_counter() - t:.1f}s")
        t = time.perf_counter()
        host_fence(mttkrp_blocked(lay, [f * 1.0 for f in fac], 0,
                                  path="sorted_onehot", impl="xla"))
        note(f"warm run {time.perf_counter() - t:.2f}s")
        del lay

    if "sweep" in stages or "phased_sweep" in stages:
        from splatt_tpu.blocked import BlockedSparse
        from splatt_tpu.config import BlockAlloc, Options, Verbosity
        from splatt_tpu.cpd import (_make_phased_sweep, _make_sweep,
                                    init_factors)
        from splatt_tpu.ops.linalg import gram

        opts = Options(random_seed=7, verbosity=Verbosity.NONE,
                       val_dtype=jnp.float32, use_pallas=False,
                       block_alloc=BlockAlloc.ALLMODE)
        X = BlockedSparse.from_coo(tt, opts)
        note("blocked ALLMODE built")
        factors = init_factors(tt.dims, rank, 7, dtype=jnp.float32)
        grams = [gram(U) for U in factors]
        builder = (_make_phased_sweep if "phased_sweep" in stages
                   else _make_sweep)
        sweep = builder(X, tt.nmodes, 0.0)
        from splatt_tpu.utils.env import host_fence

        t = time.perf_counter()
        f2, g2, *_ = sweep(factors, grams, True)
        host_fence(f2)
        note(f"full first-sweep compile+run in {time.perf_counter() - t:.1f}s")
        t = time.perf_counter()
        f2, g2, *_ = sweep(f2, g2, False)
        host_fence(f2)
        note(f"subsequent sweep compile+run in {time.perf_counter() - t:.1f}s")
        t = time.perf_counter()
        for _ in range(3):
            f2, g2, *_ = sweep(f2, g2, False)
        host_fence(f2)
        note(f"3 warm sweeps in {time.perf_counter() - t:.1f}s "
             f"({(time.perf_counter() - t) / 3:.2f} s/it)")


if __name__ == "__main__":
    main()
