"""SPL016 bad: hand-rolled durable-write protocol — an inline fsync,
a tmp-write -> os.replace publish, and an append-mode open that
writes — all outside the sanctioned helpers.  Three call sites, three
chances for the protocol to drift (this one forgot to fsync before
the rename)."""

import json
import os


def publish_record(path, record):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f)
    os.replace(tmp, path)  # no fsync: a crash can publish empty bytes


def append_record(path, record):
    with open(path, "ab") as f:
        f.write(json.dumps(record).encode() + b"\n")
        f.flush()
        os.fsync(f.fileno())  # no torn-tail heal, no flock
