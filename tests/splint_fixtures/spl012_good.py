"""SPL012 good: emission sites name events declared in
resilience.py:RUN_REPORT_EVENTS."""

from splatt_tpu import resilience


def degrade_loudly(err):
    resilience.run_report().add(
        "engine_demotion", engine="example",
        failure_class="unknown", error=str(err))
