"""Environment/platform helpers shared by entry points."""

from __future__ import annotations

import os


def ceil_to(x: int, mult: int) -> int:
    """Round x up to a multiple of mult."""
    return ((x + mult - 1) // mult) * mult


def apply_env_platform() -> None:
    """Mirror JAX_PLATFORMS into jax.config.

    Some images install a site plugin (e.g. a TPU relay) that selects
    platforms programmatically at interpreter startup, which overrides
    the JAX_PLATFORMS env var.  Calling this before any backend
    initializes makes the env var authoritative again.
    """
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        try:
            jax.config.update("jax_platforms", platforms)
        except Exception:
            pass
