"""SPL026 good: a small, gate-registered kernel — the block budget
fits, the vmem-gate-map entry exists, and the gate is consulted at
dispatch."""

import jax
from jax.experimental import pallas as pl


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def toy_vmem_ok(nblocks, block_elems):
    # dispatch-time gate: both double-buffered copies must fit
    return 2 * 2 * block_elems * 4 <= (8 << 20)


def toy_pallas_entry(x):
    if not toy_vmem_ok(4, 128 * 128):
        raise ValueError("block too large for VMEM")
    return pl.pallas_call(
        _copy_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((512, 128), x.dtype),
    )(x)
