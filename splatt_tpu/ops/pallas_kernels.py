"""Pallas TPU kernels for the MTTKRP hot path.

The performance-critical reduction in blocked MTTKRP is

    out[b, s, :] = Σ_j  [local[b, j] == s] · prod[b, j, :]

i.e. a per-block one-hot contraction (S×B)@(B×R) — the TPU replacement
for the reference's scattered accumulation with its mutex pool /
privatization / tile scheduling (src/mttkrp.c:104-236).  XLA executes
the same einsum but materializes the one-hot operand (nb·S·B elements)
in HBM; the Pallas kernel builds it on the fly in VMEM with a
broadcasted iota-compare and feeds the MXU directly, so HBM traffic is
just prod in + partials out.

Two variants:
- :func:`onehot_reduce_sorted`  — per-block partials (sorted layouts,
  combined by a small scatter outside);
- :func:`onehot_reduce_full`    — full-width accumulation across the
  whole grid (privatized short modes, no scatter at all).

Both take `interpret=` so the differential tests run on CPU
(≙ tests running the real kernels at 7 threads, tests/mttkrp_test.c).
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from splatt_tpu.ops.mttkrp import _acc_dtype, onehot_precision
from splatt_tpu.utils.env import ceil_to

# Max blocks per grid step; the actual chunk is sized against VMEM by
# vmem_chunk() below.
_CHUNK = 8

# v5e VMEM is 128MiB (measured: a 120MB-working-set kernel compiles once
# the limit is raised; Mosaic's *default* scoped limit is ~16MB and
# rejects anything bigger).  v2/v3 cores have 16MiB — budgets derive
# from the device generation so dispatch gates stay truthful there.
_VMEM_BY_KIND = {"TPU v2": 16 << 20, "TPU v3": 16 << 20}


@functools.cache
def _vmem_limit() -> int:
    try:
        kind = jax.devices()[0].device_kind
    # splint: ignore[SPL002] device discovery off-accelerator: no
    # backend means "unknown kind", which selects the generic budget
    except Exception:
        kind = ""
    for prefix, size in _VMEM_BY_KIND.items():
        if kind.startswith(prefix):
            return size - (2 << 20)
    return 100 << 20


def _vmem_budget() -> int:
    return (_vmem_limit() * 24) // 25


def _compiler_params():
    from jax.experimental.pallas import tpu as pltpu

    # jax API drift: CompilerParams (new) was TPUCompilerParams before
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(vmem_limit_bytes=_vmem_limit())


def vmem_chunk(width: int, block: int, rank: int,
               itemsize: int = 4, budget_bytes: int = None,
               out_itemsize: int = None) -> int:
    """Blocks per grid step such that the kernel's working set —
    one-hot (C,width,block) + prod (C,block,rank) + out (C,width,rank) —
    fits the VMEM budget (_vmem_budget()//2, against the measured 128MiB
    v5e VMEM and the raised _VMEM_LIMIT compiler cap, leaving room for
    double buffering).  The out term is costed at the accumulator
    width (f32 even for bf16 inputs).  Returns 0 when even one block
    does not fit: callers must fall back to the XLA engine, which
    streams the one-hot through HBM instead.
    """
    if budget_bytes is None:
        budget_bytes = _vmem_budget() // 2
    if out_itemsize is None:
        out_itemsize = max(itemsize, 4)
    per_block = ((width * block + block * rank) * itemsize
                 + width * rank * out_itemsize)
    if per_block <= 0:
        return _CHUNK
    return min(_CHUNK, budget_bytes // per_block)


def _sorted_kernel(local_ref, prod_ref, out_ref, *, seg_width: int):
    local = local_ref[:, 0, :]                  # (C, B) int32
    prod = prod_ref[...]                        # (C, B, R)
    C, B = local.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, (C, seg_width, B), 1)
    onehot = (local[:, None, :] == iota).astype(prod.dtype)
    out_ref[...] = jax.lax.dot_general(
        onehot, prod,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=out_ref.dtype,
        precision=onehot_precision(prod.dtype, "lhs"))


def _full_kernel(local_ref, prod_ref, out_ref, *, width: int):
    local = local_ref[:, 0, :]                  # (C, B) int32
    prod = prod_ref[...]                        # (C, B, R)
    C, B = local.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, (C, width, B), 1)
    onehot = (local[:, None, :] == iota).astype(prod.dtype)
    part = jax.lax.dot_general(
        onehot, prod,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=out_ref.dtype,
        precision=onehot_precision(prod.dtype, "lhs"))    # (C, width, R)
    acc = jnp.sum(part, axis=0)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = acc

    @pl.when(pl.program_id(0) != 0)
    def _accum():
        out_ref[...] += acc


def _pad_blocks(local: jax.Array, prod: jax.Array, chunk: int):
    """Pad to whole chunks; local gains a singleton middle dim so its
    Mosaic block shape (chunk, 1, B) is legal for any chunk (the last
    two block dims must divide (8, 128) or equal the array dims)."""
    nb = local.shape[0]
    nb_pad = ceil_to(max(nb, 1), chunk)
    if nb_pad != nb:
        local = jnp.pad(local, ((0, nb_pad - nb), (0, 0)),
                        constant_values=-1)
        prod = jnp.pad(prod, ((0, nb_pad - nb), (0, 0), (0, 0)))
    return local[:, None, :], prod, nb_pad


@functools.partial(jax.jit,
                   static_argnames=("seg_width", "interpret", "chunk"))
def onehot_reduce_sorted(local: jax.Array, prod: jax.Array, seg_width: int,
                         interpret: bool = False,
                         chunk: int = _CHUNK) -> jax.Array:
    """(nb, B) local ids + (nb, B, R) partials → (nb, S, R) block partials."""
    nb = local.shape[0]
    B = local.shape[1]
    R = prod.shape[-1]
    local, prod, nb_pad = _pad_blocks(local, prod, chunk)
    grid = (nb_pad // chunk,)
    out = pl.pallas_call(
        functools.partial(_sorted_kernel, seg_width=seg_width),
        grid=grid,
        in_specs=[
            pl.BlockSpec((chunk, 1, B), lambda i: (i, 0, 0)),
            pl.BlockSpec((chunk, B, R), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((chunk, seg_width, R), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb_pad, seg_width, R),
                                       _acc_dtype(prod.dtype)),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(local, prod)
    return out[:nb]


# -- fused gather + Hadamard + reduce (transposed tables) -------------------
#
# The flagship kernel.  HBM traffic per MTTKRP is inds + vals + block
# partials — the factor tables are VMEM-resident for the whole sweep, so
# the (nnz, R) partial-product tensor of the unfused paths (3.7GB logical,
# 9.5GB after XLA's R→128 lane padding at NELL-2 scale — an HBM OOM)
# never exists anywhere.  ≙ the reference's register-blocked fiber loops
# reading factor rows in-cache (src/mttkrp.c:427-463).
#
# Two Mosaic constraints shape the design (jax 0.9.0):
# - only *same-shaped* take_along_axis gathers lower (tpu.dynamic_gather);
#   an arbitrary B-row gather from a (D, R) table must be phrased as
#   lane-wise take_along_axis on a *transposed* (R, D) table with the
#   request vector padded to D — so per-block gather cost scales with
#   max(B, D), and callers pick block ≈ max other-mode dim to amortize;
# - a (D, R) f32 table in VMEM pads R→128 lanes (14.7MB for NELL-2's
#   28818×50), while the transposed (R, D) form pads R→56 sublanes
#   (6.5MB): transposed tables are what make rank-50 f32 fit at all.
# Gathers run in 8-sublane tiles so temporaries stay ≤ (8, D).

_SUBLANE = 8


def _rank_pad(R: int, dtype) -> int:
    """Rank rows padded to the dtype's NATIVE sublane packing
    (config.tile_packing: 8 sublanes f32, 16 bf16/f16 — splint
    SPL025): the transposed factor tables and (R8, width) outputs tile
    their second-minor axis by rank, and a dtype-blind pad to 8
    under-packs narrow-dtype tiles 2x.  Always a multiple of
    ``_SUBLANE``, so the 8-row gather tiling below still divides it."""
    from splatt_tpu.config import tile_packing

    return ceil_to(int(R), tile_packing(dtype)[0])


def _tile_gather(u_t, gidx, B: int):
    """rows_t = u_t[:, idx] inside a Mosaic kernel, layout-safely.

    u_t: (R8, D) transposed factor table (VMEM-resident), R8 a multiple
    of 8, D of 128.  gidx: (ck, 8, D) int32 — the request vector
    pre-chunked into ck lane-aligned groups of D and replicated across
    8 sublanes *outside* the kernel.  Mosaic's layout inference rejects
    broadcasts/slices whose input carries a nonzero lane offset, so the
    kernel must only read whole aligned tiles: each take_along_axis here
    is the exact same-shaped (8, D) form tpu.dynamic_gather supports,
    and the only slice taken is [:, :B] at offset 0.
    """
    R8, D = u_t.shape
    ck = gidx.shape[0]
    pieces = []
    for c in range(ck):
        idx8 = gidx[c]                       # (8, D), aligned tile
        tiles = [jnp.take_along_axis(u_t[r0:r0 + _SUBLANE, :], idx8, axis=1)
                 for r0 in range(0, R8, _SUBLANE)]
        pieces.append(tiles[0] if len(tiles) == 1
                      else jnp.concatenate(tiles, axis=0))   # (R8, D)
    rows = pieces[0] if ck == 1 else jnp.concatenate(pieces, axis=1)
    return rows[:, :B]


def _fused_t_kernel(local_ref, vals_ref, *refs,
                    width: int, accumulate: bool, nother: int):
    gidx_refs = refs[:nother]
    ut_refs = refs[nother:2 * nother]
    out_ref = refs[2 * nother]
    local = local_ref[0, :, :]               # (1, B) int32
    vals = vals_ref[0, :, :]                 # (1, B)
    B = local.shape[1]
    dtype = vals.dtype
    acc = out_ref.dtype
    prod = vals                              # (1, B), broadcasts up
    for j in range(nother):
        u_t = ut_refs[j][...]                # (R8, D_j) resident in VMEM
        rows_t = _tile_gather(u_t, gidx_refs[j][0], B)     # (R8, B)
        prod = prod * rows_t
    iota = jax.lax.broadcasted_iota(jnp.int32, (width, B), 0)
    onehot = (jnp.broadcast_to(local, (width, B)) == iota).astype(dtype)
    # (R8, B) · (S, B)ᵀ on the MXU → (R8, S) transposed block partials
    part = jax.lax.dot_general(
        prod, onehot,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=acc,
        precision=onehot_precision(dtype, "rhs"))
    if not accumulate:
        out_ref[...] = part[None]
        return

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = part

    @pl.when(pl.program_id(0) != 0)
    def _accum():
        out_ref[...] += part


def fused_t_vmem_ok(factors, mode: int, width: int, block: int,
                    budget_bytes: int = None) -> bool:
    """VMEM plan of the transposed-table fused kernel: every input
    factor resident as (R8, D) (R padded to 8 sublanes, D to 128
    lanes), plus per-step working set — the pre-replicated (ck, 8, D)
    index tiles, gathered rows and the accumulating (R8, B) product,
    the (S, B) one-hot, streams and partials.
    """
    if budget_bytes is None:
        budget_bytes = _vmem_budget()
    R = int(factors[0].shape[1])
    r8 = _rank_pad(R, factors[0].dtype)
    itemsize = jnp.dtype(factors[0].dtype).itemsize
    b_pad = ceil_to(block, 128)
    fac = 0
    work = 0
    for k, f in enumerate(factors):
        if k != mode:
            d = ceil_to(int(f.shape[0]), 128)
            ck = -(-b_pad // d)
            fac += r8 * d * itemsize                  # resident table
            # streamed per block -> the pipeline DOUBLE-buffers them
            # (splint SPL026's static model counts streamed specs 2x;
            # single-counting here undersold the true footprint)
            work += 2 * ck * _SUBLANE * d * 4         # replicated idx tiles
            work += r8 * ck * d * itemsize            # gathered rows
    work += (r8 * b_pad * itemsize                    # accumulating product
             + ceil_to(width, _SUBLANE) * b_pad * itemsize   # one-hot
             + r8 * ceil_to(width, 128) * 4                  # partials
             + 2 * 2 * b_pad * 4)                     # local + vals (dbuf)
    return fac + work <= budget_bytes


def _prep_t_operands(layout, factors, mode: int, accumulate: bool):
    """Shared operand prep for the transposed-table fused kernels:
    (local, vals, uts, gidxs) with the sentinel-clamp and lane-chunk
    padding contract in ONE place.

    local/vals: (nb, 1, B).  uts[j]: the (R8, d_pad) transposed,
    zero-padded factor table for the j-th non-target mode.  gidxs[j]:
    (nb, ck, 8, d_pad) gather requests — the per-block index vector
    clamped to d-1 (padding entries carry the out-of-range sentinel
    `dim`; their values are zero so the clamped row is harmless),
    padded to whole d_pad lane chunks, replicated across 8 sublanes
    (the same-shaped take_along_axis form Mosaic lowers).
    """
    nb, B = layout.nblocks, layout.block
    R = int(factors[0].shape[1])
    dtype = factors[0].dtype
    R8 = _rank_pad(R, dtype)
    others = [k for k in range(layout.nmodes) if k != mode]

    # OPERAND-PREP decode through the stream-consumer interface
    # (blocked.decode_* via mode_ids/blocked_locals): identity reads
    # for v1, trace-fused decodes for the compact encodings — the
    # kernel operands below are i32/compute-dtype either way, so these
    # Mosaic kernels are format-agnostic.  The decoded i32 streams and
    # replicated request tiles DO round-trip HBM here — the traffic
    # bench's decode_overhead prices, and what fused_mttkrp_v2's
    # in-kernel decode deletes (docs/format.md)
    if accumulate:
        local = layout.mode_ids(mode).reshape(nb, B)
    else:
        local = layout.blocked_locals()
    vals = layout.vals.reshape(nb, B).astype(dtype)
    local = local[:, None, :]
    vals = vals[:, None, :]

    uts = []
    gidxs = []
    for k in others:
        d = int(factors[k].shape[0])
        d_pad = ceil_to(d, 128)
        u_t = factors[k].T
        uts.append(jnp.pad(u_t, ((0, R8 - R), (0, d_pad - d))))
        ck = -(-B // d_pad)
        idx = jnp.minimum(layout.mode_ids(k), d - 1).reshape(nb, B)
        if ck * d_pad != B:
            idx = jnp.pad(idx, ((0, 0), (0, ck * d_pad - B)))
        gidxs.append(jnp.broadcast_to(idx.reshape(nb, ck, 1, d_pad),
                                      (nb, ck, _SUBLANE, d_pad)))
    return local, vals, uts, gidxs


@functools.partial(jax.jit, static_argnames=("mode", "width", "accumulate",
                                             "interpret"))
def fused_mttkrp_t(layout, factors, mode: int, width: int,
                   accumulate: bool, interpret: bool = False) -> jax.Array:
    """Fused MTTKRP with VMEM-resident transposed factor tables.

    Output: (nb, width, R) block partials (sorted layouts), or
    (width, R) totals when `accumulate` (privatized short modes) —
    same contract as :func:`fused_mttkrp`.
    """
    nb, B = layout.nblocks, layout.block
    R = int(factors[0].shape[1])
    dtype = factors[0].dtype
    R8 = _rank_pad(R, dtype)
    others = [k for k in range(layout.nmodes) if k != mode]
    grid = (nb,)

    local, vals, uts, gidxs = _prep_t_operands(layout, factors, mode,
                                               accumulate)
    ut_specs = [pl.BlockSpec(u.shape, lambda i: (0, 0)) for u in uts]
    gidx_specs = [pl.BlockSpec((1,) + g.shape[1:], lambda i: (i, 0, 0, 0))
                  for g in gidxs]

    acc = _acc_dtype(dtype)
    if accumulate:
        out_spec = pl.BlockSpec((R8, width), lambda i: (0, 0))
        out_shape = jax.ShapeDtypeStruct((R8, width), acc)
    else:
        out_spec = pl.BlockSpec((1, R8, width), lambda i: (i, 0, 0))
        out_shape = jax.ShapeDtypeStruct((nb, R8, width), acc)

    out = pl.pallas_call(
        functools.partial(_fused_t_kernel, width=width,
                          accumulate=accumulate, nother=len(others)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, B), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, B), lambda i: (i, 0, 0)),
            *gidx_specs,
            *ut_specs,
        ],
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(local, vals, *gidxs, *uts)
    # back to the (…, width, R) contract of the untransposed kernels
    if accumulate:
        return out.T[:, :R]
    return jnp.swapaxes(out, 1, 2)[:, :, :R]


# -- sublane-tiled fused kernel (inner grid over rank tiles) ----------------
#
# Structurally different fallback for the Mosaic compiler crashes that
# kill fused_mttkrp_t at production block sizes (tools/fused_bisect.json:
# every block>=4096 case dies with an HTTP 500 subprocess crash while
# block-128 compiles; prime suspects are the Python-unrolled ck×(R8/8)
# take_along_axis fan-out and the large lane/sublane concatenates).
# This variant:
#   * grid (R8/8, nb) — each instance computes ONE 8-sublane rank tile,
#     so the kernel body holds one take_along_axis per (factor, lane
#     chunk) and no concatenates at all;
#   * only an (8, D) slice of each transposed table is resident per
#     step; the table block index depends only on the rank-tile
#     coordinate, and nb is the fastest grid dimension, so Pallas
#     re-fetches each slice once per rank tile (~R8/8 · ΣD · 32 B per
#     MTTKRP — noise), not once per block;
#   * chunk products accumulate into a VMEM scratch at static
#     128-aligned lane offsets instead of concatenating tiles.
# The VMEM envelope is RANK-independent (only one 8-sublane rank tile
# is live per step) but DIM-linear: the per-step (8, d_pad) table slice
# and index tiles scale with the padded mode dim, so rank-200 configs
# fused_t's whole-table residency gate rejects are covered, while
# mode dims beyond a few hundred thousand still reject (a 10M-row mode
# ⇒ ~960 MB/step) and dispatch falls back to xla_scan.  What rescues
# the Amazon-scale configs is the multi-chip grid: each device sees
# only its grid-LOCAL dims, which shrink by the axis width.

def _fused_tg_kernel(local_ref, vals_ref, *refs,
                     width: int, accumulate: bool, nother: int):
    gidx_refs = refs[:nother]
    ut_refs = refs[nother:2 * nother]
    out_ref = refs[2 * nother]
    prod_ref = refs[2 * nother + 1]          # VMEM scratch (8, B)
    local = local_ref[0, :, :]               # (1, B) int32
    vals = vals_ref[0, :, :]                 # (1, B)
    B = local.shape[1]
    dtype = vals.dtype
    prod_ref[...] = jnp.broadcast_to(vals, (_SUBLANE, B))
    for j in range(nother):
        u_t = ut_refs[j][...]                # (8, D_j) slice of the table
        gidx = gidx_refs[j][0]               # (ck_j, 8, D_j)
        ck, _, D = gidx.shape
        for c in range(ck):
            w = min(B - c * D, D)
            if w <= 0:
                break
            tile = jnp.take_along_axis(u_t, gidx[c], axis=1)   # (8, D_j)
            if w == B and ck == 1:
                prod_ref[...] = prod_ref[...] * tile[:, :B]
            else:
                prod_ref[:, c * D:c * D + w] = (
                    prod_ref[:, c * D:c * D + w] * tile[:, :w])
    iota = jax.lax.broadcasted_iota(jnp.int32, (width, B), 0)
    onehot = (jnp.broadcast_to(local, (width, B)) == iota).astype(dtype)
    # (8, B) · (S, B)ᵀ on the MXU → (8, S) transposed partials tile
    part = jax.lax.dot_general(
        prod_ref[...], onehot,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=out_ref.dtype,
        precision=onehot_precision(dtype, "rhs"))
    if not accumulate:
        out_ref[...] = part[None]
        return

    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[...] = part

    @pl.when(pl.program_id(1) != 0)
    def _accum():
        out_ref[...] += part


def fused_tg_vmem_ok(factors, mode: int, width: int, block: int,
                     budget_bytes: int = None) -> bool:
    """VMEM plan of the sublane-tiled kernel — per-step only: (8, D)
    table slices, the replicated index tiles, the (8, B) product
    scratch, one-hot and partials.  ×2 on streamed operands for double
    buffering.  RANK-independent (no whole-table footprint), but
    DIM-linear: the slice/index terms grow with each padded mode dim,
    so very large local dims (≳ a few hundred thousand rows at
    block 4096) correctly reject here and dispatch falls back."""
    if budget_bytes is None:
        budget_bytes = _vmem_budget()
    itemsize = jnp.dtype(factors[0].dtype).itemsize
    b_pad = ceil_to(block, 128)
    work = 0
    for k, f in enumerate(factors):
        if k != mode:
            d = ceil_to(int(f.shape[0]), 128)
            ck = -(-b_pad // d)
            work += 2 * _SUBLANE * d * itemsize        # table slice (dbuf)
            work += 2 * ck * _SUBLANE * d * 4          # replicated idx tiles
    work += (_SUBLANE * b_pad * itemsize               # prod scratch
             + ceil_to(width, _SUBLANE) * b_pad * itemsize   # one-hot
             + _SUBLANE * ceil_to(width, 128) * 4            # partials tile
             + 4 * b_pad * 4)                                # local + vals
    return work <= budget_bytes


@functools.partial(jax.jit, static_argnames=("mode", "width", "accumulate",
                                             "interpret"))
def fused_mttkrp_tg(layout, factors, mode: int, width: int,
                    accumulate: bool, interpret: bool = False) -> jax.Array:
    """Sublane-tiled fused MTTKRP (grid over rank tiles × blocks).

    Same contract as :func:`fused_mttkrp_t`: (nb, width, R) block
    partials, or (width, R) totals when `accumulate`.
    """
    from jax.experimental.pallas import tpu as pltpu

    nb, B = layout.nblocks, layout.block
    R = int(factors[0].shape[1])
    dtype = factors[0].dtype
    R8 = _rank_pad(R, dtype)  # matches _prep_t_operands' table padding
    n_rtiles = R8 // _SUBLANE
    others = [k for k in range(layout.nmodes) if k != mode]
    grid = (n_rtiles, nb)     # nb fastest: table slices fetched per r-tile

    local, vals, uts, gidxs = _prep_t_operands(layout, factors, mode,
                                               accumulate)
    ut_specs = [pl.BlockSpec((_SUBLANE, u.shape[1]), lambda r, i: (r, 0))
                for u in uts]
    gidx_specs = [pl.BlockSpec((1,) + g.shape[1:],
                               lambda r, i: (i, 0, 0, 0)) for g in gidxs]

    acc = _acc_dtype(dtype)
    if accumulate:
        out_spec = pl.BlockSpec((_SUBLANE, width), lambda r, i: (r, 0))
        out_shape = jax.ShapeDtypeStruct((R8, width), acc)
    else:
        out_spec = pl.BlockSpec((1, _SUBLANE, width), lambda r, i: (i, r, 0))
        out_shape = jax.ShapeDtypeStruct((nb, R8, width), acc)

    out = pl.pallas_call(
        functools.partial(_fused_tg_kernel, width=width,
                          accumulate=accumulate, nother=len(others)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, B), lambda r, i: (i, 0, 0)),
            pl.BlockSpec((1, 1, B), lambda r, i: (i, 0, 0)),
            *gidx_specs,
            *ut_specs,
        ],
        out_specs=out_spec,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((_SUBLANE, B), dtype)],
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(local, vals, *gidxs, *uts)
    # back to the (…, width, R) contract of the untransposed kernels
    if accumulate:
        return out.T[:, :R]
    return jnp.swapaxes(out, 1, 2)[:, :, :R]


# -- decode-in-kernel fused MTTKRP (format v2 consumed natively) ------------
#
# The flagship of the in-kernel-decode line (ROADMAP item 3,
# docs/format.md): the kernel's HBM inputs are the RAW encoded streams
# — u8/u16 locals or segment ids (i8/i16 deltas, u16 RLE counts),
# per-block i32 bases, bf16/f32 values — and the widen/base-add/
# segment-expand decode runs in REGISTERS on the VMEM-resident chunk,
# so the decoded global-i32 form never exists in HBM and achieved
# bytes per MTTKRP track the encoded streams (~8 B/nnz at the compact
# format) instead of the ~2x the operand-prep decode of the fused_t
# family spends re-widening first.  The grid pipeline double-buffers
# the HBM→VMEM stream DMA (block s+1 lands while block s computes) —
# the programmable-memory-controller idea (PAPERS.md arXiv 2207.08298)
# with Pallas's pipeline emitter as the DMA engine.
#
# The decode vocabulary is the SHARED stream-consumer interface
# (blocked.decode_gather_ids / decode_segment_ids — the same functions
# the scanned-XLA engine runs per chunk), so interpret mode is
# bit-identical to the XLA dataflow by construction and tier-1
# exercises the exact kernel math on CPU — the async-ring pattern
# (docs/ring.md).  On real TPUs the kernel is capability-probed per
# (regime, block) like the fused_t family; gather requests are built
# in-kernel at 128-aligned static offsets in the same-shaped
# take_along_axis form Mosaic lowers.

from splatt_tpu.blocked import decode_global_ids, decode_segment_ids


def _gather_rows_t_inkernel(u_t, g, B: int):
    """rows_t = u_t[:, g] built INSIDE the kernel from an in-register
    (1, B) i32 request vector (the decoded stream) — the in-kernel
    counterpart of :func:`_tile_gather`, whose request tiles are
    materialized in HBM by :func:`_prep_t_operands`.  The request is
    replicated across sublanes and padded to whole d_pad lane chunks
    in registers; every take_along_axis is the same-shaped (8, D)
    form, and all slice offsets are 128-aligned statics."""
    R8, D = u_t.shape
    ck = -(-B // D)
    g8 = jnp.broadcast_to(g, (_SUBLANE, B))
    pieces = []
    for c in range(ck):
        w = min(B - c * D, D)
        idx = g8 if ck == 1 else g8[:, c * D:c * D + w]
        if w < D:
            idx = jnp.concatenate(
                [idx, jnp.zeros((_SUBLANE, D - w), jnp.int32)], axis=1)
        tiles = [jnp.take_along_axis(u_t[r0:r0 + _SUBLANE, :], idx, axis=1)
                 for r0 in range(0, R8, _SUBLANE)]
        rows = tiles[0] if len(tiles) == 1 \
            else jnp.concatenate(tiles, axis=0)           # (R8, D)
        pieces.append(rows[:, :w])
    return pieces[0] if ck == 1 else jnp.concatenate(pieces, axis=1)


def _fused_v2_kernel(seg_ref, vals_ref, base_ref, *refs,
                     width: int, accumulate: bool, nother: int,
                     encs: tuple, seg_enc: str, mode: int, block: int,
                     dims: tuple):
    """One block's decode + gather + Hadamard + one-hot reduce, all on
    the VMEM-staged ENCODED chunk.  `encs`/`seg_enc` are the static
    per-stream encoding kinds (blocked.STREAM_ENCODINGS); `base_ref`
    holds the block's per-mode i32 bases in SMEM."""
    loc_refs = refs[:nother]
    ut_refs = refs[nother:2 * nother]
    out_ref = refs[2 * nother]
    dtype = ut_refs[0].dtype if nother else vals_ref.dtype
    vals = vals_ref[0, :, :]                      # (1, B) stored dtype
    prod = vals.astype(dtype)                     # (1, B) → (R8, B)
    for j in range(nother):
        u_t = ut_refs[j][...]                     # (R8, D_j) resident
        # widen + base-add (+ delta cumsum / RLE expand) in registers —
        # the decoded i32 request never round-trips HBM.  Each stream
        # decodes by its OWN kind: gathering the layout's sorted mode
        # (the privatized path) expands its segment/RLE stream here.
        g = decode_global_ids(loc_refs[j][0, :, :],
                              base_ref[0, dims[j][1]], encs[j], block)
        g = jnp.minimum(g, dims[j][0] - 1)        # pad-entry clamp
        prod = prod * _gather_rows_t_inkernel(u_t, g, block)
    # the one-hot coordinates: within-block segment ids for the sorted
    # path, decoded GLOBAL ids for the accumulating privatized path —
    # u8/u16 widen (or RLE counts expand) in registers either way
    if accumulate:
        local = decode_global_ids(seg_ref[0, :, :], base_ref[0, mode],
                                  seg_enc, block)
    else:
        local = decode_segment_ids(seg_ref[0, :, :], seg_enc, block)
    iota = jax.lax.broadcasted_iota(jnp.int32, (width, block), 0)
    onehot = (jnp.broadcast_to(local, (width, block)) == iota).astype(dtype)
    # (R8, B) · (S, B)ᵀ on the MXU → (R8, S) transposed block partials
    part = jax.lax.dot_general(
        prod, onehot,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=out_ref.dtype,
        precision=onehot_precision(dtype, "rhs"))
    if not accumulate:
        out_ref[...] = part[None]
        return

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = part

    @pl.when(pl.program_id(0) != 0)
    def _accum():
        out_ref[...] += part


def fused_v2_vmem_ok(factors, mode: int, width: int, block: int,
                     budget_bytes: int = None) -> bool:
    """VMEM plan of the decode-in-kernel engine: resident transposed
    tables like fused_t, plus the per-step working set — the REGISTER-
    built (8, d_pad) request tiles and gathered rows per lane chunk,
    the accumulating (R8, B) product, one-hot and partials.  The
    streamed operands themselves are the narrow encoded chunks (u8/u16
    + bf16), a sliver of the i32 tiles _prep_t_operands streams."""
    if budget_bytes is None:
        budget_bytes = _vmem_budget()
    R = int(factors[0].shape[1])
    r8 = _rank_pad(R, factors[0].dtype)
    itemsize = jnp.dtype(factors[0].dtype).itemsize
    b_pad = ceil_to(block, 128)
    fac = 0
    work = 0
    for k, f in enumerate(factors):
        if k != mode:
            d = ceil_to(int(f.shape[0]), 128)
            ck = -(-b_pad // d)
            fac += r8 * d * itemsize                  # resident table
            work += ck * _SUBLANE * d * 4             # request tiles
            work += r8 * ck * d * itemsize            # gathered rows
    work += (r8 * b_pad * itemsize                    # product
             + ceil_to(width, _SUBLANE) * b_pad * itemsize   # one-hot
             + r8 * ceil_to(width, 128) * 4                  # partials
             # encoded streams are double-buffered by the pipeline
             # like every grid-streamed operand (splint SPL026)
             + 2 * 4 * b_pad * 4)                # decoded ids + streams
    return fac + work <= budget_bytes


@functools.partial(jax.jit, static_argnames=("mode", "width", "accumulate",
                                             "interpret"))
def fused_mttkrp_v2(layout, factors, mode: int, width: int,
                    accumulate: bool, interpret: bool = False) -> jax.Array:
    """Decode-in-kernel fused MTTKRP over a compact (v2-family)
    layout: the pallas_call's HBM inputs are the layout's RAW encoded
    streams — double-buffered into VMEM by the grid pipeline — and
    decode runs in registers next to the gather (docs/format.md).

    Same contract as :func:`fused_mttkrp_t`: (nb, width, R) block
    partials, or (width, R) totals when `accumulate`.  Requires a
    v2-family encoding (``layout.base`` present).
    """
    from jax.experimental.pallas import tpu as pltpu

    streams, bases, encs = layout.mode_streams()
    if bases is None:
        raise ValueError(
            "fused_mttkrp_v2 consumes the compact encoded streams; "
            "build the layout at a v2-family idx_width (docs/format.md)")
    nb, B = layout.nblocks, layout.block
    R = int(factors[0].shape[1])
    dtype = factors[0].dtype
    R8 = _rank_pad(R, dtype)
    others = [k for k in range(layout.nmodes) if k != mode]
    grid = (nb,)

    # RAW encoded operands at their stored widths — no host-side
    # widening, no request-tile materialization: what lands in VMEM is
    # what the format stores in HBM
    seg = streams[mode].reshape(nb, 1, -1)      # ids (nb,1,B) / counts
    vals = layout.vals.reshape(nb, 1, B)
    # gather streams keep their stored shapes too: (nb,1,B) locals, or
    # (nb,1,S) counts when the privatized path gathers the sorted
    # mode's RLE stream
    locs = [streams[k].reshape(nb, 1, -1) for k in others]
    base_mat = jnp.stack(bases, axis=1).astype(jnp.int32)  # (nb, nmodes)
    uts = []
    for k in others:
        d = int(factors[k].shape[0])
        uts.append(jnp.pad(factors[k].T,
                           ((0, R8 - R), (0, ceil_to(d, 128) - d))))
    # (clamp dim, base column) per gather mode — static for the kernel
    dims_o = tuple((int(factors[k].shape[0]), k) for k in others)
    encs_o = tuple(encs[k] for k in others)

    loc_specs = [pl.BlockSpec((1,) + l.shape[1:], lambda i: (i, 0, 0))
                 for l in locs]
    ut_specs = [pl.BlockSpec(u.shape, lambda i: (0, 0)) for u in uts]

    acc = _acc_dtype(dtype)
    if accumulate:
        out_spec = pl.BlockSpec((R8, width), lambda i: (0, 0))
        out_shape = jax.ShapeDtypeStruct((R8, width), acc)
    else:
        out_spec = pl.BlockSpec((1, R8, width), lambda i: (i, 0, 0))
        out_shape = jax.ShapeDtypeStruct((nb, R8, width), acc)

    out = pl.pallas_call(
        functools.partial(_fused_v2_kernel, width=width,
                          accumulate=accumulate, nother=len(others),
                          encs=encs_o, seg_enc=encs[mode], mode=mode,
                          block=B, dims=dims_o),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1,) + seg.shape[1:], lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, B), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, base_mat.shape[1]), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            *loc_specs,
            *ut_specs,
        ],
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(seg, vals, base_mat, *locs, *uts)
    # back to the (…, width, R) contract of the untransposed kernels
    if accumulate:
        return out.T[:, :R]
    return jnp.swapaxes(out, 1, 2)[:, :, :R]


#: outcome of each capability probe, keyed by kernel name — "ok",
#: "compile_failed", "resource", "timeout", "infra", or absent if never
#: probed.  "timeout"/"infra" mean the verdict is *unproven* (a
#: transiently slow/wedged remote-compile service, not a rejected
#: kernel) — for "timeout" an orphaned daemon thread may still be using
#: the chip; engine_plan/CLI surface these.  "resource" means the probe
#: ran out of memory: a capacity verdict scoped to this (regime, block)
#: shape, not a capability rejection.
PROBE_STATES: dict = {}


# -- persistent capability cache --------------------------------------------
#
# A capability probe costs a remote compile (~35 s healthy, 240 s on a
# wedged service) and its verdict depends only on (jax version, device
# kind, kernel, regime, block) — none of which change between the
# processes of one environment.  Every stage of tools/tpu_session.sh is
# its own process, so without persistence a precious chip window spends
# its first minutes re-proving verdicts the previous stage already paid
# for.  This cache stores proven verdicts ("ok"/"compile_failed", and
# the shape-scoped "resource") on disk; "timeout"/"infra" are stored
# for reporting but NEVER short-circuit a later process — an unproven
# verdict is retried, not inherited (a transiently wedged compile
# service must not demote the flagship engine for every future
# session).  Every entry additionally expires after a TTL
# (SPLATT_PROBE_CACHE_TTL_S, default 14 days): infrastructure drifts
# under a fixed env key (driver updates, relay reconfigurations), so
# even a proven verdict is re-earned occasionally.

_CACHE_ENV = "SPLATT_PROBE_CACHE"
_CACHE_TTL_ENV = "SPLATT_PROBE_CACHE_TTL_S"
# the default TTL (14 days) lives in utils/env.py:ENV_VARS — the
# single registry the docs and the SPL007 check read


def probe_cache_ttl() -> float:
    """Seconds a cached verdict stays fresh (<= 0 disables expiry)."""
    from splatt_tpu.utils.env import read_env_float

    return read_env_float(_CACHE_TTL_ENV)


def _cache_path():
    import pathlib

    from splatt_tpu.utils.env import read_env

    p = read_env(_CACHE_ENV)
    if p:
        return pathlib.Path(p)
    root = pathlib.Path(__file__).resolve().parents[2]
    # a real repo-checkout marker — the bare existence of a sibling
    # "tools" dir would misfire inside site-packages
    if (root / "pyproject.toml").exists() and (root / "tools").is_dir():
        return root / "tools" / "probe_cache.json"
    return pathlib.Path.home() / ".cache" / "splatt_tpu" / "probe_cache.json"


@functools.cache
def _kernel_src_hash() -> str:
    """Hash of the sources a probe verdict depends on — this module
    plus the layout/tensor builders the probe compiles through
    (blocked.py, coo.py) and the helpers the kernels import from
    ops/mttkrp.py (_acc_dtype, onehot_precision) and utils/env.py
    (ceil_to): editing any of them changes what the probe compiles, so
    it must invalidate every cached verdict — a fixed Mosaic crash is
    re-probed instead of staying disabled behind a stale
    "compile_failed" (and a stale "ok" cannot mask a new rejection)."""
    import hashlib
    import pathlib

    h = hashlib.sha256()
    pkg = pathlib.Path(__file__).resolve().parents[1]
    try:
        for src in (pathlib.Path(__file__), pkg / "blocked.py",
                    pkg / "coo.py", pkg / "config.py",
                    pkg / "ops" / "mttkrp.py", pkg / "utils" / "env.py"):
            h.update(src.read_bytes())
        return h.hexdigest()[:12]
    # splint: ignore[SPL002] sources unreadable (zipped/frozen install):
    # the sentinel keys one shared cache namespace, a safe degradation
    except Exception:
        return "nosrc"


def _cache_env_key() -> str:
    try:
        kind = jax.devices()[0].device_kind
    # splint: ignore[SPL002] device discovery off-accelerator: the
    # cache key degrades to a shared "unknown" namespace
    except Exception:
        kind = "unknown"
    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "?")
    # splint: ignore[SPL002] optional-package version probe: absence
    # is a legitimate environment, encoded as "?" in the cache key
    except Exception:
        jl = "?"
    return f"{jax.__version__}|jaxlib{jl}|{kind}|{_kernel_src_hash()}"


def _cache_io_error(op: str, exc) -> None:
    """Report a probe-cache IO failure through the failure taxonomy.

    Cache IO stays best-effort by contract (a broken cache must never
    break dispatch), but the failure used to vanish in a bare
    ``except`` — a cache silently losing every verdict re-spends a
    ~35 s remote compile per kernel per process, which is exactly the
    silent degradation the run report exists to surface."""
    from splatt_tpu import resilience

    resilience.run_report().add(
        "probe_cache_io_error", op=op,
        failure_class=resilience.classify_failure(exc).value,
        error=resilience.failure_message(exc)[:200])


def _json_cache_load(path, on_error=None):
    """The shared read side of the JSON cache protocol — used by the
    capability-probe cache here and the autotuner's plan cache
    (splatt_tpu/tune.py), and the ONLY sanctioned way to read a shared
    cache file (splint rule SPL011 flags inline ``open`` on cache
    paths): a missing file is the normal first-run path (-> None), any
    other failure is routed to `on_error(op, exc)` (classified into
    the run report) and degrades to None — a broken cache must never
    break dispatch.  Writers use :func:`_json_cache_update`; readers
    need no lock because writes are atomic replaces."""
    import json

    if on_error is None:
        on_error = _cache_io_error
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None  # first run in this environment: nothing cached yet
    except Exception as e:
        on_error("load", e)
        return None


def probe_cache_load(state_key: str):
    """Cached verdict for `state_key` in this environment, or None.
    Returns whatever was stored ("ok"/"compile_failed"/"resource"/
    "timeout"/"infra") — the CALLER decides which states are
    authoritative.  Entries older than :func:`probe_cache_ttl` are
    expired (returned as None) so every verdict, even a proven one, is
    re-earned occasionally on drifting infrastructure."""
    import time

    from splatt_tpu import trace

    data = _json_cache_load(_cache_path())
    if data is None:
        trace.metric_inc("splatt_probe_cache_total", outcome="miss")
        return None
    try:
        entry = data.get(_cache_env_key(), {}).get(state_key)
        if not entry:
            trace.metric_inc("splatt_probe_cache_total", outcome="miss")
            return None
        ttl = probe_cache_ttl()
        if ttl > 0 and time.time() - float(entry.get("ts", 0)) > ttl:
            trace.metric_inc("splatt_probe_cache_total",
                             outcome="expired")
            return None
        trace.metric_inc("splatt_probe_cache_total", outcome="hit")
        return entry["state"]
    except Exception as e:
        # a malformed entry (hand-edited file, schema drift) is an
        # unusable verdict, not a dispatch failure: report and re-probe
        _cache_io_error("load", e)
        return None


#: intra-process serialization of cache writes, complementing the
#: inter-process flock below: concurrent serve jobs (threads in ONE
#: process) tuning simultaneously must not interleave their
#: read-modify-writes.  flock on separate fds does conflict within a
#: process too, but holding a plain Lock makes the thread contract
#: independent of that platform detail and keeps the (open, flock)
#: pair itself race-free.
_JSON_CACHE_THREAD_LOCK = threading.Lock()


def _json_cache_update(path, mutate, on_error=None) -> None:
    """Locked atomic read-modify-write of a small JSON cache file —
    shared by the capability-probe cache here and the autotuner's plan
    cache (splatt_tpu/tune.py).  `mutate(data) -> data` transforms the
    loaded dict (``{}`` when absent/corrupt).  Serialized against other
    processes (flock) AND other threads of this process (concurrent
    serve jobs share the warm caches — docs/serve.md), so two writers
    never drop each other's entries.  Best-effort by contract:
    cache IO must never break dispatch, so every failure is routed to
    `on_error(op, exc)` (classified into the run report) and swallowed.
    """
    import json

    if on_error is None:
        on_error = _cache_io_error
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        # serialize concurrent read-modify-writes (two processes proving
        # different kernels must not drop each other's verdicts)
        import fcntl

        with _JSON_CACHE_THREAD_LOCK, \
                open(str(path) + ".lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                with open(path) as f:
                    data = json.load(f)
            except FileNotFoundError:
                data = {}  # first write creates the file
            except Exception as e:
                # unreadable/corrupt cache: replaced wholesale below —
                # reported, because it drops every other entry
                on_error("store", e)
                data = {}
            data = mutate(data)
            from splatt_tpu.utils.durable import publish_json

            publish_json(path, data, indent=1, sort_keys=True)
    except Exception as e:
        # best-effort by contract (cache IO must never break dispatch):
        # degrade to an uncached probe/plan, but say so in the run report
        on_error("store", e)


def probe_cache_store(state_key: str, state: str) -> None:
    """Record a probe verdict on disk (atomic replace; best-effort —
    cache IO must never break dispatch).  Timestamps let a TPU session
    commit the file as evidence of when each verdict was proven."""
    import time

    env_key = _cache_env_key()
    entry = {"state": state, "ts": time.time()}

    def mutate(data):
        data.setdefault(env_key, {})[state_key] = entry
        return data

    _json_cache_update(_cache_path(), mutate)


#: representative probe shapes per lane-chunk regime.  "ck1": the
#: flagship NELL-like production regime — mode dims in the thousands,
#: a single lane chunk per factor (d_pad >= block), wide gathers, a
#: realistic seg_width (mode-0 indices laid out so each 4096-block
#: spans ~8 rows, like a 20M-nnz tensor's density).  "multick": small
#: mode dims against the same block, so the kernels unroll many lane
#: chunks per factor (ck up to 11) — a regime that can crash Mosaic
#: independently of the ck1 shape.  Probing per regime keeps a crash
#: in one from vetoing the other.
_PROBE_DIMS = {"ck1": (12092, 9184, 28818), "multick": (512, 384, 1024)}


def probe_regime(dims, block: int) -> str:
    """Which probe regime a (dims, block) config falls in: "multick"
    when any factor needs more than one padded lane chunk per block."""
    return ("multick"
            if any(block > ceil_to(int(d), 128) for d in dims)
            else "ck1")


def _probe_case(kernel_fn, regime: str, block: int, fmt=None) -> bool:
    """The probe compile itself — module-level so tests can substitute
    it without touching the thread/deadline/cache machinery around it.
    `fmt` builds the probe layout at a specific encoding (the fused_v2
    probe compiles against real compact streams)."""
    import numpy as np

    from splatt_tpu.blocked import build_layout
    from splatt_tpu.coo import SparseTensor

    rng = np.random.default_rng(0)
    dims = _PROBE_DIMS[regime]
    nnz = max(8192, 2 * block)
    # scale the probe's rank to the device's VMEM so a capacity
    # rejection on small-VMEM parts (v2/v3: 16 MiB) is never cached
    # as a capability rejection for the whole regime
    rank = 48 if _vmem_limit() >= (32 << 20) else 16
    if regime == "ck1":
        # NELL-like density: each block spans ~8 output rows,
        # giving the production seg_width (~8-16)
        i0 = np.minimum((np.arange(nnz, dtype=np.int64) * 8) // block,
                        dims[0] - 1)
    else:
        # small dims: random rows give the regime's natural wide
        # seg_width (~dims[0]) — the width real multick kernels
        # compile at
        i0 = rng.integers(0, dims[0], nnz)
    inds = np.stack([i0] + [rng.integers(0, d, nnz)
                            for d in dims[1:]])
    tt = SparseTensor(inds=inds.astype(np.int64),
                      vals=np.ones(nnz), dims=dims)
    lay = build_layout(tt, 0, block=block, val_dtype=np.float32, fmt=fmt,  # splint: ignore[SPL005] probes compile at the production f32 shape to keep one verdict cache
                       dense=False)
    fac = [jnp.zeros((d, rank), jnp.float32) for d in dims]  # splint: ignore[SPL005] probes compile at the production f32 shape to keep one verdict cache
    kernel_fn.lower(lay, fac, mode=0, width=lay.seg_width,
                    accumulate=False, interpret=False).compile()
    return True


def _probe_compiles(kernel_fn, name: str, regime: str = "ck1",
                    block: int = 4096, fmt=None, case=None) -> bool:
    """Whether `kernel_fn(layout, factors, mode, width, accumulate,
    interpret)` COMPILES for this backend at a shape representative of
    `regime` at the CALLER's block size.  Lowering alone is not
    enough: Mosaic layout inference (e.g. the "Invalid input layout"
    broadcast restriction) only runs at compile time.  And a toy shape
    is not enough either — measured on a v5e, a (16,24,32)/block-128
    probe compiles while every block-4096 case crashes the Mosaic
    compiler subprocess (tools/fused_bisect.py); the block size is the
    variable that bisect data most implicates, so it is part of the
    probe key rather than fixed."""
    state_key = f"{name}:{regime}:b{block}"
    if jax.default_backend() != "tpu":
        PROBE_STATES[state_key] = "not_tpu"
        return False

    # Proven verdicts persist across processes ("resource" is proven
    # too, but scoped: the state_key already carries regime+block, so a
    # capacity rejection only gates this shape); "timeout"/"infra" do
    # not short-circuit (unproven — retry now that we have the chip).
    cached = probe_cache_load(state_key)
    if cached in ("ok", "compile_failed", "resource"):
        PROBE_STATES[state_key] = cached
        return cached == "ok"

    # The compile runs on a daemon thread with a deadline: a wedged
    # remote-compile service (observed: >40 min hangs) must degrade to
    # "unsupported" — blocking dispatch here would wedge the whole
    # session.  A subprocess cannot be used instead: the parent already
    # holds the single chip lease and the relay serializes claims.  A
    # daemon thread (not ThreadPoolExecutor, whose non-daemon workers
    # are joined at interpreter exit) lets the process exit even if the
    # orphaned compile never returns; its exception is swallowed.
    import threading

    from splatt_tpu import resilience
    from splatt_tpu.utils import faults

    result = []

    # Failure taxonomy (splatt_tpu.resilience): only a recognized
    # DETERMINISTIC rejection may be persisted as "compile_failed" —
    # the cache makes any misclassification permanent for the whole
    # environment, so the persisted-negative set is a whitelist (Mosaic
    # compiler crash/rejection signatures), not a transient-error
    # blocklist.  TRANSIENT failures (HTTP 5xx, bare INTERNAL:, relay
    # drops) are retried in-place with capped backoff + jitter and, if
    # they persist, recorded as "infra": rejected for THIS session,
    # re-probed by the next process (worst case one ~35 s probe per
    # process, bounded; a wrongly-persisted rejection would be
    # unbounded).  RESOURCE failures (OOM/VMEM) are proven capacity
    # verdicts scoped to this (regime, block) shape.

    def attempt():
        faults.maybe_fail("probe_compile")
        # a kernel whose call signature differs from the shared probe
        # case (fused_dense: no width/accumulate) supplies its own
        # `case` callable; fmt is only threaded through when a probe
        # needs an encoded layout (fused_v2) — the default call keeps
        # the documented 3-arg substitution contract tests stub
        # _probe_case with
        if case is not None:
            return case(kernel_fn, regime, block)
        if fmt is None:
            return _probe_case(kernel_fn, regime, block)
        return _probe_case(kernel_fn, regime, block, fmt=fmt)

    def runner():
        try:
            result.append(resilience.retry_transient(attempt,
                                                     label=state_key))
        except Exception as e:
            cls = resilience.classify_failure(e)
            if cls is resilience.FailureClass.DETERMINISTIC:
                result.append(False)
            elif cls is resilience.FailureClass.RESOURCE:
                result.append("resource")
            else:
                # transient (retries exhausted) or unknown: unproven
                result.append("infra")

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    # the probe's watchdog budget: SPLATT_DEADLINE_S when configured
    # (the shared deadline knob, docs/guarded-als.md), else the
    # measured-safe 240 s default — the probe always keeps SOME
    # deadline even when the watchdog is globally off, because a probe
    # compile is the call the >40 min hangs were observed on
    probe_deadline = resilience.deadline_seconds(default=240.0)
    t.join(timeout=probe_deadline)
    if not result:
        # close the race where the probe completed between the join
        # deadline expiring and this check: one short grace re-join,
        # then a final read, before declaring a timeout
        t.join(timeout=2.0)
    if not result:
        resilience.run_report().add("deadline_blown",
                                    site="probe_compile",
                                    seconds=float(probe_deadline))
        # Deadline hit, not a compile rejection: the verdict is unproven
        # and the orphaned thread may still occupy the (single-lease)
        # chip.  Cache it anyway — re-probing would stall every dispatch
        # by another 240 s — but say so loudly and record the distinct
        # state so engine_plan/CLI can report "unproven", not "rejected".
        PROBE_STATES[state_key] = "timeout"
        probe_cache_store(state_key, "timeout")
        resilience.run_report().add("probe_downgrade", state_key=state_key,
                                    verdict="timeout")
        import sys

        print(f"splatt-tpu: WARNING: {state_key} capability probe timed out "
              f"after {probe_deadline:g} s (remote compile slow/wedged, NOT a kernel "
              f"rejection); treating as unsupported this session — an "
              f"orphaned compile thread may briefly contend for the chip "
              f"(recorded as unproven; the next process will re-probe)",
              file=sys.stderr, flush=True)
        return False
    if result[0] == "infra":
        # unproven, like timeout: recorded for reporting, retried by the
        # next process rather than inherited as a rejection
        PROBE_STATES[state_key] = "infra"
        probe_cache_store(state_key, "infra")
        resilience.run_report().add("probe_downgrade", state_key=state_key,
                                    verdict="infra")
        import sys

        print(f"splatt-tpu: WARNING: {state_key} capability probe failed "
              f"with a transient/unrecognized error even after backoff "
              f"retries (NOT a proven kernel rejection); treating as "
              f"unsupported this session — the next process will re-probe",
              file=sys.stderr, flush=True)
        return False
    if result[0] == "resource":
        # proven capacity rejection, scoped: the state_key carries
        # (regime, block), so only this shape is demoted
        PROBE_STATES[state_key] = "resource"
        probe_cache_store(state_key, "resource")
        return False
    state = "ok" if result[0] else "compile_failed"
    PROBE_STATES[state_key] = state
    probe_cache_store(state_key, state)
    return bool(result[0])


@functools.cache
def fused_v2_supported(regime: str = "ck1", block: int = 4096,
                       idx_width: str = "auto") -> bool:
    """Whether the decode-in-kernel engine compiles here: the in-
    register widen/base-add/segment-expand plus the in-kernel-built
    same-shaped take_along_axis gather, probed per (lane-chunk regime,
    block, ENCODING) against REAL compact streams.  The encoding is
    part of the probe key because the stream kinds are static kernel
    params tracing different Mosaic code — u8/u16 widens, the delta
    lane cumsum, the RLE broadcast-compare expansion — so an "auto"
    verdict must never vouch for a delta or RLE dispatch."""
    from splatt_tpu.config import IDX_WIDTHS, LayoutFormat

    if idx_width not in IDX_WIDTHS or idx_width == "i32":
        idx_width = "auto"
    return _probe_compiles(fused_mttkrp_v2, f"fused_v2_{idx_width}",
                           regime, block,
                           fmt=LayoutFormat(idx=idx_width))


@functools.cache
def fused_t_supported(regime: str = "ck1", block: int = 4096) -> bool:
    """Whether the transposed-table fused kernel compiles here (its
    lane-wise same-shape take_along_axis gather is the form Mosaic
    supports on jax 0.9.0), probed per (lane-chunk regime, block)."""
    return _probe_compiles(fused_mttkrp_t, "fused_t", regime, block)


@functools.cache
def fused_tg_supported(regime: str = "ck1", block: int = 4096) -> bool:
    """Whether the sublane-tiled fused kernel compiles here (one
    take_along_axis per factor×chunk, no concatenates, scratch-store
    accumulation — the shape Mosaic is most likely to accept), probed
    per (lane-chunk regime, block)."""
    return _probe_compiles(fused_mttkrp_tg, "fused_tg", regime, block)


@functools.cache
def fused_gather_supported(regime: str = "ck1",
                           block: int = 4096) -> bool:
    """Whether the row-major fused kernel compiles here.  Its arbitrary
    ``u[idx]`` row gather is NOT a form jax 0.9.0's Mosaic lowers (only
    same-shaped take_along_axis is), so this is False on current
    hardware — kept for future jax versions; interpret mode covers it
    in tests."""
    return _probe_compiles(fused_mttkrp, "fused_gather", regime, block)


def fused_vmem_ok(factors, mode: int, width: int, block: int,
                  budget_bytes: int = None) -> bool:
    """Whether the fused kernel's VMEM plan fits: every *input* factor
    resident in VMEM for the whole grid, plus the per-step working set
    (gathered rows ×2, one-hot, partials), against _vmem_budget() (the
    measured 128MiB v5e VMEM minus double-buffering headroom).
    """
    if budget_bytes is None:
        budget_bytes = _vmem_budget()
    R = int(factors[0].shape[1])
    itemsize = jnp.dtype(factors[0].dtype).itemsize
    fac = sum(int(f.shape[0]) * R * itemsize
              for k, f in enumerate(factors) if k != mode)
    work = (2 * block * R * itemsize          # gathered rows + prod
            + width * block * itemsize       # one-hot
            + width * R * max(itemsize, 4)   # partials (acc width)
            + (len(factors) + 1) * block * 4)  # index + val streams
    return fac + work <= budget_bytes


def _fused_kernel(local_ref, vals_ref, ginds_ref, *refs,
                  width: int, accumulate: bool, nother: int):
    out_ref = refs[nother]
    u_refs = refs[:nother]
    local = local_ref[:, 0, :]               # (C, B) int32
    vals = vals_ref[:, 0, :]                 # (C, B)
    C, B = local.shape
    dtype = vals.dtype
    prod = vals[..., None]                   # (C, B, 1)
    for j in range(nother):
        u = u_refs[j][...]                   # (dim_j, R) resident in VMEM
        idx = ginds_ref[:, j, :].reshape(C * B)
        rows = jnp.take(u, idx, axis=0, mode="clip",
                        unique_indices=False, indices_are_sorted=False)
        prod = prod * rows.reshape(C, B, u.shape[1])
    iota = jax.lax.broadcasted_iota(jnp.int32, (C, width, B), 1)
    onehot = (local[:, None, :] == iota).astype(dtype)
    part = jax.lax.dot_general(
        onehot, prod,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=out_ref.dtype,
        precision=onehot_precision(dtype, "lhs"))          # (C, width, R)
    if not accumulate:
        out_ref[...] = part
        return
    acc = jnp.sum(part, axis=0)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = acc

    @pl.when(pl.program_id(0) != 0)
    def _accum():
        out_ref[...] += acc


@functools.partial(jax.jit, static_argnames=("mode", "width", "accumulate",
                                             "interpret", "chunk"))
def fused_mttkrp(layout, factors, mode: int, width: int,
                 accumulate: bool, interpret: bool = False,
                 chunk: int = 1) -> jax.Array:
    """Fused MTTKRP kernel: gather factor rows, Hadamard, one-hot reduce
    — entirely in VMEM (≙ the reference's register-blocked fiber loops,
    src/mttkrp.c:427-463, which read each factor row once inside the
    traversal).  The (nnz, R) partial-product tensor never exists in HBM:
    traffic is inds + vals + resident factors + output partials.

    Layout contract: `layout.inds` sorted by `mode` (for the sorted
    path) with sentinel-padded tails; every input factor must pass
    :func:`fused_vmem_ok`.  Output: (nb, width, R) block partials, or
    (width, R) totals when `accumulate` (privatized short modes).
    """
    nmodes = layout.nmodes
    nb, B = layout.nblocks, layout.block
    R = int(factors[0].shape[1])
    dtype = factors[0].dtype
    others = [k for k in range(nmodes) if k != mode]

    if accumulate:
        local = layout.mode_ids(mode).reshape(nb, B)
    else:
        local = layout.blocked_locals()
    vals = layout.vals.reshape(nb, B).astype(dtype)
    # (nb, nother, B): blocks (chunk, nother, B) keep the last two dims
    # equal to the array dims, legal for any chunk under Mosaic's rule.
    # mode_ids decodes the v2 encoding per mode (identity for v1).
    ginds = (jnp.stack([layout.mode_ids(k) for k in others])
             .reshape(len(others), nb, B).transpose(1, 0, 2))

    nb_pad = ceil_to(max(nb, 1), chunk)
    if nb_pad != nb:
        local = jnp.pad(local, ((0, nb_pad - nb), (0, 0)),
                        constant_values=-1)
        vals = jnp.pad(vals, ((0, nb_pad - nb), (0, 0)))
        ginds = jnp.pad(ginds, ((0, nb_pad - nb), (0, 0), (0, 0)))
    local = local[:, None, :]
    vals = vals[:, None, :]
    grid = (nb_pad // chunk,)

    factor_specs = [
        pl.BlockSpec((int(factors[k].shape[0]), R), lambda i: (0, 0))
        for k in others
    ]
    acc = _acc_dtype(dtype)
    if accumulate:
        out_spec = pl.BlockSpec((width, R), lambda i: (0, 0))
        out_shape = jax.ShapeDtypeStruct((width, R), acc)
    else:
        out_spec = pl.BlockSpec((chunk, width, R), lambda i: (i, 0, 0))
        out_shape = jax.ShapeDtypeStruct((nb_pad, width, R), acc)

    out = pl.pallas_call(
        functools.partial(_fused_kernel, width=width, accumulate=accumulate,
                          nother=len(others)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((chunk, 1, B), lambda i: (i, 0, 0)),
            pl.BlockSpec((chunk, 1, B), lambda i: (i, 0, 0)),
            pl.BlockSpec((chunk, len(others), B), lambda i: (i, 0, 0)),
            *factor_specs,
        ],
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(local, vals, ginds, *[factors[k] for k in others])
    if accumulate:
        return out
    return out[:nb]


@functools.partial(jax.jit,
                   static_argnames=("width", "interpret", "chunk"))
def onehot_reduce_full(local: jax.Array, prod: jax.Array, width: int,
                       interpret: bool = False,
                       chunk: int = _CHUNK) -> jax.Array:
    """(nb, B) ids + (nb, B, R) partials → (width, R) total (privatized)."""
    B = local.shape[1]
    R = prod.shape[-1]
    local, prod, nb_pad = _pad_blocks(local, prod, chunk)
    grid = (nb_pad // chunk,)
    out = pl.pallas_call(
        functools.partial(_full_kernel, width=width),
        grid=grid,
        in_specs=[
            pl.BlockSpec((chunk, 1, B), lambda i: (i, 0, 0)),
            pl.BlockSpec((chunk, B, R), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((width, R), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((width, R), _acc_dtype(prod.dtype)),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(local, prod)
    return out


# -- dense-mode MXU engine (docs/dense.md) ----------------------------------
#
# A DenseModeLayout's MTTKRP is X_(m) @ KR(other factors): a batched
# (tile, span) @ (span, R) matmul — the one shape the MXU is literally
# built for, with NO index streams, gathers or one-hots anywhere.  The
# kernel stages the two Khatri-Rao operands (the chained outer-factor
# product w and the lane-padded inner factor u, built ONCE by
# ops.mttkrp.dense_operands and shared with the XLA reference for bit
# parity) whole in VMEM, builds the (span, R) KR tile in registers via
# a broadcast multiply — the column space is a regular grid, so no
# arbitrary gather is ever needed (the construct Mosaic cannot lower) —
# and drives one dot_general per row tile.

def _dense_kernel(tiles_ref, w_ref, u_ref, out_ref, *, rank: int,
                  precision):
    w = w_ref[...]                           # (n_outer, R)
    u = u_ref[...]                           # (inner_pad, R)
    tiles = tiles_ref[0].astype(w.dtype)     # (tile, span)
    kr = (w[:, None, :] * u[None, :, :]).reshape(-1, rank)   # (span, R)
    out_ref[0] = jax.lax.dot_general(
        tiles, kr,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=out_ref.dtype,
        precision=precision)


def dense_vmem_ok(layout, factors, mode: int,
                  budget_bytes: int = None) -> bool:
    """VMEM plan of the dense MXU kernel: one (tile, span) value tile
    resident per step, both KR operands whole, the (span, R) Khatri-Rao
    product built in registers, and the (tile, R) output block at the
    accumulator width."""
    if budget_bytes is None:
        budget_bytes = _vmem_budget()
    geo = layout.geometry
    R = int(factors[0].shape[1])
    itemsize = jnp.dtype(factors[0].dtype).itemsize
    tile_bytes = layout.tile * layout.span * layout.tiles.dtype.itemsize
    work = ((geo.n_outer + geo.inner_pad) * R * itemsize      # w + u
            + layout.span * R * itemsize                      # kr
            + layout.tile * R * max(itemsize, 4))             # out block
    return tile_bytes + work <= budget_bytes


@functools.partial(jax.jit, static_argnames=("mode", "interpret"))
def fused_dense(layout, factors, mode: int,
                interpret: bool = False) -> jax.Array:
    """Dense-mode MTTKRP on the MXU over a
    :class:`splatt_tpu.blocked.DenseModeLayout`.

    Interpret mode is bit-identical to :func:`ops.mttkrp.dense_mttkrp`
    by construction: both build (w, u) through the same
    ``dense_operands``, form the same (span, R) KR product, and reduce
    each output element with ONE dot_general over span at the same
    precision and accumulator dtype.  Output: (dim, R) at the
    accumulator dtype — pad rows trimmed, pad columns contributing
    exact zeros (the inner factor is zero-padded)."""
    from splatt_tpu.ops.mttkrp import dense_operands, mxu_precision

    if mode != layout.mode:
        raise ValueError("fused_dense requires the layout's own mode")
    R = int(factors[0].shape[1])
    dtype = factors[0].dtype
    w, u = dense_operands(layout, factors, mode)
    ntiles, tile, span = (int(s) for s in layout.tiles.shape)
    acc = _acc_dtype(dtype)
    out = pl.pallas_call(
        functools.partial(_dense_kernel, rank=R,
                          precision=mxu_precision(dtype)),
        grid=(ntiles,),
        in_specs=[
            pl.BlockSpec((1, tile, span), lambda i: (i, 0, 0)),
            pl.BlockSpec((int(w.shape[0]), R), lambda i: (0, 0)),
            pl.BlockSpec((int(u.shape[0]), R), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tile, R), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((ntiles, tile, R), acc),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(layout.tiles, w, u)
    return out.reshape(-1, R)[:layout.dim]


def _probe_case_dense(kernel_fn, regime: str, block: int) -> bool:
    """The dense-engine probe compile — its own case because the
    kernel's call signature has no width/accumulate (the shared
    :func:`_probe_case` lowers the sparse-layout signature).  A
    synthetic near-dense mode at a production-like (tile, span); the
    Mosaic-sensitive step the probe exercises is the in-kernel
    (n_outer, inner_pad, R) -> (span, R) Khatri-Rao reshape."""
    import numpy as np

    from splatt_tpu.blocked import build_dense_layout
    from splatt_tpu.coo import SparseTensor

    rng = np.random.default_rng(0)
    dims = (64, 32, 256)
    nnz = 65536
    rank = 48 if _vmem_limit() >= (32 << 20) else 16
    inds = np.stack([rng.integers(0, d, nnz) for d in dims])
    tt = SparseTensor(inds=inds.astype(np.int64), vals=np.ones(nnz),
                      dims=dims)
    from splatt_tpu.config import fit_dtype

    lay = build_dense_layout(tt, 0)
    fac = [jnp.zeros((d, rank), fit_dtype()) for d in dims]
    kernel_fn.lower(lay, fac, mode=0, interpret=False).compile()
    return True


@functools.cache
def fused_dense_supported(regime: str = "ck1", block: int = 4096) -> bool:
    """Whether the dense-mode MXU kernel compiles here (the in-kernel
    broadcast-multiply + (n_outer·inner_pad, R) reshape that builds the
    Khatri-Rao tile is the Mosaic-sensitive step), probed per
    (lane-chunk regime, tile) like every engine — an unlowerable form
    demotes cleanly to the ``dense_xla`` reference path."""
    return _probe_compiles(fused_dense, "fused_dense", regime, block,
                           case=_probe_case_dense)
