"""Persistent capability-probe cache.

A probe's verdict depends only on (jax version, device kind, kernel,
regime, block), so it is cached on disk and reused by later processes —
a chip window is spent measuring, not re-proving what the previous
session stage already paid a remote compile for.  The contract under
test (the probe-cache lifecycle of the resilience layer): proven
verdicts ("ok"/"compile_failed"/"resource") short-circuit the probe,
"timeout"/"infra" are recorded but always retried, transient failures
are retried in-place with backoff and NEVER persisted as a rejection,
entries expire after a TTL, and cache IO failures never break dispatch.
"""

import json
import time

import jax
import pytest

import splatt_tpu.ops.pallas_kernels as pk
from splatt_tpu import resilience


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    """Transient-retry backoff must not slow the suite down."""
    monkeypatch.setattr(resilience.time, "sleep", lambda s: None)


@pytest.fixture()
def cache_file(tmp_path, monkeypatch):
    path = tmp_path / "probe_cache.json"
    monkeypatch.setenv(pk._CACHE_ENV, str(path))
    return path


@pytest.fixture()
def fake_tpu(monkeypatch):
    """Pretend the backend is TPU so _probe_compiles reaches the cache
    and probe machinery; the probe body itself is substituted per-test."""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")


def _states(snapshot):
    """Context to keep PROBE_STATES isolated per test."""
    pk.PROBE_STATES.clear()
    pk.PROBE_STATES.update(snapshot)


def test_store_load_roundtrip(cache_file):
    pk.probe_cache_store("fused_t:ck1:b4096", "ok")
    assert pk.probe_cache_load("fused_t:ck1:b4096") == "ok"
    assert pk.probe_cache_load("fused_t:ck1:b128") is None
    # the file is keyed by environment (jax version | device kind)
    data = json.loads(cache_file.read_text())
    (env_key,) = data.keys()
    assert jax.__version__ in env_key


def test_cache_hit_skips_probe(cache_file, fake_tpu, monkeypatch):
    _states({})
    pk.probe_cache_store("testk:ck1:b4096", "compile_failed")

    def boom(*a, **k):
        raise AssertionError("probe must not run on a cache hit")

    monkeypatch.setattr(pk, "_probe_case", boom)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is False
    assert pk.PROBE_STATES["testk:ck1:b4096"] == "compile_failed"

    pk.probe_cache_store("testk2:ck1:b4096", "ok")
    assert pk._probe_compiles(None, "testk2", "ck1", 4096) is True
    assert pk.PROBE_STATES["testk2:ck1:b4096"] == "ok"


def test_cache_miss_runs_probe_and_stores(cache_file, fake_tpu, monkeypatch):
    _states({})
    calls = []
    monkeypatch.setattr(pk, "_probe_case",
                        lambda fn, regime, block: calls.append(1) or True)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is True
    assert calls == [1]
    assert pk.probe_cache_load("testk:ck1:b4096") == "ok"
    # a second PROCESS (simulated: fresh PROBE_STATES) hits the cache
    _states({})
    monkeypatch.setattr(pk, "_probe_case",
                        lambda fn, regime, block: calls.append(2) or True)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is True
    assert calls == [1], "second process must not re-probe"


def test_timeout_is_retried_not_inherited(cache_file, fake_tpu, monkeypatch):
    _states({})
    pk.probe_cache_store("testk:ck1:b4096", "timeout")
    monkeypatch.setattr(pk, "_probe_case", lambda fn, regime, block: True)
    # an unproven verdict must NOT short-circuit: the probe runs and
    # upgrades the cached state to the proven one
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is True
    assert pk.probe_cache_load("testk:ck1:b4096") == "ok"


def test_infra_error_is_retried_not_inherited(cache_file, fake_tpu,
                                              monkeypatch):
    _states({})

    def flaky(fn, regime, block):
        raise RuntimeError("UNAVAILABLE: TPU backend setup error")

    monkeypatch.setattr(pk, "_probe_case", flaky)
    # a transient service failure is NOT a kernel rejection
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is False
    assert pk.PROBE_STATES["testk:ck1:b4096"] == "infra"
    assert pk.probe_cache_load("testk:ck1:b4096") == "infra"
    # the next process re-probes and can prove the kernel fine
    _states({})
    monkeypatch.setattr(pk, "_probe_case", lambda fn, regime, block: True)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is True
    assert pk.probe_cache_load("testk:ck1:b4096") == "ok"


def test_transient_500_retried_in_place_then_proven(cache_file, fake_tpu,
                                                    monkeypatch):
    """A transient HTTP 500 is retried with backoff INSIDE the probe:
    when the relay recovers within the retry budget, the verdict is
    proven in this very process — no demotion at all."""
    _states({})
    calls = []

    def flaky_then_ok(fn, regime, block):
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("XLA compile: HTTP code 500 from relay")
        return True

    monkeypatch.setattr(pk, "_probe_case", flaky_then_ok)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is True
    assert len(calls) == 3
    assert pk.probe_cache_load("testk:ck1:b4096") == "ok"


def test_transient_500_never_persisted_as_compile_failed(cache_file,
                                                         fake_tpu,
                                                         monkeypatch):
    """ADVICE.md medium: one wedged-relay 500 must NOT demote the
    flagship engine for every future session.  Retries exhausted →
    'infra' (re-probed next process); the on-disk cache must contain
    no 'compile_failed' entry."""
    _states({})

    def always_500(fn, regime, block):
        raise RuntimeError("XLA compile: HTTP code 500 from relay")

    monkeypatch.setattr(pk, "_probe_case", always_500)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is False
    assert pk.PROBE_STATES["testk:ck1:b4096"] == "infra"
    assert "compile_failed" not in cache_file.read_text()
    # bare INTERNAL: is transient too (no Mosaic co-marker)
    _states({})

    def always_internal(fn, regime, block):
        raise RuntimeError("INTERNAL: relay stream reset")

    monkeypatch.setattr(pk, "_probe_case", always_internal)
    assert pk._probe_compiles(None, "testk2", "ck1", 4096) is False
    assert "compile_failed" not in cache_file.read_text()
    # the next process re-probes and can prove the kernels fine
    _states({})
    monkeypatch.setattr(pk, "_probe_case", lambda fn, regime, block: True)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is True


def test_internal_mosaic_co_marker_is_deterministic(cache_file, fake_tpu,
                                                    monkeypatch):
    """'INTERNAL: Mosaic failed ...' carries a real compiler signature:
    the transient INTERNAL: prefix must not launder it into a retry —
    it persists as a proven rejection."""
    _states({})

    def mosaic_internal(fn, regime, block):
        raise RuntimeError("INTERNAL: Mosaic failed to lower the kernel")

    monkeypatch.setattr(pk, "_probe_case", mosaic_internal)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is False
    assert pk.probe_cache_load("testk:ck1:b4096") == "compile_failed"


def test_resource_verdict_is_shape_scoped_and_persisted(cache_file,
                                                        fake_tpu,
                                                        monkeypatch):
    """An OOM is capacity, not capability: persisted as 'resource' for
    THIS (regime, block) shape only — other shapes keep probing."""
    _states({})

    def oom(fn, regime, block):
        raise RuntimeError("RESOURCE_EXHAUSTED: attempting to allocate 9G")

    monkeypatch.setattr(pk, "_probe_case", oom)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is False
    assert pk.probe_cache_load("testk:ck1:b4096") == "resource"
    # the verdict short-circuits the next process for the same shape
    _states({})

    def boom(fn, regime, block):
        raise AssertionError("probe must not run on a cached resource "
                             "verdict")

    monkeypatch.setattr(pk, "_probe_case", boom)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is False
    # ... but a DIFFERENT shape still probes
    monkeypatch.setattr(pk, "_probe_case", lambda fn, regime, block: True)
    assert pk._probe_compiles(None, "testk", "ck1", 128) is True


def test_ttl_expiry_reprobes(cache_file, fake_tpu, monkeypatch):
    """Even a proven verdict expires after the TTL: infrastructure
    drifts under a fixed env key, so stale rejections (and stale OKs)
    are re-earned instead of trusted forever."""
    _states({})
    pk.probe_cache_store("testk:ck1:b4096", "compile_failed")
    # age the entry past the TTL
    data = json.loads(cache_file.read_text())
    for env in data.values():
        env["testk:ck1:b4096"]["ts"] = (
            time.time() - pk.probe_cache_ttl() - 1)
    cache_file.write_text(json.dumps(data))
    assert pk.probe_cache_load("testk:ck1:b4096") is None
    monkeypatch.setattr(pk, "_probe_case", lambda fn, regime, block: True)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is True
    assert pk.probe_cache_load("testk:ck1:b4096") == "ok"


def test_ttl_env_override(cache_file, monkeypatch):
    _states({})
    pk.probe_cache_store("testk:ck1:b4096", "ok")
    monkeypatch.setenv(pk._CACHE_TTL_ENV, "0.0")
    # TTL <= 0 disables expiry entirely
    assert pk.probe_cache_load("testk:ck1:b4096") == "ok"
    monkeypatch.setenv(pk._CACHE_TTL_ENV, "1e-9")
    assert pk.probe_cache_load("testk:ck1:b4096") is None


def test_kernel_edit_invalidates_cache(cache_file, fake_tpu, monkeypatch):
    _states({})
    pk.probe_cache_store("testk:ck1:b4096", "compile_failed")
    # simulate a kernel fix: the module source hash changes, so the old
    # environment's verdicts no longer apply and the probe re-runs
    monkeypatch.setattr(pk, "_kernel_src_hash", lambda: "newhash12345")
    monkeypatch.setattr(pk, "_probe_case", lambda fn, regime, block: True)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is True


def test_compile_failure_is_stored(cache_file, fake_tpu, monkeypatch):
    _states({})

    def fail(fn, regime, block):
        raise RuntimeError("Mosaic failed to compile the kernel")

    monkeypatch.setattr(pk, "_probe_case", fail)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is False
    assert pk.probe_cache_load("testk:ck1:b4096") == "compile_failed"
    assert pk.PROBE_STATES["testk:ck1:b4096"] == "compile_failed"


def test_unrecognized_error_is_not_persisted_as_rejection(cache_file,
                                                         fake_tpu,
                                                         monkeypatch):
    """Only whitelisted deterministic signatures may persist as
    compile_failed — the cache makes misclassification permanent, so an
    unknown exception is unproven and the next process re-probes."""
    _states({})

    def weird(fn, regime, block):
        raise OSError("Connection reset by peer")

    monkeypatch.setattr(pk, "_probe_case", weird)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is False
    assert pk.probe_cache_load("testk:ck1:b4096") == "infra"
    _states({})
    monkeypatch.setattr(pk, "_probe_case", lambda fn, regime, block: True)
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is True


def test_not_tpu_short_circuits_without_cache(cache_file):
    _states({})
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is False
    assert pk.PROBE_STATES["testk:ck1:b4096"] == "not_tpu"
    assert not cache_file.exists()


def test_cache_io_failure_is_harmless(fake_tpu, monkeypatch, tmp_path):
    _states({})
    # a path whose parent is a regular file: mkdir/open both fail
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    monkeypatch.setenv(pk._CACHE_ENV, str(blocker / "sub" / "cache.json"))
    monkeypatch.setattr(pk, "_probe_case", lambda fn, regime, block: True)
    # store/load both raise internally; dispatch still gets its verdict
    assert pk._probe_compiles(None, "testk", "ck1", 4096) is True


def test_reads_route_through_shared_json_cache_load(cache_file,
                                                    monkeypatch):
    """Regression for the SPL011 (cache-lock discipline) fix: both the
    probe cache and the autotuner's plan cache read through the single
    `_json_cache_load` helper — the sanctioned chokepoint of the locked
    cache protocol — and a corrupt file degrades through it with a
    classified run-report event instead of an inline open()."""
    calls = []
    real = pk._json_cache_load

    def spy(path, on_error=None):
        calls.append(str(path))
        return real(path, on_error=on_error)

    monkeypatch.setattr(pk, "_json_cache_load", spy)
    cache_file.write_text("{ not json")
    resilience.run_report().clear()
    assert pk.probe_cache_load("anything") is None
    assert calls and calls[0] == str(cache_file)
    assert resilience.run_report().events("probe_cache_io_error")

    from splatt_tpu import tune

    tune.reset_memo()
    monkeypatch.setenv(tune._CACHE_ENV, str(cache_file))
    resilience.run_report().clear()
    assert tune._load_file() is None
    assert len(calls) >= 2 and calls[-1] == str(cache_file)
    assert resilience.run_report().events("tune_cache_io_error")


# -- concurrent shared-cache access (docs/serve.md) --------------------------

def test_concurrent_probe_stores_lose_no_verdicts(cache_file):
    """N threads persisting distinct probe verdicts simultaneously
    (concurrent serve jobs proving different kernels): the locked
    read-modify-write keeps every verdict — no lost updates, no torn
    JSON."""
    import threading

    n = 16
    errs = []

    def store(i):
        try:
            pk.probe_cache_store(f"conc_state{i}",
                                 "ok" if i % 2 == 0 else "compile_failed")
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=store, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    data = json.loads(cache_file.read_text())  # parses: not torn
    env = data[pk._cache_env_key()]
    assert {f"conc_state{i}" for i in range(n)} <= set(env)
    for i in range(n):
        want = "ok" if i % 2 == 0 else "compile_failed"
        assert pk.probe_cache_load(f"conc_state{i}") == want


def test_concurrent_probe_and_tune_writers_share_one_protocol(cache_file,
                                                              monkeypatch):
    """Probe verdicts and tuner plans hammering their caches from
    interleaved threads (the serve steady state): both files end
    complete and parseable — the shared locked protocol serializes
    writers within the process as well as across processes."""
    import threading

    from splatt_tpu import tune

    monkeypatch.setenv(tune._CACHE_ENV,
                       str(cache_file.with_name("tc.json")))
    tune.reset_memo()
    errs = []

    def probe_writer(i):
        try:
            for k in range(4):
                pk.probe_cache_store(f"pt{i}k{k}", "ok")
        except Exception as e:  # pragma: no cover
            errs.append(e)

    def tune_writer(i):
        try:
            for k in range(4):
                tune._entry_store(
                    f"tt{i}k{k}",
                    {"plan": dict(path="sorted_onehot", engine="xla",
                                  nnz_block=512, scan_target=1 << 21,
                                  sec=0.5)})
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = ([threading.Thread(target=probe_writer, args=(i,))
                for i in range(4)]
               + [threading.Thread(target=tune_writer, args=(i,))
                  for i in range(4)])
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    probe_env = json.loads(cache_file.read_text())[pk._cache_env_key()]
    assert {f"pt{i}k{k}" for i in range(4) for k in range(4)} \
        <= set(probe_env)
    tune.reset_memo()
    for i in range(4):
        for k in range(4):
            assert tune._entry_get(f"tt{i}k{k}") is not None
