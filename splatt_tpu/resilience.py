"""Resilience layer: failure taxonomy, retries, demotions, run report.

Four consecutive rounds of chip unavailability (VERDICT.md) showed the
system's weakest point is failure HANDLING, not speed: one transient
remote-compile 500 used to be persisted as a permanent "compile_failed"
verdict, demoting the flagship Pallas engine for every future session.
Production tensor-decomposition stacks (GenTen's performance-portable
MTTKRP; the emerging-architectures survey) keep multiple backends live
so one backend's failure degrades, not kills, the run.  This module is
the single place that decides what a failure MEANS:

Failure taxonomy
    :func:`classify_failure` sorts probe/compile/runtime errors into

    - ``DETERMINISTIC`` — a proven kernel-compiler rejection (Mosaic
      signatures).  Safe to persist: the same sources on the same
      device will always fail.
    - ``TRANSIENT``     — the remote-compile relay or service hiccuping
      (HTTP 5xx, bare ``INTERNAL:``, ``UNAVAILABLE``, resets,
      timeouts).  Retried with capped exponential backoff + jitter,
      NEVER persisted.
    - ``RESOURCE``      — capacity, not capability (OOM / VMEM
      exhaustion).  Demotes the engine for this shape only.
    - ``UNKNOWN``       — anything unrecognized.  Treated like
      transient for persistence purposes (rejected this session,
      re-probed next process) but not retried in-place.
    - ``NUMERICAL``     — non-finite factors/λ/fit caught by the
      numerical-health sentinel (docs/guarded-als.md).  Handled by
      rollback + re-conditioning in the ALS drivers, never by the
      engine-demotion registry.
    - ``TIMEOUT``       — our own deadline watchdog (:func:`deadline`)
      blew on a host-side compile/measure/probe call.  Demotes
      per-shape exactly like RESOURCE.

Deadline watchdog
    :func:`deadline` — a thread-timer context manager bounding
    host-side compile/measure/probe calls (probe compiles, tuner
    measurements, first-call engine compiles); configured via
    ``SPLATT_DEADLINE_S`` / :func:`set_deadline`, fault-injectable via
    the ``slow`` kind (utils/faults.py).

Engine demotion registry
    :func:`demote_engine` / :func:`is_demoted` — runtime failures of a
    dispatch engine demote it (process-wide, or per-shape for RESOURCE
    and TIMEOUT failures) so the ordered fallback chain in
    :func:`splatt_tpu.ops.mttkrp.engine_chain` skips it mid-run instead
    of crashing ``cpd_als``.

Run report
    :func:`run_report` — an append-only event log (demotions, probe
    retries, checkpoint recoveries) the CLI prints at the end of a run,
    so silent degradation is observable (≙ the reference's stats
    reporting philosophy, src/stats.c).

Per-job scoping (docs/serve.md)
    All of the mutable state above — the demotion table, the
    last-attempt note, the run report, plus overrides for the
    health-retry budget and the deadline watchdog — lives in a
    :class:`ResilienceScope`.  Outside any scope the process-global
    scope applies (single-run CLI behavior, unchanged); the serve
    daemon wraps each supervised job in :func:`scope`, a contextvars-
    backed context manager, so one tenant's NUMERICAL rollback or OOM
    demotion is attributed to (and contained within) that job while the
    probe/tune/compile caches stay shared and warm across jobs (≙ the
    reference's per-run ``splatt_opts``/workspace separation).

Nothing here imports jax: classification is pure string logic so the
fault-injection tests exercise every branch without a device.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import enum
import random
import threading
import time
from typing import Callable, Dict, List, Optional


class FailureClass(enum.Enum):
    """What a probe/compile/runtime failure means for future dispatch."""

    DETERMINISTIC = "deterministic"   # persist: will always fail here
    TRANSIENT = "transient"           # retry w/ backoff; never persist
    RESOURCE = "resource"             # demote for this shape only
    UNKNOWN = "unknown"               # unproven; re-probe next process
    NUMERICAL = "numerical"           # non-finite factors/fit: roll back
    TIMEOUT = "timeout"               # our own deadline watchdog blew:
                                      # demote per-shape, like RESOURCE


# Capacity failures first: an OOM message may also mention the kernel
# compiler ("Mosaic ... scoped vmem limit exceeded"), and the right
# verdict there is shape-scoped demotion, not a permanent rejection.
RESOURCE_MARKERS = (
    "RESOURCE_EXHAUSTED", "Out of memory", "out of memory", "OOM",
    "vmem limit", "VMEM limit", "scoped vmem", "exceeds the limit",
    "Attempting to allocate", "Attempting to reserve",
)

# Deterministic Mosaic/kernel-compiler rejection signatures — the ONLY
# class that may be persisted as "compile_failed" (a persisted
# misclassification demotes the flagship engine for every future
# session, so this is a whitelist, not a transient-error blocklist).
# 'HTTP code 500' and bare 'INTERNAL: ' were deliberately REMOVED from
# this set (ADVICE.md round 5): they are classic transient relay
# failures and live in TRANSIENT_MARKERS below.
DETERMINISTIC_MARKERS = (
    "Mosaic", "mosaic", "Internal TPU kernel compiler",
    "Invalid input layout", "Unsupported lowering",
    "not implemented", "NotImplementedError",
    # io.py/ingest.py deliberate refusals: a torn .bin, a ragged text
    # tensor, or a corrupt ingest journal is content-deterministic —
    # retrying the same bytes reproduces the same refusal
    "truncated or torn", "ragged row", "bad token",
)

# Transient remote-compile / relay / service failures: retried with
# backoff, rejected only for this attempt window, never persisted.
TRANSIENT_MARKERS = (
    "HTTP code 500", "HTTP code 502", "HTTP code 503", "HTTP code 504",
    "INTERNAL: ", "UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED",
    "Connection reset", "Connection refused", "Socket closed",
    "Broken pipe", "timed out", "TimeoutError",
    "temporarily unavailable", "Transient",
)

# Our OWN watchdog's signature (resilience.deadline), checked before
# everything else: "DEADLINE_EXCEEDED"/"timed out" above are RPC-level
# transients worth retrying, but a deadline WE set and blew is a local
# capacity verdict for this shape — retrying the same slow compile
# would burn the budget again, so it demotes per-shape like OOM.
TIMEOUT_MARKERS = ("splatt deadline blown",)

# The health sentinel's signature (non-finite factors/λ/fit).  Never an
# engine-capability statement: rollback + re-conditioning owns it, not
# the demotion registry (docs/guarded-als.md).
NUMERICAL_MARKERS = ("non-finite", "NonFinite", "NumericalHealthError")


class DeadlineExceeded(RuntimeError):
    """The deadline watchdog (:func:`deadline`) blew on a host-side
    compile/measure/probe call.  Classifies as TIMEOUT: demoted
    per-shape like a RESOURCE failure — the same shapes will be slow
    again, other shapes are unindicted."""


class NumericalHealthError(RuntimeError):
    """The numerical-health sentinel found non-finite factors/λ/fit in
    a sweep's outputs (docs/guarded-als.md).  Classifies as NUMERICAL:
    handled by rollback + re-conditioning in the ALS drivers, never by
    the engine-demotion registry."""


def failure_message(exc) -> str:
    """The string classification runs on: "ExcType: message"."""
    if isinstance(exc, str):
        return exc
    return f"{type(exc).__name__}: {exc}"


def classify_failure(exc) -> FailureClass:
    """Classify a probe/compile/runtime error (exception or message).

    Order matters: RESOURCE outranks DETERMINISTIC (a Mosaic VMEM
    message is capacity, not capability), and DETERMINISTIC outranks
    TRANSIENT — "INTERNAL: Mosaic failed ..." carries a real compiler
    signature, so the transient 'INTERNAL: ' prefix must not launder it
    into a retry loop (ADVICE.md: bare 500/INTERNAL are transient
    UNLESS they co-occur with a Mosaic/kernel-compiler marker).
    """
    msg = failure_message(exc)
    # the two project-raised classes first: their markers are exact and
    # their messages may echo infrastructure noise (a blown deadline
    # message quoting 'timed out' must not become a retry loop)
    if isinstance(exc, DeadlineExceeded) \
            or any(m in msg for m in TIMEOUT_MARKERS):
        return FailureClass.TIMEOUT
    if isinstance(exc, NumericalHealthError) \
            or any(m in msg for m in NUMERICAL_MARKERS):
        return FailureClass.NUMERICAL
    if any(m in msg for m in RESOURCE_MARKERS):
        return FailureClass.RESOURCE
    if any(m in msg for m in DETERMINISTIC_MARKERS):
        return FailureClass.DETERMINISTIC
    if any(m in msg for m in TRANSIENT_MARKERS):
        return FailureClass.TRANSIENT
    return FailureClass.UNKNOWN


# -- transient retry --------------------------------------------------------

#: default retry budget for transient failures.  Small and capped: a
#: wedged relay must degrade the session in bounded time (the probe
#: machinery adds its own 240 s deadline on top).
TRANSIENT_RETRIES = 3
BACKOFF_BASE_S = 0.5
BACKOFF_CAP_S = 8.0


def retry_transient(fn: Callable, attempts: int = None,
                    base: float = BACKOFF_BASE_S,
                    cap: float = BACKOFF_CAP_S,
                    sleep: Optional[Callable] = None,
                    rng: Optional[Callable] = None,
                    label: str = "") -> object:
    """Run `fn`, retrying ONLY transient failures with capped
    exponential backoff + full jitter (delay ~ U(0, min(cap, base·2^a))
    — the decorrelated pattern that avoids thundering-herd re-compiles
    against a shared relay).  Deterministic / resource / unknown
    failures propagate immediately: retrying a proven rejection wastes
    the chip window.  `sleep`/`rng` are injectable for tests.
    """
    if attempts is None:
        attempts = TRANSIENT_RETRIES
    if sleep is None:
        sleep = time.sleep
    if rng is None:
        rng = random.random
    last = None
    for a in range(max(attempts, 1)):
        try:
            return fn()
        except Exception as e:
            last = e
            if (classify_failure(e) is not FailureClass.TRANSIENT
                    or a == attempts - 1):
                raise
            delay = min(cap, base * (2 ** a)) * rng()
            run_report().add("transient_retry", label=label,
                             attempt=a + 1, delay_s=round(delay, 3),
                             error=failure_message(e)[:200])
            sleep(delay)
    raise last  # pragma: no cover — loop always returns or raises


# -- engine demotion registry -----------------------------------------------

@dataclasses.dataclass
class Demotion:
    """One runtime engine demotion: which engine, why, and its scope
    (shape_key=None means process-wide; otherwise this shape only)."""

    engine: str
    failure_class: FailureClass
    error: str
    shape_key: Optional[str] = None
    ts: float = dataclasses.field(default_factory=time.time)


def _demotion_key(engine: str, shape_key: Optional[str]) -> str:
    return engine if shape_key is None else f"{engine}@{shape_key}"


def demote_engine(engine: str, error, shape_key: Optional[str] = None
                  ) -> Demotion:
    """Record a runtime demotion of `engine`; the fallback chain skips
    it from now on.  RESOURCE and TIMEOUT failures demote per-shape
    (pass the shape_key — an OOM or a blown compile deadline indicts
    only shapes of that size); everything else process-wide.  Never
    persisted to disk: a demotion lasts one process — the probe cache
    owns cross-process verdicts with its own (stricter) persistence
    rules.  Inside a :func:`scope` the demotion is confined to that
    job: one tenant's OOM must not steer its neighbors' dispatch."""
    cls = classify_failure(error)
    if cls not in (FailureClass.RESOURCE, FailureClass.TIMEOUT):
        shape_key = None
    d = Demotion(engine=engine, failure_class=cls,
                 error=failure_message(error)[:500], shape_key=shape_key)
    _state().demoted[_demotion_key(engine, shape_key)] = d
    run_report().add("engine_demotion", engine=engine,
                     failure_class=cls.value, shape_key=shape_key,
                     error=d.error[:200])
    return d


def is_demoted(engine: str, shape_key: Optional[str] = None) -> bool:
    """Whether `engine` was demoted in the current scope (process-wide
    outside any :func:`scope`), or for this shape."""
    demoted = _state().demoted
    if engine in demoted:
        return True
    return (shape_key is not None
            and _demotion_key(engine, shape_key) in demoted)


def demotions() -> List[Demotion]:
    return list(_state().demoted.values())


def reset_demotions() -> None:
    """Clear the current scope's runtime demotions (tests; a fresh run
    in one process)."""
    _state().demoted.clear()


# -- last-attempt tracking --------------------------------------------------
#
# Failures on accelerators can surface ASYNCHRONOUSLY — not at the
# mttkrp_blocked call that picked the engine, but at the next host sync
# inside the sweep.  The dispatch layer notes which engine it handed
# work to; the driver-level handler (cpd_als) uses it to demote the
# right engine when an exception arrives with no call-site context.
# Scope-local: two concurrent jobs' dispatches must not cross-attribute.


def note_engine_attempt(engine: str, shape_key: Optional[str] = None
                        ) -> None:
    _state().last_attempt = (engine, shape_key)


def last_engine_attempt() -> Optional[tuple]:
    """(engine, shape_key) of the current scope's most recent dispatch,
    or None."""
    return _state().last_attempt


# -- engine fallback switch -------------------------------------------------

_FALLBACK_ENV = "SPLATT_ENGINE_FALLBACK"
_fallback_override: Optional[bool] = None


def fallback_enabled() -> bool:
    """Whether runtime engine fallback is on (default yes).  CLI
    --engine-fallback off / SPLATT_ENGINE_FALLBACK=0 disable it — a
    differential test chasing a kernel bug wants the crash, not the
    silent rescue."""
    if _fallback_override is not None:
        return _fallback_override
    from splatt_tpu.utils.env import read_env

    return str(read_env(_FALLBACK_ENV)).lower() not in (
        "0", "off", "false", "no")


def set_fallback(enabled: Optional[bool]) -> None:
    """Process-wide override (None restores the env default)."""
    global _fallback_override
    _fallback_override = enabled


# -- deadline watchdog ------------------------------------------------------
#
# A pathological shape can hang a remote compile long past any useful
# deadline (observed: >40 min probe compiles).  The watchdog bounds
# host-side compile/measure/probe calls with a plain threading.Timer —
# no signals (they do not compose with jax's own handlers or with
# non-main threads), and jit-safe because it only ever wraps HOST-side
# work: inside a trace it wraps tracing time, which is bounded anyway.

_DEADLINE_ENV = "SPLATT_DEADLINE_S"
_deadline_override: Optional[float] = None


def set_deadline(seconds: Optional[float]) -> None:
    """Process-wide deadline override for :func:`deadline` sites (None
    restores the env default; <= 0 disables the optional sites even
    when SPLATT_DEADLINE_S is exported — sites with their own default,
    like the probe, keep it).  The chaos harness uses this instead of
    mutating the environment."""
    global _deadline_override
    _deadline_override = seconds


def deadline_seconds(default: Optional[float] = None) -> Optional[float]:
    """The configured watchdog deadline: the current job scope's
    override if set (serve gives each job its own budget), else the
    process override (<= 0 meaning "disabled" — the caller's `default`
    still applies, so the probe's always-on 240 s survives an explicit
    disable), else SPLATT_DEADLINE_S, else `default`.  None = disabled.
    """
    sc = _SCOPE.get()
    if sc is not None and sc.deadline_s is not None:
        if sc.deadline_s > 0:
            return sc.deadline_s
        return default
    if _deadline_override is not None:
        if _deadline_override > 0:
            return _deadline_override
        return default
    from splatt_tpu.utils.env import read_env_float

    env = read_env_float(_DEADLINE_ENV)
    if env is not None and float(env) > 0:
        return float(env)
    return default


@contextlib.contextmanager
def deadline(site: str, seconds: Optional[float] = None):
    """Bound a host-side compile/measure/probe call: if the wrapped
    block runs longer than `seconds` (default: the configured
    :func:`deadline_seconds`), raise :class:`DeadlineExceeded`
    (→ TIMEOUT: demoted per-shape exactly like OOM) and record a
    ``deadline_blown`` run-report event.

    Mechanics: a daemon ``threading.Timer`` fires after `seconds`.
    From the MAIN thread it additionally calls
    ``_thread.interrupt_main()`` so a blocked Python-level call is
    interrupted between bytecodes (a call hung inside C that never
    releases the GIL still gets the after-the-fact raise when it
    returns); from any other thread the blown deadline raises when the
    block completes.  Either way the failure is classified the same —
    the watchdog's job is converting "slow" into a *classified* error
    instead of an unbounded hang.
    """
    if seconds is None:
        seconds = deadline_seconds()
    if not seconds or seconds <= 0:
        yield
        return
    state = {"fired": False, "done": False}
    lock = threading.Lock()
    on_main = threading.current_thread() is threading.main_thread()

    def fire():
        # fired-flag and interrupt are one critical section: once the
        # main thread observes fired=True (it reads under this lock's
        # ordering in the finally below), the interrupt is already
        # pending, so the absorb sleep deterministically receives it —
        # no window where a stray KeyboardInterrupt can outlive the
        # context manager and kill a later, unguarded sweep
        with lock:
            if state["done"]:
                return
            state["fired"] = True
            if on_main:
                import _thread

                _thread.interrupt_main()

    # guard work is explicitly attributed (docs/observability.md):
    # arming the watchdog gets its own span so ROADMAP open item 1's
    # "are the guards taxing the hot loop?" is a trace query
    from splatt_tpu import trace

    with trace.span("guard.deadline.arm", site=site,
                    seconds=float(seconds)):
        timer = threading.Timer(seconds, fire)
        timer.daemon = True
        timer.start()

    def blew() -> "DeadlineExceeded":
        run_report().add("deadline_blown", site=site,
                         seconds=float(seconds))
        return DeadlineExceeded(
            f"splatt deadline blown at {site} after {seconds:g}s "
            f"(host-side call exceeded the watchdog budget)")

    try:
        try:
            yield
        finally:
            with trace.span("guard.deadline.disarm", site=site):
                with lock:
                    state["done"] = True
                timer.cancel()
                if state["fired"] and on_main:
                    # the timer fired (possibly while we were already
                    # exiting): absorb the pending interrupt_main HERE,
                    # inside the guarded region, so it cannot escape as
                    # a bare KeyboardInterrupt after the with-block
                    try:
                        time.sleep(0.05)
                    except KeyboardInterrupt:
                        pass
    except KeyboardInterrupt:
        # covers both the yield and the cleanup above: an interrupt
        # delivered mid-finally (lock acquire, timer.cancel) still
        # converts to the classified error instead of leaking.  Known
        # ambiguity: a GENUINE Ctrl-C landing inside a blown-deadline
        # window is indistinguishable from the watchdog's own interrupt
        # (no signal handlers by design) and is reclassified as the
        # timeout; the window is one blown call per site, after which
        # the demotion prevents repeats — a second Ctrl-C aborts.
        if state["fired"]:
            raise blew() from None
        raise
    if state["fired"]:
        raise blew()


# -- run report -------------------------------------------------------------

#: Every run-report event kind the code emits, name -> one-line doc —
#: the authoritative documentation of the observability surface,
#: mirroring utils/env.py:ENV_VARS.  `splint` rule SPL012 statically
#: checks every ``run_report().add("<kind>", ...)`` emission site
#: against this registry (both directions: undeclared emissions and
#: declared-but-never-emitted kinds are findings), so the docs and the
#: code cannot drift apart.  Tests may add ad-hoc kinds through a
#: RunReport instance directly; the registry governs production
#: emissions only.
RUN_REPORT_EVENTS = {
    "transient_retry": "a transient failure was retried in place with "
                       "capped backoff+jitter (retry_transient)",
    "engine_demotion": "a dispatch engine was demoted at runtime "
                       "(process-wide, or per-shape for RESOURCE "
                       "failures) and the fallback chain skips it",
    "checkpoint_recovery": "a corrupt/torn checkpoint degraded the "
                           "resume to the .bak generation or a fresh "
                           "start (cpd.load_checkpoint_resilient)",
    "probe_downgrade": "a capability-probe verdict was downgraded to "
                       "unproven for this session (re-probed next "
                       "process)",
    "probe_cache_io_error": "probe-cache IO failed and was degraded "
                            "(cache stays best-effort; verdicts are "
                            "re-earned)",
    "tune_cache_io_error": "plan-cache IO failed and was degraded "
                           "(dispatch falls back to re-tuning or the "
                           "heuristic chain)",
    "tuned_plan": "cpd_als dispatched through autotuned MTTKRP plans "
                  "(docs/autotune.md); carries the per-mode plans",
    "tuner_negative": "an autotuner candidate failed to measure; "
                      "deterministic/resource failures persist as "
                      "negative plan-cache entries",
    "tuner_degraded": "a mode keeps the heuristic chain instead of a "
                      "tuned plan: no candidate was measurable, or the "
                      "plan's storage verdict could not apply under "
                      "the resolved whole-tensor policy (blocked.py)",
    "block_clamp": "build_layout clamped the requested nnz block to "
                   "the tensor's size (blocked.py); carries the "
                   "requested format so v1/v2 plans stay "
                   "distinguishable in the log",
    "format_v2": "blocked layouts were built at a non-default encoding "
                 "(compact v2 local/segment indices and/or narrowed "
                 "value storage, docs/format.md); carries the achieved "
                 "per-mode format descriptions",
    "format_fallback": "a compact-format encode failed (blocked.py, "
                       "the format.encode fault site), its native "
                       "stream consumption failed at dispatch "
                       "(ops/mttkrp.py, the format.decode site — "
                       "site=decode), or a dense tile-layout build "
                       "failed (blocked.py, the format.dense site — "
                       "site=dense, docs/dense.md) and the run "
                       "degraded CLASSIFIED to the v1 i32 / sparse "
                       "path — slower bytes, never a failed build or "
                       "run",
    "dense_dispatch": "first dispatch of a dense-tile MTTKRP engine "
                      "over a dense-mode layout (ops/mttkrp.py, "
                      "docs/dense.md): records the engine, mode, row "
                      "tile, span and density bucket — the "
                      "zero-index-bytes contract made observable",
    "format_decode": "first dispatch of an engine over a compact "
                     "layout: records the consumed encoding and "
                     "whether decode runs natively in-kernel/per-"
                     "chunk (fused_v2/xla_scan/xla) or at operand "
                     "prep (the fused_t family) — the achieved-"
                     "bytes≈encoded-bytes contract made observable "
                     "(ops/mttkrp.py, docs/format.md)",
    "packing_fallback": "a balanced fiber pack failed and the build "
                        "degraded CLASSIFIED to the fixed slicing "
                        "(blocked.py, the layout.pack fault site; "
                        "docs/layout-balance.md) — worse balance, "
                        "never a failed build",
    "reorder_fallback": "a reorder recipe's permutation compute/apply "
                        "failed and the layout build degraded "
                        "CLASSIFIED to identity order (reorder.py "
                        "apply_reorder, the reorder.apply fault site; "
                        "docs/layout-balance.md) — worse locality, "
                        "never a failed run",
    "layout_imbalance": "achieved load-balance of a built layout or "
                        "distributed sharding (max/mean nnz per "
                        "block/span/shard, one-hot work "
                        "amplification; docs/layout-balance.md) — "
                        "carried by splatt cpd --json, bench and "
                        "MULTICHIP artifacts",
    "compile_cache_error": "SPLATT_COMPILE_CACHE could not be applied "
                           "to jax's persistent compilation cache "
                           "(utils/env.py:apply_compile_cache — "
                           "read-only path, older jax); the run "
                           "continues, recompiling instead of loading "
                           "shared executables",
    "env_platform_error": "JAX_PLATFORMS could not be mirrored into "
                          "jax.config (utils/env.py:"
                          "apply_env_platform); the run continues on "
                          "whatever backend jax picks",
    "health_nonfinite": "the numerical-health sentinel found "
                        "non-finite factors/λ/fit in a sweep's outputs "
                        "at a fit-check iteration "
                        "(docs/guarded-als.md)",
    "health_rollback": "the ALS driver rolled back to the last-good "
                       "host snapshot, bumped regularization and/or "
                       "re-randomized the offending factor, and "
                       "retried the sweep",
    "health_degraded": "the rollback budget (SPLATT_HEALTH_RETRIES) "
                       "was exhausted: the run checkpointed the "
                       "last-good state and stopped early with a "
                       "degraded verdict instead of diverging",
    "deadline_blown": "the deadline watchdog (resilience.deadline) "
                      "expired on a host-side compile/measure/probe "
                      "call; classified TIMEOUT and demoted per-shape "
                      "like OOM",
    "bench_path_error": "one benchmark path failed mid-run; the error "
                        "was classified and recorded and the "
                        "remaining paths continued (bench.py)",
    "bench_regression": "the fresh benchmark ran >10% slower than the "
                        "newest prior BENCH_*.json on the same metric; "
                        "bench.py --gate turns this into a nonzero "
                        "exit (record_bench_regression)",
    "job_accepted": "the serve daemon accepted a job submission and "
                    "journaled it durably (docs/serve.md); an accepted "
                    "job reaches a terminal state even across daemon "
                    "crashes",
    "job_resumed": "journal replay re-enqueued a non-terminal job "
                   "after a daemon restart; the job resumes from its "
                   "last hardened checkpoint (docs/serve.md)",
    "job_started": "a worker began running an accepted job (emitted "
                   "next to the journal's started record); as a trace "
                   "point event it is the flight recorder's "
                   "deterministic 'this job was live HERE' mark — the "
                   "fleet soak post-mortems a SIGKILLed replica's "
                   "ring for it (docs/observability.md)",
    "queue_full": "the serve daemon's bounded queue load-shed a "
                  "submission (SPLATT_SERVE_QUEUE_MAX); the client "
                  "gets an explicit rejection instead of unbounded "
                  "queueing (docs/serve.md)",
    "job_degraded": "a supervised job finished degraded or failed "
                    "(health budget exhausted, blown deadline, or a "
                    "classified error) instead of converging; the "
                    "job's own run report carries the evidence "
                    "(docs/serve.md)",
    "journal_torn": "journal replay skipped one unparseable record — "
                    "final OR mid-file, the debris a writer dying "
                    "mid-append (or a SIGKILLed fleet replica) can "
                    "leave; classified and skipped, never fatal, and "
                    "the next append heals a torn tail before writing "
                    "(serve.py Journal, docs/fleet.md)",
    "journal_unknown_kind": "journal replay skipped a record whose "
                            "kind this version does not know "
                            "(serve.KNOWN_KINDS) — a newer writer's "
                            "journal or hand-edited debris; skipped "
                            "classified instead of wedging the job "
                            "table (the SPL022 forward-compat gate, "
                            "docs/static-analysis.md)",
    "crash_windows_exercised": "which durable-op crash windows a "
                               "chaos soak's kills actually landed in "
                               "(window ids from the crash-point "
                               "checker's vocabulary, tools/splint/"
                               "crashpoint.py) — the dynamic-coverage "
                               "half of the static-vs-dynamic "
                               "comparison in docs/static-analysis.md",
    "job_adopted": "a fleet replica took over a dead peer's "
                   "non-terminal job after its lease expired (the "
                   "fleet.adopt takeover path); the job resumes from "
                   "its hardened checkpoint on the adopter "
                   "(docs/fleet.md)",
    "lease_expired": "a job lease expired: role=owner — this "
                     "replica's renew was refused and the running job "
                     "was abandoned uncommitted; role=adopter — an "
                     "expired lease was observed and taken over "
                     "(fleet.py/serve.py, docs/fleet.md)",
    "quota_rejected": "admission control shed a submission because "
                      "its tenant is at the per-tenant non-terminal-"
                      "job quota (SPLATT_FLEET_TENANT_QUOTA) — one "
                      "tenant flooding the spool cannot crowd out "
                      "the rest (serve.py, docs/fleet.md)",
    "affinity_routed": "the fleet scheduler made a cache-affinity "
                       "decision: a job dispatched to this replica's "
                       "warm caches (warm_local), deferred to a warm "
                       "peer (deferred), or taken anyway on the load "
                       "tiebreaker / deferral cap (load_tiebreak) "
                       "(serve.py, docs/fleet.md)",
    "comm_fallback": "a distributed comm engine failed its probe and "
                     "the sweep degraded down the comm chain — "
                     "async_ring -> ring -> all2all — with the failed "
                     "strategy demoted under its own comm shape key "
                     "(parallel/sharded.py, docs/ring.md)",
    "ring_overlap": "achieved comm/compute overlap of a ring-variant "
                    "distributed sweep: standalone exchange time vs "
                    "the fraction hidden under the local MTTKRP, next "
                    "to the wire model's per-device bytes "
                    "(docs/ring.md; carried into MULTICHIP artifacts "
                    "and `splatt cpd --json`)",
    "bench_noisy": "a bench --gate timing comparison was too noisy to "
                   "judge: one side's coefficient of variation "
                   "exceeded the absolute ceiling, or the delta was "
                   "smaller than CV_NOISE_MULT x the measured CV (the "
                   "carried threshold names whichever bound fired), "
                   "so the slowdown is a warning, not a gate failure "
                   "(bench.py)",
    "trace_written": "a Chrome trace-event JSON export "
                     "(trace.write_chrome_trace, the --trace <path> "
                     "flag; docs/observability.md) was written, or "
                     "failed classified — losing the trace must never "
                     "lose the run; ok=False with path '(annotation)' "
                     "records a degraded TPU trace-annotation probe",
    "metrics_snapshot": "the metrics registry was snapshotted to a "
                        "Prometheus text file (trace.write_metrics — "
                        "the serve cadence via SPLATT_METRICS_PATH / "
                        "SPLATT_METRICS_INTERVAL_S; "
                        "docs/observability.md); a write failure "
                        "degrades classified, never kills the daemon "
                        "it observes",
    "slo_burn": "an SLO's error-budget burn rate exceeded the alert "
                "threshold on BOTH the short and long windows "
                "(fleetobs.SloEvaluator, the multi-window burn-rate "
                "policy of docs/observability.md): carries the slo "
                "name, both burn rates and the window; counted into "
                "splatt_slo_burn_total so a burn spike is visible in "
                "every later fleet aggregate",
    "flight_degraded": "a flight-recorder ring flush failed (the "
                       "trace.flight fault site): the recorder is "
                       "DISARMED for the rest of the process and the "
                       "failure classified — the black box must never "
                       "take down the run it records "
                       "(docs/observability.md)",
    "batch_dispatched": "the serve daemon coalesced >= "
                        "SPLATT_SERVE_BATCH_MIN queued same-regime "
                        "jobs into ONE vmapped batched CPD "
                        "(serve.py _run_batch -> cpd.cpd_als_batched; "
                        "docs/batched.md): carries the member job "
                        "ids, the regime key and k — per-job journal "
                        "lineage, results and quotas are preserved "
                        "through the batch",
    "batch_degraded": "a coalesced batch failed at dispatch or "
                      "mid-run (the serve.batch fault site included) "
                      "and degraded CLASSIFIED to per-tensor "
                      "dispatch of its members (docs/batched.md) — "
                      "batching is an optimization, never a new way "
                      "to lose a job",
    "update_applied": "an `update` job appended its delta COO to a "
                      "checkpointed model and committed the "
                      "warm-started sweeps (serve.py _run_update; "
                      "docs/batched.md): carries base, update "
                      "ordinal, sweep count, delta nnz and the "
                      "reached fit — the model-store lineage `splatt "
                      "status --json` audits",
    "refit_scheduled": "an `update` job took the full-refit repair "
                       "path instead of (or after) the warm update: "
                       "reason records why — no_model, the periodic "
                       "SPLATT_UPDATE_REFIT_EVERY boundary, a "
                       "health-sentinel degrade, or a classified "
                       "warm-path failure (docs/batched.md)",
    "model_torn": "a model-store artifact failed its integrity fence "
                  "— a checkpoint whose factor content does not "
                  "match the generation stamp, a stamp-less or "
                  "unparseable generation file, or a `.model.npz` "
                  "missing its `applied` array / failing checksum "
                  "(serve.py _load_model_tensor, predict.py "
                  "load_model_generation): carries the failure class "
                  "and which piece tore; readers degrade to the "
                  "`.bak` generation or refuse, writers route to the "
                  "refit repair path — never a silent consume "
                  "(docs/predict.md)",
    "model_generation_advanced": "a model-store commit atomically "
                                 "advanced the model's generation "
                                 "stamp (predict.py "
                                 "advance_generation from serve.py's "
                                 "update/fit commits): carries model, "
                                 "the new gen ordinal and the factor "
                                 "content sha — the fence every "
                                 "predict pins against "
                                 "(docs/predict.md)",
    "predict_served": "a predict job answered from an intact, "
                      "generation-fenced model (serve.py "
                      "_run_predict): carries model, the served "
                      "generation, the pinned-at-admission "
                      "generation and the cache outcome — the "
                      "journal-auditable staleness evidence "
                      "(docs/predict.md)",
    "predict_degraded": "a predict's preferred path failed "
                        "classified: a poisoned cache fell back to "
                        "the direct read, or no intact generation "
                        "survived the fence and the predict was "
                        "REFUSED (reason records which) — a refusal, "
                        "never garbage (docs/predict.md)",
    "record_quarantined": "streaming ingest quarantined one malformed "
                          "stream record to the sidecar (ingest.py "
                          "parse_chunk; docs/ingest.md): carries the "
                          "chunk ordinal, source line and byte "
                          "offset, and the quarantine class — "
                          "bad_arity, bad_token, bad_index or "
                          "nonfinite_value — so a 100M-line corpus "
                          "names its bad records exactly",
    "watermark_advanced": "one ingest chunk passed its journal-append "
                          "fence (ingest.py IngestState.advance — "
                          "AFTER the durable append, docs/ingest.md "
                          "fence order): carries the chunk ordinal, "
                          "its nnz/records/quarantined counts and "
                          "the resume byte offset — the exactly-once "
                          "commit made journal-auditable",
    "ingest_resumed": "an ingest run opened against a non-empty "
                      "chunk journal and resumed from its watermark "
                      "(ingest.py IngestState._replay): carries the "
                      "watermark, skipped-chunk count and the resume "
                      "offset — the crash-recovery evidence the "
                      "SIGKILL soak asserts on (docs/ingest.md)",
    "ingest_degraded": "the quarantine budget tripped (count over "
                       "SPLATT_INGEST_QUARANTINE_MAX or rate over "
                       "SPLATT_INGEST_QUARANTINE_RATE) and the run "
                       "stopped CLASSIFIED with its committed "
                       "watermark intact (ingest.py ingest_stream; "
                       "docs/ingest.md) — degraded and resumable, "
                       "never a silently corrupt tensor",
    "vocab_stats": "ingest finalize's vocabulary report (ingest.py "
                   "IngestState.finalize; docs/ingest.md): which "
                   "modes are vocab-mapped and each mode's final "
                   "cardinality — the power-law structure evidence "
                   "ROADMAP item 1 wants from real corpora",
}


def record_path_error(label: str, exc) -> dict:
    """Classify a benchmark path failure into a ``bench_path_error``
    run-report event and return the event — the shared emission point
    bench.py uses so a failing path is recorded and skipped instead of
    aborting the whole benchmark."""
    return run_report().add(
        "bench_path_error", path=label,
        failure_class=classify_failure(exc).value,
        error=failure_message(exc)[:200])


def record_bench_regression(path: str, sec: float, prior_sec: float,
                            pct: float, prior_file: str) -> dict:
    """Record a ``bench_regression`` run-report event — the shared
    emission point bench.py's gate uses when a fresh timing runs >10%
    slower than the newest prior BENCH_*.json on the same metric, so
    every future PR ships with a perf verdict instead of a bare number
    (ROADMAP open item 1)."""
    return run_report().add(
        "bench_regression", path=path, sec=round(float(sec), 4),
        prior_sec=round(float(prior_sec), 4), pct=round(float(pct), 1),
        prior_file=prior_file)


def record_bench_noisy(path: str, cv: float, threshold: float,
                       sec: float, prior_sec: float,
                       prior_file: str) -> dict:
    """Record a ``bench_noisy`` run-report event — the shared emission
    point bench.py's gate uses when a would-be regression's timing
    distribution is too noisy to trust (CV above `threshold` on either
    side): the comparison becomes a loud warning instead of a hard
    gate failure, so regression verdicts stay verdicts rather than
    noise (ROADMAP open item 1 remnant)."""
    return run_report().add(
        "bench_noisy", path=path, cv=round(float(cv), 4),
        threshold=round(float(threshold), 4), sec=round(float(sec), 4),
        prior_sec=round(float(prior_sec), 4), prior_file=prior_file)


class RunReport:
    """Append-only log of resilience events for one run: engine
    demotions, transient retries, probe verdict downgrades, checkpoint
    recoveries.  The CLI prints :meth:`summary` after the run so silent
    degradation is observable; tests assert on :meth:`events`.  A
    report owned by a job :func:`scope` stamps its ``job_id`` onto
    every event so multi-tenant logs stay attributable."""

    def __init__(self, job_id: Optional[str] = None):
        self._events: List[dict] = []
        self.job_id = job_id

    def add(self, kind: str, **info) -> dict:
        ev = dict(kind=kind, ts=time.time(), **info)
        if self.job_id is not None and "job" not in ev:
            ev["job"] = self.job_id
        self._events.append(ev)
        # every emission is ALSO a timestamped point event attached to
        # the enclosing trace span (and feeds the always-on metrics
        # registry): demotions, fallbacks and rollbacks become visible
        # in time order on the exported trace (docs/observability.md)
        from splatt_tpu import trace

        trace.point(kind, ev)
        return ev

    def events(self, kind: Optional[str] = None) -> List[dict]:
        if kind is None:
            return list(self._events)
        return [e for e in self._events if e["kind"] == kind]

    def clear(self) -> None:
        self._events.clear()

    def summary(self) -> List[str]:
        """Human-readable lines, one per noteworthy event (retries are
        aggregated — their details matter for debugging, not reporting)."""
        lines = []
        retries = self.events("transient_retry")
        if retries:
            lines.append(f"  {len(retries)} transient failure(s) retried "
                         f"with backoff")
        for e in self.events("engine_demotion"):
            scope = (f"shape {e['shape_key']}" if e.get("shape_key")
                     else "this process")
            lines.append(f"  engine {e['engine']} demoted for {scope} "
                         f"({e['failure_class']}: {e['error'][:80]})")
        for e in self.events("checkpoint_recovery"):
            lines.append(f"  checkpoint {e['path']} was corrupt "
                         f"({e['error'][:80]}); {e['action']}")
        for e in self.events("probe_downgrade"):
            lines.append(f"  probe {e['state_key']}: {e['verdict']} "
                         f"(unproven — re-probed next process)")
        negatives = self.events("tuner_negative")
        if negatives:
            lines.append(f"  {len(negatives)} autotuner candidate(s) "
                         f"failed to measure (deterministic failures "
                         f"recorded as negative plan-cache entries)")
        for e in self.events("tuner_degraded"):
            why = e.get("reason") or ("no measurable candidate — "
                                      "dispatch keeps the heuristic "
                                      "chain")
            lines.append(f"  autotuner: mode {e['mode']}: {why}")
        nonfinite = self.events("health_nonfinite")
        if nonfinite:
            its = sorted({e.get("iteration") for e in nonfinite})
            lines.append(f"  numerical-health sentinel: non-finite "
                         f"sweep outputs at iteration(s) "
                         f"{', '.join(str(i) for i in its)}")
        for e in self.events("health_rollback"):
            lines.append(f"  rolled back to the last-good snapshot at "
                         f"iteration {e.get('iteration')} (attempt "
                         f"{e.get('attempt')}: reg={e.get('regularization')}"
                         f", re-randomized modes "
                         f"{e.get('rerandomized') or []})")
        for e in self.events("health_degraded"):
            lines.append(f"  HEALTH BUDGET EXHAUSTED at iteration "
                         f"{e.get('iteration')}: returned the last-good "
                         f"state ({e.get('action')})")
        for e in self.events("deadline_blown"):
            lines.append(f"  deadline watchdog blew at {e['site']} "
                         f"({e['seconds']:g}s budget)")
        for e in self.events("bench_path_error"):
            lines.append(f"  bench path {e['path']} failed "
                         f"({e['failure_class']}: {e['error'][:80]}); "
                         f"remaining paths continued")
        for e in self.events("format_fallback"):
            if e.get("site") == "decode":
                lines.append(f"  compact-format decode failed at "
                             f"dispatch for mode {e.get('mode')} "
                             f"({e['failure_class']}: "
                             f"{e['error'][:80]}); degraded to the "
                             f"materialized v1 i32 path")
            elif e.get("site") == "dense":
                lines.append(f"  dense tile-layout build failed for "
                             f"mode {e.get('mode')} "
                             f"({e['failure_class']}: "
                             f"{e['error'][:80]}); mode keeps the "
                             f"sparse blocked encoding")
            else:
                lines.append(f"  compact-format encode failed for mode "
                             f"{e.get('mode')} "
                             f"(requested {e.get('idx_width')}; "
                             f"{e['failure_class']}: {e['error'][:80]}); "
                             f"degraded to the v1 i32 encoding")
        for e in self.events("dense_dispatch"):
            lines.append(f"  dense-mode dispatch [{e.get('engine')}]: "
                         f"mode {e.get('mode')} as "
                         f"{e.get('tile')}x{e.get('span')} value tiles "
                         f"({e.get('density_bucket') or 'dense'}; zero "
                         f"index bytes)")
        for e in self.events("packing_fallback"):
            lines.append(f"  balanced fiber pack failed for mode "
                         f"{e.get('mode')} ({e['failure_class']}: "
                         f"{e['error'][:80]}); degraded to fixed "
                         f"slicing")
        for e in self.events("reorder_fallback"):
            lines.append(f"  reorder recipe {e.get('how')!r} failed "
                         f"({e['failure_class']}: {e['error'][:80]}); "
                         f"degraded to identity order")
        for e in self.events("layout_imbalance"):
            # only imbalanced layouts/shards are worth a summary line;
            # the full stats always ride in the --json events
            worst = max(e.get("block_nnz_max_mean", 1.0) or 1.0,
                        e.get("shard_max_mean", 1.0) or 1.0)
            if worst > 1.5:
                where = (f"{e.get('scope', 'layout')} mode {e['mode']}"
                         if "mode" in e else e.get("scope", "sharding"))
                lines.append(f"  load imbalance at {where} "
                             f"[{e.get('packing', e.get('policy', '?'))}]"
                             f": max/mean {worst} "
                             f"(seg_width {e.get('seg_width', '-')}, "
                             f"work x{e.get('work_amp', '-')}/nnz)")
        for e in self.events("bench_regression"):
            lines.append(f"  BENCH REGRESSION on {e['path']}: "
                         f"{e['sec']}s vs {e['prior_sec']}s in "
                         f"{e['prior_file']} (+{e['pct']}%)")
        for e in self.events("bench_noisy"):
            lines.append(f"  bench comparison on {e['path']} too noisy "
                         f"to gate (CV {e['cv']} > {e['threshold']}): "
                         f"{e['sec']}s vs {e['prior_sec']}s in "
                         f"{e['prior_file']} — warning, not a verdict")
        for e in self.events("comm_fallback"):
            lines.append(f"  comm engine {e['strategy']} degraded to "
                         f"{e['fallback_to']} ({e['failure_class']}: "
                         f"{e['error'][:80]})")
        for e in self.events("ring_overlap"):
            lines.append(f"  ring overlap [{e.get('engine')}]: "
                         f"{100 * e.get('overlap_frac', 0):.0f}% of "
                         f"{e.get('exchange_s')}s exchange hidden under "
                         f"compute ({e.get('model_mb_per_device')}MB/dev "
                         f"modeled)")
        for e in self.events("queue_full"):
            lines.append(f"  job {e.get('job')} load-shed: the serve "
                         f"queue was full ({e.get('queue_max')} pending)")
        for e in self.events("job_resumed"):
            lines.append(f"  job {e.get('job')} resumed from the "
                         f"journal after a daemon restart")
        torn = self.events("journal_torn")
        if torn:
            lines.append(f"  journal replay skipped {len(torn)} torn "
                         f"record(s) (crash debris; healed on the "
                         f"next append)")
        for e in self.events("job_adopted"):
            lines.append(f"  job {e.get('job')} ADOPTED by "
                         f"{e.get('replica')} from dead peer "
                         f"{e.get('from_replica')}")
        for e in self.events("lease_expired"):
            if e.get("role") == "owner":
                lines.append(f"  job {e.get('job')}: lease expired "
                             f"under {e.get('replica')} — abandoned "
                             f"uncommitted (a peer may adopt)")
        for e in self.events("quota_rejected"):
            lines.append(f"  job {e.get('job')} shed: tenant "
                         f"{e.get('tenant')} at quota "
                         f"({e.get('live')}/{e.get('quota')} "
                         f"non-terminal)")
        routed = self.events("affinity_routed")
        if routed:
            by_reason: Dict[str, int] = {}
            for e in routed:
                by_reason[e.get("reason", "?")] = \
                    by_reason.get(e.get("reason", "?"), 0) + 1
            lines.append("  affinity routing: " + ", ".join(
                f"{k}x{v}" for k, v in sorted(by_reason.items())))
        for e in self.events("job_degraded"):
            lines.append(f"  job {e.get('job')} finished degraded "
                         f"({e.get('failure_class')}: "
                         f"{str(e.get('error', ''))[:80]})")
        for e in self.events("trace_written"):
            if e.get("ok"):
                lines.append(f"  trace written to {e.get('path')} "
                             f"({e.get('spans')} spans, "
                             f"{e.get('events')} point events)")
            else:
                lines.append(f"  trace export {e.get('path')} degraded "
                             f"({e.get('failure_class')}: "
                             f"{str(e.get('error', ''))[:80]})")
        snaps = self.events("metrics_snapshot")
        ok_snaps = [e for e in snaps if e.get("ok")]
        if ok_snaps:
            lines.append(f"  {len(ok_snaps)} metrics snapshot(s) "
                         f"written to {ok_snaps[-1].get('path')}")
        for e in snaps:
            if not e.get("ok"):
                lines.append(f"  metrics snapshot to {e.get('path')} "
                             f"FAILED ({e.get('failure_class')}: "
                             f"{str(e.get('error', ''))[:80]})")
        burns = self.events("slo_burn")
        if burns:
            by_slo: Dict[str, int] = {}
            for e in burns:
                by_slo[e.get("slo", "?")] = \
                    by_slo.get(e.get("slo", "?"), 0) + 1
            worst = max(burns, key=lambda e: e.get("burn_short", 0))
            lines.append(f"  SLO BURN: " + ", ".join(
                f"{k}x{v}" for k, v in sorted(by_slo.items()))
                + f" (worst {worst.get('slo')}: "
                f"{worst.get('burn_short', 0):g}x short / "
                f"{worst.get('burn_long', 0):g}x long over "
                f"{worst.get('window_s', 0):g}s)")
        for e in self.events("flight_degraded"):
            lines.append(f"  flight recorder {e.get('path')} DISARMED "
                         f"({e.get('failure_class')}: "
                         f"{str(e.get('error', ''))[:80]})")
        for e in self.events("batch_dispatched"):
            lines.append(f"  batch of {e.get('k')} same-regime jobs "
                         f"dispatched as one vmapped CPD "
                         f"(regime {e.get('regime')})")
        for e in self.events("batch_degraded"):
            lines.append(f"  BATCH DEGRADED to per-tensor dispatch "
                         f"({e.get('failure_class')}: "
                         f"{str(e.get('error', ''))[:80]}; "
                         f"{len(e.get('jobs') or [])} member(s) re-run "
                         f"individually)")
        for e in self.events("update_applied"):
            lines.append(f"  update #{e.get('update_n')} applied to "
                         f"model {e.get('base')}: {e.get('delta_nnz')} "
                         f"delta nnz folded in over {e.get('sweeps')} "
                         f"warm sweeps (fit {e.get('fit'):.5f})"
                         if e.get("fit") is not None else
                         f"  update #{e.get('update_n')} applied to "
                         f"model {e.get('base')}")
        for e in self.events("refit_scheduled"):
            lines.append(f"  model {e.get('base')}: full refit "
                         f"scheduled at update #{e.get('update_n')} "
                         f"({e.get('reason')})")
        for e in self.events("model_torn"):
            lines.append(f"  MODEL TORN: {e.get('piece')} of "
                         f"{e.get('path')} "
                         f"({e.get('failure_class')}: "
                         f"{str(e.get('error', ''))[:80]})")
        for e in self.events("predict_degraded"):
            lines.append(f"  predict on model {e.get('model')} "
                         f"degraded ({e.get('reason')}: "
                         f"{str(e.get('error', ''))[:80]})")
        quarantined = self.events("record_quarantined")
        if quarantined:
            by_cls: Dict[str, int] = {}
            for e in quarantined:
                k = e.get("quarantine_class", "?")
                by_cls[k] = by_cls.get(k, 0) + 1
            first = quarantined[0]
            lines.append(f"  ingest quarantined {len(quarantined)} "
                         f"record(s): " + ", ".join(
                             f"{k}x{v}"
                             for k, v in sorted(by_cls.items()))
                         + f" (first at line {first.get('line')}, "
                         f"offset {first.get('offset')})")
        advanced = self.events("watermark_advanced")
        if advanced:
            last = advanced[-1]
            lines.append(f"  ingest committed {len(advanced)} "
                         f"chunk(s) this run (watermark "
                         f"{last.get('chunk')}, total nnz "
                         f"{last.get('total_nnz')})")
        for e in self.events("ingest_resumed"):
            lines.append(f"  ingest RESUMED from watermark "
                         f"{e.get('watermark')} ({e.get('chunks')} "
                         f"committed chunk(s) replayed from the "
                         f"journal, offset {e.get('offset')})")
        for e in self.events("ingest_degraded"):
            lines.append(f"  INGEST DEGRADED: quarantine budget "
                         f"tripped at watermark {e.get('watermark')} "
                         f"({e.get('quarantined')} quarantined; "
                         f"{str(e.get('error', ''))[:80]})")
        for e in self.events("vocab_stats"):
            lines.append(f"  ingest vocab: modes "
                         f"[{e.get('vocab_modes')}] vocab-mapped, "
                         f"cardinalities {e.get('cardinalities')}")
        return lines


# -- per-job scoping (docs/serve.md) ----------------------------------------
#
# One serve daemon runs many tenants' decompositions in one process.
# The mutable resilience state — the demotion table, the async
# last-attempt note, the run report — used to be module-global, so one
# tenant's OOM demotion silently steered every neighbor's dispatch and
# one job's health rollback polluted every other job's report.  A
# ResilienceScope is the isolation unit: contextvars-backed, so each
# supervised job (one thread/async context) sees its own state while
# code outside any scope keeps the process-global scope — the
# single-run CLI behavior, unchanged.  The probe/tune/compile caches
# are deliberately NOT scoped: capability and plan verdicts are
# facts about the environment, not about a tenant, and sharing them
# warm is the point of serving many jobs from one process.

@dataclasses.dataclass
class ResilienceScope:
    """One isolation unit of mutable resilience state: the engine
    demotion table, the last-attempt note, the run report, and
    per-scope overrides for the health-retry budget and the deadline
    watchdog (None = inherit the env/process default)."""

    job_id: Optional[str] = None
    demoted: Dict[str, Demotion] = dataclasses.field(default_factory=dict)
    last_attempt: Optional[tuple] = None
    health_retries: Optional[int] = None
    deadline_s: Optional[float] = None
    report: RunReport = None

    def __post_init__(self):
        if self.report is None:
            self.report = RunReport(job_id=self.job_id)


_GLOBAL_SCOPE = ResilienceScope()
_SCOPE: contextvars.ContextVar = contextvars.ContextVar(
    "splatt_resilience_scope", default=None)


def _state() -> ResilienceScope:
    """The active scope: the contextvar's if a job scope is entered on
    this thread/context, else the process-global scope."""
    return _SCOPE.get() or _GLOBAL_SCOPE


def current_job() -> Optional[str]:
    """The job id of the active scope, or None outside any scope."""
    sc = _SCOPE.get()
    return sc.job_id if sc is not None else None


def scope_health_retries() -> Optional[int]:
    """The active scope's health-retry budget override, or None (the
    env default applies) — consulted by cpd.health_retries()."""
    sc = _SCOPE.get()
    return sc.health_retries if sc is not None else None


@contextlib.contextmanager
def scope(job_id: str, health_retries: Optional[int] = None,
          deadline_s: Optional[float] = None):
    """Enter a fresh per-job resilience scope: demotions, health
    verdicts, the last-attempt note and every run-report event inside
    the block are attributed to `job_id` and isolated from the global
    scope and from every sibling job.  Scopes start EMPTY (no inherited
    demotions): a neighbor's capacity verdict is not evidence against
    this tenant's shapes — cross-job capability facts belong to the
    shared probe cache, which has stricter persistence rules.

    `health_retries` / `deadline_s` override the env-configured
    sentinel budget and watchdog deadline for this job only."""
    st = ResilienceScope(job_id=str(job_id), health_retries=health_retries,
                         deadline_s=deadline_s)
    token = _SCOPE.set(st)
    try:
        yield st
    finally:
        _SCOPE.reset(token)


def run_report() -> RunReport:
    """The active scope's resilience event log (the process-wide log
    outside any :func:`scope`)."""
    return _state().report
