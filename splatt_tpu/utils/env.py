"""Environment/platform helpers shared by entry points.

This module is additionally the single place process environment is
read from (`splint` rule SPL001 enforces it): every environment
variable the project consumes is declared once in :data:`ENV_VARS`
(name → default → doc) and read through :func:`read_env` /
:func:`read_env_int` / :func:`read_env_float`.  Centralizing the reads
matters beyond tidiness — this file feeds the probe cache's
`_kernel_src_hash`, so an env-plumbing change invalidates cached
capability verdicts instead of silently desynchronizing from them, and
the registry is what keeps the docs (docs/resilience.md, DESIGN.md)
and the SPL007 documentation check from drifting against the code.
"""

from __future__ import annotations

import os
import sys
from typing import NamedTuple, Optional


class EnvVar(NamedTuple):
    """One declared environment variable: its default (None = unset)
    and a one-line doc string (the authoritative documentation — docs
    reference this registry instead of hand-listing variables)."""

    default: Optional[object]
    doc: str


#: Every environment variable the project reads, name → (default, doc).
#: `splint` rule SPL007 statically checks each SPLATT_* reference in
#: the code against this table; `python -m tools.splint --env-docs`
#: renders it for the docs.
ENV_VARS = {
    "JAX_PLATFORMS": EnvVar(None, "standard JAX platform selection; "
                            "mirrored into jax.config by "
                            "apply_env_platform() so it beats site "
                            "plugins that pick a backend at startup"),
    "SPLATT_ENGINE_FALLBACK": EnvVar("1", "runtime MTTKRP engine "
                                     "fallback (docs/resilience.md); "
                                     "0/off/false/no = fail loudly"),
    "SPLATT_SCAN_TARGET_ELEMS": EnvVar(1 << 23, "one-hot elements "
                                       "materialized per scan step of "
                                       "the xla_scan MTTKRP engine"),
    "SPLATT_EXPERIMENTAL_FUSED": EnvVar(None, "1 re-enables the "
                                        "experimental row-major fused "
                                        "Pallas kernel in the engine "
                                        "chain (known-unlowerable on "
                                        "current Mosaic)"),
    "SPLATT_FAULTS": EnvVar("", "comma-separated fault-arming specs "
                            "site[:kind][:modifier]... for the fault-"
                            "injection harness, including seeded chaos "
                            "schedules iter=k / p=x:seed=N / after=t "
                            "(utils/faults.py, docs/guarded-als.md)"),
    "SPLATT_HEALTH_RETRIES": EnvVar(3, "numerical-health sentinel "
                                    "rollback budget: how many times a "
                                    "run may restore the last-good "
                                    "snapshot (bumping regularization "
                                    "/ re-randomizing the offending "
                                    "factor) before degrading to "
                                    "checkpoint-and-abort; 0 disables "
                                    "the sentinel "
                                    "(docs/guarded-als.md)"),
    "SPLATT_DEADLINE_S": EnvVar(0.0, "deadline watchdog budget in "
                                "seconds for host-side compile/"
                                "measure/probe calls (probe compiles, "
                                "tuner measurements, engine dispatch); "
                                "a blown deadline classifies TIMEOUT "
                                "and demotes per-shape like OOM; <= 0 "
                                "disables (the probe keeps its own "
                                "240 s default) (docs/guarded-als.md)"),
    "SPLATT_CHAOS_SCHEDULE": EnvVar("", "default fault schedule for "
                                    "the `splatt chaos` soak verb when "
                                    "no --schedule flag is given; same "
                                    "grammar as SPLATT_FAULTS "
                                    "(docs/guarded-als.md)"),
    "SPLATT_COMM": EnvVar(None, "default row-exchange strategy for "
                          "FINE-decomposition distributed runs "
                          "(docs/ring.md): all2all (collectives), "
                          "point2point (ppermute ring), async_ring "
                          "(Pallas remote-copy ring with "
                          "comm/compute overlap; degrades classified "
                          "point2point -> all2all on failure); an "
                          "explicit Options.comm_pattern / --comm "
                          "wins"),
    "SPLATT_PROBE_CACHE": EnvVar(None, "path override for the "
                                 "persistent capability-probe cache "
                                 "(default: tools/probe_cache.json in "
                                 "a repo checkout)"),
    "SPLATT_COMPILE_CACHE": EnvVar(None, "directory for JAX's "
                                   "persistent compilation cache, "
                                   "applied by every splatt entry "
                                   "point (CLI verbs, serve replicas, "
                                   "bench.py) before backends "
                                   "initialize: processes sharing the "
                                   "path reuse each other's serialized "
                                   "XLA executables, so a cold "
                                   "replica's first same-shape job "
                                   "skips compilation (the first rung "
                                   "of the warm-fleet artifact, "
                                   "ROADMAP item 4).  Unset = no "
                                   "persistent cache; enable failures "
                                   "degrade classified "
                                   "(compile_cache_error) and the run "
                                   "just compiles.  CAUTION: on "
                                   "current jaxlib, executing a "
                                   "DESERIALIZED multi-device sharded "
                                   "CPU executable corrupts the heap "
                                   "— scope the knob to single-device "
                                   "processes (fleet replicas) on CPU "
                                   "hosts"),
    "SPLATT_PROBE_CACHE_TTL_S": EnvVar(14 * 24 * 3600.0, "seconds a "
                                       "cached probe verdict stays "
                                       "fresh; <= 0 disables expiry "
                                       "(also the autotuner plan-cache "
                                       "TTL, docs/autotune.md)"),
    "SPLATT_IDX_WIDTH": EnvVar("i32", "blocked-layout index-width "
                               "policy (docs/format.md): i32 = v1 "
                               "global int32 indices; auto = compact "
                               "v2 encoding (per-block local indices, "
                               "uint16 where each mode's block extent "
                               "fits, int32 otherwise, plus int32 "
                               "per-block bases); u16 = v2 requiring "
                               "uint16 everywhere; u8 = v2 with the "
                               "sorted mode's segment-id stream at "
                               "uint8 (legal when every block's span "
                               "fits 255) and the other modes at the "
                               "auto widths; delta = v2 with the "
                               "gather modes' local streams stored as "
                               "within-block differences at the "
                               "narrowest signed width (i8 on smooth "
                               "runs; decode = one exact per-block "
                               "cumsum); rle = v2 with the sorted "
                               "mode's segment stream replaced by "
                               "per-block run-length counts (seg_width "
                               "entries instead of block entries — the "
                               "dense-ish-block hybrid).  All encode "
                               "failures degrade classified to v1"),
    "SPLATT_DECODE": EnvVar("kernel", "decode placement for compact "
                            "layouts (docs/format.md): kernel = "
                            "dispatch consumes the encoded streams "
                            "natively (the fused_v2 Pallas engine "
                            "decodes in registers; the xla_scan "
                            "engine decodes per chunk inside the "
                            "scan) so achieved HBM bytes track the "
                            "encoded bytes; prep = force operand-"
                            "prep decode (the pre-v2 dataflow: "
                            "global i32 materialized before the "
                            "kernel) — the A/B lever behind bench's "
                            "decode_overhead model"),
    "SPLATT_VAL_STORAGE": EnvVar("auto", "blocked-layout value-storage "
                                 "dtype (docs/format.md): auto = the "
                                 "resolved compute dtype; f32/bf16 pin "
                                 "it — bf16 stores nonzero values (and "
                                 "the factors derived from them) in "
                                 "bfloat16 with f32 accumulation"),
    "SPLATT_FIBER_PACKING": EnvVar("fixed", "blocked-layout fiber-"
                                   "packing policy (docs/layout-"
                                   "balance.md): fixed = slice the "
                                   "sorted stream every nnz_block "
                                   "nonzeros (the original policy); "
                                   "balanced = nnz-weighted fiber bin "
                                   "packing with long-fiber splitting, "
                                   "bounding each block's output-row "
                                   "span so skewed tensors stop "
                                   "inflating seg_width (a failed pack "
                                   "degrades classified to fixed).  An "
                                   "explicit Options.fiber_packing "
                                   "wins; unset, both are autotuner "
                                   "candidates"),
    "SPLATT_DENSE": EnvVar("off", "dense-mode tile layout policy "
                           "(docs/dense.md): off = every mode keeps "
                           "the sparse blocked encoding; auto = a "
                           "mode whose padded fiber density crosses "
                           "SPLATT_DENSE_THRESHOLD (and whose dense "
                           "cells stay within the blowup cap) gets a "
                           "dense tile layout and the MXU matmul "
                           "engines; on = force the dense tiling for "
                           "every geometrically feasible mode.  A "
                           "tuned dense plan wins over this policy; "
                           "any dense build failure degrades "
                           "classified to the sparse encoding "
                           "(format_fallback, site=dense)"),
    "SPLATT_DENSE_THRESHOLD": EnvVar("0.05", "padded per-mode density "
                                     "(nnz / dense tile cells) at or "
                                     "above which SPLATT_DENSE=auto "
                                     "elects the dense tile layout "
                                     "(docs/dense.md)"),
    "SPLATT_REORDER": EnvVar(None, "index-relabeling reorder applied "
                             "before blocked layouts are built (docs/"
                             "layout-balance.md): identity | random | "
                             "graph | hgraph | fibsched.  One whole-"
                             "tensor permutation relabels every mode; "
                             "factors are restored to original row "
                             "order on output (Permutation.undo).  An "
                             "explicit Options.reorder wins; unset, "
                             "the recipes are autotuner candidates and "
                             "compile applies a unanimous verdict.  "
                             "Any reorder failure degrades classified "
                             "to identity (reorder_fallback)"),
    "SPLATT_AUTOTUNE": EnvVar("1", "MTTKRP dispatch consults the "
                              "autotuner's persisted plan cache "
                              "(docs/autotune.md) before the heuristic "
                              "engine chain; 0/off/false/no = static "
                              "heuristics only"),
    "SPLATT_TUNE_CACHE": EnvVar(None, "path override for the "
                                "autotuner's persistent plan cache "
                                "(default: tune_cache.json next to the "
                                "probe cache)"),
    # structured tracing + metrics (splatt_tpu/trace.py,
    # docs/observability.md)
    "SPLATT_TRACE": EnvVar(None, "1/on/true/yes enables structured "
                           "span recording (docs/observability.md): "
                           "host-side spans (cpd -> sweep -> guard, "
                           "dispatch, comm) exportable as Chrome "
                           "trace-event JSON via --trace <path>.  Off "
                           "by default: disabled spans are no-ops "
                           "(one boolean check); an explicit "
                           "Options.trace / CLI --trace wins.  "
                           "Event-derived metrics are always on "
                           "regardless"),
    "SPLATT_METRICS_PATH": EnvVar(None, "serve: when set, the metrics "
                                  "registry (trace.METRICS) is "
                                  "snapshotted to this file in "
                                  "Prometheus text exposition format "
                                  "on a cadence "
                                  "(SPLATT_METRICS_INTERVAL_S) and at "
                                  "daemon exit — atomic replace, so a "
                                  "scraper never reads a torn file "
                                  "(docs/observability.md)"),
    "SPLATT_METRICS_INTERVAL_S": EnvVar(30.0, "serve: seconds between "
                                        "metrics snapshots to "
                                        "SPLATT_METRICS_PATH; <= 0 "
                                        "snapshots only at daemon "
                                        "exit"),
    "SPLATT_TRACE_MAX_RECORDS": EnvVar(100000, "in-memory span/point "
                                       "recorder bound: past this "
                                       "many finished records the "
                                       "OLDEST are dropped (counted, "
                                       "surfaced on trace_written) — "
                                       "what lets a fleet daemon run "
                                       "with recording + the flight "
                                       "ring armed for its whole "
                                       "life without unbounded RSS"),
    # flight recorder (splatt_tpu/trace.py, docs/observability.md)
    "SPLATT_FLIGHT": EnvVar("auto", "flight recorder — the bounded, "
                            "incrementally-appended ring of recent "
                            "spans/point events that survives a "
                            "SIGKILL (docs/observability.md): auto = "
                            "armed by fleet-mode `splatt serve` at "
                            "<root>/fleet/flight/<replica>.jsonl, off "
                            "elsewhere; 0/off disables even in fleet "
                            "mode; 1/on keeps the fleet default "
                            "explicit"),
    "SPLATT_FLIGHT_BYTES": EnvVar(1 << 20, "flight recorder: rotate "
                                  "the ring file atomically to "
                                  "<path>.1 once it outgrows this "
                                  "many bytes (one previous "
                                  "generation kept — the bound on "
                                  "the black box)"),
    "SPLATT_FLIGHT_FLUSH": EnvVar(32, "flight recorder: buffered "
                                  "records per ring-file flush; a "
                                  "SIGKILL loses at most this many "
                                  "trailing records (smaller = "
                                  "fresher black box, more write "
                                  "calls on the span path)"),
    # SLO layer (splatt_tpu/fleetobs.py, docs/observability.md)
    "SPLATT_SLO_QUEUE_WAIT_P95_S": EnvVar(30.0, "SLO objective: 95% "
                                          "of jobs start within this "
                                          "many seconds of acceptance "
                                          "(the splatt_serve_queue_"
                                          "wait_seconds histogram; "
                                          "threshold rounds up to a "
                                          "histogram bucket bound)"),
    "SPLATT_SLO_JOB_WALL_P95_S": EnvVar(600.0, "SLO objective: 95% of "
                                        "terminal jobs finish within "
                                        "this many wall seconds (the "
                                        "splatt_job_seconds "
                                        "histogram)"),
    "SPLATT_SLO_AVAILABILITY": EnvVar(0.99, "SLO objective: the "
                                     "accepted fraction of "
                                     "submissions — availability = "
                                     "1 - (queue_full + "
                                     "quota_rejected) / offered"),
    "SPLATT_SLO_WINDOW_S": EnvVar(300.0, "SLO burn-rate short window "
                                  "in seconds; the long window is "
                                  "SPLATT_SLO_LONG_WINDOWS times "
                                  "this (docs/observability.md)"),
    "SPLATT_SLO_LONG_WINDOWS": EnvVar(12, "SLO burn-rate long window, "
                                     "as a multiple of "
                                     "SPLATT_SLO_WINDOW_S (default "
                                     "12: a 5-minute short window "
                                     "pairs with a 1-hour long one)"),
    "SPLATT_SLO_BURN": EnvVar(2.0, "SLO alert threshold: emit "
                              "slo_burn when the error-budget burn "
                              "rate meets/exceeds this multiple on "
                              "BOTH windows (multi-window gating "
                              "suppresses blips and stale burns "
                              "alike)"),
    "SPLATT_SLO_PREDICT_P99_S": EnvVar(0.25, "SLO objective: 99% of "
                                       "served predicts complete "
                                       "within this many wall seconds "
                                       "accepted-to-served (the "
                                       "splatt_predict_latency_seconds "
                                       "histogram; threshold rounds "
                                       "up to a histogram bucket "
                                       "bound; docs/predict.md)"),
    # predict lane (splatt_tpu/predict.py + serve.py, docs/predict.md)
    "SPLATT_PREDICT_QUEUE_MAX": EnvVar(64, "serve predict lane: "
                                       "bounded pending-predict "
                                       "depth, separate from the "
                                       "fit/update queue; a predict "
                                       "past it is load-shed with an "
                                       "explicit queue_full rejection "
                                       "(<= 0 disables the bound)"),
    "SPLATT_PREDICT_CACHE_MAX": EnvVar(8, "predict hot-factor cache: "
                                      "(model, generation) entries "
                                      "kept per replica, LRU-evicted "
                                      "past the bound — an update "
                                      "commit invalidates by "
                                      "generation advance, never "
                                      "deletion, so a pinned "
                                      "in-flight predict still "
                                      "finishes on its generation; "
                                      "<= 0 disables the cache"),
    # fleet status / top (splatt_tpu/fleetobs.py, docs/fleet.md)
    "SPLATT_STATUS_JOBS": EnvVar(8, "splatt status/top: how many "
                                 "recent terminal jobs the dashboard "
                                 "lists"),
    "SPLATT_STATUS_WATCH_S": EnvVar(2.0, "splatt top / status --watch: "
                                    "seconds between dashboard "
                                    "refreshes"),
    "SPLATT_BENCH_TRACE_AB": EnvVar(None, "bench.py: 1 = time cpd_als "
                                    "with span recording enabled-but-"
                                    "unexported vs off — plus a third "
                                    "leg with the flight-recorder "
                                    "ring armed — over the same "
                                    "blocked layouts and record the "
                                    "legs under 'trace_ab' "
                                    "(trace_overhead_pct / "
                                    "flight_overhead_pct vs the <2% "
                                    "budget of docs/observability.md)"),
    # serve daemon knobs (splatt_tpu/serve.py, docs/serve.md)
    "SPLATT_SERVE_WORKERS": EnvVar(1, "serve: concurrent job-supervisor "
                                   "threads; each job runs under its "
                                   "own resilience scope, sharing the "
                                   "warm probe/tune/compile caches"),
    "SPLATT_SERVE_QUEUE_MAX": EnvVar(16, "serve: bounded pending-queue "
                                     "depth; a submission past it is "
                                     "load-shed with an explicit "
                                     "queue_full rejection instead of "
                                     "queueing unboundedly; <= 0 "
                                     "disables the bound"),
    "SPLATT_SERVE_POLL_S": EnvVar(0.5, "serve: seconds between "
                                  "filed-request spool scans in the "
                                  "daemon loop"),
    "SPLATT_SERVE_JOB_DEADLINE_S": EnvVar(0.0, "serve: default per-job "
                                          "deadline in seconds (a job "
                                          "spec's deadline_s "
                                          "overrides, 0 = explicit "
                                          "opt-out); a blown job "
                                          "deadline classifies "
                                          "TIMEOUT and the job is "
                                          "marked failed, releasing "
                                          "its worker; <= 0 disables"),
    "SPLATT_SERVE_BATCH_MIN": EnvVar(0, "serve auto-coalescing "
                                     "(docs/batched.md): when a "
                                     "replica's queue holds >= this "
                                     "many batchable jobs sharing one "
                                     "regime key, a worker dispatches "
                                     "them as ONE vmapped batched CPD "
                                     "(per-job journal lineage, "
                                     "results, deadlines and quotas "
                                     "preserved; failure degrades "
                                     "classified to per-tensor "
                                     "dispatch); <= 0 disables"),
    "SPLATT_UPDATE_SWEEPS": EnvVar(5, "update jobs (docs/batched.md): "
                                   "warm-started ALS sweeps an "
                                   "incremental model update runs "
                                   "when its spec gives no iters — "
                                   "the point of warm-starting is "
                                   "that a few sweeps suffice where "
                                   "a refit needs dozens"),
    "SPLATT_UPDATE_REFIT_EVERY": EnvVar(0, "update jobs "
                                        "(docs/batched.md): every Nth "
                                        "update of one base model "
                                        "runs a from-scratch refit of "
                                        "the merged tensor instead of "
                                        "the warm path (drift "
                                        "repair; refit_scheduled "
                                        "event); <= 0 disables the "
                                        "periodic cadence (the "
                                        "health/failure repair paths "
                                        "stay active)"),
    # fleet-mode serve knobs (splatt_tpu/fleet.py, docs/fleet.md)
    "SPLATT_FLEET_REPLICA": EnvVar(None, "fleet: this replica's "
                                   "stable id (file-name-safe); "
                                   "default is a fresh pid+random id "
                                   "per process — set it explicitly "
                                   "when a restarted replica should "
                                   "keep its identity"),
    "SPLATT_FLEET_LEASE_S": EnvVar(10.0, "fleet: job/membership lease "
                                   "duration in seconds — the "
                                   "failure-detection horizon: a "
                                   "replica silent this long is dead "
                                   "and its non-terminal jobs are "
                                   "adopted by live peers"),
    "SPLATT_FLEET_HEARTBEAT_S": EnvVar(0.0, "fleet: seconds between "
                                       "heartbeat/lease-renewal "
                                       "sweeps; <= 0 derives "
                                       "lease_s / 3"),
    "SPLATT_FLEET_TENANT_QUOTA": EnvVar(0, "serve admission control: "
                                        "max non-terminal jobs per "
                                        "tenant; past it submissions "
                                        "are shed with a "
                                        "quota_rejected event; <= 0 "
                                        "disables (docs/fleet.md)"),
    "SPLATT_FLEET_AFFINITY": EnvVar("1", "fleet: cache-affinity "
                                    "routing — jobs prefer the "
                                    "replica whose probe/tune/compile "
                                    "caches are warm for their shape "
                                    "regime, load as the tiebreaker; "
                                    "0/off/false/no = pure "
                                    "priority/FIFO dispatch"),
    "SPLATT_LOCKCHECK": EnvVar("0", "runtime lock-ownership sanitizer "
                               "(utils/lockcheck.py): the structures "
                               "declared in [tool.splint] "
                               "shared-state are wrapped in proxies "
                               "asserting their owning lock is held "
                               "by the mutating thread — the dynamic "
                               "cross-check of splint rule SPL014; "
                               "off by default (zero wrappers)"),
    # repo-root bench.py driver knobs (documented here; bench.py is a
    # standalone script outside the package's SPL001 scope)
    "SPLATT_BENCH_PRIOR_DIR": EnvVar(None, "bench.py: directory "
                                     "searched for the newest prior "
                                     "BENCH_*.json the regression "
                                     "gate compares against (default: "
                                     "the repo root)"),
    "SPLATT_BENCH_NNZ": EnvVar(None, "bench.py: synthetic tensor "
                               "nonzero count (per-driver default)"),
    "SPLATT_BENCH_RANK": EnvVar(None, "bench.py: CPD rank "
                                "(per-driver default)"),
    "SPLATT_BENCH_ITERS": EnvVar(3, "bench.py: timed iterations"),
    "SPLATT_BENCH_DTYPE": EnvVar("float32", "bench.py: compute dtype"),
    "SPLATT_BENCH_SHAPE": EnvVar("nell2", "bench.py: named tensor "
                                 "shape or IxJxK"),
    "SPLATT_BENCH_PATHS": EnvVar(None, "bench.py: comma-separated "
                                 "MTTKRP paths to time"),
    "SPLATT_BENCH_ENGINE": EnvVar("auto", "bench.py: force one "
                                  "reduction engine"),
    "SPLATT_BENCH_ALLOC": EnvVar("allmode", "bench.py: BlockAlloc "
                                 "layout policy"),
    "SPLATT_BENCH_JIT": EnvVar("auto", "bench.py: sweep jit mode"),
    "SPLATT_BENCH_SCENARIO": EnvVar("uniform", "bench.py: named nnz-"
                                    "distribution scenario (docs/"
                                    "layout-balance.md): uniform "
                                    "(default — hash-scattered, "
                                    "metric string unchanged), "
                                    "zipf:<a> (zipf-skewed slice "
                                    "popularity at exponent a, e.g. "
                                    "zipf:1.5), powerlaw (power-law "
                                    "mode sizes), amazon-like (scaled "
                                    "review-tensor shape preset), "
                                    "densemode (one near-dense mode, "
                                    "docs/dense.md — adds the hybrid "
                                    "dense-tile path row and the "
                                    "flops/roofline-verdict fields), "
                                    "batched (docs/batched.md), "
                                    "predict (docs/predict.md), or "
                                    "ingest (docs/ingest.md: "
                                    "streaming-ingest records/sec + "
                                    "update-lag p95).  "
                                    "Non-uniform scenarios tag the "
                                    "metric string so the regression "
                                    "gate only compares like "
                                    "workloads, and the JSON carries "
                                    "per-scenario imbalance stats"),
    "SPLATT_BENCH_BATCH_K": EnvVar(32, "bench.py batched scenario "
                                   "(SPLATT_BENCH_SCENARIO=batched, "
                                   "docs/batched.md): how many small "
                                   "same-regime tensors the "
                                   "batched-vs-sequential A/B stacks"),
    "SPLATT_BENCH_GUARD_AB": EnvVar(None, "bench.py: 1 = run the "
                                    "guard-cost A/B legs (ROADMAP "
                                    "open item 1): cpd_als timed with "
                                    "SPLATT_HEALTH_RETRIES on/off x "
                                    "donation on/off, recorded under "
                                    "guard_ab in the bench JSON so "
                                    "the gate can see guard overhead "
                                    "explicitly"),
    "SPLATT_BENCH_DEVICES": EnvVar(None, "bench.py: comma-separated "
                                   "device counts for the scaling "
                                   "sweep"),
    "SPLATT_SCALING_CHILD": EnvVar(None, "bench.py internal: marks a "
                                   "scaling-sweep child process"),
    # -- streaming ingest (splatt_tpu/ingest.py, docs/ingest.md) --
    "SPLATT_INGEST_CHUNK": EnvVar(5000, "ingest.py: records per "
                                  "chunk commit — the exactly-once "
                                  "watermark grain (docs/ingest.md); "
                                  "a resume must reuse the journal's "
                                  "value or ingest refuses"),
    "SPLATT_INGEST_INFLIGHT": EnvVar(4, "ingest.py: bounded reader-"
                                     "to-committer queue depth — the "
                                     "backpressure knob; the reader "
                                     "blocks rather than buffering "
                                     "the stream unboundedly"),
    "SPLATT_INGEST_QUARANTINE_MAX": EnvVar(1000, "ingest.py: absolute "
                                           "quarantined-record budget "
                                           "per run; past it the run "
                                           "DEGRADES classified "
                                           "(ingest_degraded) instead "
                                           "of shipping a corrupt "
                                           "tensor; 0 disables the "
                                           "count half of the budget"),
    "SPLATT_INGEST_QUARANTINE_RATE": EnvVar(0.5, "ingest.py: max "
                                            "quarantined/parsed ratio "
                                            "(evaluated once >= 200 "
                                            "records seen) before the "
                                            "run degrades classified; "
                                            "0 disables the rate half "
                                            "of the budget"),
    "SPLATT_INGEST_UPDATE_EVERY": EnvVar(1, "serve.py ingest job "
                                         "kind: emit one update job "
                                         "per this many committed "
                                         "chunks (the watermark "
                                         "interval of the live-feed "
                                         "lane, docs/ingest.md)"),
}


def read_env(name: str) -> Optional[object]:
    """Read a declared environment variable: the process value when
    set, the registered default otherwise.  Unregistered names raise —
    an undeclared variable is exactly the drift SPL007 exists to stop,
    so the runtime accessor enforces the same contract loudly."""
    spec = ENV_VARS.get(name)
    if spec is None:
        raise KeyError(
            f"environment variable {name!r} is not declared in "
            f"splatt_tpu.utils.env.ENV_VARS; register it (with a doc "
            f"string) before reading it")
    raw = os.environ.get(name)
    return spec.default if raw is None else raw


def _read_env_parsed(name: str, parse, kind: str):
    """Shared warn-and-default parse: a malformed value degrades to
    the registered default with one stderr line instead of killing the
    process at some random read site."""
    val = read_env(name)
    if isinstance(val, str):
        try:
            return parse(val)
        except (TypeError, ValueError):
            print(f"splatt-tpu: bad {name}={val!r} (want {kind}); "
                  f"using the default", file=sys.stderr)
            return ENV_VARS[name].default
    return val


def env_is_set(name: str) -> bool:
    """Whether the PROCESS environment explicitly sets a declared
    variable (as opposed to the registered default applying).  The
    autotuner uses this to tell a pinned format knob (measure only
    that) from an untouched default (measure the candidate matrix)."""
    if name not in ENV_VARS:
        raise KeyError(
            f"environment variable {name!r} is not declared in "
            f"splatt_tpu.utils.env.ENV_VARS")
    return name in os.environ


def read_env_int(name: str) -> Optional[int]:
    """:func:`read_env` + int parse (warn-and-default on bad values)."""
    return _read_env_parsed(name, int, "an int")


def read_env_float(name: str) -> Optional[float]:
    """:func:`read_env` + float parse (warn-and-default on bad values)."""
    return _read_env_parsed(name, float, "a float")


def ceil_to(x: int, mult: int) -> int:
    """Round x up to a multiple of mult."""
    return ((x + mult - 1) // mult) * mult


def max_mean_ratio(a) -> float:
    """round(max/mean, 3) of a nonnegative weight array — THE imbalance
    convention every layout/shard balance stat reports
    (docs/layout-balance.md); 1.0 means perfectly balanced (or empty).
    One definition so the slice/block/span/shard numbers in the run
    report, ``splatt cpd --json``, bench and MULTICHIP never drift."""
    import numpy as np

    a = np.asarray(a)
    mean = float(a.mean()) if a.size else 0.0
    return round(float(a.max()) / mean, 3) if mean > 0 else 1.0


def check_int32_dims(dims) -> None:
    """Device indices are int32 (≙ the reference's compile-time
    splatt_idx_t choice, include/splatt/types_config.h:38-43), and the
    blocked layouts use `dim` itself as the padding sentinel — so every
    dim must fit strictly below INT32_MAX.  Called by each path that
    casts host int64 coordinates down (layout build, nnz sharding,
    bucket scatter) so overflow fails loudly instead of wrapping.
    """
    limit = 2**31 - 1
    if max(dims, default=0) >= limit:
        raise ValueError(
            f"dims {tuple(dims)} exceed the int32 device index width "
            f"(max dim must be < {limit}); relabel/split the mode first")


def shard_map(f, **kwargs):
    """Version-portable `jax.shard_map` (resilience to jax API drift).

    Newer jax exposes ``jax.shard_map`` with a ``check_vma`` kwarg;
    older releases only have ``jax.experimental.shard_map.shard_map``
    with the same contract under ``check_rep``.  One hard
    ``from jax import shard_map`` at import time used to take down the
    whole :mod:`splatt_tpu.parallel` package — and with it every
    blocked-layout build — on an older jax; resolving lazily here keeps
    the distributed stack importable everywhere and fails only if a
    sweep actually runs on a jax with neither API.
    """
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm

        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return sm(f, **kwargs)


def host_fence(x):
    """Force true device completion of `x` and everything it depends on.

    block_until_ready alone is not enough on tunneled/relayed devices
    (e.g. the axon TPU relay), which can ack readiness before execution
    finishes — a one-element host fetch is a true data-dependency fence.
    Every leaf is fetched: under the phased sweep the leaves are produced
    by separate device programs, so fencing only the first would leave
    the later phases un-covered.  Returns `x` for chaining.
    """
    import jax

    jax.block_until_ready(x)
    for leaf in jax.tree_util.tree_leaves(x):
        if not hasattr(leaf, "ravel") or getattr(leaf, "size", 0) == 0:
            continue
        jax.device_get(leaf.ravel()[0])
    return x


def apply_compile_cache() -> None:
    """Point JAX's persistent compilation cache at SPLATT_COMPILE_CACHE.

    Call before any backend initializes (next to
    :func:`apply_env_platform`).  When the knob names a directory,
    every process applying it shares one on-disk store of serialized
    XLA executables keyed by HLO + topology — a fleet replica (or a
    restarted one) whose first job matches a shape some peer already
    compiled loads the executable instead of recompiling.  The floors
    are pinned to zero because a serve fleet's steady state is many
    small same-regime compiles: exactly the entries the default
    min-compile-time floor would refuse to persist.

    Unset = no-op.  Enable failures (read-only path, an older jax
    without the config) degrade classified: the run just compiles.

    CAUTION (current jaxlib, CPU): executing a deserialized
    MULTI-DEVICE sharded CPU executable corrupts the process heap
    (malloc abort inside pxla) — measured, not theoretical.
    Single-device executables round-trip fine.  On CPU hosts, set the
    knob only for processes that run single-device programs (the serve
    fleet's replica daemons — the production shape); leave it unset
    for anything driving the 8-virtual-device sharded paths.
    """
    path = read_env("SPLATT_COMPILE_CACHE")
    if not path:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", str(path))
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as e:
        from splatt_tpu import resilience

        cls = resilience.classify_failure(e)
        resilience.run_report().add(
            "compile_cache_error", path=str(path),
            failure_class=cls.value,
            error=resilience.failure_message(e)[:200])
        print(f"splatt-tpu: WARNING: could not enable the persistent "
              f"compile cache at {path} ({cls.value}: {e}); compiles "
              f"will not be cached", file=sys.stderr)


def apply_env_platform() -> None:
    """Mirror JAX_PLATFORMS into jax.config.

    Some images install a site plugin (e.g. a TPU relay) that selects
    platforms programmatically at interpreter startup, which overrides
    the JAX_PLATFORMS env var.  Calling this before any backend
    initializes makes the env var authoritative again.
    """
    platforms = read_env("JAX_PLATFORMS")
    if platforms:
        import jax

        try:
            jax.config.update("jax_platforms", platforms)
        except Exception as e:
            # Losing the platform pin silently was the PR 1 bug class:
            # the run continues (jax may still honor the env var on its
            # own), but the failure is classified and reported so a
            # CPU-pinned test run that lands on the TPU is explainable.
            from splatt_tpu import resilience

            cls = resilience.classify_failure(e)
            resilience.run_report().add(
                "env_platform_error", platforms=platforms,
                failure_class=cls.value,
                error=resilience.failure_message(e)[:200])
            print(f"splatt-tpu: WARNING: could not mirror "
                  f"JAX_PLATFORMS={platforms} into jax.config "
                  f"({cls.value}: {e}); the env var may still apply",
                  file=sys.stderr)
