"""Host-side COO sparse tensor (≙ sptensor_t, src/sptensor.h:27-40).

The COO tensor is the mutable, host-resident representation used for IO,
preprocessing and analysis; device compute happens on the compiled
:class:`splatt_tpu.blocked.BlockedSparse` format.  Arrays are numpy:
``inds`` is an ``(nmodes, nnz)`` int64 array, ``vals`` a float64 vector.

Capability parity with the reference:
- dedup with value accumulation     (≙ tt_remove_dups,  src/sptensor.h:156-167)
- empty-slice removal + indmap      (≙ tt_remove_empty, src/sptensor.h:170-180)
- mode unfold to CSR                (≙ tt_unfold,       src/sptensor.h:183-196)
- squared Frobenius norm            (≙ tt_normsq,       src/sptensor.h:199-209)
- per-mode histograms / slice counts
- lexicographic sort by any mode order (≙ tt_sort, src/sort.c:912-961 — on
  TPU hosts this is a numpy lexsort; the reference's hybrid counting sort
  exists because it hand-rolls parallelism that numpy/XLA already provide)
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from splatt_tpu.config import MAX_NMODES


@dataclasses.dataclass
class SparseTensor:
    """m-mode coordinate sparse tensor.

    Attributes:
      inds: (nmodes, nnz) int64 coordinates, 0-indexed.
      vals: (nnz,) float64 values.
      dims: tuple of mode sizes.
      indmaps: optional per-mode local->global index maps produced by
        :meth:`remove_empty_slices` (≙ sptensor_t.indmap).
    """

    inds: np.ndarray
    vals: np.ndarray
    dims: Tuple[int, ...]
    indmaps: Optional[List[Optional[np.ndarray]]] = None

    def __post_init__(self) -> None:
        # int32 is preserved (memmap-backed huge tensors); anything else
        # integer-like normalizes to int64.  ascontiguousarray is a
        # no-op (no copy) for already-contiguous arrays and memmaps.
        self.inds = np.ascontiguousarray(self.inds)
        if self.inds.dtype not in (np.dtype(np.int32), np.dtype(np.int64)):
            self.inds = self.inds.astype(np.int64)
        self.vals = np.ascontiguousarray(self.vals)
        if self.inds.ndim != 2:
            raise ValueError("inds must be (nmodes, nnz)")
        if self.nmodes > MAX_NMODES:
            raise ValueError(f"nmodes {self.nmodes} exceeds MAX_NMODES={MAX_NMODES}")
        if self.inds.shape[1] != self.vals.shape[0]:
            raise ValueError("inds/vals nnz mismatch")
        self.dims = tuple(int(d) for d in self.dims)

    # -- basic queries ----------------------------------------------------

    @property
    def nmodes(self) -> int:
        return self.inds.shape[0]

    @property
    def nnz(self) -> int:
        return self.inds.shape[1]

    def density(self) -> float:
        dense = 1.0
        for d in self.dims:
            dense *= float(d)
        return self.nnz / dense if dense > 0 else 0.0

    def normsq(self) -> float:
        """Squared Frobenius norm (≙ tt_normsq)."""
        return float(np.dot(self.vals, self.vals))

    def mode_histogram(self, mode: int) -> np.ndarray:
        """nnz count per slice of `mode` (≙ tt_get_hist)."""
        return np.bincount(self.inds[mode], minlength=self.dims[mode])

    def nslices_nonempty(self, mode: int) -> int:
        return int(np.count_nonzero(self.mode_histogram(mode)))

    # -- constructors -----------------------------------------------------

    @staticmethod
    def from_arrays(inds: Sequence[np.ndarray], vals: np.ndarray,
                    dims: Optional[Sequence[int]] = None) -> "SparseTensor":
        ind = np.stack([np.asarray(i, dtype=np.int64) for i in inds])
        if dims is None:
            dims = [int(ind[m].max()) + 1 if ind.shape[1] else 0
                    for m in range(ind.shape[0])]
        return SparseTensor(ind, np.asarray(vals), tuple(dims))

    @staticmethod
    def random(dims: Sequence[int], nnz: int, seed: int = 0,
               distinct: bool = True) -> "SparseTensor":
        """Uniform random tensor for tests/benchmarks (deterministic)."""
        rng = np.random.default_rng(seed)
        ind = np.stack([rng.integers(0, d, size=nnz) for d in dims])
        vals = rng.random(nnz)
        tt = SparseTensor(ind, vals, tuple(int(d) for d in dims))
        if distinct:
            tt = tt.deduplicate()
        return tt

    # -- transforms -------------------------------------------------------

    def sort_order(self, mode_order: Sequence[int]) -> np.ndarray:
        """Permutation sorting nnz lexicographically by `mode_order`.

        ≙ tt_sort (src/sort.c:912-961); `mode_order[0]` is the primary
        key.  Uses the native bucket+sort when the extension is built
        (both are stable, so results are identical), else np.lexsort.
        """
        order = list(mode_order)
        from splatt_tpu import native

        perm = native.sort_perm(self.inds, self.dims, order)
        if perm is not None:
            return perm
        # np.lexsort sorts by the LAST key first.
        keys = tuple(self.inds[m] for m in reversed(order))
        return np.lexsort(keys)

    def sorted_by(self, mode_order: Sequence[int]) -> "SparseTensor":
        perm = self.sort_order(mode_order)
        return SparseTensor(self.inds[:, perm], self.vals[perm], self.dims,
                            indmaps=self.indmaps)

    def deduplicate(self) -> "SparseTensor":
        """Sum values at repeated coordinates (≙ tt_remove_dups)."""
        if self.nnz == 0:
            return self
        perm = self.sort_order(range(self.nmodes))
        ind = self.inds[:, perm]
        vals = self.vals[perm]
        new = np.empty(self.nnz, dtype=bool)
        new[0] = True
        np.any(ind[:, 1:] != ind[:, :-1], axis=0, out=new[1:])
        starts = np.flatnonzero(new)
        summed = np.add.reduceat(vals, starts)
        return SparseTensor(ind[:, starts], summed, self.dims,
                            indmaps=self.indmaps)

    def count_duplicates(self) -> int:
        if self.nnz == 0:
            return 0
        perm = self.sort_order(range(self.nmodes))
        ind = self.inds[:, perm]
        same = np.all(ind[:, 1:] == ind[:, :-1], axis=0)
        return int(np.count_nonzero(same))

    def remove_empty_slices(self) -> "SparseTensor":
        """Relabel each mode to remove empty slices (≙ tt_remove_empty).

        Records per-mode ``indmap`` (local -> global index) for modes that
        shrank; identity modes keep ``None`` like the reference.
        """
        new_inds = np.empty_like(self.inds)
        indmaps: List[Optional[np.ndarray]] = []
        dims: List[int] = []
        for m in range(self.nmodes):
            uniq, inv = np.unique(self.inds[m], return_inverse=True)
            if uniq.shape[0] == self.dims[m]:
                new_inds[m] = self.inds[m]
                indmaps.append(None)
                dims.append(self.dims[m])
            else:
                new_inds[m] = inv
                indmaps.append(uniq.copy())
                dims.append(int(uniq.shape[0]))
        if all(im is None for im in indmaps):
            return self
        return SparseTensor(new_inds, self.vals.copy(), tuple(dims),
                            indmaps=indmaps)

    def unfold(self, mode: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Tuple[int, int]]:
        """Mode-`mode` matricization as CSR (≙ tt_unfold, src/sptensor.h:183-196).

        Returns (indptr, indices, data, shape) with rows = dims[mode] and
        columns = product of the other dims in increasing-mode order.
        """
        rows = self.inds[mode]
        other = [m for m in range(self.nmodes) if m != mode]
        col = np.zeros(self.nnz, dtype=np.int64)
        stride = 1
        # row-major over the remaining modes, last mode fastest;
        # int64 accumulation — int32 inds (memmap path) would wrap under
        # NEP 50 once the column space exceeds 2^31
        for m in reversed(other):
            col += self.inds[m].astype(np.int64) * stride
            stride *= self.dims[m]
        ncols = stride
        order = np.lexsort((col, rows))
        r, c, v = rows[order], col[order], self.vals[order]
        indptr = np.zeros(self.dims[mode] + 1, dtype=np.int64)
        np.add.at(indptr, r + 1, 1)
        np.cumsum(indptr, out=indptr)
        return indptr, c, v, (self.dims[mode], int(ncols))

    def permute(self, perms: Sequence[Optional[np.ndarray]]) -> "SparseTensor":
        """Apply per-mode relabeling permutations (≙ perm_apply, src/reorder.c:350).

        ``perms[m]`` maps old index -> new index for mode m (None = identity).
        """
        new_inds = self.inds.copy()
        for m, p in enumerate(perms):
            if p is not None:
                new_inds[m] = np.asarray(p, dtype=np.int64)[self.inds[m]]
        return SparseTensor(new_inds, self.vals.copy(), self.dims,
                            indmaps=self.indmaps)

    def copy(self) -> "SparseTensor":
        return SparseTensor(self.inds.copy(), self.vals.copy(), self.dims,
                            indmaps=None if self.indmaps is None else
                            [None if m is None else m.copy() for m in self.indmaps])

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, SparseTensor):
            return NotImplemented
        return (self.dims == other.dims
                and np.array_equal(self.inds, other.inds)
                and np.array_equal(self.vals, other.vals))

    def to_dense(self) -> np.ndarray:
        """Dense ndarray — tests/small tensors only."""
        out = np.zeros(self.dims, dtype=self.vals.dtype)
        np.add.at(out, tuple(self.inds), self.vals)
        return out
