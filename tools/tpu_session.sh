#!/bin/bash
# One TPU work session, ordered by value-per-minute, each step its own
# process (single client at a time — the axon relay serializes claims
# and a killed client can wedge the lease; timeouts are generous and
# SIGTERM-only).  Run: nohup bash tools/tpu_session.sh > tools/tpu_session.out 2>&1 &
cd "$(dirname "$0")/.."
set -u
note() { echo "=== $1 $(date -u +%H:%M:%S) ==="; }

note "stage A: staged probe (claim/transfer/single-kernel health)"
timeout 1800 python -u tools/stage_probe.py claim small xfer one_mttkrp
rc=$?
echo "stage A rc=$rc"
if [ $rc -ne 0 ]; then
  echo "chip unhealthy; aborting session"
  exit 1
fi

note "stage B: bench.py (the flagship number; phased jit, auto engine)"
timeout 2400 python -u bench.py > BENCH_TPU_CAND.json
echo "stage B rc=$?"
cat BENCH_TPU_CAND.json

note "stage C: mosaic op-level bisect + unfused HW validation"
timeout 2400 python -u tools/mosaic_bisect.py
echo "stage C rc=$?"

note "stage C2: kernel head-to-head (stream/blocked/fused_t/fused_tg)"
timeout 2400 python -u tools/tpu_kernel_bench.py
echo "stage C2 rc=$?"

note "stage D: tuning sweep (paths x engines x dtypes x blocks)"
timeout 3600 python -u tools/tpu_tune.py
echo "stage D rc=$?"

# blocked only: the stream oracle at rank 200 costs ~20 min of window
# for a 30x-slower number
note "stage E: rank-200 bench row (blocked only)"
SPLATT_BENCH_RANK=200 SPLATT_BENCH_ITERS=2 SPLATT_BENCH_PATHS=blocked \
  timeout 2400 python -u bench.py > BENCH_TPU_R200.json
echo "stage E rc=$?"
cat BENCH_TPU_R200.json

note "stage F: 4-mode Enron-shaped bench row"
SPLATT_BENCH_SHAPE=enron4 SPLATT_BENCH_NNZ=5000000 SPLATT_BENCH_RANK=25 \
  timeout 2400 python -u bench.py > BENCH_TPU_ENRON4.json
echo "stage F rc=$?"
cat BENCH_TPU_ENRON4.json

note "stage G: bf16 bench row (bf16 storage, f32 accumulation)"
SPLATT_BENCH_DTYPE=bfloat16 SPLATT_BENCH_PATHS=blocked \
  timeout 2400 python -u bench.py > BENCH_TPU_BF16.json
echo "stage G rc=$?"
cat BENCH_TPU_BF16.json

note "session done"
