"""Isolate which op inside the fused_t kernel crashes the Mosaic
compiler subprocess (tools/fused_bisect.py: every block-4096 case dies
with HTTP 500 while the tiny block-128 probe compiles), and validate the
*unfused* kernels at production block sizes on real hardware.

Each case compiles (and runs) one variant kernel in a subprocess with a
hard timeout.  Variants strip the fused_t kernel down op by op:

  k_dot      — one-hot build + MXU dot only (no gather)
  k_gather1  — a single (8, D) take_along_axis gather, no dot
  k_gatherN  — the full _tile_gather loop (R8/8 tiles), no dot
  k_concat   — _tile_gather minus take_along_axis: the sublane/lane
               concatenates alone (gathered tiles replaced by slices)
  k_full     — the real fused_t kernel
  k_tg       — the sublane-tiled fused_mttkrp_tg kernel (r4 variant:
               one gather per factor×chunk, scratch stores, no concat)
  u_sorted   — onehot_reduce_sorted (unfused) at block 4096
  u_full     — onehot_reduce_full (unfused, privatized width)

Cases with a `_nell` suffix run at NELL-2-like dims (12092, 9184,
28818) instead of the (512, 384, 1024) probe dims — the two regimes
differ in lane-chunk count (ck≈15 vs ck=1) and gather width (≤1024 vs
28928), which separates "too many unrolled gathers" from "gather too
wide" as crash causes.

Writes tools/mosaic_bisect.json.
"""
from __future__ import annotations

import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)


def build(case: str):
    from splatt_tpu.utils.env import apply_env_platform

    apply_env_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    from splatt_tpu.blocked import build_layout
    from splatt_tpu.coo import SparseTensor
    from splatt_tpu.ops import pallas_kernels as pk
    from splatt_tpu.ops.mttkrp import mxu_precision

    rng = np.random.default_rng(0)
    if case.endswith("_nell"):
        case = case[:-len("_nell")]
        dims = (12092, 9184, 28818)
        nnz = 500_000
    else:
        dims = (512, 384, 1024)
        nnz = 8192
    B = 4096
    R = 48
    R8 = 48
    inds = np.stack([rng.integers(0, d, nnz) for d in dims]).astype(np.int64)
    tt = SparseTensor(inds=inds, vals=rng.random(nnz), dims=dims)
    lay = build_layout(tt, 0, block=B, val_dtype=np.float32)
    fac = [jnp.asarray(rng.random((d, R)), jnp.float32) for d in dims]
    width = lay.seg_width
    nb = lay.nblocks

    if case == "k_full":
        out = pk.fused_mttkrp_t(lay, fac, mode=0, width=width,
                                accumulate=False, interpret=False)
        out.block_until_ready()
        return dict(shape=list(out.shape))

    if case == "k_tg":
        out = pk.fused_mttkrp_tg(lay, fac, mode=0, width=width,
                                 accumulate=False, interpret=False)
        out.block_until_ready()
        return dict(shape=list(out.shape))

    if case in ("u_sorted", "u_full"):
        from splatt_tpu.ops.mttkrp import _gather_prod

        prod = _gather_prod(lay.inds, lay.vals, fac, 0).reshape(nb, B, R)
        if case == "u_sorted":
            local = (lay.inds[0].reshape(nb, B)
                     - lay.row_start[:, None]).astype(jnp.int32)
            chunk = pk.vmem_chunk(width, B, R, 4)
            out = pk.onehot_reduce_sorted(local, prod, width,
                                          interpret=False, chunk=max(chunk, 1))
        else:
            local = lay.inds[0].reshape(nb, B).astype(jnp.int32)
            w = -(-(dims[0] + 1) // 8) * 8
            chunk = pk.vmem_chunk(w, B, R, 4)
            out = pk.onehot_reduce_full(local, prod, w,
                                        interpret=False, chunk=max(chunk, 1))
        out.block_until_ready()
        return dict(shape=list(out.shape))

    # hand-stripped kernel variants with the real kernels' operands
    local, vals, uts, gidxs = pk._prep_t_operands(lay, fac, 0,
                                                  accumulate=False)
    d_pads = [u.shape[1] for u in uts]

    if case == "k_dot":
        def kern(local_ref, vals_ref, out_ref):
            local = local_ref[0, :, :]
            vals = vals_ref[0, :, :]
            iota = jax.lax.broadcasted_iota(jnp.int32, (width, B), 0)
            onehot = (jnp.broadcast_to(local, (width, B)) == iota
                      ).astype(jnp.float32)
            prod = jnp.broadcast_to(vals, (R8, B))
            out_ref[...] = jax.lax.dot_general(
                prod, onehot, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=mxu_precision(jnp.float32))[None]

        out = pl.pallas_call(
            kern, grid=(nb,),
            in_specs=[pl.BlockSpec((1, 1, B), lambda i: (i, 0, 0)),
                      pl.BlockSpec((1, 1, B), lambda i: (i, 0, 0))],
            out_specs=pl.BlockSpec((1, R8, width), lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((nb, R8, width), jnp.float32),
            compiler_params=pk._compiler_params(),
        )(local, vals)
        out.block_until_ready()
        return dict(shape=list(out.shape))

    if case in ("k_gather1", "k_gatherN"):
        d_pad = d_pads[0]
        ck = gidxs[0].shape[1]

        def kern(gidx_ref, ut_ref, out_ref):
            u_t = ut_ref[...]
            if case == "k_gather1":
                rows = jnp.take_along_axis(u_t[:8, :], gidx_ref[0, 0],
                                           axis=1)
                out_ref[...] = jnp.sum(rows).reshape(1, 1)
            else:
                rows = pk._tile_gather(u_t, gidx_ref[0], B)
                out_ref[...] = jnp.sum(rows).reshape(1, 1)

        out = pl.pallas_call(
            kern, grid=(nb,),
            in_specs=[pl.BlockSpec((1, ck, 8, d_pad), lambda i: (i, 0, 0, 0)),
                      pl.BlockSpec((R8, d_pad), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
            compiler_params=pk._compiler_params(),
        )(gidxs[0], uts[0])
        out.block_until_ready()
        return dict(shape=list(out.shape))

    if case == "k_concat":
        # the concatenates of _tile_gather without any gather: same
        # tile shapes, tiles produced by aligned slices of the table
        d_pad = d_pads[0]
        ck = gidxs[0].shape[1]

        def kern(ut_ref, out_ref):
            u_t = ut_ref[...]
            pieces = []
            for c in range(ck):
                tiles = [u_t[r0:r0 + 8, :] * (c + 1.0)
                         for r0 in range(0, R8, 8)]
                pieces.append(tiles[0] if len(tiles) == 1
                              else jnp.concatenate(tiles, axis=0))
            rows = (pieces[0] if ck == 1
                    else jnp.concatenate(pieces, axis=1))[:, :B]
            out_ref[...] = jnp.sum(rows).reshape(1, 1)

        out = pl.pallas_call(
            kern, grid=(nb,),
            in_specs=[pl.BlockSpec((R8, d_pad), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
            compiler_params=pk._compiler_params(),
        )(uts[0])
        out.block_until_ready()
        return dict(shape=list(out.shape))

    raise ValueError(case)


CASES = ["k_dot", "k_gather1", "k_gatherN", "k_concat", "k_full", "k_tg",
         "u_sorted", "u_full",
         "k_gather1_nell", "k_full_nell", "k_tg_nell", "u_sorted_nell"]


def main():
    from case_runner import run_cases, run_child

    if len(sys.argv) > 1:
        run_child(build, sys.argv[1])
        return

    run_cases(os.path.abspath(__file__), CASES,
              os.path.join(HERE, "mosaic_bisect.json"), case_arg=str)


if __name__ == "__main__":
    main()
