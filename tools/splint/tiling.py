"""splint v5 (part 2): TPU tiling + plan-schema rules (SPL025–SPL027).

Mosaic's layout rules are unforgiving and invisible from Python: the
last two dims of every block must divide the dtype's native
(sublane, lane) packing — (8, 128) for 4-byte types, (16, 128) for
bf16/f16 — or equal the array dims exactly; every block buffer lives
in ~16 MiB of VMEM, with grid-streamed operands double-buffered; and
the plan cache silently mis-dispatches the moment a regime-key
component or a ``TunedPlan`` field stops being compared.  Each of
these failed at runtime at least once before these rules existed.

SPL025 tile alignment
    Every ``pl.BlockSpec`` / ``pltpu.VMEM`` block tuple in the
    ``pallas-modules`` scope has its last-two dims judged:

    * int literal / module-const int → must divide or be a multiple
      of the position's unit (8 sublane, 128 lane);
    * a name this function PADDED (assigned from ``align-helpers``
      (``ceil_to``/``_pad_blocks``) or ``tile-pack-helpers``
      (``_rank_pad``/``tile_packing``)) → the pad unit must certify
      the position — lane: multiple of 128; sublane: a tile-pack
      helper or a multiple of 16 (bf16-safe).  A dtype-blind
      ``ceil_to(R, 8)`` fires: it under-pads 2-byte storage.  This
      class is judged FIRST — such a name also matching the out-shape
      is a circular certificate (the array is that size only because
      this very computation padded it);
    * a name the function merely READS (``.shape``-derived, attribute
      extent like ``layout.block``, ``len(...)``, or appearing in the
      call's ``ShapeDtypeStruct``/``reshape`` shapes) → trusted: the
      block equals a materialized array dim (Mosaic's equal-dims
      escape);
    * anything else (arithmetic, unknown calls) → finding.

    Grid completeness: any ``//`` inside a ``grid=`` expression (or
    the local def of its elements) must have a numerator that was
    padded via the align/tile-pack helpers — ``nb // chunk`` over an
    unpadded extent silently drops the ragged tail block.

SPL026 static VMEM budget
    Per ``pallas_call``: sum of block-buffer bytes — every in/out
    spec and scratch shape, dims resolved through literals,
    module consts, and the declared dispatch envelope
    (``vmem-dim-caps``, entries ``"text=int"`` matched on the
    unparsed dim/spec expression; ``"*name=int"`` caps a starred
    spec-list's multiplicity) — at 4 B/elem (accumulator width,
    conservative for narrow storage), ×2 for specs whose index_map
    actually uses a grid axis (Pallas double-buffers streamed
    operands).  The sum must fit the kernel's budget
    (``vmem-kernel-budgets`` ``"fn=MiB"``, else ``vmem-budget-mib``).
    A tile-size bump that cannot fit now fails CI instead of a
    runtime Mosaic error.  An unresolvable dim is itself a finding —
    a budget splint cannot evaluate is not a budget.

    Gate registry, both directions: every function issuing a
    ``pallas_call`` must appear in ``vmem-gate-map`` (``"fn=gate"``),
    its gate must exist in the same module, and the gate must be
    consulted somewhere outside its own def — an ungated kernel or an
    orphaned gate is exactly how the fused_t double-buffer
    undercount shipped.

SPL027 plan-cache schema completeness
    Any module assigning ``PLAN_CACHE_VERSION`` must declare
    ``PLAN_SCHEMA`` (version / key / fields / match / exempt) and the
    code must agree with it in BOTH directions: ``plan_key`` params ==
    schema key (each actually folded into the key); ``TunedPlan``
    annotated fields == schema fields; match ∪ exempt == fields,
    disjoint; every ``plan-match-functions`` dispatch comparator
    compares at least the match set and only declared fields;
    ``PLAN_SCHEMA['version'] == PLAN_CACHE_VERSION``; and the module
    carries a ``v<n>:`` history marker for every version 2..N (the
    bump discipline).  Growing ``TunedPlan`` or ``plan_key`` without
    updating the schema — the silent mis-dispatch drift class — now
    fails statically.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.splint.core import (FileCtx, Finding, Project, walk_nodes)

_SUBLANE_UNIT = 8
_LANE_UNIT = 128
_NARROW_SUBLANE = 16   # bf16/f16 packing — the dtype-safe pad unit


def _dedupe(findings: List[Finding]) -> List[Finding]:
    seen, out = set(), []
    for f in findings:
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


def _functions(tree: ast.AST):
    for node in walk_nodes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _last_seg(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1]


def _in_scope(relpath: str, entries: List[str]) -> bool:
    for e in entries:
        e = e.rstrip("/")
        if relpath == e or relpath.startswith(e + "/"):
            return True
    return False


def _pairs(entries: List[str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for e in entries:
        k, _, v = e.partition("=")
        out[k.strip()] = v.strip()
    return out


def _module_int_consts(tree: ast.AST) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in ast.iter_child_nodes(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            out[node.targets[0].id] = node.value.value
    return out


def _contains_shape(expr: ast.AST) -> bool:
    for n in walk_nodes(expr):
        if isinstance(n, ast.Attribute) and n.attr == "shape":
            return True
        if isinstance(n, ast.Call):
            dotted = None
            if isinstance(n.func, ast.Name):
                dotted = n.func.id
            if dotted == "len":
                return True
    return False


class _FnShapes:
    """Per-function classification of the names block dims may use."""

    def __init__(self, ctx: FileCtx, fn: ast.AST, align: List[str],
                 pack: List[str], consts: Dict[str, int]):
        self.helpers = set(align) | set(pack)
        self.consts = dict(consts)
        #: name → unit expr of its ceil_to-style pad (None = unknown)
        self.ceil: Dict[str, Optional[ast.expr]] = {}
        #: names padded through a dtype-aware tile-pack helper
        self.packed: set = set()
        #: names the function merely reads off existing arrays/layouts
        self.extent: set = set()
        for st in walk_nodes(fn):
            if not isinstance(st, ast.Assign):
                continue
            for tgt, val in self._bindings(st):
                if not isinstance(tgt, ast.Name):
                    continue
                name = tgt.id
                if isinstance(val, ast.Constant) and isinstance(
                        val.value, int):
                    self.consts[name] = val.value
                    continue
                helper = ""
                if isinstance(val, ast.Call):
                    dotted = ctx.resolve(val.func) or ""
                    helper = _last_seg(dotted) if dotted else ""
                if helper in pack:
                    self.packed.add(name)
                elif helper in align:
                    self.ceil[name] = (val.args[1]
                                       if len(val.args) > 1 else None)
                elif isinstance(val, ast.BinOp) and isinstance(
                        val.op, ast.FloorDiv):
                    # n = padded // unit keeps the numerator's class
                    if (isinstance(val.left, ast.Name)
                            and (val.left.id in self.ceil
                                 or val.left.id in self.packed)):
                        self.ceil[name] = None
                elif isinstance(val, ast.Attribute):
                    self.extent.add(name)
                elif _contains_shape(val):
                    self.extent.add(name)
        # names appearing inside shape-tuple positions of
        # reshape/ShapeDtypeStruct/pad/broadcast_to calls: the block
        # dim provably equals a materialized array dim
        for call in (n for n in walk_nodes(fn) if isinstance(n, ast.Call)):
            dotted = ctx.resolve(call.func) or ""
            last = _last_seg(dotted) if dotted else (
                call.func.attr if isinstance(call.func, ast.Attribute)
                else "")
            if last not in ("reshape", "ShapeDtypeStruct", "pad",
                            "broadcast_to", "zeros", "full", "empty"):
                continue
            for a in call.args:
                for n in walk_nodes(a):
                    if isinstance(n, ast.Name):
                        self.extent.add(n.id)
        # a padded name is never a trusted extent: the out array is
        # that size only because this function padded it (circular)
        self.extent -= set(self.ceil) | set(self.packed)

    @staticmethod
    def _bindings(st: ast.Assign):
        for tgt in st.targets:
            if isinstance(tgt, ast.Name):
                yield tgt, st.value
            elif isinstance(tgt, ast.Tuple):
                if (isinstance(st.value, ast.Tuple)
                        and len(st.value.elts) == len(tgt.elts)):
                    yield from zip(tgt.elts, st.value.elts)
                else:
                    for e in tgt.elts:
                        yield e, st.value


class _TilingRule:
    id = "SPL0xx"
    title = ""
    hint = ""

    def finding(self, ctx_or_path, line: int, message: str) -> Finding:
        path = (ctx_or_path.relpath if isinstance(ctx_or_path, FileCtx)
                else ctx_or_path)
        return Finding(self.id, path, line, f"{self.title}: {message}",
                       hint=self.hint)

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        return []

    def finalize(self, project: Project) -> List[Finding]:
        return []


def _block_calls(ctx: FileCtx, fn: ast.AST):
    """Yield (call, kind) for BlockSpec / pltpu.VMEM constructors."""
    for node in walk_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        dotted = ctx.resolve(node.func) or ""
        last = _last_seg(dotted) if dotted else ""
        if last == "BlockSpec":
            yield node, "BlockSpec"
        elif last == "VMEM":
            yield node, "VMEM"


class TileAlignment(_TilingRule):
    """SPL025: block last-two dims must respect the dtype's native
    (sublane, lane) packing."""

    id = "SPL025"
    title = "tile-alignment hazard"
    hint = ("pad the sublane dim through config.tile_packing / "
            "_rank_pad (dtype-aware: 8 f32, 16 bf16) and lane dims to "
            "multiples of 128; block dims equal to the materialized "
            "array extent are fine.  If Mosaic provably accepts this "
            "shape, add `# splint: ignore[SPL025] <reason>`")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        conf = project.config
        if not _in_scope(ctx.relpath, conf.pallas_modules):
            return []
        consts = _module_int_consts(ctx.tree)
        out: List[Finding] = []
        for fn in _functions(ctx.tree):
            shapes = _FnShapes(ctx, fn, conf.align_helpers,
                               conf.tile_pack_helpers, consts)
            for call, kind in _block_calls(ctx, fn):
                if not call.args:
                    continue   # memory_space-only spec
                block = call.args[0]
                if not isinstance(block, ast.Tuple):
                    if _contains_shape(block):
                        continue   # whole-array extent (u.shape, ...)
                    out.append(self.finding(
                        ctx, call.lineno,
                        f"{kind} block {ast.unparse(block)!r} is not a "
                        "dim tuple nor a .shape-derived extent — "
                        "alignment cannot be audited"))
                    continue
                dims = block.elts
                judged = dims[-2:] if len(dims) >= 2 else dims[-1:]
                units = ([_SUBLANE_UNIT, _LANE_UNIT]
                         if len(judged) == 2 else [_LANE_UNIT])
                for dim, unit in zip(judged, units):
                    msg = self._judge(ctx, dim, unit, shapes)
                    if msg:
                        out.append(self.finding(ctx, call.lineno, msg))
            out.extend(self._check_grid(ctx, fn, shapes))
        return _dedupe(out)

    def _judge(self, ctx: FileCtx, dim: ast.expr, unit: int,
               shapes: _FnShapes) -> Optional[str]:
        pos = "sublane" if unit == _SUBLANE_UNIT else "lane"
        value: Optional[int] = None
        if isinstance(dim, ast.Constant) and isinstance(dim.value, int):
            value = dim.value
        elif isinstance(dim, ast.Name):
            name = dim.id
            # computed pads are judged FIRST (circular-certificate rule)
            if name in shapes.packed:
                return None
            if name in shapes.ceil:
                return self._judge_ceil(ctx, name, shapes.ceil[name],
                                        pos, shapes)
            if name in shapes.consts:
                value = shapes.consts[name]
            elif name in shapes.extent:
                return None
            else:
                return (f"block {pos} dim {name!r} is neither a "
                        "literal, a helper-padded value, nor a "
                        "materialized array extent")
        elif isinstance(dim, ast.Call):
            dotted = ctx.resolve(dim.func) or ""
            last = _last_seg(dotted) if dotted else ""
            if last in ("len", "int") or _contains_shape(dim):
                return None
            return (f"block {pos} dim {ast.unparse(dim)!r} cannot be "
                    "audited for alignment")
        else:
            if _contains_shape(dim):
                return None
            return (f"block {pos} dim {ast.unparse(dim)!r} cannot be "
                    "audited for alignment")
        if value is None:
            return None
        if value % unit == 0 or unit % value == 0:
            return None
        return (f"block {pos} dim {value} neither divides nor is a "
                f"multiple of the native unit {unit}")

    def _judge_ceil(self, ctx: FileCtx, name: str,
                    unit_expr: Optional[ast.expr], pos: str,
                    shapes: _FnShapes) -> Optional[str]:
        if unit_expr is None:
            return (f"block {pos} dim {name!r} was padded with a unit "
                    "splint cannot resolve")
        if isinstance(unit_expr, ast.Call):
            dotted = ctx.resolve(unit_expr.func) or ""
            # ceil_to(R, tile_packing(dtype)[0]) — dtype-aware
            return None if dotted else (
                f"block {pos} dim {name!r}: unresolvable pad unit")
        if isinstance(unit_expr, ast.Subscript):
            return None   # tile_packing(dtype)[0]-style indexing
        uval: Optional[int] = None
        if isinstance(unit_expr, ast.Constant) and isinstance(
                unit_expr.value, int):
            uval = unit_expr.value
        elif isinstance(unit_expr, ast.Name):
            uval = shapes.consts.get(unit_expr.id)
        if uval is None:
            return (f"block {pos} dim {name!r}: unresolvable pad unit "
                    f"{ast.unparse(unit_expr)!r}")
        if pos == "lane":
            return None if uval % _LANE_UNIT == 0 else (
                f"block lane dim {name!r} padded to {uval}, not a "
                f"multiple of {_LANE_UNIT}")
        # sublane: a fixed unit must cover the NARROW packing too —
        # ceil_to(R, 8) under-pads bf16 storage (needs 16)
        if uval % _NARROW_SUBLANE == 0:
            return None
        return (f"block sublane dim {name!r} padded with dtype-blind "
                f"unit {uval}; bf16/f16 storage packs "
                f"{_NARROW_SUBLANE} sublanes — pad via "
                "config.tile_packing (see _rank_pad)")

    def _check_grid(self, ctx: FileCtx, fn: ast.AST,
                    shapes: _FnShapes) -> List[Finding]:
        out: List[Finding] = []
        grid_exprs: List[ast.expr] = []
        for node in walk_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = ctx.resolve(node.func) or ""
            if _last_seg(dotted) != "pallas_call":
                continue
            for kw in node.keywords:
                if kw.arg == "grid":
                    grid_exprs.append(kw.value)
        # chase grid names to their local defs
        defs: Dict[str, ast.expr] = {}
        for st in walk_nodes(fn):
            if (isinstance(st, ast.Assign) and len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)):
                defs[st.targets[0].id] = st.value
        todo = list(grid_exprs)
        seen_names: set = set()
        while todo:
            e = todo.pop()
            for n in walk_nodes(e):
                if (isinstance(n, ast.Name) and n.id in defs
                        and n.id not in seen_names):
                    seen_names.add(n.id)
                    todo.append(defs[n.id])
                if not (isinstance(n, ast.BinOp)
                        and isinstance(n.op, ast.FloorDiv)):
                    continue
                num = n.left
                ok = (isinstance(num, ast.Name)
                      and (num.id in shapes.ceil
                           or num.id in shapes.packed))
                if not ok and isinstance(num, ast.Call):
                    # inline ceil_to(nb, chunk) // chunk
                    dotted = ctx.resolve(num.func) or ""
                    ok = _last_seg(dotted) in shapes.helpers
                if not ok:
                    out.append(self.finding(
                        ctx, n.lineno,
                        "grid division "
                        f"{ast.unparse(n)!r}: numerator was not "
                        "padded to a multiple of the divisor — the "
                        "ragged tail block is silently dropped"))
        return out


class VmemBudget(_TilingRule):
    """SPL026: static block-buffer accounting against the per-kernel
    VMEM budget, plus the kernel↔gate registry."""

    id = "SPL026"
    title = "VMEM budget"
    hint = ("shrink the block (or raise the kernel's declared budget "
            "in [tool.splint] vmem-kernel-budgets WITH a measurement); "
            "declare new block dims in vmem-dim-caps — the caps are "
            "the dispatch envelope, keep them honest")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        conf = project.config
        if not _in_scope(ctx.relpath, conf.pallas_modules):
            return []
        caps = {k: int(v) for k, v in
                _pairs(conf.vmem_dim_caps).items()}
        budgets = {k: float(v) for k, v in
                   _pairs(conf.vmem_kernel_budgets).items()}
        default_mib = float(conf.vmem_budget_mib or "16")
        consts = _module_int_consts(ctx.tree)
        gate_map = _pairs(conf.vmem_gate_map)
        out: List[Finding] = []
        for fn in _functions(ctx.tree):
            shapes = _FnShapes(ctx, fn, conf.align_helpers,
                               conf.tile_pack_helpers, consts)
            calls = [n for n in walk_nodes(fn)
                     if isinstance(n, ast.Call)
                     and _last_seg(ctx.resolve(n.func) or "")
                     == "pallas_call"]
            if not calls:
                continue
            if fn.name not in gate_map:
                out.append(self.finding(
                    ctx, fn.lineno,
                    f"kernel wrapper {fn.name!r} has no entry in "
                    "[tool.splint] vmem-gate-map — every pallas_call "
                    "needs a dispatch-time VMEM gate"))
            for call in calls:
                out.extend(self._check_call(
                    ctx, fn, call, shapes, caps,
                    budgets.get(fn.name, default_mib)))
        return _dedupe(out)

    # -- accounting -----------------------------------------------------

    def _check_call(self, ctx: FileCtx, fn, call: ast.Call,
                    shapes: _FnShapes, caps: Dict[str, int],
                    budget_mib: float) -> List[Finding]:
        out: List[Finding] = []
        total = 0
        specs: List[Tuple[ast.expr, bool]] = []   # (spec expr, scratch?)
        for kw in call.keywords:
            if kw.arg in ("in_specs", "out_specs"):
                vals = (kw.value.elts
                        if isinstance(kw.value, (ast.List, ast.Tuple))
                        else [kw.value])
                specs.extend((v, False) for v in vals)
            elif kw.arg == "scratch_shapes":
                vals = (kw.value.elts
                        if isinstance(kw.value, (ast.List, ast.Tuple))
                        else [kw.value])
                specs.extend((v, True) for v in vals)
        for spec, is_scratch in specs:
            if isinstance(spec, ast.Starred):
                got = self._starred_bytes(ctx, fn, spec, shapes, caps)
            elif isinstance(spec, ast.Name):
                # out_spec chosen by an if/else: charge the LARGEST
                # candidate — the budget must cover every branch
                cands = [st.value for st in walk_nodes(fn)
                         if isinstance(st, ast.Assign)
                         and any(isinstance(t, ast.Name)
                                 and t.id == spec.id
                                 for t in st.targets)]
                if not cands:
                    got = (f"spec {spec.id!r} has no local BlockSpec "
                           "definition splint can account")
                else:
                    sized = [self._spec_bytes(ctx, c, shapes, caps,
                                              is_scratch)
                             for c in cands]
                    errs = [s for s in sized if isinstance(s, str)]
                    got = errs[0] if errs else max(sized)
            else:
                got = self._spec_bytes(ctx, spec, shapes, caps,
                                       is_scratch)
            if isinstance(got, str):
                out.append(self.finding(ctx, spec.lineno, got))
            else:
                total += got
        limit = int(budget_mib * (1 << 20))
        if total > limit:
            out.append(self.finding(
                ctx, call.lineno,
                f"{fn.name}: static block-buffer sum "
                f"{total / (1 << 20):.1f} MiB exceeds the declared "
                f"budget {budget_mib:.0f} MiB (streamed specs counted "
                "double-buffered, 4 B/elem)"))
        return out

    def _starred_bytes(self, ctx, fn, spec: ast.Starred, shapes,
                       caps):
        name = (spec.value.id if isinstance(spec.value, ast.Name)
                else ast.unparse(spec.value))
        mult = caps.get(f"*{name}")
        if mult is None:
            return (f"starred spec list {name!r} has no "
                    f"'*{name}=<count>' multiplicity cap in "
                    "vmem-dim-caps")
        # find the list's element BlockSpec (listcomp or list literal)
        elem: Optional[ast.expr] = None
        for st in walk_nodes(fn):
            if not (isinstance(st, ast.Assign)
                    and len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)
                    and st.targets[0].id == name):
                continue
            v = st.value
            if isinstance(v, ast.ListComp):
                elem = v.elt
            elif isinstance(v, (ast.List, ast.Tuple)) and v.elts:
                elem = v.elts[0]
        if elem is None:
            return (f"starred spec list {name!r}: cannot locate its "
                    "element BlockSpec")
        got = self._spec_bytes(ctx, elem, shapes, caps, False)
        if isinstance(got, str):
            return got
        return got * mult

    def _spec_bytes(self, ctx, spec: ast.expr, shapes, caps,
                    is_scratch: bool):
        """Bytes of one BlockSpec/VMEM entry, or an error message."""
        if not isinstance(spec, ast.Call):
            return (f"spec {ast.unparse(spec)!r} is not a "
                    "BlockSpec/VMEM call splint can account")
        if not spec.args:
            return 0   # memory_space-only
        block = spec.args[0]
        streamed = (not is_scratch
                    and self._is_streamed(spec))
        elems = 1
        if isinstance(block, ast.Tuple):
            for dim in block.elts:
                got = self._dim_value(ctx, dim, shapes, caps)
                if got is None:
                    return (f"block dim {ast.unparse(dim)!r} has no "
                            "literal/const value and no vmem-dim-caps "
                            "entry — the budget cannot be evaluated")
                elems *= got
        else:
            cap = caps.get(ast.unparse(block))
            if cap is None:
                return (f"whole-extent block {ast.unparse(block)!r} "
                        "needs an element-count entry in vmem-dim-caps")
            elems = cap
        return elems * 4 * (2 if streamed else 1)

    @staticmethod
    def _is_streamed(spec: ast.Call) -> bool:
        """A spec is grid-streamed (→ double-buffered) iff its
        index_map uses at least one grid axis."""
        imap = None
        if len(spec.args) > 1:
            imap = spec.args[1]
        for kw in spec.keywords:
            if kw.arg == "index_map":
                imap = kw.value
        if not isinstance(imap, ast.Lambda):
            return imap is not None   # unknown callable: assume streamed
        params = {a.arg for a in imap.args.args}
        return any(isinstance(n, ast.Name) and n.id in params
                   for n in walk_nodes(imap.body))

    def _dim_value(self, ctx, dim: ast.expr, shapes,
                   caps) -> Optional[int]:
        if isinstance(dim, ast.Constant) and isinstance(dim.value, int):
            return dim.value
        text = ast.unparse(dim)
        if text in caps:
            return caps[text]
        if isinstance(dim, ast.Name) and dim.id in shapes.consts:
            return shapes.consts[dim.id]
        return None

    # -- gate registry --------------------------------------------------

    def finalize(self, project: Project) -> List[Finding]:
        conf = project.config
        gate_map = _pairs(conf.vmem_gate_map)
        out: List[Finding] = []
        # collect, per pallas module, defined functions + call names
        defined: Dict[str, set] = {}
        called: set = set()
        for ctx in project.files:
            for fn in _functions(ctx.tree):
                if _in_scope(ctx.relpath, conf.pallas_modules):
                    defined.setdefault(ctx.relpath, set()).add(fn.name)
            for node in walk_nodes(ctx.tree):
                if isinstance(node, ast.Call):
                    dotted = ctx.resolve(node.func) or ""
                    if dotted:
                        called.add(_last_seg(dotted))
        if not defined:
            return []
        all_defined = set().union(*defined.values())
        for kernel, gate in gate_map.items():
            if kernel not in all_defined:
                continue   # entry for a module outside this run's paths
            krel = next(r for r, fns in defined.items()
                        if kernel in fns)
            if gate not in defined.get(krel, set()):
                out.append(self.finding(
                    krel, 1,
                    f"vmem-gate-map names gate {gate!r} for "
                    f"{kernel!r} but the gate is not defined in the "
                    "kernel's module"))
                continue
            if gate not in called:
                out.append(self.finding(
                    krel, 1,
                    f"VMEM gate {gate!r} (for kernel {kernel!r}) is "
                    "never consulted — an orphaned gate guards "
                    "nothing"))
        return _dedupe(out)


class PlanSchemaDrift(_TilingRule):
    """SPL027: the plan cache's key/fields/match sets must agree with
    the declared PLAN_SCHEMA in both directions."""

    id = "SPL027"
    title = "plan-cache schema drift"
    hint = ("update PLAN_SCHEMA together with TunedPlan/plan_key/the "
            "strict-match comparator, bump PLAN_CACHE_VERSION, and "
            "add the v<n>: history marker — a key component that is "
            "stored but not compared silently mis-dispatches")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        version_node = self._module_assign(ctx, "PLAN_CACHE_VERSION")
        if version_node is None:
            return []
        out: List[Finding] = []
        schema_node = self._module_assign(ctx, "PLAN_SCHEMA")
        if schema_node is None:
            return [self.finding(
                ctx, version_node.lineno,
                "module defines PLAN_CACHE_VERSION but no PLAN_SCHEMA "
                "declaration to audit the cache against")]
        try:
            schema = ast.literal_eval(schema_node.value)
            assert isinstance(schema, dict)
        except Exception:
            return [self.finding(
                ctx, schema_node.lineno,
                "PLAN_SCHEMA is not a literal dict splint can read")]
        for req in ("version", "key", "fields", "match", "exempt"):
            if req not in schema:
                out.append(self.finding(
                    ctx, schema_node.lineno,
                    f"PLAN_SCHEMA lacks the {req!r} component"))
        if out:
            return out
        fields = set(schema["fields"])
        match = set(schema["match"])
        exempt = set(schema["exempt"])
        # version agreement + bump history
        try:
            version = int(ast.literal_eval(version_node.value))
        except Exception:
            version = None
        if version is not None and schema["version"] != version:
            out.append(self.finding(
                ctx, schema_node.lineno,
                f"PLAN_SCHEMA version {schema['version']} != "
                f"PLAN_CACHE_VERSION {version}"))
        if version is not None:
            for n in range(2, version + 1):
                if f"v{n}:" not in ctx.source:
                    out.append(self.finding(
                        ctx, version_node.lineno,
                        f"no 'v{n}:' history marker for cache version "
                        f"{n} — the bump discipline requires each "
                        "version's change to be recorded"))
        # match/exempt partition the fields
        if match & exempt:
            out.append(self.finding(
                ctx, schema_node.lineno,
                f"fields {sorted(match & exempt)} are both matched "
                "and exempt"))
        if match | exempt != fields:
            out.append(self.finding(
                ctx, schema_node.lineno,
                "match ∪ exempt != fields: "
                f"{sorted((match | exempt) ^ fields)} unaccounted — "
                "every stored field is either strictly compared or "
                "explicitly exempt"))
        out.extend(self._check_plan_class(ctx, fields))
        out.extend(self._check_plan_key(ctx, set(schema["key"])))
        return _dedupe(out)

    @staticmethod
    def _module_assign(ctx: FileCtx, name: str) -> Optional[ast.Assign]:
        for node in ast.iter_child_nodes(ctx.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == name):
                return node
        return None

    def _check_plan_class(self, ctx: FileCtx,
                          fields: set) -> List[Finding]:
        cls = None
        for node in walk_nodes(ctx.tree):
            if isinstance(node, ast.ClassDef) and node.name == "TunedPlan":
                cls = node
        if cls is None:
            return [self.finding(
                ctx, 1, "no TunedPlan class next to PLAN_SCHEMA")]
        declared = {st.target.id for st in cls.body
                    if isinstance(st, ast.AnnAssign)
                    and isinstance(st.target, ast.Name)}
        out = []
        for f in sorted(declared - fields):
            out.append(self.finding(
                ctx, cls.lineno,
                f"TunedPlan field {f!r} is not declared in "
                "PLAN_SCHEMA['fields'] — it will be stored but never "
                "audited for strict matching"))
        for f in sorted(fields - declared):
            out.append(self.finding(
                ctx, cls.lineno,
                f"PLAN_SCHEMA declares field {f!r} that TunedPlan "
                "does not carry"))
        return out

    def _check_plan_key(self, ctx: FileCtx, key: set) -> List[Finding]:
        fn = None
        for node in walk_nodes(ctx.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and node.name == "plan_key":
                fn = node
        if fn is None:
            return [self.finding(
                ctx, 1, "no plan_key function next to PLAN_SCHEMA")]
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args
                  if a.arg != "self"]
        out = []
        for p in sorted(set(params) - key):
            out.append(self.finding(
                ctx, fn.lineno,
                f"plan_key takes {p!r} which PLAN_SCHEMA['key'] does "
                "not declare"))
        for p in sorted(key - set(params)):
            out.append(self.finding(
                ctx, fn.lineno,
                f"PLAN_SCHEMA['key'] declares {p!r} but plan_key "
                "does not take it"))
        used = {n.id for st in fn.body for n in walk_nodes(st)
                if isinstance(n, ast.Name)}
        for p in sorted(set(params) & key):
            if p not in used:
                out.append(self.finding(
                    ctx, fn.lineno,
                    f"plan_key parameter {p!r} is never folded into "
                    "the key — two regimes differing only in it "
                    "share a cache entry"))
        return out

    def finalize(self, project: Project) -> List[Finding]:
        """Dispatch leg: the strict-match comparators must compare at
        least the schema's match set and only declared fields."""
        conf = project.config
        plan_ctx = None
        for ctx in project.files:
            if self._module_assign(ctx, "PLAN_SCHEMA") is not None:
                plan_ctx = ctx
        if plan_ctx is None:
            return []
        schema_node = self._module_assign(plan_ctx, "PLAN_SCHEMA")
        try:
            schema = ast.literal_eval(schema_node.value)
            fields = set(schema["fields"])
            match = set(schema["match"])
        except Exception:
            return []   # already reported by check()
        out: List[Finding] = []
        found_any = False
        for ctx in project.files:
            for fn in _functions(ctx.tree):
                if fn.name not in conf.plan_match_functions:
                    continue
                found_any = True
                # the plan variable is whichever receiver is compared
                # on >= 2 declared fields; attrs on OTHER receivers
                # (layout.block, fmt.encoding ...) are the comparison
                # TARGETS, not plan fields
                per_recv: Dict[str, set] = {}
                for node in walk_nodes(fn):
                    if not isinstance(node, ast.Compare):
                        continue
                    for side in [node.left] + list(node.comparators):
                        if (isinstance(side, ast.Attribute)
                                and isinstance(side.value, ast.Name)):
                            per_recv.setdefault(side.value.id,
                                                set()).add(side.attr)
                compared = set()
                for recv, attrs in per_recv.items():
                    if len(attrs & fields) >= 2:
                        compared |= attrs
                        for attr in sorted(attrs - fields
                                           - set(schema["key"])):
                            out.append(self.finding(
                                ctx, fn.lineno,
                                f"{fn.name} compares {recv}.{attr} "
                                "but PLAN_SCHEMA declares no such "
                                "field"))
                for attr in sorted(match - compared):
                    out.append(self.finding(
                        ctx, fn.lineno,
                        f"{fn.name} never compares match field "
                        f"{attr!r} — a plan tuned for one "
                        f"{attr} regime will be adopted by another"))
        if not found_any and conf.plan_match_functions:
            out.append(self.finding(
                plan_ctx.relpath, 1,
                "PLAN_SCHEMA is declared but none of the configured "
                "plan-match-functions exist in the analyzed files — "
                "the strict-match side of the contract is missing"))
        return _dedupe(out)


TILING_RULES = [TileAlignment(), VmemBudget(), PlanSchemaDrift()]
