"""bench.py regression gate (ROADMAP open item 1, docs/serve.md era).

The gate compares a fresh bench record against the newest prior
``BENCH_*.json`` ON THE SAME METRIC, flags >10% slowdowns as
``bench_regression`` run-report events (via the shared resilience
helper), carries them in the JSON artifact, and — under ``--gate`` —
exits nonzero so a perf PR ships with a verdict, not just a number.
"""

import json
import os
import subprocess
import sys

import pytest

import bench
from splatt_tpu import resilience

REC = {"metric": "M1", "value": 2.0, "unit": "sec/iter",
       "timing_stats": {"blocked": {"median": 2.0},
                        "stream": {"median": 10.0}}}
PRIOR = {"metric": "M1", "value": 1.5, "unit": "sec/iter",
         "timing_stats": {"blocked": {"median": 1.5},
                          "stream": {"median": 11.0}}}


def test_regressions_flag_headline_and_per_path():
    regs = bench._bench_regressions(REC, PRIOR)
    assert {r["path"] for r in regs} == {"headline", "blocked"}
    head = next(r for r in regs if r["path"] == "headline")
    assert head["sec"] == 2.0 and head["prior_sec"] == 1.5
    assert head["pct"] == pytest.approx(33.3)
    # stream got FASTER: not flagged


def test_within_threshold_is_clean():
    ok = dict(REC, value=1.64, timing_stats={})  # +9.3% < 10%
    assert bench._bench_regressions(ok, PRIOR) == []


def test_unlike_metrics_are_never_compared():
    other = dict(PRIOR, metric="a different workload")
    assert bench._bench_regressions(REC, other) == []


def test_prior_discovery_newest_usable_wins(tmp_path):
    def write(name, value, wrap=True):
        rec = {"metric": "M1", "value": value, "unit": "sec/iter"}
        payload = {"parsed": rec} if wrap else rec
        (tmp_path / name).write_text(json.dumps(payload))

    write("BENCH_r01.json", 1.0)
    write("BENCH_r02.json", 1.5)
    (tmp_path / "BENCH_r03.json").write_text("not json at all")
    name, rec = bench._prior_bench_record(str(tmp_path))
    assert name == "BENCH_r02.json" and rec["value"] == 1.5
    # a bare (unwrapped) record is also a valid prior
    write("BENCH_r04.json", 1.7, wrap=False)
    name, rec = bench._prior_bench_record(str(tmp_path))
    assert name == "BENCH_r04.json" and rec["value"] == 1.7


def test_prior_discovery_empty_dir(tmp_path):
    assert bench._prior_bench_record(str(tmp_path)) is None


def test_record_bench_regression_event():
    resilience.run_report().clear()
    ev = resilience.record_bench_regression("blocked", 2.0, 1.5, 33.3,
                                            "BENCH_r05.json")
    assert ev["kind"] == "bench_regression" and ev["pct"] == 33.3
    lines = resilience.run_report().summary()
    assert any("BENCH REGRESSION" in ln for ln in lines)
    resilience.run_report().clear()


def test_repo_priors_are_discoverable():
    """The real repo artifacts parse: the gate has a baseline today."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    found = bench._prior_bench_record(repo)
    assert found is not None
    name, rec = found
    assert name.startswith("BENCH_") and rec["value"] > 0


def test_gate_end_to_end_nonzero_exit(tmp_path):
    """--gate e2e: a tiny bench run against a fabricated prior with an
    impossibly fast value exits nonzero, records bench_regression in
    the JSON artifact, and still prints the headline number (the
    verdict never eats the measurement)."""
    nnz, rank = 60000, 4
    metric = (f"CPD-ALS sec/iteration, synthetic NELL-2-shaped "
              f"(3-mode, {nnz} nnz, rank {rank}, float32) on cpu; "
              f"baseline: reference 1-thread CPU same tensor")
    (tmp_path / "BENCH_r98.json").write_text(json.dumps(
        {"parsed": {"metric": metric, "value": 0.0001,
                    "unit": "sec/iter"}}))
    env = dict(os.environ)
    env.update(SPLATT_BENCH_NNZ=str(nnz), SPLATT_BENCH_RANK=str(rank),
               SPLATT_BENCH_ITERS="1", SPLATT_BENCH_PATHS="blocked",
               SPLATT_BENCH_PRIOR_DIR=str(tmp_path),
               SPLATT_TUNE_CACHE=str(tmp_path / "tc.json"),
               JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run([sys.executable, os.path.join(repo, "bench.py"),
                        "--gate"], env=env, capture_output=True,
                       text=True, timeout=600, cwd=repo)
    assert p.returncode == 1, (p.returncode, p.stderr[-800:])
    line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    assert line, p.stderr[-800:]
    rec = json.loads(line[-1])
    assert rec["value"] > 0                       # headline survived
    regs = rec["bench_regressions"]
    assert rec["bench_prior"] == "BENCH_r98.json"
    assert any(r["path"] == "headline" for r in regs)
    assert "REGRESSION" in p.stderr


def test_unknown_argv_rejected():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run([sys.executable, os.path.join(repo, "bench.py"),
                        "--bogus"], capture_output=True, text=True,
                       timeout=120)
    assert p.returncode == 2 and "unknown arguments" in p.stderr


def test_prior_discovery_skips_unlike_metrics_to_older_prior(tmp_path):
    """A different workload benched in between must not disable the
    gate: the search keeps walking to the newest SAME-metric prior."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"parsed": {"metric": "M1", "value": 1.5, "unit": "sec/iter"}}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"parsed": {"metric": "OTHER", "value": 9.0,
                    "unit": "sec/iter"}}))
    name, rec = bench._prior_bench_record(str(tmp_path), metric="M1")
    assert name == "BENCH_r01.json" and rec["value"] == 1.5
    # and with no metric constraint the newest usable one still wins
    name, _ = bench._prior_bench_record(str(tmp_path))
    assert name == "BENCH_r02.json"
    # no same-metric prior at all -> no baseline
    assert bench._prior_bench_record(str(tmp_path),
                                     metric="UNSEEN") is None


# -- variance hygiene (ISSUE 8 satellite): CV + bench_noisy ------------------


def _noisy_rec(cv_mine=0.3, cv_prior=None):
    rec = {"metric": "M1", "value": 2.0, "unit": "sec/iter",
           "best_path": "blocked",
           "timing_stats": {"blocked": {"median": 2.0, "cv": cv_mine}}}
    prior = {"metric": "M1", "value": 1.5, "unit": "sec/iter",
             "best_path": "blocked",
             "timing_stats": {"blocked": {"median": 1.5}}}
    if cv_prior is not None:
        prior["timing_stats"]["blocked"]["cv"] = cv_prior
    return rec, prior


def test_noisy_cv_downgrades_to_warning():
    """A >10% slowdown whose CV (either side) exceeds NOISE_CV is
    marked noisy — the gate warns (bench_noisy) instead of failing."""
    rec, prior = _noisy_rec(cv_mine=0.3)
    regs = bench._bench_regressions(rec, prior)
    assert regs and all(r.get("noisy") for r in regs)
    assert all(r["cv"] == 0.3 for r in regs)
    # prior-side noise counts too
    rec2, prior2 = _noisy_rec(cv_mine=0.01, cv_prior=0.5)
    regs2 = bench._bench_regressions(rec2, prior2)
    assert regs2 and all(r.get("noisy") for r in regs2)


def test_quiet_cv_still_gates():
    """Low CV on both sides: the regression stays a hard verdict; a
    prior WITHOUT a recorded cv gates normally (noise cannot be
    claimed, only measured)."""
    rec, prior = _noisy_rec(cv_mine=0.02, cv_prior=0.03)
    regs = bench._bench_regressions(rec, prior)
    assert regs and not any(r.get("noisy") for r in regs)
    rec2 = {"metric": "M1", "value": 2.0, "unit": "sec/iter",
            "timing_stats": {"blocked": {"median": 2.0}}}
    regs2 = bench._bench_regressions(rec2, PRIOR)
    assert regs2 and not any(r.get("noisy") for r in regs2)


def test_delta_under_2x_cv_is_noise_by_default():
    """ISSUE 14 satellite — the ROADMAP variance note made the gate's
    default: a slowdown SMALLER than CV_NOISE_MULT x the measured CV
    is one draw from the timing distribution, not a verdict, even when
    the CV itself sits under the absolute NOISE_CV ceiling."""
    # +13% delta, cv 0.10 (< NOISE_CV): 2 x 0.10 = 0.20 > 0.13 → noise
    rec = {"metric": "M1", "value": 1.13, "unit": "sec/iter",
           "best_path": "blocked",
           "timing_stats": {"blocked": {"median": 1.13, "cv": 0.10}}}
    prior = {"metric": "M1", "value": 1.0, "unit": "sec/iter",
             "best_path": "blocked",
             "timing_stats": {"blocked": {"median": 1.0}}}
    regs = bench._bench_regressions(rec, prior)
    assert regs and all(r.get("noisy") for r in regs)
    assert all(r["cv"] == 0.10 for r in regs)
    # +25% against the same cv 0.10: 0.25 > 0.20 → a real verdict
    rec2 = {"metric": "M1", "value": 1.25, "unit": "sec/iter",
            "best_path": "blocked",
            "timing_stats": {"blocked": {"median": 1.25, "cv": 0.10}}}
    regs2 = bench._bench_regressions(rec2, prior)
    assert regs2 and not any(r.get("noisy") for r in regs2)


def test_bytes_legs_are_never_noisy():
    """Encoded-bytes comparisons are deterministic: CV hygiene applies
    to timing legs only."""
    rec = {"metric": "M1", "value": 1.0, "unit": "sec/iter",
           "best_path": "blocked",
           "timing_stats": {"blocked": {"median": 1.0, "cv": 0.9}},
           "model_gb_per_path": {"blocked": 2.0}}
    prior = {"metric": "M1", "value": 1.0, "unit": "sec/iter",
             "timing_stats": {"blocked": {"median": 1.0}},
             "model_gb_per_path": {"blocked": 1.0}}
    regs = bench._bench_regressions(rec, prior)
    bytes_regs = [r for r in regs if r["path"].startswith("bytes:")]
    assert bytes_regs and not any(r.get("noisy") for r in bytes_regs)


def test_apply_gate_records_noisy_and_passes(tmp_path, monkeypatch,
                                             capsys):
    """_apply_regression_gate: noisy slowdowns emit bench_noisy events
    and the artifact's bench_noisy list, but the returned (gated) list
    is empty — warnings, not verdicts."""
    resilience.run_report().clear()
    prior = {"metric": "M1", "value": 1.5, "unit": "sec/iter",
             "best_path": "blocked",
             "timing_stats": {"blocked": {"median": 1.5}}}
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"parsed": prior}))
    monkeypatch.setenv("SPLATT_BENCH_PRIOR_DIR", str(tmp_path))
    rec = {"metric": "M1", "value": 2.0, "unit": "sec/iter",
           "best_path": "blocked",
           "timing_stats": {"blocked": {"median": 2.0, "cv": 0.4}}}
    gated = bench._apply_regression_gate(rec)
    assert gated == []
    assert rec.get("bench_noisy") and "bench_regressions" not in rec
    evs = resilience.run_report().events("bench_noisy")
    assert evs and evs[-1]["cv"] == 0.4
    assert evs[-1]["threshold"] == bench.NOISE_CV
    assert any("bench comparison" in ln
               for ln in resilience.run_report().summary())
    err = capsys.readouterr().err
    assert "NOISY" in err
    resilience.run_report().clear()


def test_run_stats_carry_cv():
    """bench.py's per-path stats include the coefficient of variation
    the gate reads (smoke-checked via the stats math, not a full
    bench run)."""
    times = [1.0, 1.1, 0.9]
    mean = sum(times) / len(times)
    var = sum((t - mean) ** 2 for t in times) / len(times)
    assert (var ** 0.5) / mean == pytest.approx(0.0816, abs=1e-3)
