"""Resilience layer: taxonomy, retries, fallback chain, checkpoints.

The contract under test (ISSUE 1 / docs/resilience.md): failures are
CLASSIFIED (deterministic / transient / resource / unknown) and each
class gets the right consequence — persist, retry-with-backoff, demote
per shape, or re-probe next process; a runtime engine failure degrades
the run to the next engine in the ordered chain instead of killing
cpd_als; corrupt checkpoints fall back a generation instead of crashing
the resume; and every branch is reachable on CPU through the fault
injection harness (splatt_tpu.utils.faults) — resilience code that only
runs when infrastructure misbehaves is dead code until it is testable.
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

import splatt_tpu.ops.pallas_kernels as pk
from splatt_tpu import resilience
from splatt_tpu.blocked import BlockedSparse
from splatt_tpu.config import Options, Verbosity
from splatt_tpu.cpd import (CheckpointError, _save_checkpoint, cpd_als,
                            load_checkpoint, load_checkpoint_resilient)
from splatt_tpu.ops.mttkrp import engine_chain, engine_plan, mttkrp
from splatt_tpu.resilience import FailureClass, classify_failure
from splatt_tpu.utils import faults
from tests import gen


@pytest.fixture(autouse=True)
def _clean_resilience_state(monkeypatch):
    """Demotions, the run report, and armed faults are process-global;
    every test starts clean and leaves nothing armed.  Backoff sleeps
    are zeroed so retry tests don't slow the suite."""
    resilience.reset_demotions()
    resilience.run_report().clear()
    resilience.set_fallback(None)
    faults.reset()
    monkeypatch.setattr(resilience.time, "sleep", lambda s: None)
    yield
    resilience.reset_demotions()
    resilience.run_report().clear()
    resilience.set_fallback(None)
    faults.reset()


def _opts(**kw):
    kw.setdefault("random_seed", 31)
    kw.setdefault("verbosity", Verbosity.NONE)
    return Options(**kw)


# -- failure taxonomy -------------------------------------------------------

@pytest.mark.parametrize("msg,cls", [
    # deterministic: Mosaic/kernel-compiler rejection signatures
    ("Mosaic failed to compile the kernel", FailureClass.DETERMINISTIC),
    ("Internal TPU kernel compiler error", FailureClass.DETERMINISTIC),
    ("Invalid input layout for broadcast", FailureClass.DETERMINISTIC),
    ("Unsupported lowering of take_along_axis",
     FailureClass.DETERMINISTIC),
    ("NotImplementedError: dynamic gather", FailureClass.DETERMINISTIC),
    # transient: relay/service failures, never persisted
    ("XLA compile: HTTP code 500 from service", FailureClass.TRANSIENT),
    ("HTTP code 503: service unavailable", FailureClass.TRANSIENT),
    ("INTERNAL: stream reset by relay", FailureClass.TRANSIENT),
    ("UNAVAILABLE: TPU backend setup error", FailureClass.TRANSIENT),
    ("DEADLINE_EXCEEDED: compile RPC", FailureClass.TRANSIENT),
    ("OSError: Connection reset by peer", FailureClass.TRANSIENT),
    ("socket.timeout: timed out", FailureClass.TRANSIENT),
    # resource: capacity, demote this shape only
    ("RESOURCE_EXHAUSTED: attempting to allocate 9G",
     FailureClass.RESOURCE),
    ("Out of memory allocating partials", FailureClass.RESOURCE),
    ("Mosaic: scoped vmem limit exceeded", FailureClass.RESOURCE),
    # unknown: unproven, re-probe next process
    ("ValueError: something else entirely", FailureClass.UNKNOWN),
])
def test_classify_failure_branches(msg, cls):
    assert classify_failure(msg) is cls


def test_classify_precedence():
    """'INTERNAL: Mosaic ...' carries a real compiler signature — the
    transient INTERNAL: prefix must not launder it into a retry; and a
    VMEM message trumping the Mosaic marker is capacity, not
    capability."""
    assert classify_failure(
        "INTERNAL: Mosaic failed to lower") is FailureClass.DETERMINISTIC
    assert classify_failure(
        "Mosaic: scoped vmem limit exceeded") is FailureClass.RESOURCE


def test_classify_accepts_exceptions():
    e = RuntimeError("UNAVAILABLE: relay dropped")
    assert classify_failure(e) is FailureClass.TRANSIENT


# -- transient retry with capped backoff + jitter ---------------------------

def test_retry_transient_retries_then_succeeds():
    calls = []
    delays = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("HTTP code 500")
        return "proved"

    out = resilience.retry_transient(flaky, attempts=3,
                                     sleep=delays.append,
                                     rng=lambda: 1.0)
    assert out == "proved"
    assert len(calls) == 3
    # exponential, capped: base, 2*base (full jitter at rng()=1.0)
    assert delays == [resilience.BACKOFF_BASE_S,
                      2 * resilience.BACKOFF_BASE_S]
    assert len(resilience.run_report().events("transient_retry")) == 2


def test_retry_transient_cap_bounds_delay():
    calls = []
    delays = []

    def always_500():
        calls.append(1)
        raise RuntimeError("HTTP code 500")

    with pytest.raises(RuntimeError):
        resilience.retry_transient(always_500, attempts=8,
                                   sleep=delays.append, rng=lambda: 1.0)
    assert len(calls) == 8
    assert max(delays) == resilience.BACKOFF_CAP_S


def test_retry_transient_does_not_retry_other_classes():
    for msg in ("Mosaic rejection", "RESOURCE_EXHAUSTED: oom",
                "ValueError: bug"):
        calls = []

        def fail():
            calls.append(1)
            raise RuntimeError(msg)

        with pytest.raises(RuntimeError):
            resilience.retry_transient(fail, attempts=5,
                                       sleep=lambda s: None)
        assert len(calls) == 1, msg


# -- fault injection harness ------------------------------------------------

def test_faults_inject_and_countdown():
    with faults.inject("somewhere", "http500", times=2):
        with pytest.raises(RuntimeError, match="HTTP code 500"):
            faults.maybe_fail("somewhere")
        with pytest.raises(RuntimeError):
            faults.maybe_fail("somewhere")
        faults.maybe_fail("somewhere")  # exhausted: no-op
    faults.maybe_fail("somewhere")      # disarmed on exit


def test_faults_env_malformed_entries_ignored(monkeypatch, capsys):
    """A typo in SPLATT_FAULTS must warn-and-ignore, not kill the run
    at some random hook site."""
    monkeypatch.setenv("SPLATT_FAULTS",
                       "ck:runtime:two,probe:htp500:1,ok_site:mosaic:1")
    faults.reset()
    faults.maybe_fail("ck")      # malformed times: ignored
    faults.maybe_fail("probe")   # unknown kind: ignored
    with pytest.raises(RuntimeError, match="Mosaic"):
        faults.maybe_fail("ok_site")  # the valid entry still armed
    err = capsys.readouterr().err
    assert "ck:runtime:two" in err and "htp500" in err


def test_faults_env_var(monkeypatch):
    monkeypatch.setenv("SPLATT_FAULTS",
                       "site_a:internal:1, site_b:oom:*")
    faults.reset()
    with pytest.raises(RuntimeError, match="INTERNAL"):
        faults.maybe_fail("site_a")
    faults.maybe_fail("site_a")  # count 1 exhausted
    for _ in range(3):           # '*' never exhausts
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            faults.maybe_fail("site_b")
    faults.maybe_fail("unarmed_site")


def test_apply_compile_cache_knob(monkeypatch):
    """SPLATT_COMPILE_CACHE points jax's persistent executable cache
    at the named directory with the caching floors zeroed (fleet
    replicas share many small same-regime compiles); unset leaves the
    config untouched.  Config only — executing deserialized entries is
    the chaos soaks' job (and is CPU-unsafe for sharded programs, see
    utils/env.py)."""
    import jax

    from splatt_tpu.utils.env import apply_compile_cache

    prior = jax.config.jax_compilation_cache_dir
    prior_t = jax.config.jax_persistent_cache_min_compile_time_secs
    prior_b = jax.config.jax_persistent_cache_min_entry_size_bytes
    try:
        monkeypatch.delenv("SPLATT_COMPILE_CACHE", raising=False)
        apply_compile_cache()   # unset: a no-op
        assert jax.config.jax_compilation_cache_dir == prior
        monkeypatch.setenv("SPLATT_COMPILE_CACHE", "/tmp/xc-test")
        apply_compile_cache()
        assert jax.config.jax_compilation_cache_dir == "/tmp/xc-test"
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
        assert jax.config.jax_persistent_cache_min_entry_size_bytes == 0
    finally:
        jax.config.update("jax_compilation_cache_dir", prior)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prior_t)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", prior_b)


def test_faults_kinds_map_to_taxonomy():
    for kind, cls in [("http500", FailureClass.TRANSIENT),
                      ("internal", FailureClass.TRANSIENT),
                      ("unavailable", FailureClass.TRANSIENT),
                      ("timeout", FailureClass.TRANSIENT),
                      ("oom", FailureClass.RESOURCE),
                      ("mosaic", FailureClass.DETERMINISTIC),
                      ("runtime", FailureClass.UNKNOWN)]:
        with faults.inject("k", kind):
            with pytest.raises(Exception) as ei:
                faults.maybe_fail("k")
        assert classify_failure(ei.value) is cls, kind


def test_faults_consume():
    assert faults.consume("torn") is False
    with faults.inject("torn", "runtime", times=1):
        assert faults.consume("torn") is True
        assert faults.consume("torn") is False


# -- demotion registry ------------------------------------------------------

def test_demotion_scopes():
    resilience.demote_engine("fused_t",
                             RuntimeError("injected runtime failure"))
    assert resilience.is_demoted("fused_t")
    assert resilience.is_demoted("fused_t", "ck1:b4096")  # any shape
    # RESOURCE failures demote per-shape only
    resilience.demote_engine("fused_tg",
                             RuntimeError("RESOURCE_EXHAUSTED: oom"),
                             shape_key="ck1:b4096")
    assert resilience.is_demoted("fused_tg", "ck1:b4096")
    assert not resilience.is_demoted("fused_tg", "ck1:b128")
    assert not resilience.is_demoted("fused_tg")
    evs = resilience.run_report().events("engine_demotion")
    assert {e["engine"] for e in evs} == {"fused_t", "fused_tg"}
    resilience.reset_demotions()
    assert not resilience.is_demoted("fused_t")


# -- engine chain / plan ----------------------------------------------------

def _blocked(name="med", **opt_kw):
    """ALLMODE BlockedSparse built without BlockedSparse.from_coo:
    from_coo reaches into splatt_tpu.parallel for the shared layout
    policy, and these tests must run even where the distributed stack's
    jax APIs are unavailable."""
    from splatt_tpu.blocked import build_layout
    from splatt_tpu.config import resolve_dtype

    tt = gen.fixture_tensor(name)
    opt_kw.setdefault("use_pallas", True)  # pallas_interpret on CPU
    opt_kw.setdefault("nnz_block", 256)
    opts = _opts(**opt_kw).validate()
    layouts = [build_layout(tt, m, block=opts.nnz_block,
                            val_dtype=resolve_dtype(opts, tt.vals.dtype))
               for m in range(tt.nmodes)]
    bs = BlockedSparse(layouts=layouts,
                       mode_map={m: m for m in range(tt.nmodes)},
                       dims=tt.dims, nnz=tt.nnz, opts=opts)
    return tt, bs


def test_engine_chain_order_and_terminal():
    tt, bs = _blocked()
    lay = bs.layouts[0]
    facs = [jnp.zeros((d, 4), jnp.float32) for d in bs.dims]
    chain = engine_chain(lay, facs, lay.mode, "sorted_onehot",
                         "pallas_interpret")
    # best-first, xla_scan before the terminal stream/scatter engine
    assert chain[0].startswith("fused")
    assert chain[-2:] == ["xla_scan", "xla"]
    assert chain.index("xla_scan") > chain.index(chain[0])
    # the xla impl has no pallas candidates
    assert engine_chain(lay, facs, lay.mode, "sorted_onehot",
                        "xla") == ["xla_scan", "xla"]
    # scatter paths are single-engine
    assert engine_chain(lay, facs, lay.mode, "sorted_scatter",
                        "pallas_interpret") == ["xla"]


def test_engine_chain_skips_demoted():
    tt, bs = _blocked()
    lay = bs.layouts[0]
    facs = [jnp.zeros((d, 4), jnp.float32) for d in bs.dims]
    full = engine_chain(lay, facs, lay.mode, "sorted_onehot",
                        "pallas_interpret")
    head = full[0]
    resilience.demote_engine(head, RuntimeError("injected runtime"))
    pruned = engine_chain(lay, facs, lay.mode, "sorted_onehot",
                          "pallas_interpret")
    assert head not in pruned
    assert engine_plan(lay, facs, lay.mode, "sorted_onehot",
                       "pallas_interpret") == pruned[0]
    # the terminal engine can never be demoted out of the chain
    for e in list(full):
        resilience.demote_engine(e, RuntimeError("injected runtime"))
    assert engine_chain(lay, facs, lay.mode, "sorted_onehot",
                        "pallas_interpret")[-1] == "xla"


# -- runtime engine fallback ------------------------------------------------

def test_mttkrp_falls_back_on_engine_fault():
    tt, bs = _blocked()
    lay = bs.layouts[0]
    mode = lay.mode
    rank = 4
    rng = np.random.default_rng(0)
    facs = [jnp.asarray(rng.random((d, rank))) for d in bs.dims]
    want = mttkrp(bs, facs, mode)
    head = engine_plan(lay, facs, mode, "sorted_onehot",
                       "pallas_interpret")
    resilience.run_report().clear()
    with faults.inject(f"engine.{head}", "runtime", times=faults.ALWAYS):
        got = mttkrp(bs, facs, mode)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-8)
    evs = resilience.run_report().events("engine_demotion")
    assert [e["engine"] for e in evs] == [head]


def test_mttkrp_fallback_off_raises():
    tt, bs = _blocked(engine_fallback=False)
    lay = bs.layouts[0]
    facs = [jnp.asarray(np.random.default_rng(0).random((d, 4)))
            for d in bs.dims]
    head = engine_plan(lay, facs, lay.mode, "sorted_onehot",
                       "pallas_interpret")
    with faults.inject(f"engine.{head}", "runtime", times=faults.ALWAYS):
        with pytest.raises(RuntimeError, match="injected"):
            mttkrp(bs, facs, lay.mode)


def test_cpd_als_completes_through_engine_fault():
    """Acceptance: with fault injection forcing the lead Pallas engine
    to fail at runtime, cpd_als completes on the next engine in the
    chain, the fit matches the no-fault run to 1e-6, and the demotion
    appears in the run report."""
    tt, bs = _blocked()
    opts = _opts(max_iterations=6, use_pallas=True)
    base = cpd_als(bs, rank=3, opts=opts)

    resilience.reset_demotions()
    resilience.run_report().clear()
    lay = bs.layouts[0]
    facs = [jnp.zeros((d, 3), jnp.float32) for d in bs.dims]
    head = engine_plan(lay, facs, lay.mode, "sorted_onehot",
                       "pallas_interpret")
    with faults.inject(f"engine.{head}", "runtime", times=faults.ALWAYS):
        faulted = cpd_als(bs, rank=3, opts=_opts(max_iterations=6,
                                                 use_pallas=True))
    assert float(faulted.fit) == pytest.approx(float(base.fit), abs=1e-6)
    demoted = [e["engine"] for e in
               resilience.run_report().events("engine_demotion")]
    assert head in demoted


def test_sweep_level_rescue_decision():
    """_try_engine_rescue: demote-and-retry only when fallback is on,
    an engine was attempted, it is not terminal, it was not already
    demoted (livelock guard), and the error is engine-shaped."""
    from splatt_tpu.cpd import _try_engine_rescue

    tt, bs = _blocked()
    err = RuntimeError("INTERNAL: async runtime failure")
    # no attempt noted yet (the attempt note is scope state now)
    resilience._state().last_attempt = None
    assert _try_engine_rescue(bs, _opts(), err) is False
    resilience.note_engine_attempt("fused_t", "ck1:b256")
    assert _try_engine_rescue(bs, _opts(), err) is True
    assert resilience.is_demoted("fused_t")
    # same engine again: already demoted, nothing new was tried
    assert _try_engine_rescue(bs, _opts(), err) is False
    # terminal engine: nothing left to fall back to
    resilience.note_engine_attempt("xla", None)
    assert _try_engine_rescue(bs, _opts(), err) is False
    # fallback off
    resilience.note_engine_attempt("fused_tg", None)
    assert _try_engine_rescue(bs, _opts(engine_fallback=False),
                              err) is False
    # a non-engine-shaped error (UNKNOWN class, e.g. a LinAlgError from
    # the solve) must surface, not demote a healthy engine
    resilience.note_engine_attempt("fused_tg", None)
    assert _try_engine_rescue(
        bs, _opts(), RuntimeError("LinAlgError: singular matrix")) is False
    assert not resilience.is_demoted("fused_tg")
    # COO oracle input has no engine chain
    resilience.note_engine_attempt("fused_tg", None)
    assert _try_engine_rescue(tt, _opts(), err) is False


# -- probe-compile fault injection (acceptance criterion) -------------------

def test_injected_compile_500_leaves_no_persisted_rejection(tmp_path,
                                                            monkeypatch):
    """Acceptance: an injected compile-time HTTP 500 leaves no
    persisted 'compile_failed' entry in the on-disk probe cache."""
    import jax

    cache = tmp_path / "probe_cache.json"
    monkeypatch.setenv(pk._CACHE_ENV, str(cache))
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    pk.PROBE_STATES.clear()
    monkeypatch.setattr(pk, "_probe_case", lambda fn, regime, block: True)
    with faults.inject("probe_compile", "http500", times=faults.ALWAYS):
        assert pk._probe_compiles(None, "testk", "ck1", 4096) is False
    assert pk.PROBE_STATES["testk:ck1:b4096"] == "infra"
    text = cache.read_text()
    assert "compile_failed" not in text
    assert json.loads(text)  # still valid JSON
    # relay recovers within the retry budget: proven in-process
    pk.PROBE_STATES.clear()
    with faults.inject("probe_compile", "http500", times=1):
        assert pk._probe_compiles(None, "testk2", "ck1", 4096) is True
    assert pk.probe_cache_load("testk2:ck1:b4096") == "ok"


# -- checkpoint integrity ---------------------------------------------------

def _mk_ckpt(path, seed=0, it=4, fit=0.5):
    rng = np.random.default_rng(seed)
    factors = [jnp.asarray(rng.random((d, 3))) for d in (6, 5, 4)]
    lam = jnp.asarray(rng.random(3))
    _save_checkpoint(str(path), factors, lam, it, fit)
    return factors, lam


def test_checkpoint_roundtrip_with_checksum(tmp_path):
    ck = tmp_path / "ck.npz"
    factors, lam = _mk_ckpt(ck)
    got_f, got_lam, it, fit = load_checkpoint(str(ck))
    assert it == 4 and fit == 0.5
    for a, b in zip(got_f, factors):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with np.load(str(ck)) as z:
        assert int(z["schema"]) == 2
        assert "checksum" in z.files


def test_checkpoint_truncation_detected(tmp_path):
    ck = tmp_path / "ck.npz"
    _mk_ckpt(ck)
    data = ck.read_bytes()
    ck.write_bytes(data[:len(data) // 2])
    with pytest.raises(CheckpointError):
        load_checkpoint(str(ck))


def test_checkpoint_checksum_catches_tampered_payload(tmp_path):
    """The content checksum catches corruption the zip container
    misses: a payload swapped under a stale checksum must not load."""
    ck = tmp_path / "ck.npz"
    _mk_ckpt(ck)
    with np.load(str(ck)) as z:
        data = {k: z[k] for k in z.files}
    data["factor0"] = data["factor0"] + 1.0
    np.savez(str(ck), **data)
    with pytest.raises(CheckpointError, match="checksum"):
        load_checkpoint(str(ck))
    # verify=False loads it anyway (forensics)
    factors, _, it, _ = load_checkpoint(str(ck), verify=False)
    assert it == 4


def test_legacy_v1_checkpoint_still_loads(tmp_path):
    ck = tmp_path / "ck.npz"
    rng = np.random.default_rng(0)
    factors = [rng.random((d, 2)) for d in (5, 4, 3)]
    np.savez(str(ck), nmodes=3, it=7, fit=0.25, lam=np.ones(2),
             dims=np.asarray([5, 4, 3]), rank=2,
             **{f"factor{m}": f for m, f in enumerate(factors)})
    got_f, lam, it, fit = load_checkpoint(str(ck))
    assert it == 7 and fit == 0.25 and len(got_f) == 3


def test_resilient_load_falls_back_to_bak(tmp_path):
    ck = tmp_path / "ck.npz"
    _mk_ckpt(ck, seed=1, it=2, fit=0.3)      # generation 1
    _mk_ckpt(ck, seed=2, it=4, fit=0.6)      # generation 2; gen1 -> .bak
    assert (tmp_path / "ck.npz.bak").exists()
    data = ck.read_bytes()
    ck.write_bytes(data[: len(data) // 3])   # corrupt the latest
    out = load_checkpoint_resilient(str(ck))
    assert out is not None
    _, _, it, fit = out
    assert (it, fit) == (2, 0.3)             # the previous generation
    ev = resilience.run_report().events("checkpoint_recovery")
    assert len(ev) == 1 and "previous generation" in ev[0]["action"]


def test_resilient_load_gives_up_gracefully(tmp_path):
    ck = tmp_path / "ck.npz"
    _mk_ckpt(ck, it=2)
    _mk_ckpt(ck, it=4)
    ck.write_bytes(b"garbage")
    (tmp_path / "ck.npz.bak").write_bytes(b"also garbage")
    assert load_checkpoint_resilient(str(ck)) is None
    ev = resilience.run_report().events("checkpoint_recovery")
    assert len(ev) == 1 and "starting fresh" in ev[0]["action"]


def test_torn_write_injection_and_resume(tmp_path):
    """Acceptance-adjacent: a torn checkpoint write (injected) corrupts
    the latest generation; the next resume degrades to .bak instead of
    crashing, and cpd_als completes."""
    tt = gen.fixture_tensor("med")
    ck = str(tmp_path / "ck.npz")
    opts = _opts(max_iterations=4)
    cpd_als(tt, rank=3, opts=opts, checkpoint_path=ck, checkpoint_every=2)
    # overwrite the latest generation with a TORN write
    with np.load(ck) as z:
        pass  # it is valid now
    factors, lam, it, fit = load_checkpoint(ck)
    with faults.inject("checkpoint_torn", "runtime", times=1):
        _save_checkpoint(ck, factors, lam, it, fit)
    with pytest.raises(CheckpointError):
        load_checkpoint(ck)
    # resume: falls back to the .bak generation, completes more sweeps
    out = cpd_als(tt, rank=3, opts=_opts(max_iterations=6),
                  checkpoint_path=ck, checkpoint_every=2)
    assert np.isfinite(float(out.fit))
    ev = resilience.run_report().events("checkpoint_recovery")
    assert len(ev) == 1


def test_resume_from_bak_when_primary_missing(tmp_path):
    """A crash between the writer's two renames can leave ONLY the
    .bak generation on disk; the resume must still find it instead of
    silently restarting from iteration 0."""
    import os

    tt = gen.fixture_tensor("med")
    ck = str(tmp_path / "ck.npz")
    a = cpd_als(tt, rank=3, opts=_opts(max_iterations=4),
                checkpoint_path=ck, checkpoint_every=2)
    # simulate the torn-rename crash: primary gone, .bak intact
    os.replace(ck, ck + ".bak")
    assert not os.path.exists(ck)
    b = cpd_als(tt, rank=3, opts=_opts(max_iterations=4),
                checkpoint_path=ck, checkpoint_every=2)
    # resumed at the checkpointed iteration -> same terminal model
    assert float(b.fit) == pytest.approx(float(a.fit), abs=1e-8)
    ev = resilience.run_report().events("checkpoint_recovery")
    assert len(ev) == 1 and "previous generation" in ev[0]["action"]


def test_checkpoint_write_fault_raises(tmp_path):
    ck = tmp_path / "ck.npz"
    with faults.inject("checkpoint_write", "runtime", times=1):
        with pytest.raises(RuntimeError, match="injected"):
            _mk_ckpt(ck)
    assert not ck.exists()


def test_distributed_resume_shares_hardened_path():
    """run_distributed_als resumes through load_checkpoint_resilient —
    the same corrupt-checkpoint degradation as the single-chip driver
    (source-level contract; the distributed sweep itself needs
    shard_map)."""
    import pathlib

    import splatt_tpu

    src = (pathlib.Path(splatt_tpu.__file__).parent / "parallel"
           / "common.py").read_text()
    assert "load_checkpoint_resilient" in src
