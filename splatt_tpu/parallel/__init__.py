from splatt_tpu.parallel.mesh import auto_grid, make_mesh
from splatt_tpu.parallel.sharded import sharded_cpd_als, sharded_mttkrp

__all__ = ["auto_grid", "make_mesh", "sharded_cpd_als", "sharded_mttkrp"]
