"""Bisect the fused_t Mosaic compile failure: compile fused_mttkrp_t
over growing largest-mode dims (each case in a subprocess with a hard
timeout so a wedged remote compile cannot eat the session).

Usage: python tools/fused_bisect.py            # run all cases
       python tools/fused_bisect.py CASE_JSON  # (internal) one case
"""
from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)


def one_case(spec):
    from splatt_tpu.utils.env import apply_env_platform

    apply_env_platform()
    import numpy as np

    from splatt_tpu.blocked import build_layout
    from splatt_tpu.coo import SparseTensor
    from splatt_tpu.ops.pallas_kernels import fused_mttkrp_t

    import jax.numpy as jnp

    dims = tuple(spec["dims"])
    nnz = spec["nnz"]
    block = spec["block"]
    rank = spec.get("rank", 50)
    rng = np.random.default_rng(0)
    inds = np.stack([rng.integers(0, d, nnz) for d in dims]).astype(np.int64)
    tt = SparseTensor(inds=inds, vals=rng.random(nnz), dims=dims)
    lay = build_layout(tt, 0, block=block, val_dtype=np.float32)
    fac = [jnp.asarray(rng.random((d, rank)), jnp.float32) for d in dims]
    t0 = time.perf_counter()
    fused_mttkrp_t.lower(lay, fac, mode=0, width=lay.seg_width,
                         accumulate=False, interpret=False).compile()
    return dict(ok=True, compile_s=round(time.perf_counter() - t0, 1),
                seg_width=lay.seg_width)


def main():
    from case_runner import run_cases, run_child

    if len(sys.argv) > 1:
        run_child(one_case, json.loads(sys.argv[1]))
        return

    cases = [
        dict(dims=(512, 384, 1024), nnz=200_000, block=4096),
        dict(dims=(1024, 768, 4096), nnz=500_000, block=4096),
        dict(dims=(2048, 1536, 8192), nnz=1_000_000, block=4096),
        dict(dims=(4096, 3072, 16384), nnz=1_000_000, block=4096),
        dict(dims=(12092, 9184, 28818), nnz=1_000_000, block=4096),
        dict(dims=(12092, 9184, 28818), nnz=20_000_000, block=4096),
    ]
    run_cases(os.path.abspath(__file__), cases,
              os.path.join(HERE, "fused_bisect.json"))


if __name__ == "__main__":
    main()
