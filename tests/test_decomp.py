"""Coarse / fine decomposition tests + the unified dispatch.

≙ correctness-under-decomposition: every decomposition type must give
the single-device answer for the same seed.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from splatt_tpu.config import Decomposition, Options, Verbosity
from splatt_tpu.cpd import cpd_als, init_factors
from splatt_tpu.parallel import (coarse_cpd_als, distributed_cpd_als,
                                 make_mesh, sharded_cpd_als)
from tests import gen


def _opts(**kw):
    kw.setdefault("random_seed", 42)
    kw.setdefault("verbosity", Verbosity.NONE)
    kw.setdefault("val_dtype", np.float64)
    return Options(**kw)


@pytest.fixture(scope="module")
def med_single():
    tt = gen.fixture_tensor("med")
    opts = _opts(max_iterations=6)
    init = init_factors(tt.dims, 5, opts.seed(), dtype=jnp.float64)
    return tt, opts, init, cpd_als(tt, rank=5, opts=opts, init=init)


def test_coarse_matches_single(med_single):
    tt, opts, init, single = med_single
    multi = coarse_cpd_als(tt, rank=5, mesh=make_mesh(n_devices=8,
                                                      axis_names=("d",)),
                           opts=opts, init=init)
    assert float(multi.fit) == pytest.approx(float(single.fit), abs=1e-8)
    for a, b in zip(single.factors, multi.factors):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_fine_custom_partition_matches_single(med_single):
    """A deliberately unbalanced user partition still gives the exact
    answer (≙ FINE with a partition file, p_rearrange_fine)."""
    tt, opts, init, single = med_single
    rng = np.random.default_rng(0)
    # skewed partition: device 0 gets ~half of everything
    part = np.where(rng.random(tt.nnz) < 0.5, 0,
                    rng.integers(0, 8, size=tt.nnz))
    multi = sharded_cpd_als(tt, rank=5, mesh=make_mesh(n_devices=8),
                            opts=opts, init=init, partition=part)
    assert float(multi.fit) == pytest.approx(float(single.fit), abs=1e-8)


def test_partition_out_of_range_raises():
    tt = gen.fixture_tensor("small")
    bad = np.full(tt.nnz, 99)
    with pytest.raises(ValueError):
        sharded_cpd_als(tt, rank=2, mesh=make_mesh(n_devices=4),
                        opts=_opts(max_iterations=2), partition=bad)


@pytest.mark.parametrize("decomp", list(Decomposition))
def test_dispatch_all_decompositions(med_single, decomp):
    tt, opts0, init, single = med_single
    opts = _opts(max_iterations=6, decomposition=decomp)
    multi = distributed_cpd_als(tt, rank=5, opts=opts, init=init)
    assert float(multi.fit) == pytest.approx(float(single.fit), abs=1e-8)


def test_dispatch_accepts_generic_mesh(med_single):
    """A plain make_mesh() mesh must work with every decomposition —
    MEDIUM re-arranges its devices into the grid, COARSE/FINE adopt its
    axis name."""
    tt, opts0, init, single = med_single
    generic = make_mesh()  # 1-D ('nnz',) over all 8 devices
    for decomp in Decomposition:
        opts = _opts(max_iterations=4, decomposition=decomp)
        out = distributed_cpd_als(tt, rank=5, opts=opts, init=init,
                                  mesh=generic)
        assert np.isfinite(float(out.fit)), decomp


def test_grid_uses_mesh_device_subset(med_single):
    """grid_cpd_als with a 4-device pool mesh sizes the grid to 4."""
    from splatt_tpu.parallel import grid_cpd_als

    tt, opts0, init, single = med_single
    pool = make_mesh(n_devices=4)
    out = grid_cpd_als(tt, rank=5, mesh=pool, opts=_opts(max_iterations=4),
                       init=init)
    assert np.isfinite(float(out.fit))


def test_multiaxis_mesh_rejected_for_1d_decomps():
    import jax
    from jax.sharding import Mesh

    tt = gen.fixture_tensor("small")
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh2d = Mesh(devs, ("a", "b"))
    with pytest.raises(ValueError, match="1-D mesh"):
        coarse_cpd_als(tt, rank=2, mesh=mesh2d, opts=_opts(max_iterations=2))


def test_partition_wrong_length_raises():
    tt = gen.fixture_tensor("small")
    with pytest.raises(ValueError, match="length"):
        sharded_cpd_als(tt, rank=2, mesh=make_mesh(n_devices=4),
                        opts=_opts(max_iterations=2),
                        partition=np.zeros(tt.nnz + 5, dtype=np.int64))


def test_zero_iterations_returns_init_shape():
    """max_iterations=0 must not crash (λ defaults to ones)."""
    tt = gen.fixture_tensor("small")
    out = sharded_cpd_als(tt, rank=2, mesh=make_mesh(n_devices=4),
                          opts=_opts(max_iterations=0))
    assert out.lam.shape == (2,)


def test_bucket_scatter_unit():
    from splatt_tpu.parallel.common import bucket_scatter

    inds = np.array([[0, 1, 2, 3, 4], [4, 3, 2, 1, 0]])
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0])
    owner = np.array([2, 0, 2, 0, 1])
    binds, bvals, C, counts = bucket_scatter(inds, vals, owner, 3,
                                             np.float64)
    assert C == 2
    np.testing.assert_array_equal(counts, [2, 1, 2])
    # bucket contents: owner order preserved (stable)
    np.testing.assert_allclose(sorted(bvals[0][bvals[0] != 0]), [2.0, 4.0])
    np.testing.assert_allclose(bvals[1][:1], [5.0])
    np.testing.assert_allclose(sorted(bvals[2]), [1.0, 3.0])
    # index columns travel with their values
    flat_v = bvals.ravel()
    flat_i0 = binds[0].ravel()
    for v, i0 in [(1.0, 0), (2.0, 1), (3.0, 2), (4.0, 3), (5.0, 4)]:
        slot = np.flatnonzero(np.isclose(flat_v, v))[0]
        assert flat_i0[slot] == i0


def test_bucket_scatter_empty_tensor():
    from splatt_tpu.parallel.common import bucket_scatter

    binds, bvals, C, counts = bucket_scatter(
        np.zeros((3, 0), dtype=np.int64), np.zeros(0),
        np.zeros(0, dtype=np.int64), 4, np.float64)
    assert binds.shape == (3, 4, 1) and bvals.shape == (4, 1) and C == 1
    np.testing.assert_array_equal(counts, np.zeros(4))


@pytest.mark.parametrize("name", ["med", "med4"])
def test_blocked_local_engine_matches_stream(name):
    """Every distributed sweep's blocked local MTTKRP engine (per-cell/
    per-shard sorted layouts through the single-chip dispatch,
    ≙ mttkrp_csf per rank, src/mpi/mpi_cpd.c:714) computes the same
    factors as the naive stream formulation — grid, sharded, coarse,
    and FINE with a partition."""
    from splatt_tpu.parallel.coarse import coarse_cpd_als as coarse
    from splatt_tpu.parallel.grid import grid_cpd_als as gridals
    from splatt_tpu.parallel.sharded import sharded_cpd_als as sharded

    tt = gen.fixture_tensor(name)
    opts = _opts(max_iterations=4)
    rng = np.random.default_rng(3)
    part = rng.integers(0, 8, tt.nnz)
    cases = [
        ("grid", lambda e: gridals(tt, 4, opts=opts, local_engine=e)),
        ("sharded", lambda e: sharded(tt, 4, opts=opts, local_engine=e)),
        ("coarse", lambda e: coarse(tt, 4, opts=opts, local_engine=e)),
        ("fine", lambda e: sharded(tt, 4, opts=opts, partition=part,
                                   local_engine=e)),
    ]
    for label, run in cases:
        a = run("stream")
        b = run("blocked")
        assert float(a.fit) == pytest.approx(float(b.fit), abs=1e-9), label
        for ua, ub in zip(a.factors, b.factors):
            np.testing.assert_allclose(np.asarray(ua), np.asarray(ub),
                                       atol=1e-8, err_msg=label)


@pytest.mark.parametrize("alloc", ["onemode", "twomode", "allmode"])
def test_blocked_engine_alloc_policies(alloc):
    """The distributed cell/shard layouts honor the alloc policy like
    the single-chip compiler (≙ splatt_csf_alloc): shared layouts run
    non-sorted modes through the generic scatter path, with identical
    results."""
    from splatt_tpu.config import BlockAlloc
    from splatt_tpu.parallel.grid import grid_cpd_als as gridals
    from splatt_tpu.parallel.sharded import sharded_cpd_als as sharded

    tt = gen.fixture_tensor("med")
    opts = _opts(max_iterations=4, block_alloc=BlockAlloc(alloc))
    for label, fn in (("grid", gridals), ("sharded", sharded)):
        a = fn(tt, 4, opts=opts, local_engine="stream")
        b = fn(tt, 4, opts=opts, local_engine="blocked")
        for ua, ub in zip(a.factors, b.factors):
            np.testing.assert_allclose(np.asarray(ua), np.asarray(ub),
                                       atol=1e-8,
                                       err_msg=f"{label}/{alloc}")


def test_blocked_buckets_contract():
    """Sentinel-padded tails, per-bucket sort, uniform shapes."""
    from splatt_tpu.parallel.common import blocked_buckets, bucket_scatter

    rng = np.random.default_rng(0)
    dims = (16, 12, 20)
    nnz = 300
    inds = np.stack([rng.integers(0, d, nnz) for d in dims]).astype(np.int64)
    vals = rng.random(nnz)
    owner = rng.integers(0, 3, nnz)
    binds, bvals, C, counts = bucket_scatter(inds, vals, owner, 3,
                                             np.float64)
    i, v, rs, blk, S = blocked_buckets(binds, bvals, counts, 1, dims[1],
                                       128)
    assert i.shape[0] == 3 and i.shape[1] == 3 and i.shape[2] % blk == 0
    for b in range(3):
        n = int(counts[b])
        row = i[1, b]
        assert (np.diff(row[:n]) >= 0).all()          # sorted
        assert (row[n:] == dims[1]).all()             # sentinel tail
        assert (v[b, n:] == 0).all()
        # values traveled with their coordinates
        assert np.isclose(sorted(v[b, :n]),
                          sorted(bvals[b, :int(counts[b])])).all()
    nb = i.shape[2] // blk
    assert rs.shape == (3, nb) and S % 8 == 0


@pytest.mark.parametrize("driver", ["grid", "fine_greedy", "coarse"])
def test_distributed_checkpoint_resume(tmp_path, driver):
    """Kill-and-resume reproduces the uninterrupted distributed fit and
    factors exactly (VERDICT r3 #5; exceeds the reference, whose
    mpi_write_mats only writes terminal outputs).  Checkpoints are in
    the original row space, so they survive relabeled placements
    (greedy row distribution)."""
    from splatt_tpu.parallel.coarse import coarse_cpd_als as coarse
    from splatt_tpu.parallel.grid import grid_cpd_als as gridals
    from splatt_tpu.parallel.sharded import sharded_cpd_als as sharded

    tt = gen.fixture_tensor("med")
    rng = np.random.default_rng(1)
    part = rng.integers(0, 8, tt.nnz)

    def run(iters, ck=None, resume=True):
        opts = _opts(max_iterations=iters, tolerance=0.0)
        kw = dict(opts=opts, checkpoint_path=ck, checkpoint_every=2,
                  resume=resume)
        if driver == "grid":
            return gridals(tt, 4, **kw)
        if driver == "coarse":
            return coarse(tt, 4, **kw)
        return sharded(tt, 4, partition=part, row_distribute="greedy",
                       **kw)

    full = run(6)
    ck = str(tmp_path / f"{driver}.npz")
    run(4, ck=ck)                      # "killed" mid-run (ckpt at it 2)
    resumed = run(6, ck=ck)            # resumes at it 2, finishes 6
    assert float(resumed.fit) == pytest.approx(float(full.fit), abs=1e-12)
    for a, b in zip(full.factors, resumed.factors):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-12, err_msg=driver)
    # a mismatched checkpoint is refused loudly
    with pytest.raises(ValueError, match="does not match"):
        opts = _opts(max_iterations=2)
        gridals(tt, 3, opts=opts, checkpoint_path=ck)


def test_distributed_final_checkpoint_is_current(tmp_path):
    """A completed (or converged) distributed run leaves the checkpoint
    at its LAST iteration, like the single-device driver — a later
    resume with a higher max_iterations must not redo work the result
    already contained (ADVICE r4)."""
    from splatt_tpu.cpd import load_checkpoint
    from splatt_tpu.parallel.grid import grid_cpd_als as gridals

    tt = gen.fixture_tensor("med")
    ck = str(tmp_path / "g.npz")
    opts = _opts(max_iterations=5, tolerance=0.0)
    res = gridals(tt, 4, opts=opts, checkpoint_path=ck, checkpoint_every=2)
    _, _, it, fit = load_checkpoint(ck)
    assert it == 5
    assert fit == pytest.approx(float(res.fit), abs=1e-12)
    # resuming with the same budget is a no-op that returns the same fit
    resumed = gridals(tt, 4, opts=opts, checkpoint_path=ck,
                      checkpoint_every=2)
    assert float(resumed.fit) == pytest.approx(float(res.fit), abs=1e-12)


def test_explicit_blocked_with_ring_rejected():
    """An explicit local_engine='blocked' under the POINT2POINT ring
    variant raises instead of silently downgrading to stream
    (ADVICE r4); auto-selection (None) quietly resolves to stream."""
    from splatt_tpu.config import CommPattern
    from splatt_tpu.parallel.sharded import sharded_cpd_als

    tt = gen.fixture_tensor("med")
    opts = _opts(max_iterations=2, comm_pattern=CommPattern.POINT2POINT)
    with pytest.raises(ValueError, match="ring"):
        sharded_cpd_als(tt, 4, opts=opts, local_engine="blocked")
    res = sharded_cpd_als(tt, 4, opts=opts)       # auto → stream, runs
    assert np.isfinite(float(res.fit))


def test_wrapper_passes_local_engine_through(tmp_path):
    """distributed_cpd_als must hand local_engine=None through to every
    driver so their memmapped auto-detection runs (ADVICE r4): a
    memmapped tensor through the public wrapper picks the streamed
    path for COARSE/FINE rather than an in-RAM blocked build."""
    from unittest import mock

    from splatt_tpu import io as tio
    from splatt_tpu.config import Decomposition
    from splatt_tpu.io import load_memmap
    from splatt_tpu.parallel import distributed_cpd_als

    tt = gen.fixture_tensor("med")
    path = str(tmp_path / "m.bin")
    tio.save(tt, path)
    mm = load_memmap(path)
    for dec, target in ((Decomposition.COARSE,
                         "splatt_tpu.parallel.coarse_cpd_als"),
                        (Decomposition.FINE,
                         "splatt_tpu.parallel.sharded_cpd_als")):
        opts = _opts(max_iterations=2, decomposition=dec)
        with mock.patch(target) as drv:
            distributed_cpd_als(mm, 4, opts=opts)
        assert drv.call_args.kwargs["local_engine"] is None, dec


def test_streamed_shard_and_coarse_builds_match(tmp_path):
    """The streamed (bounded-RSS, optionally disk-backed) FINE shard
    build and COARSE per-mode bucketing produce bit-identical arrays to
    the in-RAM builds, and a memmapped tensor runs the full distributed
    drivers end-to-end with the same fit (VERDICT r3 #4)."""
    from splatt_tpu import io as tio
    from splatt_tpu.io import load_memmap
    from splatt_tpu.parallel.coarse import _bucket_by_mode, coarse_cpd_als
    from splatt_tpu.parallel.sharded import shard_nnz_host, sharded_cpd_als

    tt = gen.fixture_tensor("med")
    path = str(tmp_path / "m.bin")
    tio.save(tt, path)
    mm = load_memmap(path)

    rng = np.random.default_rng(2)
    part = rng.integers(0, 8, tt.nnz)
    for p in (None, part):
        a_i, a_v = shard_nnz_host(tt, 8, np.float64, partition=p,
                                  streamed=False)
        for out_dir in (None, str(tmp_path / f"f{p is None}")):
            b_i, b_v = shard_nnz_host(mm, 8, np.float64, partition=p,
                                      streamed=True, out_dir=out_dir,
                                      chunk=97)  # awkward chunk size
            np.testing.assert_array_equal(a_i, np.asarray(b_i))
            np.testing.assert_array_equal(a_v, np.asarray(b_v))

    for m in range(tt.nmodes):
        a = _bucket_by_mode(tt, m, 8, np.float64, streamed=False)
        b = _bucket_by_mode(mm, m, 8, np.float64, streamed=True,
                            out_dir=str(tmp_path / f"c{m}"), chunk=61)
        np.testing.assert_array_equal(a[0], np.asarray(b[0]))
        np.testing.assert_array_equal(a[1], np.asarray(b[1]))
        assert a[2] == b[2]
        np.testing.assert_array_equal(a[3], b[3])

    # end-to-end: memmapped input auto-selects the streamed build +
    # stream engine and matches the in-RAM run exactly
    opts = _opts(max_iterations=3)
    for fn in (sharded_cpd_als, coarse_cpd_als):
        ram = fn(tt, 3, opts=opts)
        ooc = fn(mm, 3, opts=opts)
        assert float(ram.fit) == pytest.approx(float(ooc.fit), abs=1e-12)


@pytest.mark.parametrize("chunk", [7, 64, 10**6])
@pytest.mark.parametrize("disk", [False, True])
def test_streamed_blocked_buckets_bit_identical(tmp_path, chunk, disk):
    """The chunked counting-sort build (bounded RSS, optionally
    disk-backed) is BIT-identical to the in-RAM argsort build — same
    arrays, row_start, block, seg_width — across chunk sizes smaller
    and larger than any bucket, including empty buckets."""
    from splatt_tpu.parallel.common import (blocked_buckets, bucket_scatter,
                                            streamed_blocked_buckets)

    rng = np.random.default_rng(3)
    dims = (16, 12, 20)
    nnz = 500
    inds = np.stack([rng.integers(0, d, nnz) for d in dims]).astype(np.int64)
    vals = rng.random(nnz)
    owner = rng.integers(0, 4, nnz)
    owner[owner == 2] = 1                 # bucket 2 left empty
    binds, bvals, C, counts = bucket_scatter(inds, vals, owner, 4,
                                             np.float64)
    for mode in range(3):
        ref = blocked_buckets(binds, bvals, counts, mode, dims[mode], 128)
        out_dir = str(tmp_path / f"m{mode}c{chunk}") if disk else None
        got = streamed_blocked_buckets(binds, bvals, counts, mode,
                                       dims[mode], 128, out_dir=out_dir,
                                       chunk=chunk)
        np.testing.assert_array_equal(np.asarray(got[0]), ref[0])
        np.testing.assert_array_equal(np.asarray(got[1]), ref[1])
        np.testing.assert_array_equal(got[2], ref[2])
        assert got[3] == ref[3] and got[4] == ref[4]
        if disk:
            assert isinstance(got[0], np.memmap)
