"""Named wall-clock timer registry (≙ src/timer.{h,c}).

The reference keeps a global array of named timers with verbosity levels
gating which are reported (timers[TIMER_NTIMERS], src/timer.h:36-85;
report_times, src/timer.c:67-90).  Same idea here: a process-global
registry, `timers.start/stop(name)` brackets, and a leveled report.

JAX note: device work is asynchronous — wrap regions whose cost you want
attributed with ``block=True`` (calls ``block_until_ready`` on a token) or
time whole steps; fine-grained on-device attribution belongs to the JAX
profiler, not wall clocks.
"""

from __future__ import annotations

import time
from typing import Dict

# Report levels (≙ timer_lvl in src/timer.h): 0 none, 1 summary, 2 detail.
_DEFAULT_LEVELS = {
    "total": 1,
    "io": 1,
    "blocked_build": 1,   # ≙ TIMER_CSF
    "sort": 2,            # ≙ TIMER_SORT
    "cpd": 1,             # ≙ TIMER_CPD
    "mttkrp": 2,          # ≙ TIMER_MTTKRP
    "solve": 2,           # ≙ TIMER_INV
    "normalize": 2,       # ≙ TIMER_MATNORM
    "gram": 2,            # ≙ TIMER_ATA
    "fit": 2,             # ≙ TIMER_FIT
    "reorder": 2,         # ≙ TIMER_PART
    "bench": 1,
}


class Timer:
    __slots__ = ("name", "seconds", "_t0", "running", "level")

    def __init__(self, name: str, level: int = 2) -> None:
        self.name = name
        self.seconds = 0.0
        self._t0 = 0.0
        self.running = False
        self.level = level

    def start(self) -> None:
        if not self.running:
            self.running = True
            self._t0 = time.perf_counter()

    def stop(self) -> None:
        if self.running:
            self.seconds += time.perf_counter() - self._t0
            self.running = False

    def reset(self) -> None:
        self.seconds = 0.0
        self.running = False


class TimerRegistry:
    def __init__(self) -> None:
        self._timers: Dict[str, Timer] = {}
        for name, lvl in _DEFAULT_LEVELS.items():
            self._timers[name] = Timer(name, lvl)

    def get(self, name: str, level: int = 2) -> Timer:
        if name not in self._timers:
            self._timers[name] = Timer(name, level)
        return self._timers[name]

    def start(self, name: str) -> None:
        self.get(name).start()

    def stop(self, name: str) -> None:
        self.get(name).stop()

    def reset(self) -> None:
        for t in self._timers.values():
            t.reset()

    def __getitem__(self, name: str) -> float:
        return self.get(name).seconds

    class _Bracket:
        def __init__(self, timer: Timer) -> None:
            self.timer = timer

        def __enter__(self):
            self.timer.start()
            return self.timer

        def __exit__(self, *exc):
            self.timer.stop()
            return False

    def time(self, name: str) -> "TimerRegistry._Bracket":
        return self._Bracket(self.get(name))

    def report(self, level: int = 1) -> str:
        """≙ report_times (src/timer.c:67-90)."""
        lines = ["", "Timing information ---------------------------------"]
        for t in self._timers.values():
            if t.seconds > 0 and t.level <= level:
                lines.append(f"  {t.name + ':':<16s} {t.seconds:0.3f}s")
        return "\n".join(lines)


timers = TimerRegistry()
