"""The dtype-flow oracle (tools/splint/dtypecheck.py).

The oracle is the DYNAMIC plane of the SPL024/SPL028 accumulation
discipline: jax.eval_shape over the real factorization entry points
across the f32/bf16 storage matrix, one interpret-mode Pallas
execution, and a static-plane cross-check.  These tests prove (a) the
clean tree certifies, (b) every wired-in mutant is caught — the
oracle has teeth — and (c) the CLI contract CI scripts rely on.

Mutants run in SUBPROCESSES: they monkeypatch production modules and
jitted entry points may cache traces made under the patch, so a fresh
interpreter is the only honest way to run one.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from tools.splint.dtypecheck import (MUTANTS, _apply_mutant,  # noqa: E402
                                     run_dtype_check)


def test_clean_matrix_certifies():
    """The real tree passes the whole storage×compute matrix and the
    static numerics/tiling family agrees (zero findings)."""
    res = run_dtype_check()
    assert res.ok, [f"{v.scenario} [{v.storage}]: {v.detail}"
                    for v in res.violations]
    assert res.checks >= 29
    assert res.static_findings == {}


def test_unknown_mutant_rejected():
    with pytest.raises(ValueError):
        run_dtype_check(mutant="definitely_not_a_mutant")


def test_mutant_patches_are_restored():
    """_apply_mutant's undo puts the real functions back — a leaked
    patch would corrupt every later test in the process."""
    from splatt_tpu import config
    from splatt_tpu.ops import linalg

    before = (config.acc_dtype, linalg.gram, linalg.normalize_columns)
    for name in MUTANTS:
        _apply_mutant(name)()
    assert (config.acc_dtype, linalg.gram,
            linalg.normalize_columns) == before


@pytest.mark.parametrize("mutant", MUTANTS)
def test_each_mutant_is_caught(mutant):
    """Each wired-in dtype regression — the config promotion dropped,
    gram unpinned, the engines' local acc helper neutered, λ² summed
    narrow — must be caught, or the oracle is decorative.  Run in a
    subprocess: the jit caches must never see a mutant trace."""
    out = subprocess.run(
        [sys.executable, "-m", "tools.splint.dtypecheck",
         "--mutant", mutant],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "caught" in out.stdout


def test_cli_json_report():
    """`python -m tools.splint.dtypecheck --json` is the CI entry:
    exit 0 and a machine-readable certification on the clean tree."""
    out = subprocess.run(
        [sys.executable, "-m", "tools.splint.dtypecheck", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    rep = json.loads(out.stdout)
    assert rep["ok"] is True
    assert rep["violations"] == []
    assert rep["checks"] >= 29
    assert rep["static_findings"] == {}
