"""COO tensor unit tests (≙ tests/sptensor_test.c)."""

import numpy as np
import pytest

from splatt_tpu.coo import SparseTensor
from tests import gen


def test_basic_properties(any_tensor):
    tt = any_tensor
    assert tt.nnz > 0
    assert tt.nmodes == len(tt.dims)
    for m in range(tt.nmodes):
        assert tt.inds[m].min() >= 0
        assert tt.inds[m].max() < tt.dims[m]
    assert tt.normsq() == pytest.approx(np.sum(tt.vals ** 2))


def test_deduplicate_sums_values():
    ind = np.array([[0, 0, 1, 0], [1, 1, 2, 1], [2, 2, 0, 2]])
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    tt = SparseTensor(ind, vals, (2, 3, 3)).deduplicate()
    assert tt.nnz == 2
    dense = tt.to_dense()
    assert dense[0, 1, 2] == pytest.approx(7.0)
    assert dense[1, 2, 0] == pytest.approx(3.0)


def test_count_duplicates():
    ind = np.array([[0, 0, 1], [1, 1, 2], [2, 2, 0]])
    tt = SparseTensor(ind, np.ones(3), (2, 3, 3))
    assert tt.count_duplicates() == 1


def test_remove_empty_slices_indmap():
    # mode 0 uses only indices {1, 3} of dim 5
    ind = np.array([[1, 3, 3], [0, 1, 2], [0, 0, 1]])
    tt = SparseTensor(ind, np.arange(3, dtype=float), (5, 3, 2))
    out = tt.remove_empty_slices()
    assert out.dims == (2, 3, 2)
    assert out.indmaps[0].tolist() == [1, 3]
    assert out.indmaps[1] is None
    np.testing.assert_array_equal(out.inds[0], [0, 1, 1])
    # dense content preserved through the relabeling
    np.testing.assert_allclose(out.to_dense(),
                               tt.to_dense()[[1, 3], :, :])


def test_sort_lexicographic(any_tensor):
    tt = any_tensor.sorted_by(range(any_tensor.nmodes))
    keys = tt.inds
    for n in range(1, tt.nnz):
        a = tuple(keys[m, n - 1] for m in range(tt.nmodes))
        b = tuple(keys[m, n] for m in range(tt.nmodes))
        assert a <= b


def test_sort_preserves_content(any_tensor):
    tt = any_tensor
    perm_order = list(reversed(range(tt.nmodes)))
    out = tt.sorted_by(perm_order)
    np.testing.assert_allclose(out.to_dense(), tt.to_dense())


def test_unfold_matches_dense():
    tt = gen.fixture_tensor("small")
    dense = tt.to_dense()
    for mode in range(tt.nmodes):
        indptr, cols, vals, shape = tt.unfold(mode)
        mat = np.zeros(shape)
        for r in range(shape[0]):
            for k in range(indptr[r], indptr[r + 1]):
                mat[r, cols[k]] += vals[k]
        # build expected unfolding: mode first, remaining modes in order
        order = [mode] + [m for m in range(tt.nmodes) if m != mode]
        expected = np.transpose(dense, order).reshape(shape)
        np.testing.assert_allclose(mat, expected)


def test_permute_roundtrip(any_tensor):
    tt = any_tensor
    rng = np.random.default_rng(0)
    perms = [rng.permutation(d) for d in tt.dims]
    inv = [np.argsort(p) for p in perms]
    out = tt.permute(perms).permute(inv)
    np.testing.assert_array_equal(out.inds, tt.inds)


def test_mode_histogram(any_tensor):
    tt = any_tensor
    for m in range(tt.nmodes):
        hist = tt.mode_histogram(m)
        assert hist.sum() == tt.nnz
        assert hist.shape[0] == tt.dims[m]
