"""MTTKRP differential tests (≙ tests/mttkrp_test.c).

The reference's key idea: the trivially-correct streaming implementation
is the gold oracle, and every optimized configuration must match it
elementwise (tests/mttkrp_test.c:36-83, tolerance 1e-10 in double).  We go
one step further: the JAX stream path is itself checked against a pure
numpy brute-force, then the full config matrix (alloc policy × block size
× execution path) is checked against stream.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from splatt_tpu.blocked import BlockedSparse, build_layout
from splatt_tpu.config import BlockAlloc, Options
from splatt_tpu.ops.mttkrp import (mttkrp, mttkrp_blocked, mttkrp_stream,
                                   PATHS)
from tests import gen

TOL = 1e-10  # double-precision tolerance (≙ tests/mttkrp_test.c:25-30)
RANK = 16


def np_mttkrp(tt, factors, mode):
    """Independent numpy brute-force oracle."""
    prod = np.asarray(tt.vals)[:, None].astype(np.float64)
    for k, U in enumerate(factors):
        if k != mode:
            prod = prod * np.asarray(U)[tt.inds[k]]
    out = np.zeros((tt.dims[mode], prod.shape[1]))
    np.add.at(out, tt.inds[mode], prod)
    return out


def make_factors(dims, rank=RANK, seed=7):
    rng = np.random.default_rng(seed)
    return [jnp.asarray(rng.random((d, rank))) for d in dims]


def test_stream_matches_numpy(any_tensor):
    tt = any_tensor
    factors = make_factors(tt.dims)
    for mode in range(tt.nmodes):
        got = mttkrp_stream(jnp.asarray(tt.inds), jnp.asarray(tt.vals),
                            factors, mode, tt.dims[mode])
        np.testing.assert_allclose(np.asarray(got),
                                   np_mttkrp(tt, factors, mode), atol=TOL)


@pytest.mark.parametrize("alloc", list(BlockAlloc))
@pytest.mark.parametrize("block", [64, 256])
def test_blocked_config_matrix(any_tensor, alloc, block):
    """Every (alloc, block size, mode, auto-path) config matches the oracle.

    ≙ the ONEMODE/TWOMODE/ALLMODE × tiling × tile-level sweep of
    tests/mttkrp_test.c:168-259.
    """
    tt = any_tensor
    opts = Options(block_alloc=alloc, nnz_block=block,
                   val_dtype=np.float64)
    bs = BlockedSparse.from_coo(tt, opts)
    factors = make_factors(tt.dims)
    for mode in range(tt.nmodes):
        got = mttkrp(bs, factors, mode)
        np.testing.assert_allclose(np.asarray(got),
                                   np_mttkrp(tt, factors, mode), atol=TOL,
                                   err_msg=f"alloc={alloc} block={block} mode={mode}")


def test_mode_order_config_matrix(any_tensor):
    """alloc × mode-order sweep matches the oracle, and the secondary
    orderings actually differ (≙ csf_find_mode_order policies,
    src/csf.c:694-726, exercised by the config matrix of
    tests/mttkrp_test.c:168-259)."""
    from splatt_tpu.blocked import secondary_order
    from splatt_tpu.config import ModeOrder

    tt = any_tensor
    factors = make_factors(tt.dims)
    orders = [ModeOrder.SMALLFIRST, ModeOrder.BIGFIRST,
              ModeOrder.INORDER_MINUSONE]
    seen = set()
    for mo in orders:
        seen.add(tuple(secondary_order(tt.dims, 0, mo)))
        opts = Options(block_alloc=BlockAlloc.ALLMODE, nnz_block=128,
                       val_dtype=np.float64, mode_order=mo)
        bs = BlockedSparse.from_coo(tt, opts)
        for mode in range(tt.nmodes):
            got = mttkrp(bs, factors, mode)
            np.testing.assert_allclose(
                np.asarray(got), np_mttkrp(tt, factors, mode), atol=TOL,
                err_msg=f"mode_order={mo} mode={mode}")
    if len(set(tt.dims)) == tt.nmodes and tt.nmodes > 2:
        assert len(seen) > 1  # policies produce distinct layouts
    # CUSTOM: explicit permutation (reversed natural) + validation
    custom = tuple(range(tt.nmodes))[::-1]
    assert secondary_order(tt.dims, 0, ModeOrder.CUSTOM, custom) == \
        [m for m in custom if m != 0]
    with pytest.raises(ValueError):
        secondary_order(tt.dims, 0, ModeOrder.CUSTOM, None)
    with pytest.raises(ValueError):
        secondary_order(tt.dims, 0, ModeOrder.CUSTOM, (0, 1))


@pytest.mark.parametrize("path", ["sorted_onehot", "sorted_scatter",
                                  "privatized", "scatter"])
def test_forced_paths(any_tensor, path):
    """Each execution path individually matches the oracle on every mode
    where it applies (≙ per-traversal-variant testing)."""
    tt = any_tensor
    opts = Options(block_alloc=BlockAlloc.ALLMODE, nnz_block=128,
                   val_dtype=np.float64)
    bs = BlockedSparse.from_coo(tt, opts)
    factors = make_factors(tt.dims)
    for mode in range(tt.nmodes):
        if path in ("sorted_onehot", "sorted_scatter"):
            layout = bs.layout_for(mode)  # own-mode layout under ALLMODE
        else:
            # force a foreign layout so scatter/privatized are exercised
            other = (mode + 1) % tt.nmodes
            layout = bs.layout_for(other)
            if layout.mode == mode:
                continue
        got = mttkrp_blocked(layout, factors, mode, path=path)
        np.testing.assert_allclose(np.asarray(got),
                                   np_mttkrp(tt, factors, mode), atol=TOL,
                                   err_msg=f"path={path} mode={mode}")


def test_layout_structure(any_tensor):
    """Structural invariants (≙ tests/csf_test.c:31-60)."""
    tt = any_tensor
    for mode in range(tt.nmodes):
        lay = build_layout(tt, mode, block=64, val_dtype=np.float64)
        assert lay.nnz == tt.nnz
        assert lay.nnz_pad % lay.block == 0
        assert lay.seg_width % 8 == 0
        rows = np.asarray(lay.inds[mode])
        # sorted by output mode, sentinel padding at the end
        assert np.all(np.diff(rows) >= 0)
        assert np.all(rows[tt.nnz:] == tt.dims[mode])
        # row_start matches each block's first row
        rs = np.asarray(lay.row_start)
        np.testing.assert_array_equal(rs, rows.reshape(-1, lay.block)[:, 0])
        # values preserved (as multiset)
        np.testing.assert_allclose(np.sort(np.asarray(lay.vals[:tt.nnz])),
                                   np.sort(tt.vals))
        assert lay.storage_bytes() > 0


def test_mode_map_policies(any_tensor):
    tt = any_tensor
    for alloc, nlay in ((BlockAlloc.ONEMODE, 1),
                        (BlockAlloc.TWOMODE, min(2, tt.nmodes)),
                        (BlockAlloc.ALLMODE, tt.nmodes)):
        bs = BlockedSparse.from_coo(tt, Options(block_alloc=alloc,
                                                val_dtype=np.float64))
        assert len(bs.layouts) == nlay
        for m in range(tt.nmodes):
            assert 0 <= bs.mode_map[m] < nlay
        if alloc is BlockAlloc.ALLMODE:
            for m in range(tt.nmodes):
                assert bs.layout_for(m).mode == m


def test_float32_tolerance(any_tensor):
    """f32 device dtype matches at the reference's float tolerance 9e-3
    relative to magnitudes (tests/mttkrp_test.c:25-30)."""
    tt = any_tensor
    bs = BlockedSparse.from_coo(tt, Options(val_dtype=np.float32,
                                            nnz_block=256))
    factors32 = [f.astype(jnp.float32) for f in make_factors(tt.dims)]
    for mode in range(tt.nmodes):
        got = np.asarray(mttkrp(bs, factors32, mode))
        want = np_mttkrp(tt, factors32, mode)
        np.testing.assert_allclose(got, want, rtol=9e-3, atol=9e-3)


def test_layout_rejects_dims_beyond_int32():
    """Device indices are int32 (the sentinel is `dim` itself); layouts
    must fail loudly instead of wrapping in the cast (VERDICT r2 #9)."""
    import pytest

    from splatt_tpu.blocked import build_layout
    from splatt_tpu.coo import SparseTensor

    big = 2**31 - 1
    tt = SparseTensor(inds=np.array([[0], [1], [2]], dtype=np.int64),
                      vals=np.ones(1), dims=(4, 5, big))
    with pytest.raises(ValueError, match="int32"):
        build_layout(tt, 0, block=128)
