"""Worker for the two-process multi-host test (≙ the reference's
`mpirun -np N test_mpi` pattern, scripts/mpi_test.sh — multi-process
correctness checked on one machine).

Invoked as:  python tests/multihost_worker.py <process_id> <nprocs>
                <coordinator> <decomp> <out.npz>

Each process joins the jax.distributed process group (CPU backend, 2
virtual devices per process), runs distributed_cpd_als on the same
deterministically generated tensor, and writes its gathered factors +
fit; the parent test asserts both processes agree with the
single-process ground truth (device-count invariance across *process*
counts, ≙ mpi_mat_rand's rank-count invariance, src/splatt_mpi.h:368-386).
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def main():
    pid, nprocs = int(sys.argv[1]), int(sys.argv[2])
    coordinator, decomp, out_path = sys.argv[3], sys.argv[4], sys.argv[5]
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=nprocs, process_id=pid)

    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from splatt_tpu.config import Decomposition, Options, Verbosity
    from splatt_tpu.coo import SparseTensor
    from splatt_tpu.parallel import distributed_cpd_als

    rng = np.random.default_rng(17)
    dims = (24, 18, 30)
    nnz = 800
    inds = np.stack([rng.integers(0, d, nnz) for d in dims]).astype(np.int64)
    tt = SparseTensor(inds=inds, vals=rng.random(nnz), dims=dims)

    opts = Options(random_seed=5, verbosity=Verbosity.NONE,
                   max_iterations=8, tolerance=0.0,
                   val_dtype=np.float64,
                   decomposition=Decomposition(decomp))
    # checkpoint every 3 its: exercises the multi-controller save path
    # (the gather is a collective every process must enter; only
    # process 0 writes) — a wrong guard deadlocks at iteration 3
    ck = os.path.join(os.path.dirname(out_path), "mh_ck.npz")
    out = distributed_cpd_als(tt, rank=4, opts=opts,
                              checkpoint_path=ck, checkpoint_every=3,
                              resume=False)
    np.savez(out_path,
             fit=float(out.fit),
             lam=np.asarray(out.lam, dtype=np.float64),
             **{f"f{m}": np.asarray(out.factors[m], dtype=np.float64)
                for m in range(tt.nmodes)})
    print(f"worker {pid}: fit={float(out.fit):.6f}", flush=True)


if __name__ == "__main__":
    main()
