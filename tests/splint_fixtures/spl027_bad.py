"""SPL027 bad: the strict-match comparator skips a declared match
field (nnz_block) and compares a field the schema never declared
(engine) — the silent mis-dispatch drift class."""

PLAN_CACHE_VERSION = 2

PLAN_SCHEMA = {
    "version": 2,
    "key": ("dims", "nnz"),
    "fields": ("path", "nnz_block", "sec"),
    "match": ("path", "nnz_block"),
    "exempt": ("sec",),
}
# v2: nnz_block joined the measured configuration


class TunedPlan:
    path: str
    nnz_block: int
    sec: float


def plan_key(dims, nnz):
    return f"{dims}|{nnz}"


def cached_plan(key):
    return None


def _tuned_plan_for(layout, path):
    plan = cached_plan(plan_key(layout.dims, layout.nnz))
    if plan is None or plan.path != path or plan.sec <= 0.0 \
            or plan.engine != "stream":
        # nnz_block is stored and declared match, but never compared:
        # a plan measured at block 4096 steers a 16384 dispatch
        return None
    return plan
