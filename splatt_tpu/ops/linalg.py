"""Dense linear algebra for CPD-ALS (≙ src/matrix.c, src/splatt_lapack.h).

All rank×rank / dim×rank dense math lowers to XLA (MXU):
- :func:`gram`             ≙ mat_aTa          (src/matrix.c:414-455, BLAS syrk)
- :func:`form_normal_lhs`  ≙ p_form_gram      (src/matrix.c:29-83)
- :func:`solve_normals`    ≙ mat_solve_normals (src/matrix.c:529-606,
                             LAPACK potrf/potrs with gelss SVD fallback)
- :func:`normalize_columns` ≙ p_mat_2norm/p_mat_maxnorm (src/matrix.c:87-205)

The SPD-fallback is branchless: we always compute both the Cholesky solve
and a pseudoinverse (lstsq-style, via eigendecomposition) solve and select
per-call with ``jnp.where`` on NaN detection — data-dependent control flow
is hostile to XLA; two rank³ solves at rank ≤ a few hundred are noise.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp


def gram(U: jax.Array) -> jax.Array:
    """UᵀU (rank×rank Gram matrix; ≙ mat_aTa).

    The reference only fills the upper triangle then mirrors; XLA emits a
    full syrk-like matmul on the MXU either way.  Low-precision factors
    (bf16/f16) accumulate in f32 — Gram matrices feed the normal
    equations and cannot afford bf16 accumulation error.
    """
    from splatt_tpu.config import acc_dtype
    from splatt_tpu.ops.mttkrp import mxu_precision

    return jnp.matmul(U.T, U, preferred_element_type=acc_dtype(U.dtype),
                      precision=mxu_precision(U.dtype))


def form_normal_lhs(grams: Sequence[jax.Array], mode: int,
                    regularization: float = 0.0) -> jax.Array:
    """Hadamard product of all Grams except `mode`, + λI (≙ p_form_gram)."""
    rank = grams[0].shape[0]
    out = jnp.ones((rank, rank), dtype=grams[0].dtype)
    for m, g in enumerate(grams):
        if m != mode:
            out = out * g
    if regularization != 0.0:
        out = out + regularization * jnp.eye(rank, dtype=out.dtype)
    return out


def solve_normals(lhs: jax.Array, rhs: jax.Array) -> jax.Array:
    """Solve ``X · lhs = rhs`` for X (rows = factor rows; ≙ mat_solve_normals).

    lhs is the rank×rank normal-equations matrix (symmetric PSD), rhs the
    (dim, rank) MTTKRP result.  Primary path: Cholesky.  If lhs is not
    SPD (rank-deficient factors), fall back to a least-squares solve via
    symmetric eigendecomposition pseudoinverse (≙ the LAPACK gelss
    fallback, src/matrix.c:554-603) — selected branchlessly.
    """
    chol = jax.scipy.linalg.cho_factor(lhs, lower=True)
    x_chol = jax.scipy.linalg.cho_solve(chol, rhs.T).T

    # Pseudoinverse fallback via eigh (lhs symmetric): pinv = V diag(1/w) Vᵀ.
    # eigh doubles as the SPD detector — LAPACK potrf's failure (info > 0)
    # is not observable through jax, and a failed factorization can return
    # finite garbage, so NaN-scanning x_chol is not sufficient.
    # Cutoff at √eps·‖w‖: normal equations square the condition number, so
    # eigenvalues below √eps·max|w| carry no information; eps-level cutoffs
    # keep eigh noise and blow the solve up.
    from splatt_tpu.ops.mttkrp import mxu_precision

    from splatt_tpu.config import acc_dtype

    prec = mxu_precision(lhs.dtype)
    acc = acc_dtype(rhs.dtype)
    w, v = jnp.linalg.eigh(lhs)
    tol = jnp.sqrt(jnp.finfo(lhs.dtype).eps) * jnp.max(jnp.abs(w))
    w_inv = jnp.where(jnp.abs(w) > tol, 1.0 / w, 0.0)
    x_pinv = jnp.matmul(jnp.matmul(rhs, v * w_inv, precision=prec,
                                   preferred_element_type=acc), v.T,
                        precision=prec, preferred_element_type=acc)

    spd = (jnp.min(w) > tol) & jnp.all(jnp.isfinite(x_chol))
    return jnp.where(spd, x_chol, x_pinv)


@partial(jax.jit, static_argnames=("which",))
def normalize_columns(U: jax.Array, which: str = "2") -> tuple[jax.Array, jax.Array]:
    """Normalize columns, returning (normalized U, λ).

    which="2": 2-norm (used on ALS iteration 0); which="max": max-norm with
    a floor of 1 so λ never shrinks columns (≙ p_mat_2norm / p_mat_maxnorm,
    src/matrix.c:87-205).  The max-norm is the *signed* max like the
    reference (p_mat_maxnorm accumulates SS_MAX over raw vals from 0,
    then clamps to >= 1, src/matrix.c:164-194) — a column whose entries
    are all negative gets λ=1, keeping iteration trajectories comparable
    bit-for-bit with reference runs.
    """
    from splatt_tpu.config import acc_dtype

    if which == "2":
        # upcast-before-reduce: a bf16 column's squared norm loses
        # mass accumulated at 8 mantissa bits — one pinned contraction
        # accumulates wide without materializing U*U (SPL024)
        lam = jnp.sqrt(jnp.einsum("dr,dr->r", U, U,
                                  preferred_element_type=acc_dtype(U.dtype)))
    elif which == "max":
        lam = jnp.maximum(jnp.max(U, axis=0), 1.0)
    else:
        raise ValueError(f"unknown norm {which!r}")
    safe = jnp.where(lam > 0, lam, 1.0)
    return U / safe.astype(U.dtype), lam
