"""Version queries (≙ reference include/splatt/api_version.h:47-61)."""

version_major = 0
version_minor = 5
version_patch = 0

__version__ = f"{version_major}.{version_minor}.{version_patch}"
