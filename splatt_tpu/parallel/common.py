"""Shared machinery for the distributed CPD drivers.

- :func:`bucket_scatter` — the owner-bucketing scatter used by every
  decomposition's host compiler (≙ the rearrange-to-owners steps of
  src/mpi/mpi_io.c): place nonzero n in bucket owner[n], pad buckets to
  the largest, return dense (nmodes, nbuckets, C) arrays.
- :func:`run_distributed_als` — the iterate/converge/post-process loop
  shared by the fine/medium/coarse drivers (≙ the outer loop of
  mpi_cpd_als_iterate + cpd_post_process).
"""

from __future__ import annotations

import time
from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from splatt_tpu.config import Options, Verbosity
from splatt_tpu.cpd import _fit
from splatt_tpu.kruskal import KruskalTensor, post_process


def bucket_scatter(inds: np.ndarray, vals: np.ndarray, owner: np.ndarray,
                   nbuckets: int, val_dtype
                   ) -> Tuple[np.ndarray, np.ndarray, int, np.ndarray]:
    """Scatter nonzeros into equally-padded buckets by owner id.

    Returns (binds (nmodes, nbuckets, C) int32, bvals (nbuckets, C), C,
    counts (nbuckets,) — true occupancy per bucket).
    Pad slots hold index 0 / value 0 (harmless to every kernel).
    """
    nmodes, nnz = inds.shape
    owner = np.asarray(owner, dtype=np.int64)
    if owner.shape[0] != nnz:
        raise ValueError(
            f"partition/owner length {owner.shape[0]} != nnz {nnz}")
    if nnz == 0:
        return (np.zeros((nmodes, nbuckets, 1), dtype=np.int32),
                np.zeros((nbuckets, 1), dtype=val_dtype), 1,
                np.zeros(nbuckets, dtype=np.int64))
    if owner.min() < 0 or owner.max() >= nbuckets:
        raise ValueError(f"owner ids must lie in [0, {nbuckets})")
    counts = np.bincount(owner, minlength=nbuckets)
    C = max(int(counts.max()), 1)
    order = np.argsort(owner, kind="stable")
    offsets = np.zeros(nbuckets + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    slot = np.arange(nnz) - offsets[owner[order]]
    flat = owner[order] * C + slot
    binds = np.zeros((nmodes, nbuckets * C), dtype=np.int32)
    for m in range(nmodes):
        binds[m, flat] = inds[m][order]
    bvals = np.zeros(nbuckets * C, dtype=val_dtype)
    bvals[flat] = vals[order]
    return (binds.reshape(nmodes, nbuckets, C), bvals.reshape(nbuckets, C),
            C, counts)


def balanced_relabel(hist: np.ndarray, nparts: int, cap: int) -> np.ndarray:
    """nnz-balanced row→label map for equal-width fences.

    ≙ the reference's nnz-balanced layer boundary search
    (p_find_layer_boundaries, src/mpi/mpi_io.c:365-439).  The TPU grid
    needs *equal-width* fences for static shapes, so instead of moving
    the boundaries we move the rows: a capacity-constrained LPT bin
    packing assigns rows (heaviest first) to the least-loaded fence with
    free slots, then labels fence p's rows ``p*cap .. p*cap+count_p-1``.
    Underfull fences leave empty labels inside their own span — exactly
    the padding rows the grid already carries.

    Args: hist (dim,) per-row nnz counts; nparts fences of cap labels
    each (nparts*cap >= dim).  Returns (dim,) int64 old→new labels in
    [0, nparts*cap).
    """
    import heapq

    dim = int(hist.shape[0])
    if nparts * cap < dim:
        raise ValueError(f"{nparts} fences x {cap} labels < {dim} rows")
    order = np.argsort(-hist, kind="stable")
    counts = np.zeros(nparts, dtype=np.int64)
    part_of = np.empty(dim, dtype=np.int64)
    heap = [(0, p) for p in range(nparts)]
    for r in order:
        load, p = heapq.heappop(heap)
        part_of[r] = p
        counts[p] += 1
        if counts[p] < cap:  # full fences never return to the heap
            heapq.heappush(heap, (load + int(hist[r]), p))
    # fence p's rows keep their relative order within the fence
    by_part = np.lexsort((np.arange(dim), part_of))
    starts = np.zeros(nparts, dtype=np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    part_sorted = part_of[by_part]
    slot = np.arange(dim) - starts[part_sorted]
    relabel = np.empty(dim, dtype=np.int64)
    relabel[by_part] = part_sorted * cap + slot
    return relabel


def mode_update_tail(M_l, grams_l, m: int, reg: float, first_flag,
                     lam_axis, store_dtype=None):
    """Shared per-mode ALS tail: normal-equations solve on the local
    block, normalization with the λ allreduce over `lam_axis`
    (≙ mat_normalize src/matrix.c:117-187), and the Gram allreduce
    (≙ mat_aTa src/matrix.c:445-452).  Used by every distributed sweep.

    `store_dtype` keeps mixed precision consistent with the
    single-device driver: the factor is stored back in its (possibly
    bf16) dtype while solve/normalize/Gram run at accumulator width.
    """
    from splatt_tpu.ops.linalg import form_normal_lhs, gram as gram_fn, \
        solve_normals

    lhs = form_normal_lhs(grams_l, m, reg)
    U_l = solve_normals(lhs, M_l)
    lam_2 = jnp.sqrt(jax.lax.psum(jnp.sum(U_l * U_l, axis=0), lam_axis))
    # signed max clamped at 1, matching normalize_columns and the
    # reference's p_mat_maxnorm (src/matrix.c:164-194 — no fabs)
    lam_max = jnp.maximum(
        jax.lax.pmax(jnp.max(U_l, axis=0), lam_axis), 1.0)
    lam = jnp.where(first_flag > 0, lam_2, lam_max)
    U_l = U_l / jnp.where(lam > 0, lam, 1.0)
    if store_dtype is not None:
        U_l = U_l.astype(store_dtype)
    gram = jax.lax.psum(gram_fn(U_l), lam_axis)
    return U_l, gram, lam


def fit_tail(lam, grams_l, M_l, U_last, inner_axis):
    """Shared fit pieces: ⟨Z,Z⟩ from λ/Grams and ⟨X,Z⟩ from the last
    mode's MTTKRP block (≙ p_calc_fit + fit allreduce, mpi_cpd.c:92-98)."""
    had = jnp.outer(lam, lam)
    for g in grams_l:
        had = had * g
    znormsq = jnp.sum(had)
    inner = jax.lax.psum(jnp.sum(M_l * U_last * lam[None, :]), inner_axis)
    return znormsq, inner


def run_distributed_als(step: Callable, factors, grams, rank: int,
                        opts: Options, xnormsq: float,
                        dims: Sequence[int], dtype,
                        row_select=None) -> KruskalTensor:
    """Host convergence loop + post-processing for a distributed sweep.

    `step(factors, grams, first_flag) -> (factors, grams, lam, znormsq,
    inner)`; factors come back sharded, are gathered, stripped of row
    padding, and renormalized into λ (≙ cpd_post_process).
    `row_select[m]`, when given, is a (dim_m,) index array mapping the
    gathered padded factor back to original row order (the inverse of a
    balanced-fence relabeling).
    """
    fit_prev = 0.0
    lam = jnp.ones((rank,), dtype=dtype)
    for it in range(opts.max_iterations):
        t0 = time.perf_counter()
        flag = jnp.asarray(1.0 if it == 0 else 0.0, dtype=dtype)
        factors, grams, lam, znormsq, inner = step(factors, grams, flag)
        fitval = float(_fit(xnormsq, znormsq, inner))
        if opts.verbosity >= Verbosity.LOW:
            print(f"  its = {it + 1:3d} ({time.perf_counter() - t0:.3f}s)"
                  f"  fit = {fitval:0.5f}  delta = {fitval - fit_prev:+0.4e}")
        if it > 0 and abs(fitval - fit_prev) < opts.tolerance:
            fit_prev = fitval
            break
        fit_prev = fitval

    gathered = [_gather_global(U) for U in factors]
    if row_select is not None:
        gathered = [U if sel is None else jnp.asarray(np.asarray(U)[sel])
                    for U, sel in zip(gathered, row_select)]
    return post_process(gathered, lam,
                        jnp.asarray(fit_prev, dtype=dtype), dims=dims)


def _gather_global(U):
    """Bring a (possibly cross-host) sharded factor to this host.

    device_get cannot fetch shards on non-addressable devices; in a
    multi-controller program every process allgathers instead."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(U)
    return jax.device_get(U)
