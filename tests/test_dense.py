"""Dense-mode MTTKRP (docs/dense.md).

Contract under test:

- **bit parity**: the dense tile layout is a re-encoding, not a
  different computation — ``dense_mttkrp`` (XLA reference) matches the
  sparse engines within f32 accumulation tolerance on every mode, the
  interpret-mode ``fused_dense`` Pallas kernel is BIT-IDENTICAL to the
  XLA reference, and a full CPD over a hybrid (dense + sparse) build
  matches the all-sparse run, donated sweep on or off;
- **verdict**: the dense/sparse decision thresholds the PADDED density
  (the blowup the tiling actually pays), keeps a feasibility floor
  even when forced, and SPLATT_DENSE defaults off;
- **resilient build**: a failed dense tiling (the ``format.dense``
  fault site, an infeasible geometry, a blowup past the cap) degrades
  CLASSIFIED to the sparse encoding — a ``format_fallback`` event with
  ``site="dense"``, never a failed build;
- **tuner integration**: dense layouts are measured candidates, a
  path="dense" winner is persisted under the mode-density regime key
  and retrieved at dispatch, the strict match means a dense plan never
  steers a sparse layout (and vice versa), and demotions are scoped to
  the ``:dn`` shape keys — a dense-engine OOM never demotes the sparse
  path;
- **zero index bytes**: the encoded-bytes model charges a dense mode
  value tiles + pad mask ONLY (``index_bytes() == 0``), and the flop
  model + roofline verdict classify the dense path on CPU;
- **registries**: the env vars / fault site / run-report events are
  declared (splint SPL006/SPL007/SPL012 stay at zero).
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

import splatt_tpu.tune as tune
from splatt_tpu import resilience
from splatt_tpu.bench_algs import (mttkrp_bytes_encoded, mttkrp_decode_bytes,
                                   mttkrp_flops, roofline_verdict)
from splatt_tpu.blocked import (DENSE_BLOWUP_CAP, BlockedSparse,
                                DenseModeLayout, build_dense_layout,
                                build_layout, dense_mode_verdict,
                                dense_tile_geometry, densify_layout,
                                mode_density, mode_density_bucket,
                                padded_mode_density)
from splatt_tpu.config import BlockAlloc, Options, Verbosity
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import cpd_als, init_factors
from splatt_tpu.ops.mttkrp import (_DEADLINE_ARMED, _engine_shape_key,
                                   _tuned_plan_for, choose_path,
                                   dense_mttkrp, engine_chain,
                                   mttkrp_blocked)
from splatt_tpu.ops.pallas_kernels import dense_vmem_ok, fused_dense
from splatt_tpu.stats import density_stats, density_stats_text
from splatt_tpu.utils import faults
from tests import gen


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    monkeypatch.setenv(tune._CACHE_ENV, str(tmp_path / "tune_cache.json"))
    monkeypatch.delenv("SPLATT_DENSE", raising=False)
    monkeypatch.delenv("SPLATT_DENSE_THRESHOLD", raising=False)
    tune.reset_memo()
    resilience.reset_demotions()
    resilience.run_report().clear()
    _DEADLINE_ARMED.clear()
    yield
    tune.reset_memo()
    resilience.reset_demotions()
    resilience.run_report().clear()
    _DEADLINE_ARMED.clear()
    faults.reset()


def _dense_tensor(seed=3, nnz=4000, dims=(16, 32, 32)):
    """A genuinely dense-ish tensor: ~24% raw fill, ~6% PADDED fill —
    above the default 5% dense verdict threshold on every mode, unique
    coordinates (so dense placement vs sparse scatter-add agree to the
    last accumulation)."""
    rng = np.random.default_rng(seed)
    total = int(np.prod(dims))
    lin = rng.choice(total, size=nnz, replace=False)
    inds = np.stack(np.unravel_index(lin, dims)).astype(np.int64)
    vals = rng.random(nnz) + 0.1
    return SparseTensor(inds, vals, dims)


def _sparse_tensor():
    return gen.fixture_tensor("med")


def _opts(**kw):
    kw.setdefault("random_seed", 42)
    kw.setdefault("verbosity", Verbosity.NONE)
    kw.setdefault("use_pallas", False)
    kw.setdefault("autotune", False)
    return Options(**kw)


# -- geometry / metrics ------------------------------------------------------

def test_geometry_and_storage_accounting():
    """The tile geometry is derived (never stored), pads the inner dim
    to the 128-lane tile, and the layout's storage model carries ZERO
    index bytes — the point of the format."""
    tt = _dense_tensor()
    geo = dense_tile_geometry(tt.dims, 0)
    assert geo.others == (1, 2) and geo.inner == 2
    assert geo.inner_pad == 128 and geo.n_outer == 32
    assert geo.tile == 16 and geo.ntiles == 1
    assert geo.span == 32 * 128 and geo.cells == 16 * geo.span
    lay = build_dense_layout(tt, 0)
    assert isinstance(lay, DenseModeLayout)
    assert lay.tiles.shape == (geo.ntiles, geo.tile, geo.span)
    assert lay.mask.shape == (geo.span,)
    assert lay.index_bytes() == 0
    assert lay.storage_bytes() == lay.value_bytes() + geo.span
    assert lay.encoding == "dense" and lay.idx_width == "dense"
    assert lay.block == geo.tile and lay.skew == ""
    # every nonzero landed exactly once (unique coords): total mass
    np.testing.assert_allclose(float(jnp.sum(lay.tiles)),
                               float(np.sum(tt.vals)), rtol=1e-6)
    # pad columns really are masked out
    assert not bool(np.asarray(lay.mask).all())
    assert int(np.asarray(lay.mask).sum()) == 32 * 32
    assert "dense" in lay.format_desc() and "tile=16x4096" in repr(lay)


def test_density_metrics_and_bucket():
    tt = _dense_tensor()
    d = mode_density(tt.dims, 0, tt.nnz)
    pd = padded_mode_density(tt.dims, 0, tt.nnz)
    assert d == pytest.approx(4000 / 16384)
    assert pd == pytest.approx(4000 / 65536)
    assert pd < d  # padding makes the unfolding look sparser
    assert mode_density_bucket(tt.dims, 0, tt.nnz) == "dn5"
    # below ~3% the bucket is empty: legacy plan keys stay byte-identical
    assert mode_density_bucket(tt.dims, 0, 1000) == ""
    assert mode_density_bucket((2,), 0, 10) == ""  # infeasible geometry


def test_verdict_threshold_boundaries_and_caps():
    """The verdict thresholds PADDED density (>=), the blowup cap is a
    feasibility floor even under force, and degenerate tensors never
    qualify."""
    tt = _dense_tensor()
    pd = padded_mode_density(tt.dims, 0, tt.nnz)
    assert dense_mode_verdict(tt.dims, 0, tt.nnz, threshold=pd)
    assert not dense_mode_verdict(tt.dims, 0, tt.nnz, threshold=pd * 1.01)
    # blowup cap: 10 nonzeros in 65536 padded cells is past 64x even
    # when the policy forces dense
    assert not dense_mode_verdict(tt.dims, 0, 10, threshold=1e-9)
    assert not dense_mode_verdict(tt.dims, 0, 10, threshold=1e-9,
                                  force=True)
    # force skips the threshold but keeps the feasibility floor
    nnz_floor = (16 * 32 * 128) // DENSE_BLOWUP_CAP
    assert dense_mode_verdict(tt.dims, 0, nnz_floor, threshold=0.99,
                              force=True)
    assert not dense_mode_verdict(tt.dims, 0, nnz_floor, threshold=0.99)
    assert not dense_mode_verdict(tt.dims, 0, 0, threshold=1e-9, force=True)
    assert not dense_mode_verdict((7,), 0, 5, threshold=1e-9, force=True)


def test_build_dense_layout_raises_past_cap():
    tt = _dense_tensor()
    tiny = SparseTensor(tt.inds[:, :10], np.asarray(tt.vals)[:10], tt.dims)
    with pytest.raises(ValueError, match="blowup"):
        build_dense_layout(tiny, 0)


# -- bit parity --------------------------------------------------------------

def test_dense_vs_sparse_parity_all_modes():
    """dense_mttkrp equals the sparse engines on every mode within f32
    accumulation tolerance (same scatter-add semantics, different
    summation order)."""
    tt = _dense_tensor()
    facs = init_factors(tt.dims, 5, 7, dtype=jnp.float32)
    for mode in range(tt.nmodes):
        dl = build_dense_layout(tt, mode)
        sl = build_layout(tt, mode, block=1024, val_dtype=np.float32,
                          dense=False)
        ref = np.asarray(mttkrp_blocked(sl, facs, mode,
                                        path="sorted_onehot", impl="xla",
                                        autotune=False))
        out = np.asarray(dense_mttkrp(dl, facs, mode))
        assert out.shape == ref.shape == (tt.dims[mode], 5)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4,
                                   err_msg=f"mode {mode}")


def test_fused_dense_interpret_bit_identical_to_xla():
    """The Pallas kernel in interpret mode is BIT-IDENTICAL to the XLA
    reference: same operands, same (span, R) KR product, one
    dot_general over span per row tile at the same precision."""
    tt = _dense_tensor()
    for dtype in (jnp.float32, jnp.float64):
        facs = init_factors(tt.dims, 4, 2, dtype=dtype)
        for mode in range(tt.nmodes):
            dl = build_dense_layout(tt, mode)
            a = np.asarray(dense_mttkrp(dl, facs, mode))
            b = np.asarray(fused_dense(dl, facs, mode, interpret=True))
            np.testing.assert_array_equal(a, b,
                                          err_msg=f"{dtype}/{mode}")


def test_dispatched_dense_path_and_evidence():
    """mttkrp_blocked routes a dense layout through the dense chain
    (both impls), matches the reference exactly, and records the
    dense_dispatch evidence event at first (compile-bearing) dispatch."""
    tt = _dense_tensor()
    facs = init_factors(tt.dims, 4, 5, dtype=jnp.float32)
    dl = build_dense_layout(tt, 0)
    ref = np.asarray(dense_mttkrp(dl, facs, 0))
    for impl in ("xla", "pallas_interpret"):
        out = np.asarray(mttkrp_blocked(dl, facs, 0, path="dense",
                                        impl=impl, autotune=False))
        np.testing.assert_array_equal(out, ref, err_msg=impl)
    evs = resilience.run_report().events("dense_dispatch")
    assert evs, "first dense dispatch must leave evidence"
    engines = {e["engine"] for e in evs}
    assert "dense_xla" in engines
    for e in evs:
        assert e["mode"] == 0 and e["tile"] == dl.tile
        assert e["span"] == dl.span and e["density_bucket"] == "dn5"
    # once per (engine, shape): a warm dispatch adds nothing
    mttkrp_blocked(dl, facs, 0, path="dense", impl="xla", autotune=False)
    assert len(resilience.run_report().events("dense_dispatch")) == len(evs)


def test_chain_and_path_choice():
    tt = _dense_tensor()
    facs = init_factors(tt.dims, 4, 5, dtype=jnp.float32)
    dl = build_dense_layout(tt, 0)
    assert choose_path(dl, 0, _opts()) == "dense"
    assert engine_chain(dl, facs, 0, impl="xla") == ["dense_xla"]
    assert dense_vmem_ok(dl, facs, 0)
    chain = engine_chain(dl, facs, 0, impl="pallas_interpret")
    assert chain == ["fused_dense", "dense_xla"]
    # the layout's encoding overrides the sparse path default: a caller
    # that skips choose_path still lands on the dense matmul
    got = np.asarray(mttkrp_blocked(dl, facs, 0, autotune=False))
    ref = np.asarray(dense_mttkrp(dl, facs, 0))
    np.testing.assert_array_equal(got, ref)


def test_bf16_dense_storage():
    tt = _dense_tensor()
    l32 = build_dense_layout(tt, 0)
    l16 = build_dense_layout(tt, 0, val_dtype=jnp.bfloat16)
    assert l16.tiles.dtype == jnp.bfloat16 and l16.val_storage == "bf16"
    assert l16.value_bytes() == l32.value_bytes() // 2
    assert "bf16" in l16.format_desc()
    facs = init_factors(tt.dims, 3, 1, dtype=jnp.bfloat16)
    a = np.asarray(dense_mttkrp(l16, facs, 0), dtype=np.float32)
    b = np.asarray(dense_mttkrp(l32, facs, 0), dtype=np.float32)
    np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-1)


def test_densify_matches_direct_build():
    """densify_layout (the tuner's re-encoding of an existing sorted
    build) produces the same tiles as building dense directly — unique
    coordinates make placement exact."""
    tt = _dense_tensor()
    sl = build_layout(tt, 0, block=1024, val_dtype=np.float32, dense=False)
    a = densify_layout(sl, tt.dims)
    b = build_dense_layout(tt, 0)
    np.testing.assert_array_equal(np.asarray(a.tiles), np.asarray(b.tiles))
    np.testing.assert_array_equal(np.asarray(a.mask), np.asarray(b.mask))
    assert a.density_bucket == b.density_bucket == "dn5"


# -- policy / resilient build ------------------------------------------------

def test_policy_default_off_and_env(monkeypatch):
    """SPLATT_DENSE defaults off (dense tiling is opt-in, like every
    format change); auto consults the verdict; on forces feasible
    modes."""
    tt = _dense_tensor()
    assert build_layout(tt, 0).encoding == "v1"
    monkeypatch.setenv("SPLATT_DENSE", "auto")
    assert build_layout(tt, 0).encoding == "dense"
    monkeypatch.setenv("SPLATT_DENSE_THRESHOLD", "0.5")
    assert build_layout(tt, 0).encoding == "v1"  # 6% < 50%
    monkeypatch.setenv("SPLATT_DENSE", "on")
    assert build_layout(tt, 0).encoding == "dense"  # forced past threshold
    monkeypatch.setenv("SPLATT_DENSE", "off")
    assert build_layout(tt, 0).encoding == "v1"


def test_degrade_drill_build_layout():
    """Chaos drill: a raised fault at format.dense degrades the build
    CLASSIFIED to the sparse encoding — a format_fallback event with
    site="dense", never a failed build."""
    tt = _dense_tensor()
    with faults.inject("format.dense", "runtime", times=1):
        lay = build_layout(tt, 0, dense=True)
    assert lay.encoding == "v1"  # the sparse build every engine consumes
    evs = resilience.run_report().events("format_fallback")
    assert len(evs) == 1
    assert evs[0]["site"] == "dense" and evs[0]["idx_width"] == "dense"
    assert evs[0]["failure_class"] and evs[0]["error"]
    # the degraded layout still dispatches
    facs = init_factors(tt.dims, 3, 0, dtype=jnp.float32)
    out = np.asarray(mttkrp_blocked(lay, facs, 0, autotune=False))
    assert np.isfinite(out).all()
    # summary renders the dense degrade line
    text = "\n".join(resilience.run_report().summary())
    assert "dense" in text


def test_degrade_drill_from_coo():
    """A forced-dense compile whose every dense build fails still
    produces a fully sparse, dispatchable BlockedSparse."""
    tt = _dense_tensor()
    opts = _opts(dense="on", block_alloc=BlockAlloc.ALLMODE)
    with faults.inject("format.dense", "runtime", times=99):
        X = BlockedSparse.from_coo(tt, opts)
    assert all(l.encoding == "v1" for l in X.layouts)
    evs = resilience.run_report().events("format_fallback")
    assert len(evs) == tt.nmodes
    assert all(e["site"] == "dense" for e in evs)
    facs = init_factors(tt.dims, 3, 0, dtype=jnp.float32)
    for m in range(tt.nmodes):
        out = np.asarray(mttkrp_blocked(X.layout_for(m), facs, m,
                                        autotune=False))
        assert np.isfinite(out).all()


# -- hybrid per-mode builds --------------------------------------------------

def test_from_coo_hybrid_mix_parity():
    """Mode 0 dense, modes 1-2 sparse in ONE BlockedSparse: the
    per-mode mode_map routes each mode to its encoding and the MTTKRP
    outputs match the all-sparse build."""
    tt = _dense_tensor()
    opts = _opts(block_alloc=BlockAlloc.ALLMODE)
    hyb = BlockedSparse.from_coo(tt, opts, tuned_dense={0: True})
    ref = BlockedSparse.from_coo(tt, opts)
    assert hyb.layout_for(0).encoding == "dense"
    assert hyb.layout_for(1).encoding == "v1"
    assert hyb.layout_for(2).encoding == "v1"
    facs = init_factors(tt.dims, 4, 9, dtype=jnp.float32)
    for m in range(tt.nmodes):
        lay = hyb.layout_for(m)
        path = "dense" if lay.encoding == "dense" else "sorted_onehot"
        a = np.asarray(mttkrp_blocked(lay, facs, m, path=path,
                                      autotune=False))
        b = np.asarray(mttkrp_blocked(ref.layout_for(m), facs, m,
                                      path="sorted_onehot",
                                      autotune=False))
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4,
                                   err_msg=f"mode {m}")


def test_from_coo_auto_policy_densifies_eligible_modes():
    tt = _dense_tensor()
    opts = _opts(dense="auto", block_alloc=BlockAlloc.ALLMODE)
    X = BlockedSparse.from_coo(tt, opts)
    assert all(X.layout_for(m).encoding == "dense"
               for m in range(tt.nmodes))
    # imbalance reporting skips the dense layouts instead of crashing
    # (the sparse builds every dense mode degrades to are still there)
    imb = X.imbalance()
    assert isinstance(imb, dict)
    assert all("dense" not in str(v.get("packing", "")) for v in
               imb.values())
    # a sparse tensor under the same policy stays sparse
    st = _sparse_tensor()
    Y = BlockedSparse.from_coo(st, _opts(dense="auto"))
    assert all(l.encoding == "v1" for l in Y.layouts)


# -- CPD: donation parity + guarded round-trip -------------------------------

def test_cpd_hybrid_parity_and_donation():
    """A full CPD over the hybrid build reaches the all-sparse fit
    within f32 tolerance, and the donated sweep changes NOTHING bit
    for bit relative to the undonated hybrid run."""
    tt = _dense_tensor()
    init = init_factors(tt.dims, 3, 11, dtype=jnp.float32)
    outs = {}
    for name, kw in (("sparse", dict()),
                     ("dense", dict(dense="auto")),
                     ("dense_nodonate", dict(dense="auto",
                                             donate_sweep=False))):
        opts = _opts(max_iterations=5, nnz_block=1024,
                     block_alloc=BlockAlloc.ALLMODE, **kw)
        outs[name] = cpd_als(BlockedSparse.from_coo(tt, opts), 3,
                             opts=opts, init=init)
    assert float(outs["dense"].fit) == pytest.approx(
        float(outs["sparse"].fit), abs=1e-4)
    assert float(outs["dense"].fit) == float(outs["dense_nodonate"].fit)
    for ua, ub in zip(outs["dense"].factors, outs["dense_nodonate"].factors):
        np.testing.assert_array_equal(np.asarray(ua), np.asarray(ub))
    # the caller's init survives the donated dense run
    assert not any(u.is_deleted() for u in init)


def test_cpd_dense_guarded_checkpoint_resume(tmp_path):
    """The guarded-ALS surround (checkpoint/resume, health sentinel)
    works unchanged over a dense-mode tensor, and the run leaves
    dense_dispatch evidence."""
    tt = _dense_tensor()
    ck = str(tmp_path / "ck.npz")
    opts = _opts(max_iterations=4, dense="auto",
                 block_alloc=BlockAlloc.ALLMODE)
    X = BlockedSparse.from_coo(tt, opts)
    assert any(l.encoding == "dense" for l in X.layouts)
    a = cpd_als(X, rank=3, opts=opts, checkpoint_path=ck,
                checkpoint_every=2)
    assert np.isfinite(float(a.fit))
    assert resilience.run_report().events("dense_dispatch")
    # resume from the checkpoint: same terminal model
    b = cpd_als(X, rank=3, opts=opts, checkpoint_path=ck,
                checkpoint_every=2)
    assert float(b.fit) == pytest.approx(float(a.fit), abs=1e-6)


# -- tuner integration -------------------------------------------------------

def _dense_plan(dl, rank=4):
    return tune.TunedPlan(path="dense", engine="dense_xla",
                          nnz_block=dl.tile, scan_target=1 << 21,
                          sec=0.001, idx_width="dense", val_storage="auto",
                          packing="fixed", reorder="identity")


def test_strict_match_dense_vs_sparse():
    """A dense plan never steers a sparse layout and vice versa: the
    plan key carries the mode-density regime, and the field match pins
    idx_width/nnz_block to the layout that was measured."""
    tt = _dense_tensor()
    dl = build_dense_layout(tt, 0)
    sl = build_layout(tt, 0, block=1024, val_dtype=np.float32, dense=False)
    facs = init_factors(tt.dims, 4, 0, dtype=jnp.float32)
    # dense layouts dispatch with skew="" and their density bucket
    tune._entry_store(
        tune.plan_key(tt.dims, tt.nnz, 0, 4, jnp.float32, skew="",
                      mode_density=dl.density_bucket),
        {"plan": dataclasses.asdict(_dense_plan(dl))})
    got = _tuned_plan_for(dl, facs, 0, "dense", autotune=True)
    assert got is not None and got.path == "dense"
    assert got.engine == "dense_xla" and got.nnz_block == dl.tile
    # the same plan must never steer the sparse layout
    assert _tuned_plan_for(sl, facs, 0, "sorted_onehot",
                           autotune=True) is None
    assert _tuned_plan_for(sl, facs, 0, "dense", autotune=True) is None
    # ... and a sparse plan stored under the sparse key never steers
    # the dense layout
    sparse_plan = tune.TunedPlan(path="sorted_onehot", engine="xla",
                                 nnz_block=1024, scan_target=1 << 21,
                                 sec=0.001)
    tune._entry_store(
        tune.plan_key(tt.dims, tt.nnz, 0, 4, jnp.float32,
                      skew=tune.skew_of(tt, 0),
                      mode_density=sl.density_bucket),
        {"plan": dataclasses.asdict(sparse_plan)})
    assert _tuned_plan_for(sl, facs, 0, "sorted_onehot",
                           autotune=True) is not None
    # (a skew-free regime shares the key: the sparse winner then
    # REPLACES the dense entry, and the strict field match refuses to
    # apply it — dense dispatch falls back to the heuristic chain
    # instead of running the wrong plan)
    got2 = _tuned_plan_for(dl, facs, 0, "dense", autotune=True)
    assert got2 is None or got2.path == "dense"


def test_demotion_scoped_to_dense_keys():
    """An OOM under the dense engine demotes the :dn shape key only —
    the sparse path's standing is untouched, and vice versa."""
    tt = _dense_tensor()
    dl = build_dense_layout(tt, 0)
    sl = build_layout(tt, 0, block=1024, val_dtype=np.float32, dense=False)
    facs = init_factors(tt.dims, 4, 0, dtype=jnp.float32)
    kd = _engine_shape_key(dl, facs, 0)
    ks = _engine_shape_key(sl, facs, 0)
    assert ":dn" in kd and ":dn" not in ks and kd != ks
    resilience.demote_engine("fused_dense",
                             MemoryError("injected dense OOM"),
                             shape_key=kd)
    assert resilience.is_demoted("fused_dense", kd)
    assert not resilience.is_demoted("fused_dense", ks)
    # the dense chain drops the kernel and keeps the terminal engine
    assert engine_chain(dl, facs, 0, impl="pallas_interpret") == [
        "dense_xla"]
    # a sparse-side demotion never reaches the dense keys
    resilience.reset_demotions()
    resilience.demote_engine("xla_scan", MemoryError("sparse OOM"),
                             shape_key=ks)
    assert not resilience.is_demoted("xla_scan", kd)


def test_tune_measures_dense_candidates_and_persists_winner(monkeypatch):
    """tune() measures dense candidates when the policy allows them,
    and a dense winner is persisted under the mode-density regime key
    and retrieved at dispatch."""
    tt = _dense_tensor()
    kw = dict(modes=[0], blocks=(4096,), reorders=("identity",),
              formats=[("i32", "auto")], warm=0, reps=1, force=True)
    monkeypatch.setenv("SPLATT_DENSE", "off")
    off = tune.tune(tt, 4, **kw)
    assert off.measured >= 1
    assert off.plans[0].path != "dense"  # no dense candidates under off
    tune.reset_memo()
    monkeypatch.setenv("SPLATT_DENSE", "auto")
    # substitute the timing body (the module-level seam
    # _measure_candidate exists for) so the dense candidate wins
    # deterministically: the real dispatch still runs — a broken
    # candidate still classifies — but the clock is synthetic
    real = tune._measure_candidate

    def rigged(layout, factors, mode, path, impl, engine, scan_target,
               warm=1, reps=2):
        real(layout, factors, mode, path, impl, engine, scan_target,
             warm=warm, reps=reps)
        return 1e-6 if path == "dense" else 1.0

    monkeypatch.setattr(tune, "_measure_candidate", rigged)
    auto = tune.tune(tt, 4, **kw)
    assert auto.measured > off.measured  # the dense candidates ran too
    plan = auto.plans.get(0)
    assert plan is not None and plan.path == "dense"
    assert plan.engine in ("dense_xla", "fused_dense")
    assert plan.idx_width == "dense" and plan.reorder == "identity"
    # retrieval at the dispatch site: the dense layout's own regime key
    # (tune measured at the tensor's f64 dtype — look up at the same)
    dl = build_dense_layout(tt, 0, val_dtype=np.float64)
    facs = init_factors(tt.dims, 4, 0, dtype=jnp.float64)
    got = _tuned_plan_for(dl, facs, 0, "dense", autotune=True)
    assert got is not None and got.path == "dense"
    # the dispatched result still matches the reference
    ref = np.asarray(dense_mttkrp(dl, facs, 0))
    out = np.asarray(mttkrp_blocked(dl, facs, 0, path="dense",
                                    autotune=True))
    np.testing.assert_array_equal(out, ref)


# -- stats / bytes / flops / roofline ----------------------------------------

def test_density_stats_and_text():
    tt = _dense_tensor()
    st = density_stats(tt)
    assert st["threshold"] == pytest.approx(0.05)
    for m in range(tt.nmodes):
        d = st["modes"][str(m)]
        assert d["verdict"] == "dense" and d["bucket"] == "dn5"
        assert 0 < d["padded_density"] < d["density"] < 1
    assert density_stats(tt, threshold=0.5)["modes"]["0"][
        "verdict"] == "sparse"
    text = density_stats_text(tt)
    assert "Mode density" in text and "-> dense" in text
    st2 = density_stats(_sparse_tensor())
    assert all(d["verdict"] == "sparse" for d in st2["modes"].values())
    assert "-> sparse" in density_stats_text(_sparse_tensor())
    # the factoring preamble renders hybrid builds (dense layouts have
    # no nblocks/seg_width — the CLI cpd verb hits this line)
    from splatt_tpu.stats import cpd_stats_text
    hyb = BlockedSparse.from_coo(tt, _opts(block_alloc=BlockAlloc.ALLMODE),
                                 tuned_dense={0: True})
    txt = cpd_stats_text(hyb, 4, _opts())
    assert "dense tiles=" in txt and "index_bytes=0" in txt


def test_encoded_bytes_model_zero_index_bytes():
    """Acceptance: the dense mode carries ZERO index bytes in the
    encoded-bytes model — its traffic is value tiles + pad mask +
    factor tables + the KR operand + the output, nothing indexed."""
    tt = _dense_tensor()
    rank = 4
    hyb = BlockedSparse.from_coo(tt, _opts(block_alloc=BlockAlloc.ALLMODE),
                                 tuned_dense={0: True})
    dl = hyb.layout_for(0)
    assert dl.encoding == "dense" and dl.index_bytes() == 0
    got = mttkrp_bytes_encoded("blocked", hyb, rank, 0, 4)
    tables = sum(d * rank * 4 for k, d in enumerate(tt.dims) if k != 0)
    want = (dl.storage_bytes() + tables + 2 * dl.span * rank * 4
            + tt.dims[0] * rank * 4)
    assert got == pytest.approx(want)
    # no decode traffic either: the dense engines read the tiles as-is
    assert mttkrp_decode_bytes(hyb, rank, 0, "dense_xla") == 0
    assert mttkrp_decode_bytes(hyb, rank, 0, "fused_dense") == 0
    # the sparse build pays real index traffic the dense mode deleted
    ref = BlockedSparse.from_coo(tt, _opts(block_alloc=BlockAlloc.ALLMODE))
    sref = ref.layout_for(0)
    assert sref.storage_bytes() > sref.nnz * 4  # idx streams beyond vals
    assert dl.storage_bytes() == dl.value_bytes() + dl.mask.size


def test_flops_model_and_roofline_verdict():
    tt = _dense_tensor()
    rank = 4
    hyb = BlockedSparse.from_coo(tt, _opts(block_alloc=BlockAlloc.ALLMODE),
                                 tuned_dense={0: True})
    dl = hyb.layout_for(0)
    geo = dl.geometry
    assert mttkrp_flops("blocked", hyb, rank, 0) == pytest.approx(
        2.0 * geo.cells * rank + geo.span * rank)
    # sparse modes keep the per-nonzero Hadamard-chain count
    sparse_flops = mttkrp_flops("stream", hyb, rank, 1)
    assert sparse_flops >= 2.0 * tt.nnz * rank * (tt.nmodes - 1)
    # the roofline verdict classifies on CPU through the nominal peaks
    v = roofline_verdict(1e9, 1e9)
    assert set(v) == {"intensity", "ridge", "bound"}
    assert v["bound"] in ("compute", "memory") and v["ridge"] > 0
    assert roofline_verdict(1.0, 1e12)["bound"] == "compute"
    assert roofline_verdict(1e12, 1.0)["bound"] == "memory"


# -- registries (splint stays at zero) ---------------------------------------

def test_registries_declare_dense_surface():
    from splatt_tpu.resilience import RUN_REPORT_EVENTS
    from splatt_tpu.utils.env import ENV_VARS
    from splatt_tpu.utils.faults import SITES

    assert "format.dense" in SITES
    assert "SPLATT_DENSE" in ENV_VARS
    assert "SPLATT_DENSE_THRESHOLD" in ENV_VARS
    assert "dense_dispatch" in RUN_REPORT_EVENTS
    assert "dense" in RUN_REPORT_EVENTS["format_fallback"].lower()
