"""The lease-protocol interleaving checker (tools/splint/interleave.py).

The fleet chaos soak samples one kill-and-restart schedule per run;
this checker enumerates every interleaving of fixed per-replica
programs against the REAL FleetMember code under a virtual clock.
Tier-1 pins three things: the protocol passes bounded-exhaustive
schedules (2 replicas; the 3-replica sweep is the slow tier), the
PR 11 zombie-commit mutant FAILS it (the checker is load-bearing, not
decorative), and the gen-fence mutant fails it through the
restarted-replica twin scenario.
"""

import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from tools.splint.interleave import (check, interleavings,  # noqa: E402
                                     scenarios)


def test_interleavings_enumerate_exhaustively():
    """All order-preserving merges, no duplicates: programs of sizes
    (2, 2) make 4!/(2!2!) = 6 schedules; (2, 2, 1) make 30."""
    two = list(interleavings({"A": ("a1", "a2"), "B": ("b1", "b2")}))
    assert len(two) == 6
    assert len(set(two)) == 6
    assert ("A:a1", "A:a2", "B:b1", "B:b2") in two
    for sched in two:
        assert sched.index("A:a1") < sched.index("A:a2")
        assert sched.index("B:b1") < sched.index("B:b2")
    three = list(interleavings({"A": ("a1", "a2"),
                                "B": ("b1", "b2"),
                                "clock": ("t",)}))
    assert len(three) == 30


def test_protocol_passes_two_replicas(tmp_path):
    """The acceptance invariant: every interleaving of every scenario
    upholds exactly-one-owner, gen monotonicity, the gen fence, and
    at-most-one terminal append — with the real acquire/renew/adopt/
    release code doing the work."""
    res = check(replicas=2, root=str(tmp_path))
    assert res.schedules > 400  # bounded-exhaustive, not a sample
    assert res.ok, "\n".join(str(v) for v in res.violations[:5])
    # the twin-revival scenario (restarted replica id) is in the set
    assert "twin-revival" in scenarios(2)


@pytest.mark.slow
def test_protocol_passes_three_replicas(tmp_path):
    res = check(replicas=3, root=str(tmp_path))
    assert res.schedules > 2500
    assert res.ok, "\n".join(str(v) for v in res.violations[:5])


def test_zombie_commit_mutant_fails(tmp_path):
    """Re-introducing the PR 11 zombie-commit bug (terminal append
    without the last-gate renew) must produce violations — among them
    the no-append-after-expiry breach in the failover scenario."""
    res = check(replicas=2, mutant="no_fence", root=str(tmp_path))
    assert not res.ok
    kinds = {v.invariant for v in res.violations}
    assert "no-append-after-expiry" in kinds
    assert "single-terminal" in kinds
    assert any(v.scenario == "failover" for v in res.violations)


def test_gen_fence_mutant_fails(tmp_path):
    """An adopt that forgets the generation bump lets the zombie twin
    of a restarted replica revive its dead era — caught by the
    gen-fence invariant in the twin-revival scenario."""
    res = check(replicas=2, mutant="no_gen_bump", root=str(tmp_path))
    assert not res.ok
    assert any(v.invariant == "gen-fence"
               and v.scenario == "twin-revival"
               for v in res.violations)


def test_cli_exit_codes(tmp_path):
    """`python -m tools.splint.interleave` is the CI entry: 0 clean,
    1 on a mutant."""
    ok = subprocess.run(
        [sys.executable, "-m", "tools.splint.interleave",
         "--replicas", "2"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "0 violation(s)" in ok.stdout
    bad = subprocess.run(
        [sys.executable, "-m", "tools.splint.interleave",
         "--replicas", "2", "--mutant", "no_fence"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert bad.returncode == 1
    assert "zombie" in bad.stdout
