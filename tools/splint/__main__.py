"""``python -m tools.splint`` — the splint command-line front end.

Runs identically to the pytest wiring (tests/test_splint.py) and any
future CI job: same Config, same rules, same baseline reconciliation.

Exit codes: 0 = no non-baselined findings; 1 = new findings; 2 = usage
or configuration error.
"""

from __future__ import annotations

import argparse
import ast
import inspect
import json
import sys
from pathlib import Path

from tools.splint.config import load_config
from tools.splint.core import load_baseline, run, update_baseline
from tools.splint.rules import RULES


def _env_docs(config) -> str:
    """Render the ENV_VARS registry as a markdown table — statically,
    so docs can be regenerated without importing the package (or jax)."""
    path = config.resolve(config.env_module)
    tree = ast.parse(path.read_text())
    rows = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "ENV_VARS"
                and isinstance(node.value, ast.Dict)):
            for k, v in zip(node.value.keys, node.value.values):
                name = k.value if isinstance(k, ast.Constant) else "?"
                default, doc = "", ""
                if isinstance(v, ast.Call) and v.args:
                    default = ast.unparse(v.args[0])
                    if len(v.args) > 1 and isinstance(v.args[1],
                                                      ast.Constant):
                        doc = v.args[1].value
                rows.append((name, default, doc))
    out = ["| variable | default | meaning |",
           "|----------|---------|---------|"]
    for name, default, doc in rows:
        out.append(f"| `{name}` | `{default}` | {doc} |")
    return "\n".join(out)


def _explain(config, rule_id: str) -> int:
    """Print one rule's documentation plus its known-bad / known-good
    fixtures — the executable spec of what the rule flags and what the
    sanctioned idiom looks like."""
    rule = next((r for r in RULES if r.id == rule_id.upper()), None)
    if rule is None:
        ids = ", ".join(r.id for r in RULES)
        print(f"splint: unknown rule {rule_id!r} (have: {ids})",
              file=sys.stderr)
        return 2
    print(f"{rule.id}  {rule.title}")
    doc = inspect.getdoc(type(rule)) or ""
    if doc:
        print()
        print(doc)
    if rule.hint:
        print()
        print(f"fix hint: {rule.hint}")
    fixtures = config.resolve(config.tests_path) / "splint_fixtures"
    for flavor in ("bad", "good"):
        path = fixtures / f"{rule.id.lower()}_{flavor}.py"
        if path.is_file():
            rel = f"{config.tests_path}/splint_fixtures/{path.name}"
            print(f"\n-- known-{flavor} fixture ({rel}) " + "-" * 20)
            print(path.read_text().rstrip())
    return 0


def _sarif_report(shown, new_keys) -> dict:
    """The findings as a SARIF 2.1.0 log — the interchange format CI
    code-scanning upload steps consume.  Every shown finding becomes a
    result; baselined ones carry a suppression so scanners display
    them as acknowledged instead of new."""
    rule_meta = []
    for rule in RULES:
        rule_meta.append({
            "id": rule.id,
            "name": rule.id,
            "shortDescription": {"text": rule.title},
            **({"help": {"text": rule.hint}} if rule.hint else {}),
        })
    results = []
    for f in shown:
        result = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        }
        if id(f) not in new_keys:
            result["suppressions"] = [{
                "kind": "external",
                "justification": "baselined in tools/splint/baseline.json",
            }]
        results.append(result)
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "splint",
                "informationUri": "docs/static-analysis.md",
                "rules": rule_meta,
            }},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.splint",
        description="project-native static analysis "
                    "(docs/static-analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="focus the REPORT on these files/dirs. The "
                         "whole [tool.splint] tree is always analyzed "
                         "(cross-file rules like SPL006 need the full "
                         "picture); findings outside the focus are "
                         "hidden and do not affect the exit code")
    ap.add_argument("--root", default=".",
                    help="project root holding pyproject.toml")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--sarif", metavar="PATH", default=None,
                    help="also write the findings as a SARIF 2.1.0 "
                         "log (CI code-scanning upload format); "
                         "baselined findings carry suppressions")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: [tool.splint] "
                         "baseline)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(reasons are preserved)")
    ap.add_argument("--env-docs", action="store_true",
                    help="print the ENV_VARS registry as markdown and "
                         "exit")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--explain", metavar="RULE", default=None,
                    help="print one rule's doc plus its bad/good "
                         "fixtures and exit (e.g. --explain SPL008)")
    args = ap.parse_args(argv)

    try:
        config = load_config(Path(args.root))
    except ValueError as e:
        print(f"splint: {e}", file=sys.stderr)
        return 2
    # positional paths FOCUS the report; they never shrink the analyzed
    # tree — a partial analysis would feed cross-file rules (SPL006's
    # "declared site has no production call") a factually wrong world,
    # and --update-baseline would destroy entries for unanalyzed files
    focus = [_norm_focus(config, p) for p in args.paths]

    if args.list_rules:
        print("SPL000  splint usage errors (malformed/reasonless "
              "pragmas, unparseable files)")
        for rule in RULES:
            print(f"{rule.id}  {rule.title}")
        return 0
    if args.explain:
        return _explain(config, args.explain)
    if args.env_docs:
        print(_env_docs(config))
        return 0

    baseline_path = config.resolve(args.baseline or config.baseline)
    try:
        baseline = ({} if args.no_baseline
                    else load_baseline(baseline_path))
    except (ValueError, json.JSONDecodeError) as e:
        print(f"splint: bad baseline {baseline_path}: {e}",
              file=sys.stderr)
        return 2

    report = run(config, baseline=baseline)

    if args.update_baseline:
        # always from the full analyzed tree, never a focused subset
        entries = update_baseline(baseline_path, report)
        print(f"splint: baseline {baseline_path} rewritten: "
              f"{len(entries)} group(s), "
              f"{sum(e['count'] for e in entries.values())} finding(s)")
        return 0

    def in_focus(f):
        return not focus or any(f.path == p or f.path.startswith(p + "/")
                                for p in focus)

    shown = [f for f in report.findings if in_focus(f)]
    new = [f for f in report.new if in_focus(f)]
    ok = not new
    new_keys = {id(f) for f in new}

    if args.sarif:
        sarif_path = Path(args.sarif)
        if not sarif_path.is_absolute():
            sarif_path = Path(config.root) / sarif_path
        sarif_path.write_text(
            json.dumps(_sarif_report(shown, new_keys), indent=1) + "\n")

    if args.as_json:
        print(json.dumps({
            "ok": ok,
            "findings": [f.as_dict(baselined=id(f) not in new_keys)
                         for f in shown],
            "suppressed": report.suppressed,
            "stale_baseline": report.stale,
            "shrunk_baseline": {k: {"found": a, "baselined": b}
                                for k, (a, b) in report.shrunk.items()},
        }, indent=1))
        return 0 if ok else 1

    for f in new:
        print(f"{f.path}:{f.line}: {f.rule} {f.message}")
        if f.hint:
            print(f"    hint: {f.hint}")
    print(f"splint: {len(new)} new finding(s), {len(shown) - len(new)} "
          f"baselined, {report.suppressed} suppressed by pragma"
          + (f" (report focused on {', '.join(focus)})" if focus else ""))
    for key, (found, allowed) in sorted(report.shrunk.items()):
        print(f"splint: baseline shrank: {key} {found} < {allowed} — "
              f"run --update-baseline to lock in the burn-down")
    for key in report.stale:
        print(f"splint: stale baseline entry {key} (0 findings) — "
              f"run --update-baseline to drop it")
    return 0 if ok else 1


def _norm_focus(config, p: str) -> str:
    """Normalize a focus argument to the repo-relative posix form
    findings use."""
    path = Path(p)
    if not path.is_absolute():
        path = Path(config.root) / p
    try:
        return path.resolve().relative_to(
            Path(config.root).resolve()).as_posix()
    except ValueError:
        return Path(p).as_posix()


if __name__ == "__main__":
    sys.exit(main())
