"""SPL008 good: donated inputs are re-bound before any further read,
or re-materialized behind the sanctioned is_deleted guard."""

import jax
import jax.numpy as jnp
import numpy as np


def make_step(reg):
    def step(state, grad):
        return state - reg * grad

    return jax.jit(step, donate_argnums=(0,))


def rebind(state, grad, reg):
    step = make_step(reg)
    state = step(state, grad)  # donated and immediately re-bound
    return state


def rescue_with_snapshot(state, grad, reg):
    """The cpd_als engine-rescue idiom: probe is_deleted, restore the
    consumed input from a host snapshot before retrying."""
    step = make_step(reg)
    snap = np.asarray(state)
    while True:
        try:
            state = step(state, grad)
            break
        except RuntimeError:
            step = make_step(reg)
            if getattr(state, "is_deleted", lambda: False)():
                state = jnp.asarray(snap)
    return state
