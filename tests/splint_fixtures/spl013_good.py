"""SPL013 good: span-opening sites name spans declared in
trace.py:SPANS (literals, and f-strings under a declared ``x.*``
family)."""

from splatt_tpu import trace


def traced_rebuild():
    # a declared literal span name (the sweep-rebuild region of cpd.py)
    with trace.span("cpd.build_sweep"):
        pass


def traced_bracket(name):
    # f-string under the declared ``timer.*`` family (utils/timers.py)
    handle = trace.begin(f"timer.{name}")
    trace.end(handle)
