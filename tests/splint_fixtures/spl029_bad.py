"""SPL029 bad: recording a metric the METRICS registry never declared,
and recording a declared counter through the gauge verb (which would
raise at runtime)."""

from splatt_tpu import trace


def rogue_counter():
    trace.metric_inc("spl029_fixture_undeclared_total")


def mistyped_verb():
    trace.metric_set("splatt_retries_total", 1.0)
