"""SPL024 bad: reductions over possibly-narrow storage with no
accumulation-dtype discipline — an unpinned Gram, a raw segment-sum,
and a method-sum with no dtype pin.  Under bf16 factors each of these
accumulates at 8 mantissa bits."""

import jax
import jax.numpy as jnp


def bad_unpinned_gram(U):
    # no preferred_element_type: bf16 @ bf16 accumulates bf16
    return jnp.matmul(U.T, U)


def bad_raw_segment_reduce(prod, inds, dim):
    # segment_sum accumulates in the operand dtype; the operand was
    # never upcast through the acc-dtype helpers
    return jax.ops.segment_sum(prod, inds, num_segments=dim)


def bad_method_sum(had):
    # .sum() with no dtype= pin over an operand splint cannot prove
    # wide or exact
    return had.sum()
