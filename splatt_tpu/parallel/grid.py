"""n-D medium-grained grid decomposition (≙ the reference's flagship
distributed mode: the cartesian MEDIUM decomposition of src/mpi/).

The reference arranges ranks in an n-D grid (one axis per tensor mode,
p_get_best_mpi_dim src/mpi/mpi_io.c:537-574), gives each rank the
nonzeros whose coordinates fall in its cell, and fences factor-row
ownership along each axis ("layers").  The payoff: **MTTKRP inputs are
always rank-local** (a cell's nonzeros only touch the factor blocks of
its own layers) and only the *output* rows must be summed across the
layer (src/mpi/mpi_cpd.c's reduce_rows), plus small Gram/λ allreduces.

TPU mapping, one `shard_map` over a mesh with one axis per mode:

  - factor m:  (dim_pad_m, R), sharded over axis `m<m>`, replicated on
    the other axes — exactly the reference's layer ownership.
  - nonzeros: host-compiled into cells, arrays shaped
    (g_0, ..., g_{n-1}, cell_nnz) so each device holds its own cell;
    indices stored *local to the cell's blocks* (≙ the reference
    relocalizing indices to layer coordinates, mpi_io.c:816-824).
  - mode-m update: local gather-prod (NO communication — inputs are
    local by construction) → segment-sum into the local row block →
    ``psum over every axis except m`` (the layer reduce — this is
    mpi_reduce_rows+mpi_update_rows collapsed into one collective,
    since afterwards every device in the layer holds the full summed
    block) → local solve → λ/Gram psum over axis m only.

Row fences are equal-sized (static shapes).  The reference instead
computes nnz-balanced fences (p_find_layer_boundaries) and relabels
rows; the TPU equivalent of that balancing is to apply a relabeling
permutation (splatt_tpu.reorder, e.g. `random`) before building the
grid — equal fences over a randomized labeling ≈ balanced cells, and
the permutation bookkeeping restores factor row order afterwards.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from splatt_tpu.utils.env import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from splatt_tpu.config import Options, Verbosity, default_opts, resolve_dtype
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import init_factors
from splatt_tpu.kruskal import KruskalTensor
from splatt_tpu.ops.mttkrp import acc_dtype
from splatt_tpu.parallel.common import (balanced_relabel,
                                        blocked_local_mttkrp, bucket_engine,
                                        bucket_scatter, comm_volume_report,
                                        fit_tail, imbalance_report,
                                        mode_update_tail,
                                        run_distributed_als,
                                        streamed_bucket_scatter)
from splatt_tpu.parallel.mesh import auto_grid
from splatt_tpu.utils.env import ceil_to


def _axis(m: int) -> str:
    return f"m{m}"


@dataclasses.dataclass
class GridDecomp:
    """Host-compiled grid decomposition of a COO tensor.

    Arrays are laid out with one leading dim per grid axis so a
    NamedSharding puts exactly one cell on each device.
    """

    grid: Tuple[int, ...]
    dims_pad: Tuple[int, ...]      # per mode, divisible by grid[m]
    block_rows: Tuple[int, ...]    # dims_pad[m] // grid[m]
    cell_nnz: int                  # padded nnz per cell
    inds_local: np.ndarray         # (nmodes, *grid, cell_nnz) int32
    vals: np.ndarray               # (*grid, cell_nnz)
    nnz: int
    fill: float                    # nnz / (ncells * cell_nnz) — balance
    cell_counts: np.ndarray        # (ncells,) true occupancy per cell
    # per-mode old→new row label maps from the nnz-balanced fences
    # (None per mode = identity; ≙ the relabeling after
    # p_find_layer_boundaries / mpi_mat_distribute's perm)
    relabels: Optional[List[Optional[np.ndarray]]] = None

    @property
    def nmodes(self) -> int:
        return len(self.grid)

    @staticmethod
    def build(tt: SparseTensor, grid: Optional[Tuple[int, ...]] = None,
              n_devices: Optional[int] = None,
              val_dtype=np.float32,  # splint: ignore[SPL005] shard-builder signature default; callers override via Options.val_dtype
              balance: Optional[bool] = False,
              streamed: Optional[bool] = None,
              out_dir: Optional[str] = None,
              chunk: int = 1 << 22) -> "GridDecomp":
        """≙ mpi_tt_read's rearrange-to-owners (p_rearrange_medium,
        src/mpi/mpi_io.c:451-473) done as a host-side bucketing.

        `balance`: nnz-balance the row fences by relabeling rows
        (balanced_relabel ≙ p_find_layer_boundaries,
        src/mpi/mpi_io.c:365-439).  None = auto: balance when the
        equal-fence fill is poor (< 0.5) and the relabeling improves it
        — this is what *acts* on the fill statistic the reference
        prints.  Every cell is padded to the fullest cell, so fill is
        both the memory and the compute efficiency of the sweep.

        Default is False (no relabeling) because a relabeled build
        changes factor row placement: callers that scatter factors
        through :meth:`shard_factors` must restore order with
        :meth:`row_select` when gathering.  grid_cpd_als does and
        enables auto mode; direct build() users opt in explicitly.

        `streamed` (auto: when tt holds memmapped indices) bounds host
        RSS at O(chunk + cell metadata) by running the decomposition in
        chunked passes (streamed_bucket_scatter ≙ the reference's
        root-streamed chunk distribution, src/mpi/mpi_io.c:587-648);
        with `out_dir` the bucketed arrays are disk-backed memmaps, so
        a tensor bigger than host RAM decomposes end-to-end.
        """
        nmodes = tt.nmodes
        if grid is None:
            ndev = n_devices if n_devices is not None else len(jax.devices())
            grid = auto_grid(ndev, tt.dims)
        grid = tuple(int(g) for g in grid)
        dims_pad = tuple(ceil_to(max(d, g), g) for d, g in zip(tt.dims, grid))
        block_rows = tuple(dp // g for dp, g in zip(dims_pad, grid))
        ncells = int(np.prod(grid))
        if streamed is None:
            from splatt_tpu.parallel.common import is_memmapped

            streamed = is_memmapped(tt.inds)
        if streamed:
            return GridDecomp._build_streamed(
                tt, grid, dims_pad, block_rows, ncells, val_dtype,
                balance, out_dir, chunk)

        def cells_of(inds_rel):
            cell = np.zeros(tt.nnz, dtype=np.int64)
            for m in range(nmodes):
                cell = cell * grid[m] + inds_rel[m] // block_rows[m]
            return cell

        def fill_of(cell):
            if tt.nnz == 0:
                return 1.0
            counts = np.bincount(cell, minlength=ncells)
            return tt.nnz / max(ncells * int(counts.max()), 1)

        inds_rel = tt.inds
        relabels: Optional[List[Optional[np.ndarray]]] = None
        cell = cells_of(inds_rel)
        fill0 = fill_of(cell)
        if balance or (balance is None and fill0 < 0.5):
            rl = [balanced_relabel(tt.mode_histogram(m), grid[m],
                                   block_rows[m])
                  if grid[m] > 1 else None
                  for m in range(nmodes)]
            cand = np.stack([r[tt.inds[m]] if r is not None else tt.inds[m]
                             for m, r in enumerate(rl)])
            cell_b = cells_of(cand)
            if balance or fill_of(cell_b) > fill0:
                inds_rel, relabels, cell = cand, rl, cell_b

        binds, vals, cell_nnz, counts = bucket_scatter(inds_rel, tt.vals,
                                                       cell, ncells,
                                                       val_dtype)
        # localize indices to the cell's block fences (pad slots hold
        # index 0, and 0 % block == 0 — harmless)
        for m in range(nmodes):
            binds[m] %= block_rows[m]

        return GridDecomp(
            grid=grid, dims_pad=dims_pad, block_rows=block_rows,
            cell_nnz=cell_nnz,
            inds_local=binds.reshape((nmodes, *grid, cell_nnz)),
            vals=vals.reshape((*grid, cell_nnz)),
            nnz=tt.nnz,
            fill=tt.nnz / max(ncells * cell_nnz, 1),
            cell_counts=counts,
            relabels=relabels,
        )

    @staticmethod
    def _build_streamed(tt, grid, dims_pad, block_rows, ncells, val_dtype,
                        balance, out_dir, chunk) -> "GridDecomp":
        """Chunked-pass build: never materializes an O(nnz) temporary
        beyond the (optionally disk-backed) bucketed output itself."""
        nmodes = tt.nmodes
        nnz = tt.nnz

        def cells_of_chunk(ic, rl):
            cell = np.zeros(ic.shape[1], dtype=np.int64)
            for m in range(nmodes):
                col = rl[m][ic[m]] if rl and rl[m] is not None else ic[m]
                cell = cell * grid[m] + col // block_rows[m]
            return cell

        def counts_for(rl):
            from splatt_tpu.parallel.common import _drop_pages

            c = np.zeros(ncells, dtype=np.int64)
            for s in range(0, nnz, chunk):
                ic = np.asarray(tt.inds[:, s:min(nnz, s + chunk)])
                c += np.bincount(cells_of_chunk(ic, rl), minlength=ncells)
                # mapped input pages count toward RSS until advised
                # away — per-chunk keeps the pass O(chunk) resident
                _drop_pages(tt.inds)
            return c

        def fill_of(counts):
            return (nnz / max(ncells * int(counts.max()), 1)
                    if nnz else 1.0)

        def hist_of(m):
            from splatt_tpu.parallel.common import _drop_pages

            h = np.zeros(tt.dims[m], dtype=np.int64)
            col = tt.inds[m]
            for s in range(0, nnz, chunk):
                h += np.bincount(np.asarray(col[s:min(nnz, s + chunk)]),
                                 minlength=tt.dims[m])
                _drop_pages(tt.inds)
            return h

        relabels = None
        counts = counts_for(None)
        fill0 = fill_of(counts)
        if balance or (balance is None and fill0 < 0.5):
            cand = [balanced_relabel(hist_of(m), grid[m], block_rows[m])
                    if grid[m] > 1 else None for m in range(nmodes)]
            counts_b = counts_for(cand)
            if balance or fill_of(counts_b) > fill0:
                relabels, counts = cand, counts_b

        def postprocess(placed):
            for m in range(nmodes):
                rl = relabels[m] if relabels is not None else None
                col = rl[placed[m]] if rl is not None else placed[m]
                placed[m] = col % block_rows[m]
            return placed

        # counts already computed while deciding balance: the scatter
        # needs only one more pass over the tensor
        binds, bvals, cell_nnz, counts = streamed_bucket_scatter(
            tt.inds, tt.vals,
            lambda ic, s: cells_of_chunk(ic, relabels),
            ncells, val_dtype, chunk=chunk, out_dir=out_dir,
            postprocess=postprocess, counts=counts)

        return GridDecomp(
            grid=grid, dims_pad=dims_pad, block_rows=block_rows,
            cell_nnz=cell_nnz,
            inds_local=binds.reshape((nmodes, *grid, cell_nnz)),
            vals=bvals.reshape((*grid, cell_nnz)),
            nnz=nnz,
            fill=nnz / max(ncells * cell_nnz, 1),
            cell_counts=counts,
            relabels=relabels,
        )

    def make_mesh(self, devices=None) -> Mesh:
        devs = list(devices if devices is not None else jax.devices())
        n = int(np.prod(self.grid))
        if n > len(devs):
            grid = "x".join(str(g) for g in self.grid)
            raise ValueError(f"grid {grid} needs {n} devices, only "
                             f"{len(devs)} available")
        mesh_devs = np.array(devs[:n]).reshape(self.grid)
        return Mesh(mesh_devs, tuple(_axis(m) for m in range(self.nmodes)))

    def device_put(self, mesh: Mesh):
        axes = [_axis(m) for m in range(self.nmodes)]
        inds = jax.device_put(
            self.inds_local, NamedSharding(mesh, P(None, *axes, None)))
        vals = jax.device_put(
            self.vals, NamedSharding(mesh, P(*axes, None)))
        return inds, vals

    def shard_factors(self, factors: List[jax.Array], mesh: Mesh):
        out = []
        for m, U in enumerate(factors):
            dp = self.dims_pad[m]
            U_pad = jnp.zeros((dp, U.shape[1]), dtype=U.dtype)
            rl = self.relabels[m] if self.relabels is not None else None
            if rl is None:
                U_pad = U_pad.at[:U.shape[0]].set(U)
            else:
                # balanced fences: row `old` lives at label rl[old]
                U_pad = U_pad.at[jnp.asarray(rl)].set(U)
            out.append(jax.device_put(
                U_pad, NamedSharding(mesh, P(_axis(m), None))))
        return tuple(out)

    def row_select(self) -> Optional[List[Optional[np.ndarray]]]:
        """Per-mode gather indices restoring original row order from a
        padded factor (for run_distributed_als)."""
        return None if self.relabels is None else list(self.relabels)

    def build_cell_layouts(self, opts: Options,
                           out_dir: Optional[str] = None,
                           chunk: int = 1 << 22) -> "CellLayouts":
        """Per-cell sorted blocked layouts so the sweep runs the
        single-chip blocked MTTKRP engine inside every cell (≙ each
        rank building CSF over its local nonzeros and calling the same
        optimized mttkrp_csf, src/mpi/mpi_cpd.c:714).

        `opts.block_alloc` governs the layout count exactly like the
        single-chip compiler (≙ splatt_csf_alloc): ONEMODE/TWOMODE
        build 1–2 sorted copies and the remaining modes run the
        generic scatter path on the first; ALLMODE builds one per mode.

        Memmapped (disk-backed streamed) decompositions sort via the
        chunked counting-sort build, with the layout memmaps under
        `out_dir` (default: beside the decomposition's own files) —
        the blocked engine survives out-of-core scale.
        """
        from splatt_tpu.parallel.common import (_memmap_dir,
                                                alloc_build_modes,
                                                build_bucket_layout,
                                                is_memmapped)

        nmodes = self.nmodes
        ncells = int(np.prod(self.grid))
        binds = self.inds_local.reshape(nmodes, ncells, -1)
        bvals = self.vals.reshape(ncells, -1)
        if is_memmapped(binds) and out_dir is None:
            out_dir = _memmap_dir(binds)
        build_modes = alloc_build_modes(
            [self.block_rows[m] for m in range(nmodes)], opts)
        if out_dir is not None and opts.verbosity >= Verbosity.LOW:
            # another full sorted copy per build mode lands on disk —
            # say where and how big BEFORE writing, so a silently
            # chosen directory (beside the user's decomposition files)
            # and its space cost are observable.  These memmaps persist
            # after the run: cleanup is the caller's job (docs/).
            per_mode = (binds.size * binds.itemsize
                        + bvals.size * bvals.itemsize)
            print(f"  cell layouts: memmapped under {out_dir} "
                  f"(cells_m<mode>/, ~{per_mode / 1e9:.2f} GB per build "
                  f"mode x {len(build_modes)} mode(s)); not cleaned up "
                  f"automatically")
        layouts = []
        for m in build_modes:
            i, v, rs, blk, S = build_bucket_layout(
                binds, bvals, self.cell_counts, m, self.block_rows[m],
                opts.nnz_block, chunk=chunk,
                out_dir=(os.path.join(out_dir, f"cells_m{m}")
                         if out_dir is not None else None))
            path, impl = bucket_engine(S, opts)
            layouts.append(dict(
                inds=i.reshape((nmodes, *self.grid, -1)),
                vals=v.reshape((*self.grid, -1)),
                row_start=rs.reshape((*self.grid, -1)),
                block=blk, seg_width=S, path=path, impl=impl,
                sort_mode=m, sort_dim=self.block_rows[m]))
        mode_map = {m: (build_modes.index(m) if m in build_modes else 0)
                    for m in range(nmodes)}
        return CellLayouts(layouts=layouts, mode_map=mode_map)


@dataclasses.dataclass
class CellLayouts:
    """Sorted+blocked cell arrays for the grid sweep, one entry per
    built layout plus a mode→layout map (see
    GridDecomp.build_cell_layouts)."""

    layouts: List[dict]
    mode_map: dict

    def device_put(self, mesh: Mesh, nmodes: int):
        """Per-MODE cell dicts for the sweep; layouts device_put once
        and shared by reference across the modes that map to them.
        A mode whose layout is sorted for another mode runs the
        generic scatter path (≙ an internal/leaf CSF traversal)."""
        axes = [_axis(m) for m in range(nmodes)]
        placed = []
        for lay in self.layouts:
            placed.append(dict(
                inds=jax.device_put(lay["inds"],
                                    NamedSharding(mesh, P(None, *axes, None))),
                vals=jax.device_put(lay["vals"],
                                    NamedSharding(mesh, P(*axes, None))),
                row_start=jax.device_put(
                    lay["row_start"], NamedSharding(mesh, P(*axes, None))),
                block=lay["block"], seg_width=lay["seg_width"],
                path=lay["path"], impl=lay["impl"],
                sort_mode=lay["sort_mode"], sort_dim=lay["sort_dim"]))
        out = []
        for m in range(nmodes):
            lay = dict(placed[self.mode_map[m]])
            if lay["sort_mode"] != m:
                lay["path"] = "scatter"
            out.append(lay)
        return out


def make_grid_sweep(mesh: Mesh, decomp: GridDecomp, reg: float,
                    cells: Optional[List[dict]] = None):
    """One jitted shard_mapped ALS sweep over the n-D grid.

    With `cells` (the per-mode dicts from CellLayouts.device_put): the
    local MTTKRP runs the single-chip blocked engine over each cell's
    sorted arrays (≙ mpi ranks reusing the optimized mttkrp_csf,
    mpi_cpd.c:714); without, the naive stream formulation (kept as the
    differential oracle for the blocked sweep).
    """
    nmodes = decomp.nmodes
    axes = [_axis(m) for m in range(nmodes)]
    factor_specs = tuple(P(_axis(m), None) for m in range(nmodes))
    gram_specs = tuple([P()] * nmodes)
    block_rows = decomp.block_rows
    cell_specs = tuple(
        (P(None, *axes, None), P(*axes, None), P(*axes, None))
        for _ in range(nmodes)) if cells is not None else ()

    @partial(shard_map, mesh=mesh,
             in_specs=(P(None, *axes, None), P(*axes, None),
                       factor_specs, gram_specs, P(), cell_specs),
             out_specs=(factor_specs, gram_specs, P(), P(), P()),
             check_vma=False)
    def sweep(inds_l, vals_l, factors_l, grams_l, first_flag, cells_l):
        factors_l = list(factors_l)
        grams_l = list(grams_l)
        dtype = factors_l[0].dtype
        # local cell views: squeeze the grid axes (all size 1 per device)
        inds_c = inds_l.reshape(nmodes, -1)
        vals_c = vals_l.reshape(-1)
        lam = None
        M_l = None
        for m in range(nmodes):
            # inputs are cell-local: no communication (the medium-grain
            # payoff — ≙ only layer rows ever being touched)
            if cells is not None:
                ci, cv, crs = cells_l[m]
                partial_out = blocked_local_mttkrp(
                    ci.reshape(nmodes, -1), cv.reshape(-1),
                    crs.reshape(-1), factors_l, m,
                    dim=cells[m]["sort_dim"], block=cells[m]["block"],
                    seg_width=cells[m]["seg_width"],
                    path=cells[m]["path"], impl=cells[m]["impl"],
                    sort_mode=cells[m]["sort_mode"])
            else:
                prod = vals_c[:, None].astype(dtype)
                for k in range(nmodes):
                    if k != m:
                        prod = prod * jnp.take(factors_l[k], inds_c[k],
                                               axis=0, mode="clip")
                partial_out = jax.ops.segment_sum(
                    prod.astype(acc_dtype(prod.dtype)), inds_c[m],
                    num_segments=block_rows[m])
            # layer reduce (≙ mpi_reduce_rows + mpi_update_rows): after
            # this, every device in the mode-m layer holds the block
            other_axes = tuple(axes[k] for k in range(nmodes) if k != m)
            M_l = jax.lax.psum(partial_out, other_axes) if other_axes \
                else partial_out
            # λ/Gram allreduce over the owning axis only (blocks on the
            # other axes are replicas)
            U_l, gram, lam = mode_update_tail(M_l, grams_l, m, reg,
                                              first_flag, axes[m],
                                              store_dtype=dtype)
            factors_l[m] = U_l
            grams_l[m] = gram
        znormsq, inner = fit_tail(lam, grams_l, M_l, factors_l[nmodes - 1],
                                  axes[nmodes - 1])
        return tuple(factors_l), tuple(grams_l), lam, znormsq, inner

    return jax.jit(sweep)


def make_grid_profiled_sweep(mesh: Mesh, decomp: GridDecomp, reg: float,
                             store_dtype, cells: Optional[List[dict]] = None):
    """Split-jit profiled grid sweep: each phase (local MTTKRP, layer
    reduce, solve/normalize/gram update, fit) is its own shard_mapped
    program bracketed by blocking timers, so the mttkrp-vs-collective-
    vs-solve split is MEASURED (≙ mpi_time_stats reporting per-phase
    avg/max across ranks, src/mpi/mpi_cpd.c:893-939 — SPMD phases are
    barrier-synchronized, so wall clock IS the across-device max).
    Costs cross-phase fusion; the fused :func:`make_grid_sweep` is the
    production path.
    """
    nmodes = decomp.nmodes
    axes = [_axis(m) for m in range(nmodes)]
    factor_specs = tuple(P(_axis(m), None) for m in range(nmodes))
    gram_specs = tuple([P()] * nmodes)
    block_rows = decomp.block_rows
    cell_spec = (P(None, *axes, None), P(*axes, None), P(*axes, None))

    def make_local(m):
        in_specs = ((P(None, *axes, None), P(*axes, None), factor_specs)
                    + ((cell_spec,) if cells is not None else ()))

        @partial(shard_map, mesh=mesh, in_specs=in_specs,
                 out_specs=P(*axes, None, None), check_vma=False)
        def local_m(inds_l, vals_l, factors_l, *cell_m):
            if cells is not None:
                ci, cv, crs = cell_m[0]
                part = blocked_local_mttkrp(
                    ci.reshape(nmodes, -1), cv.reshape(-1),
                    crs.reshape(-1), list(factors_l), m,
                    dim=cells[m]["sort_dim"], block=cells[m]["block"],
                    seg_width=cells[m]["seg_width"],
                    path=cells[m]["path"], impl=cells[m]["impl"],
                    sort_mode=cells[m]["sort_mode"])
            else:
                inds_c = inds_l.reshape(nmodes, -1)
                vals_c = vals_l.reshape(-1)
                prod = vals_c[:, None].astype(factors_l[0].dtype)
                for k in range(nmodes):
                    if k != m:
                        prod = prod * jnp.take(factors_l[k], inds_c[k],
                                               axis=0, mode="clip")
                part = jax.ops.segment_sum(
                    prod.astype(acc_dtype(prod.dtype)), inds_c[m],
                    num_segments=block_rows[m])
            return part.reshape((1,) * nmodes + part.shape)

        return jax.jit(local_m)

    def make_reduce(m):
        other_axes = tuple(axes[k] for k in range(nmodes) if k != m)

        @partial(shard_map, mesh=mesh, in_specs=(P(*axes, None, None),),
                 out_specs=P(_axis(m), None), check_vma=False)
        def reduce_m(parts_l):
            p = parts_l.reshape(parts_l.shape[-2:])
            return jax.lax.psum(p, other_axes) if other_axes else p

        return jax.jit(reduce_m)

    def make_update(m):
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(_axis(m), None), gram_specs, P()),
                 out_specs=(P(_axis(m), None), P(), P()),
                 check_vma=False)
        def update_m(M_l, grams_l, flag):
            return mode_update_tail(M_l, list(grams_l), m, reg, flag,
                                    axes[m], store_dtype=store_dtype)

        return jax.jit(update_m)

    last = nmodes - 1

    @partial(shard_map, mesh=mesh,
             in_specs=(P(), gram_specs, P(_axis(last), None),
                       P(_axis(last), None)),
             out_specs=(P(), P()), check_vma=False)
    def fit_fn(lam, grams_l, M_l, U_l):
        return fit_tail(lam, list(grams_l), M_l, U_l, axes[last])

    locals_ = [make_local(m) for m in range(nmodes)]
    reduces = [make_reduce(m) for m in range(nmodes)]
    updates = [make_update(m) for m in range(nmodes)]
    fit_jit = jax.jit(fit_fn)

    from splatt_tpu.utils.env import host_fence as sync
    from splatt_tpu.utils.timers import timers

    def sweep(inds, vals, factors, grams, flag, cells_dev=()):
        factors = list(factors)
        grams = list(grams)
        lam = None
        M = None
        for m in range(nmodes):
            extra = (cells_dev[m],) if cells is not None else ()
            with timers.time("dist_mttkrp"):
                parts = sync(locals_[m](inds, vals, tuple(factors),
                                        *extra))
            with timers.time("dist_comm"):
                M = sync(reduces[m](parts))
            with timers.time("dist_update"):
                factors[m], grams[m], lam = sync(
                    updates[m](M, tuple(grams), flag))
        with timers.time("dist_fit"):
            znormsq, inner = sync(fit_jit(lam, tuple(grams), M,
                                          factors[last]))
        return tuple(factors), tuple(grams), lam, znormsq, inner

    return sweep


def grid_cpd_als(tt: SparseTensor, rank: int,
                 grid: Optional[Tuple[int, ...]] = None,
                 mesh: Optional[Mesh] = None,
                 opts: Optional[Options] = None,
                 init: Optional[List[jax.Array]] = None,
                 relabel: Optional[str] = None,
                 local_engine: Optional[str] = None,
                 out_dir: Optional[str] = None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 10,
                 resume: bool = True) -> KruskalTensor:
    """Distributed CPD-ALS over an n-D grid mesh (MEDIUM decomposition).

    `local_engine`: "blocked" (the default) runs the single-chip
    blocked MTTKRP engine inside every cell over per-cell sorted
    layouts (≙ mttkrp_csf per rank, mpi_cpd.c:714); "stream" keeps the
    naive gather+segment_sum formulation (the differential oracle).
    Memmapped (out-of-core) tensors keep the blocked engine: the
    decomposition builds via streamed chunked passes and the cell
    layouts via the chunked counting sort, disk-backed under `out_dir`
    when given — bounded host RSS at any scale.

    `relabel` picks the fence-balancing strategy:

    - "balanced" (also the automatic default when the equal-fence fill
      is poor): nnz-balanced fences via capacity-constrained row
      relabeling (balanced_relabel ≙ p_find_layer_boundaries,
      src/mpi/mpi_io.c:365-439);
    - any splatt_tpu.reorder PERM_TYPES entry ("random"/"graph"/
      "hgraph"/"fibsched"): a full index relabeling before decomposing
      — equal fences over relabeled indices ≈ balanced statistically.

    Factor row order is restored afterwards in both cases.
    """
    opts = (opts or default_opts()).validate()
    dtype = resolve_dtype(opts, tt.vals.dtype)

    balance = None  # auto: balance when equal fences fill poorly
    if relabel == "balanced":
        balance, relabel = True, None
    elif relabel is not None:
        balance = False  # explicit relabeling supersedes fence balancing
    perm = None
    if relabel is not None:
        if checkpoint_path is not None:
            # a PERM_TYPES relabel permutes the index space BEFORE the
            # decomposition, so checkpoints would be written in the
            # permuted row space — indistinguishable by shape from an
            # original-space checkpoint on resume.  Refuse loudly
            # rather than silently resume wrong rows.
            raise ValueError(
                "checkpoint_path cannot be combined with a PERM_TYPES "
                "relabel (checkpoints would be in the permuted row "
                "space); use relabel='balanced' or checkpoint without "
                "relabeling")
        from splatt_tpu.reorder import reorder

        perm = reorder(tt, relabel, seed=opts.seed())
        tt = perm.apply(tt)
        if init is not None:
            # init rows are in original labels; move them to relabeled
            # space (row new = row iperm[new] of the original)
            init = [np.asarray(U)[perm.iperms[m]]
                    if perm.iperms[m] is not None else U
                    for m, U in enumerate(init)]

    # A user-supplied mesh either already has the m<k> grid axes (use its
    # shape as the grid) or is treated as a pool of devices to arrange.
    devices = None
    if mesh is not None:
        expected = tuple(_axis(m) for m in range(tt.nmodes))
        if tuple(mesh.axis_names) == expected:
            grid = grid or tuple(mesh.shape[a] for a in expected)
        else:
            devices = list(np.asarray(mesh.devices).flatten())
            grid = grid or auto_grid(len(devices), tt.dims)
            mesh = None

    decomp = GridDecomp.build(tt, grid=grid,
                              n_devices=len(devices) if devices else None,
                              val_dtype=dtype, balance=balance,
                              out_dir=(os.path.join(out_dir, "scatter")
                                       if out_dir is not None else None))
    mesh = mesh or decomp.make_mesh(devices=devices)
    xnormsq = tt.normsq()

    # achieved cell balance, always recorded (layout_imbalance rides
    # --json / MULTICHIP — docs/layout-balance.md): every cell is
    # padded to the fullest, so max/mean IS the wasted-compute factor
    from splatt_tpu.parallel.common import record_shard_imbalance

    record_shard_imbalance(
        "grid_cell", decomp.cell_counts,
        policy=("balanced" if decomp.relabels is not None else "equal"),
        fill=round(float(decomp.fill), 3))

    if opts.verbosity >= Verbosity.HIGH:
        # ≙ mpi_rank_stats + mpi_send_recv_stats (src/stats.c:298-457,
        # src/splatt_mpi.h:453-463)
        print(f"GRID {'x'.join(str(g) for g in decomp.grid)} "
              f"fill={decomp.fill:0.2f}")
        print(imbalance_report(decomp.cell_counts, "cell"))
        for line in comm_volume_report(
                decomp.dims_pad, rank,
                np.dtype(dtype).itemsize, grid=decomp.grid):
            print(line)

    cells_dev = ()
    cells_host = None
    if local_engine is None:
        from splatt_tpu.parallel.common import auto_local_engine

        local_engine = auto_local_engine(tt, out_dir)
    if local_engine == "blocked":
        cells_host = decomp.build_cell_layouts(
            opts, out_dir=out_dir).device_put(mesh, tt.nmodes)
    elif local_engine != "stream":
        raise ValueError(f"unknown local_engine {local_engine!r}")
    if cells_host is not None:
        cells_dev = tuple((c["inds"], c["vals"], c["row_start"])
                          for c in cells_host)
        # the blocked sweep never reads the stream COO arrays — put
        # 1-entry dummies instead of keeping a dead O(nnz) copy in HBM
        axes_p = [_axis(m) for m in range(tt.nmodes)]
        inds = jax.device_put(
            np.zeros((tt.nmodes, *decomp.grid, 1), np.int32),
            NamedSharding(mesh, P(None, *axes_p, None)))
        vals = jax.device_put(
            np.zeros((*decomp.grid, 1), dtype),
            NamedSharding(mesh, P(*axes_p, None)))
    else:
        inds, vals = decomp.device_put(mesh)
    factors_host = (init if init is not None
                    else init_factors(tt.dims, rank, opts.seed(),
                                      dtype=dtype))
    factors = decomp.shard_factors(
        [jnp.asarray(f, dtype=dtype) for f in factors_host], mesh)
    from splatt_tpu.ops.linalg import gram

    gram_sharding = NamedSharding(mesh, P())
    grams = tuple(jax.device_put(gram(U), gram_sharding) for U in factors)

    profiled = opts.verbosity >= Verbosity.HIGH
    if profiled:
        # split-jit phases with blocking timers: measured per-phase
        # attribution (≙ mpi_time_stats) at the cost of fusion
        sweep = make_grid_profiled_sweep(mesh, decomp,
                                         opts.regularization, dtype,
                                         cells=cells_host)
    else:
        sweep = make_grid_sweep(mesh, decomp, opts.regularization,
                                cells=cells_host)

    def step(factors, grams, flag):
        return sweep(inds, vals, factors, grams, flag, cells_dev)

    if profiled:
        from splatt_tpu.parallel.common import wrap_profiled_step

        step = wrap_profiled_step(step)

    out = run_distributed_als(step, factors, grams, rank, opts, xnormsq,
                              tt.dims, dtype,
                              row_select=decomp.row_select(),
                              checkpoint_path=checkpoint_path,
                              checkpoint_every=checkpoint_every,
                              resume=resume)
    if profiled:
        from splatt_tpu.parallel.common import dist_phase_report

        for line in dist_phase_report():
            print(line)
    if perm is not None:
        out = KruskalTensor(
            factors=[jnp.asarray(perm.apply_to_factor(np.asarray(U), m))
                     for m, U in enumerate(out.factors)],
            lam=out.lam, fit=out.fit)
    return out
