"""`splatt serve` — an isolated, crash-resumable multi-tenant
decomposition daemon (ROADMAP open item 4; docs/serve.md).

The million-user scenario is many concurrent jobs, not one big run.
This module turns the single-run reliability spine (failure taxonomy,
engine demotion, health sentinel + rollback, deadline watchdog) into a
SERVICE without letting one tenant's failures poison its neighbors:

Durable job queue
    Every accepted job is journaled to an append-only JSONL file
    (:class:`Journal`) before the submitter hears "accepted" — one
    fsynced line per state transition (``accepted`` → ``started`` →
    ``done``/``failed``, plus ``resumed``/``interrupted``/``rejected``).
    A crashed or preempted daemon replays the journal on start: every
    accepted-but-non-terminal job is re-enqueued (a ``job_resumed``
    event) and resumes from its last hardened checkpoint — the
    checksummed, ``.bak``-generationed checkpoints of cpd.py, one per
    job under ``<root>/ckpt/``.  A torn final line (SIGKILL mid-append)
    is skipped, never fatal.

Per-job isolation
    Each job runs under :func:`splatt_tpu.resilience.scope`: its engine
    demotions, health verdicts, retry budget, watchdog deadline and
    run-report events are attributed to the job and invisible to every
    neighbor — one tenant's NUMERICAL rollback or OOM demotion must not
    steer another tenant's dispatch (≙ the reference's per-run
    ``splatt_opts``/workspace separation).  A job spec may declare its
    own fault schedule (``"faults"``, SPLATT_FAULTS grammar), armed via
    :func:`splatt_tpu.utils.faults.scoped` inside that job only.  The
    probe/tune/compile caches stay SHARED and warm — the Nth request in
    a known shape regime pays zero compile — behind the locked cache
    protocol (ops/pallas_kernels.py).

Overload handling
    The pending queue is bounded (``SPLATT_SERVE_QUEUE_MAX``); a
    submission past the bound is load-shed with an explicit rejection
    (``queue_full`` event + a ``rejected`` result) instead of queueing
    unboundedly.  Per-job deadlines ride the PR 5 watchdog
    (``SPLATT_SERVE_JOB_DEADLINE_S`` / spec ``deadline_s``).  SIGTERM
    drains gracefully: running jobs checkpoint through the cpd ``stop``
    hook and are journaled ``interrupted`` (→ resumed next start),
    queued jobs simply stay journaled.

Job API (machine-readable)
    Filed requests: clients drop ``<id>.json`` job specs into
    ``<root>/requests/`` (:func:`file_request` writes them atomically);
    the daemon ingests, journals and deletes them.  Results appear as
    ``<root>/results/<id>.json`` carrying the same machine-readable
    schema as ``splatt cpd --json`` (fit, events, demotions) plus the
    job's status.  :func:`read_status` / :func:`read_result` are the
    client-side readers.  The :class:`Server` methods are the same API
    in-process.

A job spec is a JSON object::

    {"id": "j1", "rank": 8, "iters": 25, "seed": 0,
     "synthetic": {"dims": [40, 32, 24], "nnz": 3000, "seed": 0},
     # or "tensor": "/path/to/tensor.tns",
     "tol": 1e-5, "checkpoint_every": 5, "tune": false,
     "autotune": null, "health_retries": null, "deadline_s": null,
     "faults": ""}
"""

from __future__ import annotations

import json
import os
import re
import signal
import threading
import time
import uuid
from collections import deque
from typing import Callable, Dict, List, Optional

# journal record kinds (the `rec` field of each JSONL line)
#: in-memory-only reservation state while the accept append fsyncs
#: (never journaled; a concurrent same-id submission dedups on it)
ACCEPTING = "accepting"
ACCEPTED = "accepted"
STARTED = "started"
RESUMED = "resumed"
INTERRUPTED = "interrupted"
DONE = "done"          # terminal: converged or degraded (see status)
FAILED = "failed"      # terminal: a classified error
REJECTED = "rejected"  # terminal: load-shed or invalid

#: records after which a job needs no further work
TERMINAL = (DONE, FAILED, REJECTED)

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


def _job_id(spec: dict) -> str:
    """The job's id: the spec's, else a fresh one.  Ids name journal
    records, checkpoint files and result files, so they are restricted
    to a filesystem-safe alphabet."""
    jid = str(spec.get("id") or uuid.uuid4().hex[:12])
    if not _ID_RE.match(jid):
        raise ValueError(
            f"job id {jid!r} is not filesystem-safe (want "
            f"[A-Za-z0-9][A-Za-z0-9._-]*, max 64 chars)")
    return jid


class Journal:
    """Append-only JSONL job journal with durable, atomic appends.

    One `write()` of a full line + flush + fsync per record: a SIGKILL
    can tear at most the final line, which :meth:`replay` skips (the
    record it carried is re-derived — an un-journaled terminal record
    just means the job re-runs, and resume makes that cheap).  Appends
    are serialized across threads; the journal is single-writer by
    design (one daemon per serve root)."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()

    def append(self, rec: dict) -> None:
        """Durably append one record (raises on IO failure — callers
        decide whether durability is load-bearing for this record)."""
        from splatt_tpu.utils import faults

        faults.maybe_fail("serve.journal_write")
        line = json.dumps(dict(rec, ts=time.time()), sort_keys=True)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())

    def replay(self):
        """Parse every complete record → (records, torn_line_count).
        A torn/garbled line (the one a SIGKILL can leave) is counted
        and skipped — replay must never die on its own crash debris."""
        recs: List[dict] = []
        torn = 0
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        torn += 1
                        continue
                    if isinstance(rec, dict):
                        recs.append(rec)
                    else:
                        torn += 1
        except FileNotFoundError:
            pass  # fresh serve root: nothing journaled yet
        return recs, torn


class Server:
    """The serve daemon: a bounded, journal-backed job queue and a
    small supervisor pool running each CPD under the guarded drivers
    with per-job resilience scoping (module docstring; docs/serve.md).
    """

    def __init__(self, root: str, workers: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 poll_s: Optional[float] = None,
                 job_deadline_s: Optional[float] = None,
                 verbose: bool = False):
        from splatt_tpu.utils.env import read_env_float, read_env_int

        self.root = os.path.abspath(root)
        self.requests_dir = os.path.join(self.root, "requests")
        self.results_dir = os.path.join(self.root, "results")
        self.ckpt_dir = os.path.join(self.root, "ckpt")
        for d in (self.root, self.requests_dir, self.results_dir,
                  self.ckpt_dir):
            os.makedirs(d, exist_ok=True)
        self.journal = Journal(os.path.join(self.root, "journal.jsonl"))
        self.workers = int(workers if workers is not None
                           else read_env_int("SPLATT_SERVE_WORKERS"))
        self.queue_max = int(queue_max if queue_max is not None
                             else read_env_int("SPLATT_SERVE_QUEUE_MAX"))
        self.poll_s = float(poll_s if poll_s is not None
                            else read_env_float("SPLATT_SERVE_POLL_S"))
        self.job_deadline_s = float(
            job_deadline_s if job_deadline_s is not None
            else read_env_float("SPLATT_SERVE_JOB_DEADLINE_S"))
        # metrics cadence (docs/observability.md): with a path set, the
        # registry is snapshotted in Prometheus text format every
        # interval seconds and at daemon exit; interval <= 0 snapshots
        # at exit only
        from splatt_tpu.utils.env import read_env

        self.metrics_path = read_env("SPLATT_METRICS_PATH") or None
        self.metrics_interval_s = float(
            read_env_float("SPLATT_METRICS_INTERVAL_S"))
        self._metrics_last = 0.0
        self.verbose = verbose
        self._lock = threading.Lock()
        #: id -> {"spec": dict|None, "state": str, "status": str|None,
        #:        "resumed": bool}
        self._jobs: Dict[str, dict] = {}
        self._queue: deque = deque()
        self._draining = threading.Event()
        self._replay()

    # -- crash recovery -----------------------------------------------------

    def _replay(self) -> None:
        """Rebuild queue state from the journal: the last record per
        job wins; every accepted-but-non-terminal job is re-enqueued
        (``job_resumed``) and will resume from its checkpoint."""
        from splatt_tpu import resilience

        recs, torn = self.journal.replay()
        if torn:
            self._log(f"journal: skipped {torn} torn line(s) "
                      f"(crash debris)")
        for rec in recs:
            jid = rec.get("job")
            kind = rec.get("rec")
            if not jid or not kind:
                continue
            j = self._jobs.setdefault(
                jid, {"spec": None, "state": None, "status": None,
                      "resumed": False})
            if kind == ACCEPTED:
                j["spec"] = rec.get("spec")
                j["state"] = ACCEPTED
            else:
                j["state"] = kind
                if kind == DONE:
                    j["status"] = rec.get("status")
        for jid, j in self._jobs.items():
            if j["state"] in TERMINAL or j["spec"] is None:
                continue
            j["resumed"] = True
            self._queue.append(jid)
            resilience.run_report().add("job_resumed", job=jid,
                                        from_state=j["state"])
            self._log(f"job {jid}: resumed from journal "
                      f"(was {j['state']})")
            try:
                self.journal.append({"rec": RESUMED, "job": jid})
            except Exception as e:
                # lineage entry only — the ACCEPTED record already
                # guarantees a later replay re-finds this job
                self._warn_journal("resume", jid, e)
        if self._queue:
            self._queue_metric(len(self._queue))

    # -- submission / job API ----------------------------------------------

    def submit(self, spec: dict) -> dict:
        """Accept (journal durably + enqueue) or reject one job.

        Durability-first: the submitter hears "accepted" only after the
        journal append succeeded — a submission the journal cannot
        record is REJECTED, because a crash would silently forget it.
        A full pending queue load-sheds with an explicit ``queue_full``
        rejection.  Re-submitting a known id is idempotent (a crashed
        client retrying, or a spool file re-ingested after a crash)."""
        from splatt_tpu import resilience
        from splatt_tpu.utils import faults

        faults.maybe_fail("serve.submit")
        jid = _job_id(spec)
        spec = dict(spec, id=jid)
        # decide under the lock, do the durable IO OUTSIDE it: fsyncs
        # must not stall the daemon's control plane (status/summary/
        # worker dequeue all share this lock)
        reason = None
        with self._lock:
            known = self._jobs.get(jid)
            if known is not None and known["state"] != REJECTED:
                # idempotent re-submission of a live/terminal job; a
                # REJECTED id may be resubmitted — load shedding is an
                # invitation to retry, not a permanent verdict
                return {"job": jid, "state": known["state"],
                        "duplicate": True}
            if not (spec.get("synthetic") or spec.get("tensor")):
                reason = ("invalid: no workload (give 'synthetic' or "
                          "'tensor')")
            elif spec.get("faults"):
                # validate the declared chaos schedule at the door: a
                # typo rejects THIS submission with the parse error
                # instead of failing the job at run time
                try:
                    faults.parse_schedule(str(spec["faults"]))
                except (ValueError, TypeError) as e:
                    reason = f"invalid: bad faults schedule ({e})"
            if reason is None and self.queue_max > 0 \
                    and len(self._queue) >= self.queue_max:
                resilience.run_report().add("queue_full", job=jid,
                                            queue_max=self.queue_max)
                reason = "queue_full"
            if reason is None:
                # reserve the id so a concurrent same-id submission
                # dedups while we journal lock-free below
                self._jobs[jid] = {"spec": spec, "state": ACCEPTING,
                                   "status": None, "resumed": False}
        if reason is not None:
            return self._reject(jid, spec, reason)
        # durability-first: the submitter hears "accepted" only once
        # this append has fsynced
        try:
            self.journal.append({"rec": ACCEPTED, "job": jid,
                                 "spec": spec})
        except Exception as e:
            cls = resilience.classify_failure(e)
            return self._reject(
                jid, spec, f"journal_error ({cls.value}: "
                f"{resilience.failure_message(e)[:120]})")
        resilience.run_report().add("job_accepted", job=jid)
        with self._lock:
            self._jobs[jid]["state"] = ACCEPTED
            self._queue.append(jid)
            # gauge published under the lock: concurrent workers'
            # pop/publish pairs stay ordered, so the depth is
            # monotone-consistent with the queue
            self._queue_metric(len(self._queue))
        self._log(f"job {jid}: accepted")
        return {"job": jid, "state": ACCEPTED}

    def _reject(self, jid: str, spec: dict, reason: str) -> dict:
        """Record one rejection (result file + best-effort journal
        line) — explicit load shedding, never a silent drop.  Takes
        the server lock only for the state update; the IO runs
        outside it."""
        from splatt_tpu import resilience

        with self._lock:
            self._jobs[jid] = {"spec": spec, "state": REJECTED,
                               "status": "rejected", "resumed": False}
        try:
            self.journal.append(
                {"rec": REJECTED, "job": jid, "reason": reason})
        except Exception as e:
            # the rejection itself needs no durability: an un-journaled
            # rejected job simply never existed after a restart
            self._warn_journal("reject", jid, e)
        self._write_result(jid, {"job": jid, "status": "rejected",
                                 "reason": reason})
        from splatt_tpu import trace

        trace.metric_inc("splatt_serve_jobs_total", status="rejected",
                         job=jid)
        self._log(f"job {jid}: rejected ({reason})")
        return {"job": jid, "state": REJECTED, "reason": reason}

    def status(self, jid: str) -> dict:
        """The job's current state (and terminal status, when known)."""
        with self._lock:
            j = self._jobs.get(jid)
            if j is None:
                return {"job": jid, "state": None}
            return {"job": jid, "state": j["state"],
                    "status": j["status"], "resumed": j["resumed"]}

    def result(self, jid: str) -> Optional[dict]:
        """The job's result record, or None while non-terminal."""
        return read_result(self.root, jid)

    def summary(self) -> dict:
        """Machine-readable daemon summary (the `splatt serve` exit
        report): per-job states, state counts, queue depth."""
        with self._lock:
            jobs = {jid: j["state"] for jid, j in self._jobs.items()}
            pending = len(self._queue)
        counts: Dict[str, int] = {}
        for s in jobs.values():
            counts[s] = counts.get(s, 0) + 1
        return {"jobs": jobs, "counts": counts, "pending": pending,
                "draining": self._draining.is_set()}

    # -- filed-request spool -------------------------------------------------

    def scan_requests(self) -> int:
        """Ingest filed requests: every ``*.json`` under ``requests/``
        is parsed, submitted and unlinked — journal-first, so a crash
        between journaling and unlink re-ingests a known id, which the
        idempotent :meth:`submit` dedups.  A malformed or failing
        request is quarantined as ``<name>.bad`` (classified, logged)
        so the scanner cannot spin on it."""
        from splatt_tpu import resilience

        n = 0
        for name in sorted(os.listdir(self.requests_dir)):
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.requests_dir, name)
            try:
                with open(path) as f:
                    spec = json.load(f)
                if not isinstance(spec, dict):
                    raise ValueError("job spec must be a JSON object")
                spec.setdefault("id", name[:-5])
                self.submit(spec)
                n += 1
            except Exception as e:
                cls = resilience.classify_failure(e)
                self._log(f"request {name} failed to ingest "
                          f"({cls.value}: "
                          f"{resilience.failure_message(e)[:120]}); "
                          f"quarantined as {name}.bad", error=True)
                try:
                    os.replace(path, path + ".bad")
                except OSError:
                    pass
                continue
            try:
                os.unlink(path)
            except OSError:
                pass  # re-ingested next scan; submit dedups
        return n

    # -- supervisor ----------------------------------------------------------

    def _next(self) -> Optional[str]:
        with self._lock:
            jid = self._queue.popleft() if self._queue else None
            if jid is not None:
                self._queue_metric(len(self._queue))
        return jid

    @staticmethod
    def _queue_metric(depth: int) -> None:
        from splatt_tpu import trace

        trace.metric_set("splatt_serve_queue_depth", float(depth))

    def run_once(self) -> dict:
        """Ingest the spool, then run every queued job to a terminal
        state (or until a drain interrupts) on `workers` supervisor
        threads.  Returns :meth:`summary`."""
        from splatt_tpu import resilience

        self.scan_requests()
        with self._lock:
            idle = not self._queue
        if idle:
            # nothing queued (the serve_forever steady state): skip
            # worker-thread construction entirely — an idle daemon
            # must not churn threads twice a second
            return self.summary()

        def loop():
            while not self._draining.is_set():
                jid = self._next()
                if jid is None:
                    return
                try:
                    self._run_job(jid)
                except Exception as e:
                    # backstop: _run_job handles job failures itself,
                    # so anything landing here is a supervisor bug —
                    # mark the job failed (classified) rather than
                    # dying silently and stranding the rest of the
                    # queue behind a dead worker
                    cls = resilience.classify_failure(e)
                    msg = resilience.failure_message(e)[:200]
                    self._log(f"job {jid}: supervisor error "
                              f"({cls.value}: {msg})", error=True)
                    self._write_result(jid, {"job": jid,
                                             "status": "failed",
                                             "failure_class": cls.value,
                                             "error": msg})
                    try:
                        self.journal.append({"rec": FAILED, "job": jid,
                                             "status": "failed"})
                    except Exception as e2:
                        self._warn_journal("finish", jid, e2)
                    with self._lock:
                        self._jobs[jid]["state"] = FAILED
                        self._jobs[jid]["status"] = "failed"

        threads = [threading.Thread(target=loop, daemon=True,
                                    name=f"splatt-serve-w{i}")
                   for i in range(max(self.workers, 1))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return self.summary()

    def serve_forever(self) -> dict:
        """The daemon loop: process the queue, poll the spool, repeat —
        until a drain (SIGTERM via :meth:`install_signal_handlers`, or
        :meth:`drain`).  Returns the final :meth:`summary`."""
        while not self._draining.is_set():
            self.run_once()
            self._maybe_write_metrics()
            self._draining.wait(self.poll_s)
        self.write_metrics_now()
        return self.summary()

    # -- metrics snapshots (docs/observability.md) ---------------------------

    def _maybe_write_metrics(self) -> None:
        """One cadence tick: snapshot the registry to
        ``SPLATT_METRICS_PATH`` when the interval elapsed (interval
        <= 0 means exit-only snapshots)."""
        if not self.metrics_path or self.metrics_interval_s <= 0:
            return
        now = time.monotonic()
        if now - self._metrics_last >= self.metrics_interval_s:
            self.write_metrics_now()

    def write_metrics_now(self) -> Optional[dict]:
        """Force one Prometheus-text snapshot (atomic replace; a write
        failure degrades classified inside write_metrics — metrics must
        never kill the daemon they observe).  No-op without
        ``SPLATT_METRICS_PATH``."""
        if not self.metrics_path:
            return None
        from splatt_tpu import trace

        self._metrics_last = time.monotonic()
        return trace.write_metrics(self.metrics_path)

    def drain(self) -> None:
        """Begin a graceful drain: stop pulling queued jobs, interrupt
        running jobs at their next fit check (they checkpoint through
        the cpd `stop` hook and are journaled ``interrupted``), leave
        everything else journaled for the next start."""
        self._draining.set()

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → graceful drain (main thread only)."""
        signal.signal(signal.SIGTERM, lambda s, f: self.drain())
        signal.signal(signal.SIGINT, lambda s, f: self.drain())

    # -- one supervised job --------------------------------------------------

    def _run_job(self, jid: str) -> None:
        from splatt_tpu import resilience

        with self._lock:
            j = self._jobs[jid]
            spec, resumed = j["spec"], j["resumed"]
            j["state"] = STARTED
        try:
            self.journal.append({"rec": STARTED, "job": jid})
        except Exception as e:
            # non-fatal: without this line a crash replays the job from
            # ACCEPTED — it re-runs, and checkpoint resume makes the
            # re-run cheap
            self._warn_journal("start", jid, e)
        self._log(f"job {jid}: started" + (" (resumed)" if resumed else ""))
        from splatt_tpu import trace

        # one span per supervised job (docs/observability.md): with
        # tracing on, a tenant's whole run — cpd.als and its guard
        # spans nested under it — carries the job id
        with trace.span("serve.job", job=jid, resumed=resumed):
            record = self._execute(jid, spec, resumed)
        if record is None:
            # drain interrupt: NOT terminal — the job already
            # checkpointed via the stop hook; journal the interruption
            # so the restart lineage is explicit
            try:
                self.journal.append({"rec": INTERRUPTED, "job": jid})
            except Exception as e:
                self._warn_journal("interrupt", jid, e)
            with self._lock:
                self._jobs[jid]["state"] = INTERRUPTED
            self._log(f"job {jid}: interrupted by drain (checkpointed; "
                      f"resumes next start)")
            return
        self._write_result(jid, record)
        kind = FAILED if record["status"] == "failed" else DONE
        try:
            self.journal.append({"rec": kind, "job": jid,
                                 "status": record["status"]})
        except Exception as e:
            self._warn_journal("finish", jid, e)
        with self._lock:
            self._jobs[jid]["state"] = kind
            self._jobs[jid]["status"] = record["status"]
        self._log(f"job {jid}: {record['status']}"
                  + (f" fit={record['fit']:.5f}"
                     if record.get("fit") is not None else ""))

    def _execute(self, jid: str, spec: dict, resumed: bool
                 ) -> Optional[dict]:
        """Run one job under its own resilience scope and fault
        schedule; returns the result record, or None when a drain
        interrupted the run (already checkpointed, not terminal)."""
        from splatt_tpu import resilience
        from splatt_tpu.utils import faults

        t0 = time.time()
        stopped = {"drain": False, "deadline": False}

        def _stop() -> bool:
            if self._draining.is_set():
                stopped["drain"] = True
                return True
            return False

        # an explicit deadline_s (0 included — a documented opt-out for
        # a known-long job) beats the server default; only an UNSET
        # spec field falls back to it
        ds = spec.get("deadline_s")
        deadline_s = float(ds if ds is not None
                           else (self.job_deadline_s or 0.0))
        deadline_end = (time.monotonic() + deadline_s
                        if deadline_s > 0 else None)

        def _stop_or_deadline() -> bool:
            # the watchdog timer cannot preempt a worker thread (no
            # interrupt_main off the main thread), so the deadline is
            # ALSO enforced cooperatively through the same fit-check
            # poll the drain uses — a runaway job releases its worker
            # at the next check instead of holding the queue hostage
            if deadline_end is not None \
                    and time.monotonic() > deadline_end:
                stopped["deadline"] = True
                return True
            return _stop()

        with resilience.scope(jid,
                              health_retries=spec.get("health_retries"),
                              deadline_s=spec.get("deadline_s")) as sc:
            record: dict = {"job": jid}
            armed: Dict[str, object] = {}
            try:
                # the job's declared fault schedule parses INSIDE the
                # guarded region: a tenant's typo fails THAT job,
                # classified — never the supervisor thread
                with faults.scoped(spec.get("faults") or "") as armed:
                    with resilience.deadline("serve.job_run",
                                             deadline_s
                                             if deadline_s > 0 else 0):
                        faults.maybe_fail("serve.job_run")
                        out, tune_info = self._run_cpd(
                            jid, spec, _stop_or_deadline)
                        if stopped["deadline"]:
                            # the cooperative stop beat the post-hoc
                            # timer raise: convert explicitly (with
                            # the watchdog's own event) so the verdict
                            # is TIMEOUT either way
                            resilience.run_report().add(
                                "deadline_blown", site="serve.job_run",
                                seconds=float(deadline_s))
                            raise resilience.DeadlineExceeded(
                                f"splatt deadline blown at "
                                f"serve.job_run after {deadline_s:g}s "
                                f"(cooperative job-deadline stop)")
                if stopped["drain"]:
                    return None
                degraded = bool(sc.report.events("health_degraded"))
                if degraded:
                    # run_report() here IS the job scope's report
                    resilience.run_report().add(
                        "job_degraded", job=jid,
                        failure_class="numerical",
                        error="health-retry budget exhausted")
                record.update(status="degraded" if degraded
                              else "converged",
                              fit=float(out.fit))
                if tune_info is not None:
                    record["tune"] = tune_info
            except Exception as e:
                cls = resilience.classify_failure(e)
                msg = resilience.failure_message(e)[:200]
                resilience.run_report().add(
                    "job_degraded", job=jid,
                    failure_class=cls.value, error=msg)
                record.update(status="failed",
                              failure_class=cls.value, error=msg)
            # fired counts survive both outcomes (a failed NaN job's
            # evidence matters most); {} when the schedule never parsed
            fired = {site: s.fired for site, s in armed.items()
                     if s.fired}
            record.update(
                resumed=resumed, seconds=round(time.time() - t0, 3),
                degraded=record["status"] != "converged",
                events=[{k: v for k, v in e.items() if k != "ts"}
                        for e in sc.report.events()],
                demotions=[dict(engine=d.engine,
                                failure_class=d.failure_class.value,
                                shape_key=d.shape_key,
                                error=d.error[:120])
                           for d in resilience.demotions()])
            if fired:
                record["faults_fired"] = fired
            # terminal-job metrics, recorded INSIDE the scope so every
            # sample carries this tenant's job label, then the job's
            # own cut of the registry embedded in its result — a
            # neighbor's counters never appear (docs/observability.md)
            from splatt_tpu import trace

            trace.metric_inc("splatt_serve_jobs_total",
                             status=record["status"])
            trace.metric_observe("splatt_job_seconds",
                                 float(record["seconds"]))
            record["metrics"] = trace.metrics_snapshot(job=jid)
        return record

    def _run_cpd(self, jid: str, spec: dict, stop: Callable[[], bool]):
        """The job body: workload → (optional pre-tune) → blocked
        build → guarded cpd_als with a per-job checkpoint."""
        import dataclasses

        from splatt_tpu import tune as _tune
        from splatt_tpu.blocked import BlockedSparse
        from splatt_tpu.config import Options, Verbosity
        from splatt_tpu.cpd import cpd_als

        tt = _load_workload(spec)
        rank = int(spec.get("rank", 8))
        opts = Options(
            random_seed=int(spec.get("seed", 0)),
            max_iterations=int(spec.get("iters", 25)),
            tolerance=float(spec.get("tol", 1e-5)),
            verbosity=Verbosity.LOW if self.verbose else Verbosity.NONE,
            use_pallas=spec.get("use_pallas"),
            autotune=spec.get("autotune"),
            engine_fallback=spec.get("engine_fallback"))
        tune_info = None
        if spec.get("tune"):
            # pre-tune inside the job scope: the Nth same-regime job
            # hits the warm shared plan cache (measured == 0), which is
            # the serving payoff the result records as evidence
            res = _tune.tune(tt, rank=rank, opts=opts)
            tune_info = dict(
                measured=res.measured, cache_hits=res.cache_hits,
                skipped=res.skipped,
                plans={str(m): dataclasses.asdict(p)
                       for m, p in sorted(res.plans.items())})
        bs = BlockedSparse.compile(tt, opts, rank=rank)
        ckpt = os.path.join(self.ckpt_dir, f"{jid}.npz")
        out = cpd_als(bs, rank=rank, opts=opts, checkpoint_path=ckpt,
                      checkpoint_every=int(spec.get("checkpoint_every", 5)),
                      stop=stop)
        return out, tune_info

    # -- plumbing ------------------------------------------------------------

    def _write_result(self, jid: str, record: dict) -> None:
        """Atomic result publish (tmp + rename): a reader never sees a
        torn result file."""
        from splatt_tpu import resilience

        path = os.path.join(self.results_dir, f"{jid}.json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(record, f, sort_keys=True)
            os.replace(tmp, path)
        except Exception as e:
            cls = resilience.classify_failure(e)
            self._log(f"job {jid}: result write failed ({cls.value}: "
                      f"{resilience.failure_message(e)[:120]}) — the "
                      f"journal still carries the terminal state",
                      error=True)

    def _warn_journal(self, op: str, jid: str, exc) -> None:
        """Classified warn-and-continue for non-load-bearing journal
        appends (submission appends are load-bearing and reject
        instead — see submit)."""
        from splatt_tpu import resilience

        cls = resilience.classify_failure(exc)
        self._log(f"job {jid}: journal append ({op}) failed "
                  f"({cls.value}: "
                  f"{resilience.failure_message(exc)[:120]}); "
                  f"continuing — replay re-derives this record",
                  error=True)

    def _log(self, msg: str, error: bool = False) -> None:
        import sys

        if error or self.verbose:
            print(f"splatt-serve: {msg}",
                  file=sys.stderr if error else sys.stdout, flush=True)


def _load_workload(spec: dict):
    """The job's tensor: an on-disk file (``tensor``) or a seeded
    synthetic (``synthetic: {dims, nnz, seed}``)."""
    if spec.get("tensor"):
        from splatt_tpu.io import load

        return load(spec["tensor"])
    syn = spec.get("synthetic")
    if not isinstance(syn, dict) or not syn.get("dims"):
        raise ValueError("job spec needs 'tensor': <path> or "
                         "'synthetic': {dims, nnz, seed}")
    from splatt_tpu.chaos import synthetic_tensor

    return synthetic_tensor(tuple(int(d) for d in syn["dims"]),
                            int(syn.get("nnz", 1000)),
                            int(syn.get("seed", 0)))


# -- client-side filed-request API -------------------------------------------

def file_request(root: str, spec: dict) -> str:
    """Client side of the filed-request API: atomically drop a job
    spec into ``<root>/requests/`` for a (possibly not-yet-running)
    daemon to ingest.  Returns the job id."""
    jid = _job_id(spec)
    spec = dict(spec, id=jid)
    reqs = os.path.join(os.path.abspath(root), "requests")
    os.makedirs(reqs, exist_ok=True)
    tmp = os.path.join(reqs, f".{jid}.tmp")
    with open(tmp, "w") as f:
        json.dump(spec, f)
    os.replace(tmp, os.path.join(reqs, f"{jid}.json"))
    return jid


def read_result(root: str, jid: str) -> Optional[dict]:
    """The published result record for `jid`, or None while the job is
    non-terminal (or unknown)."""
    path = os.path.join(os.path.abspath(root), "results", f"{jid}.json")
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except ValueError:
        return None  # mid-replace torn read cannot happen (atomic
        #               rename); a hand-damaged file reads as absent


def read_status(root: str, jid: str) -> dict:
    """Journal-derived job state (client side, no daemon needed): the
    last journal record wins; the result record rides along when the
    job is terminal."""
    journal = Journal(os.path.join(os.path.abspath(root),
                                   "journal.jsonl"))
    recs, _ = journal.replay()
    state = None
    status = None
    for rec in recs:
        if rec.get("job") != jid:
            continue
        state = rec.get("rec")
        if state in (DONE, FAILED):
            status = rec.get("status")
        elif state == REJECTED:
            status = "rejected"
        else:
            status = None  # re-accepted after a rejection: not terminal
    out = {"job": jid, "state": state, "status": status}
    if state in TERMINAL:
        res = read_result(root, jid)
        if res is not None:
            out["result"] = res
    # a spool file not yet ingested still counts as "filed"
    if state is None and os.path.exists(
            os.path.join(os.path.abspath(root), "requests",
                         f"{jid}.json")):
        out["state"] = "filed"
    return out
