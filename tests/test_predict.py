"""The generation-fenced prediction plane (docs/predict.md).

The contracts under test:

- the math: batched entry reconstruction and the top-k slice scan
  agree with the dense reconstruction, validate their inputs loudly,
  and ride KruskalTensor as `.reconstruct()` / `.top_k()`;
- model generations: every commit advances a monotonic stamp, a
  bit-identical re-commit is IDEMPOTENT (no advance), a failed stamp
  write (the ``model.generation`` fault site) aborts the commit with
  the old generation still serving, and the previous generation
  survives as the ``.bak`` rollback;
- fenced reads: a torn (checkpoint, stamp) pair degrades classified
  (``model_torn``) down the candidate chain to the ``.bak``
  generation, an unstamped checkpoint REFUSES, and a fully shredded
  store refuses — never garbage;
- the hot-factor cache: keyed by (model, generation), LRU-bounded,
  invalidated by generation ADVANCE; a poisoned lookup (the
  ``predict.cache`` fault site) degrades to the direct fenced read
  and a failed direct read (``predict.read``) refuses classified;
- the serve lane: predicts are journaled/leased like any job but
  dispatch on a dedicated bounded low-latency lane, pin their
  staleness floor at admission (the ACCEPTED record's ``gen_pinned``)
  and replay bit-exactly on the pinned generation even when a
  concurrent commit advances the model mid-flight.
"""

import json
import os

import numpy as np
import pytest

from splatt_tpu import predict, resilience, serve
from splatt_tpu.cpd import _save_checkpoint, factor_content_sha
from splatt_tpu.kruskal import KruskalTensor
from splatt_tpu.utils import faults

DIMS = (12, 10, 8)
SYN = {"dims": list(DIMS), "nnz": 400, "seed": 0}


@pytest.fixture(autouse=True)
def _clean_state():
    def clean():
        faults.reset()
        resilience.reset_demotions()
        resilience.run_report().clear()

    clean()
    yield
    clean()


def _kt(seed=0, dims=DIMS, rank=3):
    rng = np.random.default_rng(seed)
    factors = [rng.standard_normal((d, rank)) for d in dims]
    lam = rng.uniform(0.5, 2.0, rank)
    return factors, lam


def _fit_spec(jid="base", **kw):
    spec = {"id": jid, "rank": 3, "iters": 5, "seed": 0,
            "synthetic": dict(SYN)}
    spec.update(kw)
    return spec


def _run(srv, *specs):
    for spec in specs:
        r = srv.submit(spec)
        assert r["state"] == serve.ACCEPTED, r
    srv.run_once()
    return [serve.read_result(srv.root, s["id"]) for s in specs]


# -- the math ----------------------------------------------------------------

def test_reconstruct_matches_dense():
    factors, lam = _kt()
    import jax.numpy as jnp

    kt = KruskalTensor([jnp.asarray(U) for U in factors],
                       jnp.asarray(lam), jnp.asarray(1.0))
    dense = kt.to_dense()
    coords = [[0, 0, 0], [3, 4, 5], [11, 9, 7], [5, 0, 2]]
    got = predict.reconstruct_entries(factors, lam, coords)
    want = np.array([dense[tuple(c)] for c in coords])
    np.testing.assert_allclose(got, want, rtol=1e-10)
    # 1-D coords promote to a single-row batch
    one = predict.reconstruct_entries(factors, lam, [3, 4, 5])
    assert one.shape == (1,) and one[0] == pytest.approx(dense[3, 4, 5])
    # ...and the KruskalTensor method delegates
    np.testing.assert_allclose(kt.reconstruct(coords), want, rtol=1e-6)


def test_top_k_matches_dense():
    factors, lam = _kt(seed=1)
    import jax.numpy as jnp

    kt = KruskalTensor([jnp.asarray(U) for U in factors],
                       jnp.asarray(lam), jnp.asarray(1.0))
    dense = kt.to_dense()
    idx, scores = predict.top_k_slice(factors, lam, {1: 2, 2: 1},
                                      mode=0, k=4)
    col = dense[:, 2, 1]
    want = np.argsort(-col)[:4]
    np.testing.assert_array_equal(idx, want)
    np.testing.assert_allclose(scores, col[want], rtol=1e-10)
    assert list(scores) == sorted(scores, reverse=True)
    # k clamps to the mode's dim; method delegation agrees
    all_idx, _ = kt.top_k({0: 3, 2: 0}, mode=1, k=999)
    assert len(all_idx) == DIMS[1]


def test_predict_math_validates_inputs():
    factors, lam = _kt()
    with pytest.raises(ValueError, match="modes"):
        predict.reconstruct_entries(factors, lam, [[0, 0]])
    with pytest.raises(ValueError, match="out of range"):
        predict.reconstruct_entries(factors, lam, [[0, 0, 99]])
    with pytest.raises(ValueError, match="out of range"):
        predict.reconstruct_entries(factors, lam, [[-1, 0, 0]])
    with pytest.raises(ValueError, match="pin exactly"):
        predict.top_k_slice(factors, lam, {1: 0}, mode=0, k=2)
    with pytest.raises(ValueError, match="pin exactly"):
        predict.top_k_slice(factors, lam, {0: 0, 1: 0, 2: 0},
                            mode=0, k=2)
    with pytest.raises(ValueError, match="out of range"):
        predict.top_k_slice(factors, lam, {1: 99, 2: 0}, mode=0, k=2)
    with pytest.raises(ValueError, match="mode"):
        predict.top_k_slice(factors, lam, {}, mode=7, k=2)


# -- generation stamps -------------------------------------------------------

def test_generation_advance_monotonic_and_idempotent(tmp_path):
    d = str(tmp_path)
    f1, l1 = _kt(seed=2)
    f2, l2 = _kt(seed=3)
    assert predict.current_generation(d, "m") == 0
    assert predict.advance_generation(d, "m", f1, l1) == 1
    # bit-identical re-commit (a replayed/adopted commit): NO advance
    assert predict.advance_generation(d, "m", f1, l1) == 1
    assert predict.current_generation(d, "m") == 1
    assert predict.advance_generation(d, "m", f2, l2) == 2
    assert predict.current_generation(d, "m") == 2
    # the outgoing generation survives as the rollback stamp
    bak = predict.read_stamp(predict.stamp_path(d, "m") + ".bak")
    assert bak["gen"] == 1 and bak["sha"] == factor_content_sha(f1, l1)
    evs = resilience.run_report().events("model_generation_advanced")
    assert [e["gen"] for e in evs] == [1, 2]


def test_generation_stamp_fault_aborts_advance(tmp_path):
    d = str(tmp_path)
    f1, l1 = _kt(seed=2)
    f2, l2 = _kt(seed=3)
    assert predict.advance_generation(d, "m", f1, l1) == 1
    with faults.inject("model.generation", "runtime"):
        with pytest.raises(RuntimeError):
            predict.advance_generation(d, "m", f2, l2)
    # the stamp never moved: the old generation keeps serving
    assert predict.current_generation(d, "m") == 1
    stamp = predict.read_stamp(predict.stamp_path(d, "m"))
    assert stamp["sha"] == factor_content_sha(f1, l1)


def test_garbage_stamp_is_torn_not_trusted(tmp_path):
    spath = str(tmp_path / "m.gen.json")
    with open(spath, "w") as f:
        f.write("{not json")
    assert predict.read_stamp(spath) is None
    evs = resilience.run_report().events("model_torn")
    assert evs and evs[0]["piece"] == "generation-stamp"


# -- fenced reads ------------------------------------------------------------

def test_fenced_read_serves_newest_intact_generation(tmp_path):
    d = str(tmp_path)
    f1, l1 = _kt(seed=2)
    ckpt = os.path.join(d, "m.npz")
    _save_checkpoint(ckpt, f1, l1, 0, 0.9)
    predict.advance_generation(d, "m", f1, l1)
    out = predict.load_model_generation(d, "m")
    assert out["gen"] == 1 and out["sha"] == factor_content_sha(f1, l1)
    for U, W in zip(out["factors"], f1):
        np.testing.assert_array_equal(U, W)


def test_fenced_read_degrades_to_bak_on_torn_commit(tmp_path):
    d = str(tmp_path)
    f1, l1 = _kt(seed=2)
    f2, l2 = _kt(seed=3)
    ckpt = os.path.join(d, "m.npz")
    _save_checkpoint(ckpt, f1, l1, 0, 0.9)
    predict.advance_generation(d, "m", f1, l1)
    # a commit that died between checkpoint publish and stamp advance:
    # the new factors landed but the stamp still names generation 1 —
    # the .bak checkpoint is what the stamp verifies
    _save_checkpoint(ckpt, f2, l2, 0, 0.9)
    out = predict.load_model_generation(d, "m")
    assert out is not None and out["gen"] == 1
    for U, W in zip(out["factors"], f1):
        np.testing.assert_array_equal(U, W)
    assert resilience.run_report().events("model_torn")


def test_fenced_read_falls_back_to_bak_generation(tmp_path):
    d = str(tmp_path)
    f1, l1 = _kt(seed=2)
    f2, l2 = _kt(seed=3)
    ckpt = os.path.join(d, "m.npz")
    _save_checkpoint(ckpt, f1, l1, 0, 0.9)
    predict.advance_generation(d, "m", f1, l1)
    _save_checkpoint(ckpt, f2, l2, 0, 0.95)
    predict.advance_generation(d, "m", f2, l2)
    # generation 2's checkpoint shredded on disk: the fence walks back
    # to (ckpt.bak, stamp.bak) and serves generation 1
    with open(ckpt, "wb") as f:
        f.write(b"shredded")
    out = predict.load_model_generation(d, "m")
    assert out is not None and out["gen"] == 1
    for U, W in zip(out["factors"], f1):
        np.testing.assert_array_equal(U, W)
    # ...and with the rollback generation gone too, REFUSE
    os.remove(ckpt + ".bak")
    assert predict.load_model_generation(d, "m") is None


def test_unstamped_checkpoint_refuses(tmp_path):
    d = str(tmp_path)
    f1, l1 = _kt(seed=2)
    _save_checkpoint(os.path.join(d, "m.npz"), f1, l1, 0, 0.9)
    assert predict.load_model_generation(d, "m") is None
    evs = resilience.run_report().events("model_torn")
    assert evs and evs[-1]["piece"] == "no-generation-stamp"


def test_predict_read_fault_site(tmp_path):
    d = str(tmp_path)
    f1, l1 = _kt(seed=2)
    _save_checkpoint(os.path.join(d, "m.npz"), f1, l1, 0, 0.9)
    predict.advance_generation(d, "m", f1, l1)
    with faults.inject("predict.read", "runtime"):
        with pytest.raises(RuntimeError):
            predict.load_model_generation(d, "m")
    # disarmed, the same read serves
    assert predict.load_model_generation(d, "m")["gen"] == 1


# -- the hot-factor cache ----------------------------------------------------

def test_hot_cache_lru_and_generation_keying():
    cache = predict.HotFactorCache(max_entries=2)
    cache.put("m", 1, {"gen": 1})
    cache.put("m", 2, {"gen": 2})
    # generation keying: both generations coexist — an advance
    # invalidates by NEW KEY, never by deleting the pinned entry
    assert cache.get("m", 1)["gen"] == 1
    assert cache.get("m", 2)["gen"] == 2
    cache.put("other", 1, {"gen": 1})       # evicts LRU ("m", 1)
    assert len(cache) == 2
    assert cache.get("m", 1) is None
    assert cache.get("m", 2) is not None
    # disabled storage: every put is dropped
    off = predict.HotFactorCache(max_entries=0)
    off.put("m", 1, {"gen": 1})
    assert len(off) == 0 and off.get("m", 1) is None


def test_predict_cache_fault_site():
    cache = predict.HotFactorCache(max_entries=2)
    cache.put("m", 1, {"gen": 1})
    with faults.inject("predict.cache", "runtime"):
        with pytest.raises(RuntimeError):
            cache.get("m", 1)
    assert cache.get("m", 1)["gen"] == 1


# -- the serve lane ----------------------------------------------------------

def test_serve_predict_end_to_end(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    (base,) = _run(srv, _fit_spec())
    assert base["status"] == "converged"
    # the fit COMMITTED: generation 1 stamped, journal carries it
    assert base["model"] == "base" and base["model_gen"] == 1
    assert predict.current_generation(srv.ckpt_dir, "base") == 1
    spec = {"id": "p1", "kind": "predict", "model": "base",
            "coords": [[0, 0, 0], [1, 2, 3]],
            "top_k": {"fixed": {"1": 0, "2": 0}, "mode": 0, "k": 3}}
    (res,) = _run(srv, spec)
    assert res["status"] == "served"
    assert res["gen"] == 1 and res["gen_pinned"] == 1
    assert res["cache"] == "miss" and len(res["values"]) == 2
    assert len(res["top_k"]["indices"]) == 3
    # the answer verifies against the fenced read
    loaded = predict.load_model_generation(srv.ckpt_dir, "base")
    want = predict.reconstruct_entries(loaded["factors"],
                                       loaded["lam"],
                                       [[0, 0, 0], [1, 2, 3]])
    np.testing.assert_allclose(res["values"], want, rtol=1e-12)
    # a second predict hits the warmed cache, bit-exactly
    (res2,) = _run(srv, {"id": "p2", "kind": "predict",
                         "model": "base",
                         "coords": [[0, 0, 0], [1, 2, 3]]})
    assert res2["status"] == "served" and res2["cache"] == "hit"
    assert res2["values"] == res["values"]
    # journal audit: predict ACCEPTED pins the floor, DONE carries the
    # served generation — the staleness invariant is journal-checkable
    recs, _ = serve.Journal(os.path.join(
        srv.root, "journal.jsonl")).replay()
    acc = next(r for r in recs if r["job"] == "p1"
               and r["rec"] == serve.ACCEPTED)
    done = next(r for r in recs if r["job"] == "p1"
                and r["rec"] == serve.DONE)
    assert acc["gen_pinned"] == 1
    assert done["gen"] == 1 and done["gen_pinned"] == 1
    base_done = next(r for r in recs if r["job"] == "base"
                     and r["rec"] == serve.DONE)
    assert base_done["model_gen"] == 1


def test_update_commit_advances_generation_and_serving(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _fit_spec(iters=8, checkpoint_every=2))
    (p1,) = _run(srv, {"id": "p1", "kind": "predict", "model": "base",
                       "coords": [[0, 0, 0]]})
    assert p1["gen"] == 1
    (up,) = _run(srv, {"id": "up1", "kind": "update", "base": "base",
                       "delta": {"dims": list(DIMS), "nnz": 20,
                                 "seed": 9}})
    assert up["status"] == "converged"
    assert up["model"] == "base" and up["model_gen"] == 2
    # a predict admitted after the commit serves the new generation
    (p2,) = _run(srv, {"id": "p2", "kind": "predict", "model": "base",
                       "coords": [[0, 0, 0]]})
    assert p2["status"] == "served"
    assert p2["gen"] == 2 and p2["gen_pinned"] == 2


def test_predict_pinned_race_replays_bit_exactly(tmp_path):
    """The update-commit vs predict race: a predict ACCEPTED before a
    commit but EXECUTED after it finishes on its pinned generation
    bit-exactly (the hot cache holds the pinned entry; the advance
    never deletes it)."""
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _fit_spec())
    coords = [[2, 3, 4], [0, 1, 0]]
    (warm,) = _run(srv, {"id": "pw", "kind": "predict",
                         "model": "base", "coords": coords})
    assert warm["gen"] == 1
    # accept the racing predict (pins generation 1)...
    r = srv.submit({"id": "pr", "kind": "predict", "model": "base",
                    "coords": coords})
    assert r["state"] == serve.ACCEPTED
    # ...then a concurrent committer advances the model before the
    # predict runs (new factors, new checkpoint, generation 2)
    f2, l2 = _kt(seed=44)
    _save_checkpoint(os.path.join(srv.ckpt_dir, "base.npz"),
                     f2, l2, 0, 0.9)
    assert predict.advance_generation(srv.ckpt_dir, "base",
                                      f2, l2) == 2
    srv.run_once()
    res = serve.read_result(srv.root, "pr")
    assert res["status"] == "served"
    assert res["gen_pinned"] == 1 and res["gen"] == 1
    assert res["cache"] == "hit"
    # bit-exact replay of the pinned generation's answer
    assert res["values"] == warm["values"]
    # a fresh predict (pinned at 2) serves the NEW generation
    (after,) = _run(srv, {"id": "pa", "kind": "predict",
                          "model": "base", "coords": coords})
    assert after["gen"] == 2 and after["values"] != warm["values"]


def test_predict_cache_poison_degrades_to_direct_read(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _fit_spec())
    _run(srv, {"id": "pw", "kind": "predict", "model": "base",
               "coords": [[0, 0, 0]]})     # warm the cache
    (res,) = _run(srv, {"id": "pp", "kind": "predict", "model": "base",
                        "coords": [[0, 0, 0]],
                        "faults": "predict.cache:runtime"})
    # the poisoned lookup degraded classified to the direct fenced
    # read — the answer still SERVED
    assert res["status"] == "served" and res["cache"] == "miss"
    evs = [e for e in res["events"] if e["kind"] == "predict_degraded"]
    assert evs and evs[0]["reason"] == "cache_poisoned"


def test_predict_read_fault_refuses_classified(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _fit_spec())
    (res,) = _run(srv, {"id": "pf", "kind": "predict", "model": "base",
                        "coords": [[0, 0, 0]],
                        "faults": "predict.read:runtime"})
    assert res["status"] == "refused"
    reasons = {e.get("reason") for e in res["events"]
               if e["kind"] == "predict_degraded"}
    assert {"read_failed", "no_intact_generation"} <= reasons


def test_predict_refuses_on_shredded_model(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _fit_spec())
    ckpt = os.path.join(srv.ckpt_dir, "base.npz")
    for p in (ckpt, ckpt + ".bak"):
        if os.path.exists(p):
            with open(p, "wb") as f:
                f.write(b"garbage")
    for p in (predict.stamp_path(srv.ckpt_dir, "base"),
              predict.stamp_path(srv.ckpt_dir, "base") + ".bak"):
        if os.path.exists(p):
            os.remove(p)
    (res,) = _run(srv, {"id": "px", "kind": "predict", "model": "base",
                        "coords": [[0, 0, 0]]})
    assert res["status"] == "refused"
    assert res["reason"] == "no_intact_generation"
    assert "values" not in res


def test_generation_fault_aborts_update_old_gen_serves(tmp_path):
    """A failed stamp advance (the ``model.generation`` site) fails
    the update commit CLASSIFIED — and readers keep serving the old
    generation, whose stamp never moved."""
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _fit_spec(iters=8, checkpoint_every=2))
    (up,) = _run(srv, {"id": "upf", "kind": "update", "base": "base",
                       "delta": {"dims": list(DIMS), "nnz": 20,
                                 "seed": 9},
                       "faults": "model.generation:runtime"})
    assert up["status"] == "failed"
    assert predict.current_generation(srv.ckpt_dir, "base") == 1
    (res,) = _run(srv, {"id": "p1", "kind": "predict", "model": "base",
                        "coords": [[0, 0, 0]]})
    assert res["status"] == "served" and res["gen"] == 1


def test_predict_lane_bounded_and_validated(tmp_path, monkeypatch):
    monkeypatch.setenv("SPLATT_PREDICT_QUEUE_MAX", "1")
    srv = serve.Server(str(tmp_path), workers=1)
    # validation: no model / no question → rejected loudly
    r = srv.submit({"id": "bad1", "kind": "predict",
                    "coords": [[0, 0, 0]]})
    assert r["state"] == serve.REJECTED and "model" in r["reason"]
    r = srv.submit({"id": "bad2", "kind": "predict", "model": "base"})
    assert r["state"] == serve.REJECTED and "coords" in r["reason"]
    # the predict lane's own bound load-sheds without touching the
    # fit queue
    a = srv.submit({"id": "p1", "kind": "predict", "model": "base",
                    "coords": [[0, 0, 0]]})
    assert a["state"] == serve.ACCEPTED
    b = srv.submit({"id": "p2", "kind": "predict", "model": "base",
                    "coords": [[0, 0, 0]]})
    assert b["state"] == serve.REJECTED and b["reason"] == "queue_full"
    evs = resilience.run_report().events("queue_full")
    assert evs and evs[-1]["lane"] == "predict"
    assert srv.submit(_fit_spec("f1"))["state"] == serve.ACCEPTED
    assert srv.summary()["pending_predict"] == 1


def test_predict_survives_restart_replay(tmp_path):
    """A predict accepted but not yet run when the daemon dies is
    re-enqueued on the predict lane by journal replay — zero lost
    predictions."""
    srv = serve.Server(str(tmp_path), workers=1)
    _run(srv, _fit_spec())
    r = srv.submit({"id": "p1", "kind": "predict", "model": "base",
                    "coords": [[0, 0, 0]]})
    assert r["state"] == serve.ACCEPTED
    # "crash": a fresh Server over the same root replays the journal
    srv2 = serve.Server(str(tmp_path), workers=1)
    assert srv2.summary()["pending_predict"] == 1
    srv2.run_once()
    res = serve.read_result(srv2.root, "p1")
    assert res["status"] == "served"
    assert res["gen"] == 1 and res["gen_pinned"] == 1
