"""Batched fleet CPD (docs/batched.md) — cpd_als_batched + the
blocked batch stacking.

The contracts under test:

- PARITY: each slot of a batch equals its own sequential cpd_als run
  (fit and reconstruction within float tolerance), under donation
  on/off (bit-identical to each other) and bf16 storage;
- ONE COMPILE: a whole batched run traces its vmapped sweep exactly
  once (``BatchedCPD.compiles == 1``) — the amortization the serving
  layer exists to exploit;
- PER-SLOT HEALTH ISOLATION: a NaN slot (the ``cpd.batch.sweep``
  poison drill) rolls back ALONE — batch neighbors stay bit-identical
  to a clean run — and an exhausted budget degrades only that slot;
- per-slot convergence freezing, regime validation, the batch axis in
  tuner plan keys, and the new registry entries.
"""

import numpy as np
import pytest

from splatt_tpu import resilience, tune
from splatt_tpu.blocked import (BatchedBlocked, batch_compile,
                                bucket_dims, bucket_nnz_pad)
from splatt_tpu.chaos import synthetic_tensor
from splatt_tpu.config import Options, Verbosity
from splatt_tpu.cpd import cpd_als, cpd_als_batched, init_factors
from splatt_tpu.utils import faults

DIMS = (20, 16, 12)
NNZ = 600
RANK = 4


@pytest.fixture(autouse=True)
def _clean_state():
    def clean():
        faults.reset()
        resilience.reset_demotions()
        resilience.run_report().clear()

    clean()
    yield
    clean()


def _tensors(k, seed0=0):
    return [synthetic_tensor(DIMS, NNZ, seed=seed0 + i) for i in range(k)]


def _opts(seed=0, iters=8, tol=0.0, **kw):
    return Options(random_seed=seed, max_iterations=iters, tolerance=tol,
                   verbosity=Verbosity.NONE, autotune=False, **kw)


def _bit_equal(kt_a, kt_b):
    return (all(np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(kt_a.factors, kt_b.factors))
            and np.array_equal(np.asarray(kt_a.lam), np.asarray(kt_b.lam)))


# -- stacking ----------------------------------------------------------------

def test_bucket_shapes():
    assert bucket_dims((20, 16, 12)) == (32, 32, 16)
    assert bucket_nnz_pad(600, 128) == 1024
    assert bucket_nnz_pad(600, 300) == 1200  # rounded to whole blocks


def test_batch_compile_stacks_to_regime_bucket():
    k = 3
    bb = batch_compile(_tensors(k), _opts())
    assert isinstance(bb, BatchedBlocked)
    assert bb.k == k and bb.nmodes == 3
    assert bb.dims == bucket_dims(DIMS)
    assert bb.inds.shape == (k, 3, bb.nnz_pad)
    assert bb.vals.shape == (k, bb.nnz_pad)
    # per-slot nnz/frobsq match each tensor's own (synthetic_tensor
    # dedups, so true nnz can undershoot the request; pads are zero)
    for i, tt in enumerate(_tensors(k)):
        assert bb.slot_nnz[i] == tt.nnz
        assert bb.slot_frobsq()[i] == pytest.approx(tt.normsq())
    assert "BatchedBlocked" in repr(bb)


def test_batch_compile_rejects_mixed_regime():
    tensors = _tensors(2) + [synthetic_tensor((64, 50, 40), 5000, seed=9)]
    with pytest.raises(ValueError, match="regime"):
        batch_compile(tensors, _opts())
    with pytest.raises(ValueError, match="at least one"):
        batch_compile([], _opts())


def test_batch_compile_rejects_mixed_mode_count():
    tensors = [synthetic_tensor(DIMS, NNZ, seed=0),
               synthetic_tensor((20, 16, 12, 8), NNZ, seed=1)]
    with pytest.raises(ValueError, match="mode"):
        batch_compile(tensors, _opts())


# -- parity (the batched acceptance) -----------------------------------------

def test_batched_parity_with_sequential_loop():
    k = 4
    tensors = _tensors(k)
    seeds = [100 + i for i in range(k)]
    res = cpd_als_batched(tensors, rank=RANK, opts=_opts(), seeds=seeds)
    assert res.compiles == 1          # K tenants, ONE compile
    assert res.k == k
    assert res.statuses == ["converged"] * k
    for i, tt in enumerate(tensors):
        out = cpd_als(tt, rank=RANK, opts=_opts(seed=seeds[i]))
        assert res.fits[i] == pytest.approx(float(out.fit), abs=2e-4)
        np.testing.assert_allclose(res.results[i].to_dense(),
                                   out.to_dense(), atol=5e-3, rtol=1e-2)
        # results are cropped back to TRUE dims
        assert res.results[i].dims == tuple(tt.dims)


def test_batched_donation_off_bit_identical():
    k = 3
    tensors = _tensors(k)
    seeds = [7 + i for i in range(k)]
    a = cpd_als_batched(tensors, rank=RANK, opts=_opts(), seeds=seeds)
    b = cpd_als_batched(tensors, rank=RANK,
                        opts=_opts(donate_sweep=False), seeds=seeds)
    assert all(_bit_equal(x, y) for x, y in zip(a.results, b.results))
    assert a.fits == b.fits


def test_batched_bf16_storage():
    k = 3
    tensors = _tensors(k)
    seeds = [11 + i for i in range(k)]
    res = cpd_als_batched(tensors, rank=RANK,
                          opts=_opts(val_storage="bf16"), seeds=seeds)
    assert res.compiles == 1
    for i, kt in enumerate(res.results):
        assert all(str(f.dtype) == "bfloat16" for f in kt.factors)
        assert np.isfinite(res.fits[i])
    # close to the f32 batch within bf16 resolution
    f32 = cpd_als_batched(tensors, rank=RANK, opts=_opts(), seeds=seeds)
    for a, b in zip(res.fits, f32.fits):
        assert a == pytest.approx(b, abs=0.03)


def test_batched_explicit_inits_validated():
    tensors = _tensors(2)
    inits = [init_factors(t.dims, RANK, 5) for t in tensors]
    res = cpd_als_batched(tensors, rank=RANK, opts=_opts(), inits=inits,
                          seeds=[5, 5])
    assert res.statuses == ["converged"] * 2
    bad = [init_factors((8, 8, 8), RANK, 5), inits[1]]
    with pytest.raises(ValueError, match="shape"):
        cpd_als_batched(tensors, rank=RANK, opts=_opts(), inits=bad,
                        seeds=[5, 5])
    with pytest.raises(ValueError, match="per slot"):
        cpd_als_batched(tensors, rank=RANK, opts=_opts(), seeds=[1])


# -- per-slot convergence ----------------------------------------------------

def test_batched_per_slot_convergence_freeze():
    """With a real tolerance, slots stop independently — and a frozen
    slot's result equals its own sequential run with the same tol."""
    k = 3
    tensors = _tensors(k)
    seeds = [31 + i for i in range(k)]
    res = cpd_als_batched(tensors, rank=RANK,
                          opts=_opts(iters=30, tol=1e-4), seeds=seeds)
    for i, tt in enumerate(tensors):
        out = cpd_als(tt, rank=RANK,
                      opts=_opts(seed=seeds[i], iters=30, tol=1e-4))
        assert res.fits[i] == pytest.approx(float(out.fit), abs=2e-4)


def test_batched_stop_hook():
    calls = {"n": 0}

    def stop():
        calls["n"] += 1
        return calls["n"] >= 2

    res = cpd_als_batched(_tensors(2), rank=RANK,
                          opts=_opts(iters=20), seeds=[1, 2], stop=stop)
    assert res.stopped
    assert res.iterations < 20


# -- per-slot health isolation (the sentinel, vectorized) --------------------

def test_batched_nan_slot_rolls_back_alone(monkeypatch):
    monkeypatch.setenv("SPLATT_HEALTH_RETRIES", "3")
    k = 3
    tensors = _tensors(k)
    seeds = [100 + i for i in range(k)]
    clean = cpd_als_batched(tensors, rank=RANK, opts=_opts(), seeds=seeds)
    with resilience.scope("nan-batch") as sc:
        with faults.scoped("cpd.batch.sweep:nan:iter=2"):
            res = cpd_als_batched(tensors, rank=RANK, opts=_opts(),
                                  seeds=seeds)
    # slot 0 rolled back (alone) and recovered
    assert res.rollbacks[0] >= 1
    assert res.rollbacks[1:] == [0, 0]
    assert res.statuses == ["converged"] * k
    assert all(np.isfinite(np.asarray(f)).all()
               for f in res.results[0].factors)
    # the evidence carries the slot, and only slot 0
    kinds = {(e["kind"], e.get("slot")) for e in sc.report.events()}
    assert ("health_nonfinite", 0) in kinds
    assert ("health_rollback", 0) in kinds
    assert not any(s not in (0, None) for _, s in kinds)
    # neighbors are BIT-identical to the clean run — the isolation
    # acceptance: a NaN tenant cannot poison its batch
    for i in (1, 2):
        assert _bit_equal(clean.results[i], res.results[i])
        assert clean.fits[i] == res.fits[i]


def test_batched_budget_exhaustion_degrades_one_slot(monkeypatch):
    monkeypatch.setenv("SPLATT_HEALTH_RETRIES", "1")
    k = 3
    tensors = _tensors(k)
    seeds = [100 + i for i in range(k)]
    with resilience.scope("degrade-batch") as sc:
        with faults.scoped("cpd.batch.sweep:nan:*"):
            res = cpd_als_batched(tensors, rank=RANK, opts=_opts(),
                                  seeds=seeds)
    assert res.statuses[0] == "degraded"
    assert res.statuses[1:] == ["converged"] * 2
    kinds = {e["kind"] for e in sc.report.events()}
    assert "health_degraded" in kinds
    # the degraded slot still returns finite last-good factors
    assert all(np.isfinite(np.asarray(f)).all()
               for f in res.results[0].factors)
    # neighbors unaffected
    clean = cpd_als_batched(tensors, rank=RANK, opts=_opts(), seeds=seeds)
    for i in (1, 2):
        assert _bit_equal(clean.results[i], res.results[i])


def test_batched_guard_off_flows_through(monkeypatch):
    """SPLATT_HEALTH_RETRIES=0 disables the sentinel: the poisoned
    slot's NaN flows to its own result, neighbors stay clean."""
    monkeypatch.setenv("SPLATT_HEALTH_RETRIES", "0")
    tensors = _tensors(2)
    with faults.scoped("cpd.batch.sweep:nan:iter=2"):
        res = cpd_als_batched(tensors, rank=RANK, opts=_opts(),
                              seeds=[1, 2])
    assert not np.isfinite(np.asarray(res.results[0].factors[0])).all() \
        or not np.isfinite(res.fits[0])
    assert np.isfinite(res.fits[1])
    assert res.rollbacks == [0, 0]


# -- tuner plan keys: the batch axis -----------------------------------------

def test_plan_key_batch_axis():
    base = tune.plan_key(DIMS, NNZ, 0, RANK, np.float32)
    assert tune.plan_key(DIMS, NNZ, 0, RANK, np.float32, batch=1) == base
    k32 = tune.plan_key(DIMS, NNZ, 0, RANK, np.float32, batch=32)
    assert k32 == base + ":bk6"
    assert tune.plan_key(DIMS, NNZ, 0, RANK, np.float32,
                         batch=2) == base + ":bk2"


def test_batched_block_for_fallbacks(tmp_path, monkeypatch):
    monkeypatch.setenv("SPLATT_TUNE_CACHE", str(tmp_path / "tc.json"))
    tune.reset_memo()
    try:
        # untuned: None (caller falls back to opts default)
        assert tune.batched_block_for(DIMS, NNZ, 0, RANK, np.float32,
                                      8) is None
        # autotune off / no rank: None without touching the cache
        assert tune.batched_block_for(DIMS, NNZ, 0, RANK, np.float32,
                                      8, autotune=False) is None
        assert tune.batched_block_for(DIMS, NNZ, 0, None, np.float32,
                                      8) is None
        # a single-tensor plan is the batched prior
        key = tune.plan_key(DIMS, NNZ, 0, RANK, np.float32)
        tune._entry_store(key, {"plan": {
            "path": "sorted_scatter", "engine": "xla", "nnz_block": 2048,
            "scan_target": 1 << 21, "sec": 0.1}})
        assert tune.batched_block_for(DIMS, NNZ, 0, RANK, np.float32,
                                      8) == 2048
        # an explicit batch-axis plan wins over the single-tensor prior
        bkey = tune.plan_key(DIMS, NNZ, 0, RANK, np.float32, batch=8)
        tune._entry_store(bkey, {"plan": {
            "path": "sorted_scatter", "engine": "xla", "nnz_block": 1024,
            "scan_target": 1 << 21, "sec": 0.1}})
        assert tune.batched_block_for(DIMS, NNZ, 0, RANK, np.float32,
                                      8) == 1024
    finally:
        tune.reset_memo()


# -- registries --------------------------------------------------------------

def test_batched_registry_entries():
    from splatt_tpu import trace
    from splatt_tpu.resilience import RUN_REPORT_EVENTS
    from splatt_tpu.utils.env import ENV_VARS

    for var in ("SPLATT_SERVE_BATCH_MIN", "SPLATT_UPDATE_SWEEPS",
                "SPLATT_UPDATE_REFIT_EVERY", "SPLATT_BENCH_BATCH_K"):
        assert var in ENV_VARS
    for ev in ("batch_dispatched", "batch_degraded", "update_applied",
               "refit_scheduled"):
        assert ev in RUN_REPORT_EVENTS
    for site in ("serve.batch", "cpd.update", "cpd.batch.sweep"):
        assert site in faults.SITES
    for metric in ("splatt_serve_batches_total",
                   "splatt_serve_batch_jobs_total",
                   "splatt_serve_updates_total"):
        assert metric in trace.METRICS
    for span in ("cpd.batch", "cpd.batch.sweep", "cpd.update",
                 "serve.batch"):
        assert span in trace.SPANS


def test_summary_lines_for_batch_events():
    rep = resilience.run_report()
    rep.add("batch_dispatched", jobs=["a", "b"], regime="r", k=2)
    rep.add("batch_degraded", jobs=["a", "b"], failure_class="unknown",
            error="boom")
    rep.add("update_applied", job="u", base="m", update_n=2, sweeps=3,
            delta_nnz=10, fit=0.5)
    rep.add("refit_scheduled", job="u", base="m", reason="periodic",
            update_n=3)
    text = "\n".join(rep.summary())
    assert "batch of 2" in text
    assert "BATCH DEGRADED" in text
    assert "update #2 applied" in text
    assert "full refit scheduled" in text
