"""Guarded ALS: numerical-health sentinel, rollback, deadline watchdog.

The contract under test (ISSUE 5 / docs/guarded-als.md): non-finite
sweep outputs are DETECTED at the existing fit-fetch sync, rolled back
to the last-good snapshot (bump regularization / re-randomize the
offending factor), retried within SPLATT_HEALTH_RETRIES, and degraded
to checkpoint-and-abort when the budget is exhausted — in the
single-device AND distributed drivers; a blown host-side deadline
classifies TIMEOUT and demotes per-shape exactly like OOM; and the
chaos schedules that drive all of this are seeded, declarative, and
round-trip through their grammar.
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from splatt_tpu import resilience, tune
from splatt_tpu.config import Options, Verbosity
from splatt_tpu.cpd import cpd_als, load_checkpoint
from splatt_tpu.resilience import (DeadlineExceeded, FailureClass,
                                   NumericalHealthError, classify_failure)
from splatt_tpu.utils import faults
from tests import gen


@pytest.fixture(autouse=True)
def _clean_guard_state(monkeypatch):
    """Demotions, the run report, armed faults, the deadline override
    and the plan-cache override are process-global; every test starts
    clean and leaves nothing armed."""
    resilience.reset_demotions()
    resilience.run_report().clear()
    resilience.set_fallback(None)
    resilience.set_deadline(None)
    faults.reset()
    tune.set_cache_path(None)
    yield
    resilience.reset_demotions()
    resilience.run_report().clear()
    resilience.set_fallback(None)
    resilience.set_deadline(None)
    faults.reset()
    tune.set_cache_path(None)


def _opts(**kw):
    kw.setdefault("random_seed", 31)
    kw.setdefault("verbosity", Verbosity.NONE)
    return Options(**kw)


# -- schedule grammar -------------------------------------------------------

@pytest.mark.parametrize("spec_str", [
    "site_a:http500",
    "site_a:http500:3",
    "site_a:oom:*",
    "engine.fused_t:nan:iter=3",
    "probe_compile:internal:p=0.25:seed=7",
    "tuner.measure:slow:delay=2.5",
    "site_b:runtime:after=1.5",
    "site_c:mosaic:iter=2:p=0.5:seed=9:after=0.25:4",
])
def test_schedule_spec_round_trip(spec_str):
    """parse(format(parse(s))) preserves every schedule field."""
    site, spec = faults.parse_spec(spec_str)
    site2, spec2 = faults.parse_spec(faults.format_spec(site, spec))
    assert site2 == site
    for f in ("kind", "times", "iter_at", "p", "seed", "after", "delay"):
        assert getattr(spec2, f) == getattr(spec, f), f


def test_schedule_round_trip_and_kind_default():
    sched = faults.parse_schedule(
        "a:http500:2, engine.x:iter=3, b:slow:delay=0.5:*")
    # omitted kind defaults to runtime (the issue's `site:iter=k` form)
    assert sched["engine.x"].kind == "runtime"
    assert sched["engine.x"].iter_at == 3
    assert sched["b"].times == faults.ALWAYS
    back = faults.parse_schedule(faults.format_schedule(sched))
    assert back.keys() == sched.keys()
    for site in sched:
        assert back[site].kind == sched[site].kind
        assert back[site].times == sched[site].times


@pytest.mark.parametrize("bad", [
    "nosite",                      # no kind/modifier at all
    "s:unknownkind",               # unknown kind
    "s:runtime:iter=0",            # iter is 1-based
    "s:runtime:p=1.5",             # p out of range
    "s:runtime:frobnicate=1",      # unknown modifier
    "s:runtime:two",               # unparseable modifier
])
def test_schedule_malformed_specs_raise(bad):
    with pytest.raises((ValueError, TypeError)):
        faults.parse_spec(bad)


def test_schedule_env_malformed_entries_ignored(monkeypatch, capsys):
    """The env loader keeps its warn-and-ignore contract for the new
    grammar: a typo must not kill the run at a random hook site."""
    monkeypatch.setenv("SPLATT_FAULTS",
                       "s:runtime:iter=zero,ok:mosaic:iter=1")
    faults.reset()
    faults.maybe_fail("s")                       # malformed: ignored
    with pytest.raises(RuntimeError, match="Mosaic"):
        faults.maybe_fail("ok")                  # valid entry armed
    assert "iter=zero" in capsys.readouterr().err


def test_schedule_iter_fires_on_exact_call():
    fired_at = []
    with faults.inject("it_site", "runtime", iter_at=3):
        for call in range(1, 6):
            try:
                faults.maybe_fail("it_site")
            except RuntimeError:
                fired_at.append(call)
    assert fired_at == [3]


def test_schedule_p_seed_deterministic():
    def pattern():
        hits = []
        with faults.inject("p_site", "runtime", times=faults.ALWAYS,
                           p=0.3, seed=42):
            for call in range(30):
                try:
                    faults.maybe_fail("p_site")
                except RuntimeError:
                    hits.append(call)
        return hits

    a, b = pattern(), pattern()
    assert a == b                      # seeded: replayable
    assert 0 < len(a) < 30             # actually probabilistic


def test_schedule_after_gate():
    with faults.inject("af_site", "runtime", after=0.15):
        faults.maybe_fail("af_site")   # too early: no-op
        time.sleep(0.2)
        with pytest.raises(RuntimeError, match="injected"):
            faults.maybe_fail("af_site")


def test_poison_and_kind_filtering():
    """maybe_fail must not claim (and waste) a poison-armed spec at the
    same site, and poison must not claim a raising spec."""
    with faults.inject("mix", "nan", times=1):
        faults.maybe_fail("mix")                 # not claimed
        assert np.isnan(faults.poison("mix", 2.0))
        assert faults.poison("mix", 2.0) == 2.0  # exhausted
    with faults.inject("mix", "http500", times=1):
        assert faults.poison("mix", 2.0) == 2.0  # not claimed
        with pytest.raises(RuntimeError, match="HTTP code 500"):
            faults.maybe_fail("mix")
    # inf variant poisons too
    with faults.inject("mix", "inf", times=1):
        assert np.isinf(faults.poison("mix", 2.0))


def test_slow_kind_sleeps_not_raises():
    t0 = time.monotonic()
    with faults.inject("sl", "slow", delay=0.3):
        faults.maybe_fail("sl")        # sleeps, returns
        assert faults.fired("sl") == 1
    assert time.monotonic() - t0 >= 0.25


# -- taxonomy: NUMERICAL / TIMEOUT ------------------------------------------

def test_classify_new_classes_and_precedence():
    assert classify_failure(DeadlineExceeded(
        "splatt deadline blown at x after 1s")) is FailureClass.TIMEOUT
    # the watchdog marker outranks the transient 'timed out' markers a
    # blown-deadline message might echo
    assert classify_failure(
        "splatt deadline blown at probe after 240s "
        "(timed out)") is FailureClass.TIMEOUT
    assert classify_failure(NumericalHealthError(
        "non-finite sweep outputs")) is FailureClass.NUMERICAL
    assert classify_failure(
        "non-finite factors at iteration 3") is FailureClass.NUMERICAL
    # RPC-level deadline strings stay transient
    assert classify_failure(
        "DEADLINE_EXCEEDED: compile RPC") is FailureClass.TRANSIENT


def test_timeout_demotes_per_shape_like_oom():
    resilience.demote_engine(
        "fused_t", DeadlineExceeded("splatt deadline blown at "
                                    "engine.fused_t after 2s"),
        shape_key="ck1:b4096")
    assert resilience.is_demoted("fused_t", "ck1:b4096")
    assert not resilience.is_demoted("fused_t", "ck1:b128")
    assert not resilience.is_demoted("fused_t")


def test_numerical_error_never_triggers_engine_rescue():
    """A NaN is the sentinel's to roll back — it must not demote the
    engine that computed it."""
    from splatt_tpu.cpd import _try_engine_rescue
    from tests.test_resilience import _blocked

    _, bs = _blocked()
    resilience.note_engine_attempt("fused_t", "ck1:b256")
    assert _try_engine_rescue(
        bs, _opts(), NumericalHealthError("non-finite outputs")) is False
    assert not resilience.is_demoted("fused_t")


# -- deadline watchdog ------------------------------------------------------

def test_deadline_blows_and_reports():
    with pytest.raises(DeadlineExceeded, match="deadline blown at d1"):
        with resilience.deadline("d1", 0.2):
            time.sleep(0.8)
    ev = resilience.run_report().events("deadline_blown")
    assert len(ev) == 1 and ev[0]["site"] == "d1"


def test_deadline_disabled_is_noop():
    with resilience.deadline("d2", 0):
        time.sleep(0.05)
    with resilience.deadline("d3", None):
        pass
    # default env (SPLATT_DEADLINE_S=0) disables too
    with resilience.deadline("d4"):
        pass
    assert not resilience.run_report().events("deadline_blown")


def test_deadline_override_and_generous_budget():
    resilience.set_deadline(5.0)
    assert resilience.deadline_seconds() == 5.0
    with resilience.deadline("d5"):
        time.sleep(0.02)               # well under budget: no raise
    resilience.set_deadline(None)
    assert resilience.deadline_seconds(default=240.0) == 240.0


def test_deadline_explicit_disable_beats_env(monkeypatch):
    """set_deadline(0) disables the optional sites even with
    SPLATT_DEADLINE_S exported — but a site's own default (the probe's
    always-on 240 s) survives the disable."""
    monkeypatch.setenv("SPLATT_DEADLINE_S", "300")
    assert resilience.deadline_seconds() == 300.0
    resilience.set_deadline(0)
    assert resilience.deadline_seconds() is None
    assert resilience.deadline_seconds(default=240.0) == 240.0
    with resilience.deadline("d7"):    # disabled: no timer, no raise
        time.sleep(0.02)


def test_deadline_off_main_thread_raises_post_hoc():
    """Off the main thread there is no interrupt; the blown deadline
    still converts 'slow' into a classified error on completion."""
    result = {}

    def work():
        try:
            with resilience.deadline("d6", 0.1):
                time.sleep(0.3)
            result["ok"] = True
        except DeadlineExceeded as e:
            result["err"] = e

    t = threading.Thread(target=work)
    t.start()
    t.join(timeout=5)
    assert isinstance(result.get("err"), DeadlineExceeded)


def test_deadline_fault_injectable_via_slow():
    """The watchdog is fault-injectable: a `slow` fault at a guarded
    site makes the REAL timer fire."""
    with faults.inject("slow_site", "slow", delay=0.5):
        with pytest.raises(DeadlineExceeded):
            with resilience.deadline("slow_site", 0.15):
                faults.maybe_fail("slow_site")


def test_tuner_deadline_skips_but_never_persists(tmp_path):
    """A tuner measurement that blows the deadline is skipped this
    session (tuner_negative, failure_class=timeout) but NOT persisted
    as a negative plan-cache entry — a re-tune measures it again."""
    tt = gen.fixture_tensor("med")
    tune.set_cache_path(str(tmp_path / "tc.json"))
    resilience.set_deadline(0.2)
    # pinned format + packing + reorder: exactly ONE candidate (the
    # sorted_scatter chain is ["xla"]), so the single blown measurement
    # leaves the mode planless
    opts = _opts(use_pallas=False, idx_width="i32", val_storage="auto",
                 fiber_packing="fixed", reorder="identity")
    with faults.inject("tuner.measure", "slow", delay=0.7, times=1):
        res = tune.tune(tt, rank=3, opts=opts, modes=[0],
                        blocks=(256,), reps=1)
    assert res.plans == {} and res.skipped == 1
    negs = resilience.run_report().events("tuner_negative")
    assert len(negs) == 1 and negs[0]["failure_class"] == "timeout"
    blown = resilience.run_report().events("deadline_blown")
    assert blown and blown[0]["site"] == "tuner.measure"
    text = (tmp_path / "tc.json").read_text() \
        if (tmp_path / "tc.json").exists() else "{}"
    assert "neg:" not in text          # never persisted
    # the fault is exhausted: a re-tune measures the candidate fine
    tune.reset_memo()
    res2 = tune.tune(tt, rank=3, opts=opts, modes=[0], blocks=(256,),
                     reps=1)
    assert 0 in res2.plans


# -- numerical-health sentinel + rollback -----------------------------------

@pytest.mark.parametrize("k", [1, 3])
def test_nan_at_iteration_k_rolls_back_to_finite(k):
    """Property (acceptance): an injected NaN at iteration k triggers
    rollback and yields finite final factors with fit within tolerance
    of the fault-free run."""
    tt = gen.fixture_tensor("med")
    opts = _opts(max_iterations=8)
    base = cpd_als(tt, rank=3, opts=opts)
    resilience.run_report().clear()
    with faults.inject("cpd.sweep", "nan", iter_at=k):
        out = cpd_als(tt, rank=3, opts=opts)
    assert all(np.isfinite(np.asarray(U)).all() for U in out.factors)
    assert np.isfinite(float(out.fit))
    # the rollback re-randomizes the offending factor, so the retry
    # converges from a different start: same-ballpark fit, not bitwise
    assert abs(float(out.fit) - float(base.fit)) < 0.01
    last = tt.nmodes - 1
    report = resilience.run_report()
    nf = report.events("health_nonfinite")
    assert nf and nf[0]["iteration"] == k and nf[0]["modes"] == [last]
    rb = report.events("health_rollback")
    assert rb and rb[0]["rerandomized"] == [last]
    assert not report.events("health_degraded")


def test_engine_site_nan_rolls_back_through_sweep_rebuild():
    """The issue's `engine.fused_t:...` schedule: a poison-armed engine
    fault corrupts the engine's output inside the fused sweep's TRACE;
    the rollback's sweep rebuild flushes the poisoned program and the
    run recovers."""
    from splatt_tpu.ops.mttkrp import engine_plan
    from tests.test_resilience import _blocked

    _, bs = _blocked()
    facs = [jnp.zeros((d, 3), jnp.float32) for d in bs.dims]
    lay = bs.layouts[0]
    head = engine_plan(lay, facs, lay.mode, "sorted_onehot",
                       "pallas_interpret")
    opts = _opts(max_iterations=6, use_pallas=True)
    base = cpd_als(bs, rank=3, opts=opts)
    resilience.run_report().clear()
    with faults.inject(f"engine.{head}", "nan", iter_at=1):
        out = cpd_als(bs, rank=3, opts=opts)
    assert all(np.isfinite(np.asarray(U)).all() for U in out.factors)
    # the rollback re-randomizes the offending factor(s), so the retry
    # converges from a different start: same-ballpark fit, not bitwise
    assert abs(float(out.fit) - float(base.fit)) < 0.05
    assert resilience.run_report().events("health_rollback")
    # the engine was NOT demoted: NaN is not a capability statement
    assert not resilience.is_demoted(head)


def test_health_budget_exhaustion_degrades_with_checkpoint(tmp_path):
    """Every retry poisoned: the run degrades to checkpoint-and-abort —
    finite last-good factors, a health_degraded event, a loadable
    checkpoint — instead of diverging or raising."""
    tt = gen.fixture_tensor("med")
    ck = str(tmp_path / "ck.npz")
    with faults.inject("cpd.sweep", "nan", times=faults.ALWAYS):
        out = cpd_als(tt, rank=3, opts=_opts(max_iterations=6),
                      checkpoint_path=ck, checkpoint_every=100)
    assert all(np.isfinite(np.asarray(U)).all() for U in out.factors)
    report = resilience.run_report()
    assert report.events("health_degraded")
    # budget respected: retries == SPLATT_HEALTH_RETRIES default (3)
    assert len(report.events("health_rollback")) == 3
    factors, lam, it, fit = load_checkpoint(ck)
    assert all(np.isfinite(np.asarray(U)).all() for U in factors)
    # the checkpoint records the last HEALTHY check's iteration (the
    # snapshot's provenance — here the pre-loop init), so a resume
    # redoes the rolled-back window instead of skipping it
    assert it == 0


def test_health_guard_disabled_by_env(monkeypatch):
    """SPLATT_HEALTH_RETRIES=0 disables the sentinel: the NaN flows
    through (legacy behavior) rather than being rolled back."""
    monkeypatch.setenv("SPLATT_HEALTH_RETRIES", "0")
    tt = gen.fixture_tensor("med")
    with faults.inject("cpd.sweep", "nan", iter_at=1):
        out = cpd_als(tt, rank=3, opts=_opts(max_iterations=3))
    assert not resilience.run_report().events("health_nonfinite")
    assert not all(np.isfinite(np.asarray(U)).all()
                   for U in out.factors)


def test_rollback_with_donated_sweep_preserves_callers_init():
    """Donated-sweep + rollback interaction: the donated fused sweep
    consumes its inputs, the rollback re-materializes from the host
    snapshot, and the CALLER's init arrays survive untouched."""
    from tests.test_resilience import _blocked

    _, bs = _blocked()
    rng = np.random.default_rng(5)
    init = [jnp.asarray(rng.random((d, 3)), dtype=jnp.float32)
            for d in bs.dims]
    init_copy = [np.asarray(u).copy() for u in init]
    opts = _opts(max_iterations=6, use_pallas=True, donate_sweep=True)
    with faults.inject("cpd.sweep", "nan", iter_at=2):
        out = cpd_als(bs, rank=3, opts=opts, init=init)
    assert resilience.run_report().events("health_rollback")
    assert all(np.isfinite(np.asarray(U)).all() for U in out.factors)
    for u, want in zip(init, init_copy):
        np.testing.assert_array_equal(np.asarray(u), want)


def test_rollback_bumps_regularization_each_attempt():
    tt = gen.fixture_tensor("med")
    with faults.inject("cpd.sweep", "nan", times=2):
        cpd_als(tt, rank=3, opts=_opts(max_iterations=8))
    regs = [e["regularization"] for e in
            resilience.run_report().events("health_rollback")]
    assert len(regs) == 2 and regs[1] > regs[0] > 0


# -- distributed rollback ---------------------------------------------------

def test_distributed_nan_rolls_back_to_finite():
    from splatt_tpu.parallel.sharded import sharded_cpd_als

    tt = gen.fixture_tensor("med")
    opts = _opts(random_seed=42, val_dtype=np.float64, max_iterations=6)
    base = sharded_cpd_als(tt, rank=4, opts=opts)
    resilience.run_report().clear()
    with faults.inject("cpd.sweep", "nan", iter_at=2):
        out = sharded_cpd_als(tt, rank=4, opts=opts)
    assert all(np.isfinite(np.asarray(U)).all() for U in out.factors)
    report = resilience.run_report()
    assert report.events("health_nonfinite")
    rb = report.events("health_rollback")
    assert rb and rb[0]["rerandomized"] == [tt.nmodes - 1]
    # distributed rollback re-randomizes without a reg bump (the step
    # closure owns reg; docs/MULTIHOST.md)
    assert rb[0]["regularization"] is None
    assert abs(float(out.fit) - float(base.fit)) < 0.05


def test_distributed_budget_exhaustion_degrades():
    from splatt_tpu.parallel.sharded import sharded_cpd_als

    tt = gen.fixture_tensor("med")
    opts = _opts(random_seed=42, val_dtype=np.float64, max_iterations=5)
    with faults.inject("cpd.sweep", "nan", times=faults.ALWAYS):
        out = sharded_cpd_als(tt, rank=3, opts=opts)
    assert resilience.run_report().events("health_degraded")
    assert all(np.isfinite(np.asarray(U)).all() for U in out.factors)


# -- registries -------------------------------------------------------------

def test_new_events_and_sites_registered():
    for kind in ("health_nonfinite", "health_rollback",
                 "health_degraded", "deadline_blown",
                 "bench_path_error"):
        assert kind in resilience.RUN_REPORT_EVENTS, kind
    assert "cpd.sweep" in faults.SITES
    from splatt_tpu.utils.env import ENV_VARS

    for var in ("SPLATT_HEALTH_RETRIES", "SPLATT_DEADLINE_S",
                "SPLATT_CHAOS_SCHEDULE"):
        assert var in ENV_VARS, var


def test_record_path_error_classifies():
    ev = resilience.record_path_error(
        "blocked", RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert ev["failure_class"] == "resource" and ev["path"] == "blocked"
    assert resilience.run_report().events("bench_path_error")
    assert any("bench path blocked" in line
               for line in resilience.run_report().summary())
