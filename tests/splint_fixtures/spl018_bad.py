"""SPL018 bad: ContextVar.set without a crash-safe reset — a thrown
exception strands one job's scoped state on the worker thread, and the
next tenant on that thread inherits it."""

import contextvars

_SCOPE = contextvars.ContextVar("scope", default=None)


def run_job_leaky(job_id, body):
    _SCOPE.set(job_id)  # token discarded: unrestorable
    return body()


def run_job_unguarded(job_id, body):
    token = _SCOPE.set(job_id)
    out = body()            # a raise here skips the reset entirely
    _SCOPE.reset(token)     # reset exists, but not in a finally
    return out
