"""Quickstart: factor a sparse tensor and inspect the result.

Run:  python examples/quickstart.py [tensor.tns]
(with no argument, a small synthetic tensor is generated)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from splatt_tpu.utils.env import apply_env_platform

apply_env_platform()  # make JAX_PLATFORMS authoritative over site plugins

import numpy as np

import splatt_tpu
from splatt_tpu.config import Options, Verbosity


def main() -> None:
    if len(sys.argv) > 1:
        tt = splatt_tpu.load(sys.argv[1])
    else:
        tt = splatt_tpu.SparseTensor.random((200, 150, 120), 20_000, seed=0)
    print(f"tensor: dims={tt.dims} nnz={tt.nnz}")

    # compile into the blocked device format and factor
    opts = Options(random_seed=42, max_iterations=25,
                   verbosity=Verbosity.LOW)
    bs = splatt_tpu.BlockedSparse.from_coo(tt, opts)
    out = splatt_tpu.cpd_als(bs, rank=16, opts=opts)

    print(f"fit = {float(out.fit):.4f}")
    print(f"lambda = {np.asarray(out.lam)[:5].round(3)} ...")
    # factors are (dim, rank) jax arrays with unit-norm columns
    for m, U in enumerate(out.factors):
        print(f"  factor {m}: {U.shape}")

    # persist like the reference CLI (modeN.mat + lambda.mat)
    out.save("quickstart_output")
    print("factors written to quickstart_output/")


if __name__ == "__main__":
    main()
