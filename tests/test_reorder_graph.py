"""Graph/hypergraph/reorder tests (≙ tests/reorder_test.c + graph fixtures)."""

import numpy as np
import pytest

from splatt_tpu.config import Options, Verbosity
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import cpd_als
from splatt_tpu.graph import (hypergraph_fibers, hypergraph_nnz,
                              tensor_to_graph)
from splatt_tpu.reorder import (PERM_TYPES, Permutation, partition_to_perm,
                                reorder)
from tests import gen


def test_graph_structure(any_tensor):
    tt = any_tensor
    g = tensor_to_graph(tt)
    assert g.nvtxs == sum(tt.dims)
    assert g.indptr[-1] == g.nedges
    # symmetry: edge (u,v) implies (v,u) with equal weight
    edges = {}
    for u in range(g.nvtxs):
        for k in range(g.indptr[u], g.indptr[u + 1]):
            edges[(u, int(g.adj[k]))] = int(g.ewts[k])
    for (u, v), w in edges.items():
        assert edges.get((v, u)) == w
    # vertex weights = slice nnz counts
    assert g.vwts.sum() == tt.nnz * tt.nmodes


def test_hypergraph_nnz(any_tensor):
    tt = any_tensor
    h = hypergraph_nnz(tt)
    assert h.nvtxs == tt.nnz
    assert h.nhedges == sum(tt.dims)
    # every nonzero appears in exactly one hyperedge per mode
    assert h.eptr[-1] == tt.nnz * tt.nmodes
    assert h.eind.max() < tt.nnz


def test_hypergraph_fibers():
    tt = gen.fixture_tensor("med")
    h = hypergraph_fibers(tt, mode=0)
    # fibers: distinct (j,k) pairs
    pairs = set(zip(tt.inds[1], tt.inds[2]))
    assert h.nvtxs == len(pairs)
    assert h.eind.max() < h.nvtxs


@pytest.mark.parametrize("how", PERM_TYPES)
def test_reorder_bijections(how):
    tt = gen.fixture_tensor("med4")
    perm = reorder(tt, how, seed=3)
    for m, p in enumerate(perm.perms):
        if p is not None:
            assert sorted(p.tolist()) == list(range(tt.dims[m]))
    # apply + undo = identity
    back = perm.undo(perm.apply(tt))
    np.testing.assert_array_equal(back.inds, tt.inds)


def test_reorder_preserves_dense():
    tt = gen.fixture_tensor("small4")
    perm = reorder(tt, "random", seed=1)
    out = perm.apply(tt)
    dense = tt.to_dense()
    rdense = out.to_dense()
    # walk every nonzero through the permutation
    it = np.nditer(dense, flags=["multi_index"])
    for v in it:
        if v != 0:
            idx = tuple(
                (perm.perms[m][i] if perm.perms[m] is not None else i)
                for m, i in enumerate(it.multi_index))
            assert rdense[idx] == pytest.approx(float(v))


def test_apply_to_factor_consistency():
    """CPD on a reordered tensor + row un-permutation reproduces the
    original tensor's factors (same seed, same math)."""
    tt = gen.fixture_tensor("med")
    opts = Options(random_seed=5, max_iterations=4,
                   verbosity=Verbosity.NONE, val_dtype=np.float64)
    perm = reorder(tt, "random", seed=9)
    rtt = perm.apply(tt)
    out_r = cpd_als(rtt, rank=4, opts=opts)
    # reconstruct with un-permuted factors and compare against the
    # original tensor's entries
    restored = [perm.apply_to_factor(np.asarray(U), m)
                for m, U in enumerate(out_r.factors)]
    import itertools
    recon = np.einsum("ir,jr,kr,r->ijk", *restored, np.asarray(out_r.lam))
    dense = tt.to_dense()
    rel = np.linalg.norm(recon - dense) / np.linalg.norm(dense)
    assert rel < 1.0  # sane reconstruction
    # exactness check: relabeled reconstruction equals direct reconstruction
    recon_r = np.einsum("ir,jr,kr,r->ijk",
                        *[np.asarray(U) for U in out_r.factors],
                        np.asarray(out_r.lam))
    for m, p in enumerate(perm.perms):
        recon_r = np.take(recon_r, p, axis=m)
    np.testing.assert_allclose(recon, recon_r, atol=1e-10)


def test_partition_to_perm():
    parts = np.array([2, 0, 1, 0, 2, 1])
    p = partition_to_perm(parts, 6)
    assert sorted(p.tolist()) == list(range(6))
    # indices of part 0 get the lowest labels, in stable order
    assert p[1] == 0 and p[3] == 1
    assert p[2] == 2 and p[5] == 3
    assert p[0] == 4 and p[4] == 5


def test_hgraph_nontrivial_all_modes():
    """hgraph must relabel every mode (a sort keyed by the mode itself
    would degenerate to the identity for that mode)."""
    tt = gen.fixture_tensor("med")
    perm = reorder(tt, "hgraph")
    for m, p in enumerate(perm.perms):
        assert not np.array_equal(p, np.arange(tt.dims[m])), f"mode {m}"


def test_hypergraph_uncut():
    """≙ hgraph_uncut (src/graph.c:576-624): hyperedges with every pin
    in one part, checked against a brute-force loop."""
    from splatt_tpu.graph import hypergraph_uncut

    tt = gen.fixture_tensor("med")
    h = hypergraph_nnz(tt)
    rng = np.random.default_rng(3)
    parts = rng.integers(0, 4, size=h.nvtxs)
    got = hypergraph_uncut(h, parts)
    expect = [e for e in range(h.nhedges)
              if len(set(parts[h.eind[h.eptr[e]:h.eptr[e + 1]]])) <= 1]
    assert list(got) == expect
    # one part -> nothing is cut
    assert len(hypergraph_uncut(h, np.zeros(h.nvtxs, dtype=int))) == h.nhedges
    # negative part ids (unassigned sentinels) work the same
    got_neg = hypergraph_uncut(h, parts - 5)
    assert list(got_neg) == expect
