"""SPL028 good: the upcast happens INSIDE the sanctioned accumulate
point — one pinned contraction, no wide elementwise intermediate."""

import jax.numpy as jnp

from splatt_tpu.config import acc_dtype


def zz_stream(M, U, lam):
    acc = acc_dtype(M.dtype)
    return jnp.einsum("dr,dr->", M, U, preferred_element_type=acc)
