"""Kruskal tensor — the CPD output (≙ splatt_kruskal, include/splatt/structs.h:25-44)."""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KruskalTensor:
    """Rank-R factorization: ``X ≈ Σ_r λ_r · U1[:,r] ∘ ... ∘ Um[:,r]``.

    Attributes:
      factors: list of (dim_m, rank) factor matrices.
      lam: (rank,) column norms λ.
      fit: scalar quality-of-fit in [0, 1] (1 = exact).
    """

    factors: List[jax.Array]
    lam: jax.Array
    fit: jax.Array

    @property
    def rank(self) -> int:
        return int(self.factors[0].shape[1])

    @property
    def nmodes(self) -> int:
        return len(self.factors)

    @property
    def dims(self) -> Tuple[int, ...]:
        return tuple(int(f.shape[0]) for f in self.factors)

    def to_dense(self) -> np.ndarray:
        """Reconstruct the dense tensor — tests/small problems only."""
        rank = self.rank
        out = None
        for r in range(rank):
            term = np.asarray(self.lam)[r]
            vec = None
            for f in self.factors:
                col = np.asarray(f)[:, r]
                vec = col if vec is None else np.multiply.outer(vec, col)
            out = term * vec if out is None else out + term * vec
        return out

    def save(self, directory: str, stem: str = "") -> None:
        """Write factors + λ as the reference's terminal outputs
        (mode<N>.mat / lambda.mat, ≙ src/cmds/cmd_cpd.c:206-233)."""
        import os

        from splatt_tpu.io import write_matrix, write_vector

        os.makedirs(directory, exist_ok=True)
        for m, U in enumerate(self.factors):
            write_matrix(np.asarray(U), os.path.join(directory,
                                                     f"{stem}mode{m + 1}.mat"))
        write_vector(np.asarray(self.lam),
                     os.path.join(directory, f"{stem}lambda.mat"))

    @staticmethod
    def load(directory: str, nmodes: int, stem: str = "") -> "KruskalTensor":
        import os

        import jax.numpy as jnp

        from splatt_tpu.io import read_matrix

        factors = [jnp.asarray(read_matrix(
            os.path.join(directory, f"{stem}mode{m + 1}.mat")))
            for m in range(nmodes)]
        lam_raw = read_matrix(os.path.join(directory, f"{stem}lambda.mat"))
        lam = jnp.asarray(np.asarray(lam_raw).ravel())
        # the fit is not stored in the factor files — NaN marks it
        # unknown rather than masquerading as a zero-fit model
        return KruskalTensor(factors=factors, lam=lam,
                             fit=jnp.asarray(np.nan, dtype=lam.dtype))

    def reconstruct(self, coords) -> np.ndarray:
        """Estimate entries at `coords` (``(B, nmodes)`` indices):
        ``x̂ = Σ_r λ_r Π_m U_m[i_m, r]`` — the prediction plane's
        batched gather-matmul (predict.reconstruct_entries,
        docs/predict.md)."""
        from splatt_tpu.predict import reconstruct_entries

        return reconstruct_entries(self.factors, self.lam, coords)

    def top_k(self, fixed, mode: int, k: int):
        """Top-k completion scan of one slice: fix every mode but
        `mode` via ``fixed={mode: index}``, return the k best
        ``(indices, scores)`` (predict.top_k_slice, docs/predict.md)."""
        from splatt_tpu.predict import top_k_slice

        return top_k_slice(self.factors, self.lam, fixed, mode, k)

    def normsq(self) -> jax.Array:
        """⟨Z,Z⟩ = λᵀ (⊛_m UᵐᵀUᵐ) λ (≙ p_kruskal_norm, src/cpd.c:116-152)."""
        # gram() pins the accumulation dtype — a raw `f.T @ f` over
        # bf16 factors would accumulate the Gram at 8 mantissa bits
        from splatt_tpu.config import acc_dtype
        from splatt_tpu.ops.linalg import gram

        had = jnp.outer(self.lam, self.lam)
        for f in self.factors:
            had = had * gram(f)
        return jnp.sum(had, dtype=acc_dtype(had.dtype))


def unstack_batched(factors, lam, fits, dims_list) -> List["KruskalTensor"]:
    """Split stacked batched-ALS state (docs/batched.md) into per-slot
    :class:`KruskalTensor` results: `factors` is the per-mode list of
    ``(K, dim_pad, R)`` stacked arrays, `lam` the ``(K, R)`` stacked λ,
    `fits` the per-slot fit scalars, and `dims_list` each slot's TRUE
    dims — :func:`post_process` crops the bucket padding and folds the
    remaining column norms into λ exactly as every single-tensor driver
    does."""
    out = []
    for i, dims in enumerate(dims_list):
        out.append(post_process([F[i] for F in factors], lam[i],
                                jnp.asarray(fits[i]), dims=tuple(dims)))
    return out


def post_process(factors, lam, fit, dims=None) -> "KruskalTensor":
    """Fold remaining column norms into λ (≙ cpd_post_process,
    src/cpd.c:391-411), optionally cropping padded rows first.  The
    shared finalization of every CPD driver."""
    from splatt_tpu.ops.linalg import normalize_columns  # noqa: deferred — linalg is heavier than this module needs at import

    out = []
    for m, U in enumerate(factors):
        U = jnp.asarray(U)
        if dims is not None:
            U = U[:dims[m]]
        U, norms = normalize_columns(U, "2")
        lam = lam * norms
        out.append(U)
    return KruskalTensor(factors=out, lam=lam, fit=fit)
