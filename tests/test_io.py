"""IO round-trip tests (≙ tests/io_test.c)."""

import numpy as np
import pytest

from splatt_tpu.coo import SparseTensor
from splatt_tpu.io import (load, read_matrix, read_permutation, save,
                           write_matrix, write_permutation, write_vector)
from tests import gen


def test_text_roundtrip(tmp_path, any_tensor):
    tt = any_tensor
    path = str(tmp_path / "t.tns")
    save(tt, path)
    out = load(path)
    assert out.dims == tt.dims
    np.testing.assert_array_equal(out.inds, tt.inds)
    np.testing.assert_allclose(out.vals, tt.vals)


def test_zero_vs_one_indexed(tmp_path):
    """≙ small4_zeroidx.tns autodetect (src/io.c:273-348)."""
    tt = gen.fixture_tensor("small4")
    p1 = str(tmp_path / "one.tns")
    p0 = str(tmp_path / "zero.tns")
    save(tt, p1, one_indexed=True)
    save(tt, p0, one_indexed=False)
    a, b = load(p1), load(p0)
    np.testing.assert_array_equal(a.inds, b.inds)
    assert a.dims == b.dims


def test_binary_roundtrip(tmp_path, any_tensor):
    tt = any_tensor
    path = str(tmp_path / "t.bin")
    save(tt, path)
    out = load(path)
    assert out.dims == tt.dims
    np.testing.assert_array_equal(out.inds, tt.inds)
    np.testing.assert_allclose(out.vals, tt.vals)


def test_binary_wide_indices(tmp_path):
    """Indices above 2^31 force 8-byte storage."""
    ind = np.array([[0, 2**31 + 5], [1, 0], [0, 1]], dtype=np.int64)
    tt = SparseTensor(ind, np.array([1.0, 2.0]), (2**31 + 6, 2, 2))
    path = str(tmp_path / "wide.bin")
    save(tt, path)
    out = load(path)
    np.testing.assert_array_equal(out.inds, tt.inds)


def test_comments_and_blank_lines(tmp_path):
    path = tmp_path / "c.tns"
    path.write_text("# header comment\n\n1 2 1 1.5\n# mid comment\n2 1 2 2.5\n")
    tt = load(str(path))
    assert tt.nnz == 2
    assert tt.dims == (2, 2, 2)
    np.testing.assert_allclose(tt.vals, [1.5, 2.5])


def test_fixture_files_load(tensors_dir):
    for name in ("small", "med", "small4", "med4", "med5"):
        tt = load(str(tensors_dir / f"{name}.tns"))
        ref = gen.fixture_tensor(name)
        assert tt.dims == ref.dims
        assert tt.nnz == ref.nnz


def test_matrix_vector_perm_roundtrip(tmp_path):
    mat = np.arange(12, dtype=float).reshape(4, 3) / 7.0
    write_matrix(mat, str(tmp_path / "m.mat"))
    np.testing.assert_allclose(read_matrix(str(tmp_path / "m.mat")), mat)
    write_vector(mat[:, 0], str(tmp_path / "v.vec"))
    perm = np.array([3, 1, 0, 2])
    write_permutation(perm, str(tmp_path / "p.perm"))
    np.testing.assert_array_equal(read_permutation(str(tmp_path / "p.perm")), perm)


def test_load_memmap_roundtrip(tmp_path, any_tensor):
    from splatt_tpu.io import load_memmap

    tt = any_tensor
    path = str(tmp_path / "t.bin")
    save(tt, path)
    out = load_memmap(path)
    # no copy on load: arrays are views over the mapped file
    assert isinstance(out.inds.base, np.memmap)
    assert isinstance(out.vals.base, np.memmap)
    assert out.dims == tt.dims
    np.testing.assert_array_equal(np.asarray(out.inds), tt.inds)
    np.testing.assert_allclose(np.asarray(out.vals), tt.vals)
    # memmapped tensors work through the normal pipeline
    assert out.normsq() == pytest.approx(tt.normsq())
    assert out.sorted_by(range(out.nmodes)).nnz == tt.nnz
