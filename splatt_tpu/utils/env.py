"""Environment/platform helpers shared by entry points."""

from __future__ import annotations

import os


def ceil_to(x: int, mult: int) -> int:
    """Round x up to a multiple of mult."""
    return ((x + mult - 1) // mult) * mult


def host_fence(x):
    """Force true device completion of `x` and everything it depends on.

    block_until_ready alone is not enough on tunneled/relayed devices
    (e.g. the axon TPU relay), which can ack readiness before execution
    finishes — a one-element host fetch is a true data-dependency fence.
    Returns `x` for chaining.
    """
    import jax

    leaf = jax.tree_util.tree_leaves(x)[0]
    jax.block_until_ready(x)
    jax.device_get(leaf.ravel()[0])
    return x


def apply_env_platform() -> None:
    """Mirror JAX_PLATFORMS into jax.config.

    Some images install a site plugin (e.g. a TPU relay) that selects
    platforms programmatically at interpreter startup, which overrides
    the JAX_PLATFORMS env var.  Calling this before any backend
    initializes makes the env var authoritative again.
    """
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax

        try:
            jax.config.update("jax_platforms", platforms)
        except Exception:
            pass
