"""SPL002 bad: broad excepts that lose the failure class entirely."""


def swallow_and_default(fn):
    try:
        return fn()
    except Exception:
        return None


def swallow_bare(fn):
    try:
        return fn()
    except:  # noqa: E722
        pass


def swallow_tuple(fn):
    try:
        return fn()
    except (ValueError, Exception):
        return 0
