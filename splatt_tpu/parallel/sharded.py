"""Distributed CPD via sharding + XLA collectives (≙ src/mpi/).

The reference's medium-grained distributed ALS (mpi_cpd_als_iterate,
src/mpi/mpi_cpd.c:627-804) does, per mode per iteration:

  local MTTKRP → add own partials → reduce rows owned by me
  (MPI_Alltoallv) → solve for owned rows → normalize (λ allreduce) →
  broadcast updated rows to neighbors (Alltoallv) → Gram allreduce.

The TPU mapping (SURVEY §5/§7): nonzeros are sharded over a mesh axis
(equal-nnz shards ≙ the nnz-balanced layer boundaries of
p_find_layer_boundaries) and every factor matrix is row-sharded over the
same axis.  Inside one `shard_map`:

  - ``all_gather``     ≙ mpi_update_rows (neighbors fetch rows they need)
  - local gather-prod + segment-sum over the *global* row space
                       ≙ local MTTKRP + mpi_add_my_partials
  - ``psum_scatter``   ≙ mpi_reduce_rows (each device keeps the summed
                         rows it owns)
  - ``psum``           ≙ the Gram / λ / fit MPI_Allreduce calls
                         (src/matrix.c:445-452, :121,181; mpi_cpd.c:94)

No comm plan, no ineed lists, no greedy row assignment: ownership is the
contiguous row blocks of the sharding, and XLA schedules the collectives
over ICI.  The reference's POINT2POINT row-exchange variant
(p_reduce_rows_point2point, src/mpi/mpi_cpd.c:323-423) maps to the
ppermute ring sweep in :mod:`splatt_tpu.parallel.ring`, selected via
``opts.comm_pattern`` — same math, O(dim/ndev) peak factor memory.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from splatt_tpu.utils.env import shard_map

from splatt_tpu.config import (CommPattern, Options, Verbosity, default_opts,
                               resolve_comm_pattern, resolve_dtype)
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import init_factors
from splatt_tpu.kruskal import KruskalTensor
from splatt_tpu.ops.mttkrp import acc_dtype
from splatt_tpu.parallel.common import (blocked_local_mttkrp, bucket_engine,
                                        bucket_scatter, comm_volume_report,
                                        fit_tail, imbalance_report,
                                        mode_update_tail,
                                        run_distributed_als)
from splatt_tpu.parallel.mesh import make_mesh, single_axis_of
from splatt_tpu.utils.env import ceil_to as _pad_to


def shard_nnz_host(tt: SparseTensor, ndev: int, val_dtype=np.float32,  # splint: ignore[SPL005] shard-builder signature default; callers override via Options.val_dtype
                   partition: Optional[np.ndarray] = None,
                   streamed: Optional[bool] = None,
                   out_dir: Optional[str] = None,
                   chunk: int = 1 << 22
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Host side of :func:`shard_nnz`: the padded (nmodes, nnz_pad)
    arrays, without the device_put.

    `streamed` (auto: when tt holds memmapped indices) runs the
    bucketing in chunked passes so host RSS stays O(chunk + bucket
    metadata); with `out_dir` the outputs are disk-backed memmaps —
    a beyond-RAM tensor shards end-to-end (≙ the reference streaming
    equal-nnz chunks from the root rank, src/mpi/mpi_io.c:587-648).
    """
    from splatt_tpu.parallel.common import (is_memmapped,
                                            streamed_bucket_scatter)
    from splatt_tpu.utils.env import check_int32_dims

    check_int32_dims(tt.dims)
    if streamed is None:
        streamed = is_memmapped(tt.inds)
    if streamed:
        if partition is None:
            csize = max(ndev, _pad_to(tt.nnz, ndev)) // ndev

            def owner_fn(ic, s):
                return np.minimum(
                    (s + np.arange(ic.shape[1], dtype=np.int64)) // csize,
                    ndev - 1)
        else:
            part = partition  # may itself be a memmap

            def owner_fn(ic, s):
                return np.asarray(part[s:s + ic.shape[1]], dtype=np.int64)

        binds, bvals, _, _ = streamed_bucket_scatter(
            tt.inds, tt.vals, owner_fn, ndev, val_dtype, chunk=chunk,
            out_dir=out_dir)
        return binds.reshape(tt.nmodes, -1), bvals.reshape(-1)
    if partition is None:
        nnz_pad = max(ndev, _pad_to(tt.nnz, ndev))
        inds = np.zeros((tt.nmodes, nnz_pad), dtype=np.int32)
        inds[:, :tt.nnz] = tt.inds
        vals = np.zeros(nnz_pad, dtype=val_dtype)
        vals[:tt.nnz] = tt.vals
        return inds, vals
    binds, bvals, _, _ = bucket_scatter(tt.inds, tt.vals,
                                        np.asarray(partition), ndev,
                                        val_dtype)
    return binds.reshape(tt.nmodes, -1), bvals.reshape(-1)


def shard_nnz(tt: SparseTensor, mesh: Mesh, axis: str = "nnz",
              val_dtype=np.float32,  # splint: ignore[SPL005] shard-builder signature default; callers override via Options.val_dtype
              partition: Optional[np.ndarray] = None,
              streamed: Optional[bool] = None,
              out_dir: Optional[str] = None
              ) -> Tuple[jax.Array, jax.Array]:
    """Pad nonzeros to the device count and shard them over `axis`.

    With `partition=None`: equal contiguous chunks (≙ mpi_tt_read's
    equal-nnz distribution, mpi_simple_distribute,
    src/mpi/mpi_io.c:587-648).  With a per-nonzero `partition` array
    (values in [0, ndev)): nonzero n is placed on device partition[n]
    — the FINE decomposition's user-supplied nonzero-level partition
    (≙ p_rearrange_fine, src/mpi/mpi_io.c:486-499), with buckets padded
    to the largest.  Pad entries point at row 0 with value 0 — harmless
    to every kernel.  See :func:`shard_nnz_host` for the streamed
    (bounded-RSS / disk-backed) build knobs.
    """
    inds, vals = shard_nnz_host(tt, mesh.shape[axis], val_dtype,
                                partition=partition, streamed=streamed,
                                out_dir=out_dir)
    inds_s = jax.device_put(inds, NamedSharding(mesh, P(None, axis)))
    vals_s = jax.device_put(vals, NamedSharding(mesh, P(axis)))
    return inds_s, vals_s


def shard_blocked_layouts(tt: SparseTensor, mesh: Mesh, opts: Options,
                          dims_pad: Tuple[int, ...], axis: str = "nnz",
                          val_dtype=np.float32,  # splint: ignore[SPL005] shard-builder signature default; callers override via Options.val_dtype
                          partition: Optional[np.ndarray] = None,
                          out_dir: Optional[str] = None,
                          chunk: int = 1 << 22):
    """Per-shard sorted blocked layouts so the sweep runs the
    single-chip blocked MTTKRP engine inside every shard (≙ each MPI
    rank building CSF over its local nonzeros, mpi_cpd.c:714).  The
    mode-m row space stays GLOBAL (the psum_scatter reduce owns the
    fence split), so the sentinel dim is dims_pad[sort_mode].

    `opts.block_alloc` governs the layout count exactly like the
    single-chip compiler (≙ splatt_csf_alloc): ONEMODE/TWOMODE build
    1–2 sorted copies (shared by reference across modes, the
    non-sorted ones running the generic scatter path); ALLMODE builds
    one per mode.

    Returns (host_meta, device_arrays): host_meta[m] holds the statics
    (block, seg_width, path, impl, sort_mode, sort_dim);
    device_arrays[m] the device-put (inds, vals, row_start) triple.

    Memmapped (out-of-core) tensors build via the streamed chunked
    passes — bucket scatter and the per-bucket counting sort both
    disk-backed under `out_dir` when given — so the optimized engine
    survives beyond-RAM scale (≙ mttkrp_csf per rank regardless of
    size, src/mpi/mpi_cpd.c:714).
    """
    import os

    from splatt_tpu.parallel.common import (alloc_build_modes,
                                            build_bucket_layout,
                                            is_memmapped,
                                            streamed_bucket_scatter)

    ndev = mesh.shape[axis]
    streamed = is_memmapped(tt.inds)
    fence = max(ndev, _pad_to(tt.nnz, ndev)) // ndev
    if streamed:
        if partition is None:
            def owner_fn(ic, s):
                return np.arange(s, s + ic.shape[1], dtype=np.int64) // fence
        else:
            part = np.asarray(partition, dtype=np.int64)

            def owner_fn(ic, s):
                return part[s:s + ic.shape[1]]

        binds, bvals, _, counts = streamed_bucket_scatter(
            tt.inds, tt.vals, owner_fn, ndev, val_dtype, chunk=chunk,
            out_dir=(os.path.join(out_dir, "shards")
                     if out_dir is not None else None))
    else:
        if partition is None:
            owner = np.arange(tt.nnz, dtype=np.int64) // fence
        else:
            owner = np.asarray(partition, dtype=np.int64)
        binds, bvals, _, counts = bucket_scatter(tt.inds, tt.vals, owner,
                                                 ndev, val_dtype)
    build_modes = alloc_build_modes(dims_pad, opts)
    built_meta = []
    built_arr = []
    for m in build_modes:
        i, v, rs, blk, S = build_bucket_layout(
            binds, bvals, counts, m, dims_pad[m], opts.nnz_block,
            chunk=chunk,
            out_dir=(os.path.join(out_dir, f"blocked_m{m}")
                     if out_dir is not None else None))
        path, impl = bucket_engine(S, opts)
        built_meta.append(dict(block=blk, seg_width=S, path=path,
                               impl=impl, sort_mode=m,
                               sort_dim=dims_pad[m]))
        built_arr.append((
            jax.device_put(i, NamedSharding(mesh, P(None, axis, None))),
            jax.device_put(v, NamedSharding(mesh, P(axis, None))),
            jax.device_put(rs, NamedSharding(mesh, P(axis, None)))))
    meta = []
    arrays = []
    for m in range(tt.nmodes):
        j = build_modes.index(m) if m in build_modes else 0
        mm = dict(built_meta[j])
        if mm["sort_mode"] != m:
            mm["path"] = "scatter"
        meta.append(mm)
        arrays.append(built_arr[j])
    return meta, tuple(arrays)


def shard_factors(factors: List[jax.Array], dims: Tuple[int, ...],
                  mesh: Mesh, axis: str = "nnz",
                  relabels: Optional[List[Optional[np.ndarray]]] = None
                  ) -> List[jax.Array]:
    """Row-shard factors, zero-padding rows to the device count.

    Zero pad rows keep Grams, norms and solves exact (they contribute
    nothing), mirroring how the reference's ownership fences
    (mat_ptrs, src/mpi/mpi_mat_distribute.c:558-582) exclude non-owned
    rows from every reduction.  `relabels[m]`, when given, places row
    `old` at label `relabels[m][old]` (comm-minimizing distribution).
    """
    ndev = mesh.shape[axis]
    out = []
    for m, (U, d) in enumerate(zip(factors, dims)):
        d_pad = _pad_to(d, ndev)
        U_pad = jnp.zeros((d_pad, U.shape[1]), dtype=U.dtype)
        rl = relabels[m] if relabels is not None else None
        if rl is None:
            U_pad = U_pad.at[:d].set(U[:d])
        else:
            U_pad = U_pad.at[jnp.asarray(rl)].set(U[:d])
        out.append(jax.device_put(U_pad, NamedSharding(mesh, P(axis, None))))
    return out


def sharded_mttkrp(inds: jax.Array, vals: jax.Array, factors: List[jax.Array],
                   mode: int, mesh: Mesh, axis: str = "nnz") -> jax.Array:
    """Distributed MTTKRP: result row-sharded like ``factors[mode]``.

    `factors` are row-sharded (dim_pad, R); `inds`/`vals` nnz-sharded.
    One all_gather per input factor, one psum_scatter for the output —
    the two row-exchange phases of the reference, as collectives.
    """
    nmodes = len(factors)
    dims_pad = tuple(int(f.shape[0]) for f in factors)

    @partial(shard_map, mesh=mesh,
             in_specs=(P(None, axis), P(axis), *[P(axis, None)] * nmodes),
             out_specs=P(axis, None))
    def run(inds_l, vals_l, *factors_l):
        prod = vals_l[:, None].astype(factors_l[0].dtype)
        for k in range(nmodes):
            if k != mode:
                U = jax.lax.all_gather(factors_l[k], axis, axis=0, tiled=True)
                prod = prod * jnp.take(U, inds_l[k], axis=0, mode="clip")
        partial_out = jax.ops.segment_sum(prod.astype(acc_dtype(prod.dtype)),
                                          inds_l[mode],
                                          num_segments=dims_pad[mode])
        return jax.lax.psum_scatter(partial_out, axis, scatter_dimension=0,
                                    tiled=True)

    return run(inds, vals, *factors)


def make_sharded_sweep(mesh: Mesh, nmodes: int, reg: float,
                       dims_pad: Tuple[int, ...], axis: str = "nnz",
                       variant: str = "all2all",
                       cells: Optional[List[dict]] = None):
    """Build the jitted, shard_mapped one-iteration ALS sweep.

    `first_flag` is a replicated scalar array selecting 2-norm (iteration
    0) vs max-norm normalization (≙ src/cpd.c:343-347) so a single
    compilation serves every iteration.  `variant` picks the comm
    primitives for the two row-exchange phases (≙ SPLATT_OPTION_COMM):
    "all2all" = all_gather + psum_scatter, "ring" = ppermute ring
    (splatt_tpu.parallel.ring) with O(dim/ndev) peak factor memory,
    "async_ring" = the Pallas remote-copy ring
    (splatt_tpu.parallel.ring_kernels, docs/ring.md) that overlaps the
    exchange with the local compute on TPU and keeps the ppermute
    semantics bit-for-bit elsewhere.  "local_stub" is a TIMING-ONLY
    variant (measure_ring_overlap): the exchanges are replaced by
    local reads so a step costs exactly the compute — its outputs are
    mathematically WRONG and must never reach a driver.

    `cells` (shard_blocked_layouts meta; all2all only): the local
    MTTKRP runs the single-chip blocked engine over each shard's
    sorted arrays instead of the stream formulation.
    """
    ndev = mesh.shape[axis]
    factor_specs = tuple([P(axis, None)] * nmodes)
    gram_specs = tuple([P(None, None)] * nmodes)
    if cells is not None and variant != "all2all":
        raise ValueError("blocked local engine requires the all2all "
                         "variant (the ring reduce is blockwise)")
    cell_specs = tuple(
        (P(None, axis, None), P(axis, None), P(axis, None))
        for _ in range(nmodes)) if cells is not None else ()

    if variant == "ring":
        from splatt_tpu.parallel.ring import (blockwise_reduce_rows,
                                              ring_gather_rows)

        def gather_rows(U_l, idx):
            return ring_gather_rows(U_l, idx, axis, ndev)

        def reduce_rows(prod, idx, m):
            return blockwise_reduce_rows(prod, idx, axis, ndev,
                                         dims_pad[m] // ndev)
    elif variant == "async_ring":
        from splatt_tpu.parallel.ring_kernels import (
            async_blockwise_reduce_rows, async_ring_gather_rows)

        def gather_rows(U_l, idx):
            return async_ring_gather_rows(U_l, idx, axis, ndev)

        def reduce_rows(prod, idx, m):
            return async_blockwise_reduce_rows(prod, idx, axis, ndev,
                                               dims_pad[m] // ndev)
    elif variant == "local_stub":
        # compute-only baseline for the overlap metric: same per-step
        # masked passes and reductions, zero inter-device traffic
        def gather_rows(U_l, idx):
            block = U_l.shape[0]
            rows0 = jnp.zeros((idx.shape[0], U_l.shape[1]), U_l.dtype)
            my_id = jax.lax.axis_index(axis)

            def body(step, rows):
                shard_id = jnp.mod(my_id - step, ndev)
                mask = (idx // block) == shard_id
                local = jnp.where(mask, jnp.mod(idx, block), 0)
                picked = jnp.take(U_l, local, axis=0, mode="clip")
                return rows + jnp.where(mask[:, None], picked, 0)

            return jax.lax.fori_loop(0, ndev, body, rows0)

        def reduce_rows(prod, idx, m):
            block = dims_pad[m] // ndev
            my_id = jax.lax.axis_index(axis)
            out_dtype = acc_dtype(prod.dtype)

            def body(jb, acc):
                mask = (idx // block) == jb
                p = jax.ops.segment_sum(
                    (prod * mask[:, None]).astype(out_dtype),
                    jnp.where(mask, jnp.mod(idx, block), 0),
                    num_segments=block)
                return jnp.where(jb == my_id, p, acc)

            acc0 = jnp.zeros((block, prod.shape[1]), dtype=out_dtype)
            return jax.lax.fori_loop(0, ndev, body, acc0)
    elif variant == "all2all":
        def gather_rows(U_l, idx):
            # ≙ mpi_update_rows: fetch the rows of the other factors
            U = jax.lax.all_gather(U_l, axis, axis=0, tiled=True)
            return jnp.take(U, idx, axis=0, mode="clip")

        def reduce_rows(prod, idx, m):
            # local MTTKRP partials over the global row space (f32
            # accumulation for low-precision operands), then
            # ≙ mpi_reduce_rows: I keep the summed rows I own
            partial_out = jax.ops.segment_sum(
                prod.astype(acc_dtype(prod.dtype)), idx,
                num_segments=dims_pad[m])
            return jax.lax.psum_scatter(partial_out, axis,
                                        scatter_dimension=0, tiled=True)
    else:
        raise ValueError(f"unknown comm variant {variant!r}")

    @partial(shard_map, mesh=mesh,
             in_specs=(P(None, axis), P(axis), factor_specs, gram_specs,
                       P(), cell_specs),
             out_specs=(factor_specs, gram_specs, P(), P(), P()),
             check_vma=False)
    def sweep(inds_l, vals_l, factors_l, grams_l, first_flag, cells_l):
        factors_l = list(factors_l)
        grams_l = list(grams_l)
        dtype = factors_l[0].dtype
        lam = None
        M_l = None
        for m in range(nmodes):
            if cells is not None:
                # ≙ mpi_update_rows then the rank-local optimized
                # MTTKRP (mttkrp_csf, mpi_cpd.c:714) over the shard's
                # sorted blocked arrays, then mpi_reduce_rows
                ci, cv, crs = cells_l[m]
                R = factors_l[0].shape[1]
                fac_full = [
                    jax.lax.all_gather(factors_l[k], axis, axis=0,
                                       tiled=True) if k != m
                    # shape carrier for the output row space (values
                    # unused by the sorted paths; DCE'd)
                    else jnp.zeros((dims_pad[m], R), dtype)
                    for k in range(nmodes)]
                partial_out = blocked_local_mttkrp(
                    ci.reshape(nmodes, -1), cv.reshape(-1),
                    crs.reshape(-1), fac_full, m,
                    dim=cells[m]["sort_dim"], block=cells[m]["block"],
                    seg_width=cells[m]["seg_width"],
                    path=cells[m]["path"], impl=cells[m]["impl"],
                    sort_mode=cells[m]["sort_mode"])
                M_l = jax.lax.psum_scatter(partial_out, axis,
                                           scatter_dimension=0, tiled=True)
            else:
                prod = vals_l[:, None].astype(dtype)
                for k in range(nmodes):
                    if k != m:
                        prod = prod * gather_rows(factors_l[k], inds_l[k])
                M_l = reduce_rows(prod, inds_l[m], m)
            U_l, gram, lam = mode_update_tail(M_l, grams_l, m, reg,
                                              first_flag, axis,
                                              store_dtype=dtype)
            factors_l[m] = U_l
            grams_l[m] = gram
        znormsq, inner = fit_tail(lam, grams_l, M_l, factors_l[nmodes - 1],
                                  axis)
        return tuple(factors_l), tuple(grams_l), lam, znormsq, inner

    return jax.jit(sweep)


def make_sharded_profiled_sweep(mesh: Mesh, nmodes: int, reg: float,
                                dims_pad: Tuple[int, ...], store_dtype,
                                axis: str = "nnz",
                                cells: Optional[List[dict]] = None):
    """Split-jit profiled sharded sweep (all2all variant only): gather,
    local MTTKRP, reduce, update, and fit each run as their own
    shard_mapped program bracketed by blocking timers — the measured
    mttkrp/collective/solve attribution of ≙ mpi_time_stats
    (src/mpi/mpi_cpd.c:893-939).  Costs cross-phase fusion and
    materializes the gathered factors between phases; the fused
    :func:`make_sharded_sweep` is the production path.
    """
    factor_specs = tuple([P(axis, None)] * nmodes)
    gram_specs = tuple([P(None, None)] * nmodes)
    cell_spec = (P(None, axis, None), P(axis, None), P(axis, None))

    def make_gather(m):
        others = [k for k in range(nmodes) if k != m]

        @partial(shard_map, mesh=mesh, in_specs=(factor_specs,),
                 out_specs=tuple(P(None, None) for _ in others),
                 check_vma=False)
        def gather_m(factors_l):
            # ≙ mpi_update_rows: fetch the other factors whole
            return tuple(jax.lax.all_gather(factors_l[k], axis, axis=0,
                                            tiled=True) for k in others)

        return jax.jit(gather_m)

    def make_local(m):
        others = [k for k in range(nmodes) if k != m]
        gathered_specs = tuple(P(None, None) for _ in others)
        in_specs = ((P(None, axis), P(axis), gathered_specs)
                    + ((cell_spec,) if cells is not None else ()))

        @partial(shard_map, mesh=mesh, in_specs=in_specs,
                 out_specs=P(axis, None), check_vma=False)
        def local_m(inds_l, vals_l, gathered, *cell_m):
            if cells is not None:
                ci, cv, crs = cell_m[0]
                R = gathered[0].shape[1]
                fac_full = []
                gi = iter(gathered)
                for k in range(nmodes):
                    fac_full.append(
                        jnp.zeros((dims_pad[m], R), gathered[0].dtype)
                        if k == m else next(gi))
                return blocked_local_mttkrp(
                    ci.reshape(nmodes, -1), cv.reshape(-1),
                    crs.reshape(-1), fac_full, m,
                    dim=cells[m]["sort_dim"], block=cells[m]["block"],
                    seg_width=cells[m]["seg_width"],
                    path=cells[m]["path"], impl=cells[m]["impl"],
                    sort_mode=cells[m]["sort_mode"])
            prod = vals_l[:, None].astype(gathered[0].dtype)
            for j, k in enumerate(others):
                prod = prod * jnp.take(gathered[j], inds_l[k], axis=0,
                                       mode="clip")
            return jax.ops.segment_sum(
                prod.astype(acc_dtype(prod.dtype)), inds_l[m],
                num_segments=dims_pad[m])

        return jax.jit(local_m)

    def make_reduce(m):
        @partial(shard_map, mesh=mesh, in_specs=(P(axis, None),),
                 out_specs=P(axis, None), check_vma=False)
        def reduce_m(part_l):
            # ≙ mpi_reduce_rows: keep the summed rows I own
            return jax.lax.psum_scatter(part_l, axis,
                                        scatter_dimension=0, tiled=True)

        return jax.jit(reduce_m)

    def make_update(m):
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(axis, None), gram_specs, P()),
                 out_specs=(P(axis, None), P(), P()), check_vma=False)
        def update_m(M_l, grams_l, flag):
            return mode_update_tail(M_l, list(grams_l), m, reg, flag,
                                    axis, store_dtype=store_dtype)

        return jax.jit(update_m)

    @partial(shard_map, mesh=mesh,
             in_specs=(P(), gram_specs, P(axis, None), P(axis, None)),
             out_specs=(P(), P()), check_vma=False)
    def fit_fn(lam, grams_l, M_l, U_l):
        return fit_tail(lam, list(grams_l), M_l, U_l, axis)

    gathers = [make_gather(m) for m in range(nmodes)]
    locals_ = [make_local(m) for m in range(nmodes)]
    reduces = [make_reduce(m) for m in range(nmodes)]
    updates = [make_update(m) for m in range(nmodes)]
    fit_jit = jax.jit(fit_fn)

    from splatt_tpu.utils.env import host_fence as sync
    from splatt_tpu.utils.timers import timers

    def sweep(inds, vals, factors, grams, flag, cells_dev=()):
        factors = list(factors)
        grams = list(grams)
        lam = None
        M = None
        for m in range(nmodes):
            with timers.time("dist_gather"):
                gathered = sync(gathers[m](tuple(factors)))
            extra = (cells_dev[m],) if cells is not None else ()
            with timers.time("dist_mttkrp"):
                part = sync(locals_[m](inds, vals, gathered, *extra))
            with timers.time("dist_comm"):
                M = sync(reduces[m](part))
            with timers.time("dist_update"):
                factors[m], grams[m], lam = sync(
                    updates[m](M, tuple(grams), flag))
        with timers.time("dist_fit"):
            znormsq, inner = sync(fit_jit(lam, tuple(grams), M,
                                          factors[nmodes - 1]))
        return tuple(factors), tuple(grams), lam, znormsq, inner

    return sweep


#: ordered comm-engine fallback chains (docs/ring.md): a failing
#: strategy degrades CLASSIFIED to the next entry — async ring to the
#: hop-barriered ppermute ring to the all2all collectives, which have
#: no preconditions and cannot fail to apply (the terminal engine).
_COMM_CHAINS = {
    CommPattern.ALL2ALL: ("all2all",),
    CommPattern.POINT2POINT: ("ring", "all2all"),
    CommPattern.ASYNC_RING: ("async_ring", "ring", "all2all"),
}


def comm_chain(comm: CommPattern) -> tuple:
    """The ordered comm-strategy fallback chain for a requested
    pattern (best first, terminal last)."""
    return _COMM_CHAINS[comm]


def _comm_shape_key(dims_pad, ndev: int, rank: int, dtype) -> str:
    """Demotion scope of a comm-engine failure — its own ``:comm``
    suffix keeps ring demotions disjoint from the MTTKRP engine keys
    (an async-ring OOM indicts the async ring at this shape, never the
    all2all path or a compute engine)."""
    dims = "x".join(str(int(d)) for d in dims_pad)
    return f"d{dims}:w{ndev}:r{int(rank)}:{jnp.dtype(dtype).name}:comm"


def _select_comm_sweep(chain, mesh, nmodes, reg, dims_pad, axis, cells_meta,
                       inds, vals, cells_dev, factors, grams, dtype, opts):
    """Build the sweep on the best LIVE comm strategy, probing each
    non-terminal candidate with one discarded step invocation (the
    sweep is pure, so the probe costs compute but never state).  A
    probe failure is classified, demotes ``comm.<variant>`` under the
    comm shape key (per-shape for RESOURCE/TIMEOUT, process-wide
    otherwise) and falls to the next strategy with a ``comm_fallback``
    run-report event — the ladder the ``comm.ring_exchange`` chaos
    drills assert on.  Returns (variant, step)."""
    from splatt_tpu import resilience

    ndev = mesh.shape[axis]
    rank = int(factors[0].shape[1])
    ckey = _comm_shape_key(dims_pad, ndev, rank, dtype)
    fallback = (opts.engine_fallback if opts.engine_fallback is not None
                else resilience.fallback_enabled())
    # demotion pruning: a previously indicted strategy is skipped, but
    # the terminal all2all is always live
    live = [v for v in chain
            if v == chain[-1]
            or not resilience.is_demoted(f"comm.{v}", ckey)]
    for i, variant in enumerate(live):
        sweep = make_sharded_sweep(mesh, nmodes, reg, dims_pad, axis=axis,
                                   variant=variant, cells=cells_meta)

        def step(f, g, flag, sweep=sweep):
            return sweep(inds, vals, f, g, flag, cells_dev)

        if i == len(live) - 1 or not fallback:
            # terminal (or fallback disabled: fail loudly at the real
            # first step, not a probe)
            return variant, step
        try:
            probe = step(factors, grams, jnp.asarray(1.0, dtype=dtype))
            # async failures surface at the fence, not the call
            jax.block_until_ready(probe[2])
            return variant, step
        except Exception as e:
            cls = resilience.classify_failure(e)
            resilience.demote_engine(f"comm.{variant}", e, shape_key=ckey)
            resilience.run_report().add(
                "comm_fallback", strategy=variant, fallback_to=live[i + 1],
                failure_class=cls.value,
                error=resilience.failure_message(e)[:200])
            if opts.verbosity >= Verbosity.LOW:
                print(f"  comm engine {variant} failed ({cls.value}); "
                      f"falling back to {live[i + 1]}")
    raise AssertionError("unreachable: the terminal comm engine returns")


def _make_exchange_only(mesh, nmodes, dims_pad, axis, rank, dtype,
                        hops: int):
    """A jitted program that performs EXACTLY one sweep's ring traffic
    (every gather leg's hops + the reduce leg) with no MTTKRP compute —
    the fully-exposed exchange time, i.e. the denominator of the
    achieved-overlap metric (docs/ring.md).  `hops` follows the variant
    as it actually runs: ndev ppermutes per leg for the sync ring (and
    the async variant's CPU fallback), ndev-1 real RDMA hops for the
    Pallas async ring — an overstated denominator would inflate the
    reported overlap."""
    ndev = mesh.shape[axis]
    factor_specs = tuple([P(axis, None)] * nmodes)

    @partial(shard_map, mesh=mesh, in_specs=(factor_specs,),
             out_specs=P(axis, None), check_vma=False)
    def exchange(factors_l):
        perm = [(i, (i + 1) % ndev) for i in range(ndev)]

        def hop(_, U):
            return jax.lax.ppermute(U, axis, perm)

        tot = jnp.zeros((1, rank), dtype)
        for m in range(nmodes):
            for k in range(nmodes):
                if k != m:
                    U = jax.lax.fori_loop(0, hops, hop, factors_l[k])
                    tot = tot + U[:1]
            blk = jnp.zeros((dims_pad[m] // ndev, rank),
                            acc_dtype(jnp.dtype(dtype)))
            blk = jax.lax.fori_loop(0, hops, hop, blk)
            tot = tot + blk[:1].astype(dtype)
        return tot

    return jax.jit(exchange)


def measure_ring_overlap(mesh, nmodes, reg, dims_pad, axis, variant,
                         inds, vals, factors, grams, dtype,
                         reps: int = 3, step_fn=None) -> dict:
    """Measure the ACHIEVED comm/compute overlap of a ring sweep
    (docs/ring.md defines the metric):

        exchange_s  — the sweep's ring traffic alone, fully exposed
        compute_s   — a "local_stub" sweep step (identical compute,
                      zero traffic; timing-only — its math is wrong)
        step_s      — the real sweep step (comm + compute)

        exposed = max(0, step_s - compute_s)
        hidden  = max(0, exchange_s - exposed)
        overlap_frac = hidden / exchange_s

    All three run warm (compile excluded, median of `reps`).  The wire
    model's per-device bytes ride along so MULTICHIP artifacts can put
    the measured seconds next to the modeled traffic.  On CPU the
    fallback engines expose every hop — overlap_frac near 0 is the
    honest reading there, labelled by ``backend``/``engine``.

    `step_fn(factors, grams, flag)`, when the caller already built and
    compiled the production sweep (sharded_cpd_als did, for its comm
    probe), is timed directly instead of re-tracing an identical sweep
    — the real step's compile is not paid twice.
    """
    from splatt_tpu import trace

    # the measurement pays extra compiles (stub + exchange-only
    # programs) — attribute it so a traced distributed run shows the
    # overlap probe's cost next to the sweep it instruments
    with trace.span("dist.measure_overlap", variant=variant):
        return _measure_ring_overlap(
            mesh, nmodes, reg, dims_pad, axis, variant, inds, vals,
            factors, grams, dtype, reps, step_fn)


def _measure_ring_overlap(mesh, nmodes, reg, dims_pad, axis, variant,
                          inds, vals, factors, grams, dtype, reps,
                          step_fn) -> dict:
    """:func:`measure_ring_overlap` body, inside its span."""
    import time as _time

    from splatt_tpu.parallel.common import comm_volume_model
    from splatt_tpu.parallel.ring_kernels import async_ring_supported
    from splatt_tpu.utils.env import host_fence

    ndev = mesh.shape[axis]
    rank = int(factors[0].shape[1])
    flag = jnp.asarray(0.0, dtype=dtype)
    if step_fn is None:
        sweep = make_sharded_sweep(mesh, nmodes, reg, dims_pad, axis=axis,
                                   variant=variant)

        def step_fn(f, g, fl):
            return sweep(inds, vals, f, g, fl, ())
    stub = make_sharded_sweep(mesh, nmodes, reg, dims_pad, axis=axis,
                              variant="local_stub")
    rdma = (variant == "async_ring" and ndev >= 2
            and async_ring_supported())
    exchange = _make_exchange_only(mesh, nmodes, dims_pad, axis, rank,
                                   dtype,
                                   hops=(ndev - 1) if rdma else ndev)

    def timed(fn) -> float:
        host_fence(fn())  # warm: compile + first run excluded
        ts = []
        for _ in range(max(reps, 1)):
            t0 = _time.perf_counter()
            host_fence(fn())
            ts.append(_time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    t_comm = timed(lambda: exchange(tuple(factors)))
    t_comp = timed(lambda: stub(inds, vals, factors, grams, flag, ())[2])
    t_step = timed(lambda: step_fn(factors, grams, flag)[2])
    exposed = max(0.0, t_step - t_comp)
    hidden = max(0.0, t_comm - exposed)
    overlap = hidden / t_comm if t_comm > 0 else 0.0
    model = comm_volume_model(
        dims_pad, rank, jnp.dtype(dtype).itemsize, ndev=ndev,
        variant=variant,
        acc_itemsize=jnp.dtype(acc_dtype(jnp.dtype(dtype))).itemsize)
    return dict(variant=variant,
                backend=jax.default_backend(),
                engine="pallas_rdma" if rdma else "ppermute_fallback",
                step_s=round(t_step, 6), compute_s=round(t_comp, 6),
                exchange_s=round(t_comm, 6),
                exposed_comm_s=round(exposed, 6),
                hidden_comm_s=round(hidden, 6),
                overlap_frac=round(overlap, 4),
                model_mb_per_device=round(
                    model["gather_mb"] + model["reduce_mb"]
                    + model["allreduce_mb"], 4),
                per_hop_mb=model["per_hop_mb"],
                overlap_eligible_frac=model["overlap_eligible_frac"])


def sharded_cpd_als(tt: SparseTensor, rank: int, mesh: Optional[Mesh] = None,
                    opts: Optional[Options] = None,
                    init: Optional[List[jax.Array]] = None,
                    axis: str = "nnz",
                    partition: Optional[np.ndarray] = None,
                    row_distribute: Optional[str] = None,
                    local_engine: Optional[str] = None,
                    out_dir: Optional[str] = None,
                    checkpoint_path: Optional[str] = None,
                    checkpoint_every: int = 10,
                    resume: bool = True,
                    measure_overlap: Optional[bool] = None
                    ) -> KruskalTensor:
    """Distributed CPD-ALS over a device mesh (≙ the mpirun cpd path,
    src/cmds/mpi_cmd_cpd.c:175-338).

    `opts.comm_pattern` (default: SPLATT_COMM, else ALL2ALL) picks the
    row-exchange strategy; POINT2POINT/ASYNC_RING runs degrade
    classified down the comm chain (docs/ring.md) and, unless
    `measure_overlap` is False (None = auto at verbosity >= HIGH;
    True forces it — the CLI does for --json ring runs), report the
    achieved comm/compute overlap as a ``ring_overlap`` run-report
    event.

    Results are rank-count invariant: the same seed gives the same
    factors at any device count (≙ mpi_mat_rand, src/splatt_mpi.h:368-386)
    because initialization happens in the global row space before
    sharding, and all reductions are deterministic collectives.

    `row_distribute="greedy"`: comm-minimizing factor-row relabeling —
    each shard's touched rows are greedily claimed into its own fence
    (≙ p_greedy_mat_distribution, src/mpi/mpi_mat_distribute.c:436-548)
    — before fences are cut; original row order is restored on gather.

    `local_engine`: "blocked" (all2all variant only; the default) runs
    the single-chip blocked MTTKRP engine over per-shard sorted layouts
    inside the sweep (≙ mttkrp_csf per rank, mpi_cpd.c:714); "stream"
    keeps the naive formulation (the differential oracle; always used
    by the ring variant, whose reduce is blockwise).  Memmapped
    (out-of-core) tensors keep the blocked engine: the shard build and
    the per-shard sorts run as streamed chunked passes (disk-backed
    under `out_dir` when given), so host RSS stays bounded at any
    scale.
    """
    opts = (opts or default_opts()).validate()
    mesh, axis = single_axis_of(mesh, axis)
    mesh = mesh or make_mesh(axis_names=(axis,))
    ndev = mesh.shape[axis]
    nmodes = tt.nmodes
    dims_pad = tuple(_pad_to(d, ndev) for d in tt.dims)
    xnormsq = tt.normsq()

    dtype = resolve_dtype(opts, tt.vals.dtype)

    orig_dims = tt.dims
    relabels = None
    if row_distribute == "greedy":
        from splatt_tpu.parallel.distribute import comm_minimizing_relabels

        shard_of = (np.asarray(partition, dtype=np.int64)
                    if partition is not None else None)
        relabels, dstats = comm_minimizing_relabels(
            np.asarray(tt.inds), orig_dims, ndev, shard_of=shard_of)
        if opts.verbosity >= Verbosity.HIGH:
            # ≙ the comm-volume reduction mpi_send_recv_stats reports
            for st in dstats:
                print(f"  rowdist mode {st['mode']}: local touches "
                      f"{st['local_before']:.1%} -> {st['local_after']:.1%}")
        from splatt_tpu.parallel.common import relabel_tensor

        tt = relabel_tensor(tt, relabels, dims_pad)
    elif row_distribute == "balanced":
        # nnz-weighted factor-row relabeling (≙ the chains-on-chains
        # p_find_layer_boundaries, docs/layout-balance.md): hot slices
        # are spread across the equal-width row fences by a
        # capacity-constrained LPT pack, so no device's fence owns a
        # disproportionate share of the gather/reduce row traffic — the
        # balanced-sharding leg of the skewed-tensor playbook
        from splatt_tpu.parallel.common import balanced_relabel

        relabels = [balanced_relabel(tt.mode_histogram(m), ndev,
                                     dims_pad[m] // ndev)
                    if ndev > 1 else None
                    for m in range(nmodes)]
        # (the achieved fence balance is computed once, post-relabel,
        # by the fence_mm block below — which also prints the HIGH-
        # verbosity per-mode report, so no second full-tensor pass)
        from splatt_tpu.parallel.common import relabel_tensor

        tt = relabel_tensor(tt, relabels, dims_pad)
    elif row_distribute is not None:
        raise ValueError(f"unknown row_distribute {row_distribute!r}")

    comm = resolve_comm_pattern(opts)
    chain = comm_chain(comm)
    ring_family = chain[0] != "all2all"
    if local_engine is None:
        # shared auto policy, plus the FINE-only condition: the ring
        # variants' blockwise reduce is stream-only
        from splatt_tpu.parallel.common import auto_local_engine

        local_engine = ("stream" if ring_family
                        else auto_local_engine(tt, out_dir))
    elif local_engine == "blocked" and ring_family:
        # never silently ignore an explicit engine request (the ring
        # sweeps are stream-only; make_sharded_sweep has the same
        # guard) — and a comm fallback landing on all2all keeps the
        # stream engine it started with rather than rebuilding layouts
        raise ValueError(f"local_engine='blocked' is not supported with "
                         f"the {comm.value} (ring) comm pattern; use "
                         f"ALL2ALL or local_engine='stream'")
    cells_meta = None
    cells_dev = ()
    if local_engine == "blocked" and not ring_family:
        cells_meta, cells_dev = shard_blocked_layouts(
            tt, mesh, opts, dims_pad, axis=axis, val_dtype=dtype,
            partition=partition, out_dir=out_dir)
        # the blocked sweep never reads the stream shard arrays — put
        # 1-entry-per-device dummies instead of a dead O(nnz) HBM copy
        inds = jax.device_put(np.zeros((nmodes, ndev), np.int32),
                              NamedSharding(mesh, P(None, axis)))
        vals = jax.device_put(np.zeros(ndev, dtype),
                              NamedSharding(mesh, P(axis)))
    elif local_engine not in ("blocked", "stream"):
        raise ValueError(f"unknown local_engine {local_engine!r}")
    else:
        inds, vals = shard_nnz(tt, mesh, axis=axis, val_dtype=dtype,
                               partition=partition, out_dir=out_dir)
    # init in the ORIGINAL row space (rank-count/distribution
    # invariance, ≙ mpi_mat_rand); relabels only affect placement
    factors_host = (init if init is not None
                    else init_factors(orig_dims, rank, opts.seed(),
                                      dtype=dtype))
    factors = tuple(shard_factors(
        [jnp.asarray(f, dtype=dtype) for f in factors_host],
        orig_dims, mesh, axis=axis, relabels=relabels))
    from splatt_tpu.ops.linalg import gram

    gram_sharding = NamedSharding(mesh, P(None, None))
    grams = tuple(
        jax.device_put(gram(U), gram_sharding) for U in factors
    )

    # ≙ mpi_rank_stats + mpi_send_recv_stats.  Measured occupancy,
    # not the equal-chunk assumption: padding trails, so the last
    # chunk(s) hold the shortfall.  Always RECORDED (the
    # layout_imbalance event rides `splatt cpd --json` and MULTICHIP
    # artifacts — docs/layout-balance.md); printed at HIGH.
    if partition is not None:
        counts = np.bincount(np.asarray(partition), minlength=ndev)
    else:
        chunk = max(ndev, _pad_to(tt.nnz, ndev)) // ndev
        counts = np.clip(tt.nnz - chunk * np.arange(ndev), 0, chunk)
    from splatt_tpu.parallel.common import record_shard_imbalance

    # per-mode factor-row fence weights: the row traffic the balanced
    # rowdist exists to even out — a device whose fence owns hot
    # slices gates the gather/reduce legs of the ring.  The fence
    # histogram is a full O(nnz) host pass per mode (sequential reads
    # of a memmapped index stream on the out-of-core path), so it is
    # only paid when a rowdist policy makes it the evidence, or at
    # HIGH verbosity as a diagnostic — never as unconditional startup
    # cost on every sharded run
    fence_mm = None
    if row_distribute is not None or opts.verbosity >= Verbosity.HIGH:
        from splatt_tpu.utils.env import max_mean_ratio

        fence_mm = {}
        for m in range(nmodes):
            fences = np.add.reduceat(
                np.bincount(np.asarray(tt.inds[m]), minlength=dims_pad[m]),
                np.arange(0, dims_pad[m], dims_pad[m] // ndev))
            fence_mm[str(m)] = max_mean_ratio(fences)
            if opts.verbosity >= Verbosity.HIGH:
                print(imbalance_report(fences, f"mode{m} row-fence"))
    record_shard_imbalance(
        "shard", counts,
        policy=row_distribute or ("partition" if partition is not None
                                  else "equal"),
        **({"row_fence_max_mean": fence_mm} if fence_mm is not None
           else {}))
    if opts.verbosity >= Verbosity.HIGH:
        print(imbalance_report(counts, "shard"))
    profiled = (opts.verbosity >= Verbosity.HIGH and not ring_family)
    if profiled:
        # split-jit phases with blocking timers: measured gather/mttkrp/
        # reduce/solve attribution (≙ mpi_time_stats); all2all only —
        # the ring variants' overlap makes phase barriers meaningless,
        # so they report the achieved-overlap metric instead
        variant = "all2all"
        sweep = make_sharded_profiled_sweep(mesh, nmodes,
                                            opts.regularization, dims_pad,
                                            dtype, axis=axis,
                                            cells=cells_meta)

        def step(factors, grams, flag):
            return sweep(inds, vals, factors, grams, flag, cells_dev)

        from splatt_tpu.parallel.common import wrap_profiled_step

        step = wrap_profiled_step(step)
    else:
        # comm-engine selection with the classified fallback ladder
        # (docs/ring.md): async_ring -> ring -> all2all.  This (and the
        # overlap probe below) runs BEFORE run_distributed_als opens
        # its enabling scope, so the Options.trace per-run pin must be
        # honored here too
        from splatt_tpu import trace

        with trace.enabling(opts.trace):
            with trace.span("dist.comm_select") as _sp:
                variant, step = _select_comm_sweep(
                    chain, mesh, nmodes, opts.regularization, dims_pad,
                    axis, cells_meta, inds, vals, cells_dev, factors,
                    grams, dtype, opts)
                _sp.set(variant=variant)
    if opts.verbosity >= Verbosity.HIGH:
        # the wire model follows the SELECTED strategy, not an all2all
        # assumption (ISSUE 8 satellite)
        for line in comm_volume_report(dims_pad, rank,
                                       np.dtype(dtype).itemsize, ndev=ndev,
                                       variant=variant):
            print(line)
    if variant in ("ring", "async_ring") and measure_overlap is not False \
            and (measure_overlap or opts.verbosity >= Verbosity.HIGH):
        # achieved-overlap metric (docs/ring.md): exchange time hidden
        # vs exposed, next to the wire model's per-device bytes —
        # reported as a ring_overlap run-report event so `splatt cpd
        # --json` distributed runs (the CLI passes measure_overlap=True
        # there) and MULTICHIP artifacts carry the number.  Auto only
        # at HIGH, like the other startup diagnostics: the measurement
        # compiles two extra programs and runs ~a dozen step-scale
        # invocations — not a cost every default run should pay.
        # Best-effort: a measurement failure must never take down the
        # run it measures.
        from splatt_tpu import resilience, trace

        try:
            with trace.enabling(opts.trace):
                ov = measure_ring_overlap(
                    mesh, nmodes, opts.regularization, dims_pad, axis,
                    variant, inds, vals, factors, grams, dtype,
                    step_fn=step)
            resilience.run_report().add("ring_overlap", **ov)
            if opts.verbosity >= Verbosity.LOW:
                print(f"  ring overlap [{ov['engine']}]: "
                      f"exchange {ov['exchange_s']:.4f}s, "
                      f"{100 * ov['overlap_frac']:.0f}% hidden "
                      f"(exposed {ov['exposed_comm_s']:.4f}s of "
                      f"step {ov['step_s']:.4f}s)")
        except Exception as e:
            cls = resilience.classify_failure(e)
            if opts.verbosity >= Verbosity.LOW:
                print(f"  ring overlap measurement skipped "
                      f"({cls.value}: {resilience.failure_message(e)[:120]})")

    out = run_distributed_als(step, factors, grams, rank, opts, xnormsq,
                              orig_dims, dtype, row_select=relabels,
                              checkpoint_path=checkpoint_path,
                              checkpoint_every=checkpoint_every,
                              resume=resume)
    if profiled:
        from splatt_tpu.parallel.common import dist_phase_report

        for line in dist_phase_report():
            print(line)
    return out
