"""Fixture snippets for the splint rule tests (tests/test_splint.py).

One known-bad and one known-good example per rule id.  These files are
PARSED by the analyzer, never imported — they reference modules and
names that may not resolve at runtime on purpose.
"""
