"""SPL005 good: dtypes resolved through the central policy or derived
from inputs."""

import jax.numpy as jnp

from splatt_tpu.config import resolve_dtype


def make(x, opts):
    dtype = resolve_dtype(opts, x.dtype)
    a = jnp.zeros((4, 4), dtype)
    b = jnp.zeros(4, dtype=x.dtype)
    return a, b
