"""Blocked sparse format — the TPU-native answer to CSF (≙ src/csf.c).

Design (SURVEY §7): CSF's pointer-tree (variable-length fibers,
data-dependent traversal) is hostile to XLA.  The TPU equivalent of
"CSF + chains-on-chains partitioning + cache tiling" is a blocked/padded
layout:

- nonzeros are **sorted by the output mode** (≙ tt_sort + csf mode
  permutation), then segmented into **fixed-size nnz blocks** — equal work
  per block *by construction*, which is exactly what the reference's
  chains-on-chains partitioner (src/thread_partition.c:156-195) achieves
  dynamically for threads;
- each block records the first output row it touches (``row_start``) and
  the layout records the maximum row-span any block covers (``seg_width``)
  — together these let MTTKRP reduce each block with a small one-hot
  matmul on the MXU instead of a scatter (the locked/privatized/tiled
  trichotomy of src/mttkrp.c:104-236 collapses into this);
- indices are padded to a whole number of blocks with a sentinel row
  (= dim) and zero values, keeping every shape static for XLA.

The reference's ONEMODE/TWOMODE/ALLMODE allocation policy
(include/splatt/types_config.h:168-173, src/csf.c:770-814) survives as
"how many sorted layouts do we precompute": a layout sorted for mode k is
the fast path for output mode k and a generic (scatter) path otherwise —
mirroring CSF's root vs. internal/leaf mode traversals.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from splatt_tpu.config import (BlockAlloc, Options, Verbosity, default_opts,
                               resolve_dtype)
from splatt_tpu.coo import SparseTensor
from splatt_tpu.utils.env import ceil_to as _ceil_to


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ModeLayout:
    """One sorted+blocked copy of the nonzeros (≙ one splatt_csf).

    Data (device arrays):
      inds: (nmodes, nnz_pad) int32 coordinates, sorted by ``mode``;
        pad entries hold ``dim`` for ``mode`` and 0 elsewhere.
      vals: (nnz_pad,) values, zero-padded.
      row_start: (nblocks,) int32 — first output row each block touches
        (``dim`` for all-padding blocks).

    Static metadata:
      mode: the output mode this layout is sorted for.
      dim: dims[mode].
      block: nnz per block (B).
      seg_width: S — max output-row span of any block, rounded up to a
        multiple of 8 (f32 sublane); the one-hot reduce is (S×B)@(B×R).
      nnz: true nonzero count (before padding).
    """

    inds: jax.Array
    vals: jax.Array
    row_start: jax.Array
    mode: int = dataclasses.field(metadata=dict(static=True))
    dim: int = dataclasses.field(metadata=dict(static=True))
    block: int = dataclasses.field(metadata=dict(static=True))
    seg_width: int = dataclasses.field(metadata=dict(static=True))
    nnz: int = dataclasses.field(metadata=dict(static=True))

    @property
    def nnz_pad(self) -> int:
        return int(self.inds.shape[1])

    @property
    def nblocks(self) -> int:
        return int(self.row_start.shape[0])

    @property
    def nmodes(self) -> int:
        return int(self.inds.shape[0])

    def storage_bytes(self) -> int:
        """≙ csf_storage (src/csf.c:729-767)."""
        return (self.inds.size * self.inds.dtype.itemsize
                + self.vals.size * self.vals.dtype.itemsize
                + self.row_start.size * self.row_start.dtype.itemsize)

    def __repr__(self) -> str:
        # the EFFECTIVE block is load-bearing (build_layout clamps the
        # requested one), so surface it instead of the dataclass default
        # repr dumping whole device arrays
        return (f"ModeLayout(mode={self.mode}, dim={self.dim}, "
                f"block={self.block}, seg_width={self.seg_width}, "
                f"nnz={self.nnz}, nnz_pad={self.nnz_pad}, "
                f"nblocks={self.nblocks})")


def secondary_order(dims, mode: int, policy: "ModeOrder" = None,
                    custom=None) -> List[int]:
    """Order of the non-output modes within a layout
    (≙ csf_find_mode_order, src/csf.c:694-726; see ModeOrder for the
    mapping — the output mode is always the primary key here)."""
    from splatt_tpu.config import ModeOrder

    policy = policy or ModeOrder.SMALLFIRST
    others = [m for m in range(len(dims)) if m != mode]
    if policy in (ModeOrder.SMALLFIRST, ModeOrder.SORTED_MINUSONE):
        return sorted(others, key=lambda m: (dims[m], m))
    if policy is ModeOrder.BIGFIRST:
        return sorted(others, key=lambda m: (-dims[m], m))
    if policy is ModeOrder.INORDER_MINUSONE:
        return others
    if policy is ModeOrder.CUSTOM:
        if custom is None:
            raise ValueError("ModeOrder.CUSTOM requires mode_order_custom")
        seq = [m for m in custom if m != mode]
        if sorted(seq) != others:
            raise ValueError(
                f"mode_order_custom {custom!r} is not a permutation "
                f"covering all non-output modes for mode {mode}")
        return seq
    raise ValueError(f"unknown mode order {policy!r}")


def build_layout(tt: SparseTensor, mode: int, block: int = 4096,
                 val_dtype=np.float32, mode_order=None,
                 mode_order_custom=None, verbose: bool = False) -> ModeLayout:
    """Sort, block and pad the tensor for output mode `mode`.

    ≙ csf_alloc's sort + fiber build (src/csf.c:613-726); the secondary
    mode ordering follows `mode_order` (default SMALLFIRST,
    ≙ csf_find_mode_order).  The block a caller (or the autotuner)
    requests may be clamped to the tensor size; the override is
    recorded in the run report (and printed when `verbose`) and the
    effective block is what :class:`ModeLayout` reports.
    """
    nmodes, nnz = tt.nmodes, tt.nnz
    from splatt_tpu.utils.env import check_int32_dims

    check_int32_dims(tt.dims)
    others = secondary_order(tt.dims, mode, mode_order, mode_order_custom)
    order = [mode] + others
    perm = tt.sort_order(order)
    dim = tt.dims[mode]

    # Don't let the block dwarf a small tensor: clamp to the padded nnz
    # count (kept a multiple of 128 for lane alignment).
    requested = int(block)
    block = max(128, min(block, _ceil_to(max(nnz, 1), 128)))
    if block != requested:
        # a silent override of a caller-requested block made the
        # effective plan unobservable (ISSUE 3 satellite): record it
        from splatt_tpu import resilience

        resilience.run_report().add("block_clamp", mode=mode,
                                    requested=requested, effective=block,
                                    nnz=nnz)
        if verbose:
            print(f"  layout mode{mode}: requested nnz_block {requested} "
                  f"clamped to {block} (nnz={nnz})")
    nnz_pad = max(block, _ceil_to(nnz, block))
    nblocks = nnz_pad // block
    inds = np.zeros((nmodes, nnz_pad), dtype=np.int32)
    inds[:, :nnz] = tt.inds[:, perm]
    inds[mode, nnz:] = dim  # sentinel row for padding
    vals = np.zeros(nnz_pad, dtype=val_dtype)
    vals[:nnz] = tt.vals[perm]

    rows = inds[mode].reshape(nblocks, block)
    row_start = rows[:, 0].astype(np.int32)
    span = int((rows[:, -1] - rows[:, 0]).max()) + 1 if nnz else 1
    # Padding sentinels in the last real block can inflate its span; the
    # one-hot simply never matches those lanes (vals are zero anyway), so
    # clamp to the widest span a block of real rows can have.
    seg_width = _ceil_to(min(span, dim if dim > 0 else 1), 8)

    return ModeLayout(
        inds=jnp.asarray(inds),
        vals=jnp.asarray(vals),
        row_start=jnp.asarray(row_start),
        mode=mode,
        dim=dim,
        block=block,
        seg_width=seg_width,
        nnz=nnz,
    )


@dataclasses.dataclass
class BlockedSparse:
    """A set of per-mode layouts + the mode→layout assignment.

    ≙ splatt_csf[] + the workspace mode map (splatt_mttkrp_alloc_ws,
    src/mttkrp.c:1814-1912).
    """

    layouts: List[ModeLayout]
    mode_map: Dict[int, int]          # output mode -> index into layouts
    dims: Tuple[int, ...]
    nnz: int
    opts: Options

    @property
    def nmodes(self) -> int:
        return len(self.dims)

    def layout_for(self, mode: int) -> ModeLayout:
        return self.layouts[self.mode_map[mode]]

    def storage_bytes(self) -> int:
        return sum(l.storage_bytes() for l in self.layouts)

    @staticmethod
    def from_coo(tt: SparseTensor, opts: Optional[Options] = None,
                 tuned_blocks: Optional[Dict[int, int]] = None
                 ) -> "BlockedSparse":
        """Compile a COO tensor into blocked layouts per the alloc policy.

        ≙ splatt_csf_alloc (src/csf.c:770-814):
        - ONEMODE: one layout, sorted for the smallest mode;
        - TWOMODE (default): smallest mode + largest mode (≙ smallest-first
          CSF + leaf-rooted CSF, src/csf.c:787-803);
        - ALLMODE: one per mode.
        Every mode maps to its own layout when one exists, else to the
        first layout (generic path).

        `tuned_blocks` (mode -> nnz_block, from the autotuner's plan
        cache) overrides ``opts.nnz_block`` per build mode — the layout
        is built once at the tuned block instead of rebuilt when the
        plan disagrees with the default.  :meth:`compile` fills it in.
        """
        opts = (opts or default_opts()).validate()
        nmodes = tt.nmodes
        tuned_blocks = tuned_blocks or {}
        # one selection rule shared with the distributed cell/shard
        # layout builders — they must never desynchronize
        from splatt_tpu.parallel.common import alloc_build_modes

        build_modes = alloc_build_modes(tt.dims, opts)

        layouts = [build_layout(tt, m,
                                block=tuned_blocks.get(m, opts.nnz_block),
                                val_dtype=resolve_dtype(opts, tt.vals.dtype),
                                mode_order=opts.mode_order,
                                mode_order_custom=opts.mode_order_custom,
                                verbose=opts.verbosity >= Verbosity.LOW)
                   for m in build_modes]
        mode_map = {}
        for m in range(nmodes):
            mode_map[m] = build_modes.index(m) if m in build_modes else 0
        return BlockedSparse(layouts=layouts, mode_map=mode_map,
                             dims=tt.dims, nnz=tt.nnz, opts=opts)

    @staticmethod
    def compile(tt: SparseTensor, opts: Optional[Options] = None,
                rank: Optional[int] = None) -> "BlockedSparse":
        """:meth:`from_coo` + autotune: consult the tuner's plan cache
        (splatt_tpu/tune.py) for each mode's winning ``nnz_block`` and
        build the layouts at it directly.  `rank` keys the plan lookup
        (the winning configuration is rank-dependent); without it, or
        with autotune off, this is plain :meth:`from_coo`."""
        opts = (opts or default_opts()).validate()
        tuned_blocks = None
        if rank is not None:
            from splatt_tpu import tune

            if tune.autotune_enabled(opts.autotune):
                tuned_blocks = tune.tuned_blocks_for(
                    tt.dims, tt.nnz, rank, resolve_dtype(opts, tt.vals.dtype))
        return BlockedSparse.from_coo(tt, opts, tuned_blocks=tuned_blocks)

    def frobsq(self) -> float:
        """Squared Frobenius norm (≙ csf_frobsq, src/csf.c:828-851).

        Accumulated in f64 on host so both cpd_als drivers (COO via
        coo.normsq, blocked via this) share the same ⟨X,X⟩ to full
        precision — at 77M+ nnz an f32 accumulation loses digits in the
        fit denominator.
        """
        v = np.asarray(self.layouts[0].vals, dtype=np.float64)
        return float(np.dot(v, v))
