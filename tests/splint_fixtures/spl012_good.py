"""SPL012 good: emission sites name events declared in
resilience.py:RUN_REPORT_EVENTS."""

from splatt_tpu import resilience


def degrade_loudly(err):
    resilience.run_report().add(
        "engine_demotion", engine="example",
        failure_class="unknown", error=str(err))


def degrade_comm(err):
    # the comm-engine fallback ladder's evidence (docs/ring.md)
    resilience.run_report().add(
        "comm_fallback", strategy="async_ring", fallback_to="ring",
        failure_class="unknown", error=str(err))
