"""Interop bindings tests (≙ the MEX binding layer's role)."""

import numpy as np
import pytest

from splatt_tpu import interop
from splatt_tpu.config import Options, Verbosity
from tests import gen
from tests.test_mttkrp import np_mttkrp

torch = pytest.importorskip("torch")


def test_torch_roundtrip():
    tt = gen.fixture_tensor("med")
    t = interop.to_torch(tt)
    back = interop.from_torch(t)
    assert back.dims == tt.dims
    # coalesce sorts lexicographically; compare as dense
    np.testing.assert_allclose(back.to_dense(), tt.to_dense())


def test_torch_dense_input():
    dense = np.zeros((3, 4, 2))
    dense[0, 1, 0] = 2.0
    dense[2, 3, 1] = -1.5
    tt = interop.from_torch(torch.from_numpy(dense))
    assert tt.nnz == 2
    np.testing.assert_allclose(tt.to_dense(), dense)


def test_cpd_als_torch():
    tt = gen.fixture_tensor("small")
    t = interop.to_torch(tt)
    factors, lam, fit = interop.cpd_als_torch(
        t, rank=3, opts=Options(random_seed=2, max_iterations=5,
                                verbosity=Verbosity.NONE,
                                val_dtype=np.float64))
    assert len(factors) == 3
    assert factors[0].shape == (tt.dims[0], 3)
    assert lam.shape == (3,)
    assert 0.0 <= fit <= 1.0


def test_mttkrp_torch():
    tt = gen.fixture_tensor("small4")
    t = interop.to_torch(tt)
    rng = np.random.default_rng(5)
    factors = [torch.from_numpy(rng.random((d, 4))) for d in tt.dims]
    got = interop.mttkrp_torch(t, factors, 1).numpy()
    # torch coalesce re-sorts the tensor; MTTKRP is order-invariant
    want = np_mttkrp(interop.from_torch(t), factors, 1)
    np.testing.assert_allclose(got, want, atol=1e-10)


def test_scipy_bridge():
    scipy_sparse = pytest.importorskip("scipy.sparse")
    tt = gen.fixture_tensor("med")
    csr = interop.unfold_to_scipy(tt, 0)
    assert csr.shape[0] == tt.dims[0]
    assert csr.nnz == tt.nnz
    mat2 = interop.from_scipy(csr)
    assert mat2.nmodes == 2
    assert mat2.nnz == tt.nnz


def test_from_torch_requires_grad():
    dense = torch.rand(3, 4, 2, dtype=torch.float64, requires_grad=True)
    tt = interop.from_torch(dense)
    assert tt.nmodes == 3


def test_torch_outputs_are_writable():
    tt = gen.fixture_tensor("small")
    t = interop.to_torch(tt)
    factors, lam, fit = interop.cpd_als_torch(
        t, rank=2, opts=Options(random_seed=1, max_iterations=3,
                                verbosity=Verbosity.NONE,
                                val_dtype=np.float64))
    factors[0].mul_(2.0)  # in-place op must be safe (copied buffers)
    lam.add_(1.0)
