"""Fleet observability plane (docs/observability.md "Fleet"): metrics
aggregation, the SLO burn-rate layer, the flight recorder, merged
cross-replica traces, and `splatt status`/`top`.

The soak-level acceptance (a SIGKILL visible end-to-end: lease expiry
→ adoption → slo_burn spike → recovery, plus the victim's flight ring)
lives in tests/test_chaos.py::test_fleet_chaos_smoke_kill_and_failover;
this file pins each mechanism in isolation.
"""

import json
import os
import time

import pytest

from splatt_tpu import fleetobs, resilience, trace
from splatt_tpu.utils import faults
from splatt_tpu.utils.durable import publish_json, publish_text


@pytest.fixture(autouse=True)
def _clean_trace_state():
    trace.reset()
    trace.reset_metrics()
    trace.set_enabled(None)
    trace.set_replica(None)
    trace.set_flight(None)
    resilience.run_report().clear()
    faults.reset()
    yield
    trace.reset()
    trace.reset_metrics()
    trace.set_enabled(None)
    trace.set_replica(None)
    trace.set_flight(None)
    resilience.run_report().clear()
    faults.reset()


def _seed_metrics():
    trace.metric_inc("splatt_retries_total", 3)
    trace.metric_set("splatt_serve_queue_depth", 5.0)
    trace.metric_observe("splatt_job_seconds", 2.0)
    trace.metric_observe("splatt_serve_queue_wait_seconds", 0.05)


def _spool(tmp_path, reps=(("r0", True), ("r1", False)), text=None):
    """A synthetic shared spool: heartbeats (alive/dead) + snapshots."""
    now = time.time()
    os.makedirs(tmp_path / "fleet" / "replicas", exist_ok=True)
    os.makedirs(tmp_path / "fleet" / "metrics", exist_ok=True)
    for rid, alive in reps:
        publish_json(str(tmp_path / "fleet" / "replicas"
                         / f"{rid}.json"),
                     {"replica": rid, "pid": 1, "ts": now - 5,
                      "expires": now + (30 if alive else -1),
                      "regimes": ["d1:r4"], "active": 1})
        if text is not None:
            publish_text(str(tmp_path / "fleet" / "metrics"
                             / f"{rid}.prom"), text)
    return str(tmp_path)


# -- Prometheus parse / merge ------------------------------------------------

def test_prometheus_text_round_trips():
    """parse_prometheus inverts render_samples exactly — histograms
    (cumulative le series), labelled counters, gauges."""
    _seed_metrics()
    trace.metric_inc("splatt_events_total", kind="job_accepted")
    assert fleetobs.parse_prometheus(trace.metrics_text()) \
        == trace.samples()


def test_parse_skips_foreign_and_garbled_lines():
    text = ("garbage line without a value\n"
            "not_even{ 1.0\n"
            "splatt_retries_total 2.0\n"
            'foreign_series{x="y"} 7\n')
    out = fleetobs.parse_prometheus(text)
    assert out[("splatt_retries_total", ())] == 2.0
    assert ("foreign_series", (("x", "y"),)) in out


def test_aggregate_merge_semantics(tmp_path):
    """Counters sum (dead replicas' retained), gauges become
    per-replica series (dead replicas' dropped), histograms
    bucket-merge, and the synthesized liveness census gauge counts
    heartbeats by state."""
    _seed_metrics()
    root = _spool(tmp_path, text=trace.metrics_text())
    agg = fleetobs.aggregate(root)
    s = agg.samples
    assert s[("splatt_retries_total", ())] == 6.0
    assert s[("splatt_serve_queue_depth",
              (("replica", "r0"),))] == 5.0
    assert not any(n == "splatt_serve_queue_depth"
                   and dict(lk).get("replica") == "r1"
                   for (n, lk) in s)
    assert s[("splatt_job_seconds", ())]["count"] == 2
    assert s[("splatt_fleet_replicas", (("state", "alive"),))] == 1.0
    assert s[("splatt_fleet_replicas", (("state", "dead"),))] == 1.0
    assert agg.replicas["r0"]["alive"] and not agg.replicas["r1"]["alive"]
    # the merged exposition renders and re-parses
    path = fleetobs.write_fleet_metrics(agg)
    merged = fleetobs.parse_prometheus(open(path).read())
    assert merged[("splatt_retries_total", ())] == 6.0


def test_aggregate_finds_retired_replicas_snapshots(tmp_path):
    """A gracefully retired replica (heartbeat deleted, snapshot left
    in fleet/metrics/) keeps contributing its counters — gauges and
    the census exclude it."""
    _seed_metrics()
    root = _spool(tmp_path, reps=(), text=None)
    publish_text(os.path.join(root, "fleet", "metrics", "gone.prom"),
                 trace.metrics_text())
    agg = fleetobs.aggregate(root)
    assert agg.samples[("splatt_retries_total", ())] == 3.0
    assert not any(n == "splatt_serve_queue_depth"
                   for (n, _lk) in agg.samples)
    assert agg.samples[("splatt_fleet_replicas",
                        (("state", "dead"),))] == 0.0
    assert agg.replicas["gone"]["heartbeat"] is False


# -- SLO layer ---------------------------------------------------------------

def _burn_env(monkeypatch):
    monkeypatch.setenv("SPLATT_SLO_QUEUE_WAIT_P95_S", "1.0")


def test_slo_first_evaluation_is_baseline(monkeypatch):
    _burn_env(monkeypatch)
    ev = fleetobs.SloEvaluator(window_s=10, long_windows=2, burn=1.0)
    res = ev.evaluate(trace.samples(), now=1000.0)
    assert all(s["baseline"] and not s["burning"]
               for s in res["slos"].values())
    assert not resilience.run_report().events("slo_burn")


def test_slo_burn_fires_and_recovers(monkeypatch):
    """Bad queue waits burn the budget on both windows → slo_burn
    event + splatt_slo_burn_total; a later quiet window recovers."""
    _burn_env(monkeypatch)
    ev = fleetobs.SloEvaluator(window_s=10, long_windows=2, burn=1.0)
    trace.metric_observe("splatt_serve_queue_wait_seconds", 0.05)
    ev.evaluate(trace.samples(), now=1000.0)
    trace.metric_observe("splatt_serve_queue_wait_seconds", 50.0)
    trace.metric_observe("splatt_serve_queue_wait_seconds", 50.0)
    res = ev.evaluate(trace.samples(), now=1005.0)
    slo = res["slos"]["queue_wait_p95"]
    assert slo["burning"] and slo["burn_short"] >= 1.0
    evs = resilience.run_report().events("slo_burn")
    assert evs and evs[-1]["slo"] == "queue_wait_p95"
    assert trace.samples()[("splatt_slo_burn_total",
                            (("slo", "queue_wait_p95"),))] >= 1.0
    # recovery: no new traffic in the window → not burning
    res2 = ev.evaluate(trace.samples(), now=1030.0)
    assert not res2["slos"]["queue_wait_p95"]["burning"]


def test_slo_multi_window_gating_suppresses_stale_burn(monkeypatch):
    """A spike older than the short window but inside the long one
    must NOT page: both windows have to burn (the multi-window point)."""
    _burn_env(monkeypatch)
    ev = fleetobs.SloEvaluator(window_s=5, long_windows=6, burn=1.0)
    ev.evaluate(trace.samples(), now=1000.0)
    trace.metric_observe("splatt_serve_queue_wait_seconds", 50.0)
    res = ev.evaluate(trace.samples(), now=1002.0)
    assert res["slos"]["queue_wait_p95"]["burning"]
    resilience.run_report().clear()
    # 20s later (outside the 5s short window, inside the 30s long one)
    res2 = ev.evaluate(trace.samples(), now=1022.0)
    slo = res2["slos"]["queue_wait_p95"]
    assert slo["burn_long"] >= 1.0 and not slo["burning"]
    assert not resilience.run_report().events("slo_burn")


def test_slo_availability_counts_shed_fraction():
    for _ in range(3):
        trace.metric_inc("splatt_events_total", kind="job_accepted")
    trace.metric_inc("splatt_events_total", kind="queue_full")
    trace.metric_inc("splatt_events_total", kind="quota_rejected")
    good, total = fleetobs._availability_good_total(trace.samples())
    assert (good, total) == (3, 5)


def test_slo_counter_reset_clamps_to_zero(monkeypatch):
    """A restarted replica shrinking the merged counters must not burn
    a negative budget (deltas clamp at zero)."""
    _burn_env(monkeypatch)
    ev = fleetobs.SloEvaluator(window_s=10, long_windows=2, burn=1.0)
    trace.metric_observe("splatt_serve_queue_wait_seconds", 50.0)
    ev.evaluate(trace.samples(), now=1000.0)
    trace.reset_metrics()  # the "restart"
    res = ev.evaluate(trace.samples(), now=1005.0)
    slo = res["slos"]["queue_wait_p95"]
    assert not slo["burning"] and slo["burn_short"] == 0.0


def test_slo_state_roundtrip(tmp_path):
    ev = fleetobs.SloEvaluator(window_s=10, long_windows=2,
                               burn=1.0, replica="r0")
    ev.evaluate(trace.samples(), now=1000.0)
    os.makedirs(tmp_path / "fleet", exist_ok=True)
    ev.write_state(fleetobs.slo_state_path(str(tmp_path), "r0"))
    states = fleetobs.read_slo_states(str(tmp_path))
    assert states["r0"]["replica"] == "r0"
    assert states["latest"]["slos"].keys() == \
        {"queue_wait_p95", "job_wall_p95", "availability",
         "predict_latency_p99"}


# -- flight recorder ---------------------------------------------------------

def test_flight_ring_records_and_rotates(tmp_path):
    """Finished spans/points append to the ring; the file rotates
    atomically at the byte bound (one .1 generation kept) so the black
    box stays bounded."""
    trace.set_enabled(True)
    fp = str(tmp_path / "flight.jsonl")
    trace.set_flight(fp, max_bytes=400, flush_every=1)
    for i in range(8):
        with trace.span("cpd.iter", it=i):
            pass
    trace.flight_flush()
    assert os.path.exists(fp + ".1")
    evs = trace.load_flight(fp)
    assert evs and all(e["ph"] in ("X", "i") for e in evs)
    assert os.path.getsize(fp + ".1") <= 800  # bounded, not unbounded


def test_flight_survives_torn_tail(tmp_path):
    trace.set_enabled(True)
    fp = str(tmp_path / "flight.jsonl")
    trace.set_flight(fp, max_bytes=1 << 20, flush_every=1)
    with trace.span("cpd.iter", it=0):
        pass
    trace.set_flight(None)
    with open(fp, "ab") as f:
        f.write(b'{"half-a-record')  # the SIGKILL mid-append shape
    evs = trace.load_flight(fp)
    assert len(evs) == 1 and evs[0]["name"] == "cpd.iter"


def test_orphaned_rotated_ring_still_merges(tmp_path):
    """A SIGKILL between rotation and the next flush leaves only
    <ring>.jsonl.1: directory expansion (and an explicit .jsonl.1
    path) must still surface the ring via its base name instead of
    silently dropping the victim's black box."""
    trace.set_enabled(True)
    fp = str(tmp_path / "flight-rv.jsonl")
    trace.set_flight(fp, max_bytes=1, flush_every=1)  # rotate always
    with trace.span("cpd.iter", it=0):
        pass
    trace.set_flight(None)
    assert os.path.exists(fp + ".1") and not os.path.exists(fp)
    assert trace.expand_trace_paths([str(tmp_path)]) == [fp]
    assert trace.expand_trace_paths([fp + ".1"]) == [fp]
    merged = trace.merge_trace_files([str(tmp_path)])
    assert any(e.get("name") == "cpd.iter" for e in merged)


def test_recorder_bounded_for_long_lived_daemons(tmp_path, monkeypatch):
    """A fleet daemon runs with recording on for life: past
    SPLATT_TRACE_MAX_RECORDS the recorder drops its OLDEST records
    (the flight ring already persisted them) and the export says so
    (dropped_spans on trace_written) instead of growing RSS forever."""
    monkeypatch.setenv("SPLATT_TRACE_MAX_RECORDS", "100")
    trace.reset()  # re-earn the cap verdict
    trace.set_enabled(True)
    for i in range(1500):
        with trace.span("cpd.iter", it=i):
            pass
    assert len(trace.spans()) <= 1000  # the enforced floor of the cap
    ev = trace.write_chrome_trace(str(tmp_path / "t.json"))
    assert ev["ok"] and ev["dropped_spans"] > 0
    # the newest records survive, the oldest fell off
    its = [s["args"]["it"] for s in trace.spans("cpd.iter")]
    assert its[-1] == 1499 and its[0] > 0


def test_trace_spool_directory_finds_flight_rings(tmp_path):
    """`splatt trace <spool>` merges the spool's fleet/flight rings
    (docs/fleet.md's promise) without the operator naming the subdir,
    and a journal.jsonl swept up by the expansion contributes no
    bogus process row."""
    trace.set_enabled(True)
    fdir = tmp_path / "fleet" / "flight"
    os.makedirs(fdir)
    trace.set_flight(str(fdir / "rv.jsonl"), flush_every=1)
    with trace.span("cpd.iter", it=0, job="jx"):
        pass
    trace.set_flight(None)
    (tmp_path / "journal.jsonl").write_text(
        '{"rec": "accepted", "job": "jx"}\n')
    files = trace.expand_trace_paths([str(tmp_path)])
    assert str(fdir / "rv.jsonl") in files
    merged = trace.merge_trace_files([str(tmp_path)])
    assert any(e.get("name") == "cpd.iter" for e in merged)
    rows = [e for e in merged if e.get("ph") == "M"]
    assert len(rows) == 1  # the ring's row only — no journal row


def test_exit_tick_burn_is_durable_in_snapshot(tmp_path, monkeypatch):
    """A burn detected on the daemon's LAST metrics tick must still
    land in the written snapshot: write_metrics_now re-snapshots
    after a burning SLO tick, so the post-mortem aggregate counts it."""
    from splatt_tpu import serve

    mpath = str(tmp_path / "m.prom")
    monkeypatch.setenv("SPLATT_METRICS_PATH", mpath)
    monkeypatch.setenv("SPLATT_SLO_QUEUE_WAIT_P95_S", "1.0")
    srv = serve.Server(str(tmp_path / "root"))
    srv.write_metrics_now()  # baseline evaluation, nothing burning
    assert "splatt_slo_burn_total" not in open(mpath).read()
    trace.metric_observe("splatt_serve_queue_wait_seconds", 50.0)
    srv.write_metrics_now()  # burns on THIS tick — the "exit" tick
    assert "splatt_slo_burn_total" in open(mpath).read()


def test_flight_missing_ring_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        trace.load_flight(str(tmp_path / "nope.jsonl"))


def test_flight_fault_disarms_classified(tmp_path):
    """The trace.flight fault-site drill (trace.export discipline): a
    flush failure DISARMS the recorder and degrades to a classified
    flight_degraded event — never an exception on the span path."""
    trace.set_enabled(True)
    fp = str(tmp_path / "flight.jsonl")
    with faults.inject("trace.flight", "runtime"):
        trace.set_flight(fp, flush_every=1)
        with trace.span("cpd.iter", it=0):
            pass
    assert trace.flight_path() is None
    evs = resilience.run_report().events("flight_degraded")
    assert evs and evs[-1]["path"] == fp
    assert evs[-1]["failure_class"]
    # and the kind is declared (SPL012 discipline)
    assert "flight_degraded" in resilience.RUN_REPORT_EVENTS
    assert any("flight recorder" in ln
               for ln in resilience.run_report().summary())


def test_flight_points_ride_the_ring(tmp_path):
    trace.set_enabled(True)
    trace.set_replica("rX")
    fp = str(tmp_path / "flight.jsonl")
    trace.set_flight(fp, flush_every=1)
    resilience.run_report().add("job_started", job="j9")
    trace.set_flight(None)
    evs = trace.load_flight(fp)
    marks = [e for e in evs if e["name"] == "job_started"]
    assert marks and marks[0]["args"]["job"] == "j9"
    assert marks[0]["args"]["replica"] == "rX"


# -- cross-replica merge + adoption lineage ----------------------------------

def _victim_and_adopter(tmp_path):
    """Simulate the failover's trace artifacts: the victim leaves only
    a flight ring (SIGKILL — its serve.job span never closed); the
    adopter exports a Chrome trace whose serve.job span carries
    adopted_from + the terminal status."""
    trace.set_enabled(True)
    trace.set_replica("rv")
    vpath = str(tmp_path / "flight-rv.jsonl")
    trace.set_flight(vpath, flush_every=1)
    resilience.run_report().add("job_started", job="j1")
    with trace.span("cpd.iter", it=0, job="j1"):
        pass
    trace.set_flight(None)
    trace.reset()
    trace.set_replica("ra")
    with trace.span("serve.job", job="j1", resumed=True,
                    adopted_from="rv", replica="ra") as sp:
        sp.set(status="converged")
    apath = str(tmp_path / "trace-ra.json")
    trace.write_chrome_trace(apath)
    return vpath, apath


def test_merged_trace_links_adoption_lineage(tmp_path):
    """ISSUE 14 satellite: the merged trace renders victim + adopter
    as ONE logical job timeline — flow events from the victim's last
    pre-kill event to the adopter's serve.job span, per-source process
    rows, and exactly one terminal commit in the lineage summary."""
    vpath, apath = _victim_and_adopter(tmp_path)
    merged = trace.merge_trace_files([apath, vpath])
    # distinct process rows named by replica
    rows = {(e["pid"], e["args"]["name"]) for e in merged
            if e.get("ph") == "M" and e["name"] == "process_name"}
    assert len(rows) == 2
    assert {n for _, n in rows} == {"replica ra", "replica rv"}
    # the flow arrow: ph s on the victim's row, ph f on the adopter's
    flows = [e for e in merged if e.get("name") == "job_lineage"]
    assert {e["ph"] for e in flows} == {"s", "f"}
    s_ev = next(e for e in flows if e["ph"] == "s")
    f_ev = next(e for e in flows if e["ph"] == "f")
    assert s_ev["pid"] != f_ev["pid"]
    assert s_ev["args"] == {"job": "j1", "from_replica": "rv"}
    assert s_ev["ts"] <= f_ev["ts"]
    # lineage summary: adopted_from carried, exactly ONE terminal commit
    summ = trace.summarize(merged)
    lineage = summ["jobs"]["j1"]
    assert [r for r in lineage if r["adopted_from"] == "rv"]
    terminal = [r for r in lineage
                if r["status"] in ("converged", "degraded", "failed")]
    assert len(terminal) == 1 and terminal[0]["replica"] == "ra"
    # the human summary names the hop
    text = "\n".join(trace.format_summary(summ))
    assert "adopted_from=rv" in text


def test_merge_directory_and_cli(tmp_path, capsys):
    """`splatt trace` accepts multiple files / a directory, merges
    them, and --out writes a perfetto-loadable merged file."""
    from splatt_tpu.cli import main

    vpath, apath = _victim_and_adopter(tmp_path)
    out = str(tmp_path / "merged.json")
    rc = main(["trace", str(tmp_path), "--out", out, "--json"])
    assert rc == 0
    outtext = capsys.readouterr().out
    rec = json.loads([l for l in outtext.splitlines()
                      if l.startswith("{")][-1])
    assert rec["jobs"]["j1"]
    merged = trace.load_trace(out)
    assert any(e.get("name") == "job_lineage" for e in merged)
    # single-file form still works
    rc = main(["trace", apath])
    assert rc == 0
    assert "serve.job" in capsys.readouterr().out


def test_span_and_point_records_carry_replica():
    trace.set_enabled(True)
    trace.set_replica("r7")
    with trace.span("cpd.iter", it=0):
        trace.point("health_rollback", {})
    assert trace.spans("cpd.iter")[-1]["replica"] == "r7"
    assert trace.points("health_rollback")[-1]["replica"] == "r7"
    evs = trace.chrome_events()
    assert evs[0]["ph"] == "M" \
        and evs[0]["args"]["name"] == "replica r7"
    assert all(e["args"].get("replica") == "r7"
               for e in evs if e.get("ph") in ("X", "i"))


# -- serve integration: queue-wait + status ----------------------------------

def _tiny_spec(jid="q1", **kw):
    return dict({"id": jid, "rank": 3, "iters": 2,
                 "synthetic": {"dims": [10, 8, 6], "nnz": 200,
                               "seed": 0}}, **kw)


def test_server_observes_queue_wait(tmp_path):
    from splatt_tpu import serve

    srv = serve.Server(str(tmp_path), workers=1)
    srv.submit(_tiny_spec())
    srv.run_once()
    s = trace.samples()
    waits = [v for (n, _lk), v in s.items()
             if n == "splatt_serve_queue_wait_seconds"]
    assert waits and sum(h["count"] for h in waits) >= 1


def test_fleet_status_reads_spool(tmp_path):
    """fleet_status derives jobs/queue/tenants/recent from the journal
    + heartbeats alone, and format_status renders it."""
    from splatt_tpu import serve

    srv = serve.Server(str(tmp_path), workers=1, fleet=True,
                       replica="r0")
    srv.submit(_tiny_spec("s1", tenant="acme"))
    srv.run_once()
    srv.submit(_tiny_spec("s2", tenant="beta"))  # queued, not run
    st = fleetobs.fleet_status(str(tmp_path))
    assert st["jobs"]["s1"] == "done" and st["jobs"]["s2"] == "accepted"
    assert st["pending"] == 1
    assert st["tenants"] == {"beta": 1}
    assert [r["job"] for r in st["recent"]] == ["s1"]
    assert st["replicas"]["r0"]["alive"]
    text = "\n".join(fleetobs.format_status(st))
    assert "s1" in text and "ALIVE r0" in text
    srv.shutdown()


def test_status_cli_json_and_metrics_out(tmp_path, capsys):
    from splatt_tpu import serve
    from splatt_tpu.cli import main

    srv = serve.Server(str(tmp_path), workers=1, fleet=True,
                       replica="r0")
    srv.submit(_tiny_spec("s1"))
    srv.run_once()
    srv.write_metrics_now()
    srv.shutdown()
    mout = str(tmp_path / "fleet-agg.prom")
    rc = main(["status", str(tmp_path), "--json",
               "--metrics-out", mout])
    assert rc == 0
    out = capsys.readouterr().out
    st = json.loads([l for l in out.splitlines()
                     if l.startswith("{")][-1])
    assert st["jobs"]["s1"] == "done"
    merged = fleetobs.parse_prometheus(open(mout).read())
    assert any(n == "splatt_serve_jobs_total"
               for (n, _lk) in merged)


def test_top_parser_watches_by_default():
    from splatt_tpu.cli import build_parser

    args = build_parser().parse_args(["top", "/tmp/x"])
    assert args.watch and args.fn.__name__ == "cmd_status"
    args = build_parser().parse_args(["top", "/tmp/x", "--once"])
    assert not args.watch
    args = build_parser().parse_args(["status", "/tmp/x"])
    assert not args.watch
    args = build_parser().parse_args(["status", "/tmp/x", "--watch"])
    assert args.watch


# -- exit-snapshot audit (drain, SIGTERM, torn-file) -------------------------

def test_sigterm_drain_writes_exit_snapshot_and_trace(tmp_path):
    """ISSUE 14 satellite audit: a SIGTERM'd `splatt serve` daemon (not
    just a normal --once return) still writes the exit Prometheus
    snapshot AND exports its --trace file; the snapshot parses whole
    (atomic replace — never a torn file)."""
    import subprocess
    import sys

    mpath = str(tmp_path / "metrics.prom")
    tpath = str(tmp_path / "trace.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               SPLATT_METRICS_PATH=mpath,
               SPLATT_METRICS_INTERVAL_S="0.2",
               SPLATT_SERVE_POLL_S="0.1")
    p = subprocess.Popen(
        [sys.executable, "-m", "splatt_tpu.cli", "serve",
         str(tmp_path), "--trace", tpath],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    deadline = time.time() + 60
    while time.time() < deadline and not os.path.exists(mpath):
        if p.poll() is not None:
            raise AssertionError(p.stderr.read().decode()[-500:])
        time.sleep(0.1)
    p.terminate()  # SIGTERM → graceful drain
    p.wait(timeout=60)
    assert p.returncode == 0
    assert fleetobs.parse_prometheus(open(mpath).read())
    evs = trace.load_trace(tpath)
    assert isinstance(evs, list)  # loadable Chrome trace JSON


def test_metrics_snapshots_only_via_atomic_publish():
    """Every metrics-snapshot path goes through the sanctioned atomic
    publish (tmp + fsync + rename): a mid-write kill can never leave a
    torn file.  Enforced statically by splint SPL016 over the whole
    tree; spot-checked here at the two snapshot chokepoints."""
    import inspect

    from splatt_tpu import trace as _t

    assert "publish_text" in inspect.getsource(_t.write_metrics)
    assert "publish_text" in inspect.getsource(
        fleetobs.write_fleet_metrics)
