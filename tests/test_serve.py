"""`splatt serve` — the isolated, crash-resumable multi-tenant daemon.

The contracts under test (docs/serve.md):

- durability-first accept: a job is journaled before the submitter
  hears "accepted"; journal failure rejects instead of silently
  forgetting; re-submission is idempotent;
- bounded queue with explicit `queue_full` load shedding;
- journal replay: a fresh Server over a crashed daemon's root
  re-enqueues every accepted-but-non-terminal job (torn final lines
  skipped) and the jobs resume from their checkpoints;
- THE ISOLATION INVARIANT: two concurrent jobs — one driven to a
  NUMERICAL rollback, one to an OOM engine demotion via per-job fault
  schedules — finish with each other's demotion tables and health
  verdicts untouched, and a later same-regime job hits the warm shared
  plan cache with zero measurements;
- graceful drain: SIGTERM interrupts running jobs at a fit check,
  checkpoints them, and the next start resumes them;
- the serve fault sites (serve.submit / serve.journal_write /
  serve.job_run) degrade, classified, never killing the daemon.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from splatt_tpu import resilience, serve, tune
from splatt_tpu.utils import faults

SYN = {"dims": [20, 16, 12], "nnz": 1200, "seed": 0}


def _spec(jid, **kw):
    spec = {"id": jid, "rank": 3, "iters": 6, "seed": 0,
            "synthetic": dict(SYN)}
    spec.update(kw)
    return spec


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    def clean():
        faults.reset()
        resilience.reset_demotions()
        resilience.run_report().clear()
        # the global scope's async attempt note (other modules' dispatch
        # tests leave one behind)
        resilience._state().last_attempt = None

    clean()
    yield
    clean()


@pytest.fixture()
def private_caches(tmp_path, monkeypatch):
    """Throwaway probe/plan caches so tuning jobs cannot dirty (or be
    steered by) the repo's real shared caches."""
    monkeypatch.setenv("SPLATT_TUNE_CACHE", str(tmp_path / "tc.json"))
    monkeypatch.setenv("SPLATT_PROBE_CACHE", str(tmp_path / "pc.json"))
    tune.reset_memo()
    yield
    tune.reset_memo()


def _journal_kinds(root, jid):
    recs, _ = serve.Journal(os.path.join(root, "journal.jsonl")).replay()
    return [r["rec"] for r in recs if r.get("job") == jid]


# -- queue / API basics ------------------------------------------------------

def test_submit_run_result_and_lineage(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    r = srv.submit(_spec("j1"))
    assert r["state"] == serve.ACCEPTED
    assert resilience.run_report().events("job_accepted")
    summary = srv.run_once()
    assert summary["counts"] == {serve.DONE: 1}
    res = srv.result("j1")
    assert res["status"] == "converged" and res["fit"] > 0
    assert res["demotions"] == [] and res["resumed"] is False
    assert _journal_kinds(str(tmp_path), "j1") == [
        serve.ACCEPTED, serve.STARTED, serve.DONE]
    assert srv.status("j1")["status"] == "converged"


def test_filed_request_roundtrip(tmp_path):
    root = str(tmp_path)
    jid = serve.file_request(root, _spec("filed1"))
    assert jid == "filed1"
    assert serve.read_status(root, jid)["state"] == "filed"
    srv = serve.Server(root, workers=1)
    srv.run_once()
    # spool file consumed, result published, status journal-derived
    assert not os.path.exists(
        os.path.join(root, "requests", "filed1.json"))
    st = serve.read_status(root, jid)
    assert st["state"] == serve.DONE and st["status"] == "converged"
    assert st["result"]["fit"] > 0
    assert serve.read_result(root, jid)["job"] == jid


def test_duplicate_submission_is_idempotent(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1)
    srv.submit(_spec("dup"))
    again = srv.submit(_spec("dup"))
    assert again["duplicate"] is True
    srv.run_once()
    # a crashed client retrying after completion: still deduped
    after = srv.submit(_spec("dup"))
    assert after["duplicate"] is True and after["state"] == serve.DONE
    assert _journal_kinds(str(tmp_path), "dup").count(serve.ACCEPTED) == 1


def test_invalid_spec_rejected(tmp_path):
    srv = serve.Server(str(tmp_path))
    r = srv.submit({"id": "bad", "rank": 3})  # no workload
    assert r["state"] == serve.REJECTED and "invalid" in r["reason"]
    assert serve.read_result(str(tmp_path), "bad")["status"] == "rejected"
    with pytest.raises(ValueError):
        srv.submit({"id": "../escape", "synthetic": SYN})


def test_queue_full_load_shedding(tmp_path):
    srv = serve.Server(str(tmp_path), workers=1, queue_max=1)
    assert srv.submit(_spec("q1"))["state"] == serve.ACCEPTED
    r2 = srv.submit(_spec("q2"))
    assert r2["state"] == serve.REJECTED and r2["reason"] == "queue_full"
    evs = resilience.run_report().events("queue_full")
    assert len(evs) == 1 and evs[0]["job"] == "q2"
    # the rejection is a published, machine-readable verdict
    res = serve.read_result(str(tmp_path), "q2")
    assert res["status"] == "rejected" and res["reason"] == "queue_full"
    assert serve.REJECTED in _journal_kinds(str(tmp_path), "q2")
    # the accepted job still runs to done; the queue frees up again
    srv.run_once()
    assert srv.status("q1")["status"] == "converged"
    assert srv.submit(_spec("q3"))["state"] == serve.ACCEPTED


def test_malformed_request_quarantined(tmp_path):
    root = str(tmp_path)
    srv = serve.Server(root, workers=1)
    bad = os.path.join(root, "requests", "broken.json")
    with open(bad, "w") as f:
        f.write("{not json")
    srv.scan_requests()
    assert not os.path.exists(bad)
    assert os.path.exists(bad + ".bad")
    # the scanner does not spin on the quarantined file
    assert srv.scan_requests() == 0


# -- crash-resume ------------------------------------------------------------

def test_replay_resumes_accepted_jobs(tmp_path):
    """CRASH-RESUME INVARIANT (in-process half; the SIGKILL half lives
    in test_chaos.py's serve soak): accepted-but-non-terminal jobs are
    re-enqueued on restart, reach terminal states, and their journal
    lineage is intact."""
    root = str(tmp_path)
    s1 = serve.Server(root, workers=1)
    s1.submit(_spec("r1"))
    s1.submit(_spec("r2", synthetic=dict(SYN, seed=1)))
    del s1  # "crash": accepted, never run
    s2 = serve.Server(root, workers=1)
    resumed = {e["job"] for e in
               resilience.run_report().events("job_resumed")}
    assert {"r1", "r2"} <= resumed
    assert s2.status("r1")["resumed"] is True
    summary = s2.run_once()
    assert summary["counts"] == {serve.DONE: 2}
    for jid in ("r1", "r2"):
        res = serve.read_result(root, jid)
        assert res["status"] == "converged" and res["resumed"] is True
        assert _journal_kinds(root, jid) == [
            serve.ACCEPTED, serve.RESUMED, serve.STARTED, serve.DONE]


def test_torn_journal_line_is_skipped(tmp_path):
    """A SIGKILL can tear the final journal line; replay must skip it
    and keep every complete record."""
    root = str(tmp_path)
    s1 = serve.Server(root, workers=1)
    s1.submit(_spec("t1"))
    s1.run_once()
    with open(os.path.join(root, "journal.jsonl"), "a") as f:
        f.write('{"rec": "acce')  # torn mid-append
    s2 = serve.Server(root)
    assert s2.status("t1")["status"] == "converged"
    recs, torn = s2.journal.replay()
    assert torn == 1 and len(recs) == 3


def test_unknown_journal_kind_is_skipped_and_classified(tmp_path):
    """FORWARD-COMPAT INVARIANT: a record kind this version does not
    know (a newer writer's journal, or corruption that still parses)
    is SKIPPED with a classified ``journal_unknown_kind`` event — it
    must neither wedge replay nor invent job-table state."""
    root = str(tmp_path)
    s1 = serve.Server(root, workers=1)
    s1.submit(_spec("u1"))
    s1.run_once()
    with open(os.path.join(root, "journal.jsonl"), "a") as f:
        f.write('{"rec": "paused_v99", "job": "u1", "ts": 1.0}\n')
        f.write('{"rec": "paused_v99", "job": "u2", "ts": 2.0}\n')
    s2 = serve.Server(root, workers=1)
    # the known lineage replays untouched; the unknown kinds are
    # dropped on the floor rather than mutating (or creating) jobs
    assert s2.status("u1")["status"] == "converged"
    # no job-table entry was invented for u2 (absent jobs report a
    # bare state-None shell with no status field)
    assert s2.status("u2") == {"job": "u2", "state": None}
    evs = resilience.run_report().events("journal_unknown_kind")
    assert {e["job"] for e in evs} == {"u1", "u2"}
    assert all(e["record_kind"] == "paused_v99" for e in evs)
    # the declared vocabulary is what replay checks against
    assert "paused_v99" not in serve.KNOWN_KINDS
    assert serve.DONE in serve.KNOWN_KINDS
    # and the scheduler is not wedged: u1 stays terminal, started once
    assert s2.run_once()["counts"] == {serve.DONE: 1}
    assert _journal_kinds(root, "u1").count(serve.STARTED) == 1


def test_terminal_jobs_are_not_rerun(tmp_path):
    root = str(tmp_path)
    s1 = serve.Server(root, workers=1)
    s1.submit(_spec("fin"))
    s1.run_once()
    s2 = serve.Server(root, workers=1)
    assert s2.summary()["pending"] == 0
    assert s2.run_once()["counts"] == {serve.DONE: 1}
    # started exactly once: the journal shows a single start
    assert _journal_kinds(root, "fin").count(serve.STARTED) == 1


# -- THE isolation invariant -------------------------------------------------

def test_isolation_two_concurrent_jobs_and_warm_cache(tmp_path,
                                                      private_caches):
    """ISOLATION INVARIANT (acceptance): two concurrent jobs — one
    driven to a NUMERICAL rollback by a per-job NaN schedule, one to an
    OOM engine demotion — finish with the *other* job's demotion table
    and health verdicts untouched (and the global scope clean), while a
    later same-regime job records a warm plan-cache hit with zero
    measurements."""
    srv = serve.Server(str(tmp_path), workers=2, queue_max=8)
    nan_job = _spec("nanjob", iters=8, tune=True, health_retries=2,
                    faults="cpd.sweep:nan:iter=2")
    # interpret-mode pallas gives a real multi-engine chain on CPU;
    # every non-terminal engine is OOM-armed once, so whichever heads
    # the chain demotes per-shape (RESOURCE) and dispatch degrades
    oom_job = _spec("oomjob", iters=8, use_pallas=True, autotune=False,
                    synthetic=dict(SYN, seed=1),
                    faults="engine.fused_t:oom:1,engine.fused_tg:oom:1,"
                           "engine.unfused_pallas:oom:1,"
                           "engine.xla_scan:oom:1")
    srv.submit(nan_job)
    srv.submit(oom_job)
    summary = srv.run_once()
    assert summary["counts"] == {serve.DONE: 2}, summary

    ra = serve.read_result(str(tmp_path), "nanjob")
    rb = serve.read_result(str(tmp_path), "oomjob")
    kinds_a = {e["kind"] for e in ra["events"]}
    kinds_b = {e["kind"] for e in rb["events"]}

    # job A: rolled back, converged, demoted NOTHING (NUMERICAL is the
    # sentinel's, never the demotion registry's)
    assert ra["status"] == "converged"
    assert {"health_nonfinite", "health_rollback"} <= kinds_a
    assert ra["demotions"] == []
    assert ra["faults_fired"] == {"cpd.sweep": 1}
    # every event in A's report is attributed to A
    assert all(e.get("job", "nanjob") == "nanjob" for e in ra["events"])

    # job B: OOM-demoted per-shape, degraded to the next engine,
    # converged — and saw NONE of A's health trouble
    assert rb["status"] == "converged"
    assert "engine_demotion" in kinds_b
    assert rb["demotions"], "the OOM never demoted an engine"
    assert all(d["failure_class"] == "resource" and d["shape_key"]
               for d in rb["demotions"])
    assert not (kinds_b & {"health_nonfinite", "health_rollback",
                           "health_degraded"})

    # the global scope is untouched by either tenant
    assert resilience.demotions() == []
    for d in rb["demotions"]:
        assert not resilience.is_demoted(d["engine"], d["shape_key"])
    global_kinds = {e["kind"] for e in resilience.run_report().events()}
    assert not (global_kinds & {"engine_demotion", "health_nonfinite",
                                "health_rollback"})

    # the second same-regime tuning job (same shape regime as the NaN
    # tenant's — regimes bucket by power-of-two dims/nnz): warm shared
    # plan cache — zero measurements, one cache hit per mode
    warm = _spec("warmjob", tune=True, synthetic=dict(SYN))
    srv.submit(warm)
    srv.run_once()
    rc = serve.read_result(str(tmp_path), "warmjob")
    assert rc["status"] == "converged"
    assert rc["tune"]["measured"] == 0
    assert rc["tune"]["cache_hits"] == len(SYN["dims"])


# -- graceful drain ----------------------------------------------------------

def test_drain_checkpoints_running_job_and_restart_resumes(tmp_path):
    """SIGTERM semantics: a running job is interrupted at a fit check
    through the cpd stop hook, checkpoints, is journaled
    `interrupted`, and the next start resumes it to convergence."""
    root = str(tmp_path)
    s1 = serve.Server(root, workers=1)
    # the slow fault pins the job open at start so the drain
    # deterministically lands while it runs
    s1.submit(_spec("d1", iters=50, tol=0.0, checkpoint_every=100,
                    synthetic=dict(SYN, nnz=3000),
                    faults="serve.job_run:slow:delay=1.5"))
    t = threading.Thread(target=s1.run_once)
    t.start()
    time.sleep(0.6)  # inside the slow-fault window
    s1.drain()
    t.join(timeout=180)
    assert not t.is_alive()
    assert s1.status("d1")["state"] == serve.INTERRUPTED
    ck = os.path.join(root, "ckpt", "d1.npz")
    assert os.path.exists(ck)
    from splatt_tpu.cpd import load_checkpoint

    _, _, it, _ = load_checkpoint(ck)
    assert 1 <= it < 50  # checkpointed mid-run by the stop hook

    s2 = serve.Server(root, workers=1)
    assert s2.status("d1")["resumed"] is True
    assert s2.run_once()["counts"] == {serve.DONE: 1}
    res = serve.read_result(root, "d1")
    assert res["resumed"] is True and res["status"] in ("converged",)
    assert _journal_kinds(root, "d1") == [
        serve.ACCEPTED, serve.STARTED, serve.INTERRUPTED,
        serve.RESUMED, serve.STARTED, serve.DONE]


def test_drain_leaves_queued_jobs_journaled(tmp_path):
    root = str(tmp_path)
    srv = serve.Server(root, workers=1)
    srv.submit(_spec("never-ran"))
    srv.drain()
    assert srv.run_once()["counts"] == {serve.ACCEPTED: 1}
    # the restart picks it up
    s2 = serve.Server(root, workers=1)
    assert s2.run_once()["counts"] == {serve.DONE: 1}


# -- serve fault sites (SPL006) ----------------------------------------------

def test_submit_fault_quarantines_filed_request(tmp_path):
    """serve.submit: a raised fault rejects THAT submission (the spool
    scanner quarantines the request, classified) — the daemon lives."""
    root = str(tmp_path)
    srv = serve.Server(root, workers=1)
    serve.file_request(root, _spec("sf1"))
    with faults.inject("serve.submit", "runtime", times=1):
        assert srv.scan_requests() == 0
    assert os.path.exists(
        os.path.join(root, "requests", "sf1.json.bad"))
    # the daemon keeps serving
    srv.submit(_spec("sf2"))
    srv.run_once()
    assert srv.status("sf2")["status"] == "converged"


def test_journal_fault_rejects_submission_durability_first(tmp_path):
    """serve.journal_write: a submission the journal cannot record is
    REJECTED — a crash would silently forget it otherwise."""
    srv = serve.Server(str(tmp_path), workers=1)
    with faults.inject("serve.journal_write", "runtime", times=1):
        r = srv.submit(_spec("jf1"))
    assert r["state"] == serve.REJECTED
    assert "journal_error" in r["reason"]
    assert "unknown" in r["reason"]  # classified
    # nothing queued, nothing journaled as accepted
    assert srv.summary()["pending"] == 0
    assert serve.ACCEPTED not in _journal_kinds(str(tmp_path), "jf1")
    # the next submission (journal healthy again) is accepted
    assert srv.submit(_spec("jf2"))["state"] == serve.ACCEPTED


def test_job_run_fault_fails_job_classified(tmp_path):
    """serve.job_run: a raising fault marks the job failed with the
    failure class, a job_degraded event, and a nonzero --once-style
    verdict — never a dead worker."""
    srv = serve.Server(str(tmp_path), workers=1)
    srv.submit(_spec("jr1"))
    with faults.inject("serve.job_run", "oom", times=1):
        summary = srv.run_once()
    assert summary["counts"] == {serve.FAILED: 1}
    res = serve.read_result(str(tmp_path), "jr1")
    assert res["status"] == "failed"
    assert res["failure_class"] == "resource"
    kinds = {e["kind"] for e in res["events"]}
    assert "job_degraded" in kinds
    assert serve.FAILED in _journal_kinds(str(tmp_path), "jr1")
    # the failure stayed in the job's scope
    assert not resilience.run_report().events("job_degraded")


def test_job_deadline_blows_classified_timeout(tmp_path):
    """A per-job deadline (spec deadline_s + the PR 5 watchdog) bounds
    a wedged job: the slow fault holds the job past its budget and the
    job finishes failed/TIMEOUT, not hung."""
    srv = serve.Server(str(tmp_path), workers=1)
    srv.submit(_spec("dl1", deadline_s=0.2,
                     faults="serve.job_run:slow:delay=0.8"))
    summary = srv.run_once()
    assert summary["counts"] == {serve.FAILED: 1}
    res = serve.read_result(str(tmp_path), "dl1")
    assert res["failure_class"] == "timeout"
    assert any(e["kind"] == "deadline_blown" for e in res["events"])


# -- per-job resilience scope (unit) -----------------------------------------

def test_scope_isolates_demotions_and_attributes_events():
    resilience.demote_engine("outer", RuntimeError("Mosaic dead"))
    with resilience.scope("tenant1") as sc:
        assert not resilience.is_demoted("outer")
        assert resilience.demotions() == []
        resilience.demote_engine(
            "inner", RuntimeError("RESOURCE_EXHAUSTED: x"),
            shape_key="s1")
        assert resilience.is_demoted("inner", "s1")
        ev = resilience.run_report().add("transient_retry", label="x",
                                         attempt=1, delay_s=0, error="e")
        assert ev["job"] == "tenant1"
        assert resilience.current_job() == "tenant1"
    assert not resilience.is_demoted("inner", "s1")
    assert resilience.is_demoted("outer")
    assert resilience.current_job() is None
    # the scope object keeps its evidence after exit (serve reads it)
    assert sc.report.events("engine_demotion")


def test_scope_is_thread_local():
    """contextvars: a scope entered in one thread is invisible in
    another — the property concurrent serve workers rely on."""
    seen = {}

    def worker(name):
        with resilience.scope(name):
            resilience.note_engine_attempt(name, None)
            resilience.demote_engine(
                name, RuntimeError("RESOURCE_EXHAUSTED: x"),
                shape_key="sk")
            time.sleep(0.05)  # overlap the two scopes
            seen[name] = (resilience.last_engine_attempt()[0],
                          [d.engine for d in resilience.demotions()])

    ts = [threading.Thread(target=worker, args=(f"t{i}",))
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert seen["t0"] == ("t0", ["t0"])
    assert seen["t1"] == ("t1", ["t1"])
    assert resilience.demotions() == []
    assert resilience.last_engine_attempt() is None


def test_scope_overrides_health_budget_and_deadline():
    from splatt_tpu.cpd import health_retries

    with resilience.scope("j", health_retries=7, deadline_s=3.5):
        assert health_retries() == 7
        assert resilience.deadline_seconds() == 3.5
        assert resilience.deadline_seconds(default=240) == 3.5
    with resilience.scope("j2", deadline_s=0):
        # 0 = explicitly disabled for this job; site defaults survive
        assert resilience.deadline_seconds() is None
        assert resilience.deadline_seconds(default=240) == 240
    assert resilience.deadline_seconds() is None


def test_scoped_faults_shadow_global_per_context():
    faults.arm("shadow.site", faults.FaultSpec(kind="runtime",
                                               times=faults.ALWAYS))
    with faults.scoped("shadow.site:oom:1"):
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            faults.maybe_fail("shadow.site")
        faults.maybe_fail("shadow.site")  # overlay exhausted: silent
        # un-named sites fall through to the global registry
        with pytest.raises(RuntimeError, match="injected engine"):
            with faults.scoped("other.site:oom:1"):
                faults.maybe_fail("shadow.site")
    with pytest.raises(RuntimeError):  # global spec untouched
        faults.maybe_fail("shadow.site")


def test_scoped_faults_are_context_local():
    res = {}
    with faults.scoped("ctx.site:runtime:*"):
        def w():
            try:
                faults.maybe_fail("ctx.site")
                res["fired"] = False
            except RuntimeError:
                res["fired"] = True
        t = threading.Thread(target=w)
        t.start()
        t.join()
        with pytest.raises(RuntimeError):
            faults.maybe_fail("ctx.site")
    assert res["fired"] is False


# -- review-driven hardening -------------------------------------------------

def test_bad_faults_schedule_rejected_at_submit(tmp_path):
    """A tenant's chaos-schedule typo is rejected at the door with the
    parse error — it can never reach (let alone kill) a supervisor
    worker."""
    srv = serve.Server(str(tmp_path), workers=1)
    r = srv.submit(_spec("typo", faults="cpd.sweep:bogus_kind"))
    assert r["state"] == serve.REJECTED
    assert "bad faults schedule" in r["reason"]
    # the daemon keeps serving its other tenants
    srv.submit(_spec("fine"))
    assert srv.run_once()["counts"][serve.DONE] == 1


def test_rejected_id_may_be_resubmitted(tmp_path):
    """Load shedding invites a retry: once the queue drains, the SAME
    job id is accepted and runs — a queue_full rejection is not a
    permanent verdict."""
    srv = serve.Server(str(tmp_path), workers=1, queue_max=1)
    srv.submit(_spec("first"))
    assert srv.submit(_spec("again"))["state"] == serve.REJECTED
    srv.run_once()  # drains the queue
    retry = srv.submit(_spec("again"))
    assert retry["state"] == serve.ACCEPTED and "duplicate" not in retry
    srv.run_once()
    assert serve.read_result(str(tmp_path), "again")["status"] == \
        "converged"


def test_cooperative_deadline_preempts_worker_thread(tmp_path):
    """The watchdog timer cannot interrupt a non-main worker thread,
    so the job deadline is ALSO enforced through the fit-check stop
    poll: a runaway job releases its worker at the next check,
    TIMEOUT-classified, instead of running its full iteration count."""
    srv = serve.Server(str(tmp_path), workers=1)
    t0 = time.time()
    srv.submit(_spec("runaway", iters=5000, tol=0.0, deadline_s=0.5,
                     synthetic=dict(SYN, nnz=3000)))
    summary = srv.run_once()
    elapsed = time.time() - t0
    assert summary["counts"] == {serve.FAILED: 1}
    res = serve.read_result(str(tmp_path), "runaway")
    assert res["failure_class"] == "timeout"
    # released the worker promptly: nowhere near 5000 iterations
    assert elapsed < 60


def test_explicit_deadline_zero_opts_out_of_server_default(tmp_path):
    """A spec's deadline_s=0 is a documented opt-out: the server-wide
    default must NOT be applied over it."""
    srv = serve.Server(str(tmp_path), workers=1, job_deadline_s=0.2)
    srv.submit(_spec("optout", deadline_s=0,
                     faults="serve.job_run:slow:delay=0.5"))
    summary = srv.run_once()
    assert summary["counts"] == {serve.DONE: 1}, summary
    assert serve.read_result(str(tmp_path), "optout")["status"] == \
        "converged"


def test_idle_run_once_spawns_no_workers(tmp_path, monkeypatch):
    """The serve_forever steady state: an empty queue skips worker-
    thread construction entirely (no per-poll thread churn)."""
    srv = serve.Server(str(tmp_path), workers=4)
    spawned = []
    real = threading.Thread

    class CountingThread(real):
        def __init__(self, *a, **kw):
            spawned.append(kw.get("name"))
            super().__init__(*a, **kw)

    monkeypatch.setattr(threading, "Thread", CountingThread)
    assert srv.run_once()["pending"] == 0
    assert spawned == []


def test_read_status_reports_terminal_status_for_failed_and_rejected(
        tmp_path):
    """The filed-request status API agrees with Server.status() on
    terminal verdicts: failed and rejected jobs report their status,
    and a re-accepted id clears the stale rejection verdict."""
    root = str(tmp_path)
    srv = serve.Server(root, workers=1, queue_max=1)
    srv.submit(_spec("ok"))
    srv.submit(_spec("shed"))  # queue_full -> rejected
    with faults.inject("serve.job_run", "oom", times=1):
        srv.run_once()
    assert serve.read_status(root, "ok")["status"] == "failed"
    assert serve.read_status(root, "shed")["status"] == "rejected"
    # resubmitted after the queue drained: no longer terminal
    srv.submit(_spec("shed"))
    st = serve.read_status(root, "shed")
    assert st["state"] == serve.ACCEPTED and st["status"] is None
