"""SPL017 good: decide under the lock, do the durable IO outside it —
the reservation is in-memory state (cheap, lock-held), the fsync
happens with the lock released (serve.submit's ACCEPTING pattern)."""

import threading


def append_line(path, data):
    # stand-in for splatt_tpu.utils.durable.append_line (the
    # configured durable-write helper; its body owns the fsync)
    with open(path, "ab") as f:
        f.write(data)


class Server:
    def __init__(self, journal_path):
        self._lock = threading.Lock()
        self._jobs = {}
        self._journal_path = journal_path

    def submit_hot(self, jid, spec):
        with self._lock:
            # reserve the id so a concurrent same-id submission dedups
            # while the durable append runs lock-free below
            self._jobs[jid] = spec
        append_line(self._journal_path, b"accepted\n")
        return jid
