"""Pallas TPU kernels for the MTTKRP hot path.

The performance-critical reduction in blocked MTTKRP is

    out[b, s, :] = Σ_j  [local[b, j] == s] · prod[b, j, :]

i.e. a per-block one-hot contraction (S×B)@(B×R) — the TPU replacement
for the reference's scattered accumulation with its mutex pool /
privatization / tile scheduling (src/mttkrp.c:104-236).  XLA executes
the same einsum but materializes the one-hot operand (nb·S·B elements)
in HBM; the Pallas kernel builds it on the fly in VMEM with a
broadcasted iota-compare and feeds the MXU directly, so HBM traffic is
just prod in + partials out.

Two variants:
- :func:`onehot_reduce_sorted`  — per-block partials (sorted layouts,
  combined by a small scatter outside);
- :func:`onehot_reduce_full`    — full-width accumulation across the
  whole grid (privatized short modes, no scatter at all).

Both take `interpret=` so the differential tests run on CPU
(≙ tests running the real kernels at 7 threads, tests/mttkrp_test.c).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from splatt_tpu.ops.mttkrp import _acc_dtype, mxu_precision
from splatt_tpu.utils.env import ceil_to

# Max blocks per grid step; the actual chunk is sized against VMEM by
# vmem_chunk() below.
_CHUNK = 8


def vmem_chunk(width: int, block: int, rank: int,
               itemsize: int = 4, budget_bytes: int = 8 << 20,
               out_itemsize: int = None) -> int:
    """Blocks per grid step such that the kernel's working set —
    one-hot (C,width,block) + prod (C,block,rank) + out (C,width,rank) —
    fits the VMEM budget (half of the ~16MB scratchpad, leaving room
    for double buffering).  The out term is costed at the accumulator
    width (f32 even for bf16 inputs).  Returns 0 when even one block
    does not fit: callers must fall back to the XLA engine, which
    streams the one-hot through HBM instead.
    """
    if out_itemsize is None:
        out_itemsize = max(itemsize, 4)
    per_block = ((width * block + block * rank) * itemsize
                 + width * rank * out_itemsize)
    if per_block <= 0:
        return _CHUNK
    return min(_CHUNK, budget_bytes // per_block)


def _sorted_kernel(local_ref, prod_ref, out_ref, *, seg_width: int):
    local = local_ref[:, 0, :]                  # (C, B) int32
    prod = prod_ref[...]                        # (C, B, R)
    C, B = local.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, (C, seg_width, B), 1)
    onehot = (local[:, None, :] == iota).astype(prod.dtype)
    out_ref[...] = jax.lax.dot_general(
        onehot, prod,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=out_ref.dtype,
        precision=mxu_precision(prod.dtype))


def _full_kernel(local_ref, prod_ref, out_ref, *, width: int):
    local = local_ref[:, 0, :]                  # (C, B) int32
    prod = prod_ref[...]                        # (C, B, R)
    C, B = local.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, (C, width, B), 1)
    onehot = (local[:, None, :] == iota).astype(prod.dtype)
    part = jax.lax.dot_general(
        onehot, prod,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=out_ref.dtype,
        precision=mxu_precision(prod.dtype))    # (C, width, R)
    acc = jnp.sum(part, axis=0)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = acc

    @pl.when(pl.program_id(0) != 0)
    def _accum():
        out_ref[...] += acc


def _pad_blocks(local: jax.Array, prod: jax.Array, chunk: int):
    """Pad to whole chunks; local gains a singleton middle dim so its
    Mosaic block shape (chunk, 1, B) is legal for any chunk (the last
    two block dims must divide (8, 128) or equal the array dims)."""
    nb = local.shape[0]
    nb_pad = ceil_to(max(nb, 1), chunk)
    if nb_pad != nb:
        local = jnp.pad(local, ((0, nb_pad - nb), (0, 0)),
                        constant_values=-1)
        prod = jnp.pad(prod, ((0, nb_pad - nb), (0, 0), (0, 0)))
    return local[:, None, :], prod, nb_pad


@functools.partial(jax.jit,
                   static_argnames=("seg_width", "interpret", "chunk"))
def onehot_reduce_sorted(local: jax.Array, prod: jax.Array, seg_width: int,
                         interpret: bool = False,
                         chunk: int = _CHUNK) -> jax.Array:
    """(nb, B) local ids + (nb, B, R) partials → (nb, S, R) block partials."""
    nb = local.shape[0]
    B = local.shape[1]
    R = prod.shape[-1]
    local, prod, nb_pad = _pad_blocks(local, prod, chunk)
    grid = (nb_pad // chunk,)
    out = pl.pallas_call(
        functools.partial(_sorted_kernel, seg_width=seg_width),
        grid=grid,
        in_specs=[
            pl.BlockSpec((chunk, 1, B), lambda i: (i, 0, 0)),
            pl.BlockSpec((chunk, B, R), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((chunk, seg_width, R), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((nb_pad, seg_width, R),
                                       _acc_dtype(prod.dtype)),
        interpret=interpret,
    )(local, prod)
    return out[:nb]


# -- fused gather + Hadamard + reduce ---------------------------------------

@functools.cache
def fused_gather_supported() -> bool:
    """Whether Mosaic can lower the fused kernel's in-VMEM row gather.

    jax 0.9.0's Mosaic gather rule only lowers same-shaped
    take_along_axis forms (tpu.dynamic_gather); an arbitrary
    ``u[idx]`` row gather with len(idx) != dim raises at lowering.
    Probe by *lowering* (not running) a tiny fused kernel once per
    process — callers fall back to the unfused kernels / XLA scan.
    """
    if jax.default_backend() != "tpu":
        return False
    try:
        import numpy as np

        from splatt_tpu.blocked import build_layout
        from splatt_tpu.coo import SparseTensor

        rng = np.random.default_rng(0)
        dims = (16, 24, 32)
        inds = np.stack([rng.integers(0, d, 256) for d in dims])
        tt = SparseTensor(inds=inds.astype(np.int64),
                          vals=np.ones(256), dims=dims)
        lay = build_layout(tt, 0, block=128, val_dtype=np.float32)
        fac = [jnp.zeros((d, 8), jnp.float32) for d in dims]
        fused_mttkrp.lower(lay, fac, mode=0, width=lay.seg_width,
                           accumulate=False, interpret=False)
        return True
    except Exception:
        return False


def fused_vmem_ok(factors, mode: int, width: int, block: int,
                  budget_bytes: int = 12 << 20) -> bool:
    """Whether the fused kernel's VMEM plan fits: every *input* factor
    resident in VMEM for the whole grid, plus the per-step working set
    (gathered rows ×2, one-hot, partials).  The ~16MB/core scratchpad
    keeps ~4MB back for double-buffered block streams.
    """
    R = int(factors[0].shape[1])
    itemsize = jnp.dtype(factors[0].dtype).itemsize
    fac = sum(int(f.shape[0]) * R * itemsize
              for k, f in enumerate(factors) if k != mode)
    work = (2 * block * R * itemsize          # gathered rows + prod
            + width * block * itemsize       # one-hot
            + width * R * max(itemsize, 4)   # partials (acc width)
            + (len(factors) + 1) * block * 4)  # index + val streams
    return fac + work <= budget_bytes


def _fused_kernel(local_ref, vals_ref, ginds_ref, *refs,
                  width: int, accumulate: bool, nother: int):
    out_ref = refs[nother]
    u_refs = refs[:nother]
    local = local_ref[:, 0, :]               # (C, B) int32
    vals = vals_ref[:, 0, :]                 # (C, B)
    C, B = local.shape
    dtype = vals.dtype
    prod = vals[..., None]                   # (C, B, 1)
    for j in range(nother):
        u = u_refs[j][...]                   # (dim_j, R) resident in VMEM
        idx = ginds_ref[:, j, :].reshape(C * B)
        rows = jnp.take(u, idx, axis=0, mode="clip",
                        unique_indices=False, indices_are_sorted=False)
        prod = prod * rows.reshape(C, B, u.shape[1])
    iota = jax.lax.broadcasted_iota(jnp.int32, (C, width, B), 1)
    onehot = (local[:, None, :] == iota).astype(dtype)
    part = jax.lax.dot_general(
        onehot, prod,
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=out_ref.dtype,
        precision=mxu_precision(dtype))          # (C, width, R)
    if not accumulate:
        out_ref[...] = part
        return
    acc = jnp.sum(part, axis=0)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = acc

    @pl.when(pl.program_id(0) != 0)
    def _accum():
        out_ref[...] += acc


@functools.partial(jax.jit, static_argnames=("mode", "width", "accumulate",
                                             "interpret", "chunk"))
def fused_mttkrp(layout, factors, mode: int, width: int,
                 accumulate: bool, interpret: bool = False,
                 chunk: int = 1) -> jax.Array:
    """Fused MTTKRP kernel: gather factor rows, Hadamard, one-hot reduce
    — entirely in VMEM (≙ the reference's register-blocked fiber loops,
    src/mttkrp.c:427-463, which read each factor row once inside the
    traversal).  The (nnz, R) partial-product tensor never exists in HBM:
    traffic is inds + vals + resident factors + output partials.

    Layout contract: `layout.inds` sorted by `mode` (for the sorted
    path) with sentinel-padded tails; every input factor must pass
    :func:`fused_vmem_ok`.  Output: (nb, width, R) block partials, or
    (width, R) totals when `accumulate` (privatized short modes).
    """
    nmodes = layout.nmodes
    nb, B = layout.nblocks, layout.block
    R = int(factors[0].shape[1])
    dtype = factors[0].dtype
    others = [k for k in range(nmodes) if k != mode]

    seg = layout.inds[mode]
    if accumulate:
        local = seg.reshape(nb, B)
    else:
        local = seg.reshape(nb, B) - layout.row_start[:, None]
    vals = layout.vals.reshape(nb, B).astype(dtype)
    # (nb, nother, B): blocks (chunk, nother, B) keep the last two dims
    # equal to the array dims, legal for any chunk under Mosaic's rule.
    ginds = (layout.inds[jnp.asarray(others)]
             .reshape(len(others), nb, B).transpose(1, 0, 2))

    nb_pad = ceil_to(max(nb, 1), chunk)
    if nb_pad != nb:
        local = jnp.pad(local, ((0, nb_pad - nb), (0, 0)),
                        constant_values=-1)
        vals = jnp.pad(vals, ((0, nb_pad - nb), (0, 0)))
        ginds = jnp.pad(ginds, ((0, nb_pad - nb), (0, 0), (0, 0)))
    local = local[:, None, :]
    vals = vals[:, None, :]
    grid = (nb_pad // chunk,)

    factor_specs = [
        pl.BlockSpec((int(factors[k].shape[0]), R), lambda i: (0, 0))
        for k in others
    ]
    acc = _acc_dtype(dtype)
    if accumulate:
        out_spec = pl.BlockSpec((width, R), lambda i: (0, 0))
        out_shape = jax.ShapeDtypeStruct((width, R), acc)
    else:
        out_spec = pl.BlockSpec((chunk, width, R), lambda i: (i, 0, 0))
        out_shape = jax.ShapeDtypeStruct((nb_pad, width, R), acc)

    out = pl.pallas_call(
        functools.partial(_fused_kernel, width=width, accumulate=accumulate,
                          nother=len(others)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((chunk, 1, B), lambda i: (i, 0, 0)),
            pl.BlockSpec((chunk, 1, B), lambda i: (i, 0, 0)),
            pl.BlockSpec((chunk, len(others), B), lambda i: (i, 0, 0)),
            *factor_specs,
        ],
        out_specs=out_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(local, vals, ginds, *[factors[k] for k in others])
    if accumulate:
        return out
    return out[:nb]


@functools.partial(jax.jit,
                   static_argnames=("width", "interpret", "chunk"))
def onehot_reduce_full(local: jax.Array, prod: jax.Array, width: int,
                       interpret: bool = False,
                       chunk: int = _CHUNK) -> jax.Array:
    """(nb, B) ids + (nb, B, R) partials → (width, R) total (privatized)."""
    B = local.shape[1]
    R = prod.shape[-1]
    local, prod, nb_pad = _pad_blocks(local, prod, chunk)
    grid = (nb_pad // chunk,)
    out = pl.pallas_call(
        functools.partial(_full_kernel, width=width),
        grid=grid,
        in_specs=[
            pl.BlockSpec((chunk, 1, B), lambda i: (i, 0, 0)),
            pl.BlockSpec((chunk, B, R), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((width, R), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((width, R), _acc_dtype(prod.dtype)),
        interpret=interpret,
    )(local, prod)
    return out
