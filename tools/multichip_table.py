"""Multi-chip scaling-efficiency table from the virtual CPU mesh
(VERDICT r4 missing #5 / weak #7): grid (MEDIUM) vs fine (FINE)
decompositions — and the fine comm strategies (all2all, ppermute ring,
async remote-copy ring) — at 1/2/4/8 devices, with the MEASURED
per-phase attribution of the profiled distributed sweeps
(≙ mpi_time_stats' per-phase avg/max table, src/mpi/mpi_cpd.c:893-939,
run with mpirun -np {1,2,4,8}) and, for the ring drivers, the
ACHIEVED-overlap metric (docs/ring.md): standalone exchange time vs
the fraction hidden under compute, next to the wire model's per-device
bytes.  On the CPU virtual mesh the ppermute fallback exposes every
hop, so overlap_frac near 0 is the honest reading there — the metric
becomes a gated number on a real TPU window.

One subprocess per (driver, device count) — the virtual device count is
fixed at interpreter start.  Writes tools/multichip_eff.json and a
markdown table to stdout.

Usage: python tools/multichip_table.py [nnz] [rank]
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = '''
import contextlib, io, json, re, sys, time
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from splatt_tpu import resilience
from splatt_tpu.config import CommPattern, Options, Verbosity
from splatt_tpu.parallel.grid import grid_cpd_als
from splatt_tpu.parallel.sharded import sharded_cpd_als
from splatt_tpu.parallel.common import DIST_TIMER_NAMES, comm_volume_model
from splatt_tpu.utils.env import ceil_to
from splatt_tpu.utils.timers import timers
sys.path.insert(0, {repo!r})
from bench import synthetic_tensor

tt = synthetic_tensor((3000, 2400, 4200), {nnz}, seed=0)
iters = 6
ndev = len(jax.devices())
comm = {{"fine-ring": CommPattern.POINT2POINT,
        "fine-async": CommPattern.ASYNC_RING}}.get({driver!r})
opts = Options(random_seed=7, verbosity=Verbosity.HIGH,
               val_dtype=np.float32, max_iterations=iters,
               tolerance=0.0, fit_check_every=1, comm_pattern=comm)
buf = io.StringIO()
t0 = time.perf_counter()
with contextlib.redirect_stdout(buf):
    if {driver!r} == "grid":
        res = grid_cpd_als(tt, {rank}, opts=opts)
    else:
        res = sharded_cpd_als(tt, {rank}, opts=opts)
wall = time.perf_counter() - t0
times = [float(s) for s in
         re.findall(r"its =\\s*\\d+ \\(([0-9.]+)s\\)", buf.getvalue())]
steady = sorted(times[2:]) or sorted(times)
phases = dict()
for name in DIST_TIMER_NAMES:
    t = timers.get(name)
    if t.seconds > 0:
        # profiled sweeps reset after iteration 1: totals cover the
        # warm iterations only
        phases[name] = round(t.seconds / max(1, iters - 1), 5)
rec = dict(
    sec_per_iter=steady[len(steady) // 2] if steady else None,
    phases=phases, fit=float(res.fit), wall=round(wall, 1))
imb = [{{k: v for k, v in e.items() if k != "ts"}}
       for e in resilience.run_report().events("layout_imbalance")]
if imb:
    # achieved shard/cell balance (docs/layout-balance.md): max/mean
    # nnz per worker next to the measured seconds
    rec["imbalance"] = imb
if comm is not None:
    # the achieved-overlap metric the driver measured (docs/ring.md)
    # + the wire model of the SELECTED strategy — MULTICHIP artifacts
    # must carry the per-device bytes next to the measured seconds
    ov = next(iter(resilience.run_report().events("ring_overlap")), None)
    if ov is not None:
        rec["overlap"] = {{k: v for k, v in ov.items() if k != "ts"}}
    dims_pad = tuple(ceil_to(d, ndev) for d in tt.dims)
    rec["comm_model"] = comm_volume_model(
        dims_pad, {rank}, 4, ndev=ndev, variant=comm.value.replace(
            "point2point", "ring"))
    rec["comm_fallbacks"] = [
        {{k: v for k, v in e.items() if k != "ts"}}
        for e in resilience.run_report().events("comm_fallback")]
print("RESULT " + json.dumps(rec))
'''


def run_case(driver: str, n: int, nnz: int, rank: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={n}"])
    code = CHILD.format(repo=REPO, nnz=nnz, rank=rank, driver=driver)
    try:
        p = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=1800)
        line = [l for l in p.stdout.splitlines() if l.startswith("RESULT ")]
        if not line:
            return dict(error=(p.stderr or p.stdout)[-300:])
        return json.loads(line[-1][7:])
    except subprocess.SubprocessError as e:
        return dict(error=str(e)[:300])


def main():
    nnz = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    rank = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    devices = [1, 2, 4, 8]
    out = dict(nnz=nnz, rank=rank, devices=devices, drivers={})
    for driver in ("grid", "fine", "fine-ring", "fine-async"):
        rows = []
        for n in devices:
            r = run_case(driver, n, nnz, rank)
            r["n_devices"] = n
            rows.append(r)
            print(f"# {driver} n={n}: {json.dumps(r)}", file=sys.stderr,
                  flush=True)
        base = next((r["sec_per_iter"] for r in rows
                     if r.get("sec_per_iter")), None)
        n0 = next((r["n_devices"] for r in rows
                   if r.get("sec_per_iter")), None)
        for r in rows:
            s = r.get("sec_per_iter")
            r["efficiency"] = (round(base * n0 / (r["n_devices"] * s), 3)
                               if base and s else None)
        out["drivers"][driver] = rows
    with open(os.path.join(REPO, "tools", "multichip_eff.json"), "w") as f:
        json.dump(out, f, indent=1)

    # markdown table
    print(f"\n## Virtual-mesh scaling (synthetic 3-mode, {nnz} nnz, "
          f"rank {rank}, f32, CPU host devices)\n")
    print("| driver | devices | sec/iter | efficiency | mttkrp | comm | "
          "solve+update | fit | overlap |")
    print("|---|---|---|---|---|---|---|---|---|")
    for driver, rows in out["drivers"].items():
        for r in rows:
            ph = r.get("phases", {})
            ov = r.get("overlap") or {}
            ovs = (f"{100 * ov['overlap_frac']:.0f}% of "
                   f"{ov['exchange_s']}s" if ov else "—")
            print(f"| {driver} | {r['n_devices']} | "
                  f"{r.get('sec_per_iter', '—')} | "
                  f"{r.get('efficiency', '—')} | "
                  f"{ph.get('dist_mttkrp', '—')} | "
                  f"{ph.get('dist_comm', '—')} | "
                  f"{ph.get('dist_update', '—')} | "
                  f"{ph.get('dist_fit', '—')} | {ovs} |")


if __name__ == "__main__":
    main()
