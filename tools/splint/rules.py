"""The splint rule catalog — each rule encodes a project invariant.

Every rule here is grounded in a real hazard this codebase has already
paid for (see docs/static-analysis.md for the war stories): these are
code-shape properties — what the code *would* do when infrastructure
misbehaves — which is exactly what behavioral tests cannot catch.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.splint.core import (FileCtx, Finding, FunctionCFG, JitSpec,
                               walk_nodes,
                               Project, _body_stmts, _expr_loads,
                               callable_jit_spec, free_reads,
                               jit_boundary, jit_call_spec, nested_defs,
                               returns_jit_spec, scope_functions)

#: handler-body names accepted as "routing the failure through the
#: taxonomy" — the resilience module's public verbs.  Projects add
#: their own wrappers via [tool.splint] resilience-routers.
RESILIENCE_ROUTERS = {
    "classify_failure", "demote_engine", "retry_transient",
    "run_report", "failure_message",
}

_DTYPE_LITERALS = {"float32", "float64", "bfloat16", "float16"}
_DTYPE_MODULES = {"numpy", "jax.numpy"}
_SYNC_JAX = {"jax.block_until_ready", "jax.device_get"}
_NP_HOST = {"numpy.asarray", "numpy.array"}
_FAULT_FNS = {"maybe_fail", "consume", "active", "inject", "poison"}
_ENV_READ_FNS = {"read_env", "read_env_int", "read_env_float"}


class Rule:
    id = "SPL?"
    title = ""
    hint = ""

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        return []

    def finalize(self, project: Project) -> List[Finding]:
        return []

    def finding(self, ctx_or_path, line: int, message: str) -> Finding:
        path = (ctx_or_path.relpath if isinstance(ctx_or_path, FileCtx)
                else ctx_or_path)
        return Finding(self.id, path, line, message, hint=self.hint)


# -- SPL001 -----------------------------------------------------------------

class RawEnvironAccess(Rule):
    """Raw ``os.environ`` access outside the sanctioned env module.

    Every env read outside ``utils/env.py`` bypasses the ENV_VARS
    registry (so the variable escapes documentation and SPL007), and —
    because env.py feeds the probe cache's ``_kernel_src_hash`` — can
    change dispatch-relevant behavior without invalidating cached
    capability verdicts."""

    id = "SPL001"
    title = "raw os.environ access outside utils/env.py"
    hint = ("read through splatt_tpu.utils.env.read_env/read_env_int/"
            "read_env_float and declare the variable in ENV_VARS")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        if ctx.relpath == project.config.env_module:
            return []
        out = []
        for node in walk_nodes(ctx.tree):
            dotted = None
            if isinstance(node, ast.Attribute):
                dotted = ctx.resolve(node)
            elif isinstance(node, ast.Name):
                dotted = ctx.aliases.get(node.id)
            if dotted in ("os.environ", "os.getenv", "os.putenv"):
                out.append(self.finding(
                    ctx, node.lineno,
                    f"raw {dotted} access bypasses the ENV_VARS "
                    f"registry in {project.config.env_module}"))
        return _dedupe(out)


# -- SPL002 -----------------------------------------------------------------

class BroadExceptSwallows(Rule):
    """``except Exception`` that neither re-raises nor routes the error
    through the failure taxonomy.  The PR 1 bug class: one broad except
    swallowed a transient HTTP 500 and persisted it as a permanent
    engine demotion."""

    id = "SPL002"
    title = "except Exception swallows the failure class"
    hint = ("classify via resilience.classify_failure (or demote_engine/"
            "retry_transient/run_report), re-raise, or add a justified "
            "'# splint: ignore[SPL002] <reason>'")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        routers = RESILIENCE_ROUTERS | set(
            project.config.resilience_routers)
        out = []
        for node in walk_nodes(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            names: Set[str] = set()
            reraises = False
            for sub in node.body:
                for n in ast.walk(sub):
                    if isinstance(n, ast.Raise):
                        reraises = True
                    elif isinstance(n, ast.Name):
                        names.add(n.id)
                    elif isinstance(n, ast.Attribute):
                        names.add(n.attr)
            if reraises or names & routers:
                continue
            out.append(self.finding(
                ctx, node.lineno,
                "broad except swallows the error without classifying "
                "it — a transient infra failure and a real bug become "
                "indistinguishable here"))
        return out

    @staticmethod
    def _is_broad(type_node) -> bool:
        if type_node is None:
            return True  # bare except
        nodes = (type_node.elts if isinstance(type_node, ast.Tuple)
                 else [type_node])
        return any(isinstance(n, ast.Name)
                   and n.id in ("Exception", "BaseException")
                   for n in nodes)


# -- jit helpers (SPL003 / SPL004) ------------------------------------------

def _jit_static_names(ctx: FileCtx,
                      fn: ast.FunctionDef) -> Optional[Set[str]]:
    """The static argnames of a jit-decorated function, or None when
    the function is not jitted.  Handles ``@jax.jit``,
    ``@jax.jit(...)`` and ``@partial(jax.jit, ...)``."""
    for dec in fn.decorator_list:
        call = dec if isinstance(dec, ast.Call) else None
        target = call.func if call else dec
        dotted = ctx.resolve(target) or ""
        kwargs = {k.arg: k.value for k in call.keywords} if call else {}
        if dotted.split(".")[-1] == "partial" and call and call.args:
            inner = ctx.resolve(call.args[0]) or ""
            if inner in ("jax.jit", "jit"):
                return _static_names_from(kwargs, fn)
            continue
        if dotted in ("jax.jit", "jit"):
            return _static_names_from(kwargs, fn)
    return None


def _static_names_from(kwargs: Dict[str, ast.AST],
                       fn: ast.FunctionDef) -> Set[str]:
    static: Set[str] = set()
    names = kwargs.get("static_argnames")
    if names is not None:
        for n in ([names] if isinstance(names, ast.Constant)
                  else getattr(names, "elts", [])):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                static.add(n.value)
    nums = kwargs.get("static_argnums")
    if nums is not None:
        all_args = [a.arg for a in
                    fn.args.posonlyargs + fn.args.args]
        for n in ([nums] if isinstance(nums, ast.Constant)
                  else getattr(nums, "elts", [])):
            if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                    and 0 <= n.value < len(all_args):
                static.add(all_args[n.value])
    return static


def _fn_params(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]


# -- SPL003 -----------------------------------------------------------------

class HostSyncInJit(Rule):
    """Host-device synchronization inside a jitted function (where it
    either fails at trace time or silently forces a device round-trip
    per call) or a configured hot-path function."""

    id = "SPL003"
    title = "host sync inside a jitted function / hot path"
    hint = ("keep block_until_ready/np.asarray/.item()/device_get out "
            "of traced code; batch host fetches at the sweep boundary "
            "(cpd.py's fit_check_every pattern)")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        hot = set(project.config.hot_functions)
        out = []
        for fn in walk_nodes(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            jitted = _jit_static_names(ctx, fn) is not None
            if not jitted and f"{ctx.relpath}::{fn.name}" not in hot:
                continue
            where = ("jitted function" if jitted
                     else "configured hot path")
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                dotted = ctx.resolve(node.func) or ""
                label = None
                if dotted in _SYNC_JAX or \
                        dotted.split(".")[-1] == "block_until_ready":
                    label = dotted.split(".")[-1]
                elif dotted in _NP_HOST:
                    label = dotted
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"
                        and not node.args and not node.keywords):
                    label = ".item()"
                if label:
                    out.append(self.finding(
                        ctx, node.lineno,
                        f"host sync {label} inside {where} "
                        f"'{fn.name}'"))
        return out


# -- SPL004 -----------------------------------------------------------------

class RecompilationHazard(Rule):
    """A jitted function branching in Python on a non-static argument:
    jax either fails at trace time (tracer in bool context) or — when
    the value is concrete, e.g. a shape-dependent int — specializes
    the compilation to it, recompiling per distinct value."""

    id = "SPL004"
    title = "Python branch on a non-static jit argument"
    hint = ("mark the argument static_argnames (accepting per-value "
            "retraces deliberately) or branch on-device with "
            "jnp.where/lax.cond/lax.while_loop")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        out = []
        for fn in walk_nodes(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            static = _jit_static_names(ctx, fn)
            if static is None:
                continue
            nonstatic = set(_fn_params(fn)) - static - {"self"}
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                for name in self._branching_names(node.test, nonstatic):
                    kind = "while" if isinstance(node, ast.While) else "if"
                    out.append(self.finding(
                        ctx, node.lineno,
                        f"Python {kind} on non-static jit argument "
                        f"'{name}' of '{fn.name}' — recompiles per "
                        f"value (or fails on a traced value)"))
        return out

    @staticmethod
    def _branching_names(test: ast.AST, nonstatic: Set[str]) -> List[str]:
        parents = {child: parent for parent in ast.walk(test)
                   for child in ast.iter_child_nodes(parent)}
        hits = []
        for node in ast.walk(test):
            if not (isinstance(node, ast.Name) and node.id in nonstatic):
                continue
            parent = parents.get(node)
            # attribute access (x.mode) is usually static metadata, and
            # call arguments (len(x), isinstance(x, ...)) resolve to
            # static values at trace time — only a direct value use of
            # the argument is a per-value specialization
            if isinstance(parent, ast.Attribute) and parent.value is node:
                continue
            if isinstance(parent, ast.Call) and node is not parent.func:
                continue
            if isinstance(parent, ast.Compare) and \
                    all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in parent.ops):
                continue  # `x is None`: pytree structure, static
            hits.append(node.id)
        return hits


# -- SPL005 -----------------------------------------------------------------

class DtypeLiteral(Rule):
    """A dtype literal outside the config module: per-site dtype
    choices drift from the central Options.val_dtype / resolve_dtype
    policy (the bf16 and f64 paths both exist because dtype is a
    *policy*, not a per-callsite constant)."""

    id = "SPL005"
    title = "dtype literal outside config.py"
    hint = ("resolve dtypes through splatt_tpu.config.resolve_dtype / "
            "Options.val_dtype (or derive from an input's .dtype)")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        if ctx.relpath == project.config.config_module:
            return []
        out = []
        for node in walk_nodes(ctx.tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr in _DTYPE_LITERALS
                    and (ctx.resolve(node.value) or "") in _DTYPE_MODULES):
                out.append(self.finding(
                    ctx, node.lineno,
                    f"dtype literal .{node.attr} outside "
                    f"{project.config.config_module}"))
        return out


# -- SPL006 -----------------------------------------------------------------

def _call_sites(ctx: FileCtx) -> List[Tuple[Optional[str], int]]:
    """(site, lineno) for every fault-hook call in `ctx`; site is the
    literal string, 'prefix.*' for an f-string with a literal prefix,
    or None when not statically resolvable."""
    out = []
    for node in walk_nodes(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = ctx.resolve(node.func) or ""
        if dotted.split(".")[-1] not in _FAULT_FNS or \
                "faults" not in dotted:
            continue
        arg = node.args[0] if node.args else None
        site: Optional[str] = None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            site = arg.value
        elif isinstance(arg, ast.Name):
            site = ctx.str_consts.get(arg.id)
        elif isinstance(arg, ast.JoinedStr) and arg.values:
            first = arg.values[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str) and first.value:
                site = first.value + "*"
        out.append((site, node.lineno))
    return out


def _declared_sites(ctx: FileCtx) -> Dict[str, int]:
    for node in walk_nodes(ctx.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "SITES"
                and isinstance(node.value, ast.Dict)):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return {}


def _site_matches(declared: str, used: str) -> bool:
    if declared.endswith(".*"):
        return used == declared or used.startswith(declared[:-1])
    return used == declared


class FaultSiteDrift(Rule):
    """Fault-site drift: every site string the production code passes
    to the fault hooks must be declared in the faults module's SITES
    registry and exercised by at least one test — and every declared
    site must still exist in production.  A renamed hook otherwise
    silently orphans the resilience path it was built to exercise."""

    id = "SPL006"
    title = "fault-site drift against utils/faults.py:SITES"
    hint = ("declare the site (with a doc) in faults.SITES and "
            "exercise it from a test via faults.inject")

    def finalize(self, project: Project) -> List[Finding]:
        cfg = project.config
        faults_ctx = project.ctx_for(cfg.faults_module)
        if faults_ctx is None:
            return []
        declared = _declared_sites(faults_ctx)
        out = []
        prod_sites: List[Tuple[str, FileCtx, int]] = []
        for ctx in project.files:
            if ctx.relpath == cfg.faults_module:
                continue
            for site, line in _call_sites(ctx):
                if site is None:
                    out.append(self.finding(
                        ctx, line,
                        "fault site is not statically resolvable — "
                        "splint cannot check it against SITES"))
                else:
                    prod_sites.append((site, ctx, line))
        test_sites = {site for tctx in project.test_ctxs()
                      for site, _ in _call_sites(tctx) if site}
        for site, ctx, line in prod_sites:
            if not any(_site_matches(d, site) for d in declared):
                out.append(self.finding(
                    ctx, line,
                    f"fault site '{site}' is not declared in "
                    f"{cfg.faults_module}:SITES"))
        used = {s for s, _, _ in prod_sites}
        for d, line in declared.items():
            if not any(_site_matches(d, u) for u in used):
                out.append(self.finding(
                    faults_ctx, line,
                    f"declared fault site '{d}' has no production "
                    f"call — dead declaration or renamed hook"))
            elif not any(_site_matches(d, t) for t in test_sites):
                out.append(self.finding(
                    faults_ctx, line,
                    f"declared fault site '{d}' is not exercised by "
                    f"any test under {cfg.tests_path}/"))
        return out


# -- SPL007 -----------------------------------------------------------------

def _declared_env_vars(ctx: FileCtx) -> Dict[str, int]:
    for node in walk_nodes(ctx.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "ENV_VARS"
                and isinstance(node.value, ast.Dict)):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return {}


class UndocumentedEnvVar(Rule):
    """Every SPLATT_* environment variable the code reads must be
    declared (with a doc string) in the env module's ENV_VARS registry
    — the single source the docs render from."""

    id = "SPL007"
    title = "undocumented SPLATT_* environment variable"
    hint = ("declare the variable in splatt_tpu/utils/env.py:ENV_VARS "
            "(name -> default -> doc); docs render from that registry")

    def finalize(self, project: Project) -> List[Finding]:
        env_ctx = project.ctx_for(project.config.env_module)
        declared = _declared_env_vars(env_ctx) if env_ctx else {}
        out = []
        for ctx in project.files:
            for name, line in self._env_reads(ctx):
                if name.startswith("SPLATT_") and name not in declared:
                    out.append(self.finding(
                        ctx, line,
                        f"env var {name} is read but not declared in "
                        f"{project.config.env_module}:ENV_VARS"))
        return out

    @staticmethod
    def _env_reads(ctx: FileCtx) -> List[Tuple[str, int]]:
        out = []

        def literal(arg) -> Optional[str]:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value
            if isinstance(arg, ast.Name):
                return ctx.str_consts.get(arg.id)
            return None

        for node in walk_nodes(ctx.tree):
            if isinstance(node, ast.Call):
                dotted = ctx.resolve(node.func) or ""
                if (dotted in ("os.environ.get", "os.getenv")
                        or dotted.split(".")[-1] in _ENV_READ_FNS):
                    name = literal(node.args[0]) if node.args else None
                    if name:
                        out.append((name, node.lineno))
            elif isinstance(node, ast.Subscript) and \
                    (ctx.resolve(node.value) or "") == "os.environ":
                name = literal(node.slice)
                if name:
                    out.append((name, node.lineno))
        return out


# -- SPL008 -----------------------------------------------------------------

def _all_functions(tree) -> List[ast.FunctionDef]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _is_deleted_probe(test: ast.AST) -> bool:
    """Whether a branch test probes buffer deletion — the sanctioned
    re-materialization guard (``if any(a.is_deleted() for a in ...)``
    or the ``getattr(a, "is_deleted", ...)`` spelling)."""
    for n in ast.walk(test):
        if isinstance(n, ast.Attribute) and n.attr == "is_deleted":
            return True
        if isinstance(n, ast.Constant) and n.value == "is_deleted":
            return True
    return False


class UseAfterDonate(Rule):
    """A value handed to a jitted call at a donated argnum is read
    again without re-materialization.  ``donate_argnums`` aliases the
    output buffers onto the inputs (what makes the ALS sweep update in
    place), so the caller's array is GONE after the call — jax only
    reports the re-read at runtime, as a RuntimeError naming a deleted
    buffer.  The analysis is flow-sensitive (may-donate union over
    conditional wrappers, exception edges into handlers) and follows
    jit factories across function boundaries via the jit-boundary map.
    Re-binding the name clears the state; so does the sanctioned
    rescue idiom — a branch probing ``is_deleted`` whose body
    re-materializes the name (cpd_als's engine-rescue path).  Known
    imprecision: aliases (``a = factors``) and containers are not
    tracked; nested-function bodies are opaque, but calling a local
    closure counts as reading every name it closes over."""

    id = "SPL008"
    title = "donated buffer read after the jitted call"
    hint = ("re-materialize before the read (re-bind the name, or "
            "guard with the is_deleted + host-snapshot rescue idiom "
            "in cpd.py), or drop the argnum from donate_argnums")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        jb = jit_boundary(ctx)
        out: List[Finding] = []

        def analyze(fn, env: Dict[str, JitSpec],
                    factories: Dict[str, JitSpec]) -> None:
            env = dict(env)
            factories = dict(factories)
            subs = nested_defs(fn)
            # nested factories (build_sweep) against the inherited maps
            for _ in range(4):
                changed = False
                for sub in subs:
                    spec = returns_jit_spec(ctx, sub, env, factories)
                    if spec is not None and spec != factories.get(sub.name):
                        factories[sub.name] = spec
                        changed = True
                if not changed:
                    break
            # flow-insensitive local bindings: sweep = build_sweep()
            for s in _body_stmts(fn):
                if (isinstance(s, ast.Assign) and len(s.targets) == 1
                        and isinstance(s.targets[0], ast.Name)):
                    spec = callable_jit_spec(ctx, s.value, env, factories)
                    if spec is not None:
                        env[s.targets[0].id] = spec
            donating = (any(s.donates for s in env.values())
                        or any(s.donates for s in factories.values()))
            if not donating:
                # a donating wrapper invoked without ever being bound:
                # jax.jit(f, donate_argnums=...)(x), make_step(r)(x, g)
                donating = any(
                    (spec := jit_call_spec(ctx, n)) is not None
                    and spec.donates
                    for n in ast.walk(fn) if isinstance(n, ast.Call))
            if donating:
                out.extend(self._dataflow(ctx, fn, env, factories))
            for sub in subs:
                analyze(sub, env, factories)

        module_env = dict(jb.wrapped)
        for fn in scope_functions(ctx.tree):
            analyze(fn, module_env, dict(jb.factories))
        return _dedupe(out)

    def _dataflow(self, ctx, fn, env, factories) -> List[Finding]:
        cfg = FunctionCFG(fn)
        closures = {sub.name: free_reads(sub) for sub in nested_defs(fn)}
        findings: Dict[Tuple[str, int], Finding] = {}

        def node_effects(node):
            """(exempt_uses, extra_uses, sanitized, donations) of one
            CFG node; donations = [(name, call line)]."""
            stmt = node.stmt
            exprs: List[ast.AST] = []
            if node.kind == "test":
                exprs = [stmt.test]
            elif node.kind == "for":
                exprs = [stmt.iter]
            elif node.kind == "with":
                exprs = [i.context_expr for i in stmt.items]
            elif node.kind == "except":
                exprs = [stmt.type] if stmt.type is not None else []
            elif node.kind == "stmt" and not isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
                exprs = [stmt]
            exempt = (node.kind == "test"
                      and _is_deleted_probe(stmt.test))
            sanitized: Set[str] = set()
            if exempt and isinstance(stmt, ast.If):
                # the guard's body re-materializes these names; the
                # false branch has PROVEN the buffers are not deleted,
                # so both out-edges are clean
                for sub in stmt.body:
                    for n in ast.walk(sub):
                        if isinstance(n, ast.Name) and \
                                isinstance(n.ctx, ast.Store):
                            sanitized.add(n.id)
            extra_uses: List[Tuple[str, int]] = []
            donations: List[Tuple[str, int]] = []
            for root in exprs:
                for call in ast.walk(root):
                    if not isinstance(call, ast.Call):
                        continue
                    if isinstance(call.func, ast.Name) and \
                            call.func.id in closures:
                        extra_uses += [(n, call.lineno)
                                       for n in closures[call.func.id]]
                    spec = callable_jit_spec(ctx, call.func, env,
                                             factories)
                    if spec is None or not spec.donates:
                        continue
                    for i in sorted(spec.donate_argnums):
                        if i < len(call.args) and \
                                isinstance(call.args[i], ast.Name):
                            donations.append(
                                (call.args[i].id, call.lineno))
                    for kw in call.keywords:
                        if kw.arg in spec.donate_argnames and \
                                isinstance(kw.value, ast.Name):
                            donations.append((kw.value.id, call.lineno))
            return exempt, extra_uses, sanitized, donations

        effects = {n.idx: node_effects(n) for n in cfg.nodes}
        preds = cfg.preds()
        # state: name -> line of the donating call; merge = union
        ins: List[Dict[str, int]] = [{} for _ in cfg.nodes]
        outs: List[Dict[str, int]] = [{} for _ in cfg.nodes]
        excs: List[Dict[str, int]] = [{} for _ in cfg.nodes]
        work = [n.idx for n in cfg.nodes]
        while work:
            i = work.pop()
            node = cfg.nodes[i]
            exempt, extra_uses, sanitized, donations = effects[i]
            merged: Dict[str, int] = {}
            for p, via_exc in preds[i]:
                src = excs[p] if via_exc else outs[p]
                for name, line in src.items():
                    merged[name] = min(merged.get(name, line), line)
            state = {k: v for k, v in merged.items()
                     if k not in sanitized}
            if not exempt:
                for name, line in list(node.uses) + extra_uses:
                    if name in state:
                        key = (name, line)
                        if key not in findings:
                            findings[key] = self.finding(
                                ctx, line,
                                f"'{name}' was donated to the jitted "
                                f"call at line {state[name]} "
                                f"(donate_argnums) and is read here "
                                f"without re-materialization")
            after_donate = dict(state)
            for name, line in donations:
                after_donate[name] = line
            new_out = {k: v for k, v in after_donate.items()
                       if k not in node.defs}
            if merged != ins[i] or new_out != outs[i] \
                    or after_donate != excs[i]:
                ins[i], outs[i], excs[i] = merged, new_out, after_donate
                for s in node.succs + node.exc_succs:
                    if s not in work:
                        work.append(s)
        return list(findings.values())


# -- SPL009 -----------------------------------------------------------------

_MUTATORS = {"append", "extend", "add", "insert", "update", "setdefault",
             "appendleft"}


class TracerLeak(Rule):
    """A value derived from a traced argument escapes the trace into
    long-lived state: assigned to ``self.``/a global/nonlocal, or
    pushed into a closed-over container.  The stored object is a
    tracer (or, post-trace, a stale constant from one compilation) —
    it outlives the trace that created it, and jax reports the misuse
    only when the leaked tracer is touched later, far from the leak."""

    id = "SPL009"
    title = "traced value escapes the trace into outer state"
    hint = ("return the value from the jitted function instead of "
            "stashing it on self/globals/closures; host-side logging "
            "belongs outside the traced region")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        out: List[Finding] = []
        seen: Set[int] = set()
        for fn, spec in jit_boundary(ctx).traced:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            out.extend(self._check_traced(ctx, fn, spec))
        return _dedupe(out)

    def _check_traced(self, ctx, fn, spec: JitSpec) -> List[Finding]:
        params = _fn_params(fn)
        static = set(spec.static_argnames) | {
            params[i] for i in spec.static_argnums if i < len(params)}
        tainted: Set[str] = set(params) - static - {"self"}
        if not tainted:
            return []
        body = _body_stmts(fn)
        local: Set[str] = set(params)
        declared_outer: Set[str] = set()
        for s in body:
            for n in ast.walk(s):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    local.add(n.id)
            if isinstance(s, (ast.Global, ast.Nonlocal)):
                declared_outer.update(s.names)
        local -= declared_outer

        def value_tainted(expr) -> bool:
            return any(name in tainted for name, _ in _expr_loads(expr))

        # taint propagation to a fixpoint (assignments only: the leak
        # verbs below are the sinks, not propagators)
        changed = True
        while changed:
            changed = False
            for s in body:
                targets = []
                if isinstance(s, ast.Assign):
                    targets, value = s.targets, s.value
                elif isinstance(s, (ast.AnnAssign, ast.AugAssign)):
                    targets, value = [s.target], s.value
                elif isinstance(s, (ast.For, ast.AsyncFor)):
                    targets, value = [s.target], s.iter
                else:
                    continue
                if value is None or not value_tainted(value):
                    continue
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and \
                                isinstance(n.ctx, ast.Store) and \
                                n.id not in tainted:
                            tainted.add(n.id)
                            changed = True

        out: List[Finding] = []
        for s in body:
            if isinstance(s, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = s.targets if isinstance(s, ast.Assign) \
                    else [s.target]
                value = getattr(s, "value", None)
                if value is None or not value_tainted(value):
                    # a nonlocal/global REBIND leaks even untainted?
                    # no: only traced-derived values are the hazard
                    continue
                for t in targets:
                    base = t.value if isinstance(
                        t, (ast.Attribute, ast.Subscript)) else None
                    if isinstance(base, ast.Name) and (
                            base.id == "self" or base.id not in local):
                        kind = ("self" if base.id == "self"
                                else f"outer object '{base.id}'")
                        out.append(self.finding(
                            ctx, s.lineno,
                            f"traced value stored on {kind} inside "
                            f"jitted '{fn.name}' — the tracer outlives "
                            f"its trace"))
                    elif isinstance(t, ast.Name) and \
                            t.id in declared_outer:
                        out.append(self.finding(
                            ctx, s.lineno,
                            f"traced value assigned to "
                            f"global/nonlocal '{t.id}' inside jitted "
                            f"'{fn.name}' — the tracer outlives its "
                            f"trace"))
            elif isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
                call = s.value
                f = call.func
                if not (isinstance(f, ast.Attribute)
                        and f.attr in _MUTATORS
                        and isinstance(f.value, ast.Name)):
                    continue
                holder = f.value.id
                if holder in local and holder != "self":
                    continue
                if any(value_tainted(a) for a in call.args) or any(
                        value_tainted(k.value) for k in call.keywords):
                    out.append(self.finding(
                        ctx, s.lineno,
                        f"traced value .{f.attr}()-ed into closed-over "
                        f"container '{holder}' inside jitted "
                        f"'{fn.name}' — the tracer outlives its trace"))
        return out


# -- SPL010 -----------------------------------------------------------------

_ARRAY_MAKERS = {
    "jax.numpy.asarray", "jax.numpy.array", "jax.numpy.zeros",
    "jax.numpy.ones", "jax.numpy.full", "jax.numpy.arange",
    "jax.numpy.empty", "jax.numpy.linspace", "jax.device_put",
    "numpy.asarray", "numpy.array", "numpy.zeros", "numpy.ones",
}

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
               ast.DictComp, ast.GeneratorExp)


class RecompileTrigger(Rule):
    """Constructs that silently rebuild or re-specialize a compiled
    program: a ``jax.jit`` wrapper created inside a loop (every
    iteration compiles from scratch — each a ~35 s remote compile on
    the relay), a jitted closure capturing a device array from an
    enclosing function (baked into the executable as a constant:
    silent staleness when the array changes, a retrace when the
    closure is rebuilt), and an unhashable literal (list/dict/set)
    passed at a static argnum — a guaranteed ``TypeError`` at call
    time."""

    id = "SPL010"
    title = "recompile/retrace trigger (jit-in-loop, captured array, "\
            "unhashable static)"
    hint = ("hoist the jit wrapper out of the loop (rebuild only on "
            "demotion — the build_sweep factory pattern); pass device "
            "arrays as arguments, not closure captures; static args "
            "must be hashable (tuples, not lists)")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        out: List[Finding] = []
        out += self._jit_in_loop(ctx)
        out += self._captured_arrays(ctx)
        out += self._unhashable_statics(ctx)
        return _dedupe(out)

    # - (a) jit constructed inside a loop -

    def _jit_in_loop(self, ctx) -> List[Finding]:
        out = []

        def walk(node, depth):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                for child in ast.iter_child_nodes(node):
                    walk(child, 0)  # new scope: built when called
                return
            if isinstance(node, (ast.For, ast.AsyncFor)):
                # target/iter evaluate once per loop ENTRY; only the
                # body (and a while test) re-run per iteration
                walk(node.target, depth)
                walk(node.iter, depth)
                for s in node.body:
                    walk(s, depth + 1)
                for s in node.orelse:
                    walk(s, depth)
                return
            if isinstance(node, ast.While):
                walk(node.test, depth + 1)
                for s in node.body:
                    walk(s, depth + 1)
                for s in node.orelse:
                    walk(s, depth)
                return
            if isinstance(node, ast.Call) and depth > 0 \
                    and jit_call_spec(ctx, node) is not None:
                out.append(self.finding(
                    ctx, node.lineno,
                    "jax.jit wrapper constructed inside a loop — "
                    "every iteration pays a fresh trace+compile"))
            for child in ast.iter_child_nodes(node):
                walk(child, depth)

        walk(ctx.tree, 0)
        return out

    # - (b) jitted closure capturing an enclosing-scope device array -

    def _captured_arrays(self, ctx) -> List[Finding]:
        jb = jit_boundary(ctx)
        traced_ids = {id(fn) for fn, _ in jb.traced}
        out = []

        def array_bindings(fn) -> Dict[str, int]:
            binds = {}
            for s in _body_stmts(fn):
                if not (isinstance(s, ast.Assign)
                        and isinstance(s.value, ast.Call)):
                    continue
                if (ctx.resolve(s.value.func) or "") not in _ARRAY_MAKERS:
                    continue
                for t in s.targets:
                    if isinstance(t, ast.Name):
                        binds[t.id] = s.lineno
            return binds

        def visit(fn, outer_binds: Dict[str, int]):
            binds = dict(outer_binds, **array_bindings(fn))
            for sub in nested_defs(fn):
                if id(sub) in traced_ids:
                    for name in sorted(free_reads(sub) & set(binds)):
                        out.append(self.finding(
                            ctx, sub.lineno,
                            f"jitted '{sub.name}' closes over device "
                            f"array '{name}' (materialized at line "
                            f"{binds[name]}) — baked into the trace "
                            f"as a constant"))
                visit(sub, binds)

        for fn in scope_functions(ctx.tree):
            visit(fn, {})
        return out

    # - (c) unhashable literal at a static argnum -

    def _unhashable_statics(self, ctx) -> List[Finding]:
        jb = jit_boundary(ctx)
        out = []

        def analyze(fn, env):
            env = dict(env)
            body = _body_stmts(fn)
            # bindings first (flow-insensitively), then the call scan —
            # statement order must not hide a wrapper from its calls
            for s in body:
                if (isinstance(s, ast.Assign) and len(s.targets) == 1
                        and isinstance(s.targets[0], ast.Name)):
                    spec = callable_jit_spec(ctx, s.value, env,
                                             jb.factories)
                    if spec is not None:
                        env[s.targets[0].id] = spec
            for s in body:
                for call in ast.walk(s):
                    if not (isinstance(call, ast.Call)
                            and isinstance(call.func, ast.Name)):
                        continue
                    spec = env.get(call.func.id)
                    if spec is None:
                        continue
                    for i in sorted(spec.static_argnums):
                        if i < len(call.args) and isinstance(
                                call.args[i], _UNHASHABLE):
                            out.append(self.finding(
                                ctx, call.lineno,
                                f"unhashable literal at static argnum "
                                f"{i} of jitted '{call.func.id}' — "
                                f"TypeError at call time"))
                    for kw in call.keywords:
                        if kw.arg in spec.static_argnames and \
                                isinstance(kw.value, _UNHASHABLE):
                            out.append(self.finding(
                                ctx, call.lineno,
                                f"unhashable literal for static arg "
                                f"'{kw.arg}' of jitted "
                                f"'{call.func.id}' — TypeError at "
                                f"call time"))
            for sub in nested_defs(fn):
                analyze(sub, env)

        for fn in scope_functions(ctx.tree):
            analyze(fn, jb.wrapped)
        return out


# -- SPL011 -----------------------------------------------------------------

_IO_PATH_METHODS = {"open", "read_text", "write_text", "read_bytes",
                    "write_bytes", "unlink", "rename", "replace"}
_IO_OS_FNS = {"os.replace", "os.rename", "os.remove", "os.unlink",
              "shutil.move", "shutil.copy"}


class CacheLockDiscipline(Rule):
    """Raw IO on the shared probe/tune JSON cache files outside the
    locked helpers.  Two processes proving kernels or tuning plans
    share one cache file; only ``_json_cache_update`` (flock +
    atomic-replace read-modify-write) and ``_json_cache_load`` (the
    degrading read side) uphold the concurrency and best-effort
    contracts — an inline ``open(cache_path())``/``json.dump`` can
    drop concurrent writers' entries or crash dispatch on a corrupt
    file.  Detection is dataflow-based: any value derived from a
    configured cache-path function that reaches an IO verb is
    flagged.  Known imprecision: a helper that receives the path as a
    parameter is trusted (that is the sanctioned chokepoint shape)."""

    id = "SPL011"
    title = "cache-file IO bypasses the locked cache helpers"
    hint = ("route writes through pallas_kernels._json_cache_update "
            "and reads through _json_cache_load (tune.py and the "
            "probe cache share them); see docs/autotune.md")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        cfg = project.config
        path_fns = set(cfg.cache_path_functions)
        helpers = set(cfg.cache_io_helpers)
        if not path_fns:
            return []
        out: List[Finding] = []

        def is_path_call(node) -> bool:
            return (isinstance(node, ast.Call)
                    and (ctx.resolve(node.func) or ""
                         ).split(".")[-1] in path_fns)

        def scope(stmts, fname: str) -> None:
            if fname in helpers:
                return
            tainted: Set[str] = set()
            flat: List[ast.stmt] = []
            for s in stmts:
                flat.append(s)
                if not isinstance(s, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    flat.extend(c for c in ast.walk(s)
                                if isinstance(c, ast.stmt)
                                and c is not s)

            def expr_tainted(expr) -> bool:
                if any(is_path_call(n) for n in ast.walk(expr)):
                    return True
                return any(n in tainted for n, _ in _expr_loads(expr))

            changed = True
            while changed:
                changed = False
                for s in flat:
                    pairs = []
                    if isinstance(s, ast.Assign):
                        pairs = [(t, s.value) for t in s.targets]
                    elif isinstance(s, (ast.With, ast.AsyncWith)):
                        pairs = [(i.optional_vars, i.context_expr)
                                 for i in s.items if i.optional_vars]
                    for t, v in pairs:
                        if not expr_tainted(v):
                            continue
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name) and \
                                    isinstance(n.ctx, ast.Store) and \
                                    n.id not in tainted:
                                tainted.add(n.id)
                                changed = True
            for s in flat:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    continue
                for call in ast.walk(s):
                    if not isinstance(call, ast.Call):
                        continue
                    dotted = ctx.resolve(call.func) or ""
                    hit = None
                    if dotted == "open" and call.args and \
                            expr_tainted(call.args[0]):
                        hit = "open()"
                    elif isinstance(call.func, ast.Attribute) and \
                            call.func.attr in _IO_PATH_METHODS and \
                            expr_tainted(call.func.value):
                        hit = f".{call.func.attr}()"
                    elif dotted in _IO_OS_FNS and any(
                            expr_tainted(a) for a in call.args):
                        hit = dotted
                    if hit:
                        out.append(self.finding(
                            ctx, call.lineno,
                            f"direct {hit} on the shared cache file "
                            f"bypasses the locked cache helpers"))
            for s in flat:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scope(s.body, s.name)
                elif isinstance(s, ast.ClassDef):
                    # class bodies hold methods (their own scopes) and
                    # occasionally class-level statements
                    scope(s.body, f"<class {s.name}>")

        module_stmts = [s for s in ctx.tree.body]
        scope(module_stmts, "<module>")
        return _dedupe(out)


# -- SPL012 -----------------------------------------------------------------

def _declared_registry(ctx: FileCtx, registry: str) -> Dict[str, int]:
    """String keys (-> line) of a module-level ``REGISTRY = {...}``."""
    for node in walk_nodes(ctx.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == registry
                and isinstance(node.value, ast.Dict)):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return {}


class RunReportEventDrift(Rule):
    """Run-report event drift: every event kind the code emits via
    ``run_report().add("<kind>", ...)`` must be declared (with a doc)
    in the resilience module's RUN_REPORT_EVENTS registry, and every
    declared kind must still be emitted somewhere.  The run report is
    the observability surface for silent degradation — an undocumented
    event is invisible to operators reading the docs, and a declared-
    but-never-emitted one is a dead promise (usually a renamed
    emission site)."""

    id = "SPL012"
    title = "run-report event drift against resilience.py:" \
            "RUN_REPORT_EVENTS"
    hint = ("declare the event kind (with a one-line doc) in "
            "splatt_tpu/resilience.py:RUN_REPORT_EVENTS; docs render "
            "from that registry")

    def finalize(self, project: Project) -> List[Finding]:
        cfg = project.config
        res_ctx = project.ctx_for(cfg.resilience_module)
        if res_ctx is None:
            return []
        declared = _declared_registry(res_ctx, "RUN_REPORT_EVENTS")
        if not declared:
            return []  # registry-less mini-projects: nothing to check
        out: List[Finding] = []
        emitted: Set[str] = set()
        for ctx in project.files + (
                [res_ctx] if res_ctx not in project.files else []):
            for kind, line in self._emissions(ctx):
                if kind is None:
                    out.append(self.finding(
                        ctx, line,
                        "run-report event kind is not statically "
                        "resolvable — splint cannot check it against "
                        "RUN_REPORT_EVENTS"))
                    continue
                emitted.add(kind)
                if kind not in declared and ctx in project.files:
                    out.append(self.finding(
                        ctx, line,
                        f"run-report event '{kind}' is not declared "
                        f"in {cfg.resilience_module}:RUN_REPORT_EVENTS"))
        for kind, line in declared.items():
            if kind not in emitted:
                out.append(self.finding(
                    res_ctx, line,
                    f"declared run-report event '{kind}' is never "
                    f"emitted — dead declaration or renamed emission "
                    f"site"))
        return out

    @staticmethod
    def _emissions(ctx: FileCtx) -> List[Tuple[Optional[str], int]]:
        def is_run_report_call(node) -> bool:
            return (isinstance(node, ast.Call)
                    and (ctx.resolve(node.func) or ""
                         ).split(".")[-1] == "run_report")

        # names bound to the report object: rr = run_report()
        report_names: Set[str] = set()
        for node in walk_nodes(ctx.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and is_run_report_call(node.value)):
                report_names.add(node.targets[0].id)
        out = []
        for node in walk_nodes(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add"):
                continue
            base = node.func.value
            if not (is_run_report_call(base)
                    or (isinstance(base, ast.Name)
                        and base.id in report_names)):
                continue
            arg = node.args[0] if node.args else None
            kind: Optional[str] = None
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                kind = arg.value
            elif isinstance(arg, ast.Name):
                kind = ctx.str_consts.get(arg.id)
            out.append((kind, node.lineno))
        return out


# -- SPL013 -----------------------------------------------------------------

_SPAN_FNS = {"span", "begin"}


def _span_opens(ctx: FileCtx, is_trace_module: bool
                ) -> List[Tuple[Optional[str], int]]:
    """(name, lineno) for every span-opening call in `ctx`: the literal
    string, 'prefix.*' for an f-string with a literal prefix, or None
    when not statically resolvable.  ``trace.span(...)``/
    ``trace.begin(...)`` everywhere; inside the trace module itself the
    bare ``span(...)``/``begin(...)`` spellings count too (the module
    opens its own ``trace.export`` span)."""
    out = []
    for node in walk_nodes(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = ctx.resolve(node.func) or ""
        tail = dotted.split(".")[-1]
        if tail not in _SPAN_FNS:
            continue
        if not ("trace" in dotted.split(".")[:-1]
                or (is_trace_module and dotted == tail)):
            continue
        arg = node.args[0] if node.args else None
        name: Optional[str] = None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
        elif isinstance(arg, ast.Name):
            name = ctx.str_consts.get(arg.id)
        elif isinstance(arg, ast.JoinedStr) and arg.values:
            first = arg.values[0]
            if isinstance(first, ast.Constant) and \
                    isinstance(first.value, str) and first.value:
                name = first.value + "*"
        out.append((name, node.lineno))
    return out


class SpanNameDrift(Rule):
    """Span-name drift: every name production code opens a trace span
    under (``trace.span("...")`` / ``trace.begin("...")``) must be
    declared in the trace module's SPANS registry — the catalog
    docs/observability.md renders and ``splatt trace`` summaries are
    read against — and every declared name must still be opened
    somewhere in production.  A renamed span otherwise silently orphans
    the queries and dashboards built on it, exactly like a renamed
    fault site (SPL006) or run-report event (SPL012).  A trailing
    ``.*`` declares an f-string family (``trace.span(f"timer.{n}")``
    matches a declared ``timer.*``)."""

    id = "SPL013"
    title = "span-name drift against trace.py:SPANS"
    hint = ("declare the span name (with a one-line doc) in "
            "splatt_tpu/trace.py:SPANS; docs/observability.md renders "
            "from that registry")

    def finalize(self, project: Project) -> List[Finding]:
        cfg = project.config
        trace_ctx = project.ctx_for(cfg.trace_module)
        if trace_ctx is None:
            return []
        declared = _declared_registry(trace_ctx, "SPANS")
        if not declared:
            return []  # registry-less mini-projects: nothing to check
        out: List[Finding] = []
        used: Set[str] = set()
        ctxs = project.files + ([trace_ctx]
                                if trace_ctx not in project.files else [])
        for ctx in ctxs:
            in_trace = ctx.relpath == cfg.trace_module
            for name, line in _span_opens(ctx, in_trace):
                if name is None:
                    # the trace module's own API helpers forward the
                    # caller's name (begin() -> span(name)); those are
                    # the sanctioned chokepoints, not open sites
                    if not in_trace:
                        out.append(self.finding(
                            ctx, line,
                            "span name is not statically resolvable — "
                            "splint cannot check it against "
                            "trace.SPANS"))
                    continue
                used.add(name)
                if not any(_site_matches(d, name) for d in declared) \
                        and ctx in project.files:
                    out.append(self.finding(
                        ctx, line,
                        f"span name '{name}' is not declared in "
                        f"{cfg.trace_module}:SPANS"))
        for d, line in declared.items():
            if not any(_site_matches(d, u) for u in used):
                out.append(self.finding(
                    trace_ctx, line,
                    f"declared span name '{d}' is never opened — dead "
                    f"declaration or renamed span"))
        return out


# -- SPL029 -----------------------------------------------------------------

#: the metric-recording verbs, each bound to the one sample type it
#: may record (trace.py raises on the mismatch at runtime; SPL029
#: catches it before anything runs)
_METRIC_FNS = {"metric_inc": "counter", "metric_set": "gauge",
               "metric_observe": "histogram"}


def _declared_metric_types(ctx: FileCtx) -> Dict[str, Tuple[Optional[str], int]]:
    """name -> (declared type, line) of the trace module's
    ``METRICS = {"name": ("type", "doc"), ...}`` registry."""
    for node in walk_nodes(ctx.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "METRICS"
                and isinstance(node.value, ast.Dict)):
            out: Dict[str, Tuple[Optional[str], int]] = {}
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    continue
                typ = None
                if isinstance(v, ast.Tuple) and v.elts and \
                        isinstance(v.elts[0], ast.Constant):
                    typ = str(v.elts[0].value)
                out[k.value] = (typ, k.lineno)
            return out
    return {}


def _metric_emissions(ctx: FileCtx, is_trace_module: bool
                      ) -> List[Tuple[Optional[str], str, int]]:
    """(name, verb, lineno) for every ``trace.metric_inc/metric_set/
    metric_observe`` call in `ctx` (bare spellings inside the trace
    module itself count too — _event_metrics records there)."""
    out = []
    for node in walk_nodes(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = ctx.resolve(node.func) or ""
        tail = dotted.split(".")[-1]
        if tail not in _METRIC_FNS:
            continue
        if not ("trace" in dotted.split(".")[:-1]
                or (is_trace_module and dotted == tail)):
            continue
        arg = node.args[0] if node.args else None
        name: Optional[str] = None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
        elif isinstance(arg, ast.Name):
            name = ctx.str_consts.get(arg.id)
        out.append((name, tail, node.lineno))
    return out


class MetricNameDrift(Rule):
    """Metric-name drift: every name the code records through
    ``trace.metric_inc``/``metric_set``/``metric_observe`` must be
    declared in the trace module's METRICS registry — with the verb
    matching the declared type (incrementing a gauge would raise at
    runtime; here it is a finding before anything runs) — and every
    declared metric must still be recorded somewhere.  The docs
    metrics table ([tool.splint] ``metrics-doc``) is checked in both
    directions too: a declared metric missing from the docs is
    invisible to operators, and a documented-but-undeclared one is a
    dead promise.  The SPL013 span-name discipline, applied to the
    Prometheus surface that dashboards and the fleet aggregator are
    built on (docs/observability.md)."""

    id = "SPL029"
    title = "metric-name drift against trace.py:METRICS / the docs table"
    hint = ("declare the metric (name -> (type, doc)) in "
            "splatt_tpu/trace.py:METRICS and add its row to the docs "
            "metrics table; the registry is the exposition contract")

    def finalize(self, project: Project) -> List[Finding]:
        import re as _re

        cfg = project.config
        trace_ctx = project.ctx_for(cfg.trace_module)
        if trace_ctx is None:
            return []
        declared = _declared_metric_types(trace_ctx)
        if not declared:
            return []  # registry-less mini-projects: nothing to check
        out: List[Finding] = []
        used: Set[str] = set()
        ctxs = project.files + ([trace_ctx]
                                if trace_ctx not in project.files else [])
        for ctx in ctxs:
            in_trace = ctx.relpath == cfg.trace_module
            for name, verb, line in _metric_emissions(ctx, in_trace):
                if name is None:
                    if not in_trace and ctx in project.files:
                        out.append(self.finding(
                            ctx, line,
                            "metric name is not statically resolvable "
                            "— splint cannot check it against "
                            "trace.METRICS"))
                    continue
                used.add(name)
                if name not in declared:
                    if ctx in project.files:
                        out.append(self.finding(
                            ctx, line,
                            f"metric '{name}' is not declared in "
                            f"{cfg.trace_module}:METRICS"))
                    continue
                want = declared[name][0]
                if want and _METRIC_FNS[verb] != want \
                        and ctx in project.files:
                    out.append(self.finding(
                        ctx, line,
                        f"metric '{name}' is declared as a {want} but "
                        f"recorded via {verb} (the "
                        f"{_METRIC_FNS[verb]} verb) — this raises at "
                        f"runtime"))
        for name, (typ, line) in declared.items():
            if name not in used:
                out.append(self.finding(
                    trace_ctx, line,
                    f"declared metric '{name}' is never recorded — "
                    f"dead declaration or renamed emission site"))
        # the docs table, both directions (skipped when the configured
        # doc does not exist — fixture mini-projects)
        doc_path = (cfg.resolve(cfg.metrics_doc)
                    if getattr(cfg, "metrics_doc", "") else None)
        if doc_path is not None and doc_path.exists():
            text = doc_path.read_text()
            table_names = set()
            for line_txt in text.splitlines():
                if line_txt.lstrip().startswith("|"):
                    table_names.update(
                        _re.findall(r"splatt_[a-z0-9_]+", line_txt))
            for name, (typ, line) in declared.items():
                # membership is judged against TABLE rows, not prose:
                # a metric merely name-dropped in body text is still
                # missing its row
                if name not in table_names:
                    out.append(self.finding(
                        trace_ctx, line,
                        f"declared metric '{name}' has no row in "
                        f"{cfg.metrics_doc} — the metrics table "
                        f"renders from the registry"))
            for name in sorted(table_names - set(declared)):
                out.append(self.finding(
                    trace_ctx, 1,
                    f"{cfg.metrics_doc} documents metric '{name}' "
                    f"which {cfg.trace_module}:METRICS never declares "
                    f"— a dead promise to operators"))
        return out


# -- SPL014 -----------------------------------------------------------------

#: method names that mutate a container in place (the write verbs the
#: shared-state rule guards, alongside subscript/attribute stores)
_CONTAINER_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "appendleft", "popleft",
    "sort", "reverse",
}


def _parse_shared_state(entries) -> Dict[str, List[Tuple[str, str]]]:
    """Config entries ``relpath::target=lock`` → {relpath: [(target,
    lock)]}; malformed entries raise (a typo'd map must fail loudly,
    not silently unguard a structure)."""
    out: Dict[str, List[Tuple[str, str]]] = {}
    for entry in entries:
        try:
            loc, lock = entry.split("=", 1)
            rel, target = loc.split("::", 1)
        except ValueError:
            raise ValueError(
                f"splint: bad shared-state entry {entry!r} (want "
                f"'relpath::target=lock')")
        out.setdefault(rel, []).append((target.strip(), lock.strip()))
    return out


def _struct_root(expr) -> object:
    """The root object being stored into: peel subscripts off an
    assignment target (``self._jobs[jid]["state"]`` → the
    ``self._jobs`` attribute node)."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    return expr


def _matches_target(expr, target: str) -> bool:
    """Whether an expression names the configured structure: a bare
    ``NAME`` for module globals, ``self.attr`` for instance state."""
    if target.startswith("self."):
        return (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr == target[5:])
    return isinstance(expr, ast.Name) and expr.id == target


def _required_lock(rel: str, cls: Optional[str], lock: str) -> str:
    """The canonical id the configured guard spelling must resolve to
    at a mutation site inside class `cls`."""
    if lock.startswith("self."):
        return f"{rel}::{cls}.{lock[5:]}"
    return f"{rel}::{lock}"


class SharedStateWithoutLock(Rule):
    """A write to a declared shared structure without its owning lock
    held.  The ``[tool.splint] shared-state`` map records which lock
    guards which structure (the Server job table and queue, the fleet
    lease maps, tune's plan memo, trace's span/metric registries); the
    lock-set analysis (tools/splint/locks.py) proves each mutation
    site holds it.  Functions whose name ends in ``_locked`` are the
    caller-owns-the-lock convention and are exempt, as is ``__init__``
    (the object is not yet shared).  Known imprecision: aliases
    (``j = self._jobs[jid]``) and container elements are not tracked —
    the SPLATT_LOCKCHECK runtime sanitizer is the dynamic
    cross-check."""

    id = "SPL014"
    title = "shared-state write without the owning lock"
    hint = ("take the configured guard lock around the mutation (or "
            "move it into a '*_locked' helper whose callers hold it); "
            "the [tool.splint] shared-state map names the owner")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        from tools.splint.locks import (FileLocks, iter_scope_functions,
                                        lock_walk)

        entries = _parse_shared_state(
            project.config.shared_state).get(ctx.relpath)
        if not entries:
            return []
        fl = FileLocks(ctx)
        out: List[Finding] = []

        def scan(fn, cls):
            if fn.name == "__init__" or fn.name.endswith("_locked"):
                return
            nested: List[Tuple[object, object]] = []
            walk = lock_walk(ctx, fn, cls, fl,
                             on_nested=lambda sub, held:
                             nested.append((sub, cls)))
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.stmt):
                    continue
                held = walk.held_at.get(id(stmt))
                if held is None:
                    continue  # nested-def body: scanned on its own
                for target, lock, line in self._mutations(stmt, entries):
                    need = _required_lock(ctx.relpath, cls, lock)
                    if need not in held:
                        out.append(self.finding(
                            ctx, line,
                            f"write to shared '{target}' without "
                            f"holding its owning lock '{lock}' "
                            f"(declared in [tool.splint] "
                            f"shared-state)"))
            for sub, subcls in nested:
                scan(sub, subcls)

        for fn, cls in iter_scope_functions(ctx.tree):
            scan(fn, cls)
        return _dedupe(out)

    @staticmethod
    def _mutations(stmt, entries) -> List[Tuple[str, str, int]]:
        """(target, lock, line) for each configured-structure write in
        ONE statement.  Simple statements are scanned whole (a mutator
        call anywhere in them — ``jid = self._queue.pop(0)``, a return
        value, a boolean test — is still a mutation); compound
        statements contribute only their HEADER expressions, because
        their bodies are separate statements the caller visits with
        their own (possibly larger) lock sets."""
        out = []

        def hit(expr, line):
            for target, lock in entries:
                if _matches_target(expr, target):
                    out.append((target, lock, line))

        def scan_calls(root, line):
            for call in ast.walk(root):
                if isinstance(call, ast.Call) and \
                        isinstance(call.func, ast.Attribute) and \
                        call.func.attr in _CONTAINER_MUTATORS:
                    hit(_struct_root(call.func.value), line)

        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for t in targets:
                root = _struct_root(t)
                if isinstance(t, ast.Subscript):
                    hit(root, stmt.lineno)      # X[k] = ... mutates X
                elif isinstance(stmt, ast.AugAssign):
                    hit(root, stmt.lineno)      # X += ... rebinds X
                else:
                    # a direct rebind swaps the shared object under
                    # concurrent readers — same owner, same lock
                    hit(root, stmt.lineno)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Subscript):
                    hit(_struct_root(t), stmt.lineno)
        if isinstance(stmt, (ast.If, ast.While)):
            scan_calls(stmt.test, stmt.lineno)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            scan_calls(stmt.iter, stmt.lineno)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                scan_calls(item.context_expr, stmt.lineno)
        elif isinstance(stmt, ast.Try):
            pass  # no header expression of its own
        elif not isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
            scan_calls(stmt, stmt.lineno)
        return out


# -- SPL015 -----------------------------------------------------------------

class LockOrderCycle(Rule):
    """A cycle in the project-wide lock acquisition graph: somewhere
    lock A is taken while B is held and somewhere else B while A is
    held — two threads walking the two sites deadlock.  Edges come
    from the lock-set analysis: direct nesting (``with a: with b:``,
    including flock sidecars entered via contextmanager wrappers) and
    call sites under a held lock, resolved through the conservative
    call summaries of tools/splint/locks.py.  A self-loop — taking a
    non-reentrant lock while already holding it — is the degenerate
    cycle and deadlocks a single thread.  The in-process-lock-before-
    flock nesting of the cache/journal writers and the flock-before-
    in-process nesting of the fleet lease protocol stay consistent
    exactly because this graph is kept acyclic."""

    id = "SPL015"
    title = "lock-order cycle in the acquisition graph"
    hint = ("pick ONE global order for the locks in the cycle and "
            "re-nest the offending site (usually: move the inner "
            "acquisition out of the outer lock's critical section)")

    def finalize(self, project: Project) -> List[Finding]:
        from tools.splint.locks import project_locks

        pl = project_locks(project)
        edges = pl.order_edges()
        out: List[Finding] = []
        for cycle in pl.cycles():
            pairs = list(zip(cycle, cycle[1:]))
            rel, line = edges[pairs[0]]
            path = " -> ".join(c.split("::", 1)[-1] for c in cycle)
            sites = "; ".join(
                f"{edges[p][0]}:{edges[p][1]} takes "
                f"{p[1].split('::', 1)[-1]} under "
                f"{p[0].split('::', 1)[-1]}" for p in pairs)
            out.append(self.finding(
                rel, line,
                f"lock-order cycle {path} ({sites})"))
        return out


# -- SPL016 -----------------------------------------------------------------

_WRITE_MODES = {"w", "wb", "x", "xb", "w+", "wb+", "w+b"}
_APPEND_MODES = {"a", "ab", "a+", "ab+", "a+b"}
_TMP_WRITERS = {"numpy.savez", "numpy.savez_compressed", "numpy.save"}


class DurabilityProtocolDrift(Rule):
    """A durable-write protocol verb outside the sanctioned helpers
    (splatt_tpu/utils/durable.py; ``[tool.splint]``
    durable-write-helpers): an ``os.fsync``, a tmp-write→``os.replace``
    publish (an ``os.replace`` whose source this function itself wrote
    — claim/.bak renames of existing files are a different verb and
    stay clean), or a written append-mode ``open``.  Every journal
    line, lease, checkpoint, cache file and metrics snapshot must go
    through the one helper so the fsync/heal/atomic-rename discipline
    cannot drift per call site — the hand-rolled copies this rule
    replaced disagreed about fsync."""

    id = "SPL016"
    title = "durable write outside the sanctioned durable-write helpers"
    hint = ("route the write through splatt_tpu.utils.durable "
            "(publish_bytes/publish_json/publish_file for atomic "
            "publishes, append_line for durable appends)")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        helpers = set(project.config.durable_write_helpers)
        if not helpers:
            return []
        out: List[Finding] = []
        for fn in _all_functions(ctx.tree):
            if fn.name in helpers:
                continue
            out.extend(self._scan_fn(ctx, fn))
        return _dedupe(out)

    def _scan_fn(self, ctx, fn) -> List[Finding]:
        out: List[Finding] = []
        written: Set[str] = set()   # names holding a locally-written tmp
        appended: Dict[str, int] = {}  # append-mode file object names
        wrote_to: Set[str] = set()

        def mode_of(call) -> Optional[str]:
            if len(call.args) > 1 and isinstance(call.args[1],
                                                 ast.Constant):
                return str(call.args[1].value)
            for kw in call.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    return str(kw.value.value)
            return None

        body = [s for s in _body_stmts(fn)
                if not isinstance(s, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef))]
        for s in body:
            for call in ast.walk(s):
                if not isinstance(call, ast.Call):
                    continue
                dotted = ctx.resolve(call.func) or ""
                # (a) fsync is the durability verb itself
                if dotted == "os.fsync":
                    out.append(self.finding(
                        ctx, call.lineno,
                        "os.fsync outside the sanctioned durable-write "
                        "helpers"))
                # track written-tmp names
                if dotted == "open" and call.args:
                    mode = mode_of(call)
                    argnames = {n.id for n in ast.walk(call.args[0])
                                if isinstance(n, ast.Name)}
                    if mode in _WRITE_MODES:
                        written.update(argnames)
                    elif mode in _APPEND_MODES:
                        for name in argnames:
                            appended[name] = call.lineno
                if dotted in _TMP_WRITERS and call.args:
                    written.update(n.id for n in ast.walk(call.args[0])
                                   if isinstance(n, ast.Name))
                if isinstance(call.func, ast.Attribute) and \
                        call.func.attr in ("write_text", "write_bytes") \
                        and isinstance(call.func.value, ast.Name):
                    written.add(call.func.value.id)
            if isinstance(s, ast.Assign) and isinstance(s.value, ast.Call):
                vdot = (ctx.resolve(s.value.func) or "")
                if vdot.split(".")[-1] == "mkstemp":
                    # fd, tmp = tempfile.mkstemp(...): the tmp path is
                    # a locally-written temp by construction
                    for t in s.targets:
                        written.update(n.id for n in ast.walk(t)
                                       if isinstance(n, ast.Name)
                                       and isinstance(n.ctx, ast.Store))
        # which bound file objects actually got .write()?
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "write" and \
                    isinstance(node.func.value, ast.Name):
                wrote_to.add(node.func.value.id)
        # with open(p, "ab") as f: ... f.write(...) — map the file
        # object back to the opened path name
        for node in ast.walk(fn):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                cexpr = item.context_expr
                if not (isinstance(cexpr, ast.Call)
                        and (ctx.resolve(cexpr.func) or "") == "open"
                        and cexpr.args):
                    continue
                mode = None
                if len(cexpr.args) > 1 and isinstance(cexpr.args[1],
                                                      ast.Constant):
                    mode = str(cexpr.args[1].value)
                if mode in _APPEND_MODES and item.optional_vars is not None:
                    fname = getattr(item.optional_vars, "id", None)
                    if fname in wrote_to:
                        out.append(self.finding(
                            ctx, cexpr.lineno,
                            "hand-rolled durable append (append-mode "
                            "open + write) outside the sanctioned "
                            "helpers"))
        # (b) publishing a locally-written tmp by rename
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = ctx.resolve(node.func) or ""
            src = None
            if dotted in ("os.replace", "os.rename", "shutil.move") \
                    and node.args:
                src = node.args[0]
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("replace", "rename") and \
                    isinstance(node.func.value, ast.Name) and node.args:
                src = node.func.value
            if src is None:
                continue
            names = {n.id for n in ast.walk(src)
                     if isinstance(n, ast.Name)}
            if names & written:
                out.append(self.finding(
                    ctx, node.lineno,
                    "hand-rolled tmp-write -> rename publish outside "
                    "the sanctioned durable-write helpers"))
        return out


# -- SPL017 -----------------------------------------------------------------

class BlockingCallUnderLock(Rule):
    """A blocking call — fsync, flock, sleep, a thread join, an Event
    wait, a subprocess — made while an in-process lock is held, on a
    configured control-plane hot path ([tool.splint] hot-lock-paths).
    Every status poll, submission and worker dequeue serializes on
    these locks: one fsync inside the critical section stalls the
    whole daemon's control plane (the PR 11 submit fix — decide under
    the lock, do the durable IO outside it — made permanent).  Calls
    are checked transitively through the conservative call summaries,
    so ``self.journal.append(...)`` under the server lock is caught
    even though the fsync is two frames down."""

    id = "SPL017"
    title = "blocking call while holding an in-process lock (hot path)"
    hint = ("decide under the lock, perform the blocking IO outside "
            "it (serve.submit's ACCEPTING-reservation pattern), or "
            "drop the path from hot-lock-paths with a justification")

    def finalize(self, project: Project) -> List[Finding]:
        from tools.splint.locks import (_blocking_verb, is_flock_id,
                                        project_locks)

        hot = set(project.config.hot_lock_paths)
        if not hot:
            return []
        pl = project_locks(project)
        out: List[Finding] = []
        for key, (ctx, fn, cls) in pl.functions.items():
            if f"{ctx.relpath}::{fn.name}" not in hot:
                continue
            walk = pl.walk_of(key)
            for stmt in ast.walk(fn):
                if not isinstance(stmt, ast.stmt):
                    continue
                held = walk.held_at.get(id(stmt))
                if held is None:
                    continue
                held = {h for h in held if not is_flock_id(h)}
                if not held:
                    continue
                for call in ast.walk(stmt):
                    if not isinstance(call, ast.Call):
                        continue
                    verb = _blocking_verb(ctx, call)
                    via = None
                    if verb is None:
                        for callee in pl.call_targets(ctx, cls, call):
                            blocked = pl.blocks(callee)
                            if blocked:
                                verb = sorted(blocked)[0]
                                via = callee.split("::", 1)[-1]
                                break
                    if verb is None:
                        continue
                    lock = sorted(held)[0].split("::", 1)[-1]
                    how = f" (via {via})" if via else ""
                    out.append(self.finding(
                        ctx, call.lineno,
                        f"blocking {verb}{how} while holding "
                        f"'{lock}' on hot path '{fn.name}' — the "
                        f"control plane stalls behind it"))
        return _dedupe(out)


# -- SPL018 -----------------------------------------------------------------

class ContextvarLeak(Rule):
    """A ``ContextVar.set`` whose reset is not crash-safe: the token is
    discarded, or the matching ``reset(token)`` is not inside the
    ``finally`` of the try that immediately guards the scoped region.
    The per-job isolation machinery (``resilience.scope``,
    ``faults.scoped``, trace's ``enabling``) all stack per-tenant
    state in contextvars — a set that an exception can strand leaks
    one tenant's demotions, fault schedule or trace toggle into the
    next job that reuses the context.  The sanctioned idiom::

        token = VAR.set(value)
        try:
            ...
        finally:
            VAR.reset(token)

    ``__enter__``/``__exit__`` method bodies are exempt (the pairing
    spans two functions — trace's span-stack push/pop — which this
    single-function analysis cannot see; documented imprecision)."""

    id = "SPL018"
    title = "ContextVar.set without a try/finally reset"
    hint = ("bind the token and reset it in the finally of the very "
            "next try block (resilience.scope is the exemplar); for "
            "__enter__/__exit__ pairs keep the reset in __exit__")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        ctxvars: Set[str] = set()
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and (ctx.resolve(node.value.func) or "") \
                    == "contextvars.ContextVar":
                ctxvars.add(node.targets[0].id)
        if not ctxvars:
            return []
        out: List[Finding] = []
        for fn in _all_functions(ctx.tree):
            if fn.name in ("__enter__", "__exit__"):
                continue
            self._scan_body(ctx, fn.body, ctxvars, out)
        return _dedupe(out)

    def _is_set(self, ctx, expr, ctxvars) -> Optional[str]:
        if isinstance(expr, ast.Call) and \
                isinstance(expr.func, ast.Attribute) and \
                expr.func.attr == "set" and \
                isinstance(expr.func.value, ast.Name) and \
                expr.func.value.id in ctxvars:
            return expr.func.value.id
        return None

    def _scan_body(self, ctx, body, ctxvars, out) -> None:
        for i, stmt in enumerate(body):
            # recurse into nested blocks
            for attr in ("body", "orelse", "finalbody"):
                nested = getattr(stmt, attr, None)
                if isinstance(nested, list) and not isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                    self._scan_body(ctx, nested, ctxvars, out)
            for h in getattr(stmt, "handlers", []):
                self._scan_body(ctx, h.body, ctxvars, out)
            # a bare set expression discards the token outright
            if isinstance(stmt, ast.Expr):
                var = self._is_set(ctx, stmt.value, ctxvars)
                if var is not None:
                    out.append(self.finding(
                        ctx, stmt.lineno,
                        f"{var}.set(...) discards its reset token — "
                        f"the previous context value is "
                        f"unrestorable"))
                continue
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            var = self._is_set(ctx, stmt.value, ctxvars)
            if var is None:
                continue
            token = stmt.targets[0].id
            nxt = body[i + 1] if i + 1 < len(body) else None
            if not (isinstance(nxt, ast.Try)
                    and self._resets(nxt.finalbody, var, token)):
                out.append(self.finding(
                    ctx, stmt.lineno,
                    f"{var}.set(...) is not guarded by an immediate "
                    f"try/finally {var}.reset({token}) — an exception "
                    f"here leaks the scoped state into the next job "
                    f"on this context"))

    @staticmethod
    def _resets(finalbody, var: str, token: str) -> bool:
        for s in finalbody:
            for call in ast.walk(s):
                if isinstance(call, ast.Call) and \
                        isinstance(call.func, ast.Attribute) and \
                        call.func.attr == "reset" and \
                        isinstance(call.func.value, ast.Name) and \
                        call.func.value.id == var and \
                        any(isinstance(a, ast.Name) and a.id == token
                            for a in call.args):
                    return True
        return False


def _dedupe(findings: List[Finding]) -> List[Finding]:
    seen = set()
    out = []
    for f in findings:
        k = (f.rule, f.path, f.line, f.message)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


# the crash-consistency protocol rules (SPL019-SPL023) live in their
# own module; it imports only from core, so this import is cycle-free
from tools.splint.durability import (ReplayTotality,  # noqa: E402
                                     FsyncBarrier, StampFactorAtomicity,
                                     TornPublish, UnfencedTerminalCommit)
from tools.splint.numerics import (AccumulationDiscipline,  # noqa: E402
                                   ImplicitHotUpcast)
from tools.splint.tiling import (PlanSchemaDrift,  # noqa: E402
                                 TileAlignment, VmemBudget)

RULES: List[Rule] = [
    RawEnvironAccess(),
    BroadExceptSwallows(),
    HostSyncInJit(),
    RecompilationHazard(),
    DtypeLiteral(),
    FaultSiteDrift(),
    UndocumentedEnvVar(),
    UseAfterDonate(),
    TracerLeak(),
    RecompileTrigger(),
    CacheLockDiscipline(),
    RunReportEventDrift(),
    SpanNameDrift(),
    MetricNameDrift(),
    SharedStateWithoutLock(),
    LockOrderCycle(),
    DurabilityProtocolDrift(),
    BlockingCallUnderLock(),
    ContextvarLeak(),
    TornPublish(),
    UnfencedTerminalCommit(),
    StampFactorAtomicity(),
    ReplayTotality(),
    FsyncBarrier(),
    AccumulationDiscipline(),
    TileAlignment(),
    VmemBudget(),
    PlanSchemaDrift(),
    ImplicitHotUpcast(),
]
