"""SPL022 bad: a journal record kind emitted nowhere in serve's
KNOWN_KINDS vocabulary — replay will skip it as unknown — plus an
emission splint cannot resolve statically."""


class MiniServer:
    def _rec(self, kind, jid, **kw):
        return {"rec": kind, "job": jid, **kw}

    def emit_undeclared(self, sink, jid):
        # not in serve.KNOWN_KINDS: the replay forward-compat gate
        # will drop this record on the floor
        sink.append(self._rec("spl022_fixture_unknown_kind", jid))

    def emit_unresolvable(self, sink, jid, kind_from_caller):
        # the kind is a bare parameter — replay totality cannot be
        # audited for an emission splint cannot resolve
        sink.append(self._rec(kind_from_caller, jid))
