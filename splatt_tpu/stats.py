"""Tensor and factorization statistics (≙ src/stats.c).

- :func:`tensor_stats`  ≙ stats_tt basic dims/nnz/density/storage
  (src/stats.c:26-42)
- :func:`cpd_stats_text` ≙ cpd_stats factoring header (rank, iters, tol,
  allocation, storage — src/stats.c:226-296)
"""

from __future__ import annotations

import numpy as np

from splatt_tpu.blocked import BlockedSparse
from splatt_tpu.config import Options
from splatt_tpu.coo import SparseTensor


def _human_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.2f}{unit}"
        n /= 1024
    return f"{n:.2f}TB"


def coo_storage_bytes(tt: SparseTensor) -> int:
    return tt.inds.size * tt.inds.dtype.itemsize + tt.vals.nbytes


def tensor_stats(tt: SparseTensor, name: str = "tensor") -> str:
    dims = "x".join(str(d) for d in tt.dims)
    lines = [
        f"Tensor information ---------------------------------",
        f"FILE={name}",
        f"DIMS={dims} NNZ={tt.nnz}",
        f"DENSITY={tt.density():e}",
        f"COORD-STORAGE={_human_bytes(coo_storage_bytes(tt))}",
    ]
    return "\n".join(lines)


def skew_stats(tt: SparseTensor) -> dict:
    """Per-mode slice/fiber skew metrics (docs/layout-balance.md): how
    power-law an input is, as numbers.  Per mode: max/mean and
    p99/median nnz per nonempty slice, the hottest slice's share of all
    nonzeros, and the skew regime bucket the autotuner keys plans by
    (blocked.nnz_skew_bucket).  ``fiber_max_mean`` is the same ratio
    over the mode-rooted fibers of the smallest mode — fiber weight is
    what the balanced packing bin-packs."""
    from splatt_tpu.blocked import nnz_skew_bucket

    out = {"modes": {}}
    for m in range(tt.nmodes):
        hist = tt.mode_histogram(m)
        nz = hist[hist > 0]
        if nz.size == 0:
            out["modes"][str(m)] = dict(max_mean=1.0, p99_median=1.0,
                                        top_share=0.0, bucket="k0")
            continue
        med = float(np.median(nz))
        out["modes"][str(m)] = dict(
            max_mean=round(float(nz.max()) / float(nz.mean()), 3),
            p99_median=round(float(np.percentile(nz, 99))
                             / max(med, 1.0), 3),
            top_share=round(float(nz.max()) / max(tt.nnz, 1), 4),
            nonempty=int(nz.size),
            bucket=nnz_skew_bucket(hist))
    if tt.nnz and tt.nmodes > 1:
        # fiber weights of the smallest mode's fibers (all coordinates
        # but `root` shared): the unit the balanced packer weighs.
        # 1-mode tensors have no other coordinates to key fibers by —
        # the slice stats above are the whole story there.
        root = int(np.argmin(tt.dims))
        others = [m for m in range(tt.nmodes) if m != root]
        keys = np.stack([np.asarray(tt.inds[m]) for m in others])
        order = np.lexsort(keys[::-1])
        sk = keys[:, order]
        new_fiber = np.ones(tt.nnz, dtype=bool)
        if tt.nnz > 1:
            new_fiber[1:] = np.any(sk[:, 1:] != sk[:, :-1], axis=0)
        sizes = np.diff(np.concatenate(
            [np.flatnonzero(new_fiber), [tt.nnz]]))
        out["fiber_max_mean"] = round(float(sizes.max())
                                      / float(sizes.mean()), 3)
        out["fiber_count"] = int(sizes.size)
    return out


def skew_stats_text(tt: SparseTensor) -> str:
    """Human-readable skew report (the `splatt stats` view of
    :func:`skew_stats`) — lets a user (and the log reader) tell a
    uniform tensor from a power-law one before picking layouts."""
    st = skew_stats(tt)
    lines = ["Slice skew -----------------------------------------"]
    for m, d in st["modes"].items():
        lines.append(
            f"  mode {m}: nnz/slice max/mean={d['max_mean']} "
            f"p99/median={d['p99_median']} top-slice "
            f"{100 * d['top_share']:.1f}% of nnz [{d['bucket']}]")
    if "fiber_max_mean" in st:
        lines.append(f"  fibers (smallest-mode-rooted): "
                     f"{st['fiber_count']} fibers, nnz/fiber "
                     f"max/mean={st['fiber_max_mean']}")
    return "\n".join(lines)


def density_stats(tt: SparseTensor, threshold: float = None) -> dict:
    """Per-mode density metrics (docs/dense.md): the raw mode density
    (nnz over the full dense cell count), the PADDED density against
    the dense tile layout's cells (what the verdict thresholds), the
    autotuner's density regime bucket, and the dense/sparse verdict at
    `threshold` (default: the resolved SPLATT_DENSE_THRESHOLD)."""
    from splatt_tpu.blocked import (dense_mode_verdict, mode_density,
                                    mode_density_bucket,
                                    padded_mode_density)
    from splatt_tpu.config import Options, resolve_dense_threshold

    if threshold is None:
        threshold = resolve_dense_threshold(Options())
    out = {"threshold": threshold, "modes": {}}
    for m in range(tt.nmodes):
        out["modes"][str(m)] = dict(
            density=float(mode_density(tt.dims, m, tt.nnz)),
            padded_density=float(padded_mode_density(tt.dims, m, tt.nnz)),
            bucket=mode_density_bucket(tt.dims, m, tt.nnz),
            verdict=("dense" if dense_mode_verdict(tt.dims, m, tt.nnz,
                                                   threshold)
                     else "sparse"))
    return out


def density_stats_text(tt: SparseTensor) -> str:
    """Human-readable per-mode density report (the `splatt stats` view
    of :func:`density_stats`) — tells a dense-mode workload from a
    sparse one before picking layouts (docs/dense.md)."""
    st = density_stats(tt)
    lines = ["Mode density ---------------------------------------"]
    for m, d in st["modes"].items():
        bucket = f" [{d['bucket']}]" if d["bucket"] else ""
        lines.append(
            f"  mode {m}: density={d['density']:.3e} "
            f"padded={d['padded_density']:.3e}{bucket} -> "
            f"{d['verdict']}")
    lines.append(f"  (dense verdict at padded density >= "
                 f"{st['threshold']:g}; SPLATT_DENSE governs dispatch)")
    return "\n".join(lines)


def grid_stats_text(decomp) -> str:
    """Distributed decomposition stats (≙ mpi_global_stats /
    mpi_rank_stats / mpi_cpd_stats, src/stats.c:298-457)."""
    grid = "x".join(str(g) for g in decomp.grid)
    ncells = int(np.prod(decomp.grid))
    lines = [
        "Decomposition --------------------------------------",
        f"GRID={grid} CELLS={ncells} CELL-NNZ={decomp.cell_nnz} "
        f"FILL={decomp.fill:0.3f}",
        f"LAYER-ROWS={'x'.join(str(b) for b in decomp.block_rows)} "
        f"(padded dims {'x'.join(str(d) for d in decomp.dims_pad)})",
    ]
    # per-cell imbalance: padded slots are wasted work (exact counts
    # recorded at build time — explicit zero-valued entries count)
    occupied = np.asarray(decomp.cell_counts).ravel()
    if occupied.size:
        lines.append(
            f"CELL-NNZ min={int(occupied.min())} "
            f"avg={float(occupied.mean()):0.1f} max={int(occupied.max())}")
    return "\n".join(lines)


def partition_quality_text(tt: SparseTensor, parts: np.ndarray) -> str:
    """Quality of a nonzero-level partition (≙ the hypergraph partition
    stats, src/stats.c:53-170): load balance plus the connectivity-1
    cut of each mode's slice hyperedges — for every slice, the number
    of extra parts it spans (= factor rows that must be exchanged under
    the FINE decomposition).
    """
    parts = np.asarray(parts)
    if parts.shape[0] != tt.nnz:
        raise ValueError(
            f"partition length {parts.shape[0]} != nnz {tt.nnz}")
    nparts = int(parts.max()) + 1 if parts.size else 1
    counts = np.bincount(parts, minlength=nparts)
    avg = tt.nnz / max(nparts, 1)
    lines = [
        "Partition quality ----------------------------------",
        f"PARTS={nparts} NNZ-BALANCE max/avg={counts.max() / max(avg, 1e-12):0.3f} "
        f"(min={counts.min()} avg={avg:0.1f} max={counts.max()})",
    ]
    total_cut = 0
    for m in range(tt.nmodes):
        # distinct (slice, part) pairs minus nonempty slices
        key = tt.inds[m].astype(np.int64) * nparts + parts
        pairs = np.unique(key).size
        nonempty = np.unique(tt.inds[m]).size
        cut = pairs - nonempty
        total_cut += cut
        lines.append(f"  mode {m}: connectivity-1 cut = {cut} "
                     f"(of {nonempty} slices)")
    lines.append(f"TOTAL-CUT={total_cut}")
    return "\n".join(lines)


def cpd_stats_text(bs_or_tt, rank: int, opts: Options) -> str:
    lines = [
        "Factoring ------------------------------------------",
        f"NFACTORS={rank} MAXITS={opts.max_iterations} TOL={opts.tolerance:0.1e} "
        f"REG={opts.regularization:0.1e} SEED={opts.seed()} THREADS=XLA",
    ]
    if isinstance(bs_or_tt, BlockedSparse):
        bs = bs_or_tt
        nlay = len(bs.layouts)
        lines.append(
            f"BLOCKED-ALLOC={bs.opts.block_alloc.value} NNZ-BLOCK={bs.opts.nnz_block} "
            f"LAYOUTS={nlay}")
        lines.append(f"BLOCKED-STORAGE={_human_bytes(bs.storage_bytes())}")
        for i, lay in enumerate(bs.layouts):
            if getattr(lay, "encoding", "v1") == "dense":
                # dense tile layouts have no blocks/segments/pad — the
                # tile geometry is the whole story (docs/dense.md)
                lines.append(
                    f"  layout[{i}]: mode={lay.mode} dense "
                    f"tiles={lay.ntiles}x{lay.block}x{lay.span} "
                    f"index_bytes=0")
                continue
            lines.append(
                f"  layout[{i}]: mode={lay.mode} nblocks={lay.nblocks} "
                f"seg_width={lay.seg_width} pad={lay.nnz_pad - lay.nnz}")
    return "\n".join(lines)
