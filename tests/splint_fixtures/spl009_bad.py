"""SPL009 bad: values derived from traced arguments escaping the
trace into long-lived state."""

import jax

TRACE_LOG = []

_LAST = None


class Model:
    @jax.jit
    def forward(self, x):
        self.last_input = x * 1.0  # tracer stored on self
        return x * 2


@jax.jit
def log_and_scale(x):
    TRACE_LOG.append(x * 2)  # tracer pushed into a global container
    return x * 3


@jax.jit
def stash(x):
    global _LAST
    _LAST = x + 1  # tracer assigned to module state
    return x
