"""SPL011 bad: inline IO on the shared cache file, bypassing the
locked read/write helpers."""

import json
import pathlib


def cache_path():
    return pathlib.Path("/tmp/spl011_fixture_cache.json")


def read_inline():
    with open(cache_path()) as f:  # bypasses _json_cache_load
        return json.load(f)


def write_inline(entry):
    p = cache_path()
    data = {"entry": entry}
    with open(p, "w") as f:  # unlocked read-modify-write: drops
        json.dump(data, f)   # concurrent writers' entries
