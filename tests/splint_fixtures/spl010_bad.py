"""SPL010 bad: recompile/retrace triggers — jit built per iteration,
a closure-captured device array, an unhashable static argument."""

import jax
import jax.numpy as jnp


def per_step_jit(xs):
    total = None
    for x in xs:
        step = jax.jit(lambda a: a * 2)  # fresh wrapper per iteration
        total = step(x) if total is None else total + step(x)
    return total


def captured_array(n):
    table = jnp.arange(n)

    @jax.jit
    def lookup(i):
        return table[i]  # device array baked into the trace

    return lookup


def unhashable_static(x):
    f = jax.jit(lambda a, cfg: a, static_argnums=(1,))
    return f(x, [1, 2, 3])  # list at a static argnum: TypeError
