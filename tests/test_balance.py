"""Load-balanced layouts (docs/layout-balance.md): nnz-balanced fiber
packing with long-fiber splitting, reorder recipes in production, the
skew-aware tuner axes, and the nnz-weighted distributed sharding.

Contract under test:
- parity of balanced / split / reordered layouts against the v1 fixed
  path across every engine (xla, xla_scan, interpret-Pallas): the
  scatter-family engines are BIT-identical (pads are additive
  identities appended in sorted order); the one-hot engines regroup
  block summation, so they match to accumulation tolerance;
- the balance contract: block budget respected, every nonzero placed
  exactly once, fill >= ~0.9 so max/mean real nnz per block <= ~1.1;
- degenerate inputs (one slice holding 50% of nnz, a single-fiber
  tensor, empty/tiny tensors);
- classified degrade drills: layout.pack -> fixed (packing_fallback),
  reorder.apply -> identity (reorder_fallback) — never a failed run;
- Permutation apply/undo round-trips on factors and checkpoints;
- tuner integration: packing/reorder candidates, strict plan match,
  whole-tensor reorder unanimity, skew-keyed regimes, demotion scope
  suffixes;
- balanced distributed sharding (fine + coarse) parity and the
  layout_imbalance evidence trail.
"""

import contextlib
import io
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import splatt_tpu.tune as tune
from splatt_tpu import resilience
from splatt_tpu.blocked import (BlockedSparse, build_layout,
                                nnz_skew_bucket, plan_balanced_blocks,
                                reencode_layout)
from splatt_tpu.config import (LayoutFormat, Options, Verbosity,
                               default_opts)
from splatt_tpu.coo import SparseTensor
from splatt_tpu.cpd import cpd_als, init_factors
from splatt_tpu.ops.mttkrp import (_engine_shape_key, _mttkrp_blocked_jit,
                                   _tuned_plan_for, mttkrp_blocked,
                                   mttkrp_stream)
from splatt_tpu.reorder import Permutation, apply_reorder, reorder
from splatt_tpu.utils import faults
from tests import gen


@pytest.fixture(autouse=True)
def _isolated(tmp_path, monkeypatch):
    monkeypatch.setenv(tune._CACHE_ENV, str(tmp_path / "tune_cache.json"))
    monkeypatch.setattr(resilience.time, "sleep", lambda s: None)
    tune.reset_memo()
    resilience.reset_demotions()
    resilience.run_report().clear()
    yield
    tune.reset_memo()
    resilience.reset_demotions()
    resilience.run_report().clear()
    faults.reset()


def _zipf_tensor(dims=(60, 44, 52), nnz=4000, a=1.5, seed=0):
    rng = np.random.default_rng(seed)
    inds = np.stack([(rng.zipf(a, nnz) - 1) % d for d in dims])
    return SparseTensor(inds, np.round(rng.random(nnz), 3) + 0.1, dims)


def _opts(**kw):
    kw.setdefault("random_seed", 42)
    kw.setdefault("verbosity", Verbosity.NONE)
    kw.setdefault("val_dtype", np.float64)
    kw.setdefault("use_pallas", False)
    kw.setdefault("autotune", False)
    return Options(**kw)


# -- the packer itself ------------------------------------------------------

def test_balanced_blocks_budget_and_coverage():
    """Every block holds <= B real nonzeros, the blocks tile the sorted
    stream exactly (no nonzero lost or duplicated), and the fill floor
    keeps max/mean real nnz per block <= ~1.1."""
    tt = _zipf_tensor()
    rows = np.sort(tt.inds[0])
    B = 256
    starts, counts, span = plan_balanced_blocks(rows, B, tt.dims[0])
    assert counts.max() <= B
    # exact tiling: consecutive, disjoint, covering
    assert starts[0] == 0
    assert np.all(starts[1:] == starts[:-1] + counts[:-1])
    assert starts[-1] + counts[-1] == rows.shape[0]
    assert counts.min() >= 1
    fill = rows.shape[0] / (len(counts) * B)
    assert fill >= 0.9  # the MIN_FILL contract: max/mean <= ~1.1
    assert counts.max() / counts.mean() <= 1.12
    assert span >= 1


def test_balanced_improves_span_on_skew():
    """On a zipf input the balanced layout's seg_width (and with it the
    one-hot work per nonzero) improves on the fixed slicing while the
    block-nnz balance stays within the ~1.1 contract."""
    tt = _zipf_tensor(dims=(120, 90, 100), nnz=12000, a=1.5)
    fixed = build_layout(tt, 0, block=512, record_stats=False)
    bal = build_layout(tt, 0, block=512, packing="balanced",
                       record_stats=False)
    assert bal.packing == "balanced" and bal.block_nnz is not None
    assert bal.seg_width <= fixed.seg_width
    # W=None is in the candidate set and IS the fixed slicing, so the
    # packer's cost (one-hot lanes + per-block overhead) never regresses
    cost_fixed = fixed.nblocks * (fixed.seg_width + 8)
    cost_bal = bal.nblocks * (bal.seg_width + 8)
    assert cost_bal <= cost_fixed
    counts = np.asarray(bal.block_nnz)
    assert counts.max() / counts.mean() <= 1.12


# -- engine parity ----------------------------------------------------------

ENGINES = ("xla", "xla_scan")


def _forced(layout, facs, mode, path, engine, impl="xla"):
    return np.asarray(_mttkrp_blocked_jit(layout, facs, mode, path, impl,
                                          1 << 21, engine))


def test_balanced_parity_every_engine():
    """Balanced vs fixed across engines: scatter paths bit-identical,
    one-hot paths within accumulation tolerance of the stream oracle,
    and the balanced layout bit-identical across ITS OWN engines."""
    tt = _zipf_tensor()
    facs = init_factors(tt.dims, 5, 1, dtype=jnp.float64)
    oracle = {m: np.asarray(mttkrp_stream(jnp.asarray(tt.inds),
                                          jnp.asarray(tt.vals), facs, m,
                                          tt.dims[m]))
              for m in range(tt.nmodes)}
    fixed = build_layout(tt, 0, block=256, val_dtype=np.float64,
                         record_stats=False)
    bal = build_layout(tt, 0, block=256, val_dtype=np.float64,
                       packing="balanced", record_stats=False)
    # scatter family: pads are additive identities in sorted order ->
    # bit parity with the fixed layout
    for path, mode in (("sorted_scatter", 0), ("scatter", 1),
                       ("scatter", 2)):
        a = _forced(fixed, facs, mode, path, "xla")
        b = _forced(bal, facs, mode, path, "xla")
        np.testing.assert_array_equal(a, b)
        np.testing.assert_allclose(b, oracle[mode], rtol=1e-10, atol=1e-10)
    # one-hot family: block regrouping changes summation association
    outs = {}
    for engine in ENGINES:
        outs[engine] = _forced(bal, facs, 0, "sorted_onehot", engine)
        np.testing.assert_allclose(outs[engine], oracle[0], rtol=1e-8,
                                   atol=1e-8)
    fx = _forced(fixed, facs, 0, "sorted_onehot", "xla")
    np.testing.assert_allclose(outs["xla"], fx, rtol=1e-8, atol=1e-8)


def test_balanced_parity_interpret_pallas():
    """The interpret-mode Pallas engines consume balanced layouts
    through the same decode contract."""
    tt = _zipf_tensor(dims=(48, 40, 44), nnz=2500)
    facs = init_factors(tt.dims, 4, 2, dtype=jnp.float32)
    bal = build_layout(tt, 0, block=256, val_dtype=np.float32,
                       packing="balanced", record_stats=False)
    want = _forced(bal, facs, 0, "sorted_onehot", "xla")
    got = _forced(bal, facs, 0, "sorted_onehot", "unfused_pallas",
                  impl="pallas_interpret")
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_balanced_v2_and_u8_bitexact():
    """The v2 compact encodings of a balanced layout (auto and u8
    segment ids) decode bit-identically to its v1 form, via direct
    build AND reencode."""
    tt = _zipf_tensor()
    facs = init_factors(tt.dims, 4, 3, dtype=jnp.float32)
    v1 = build_layout(tt, 0, block=256, val_dtype=np.float32,
                      packing="balanced", record_stats=False)
    want = _forced(v1, facs, 0, "sorted_onehot", "xla")
    for idx in ("auto", "u8"):
        direct = build_layout(tt, 0, block=256, val_dtype=np.float32,
                              packing="balanced", record_stats=False,
                              fmt=LayoutFormat(idx=idx))
        assert direct.encoding == "v2" and direct.packing == "balanced"
        re = reencode_layout(v1, LayoutFormat(idx=idx))
        assert re.packing == "balanced" and re.block_nnz is not None
        for lay in (direct, re):
            for engine in ENGINES:
                got = _forced(lay, facs, 0, "sorted_onehot", engine)
                np.testing.assert_array_equal(
                    got, _forced(v1, facs, 0, "sorted_onehot", engine))
            np.testing.assert_array_equal(
                _forced(lay, facs, 1, "scatter", "xla"),
                _forced(v1, facs, 1, "scatter", "xla"))
        assert want is not None


# -- degenerate inputs ------------------------------------------------------

def test_hot_slice_long_fiber_split():
    """One slice holding 50% of all nonzeros: the hot fiber is split
    across blocks (span 1 each), the result matches the oracle, and
    seg_width collapses versus the fixed slicing."""
    rng = np.random.default_rng(5)
    dims = (80, 50, 60)
    nnz = 6000
    hot = nnz // 2
    i0 = np.concatenate([np.full(hot, 7), rng.integers(0, 80, nnz - hot)])
    inds = np.stack([i0, rng.integers(0, 50, nnz),
                     rng.integers(0, 60, nnz)])
    tt = SparseTensor(inds, rng.random(nnz), dims)
    bal = build_layout(tt, 0, block=256, val_dtype=np.float64,
                       packing="balanced", record_stats=False)
    counts = np.asarray(bal.block_nnz)
    # the hot fiber alone fills >= hot // 256 whole blocks
    full = int((counts == 256).sum())
    assert full >= hot // 256
    facs = init_factors(dims, 4, 0, dtype=jnp.float64)
    got = _forced(bal, facs, 0, "sorted_onehot", "xla")
    want = np.asarray(mttkrp_stream(jnp.asarray(tt.inds),
                                    jnp.asarray(tt.vals), facs, 0,
                                    dims[0]))
    np.testing.assert_allclose(got, want, rtol=1e-8, atol=1e-8)


def test_single_fiber_tensor():
    """Every nonzero in one slice: balanced packing is pure splitting
    — span 1, minimal seg_width — and still exact."""
    rng = np.random.default_rng(6)
    nnz = 900
    dims = (10, 30, 40)
    inds = np.stack([np.full(nnz, 3), rng.integers(0, 30, nnz),
                     rng.integers(0, 40, nnz)])
    tt = SparseTensor(inds, rng.random(nnz), dims)
    bal = build_layout(tt, 0, block=128, val_dtype=np.float64,
                       packing="balanced", record_stats=False)
    assert bal.seg_width == 8  # span 1, rounded to the sublane
    facs = init_factors(dims, 3, 0, dtype=jnp.float64)
    got = _forced(bal, facs, 0, "sorted_onehot", "xla")
    want = np.asarray(mttkrp_stream(jnp.asarray(tt.inds),
                                    jnp.asarray(tt.vals), facs, 0,
                                    dims[0]))
    np.testing.assert_allclose(got, want, rtol=1e-8, atol=1e-8)


def test_empty_and_tiny_tensors():
    tt0 = SparseTensor(np.zeros((3, 0), dtype=np.int64),
                       np.zeros(0), (4, 5, 6))
    lay = build_layout(tt0, 0, block=256, packing="balanced",
                       record_stats=False)
    assert lay.packing == "fixed"  # nothing to balance: degrades clean
    tt1 = SparseTensor(np.array([[1], [2], [3]]), np.array([2.0]),
                       (4, 5, 6))
    lay1 = build_layout(tt1, 0, block=256, packing="balanced",
                        record_stats=False)
    facs = init_factors((4, 5, 6), 3, 0, dtype=jnp.float64)
    got = _forced(lay1, facs, 0, "sorted_onehot", "xla")
    want = np.asarray(mttkrp_stream(jnp.asarray(tt1.inds),
                                    jnp.asarray(tt1.vals), facs, 0, 4))
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10)


# -- classified degrade drills ----------------------------------------------

def test_packing_fault_degrades_classified():
    """A crashing balanced pack (the layout.pack fault site) degrades
    the BUILD to the fixed slicing with a packing_fallback event —
    never a failed run."""
    tt = _zipf_tensor()
    with faults.inject("layout.pack", "runtime", times=1):
        lay = build_layout(tt, 0, block=256, packing="balanced",
                           record_stats=False)
    assert lay.packing == "fixed" and lay.block_nnz is None
    evs = resilience.run_report().events("packing_fallback")
    assert evs and evs[0]["failure_class"]
    assert any("balanced fiber pack failed" in ln
               for ln in resilience.run_report().summary())
    # the degraded layout still computes
    facs = init_factors(tt.dims, 3, 0, dtype=jnp.float64)
    assert np.isfinite(_forced(lay, facs, 0, "sorted_scatter",
                               "xla")).all()


def test_reorder_fault_degrades_to_identity():
    """Chaos drill: a crashing reorder.apply degrades CLASSIFIED to
    identity order (reorder_fallback event) and the CPD still
    converges — the acceptance drill of docs/layout-balance.md."""
    tt = _zipf_tensor()
    opts = _opts(reorder="hgraph", max_iterations=4, tolerance=0.0)
    with faults.inject("reorder.apply", "runtime", times=1):
        bs = BlockedSparse.compile(tt, opts, rank=3)
    assert bs.reorder == "identity" and bs.perm is None
    assert all(l.reorder == "identity" for l in bs.layouts)
    evs = resilience.run_report().events("reorder_fallback")
    assert evs and evs[0]["how"] == "hgraph" and evs[0]["failure_class"]
    assert any("degraded to identity order" in ln
               for ln in resilience.run_report().summary())
    out = cpd_als(bs, 3, opts=opts)
    assert np.isfinite(float(out.fit))


# -- reorder round-trips ----------------------------------------------------

def test_permutation_factor_roundtrip():
    tt = _zipf_tensor()
    perm = reorder(tt, "hgraph")
    U = [np.asarray(u) for u in init_factors(tt.dims, 4, 0)]
    fwd = [perm.permute_factor(u, m) for m, u in enumerate(U)]
    back = perm.undo_factors(fwd)
    for a, b in zip(back, U):
        np.testing.assert_array_equal(np.asarray(a), b)
    # undo really relabels: a non-identity mode moves rows
    assert any(not np.array_equal(np.asarray(f), u)
               for f, u in zip(fwd, U))


@pytest.mark.parametrize("how", ["hgraph", "fibsched", "graph"])
def test_reordered_cpd_matches_identity(how):
    """CPD over a reordered+balanced BlockedSparse returns factors in
    ORIGINAL row order (Permutation.undo on output), matching the
    unreordered run to iteration tolerance."""
    tt = _zipf_tensor(dims=(30, 24, 28), nnz=1500, seed=3)
    init = init_factors(tt.dims, 3, 7)
    base_opts = _opts(max_iterations=6, tolerance=0.0, val_dtype=np.float64)
    ref = cpd_als(BlockedSparse.compile(tt, base_opts, rank=3), 3,
                  opts=base_opts, init=init)
    ro = _opts(max_iterations=6, tolerance=0.0, val_dtype=np.float64,
               reorder=how, fiber_packing="balanced")
    bs = BlockedSparse.compile(tt, ro, rank=3)
    assert bs.reorder == how and bs.perm is not None
    assert all(l.reorder == how for l in bs.layouts)
    out = cpd_als(bs, 3, opts=ro, init=init)
    assert abs(float(out.fit) - float(ref.fit)) < 1e-6
    for m in range(tt.nmodes):
        np.testing.assert_allclose(np.asarray(out.factors[m]),
                                   np.asarray(ref.factors[m]),
                                   rtol=1e-5, atol=1e-6)


def test_checkpoint_reorder_mismatch_degrades_to_fresh(tmp_path):
    """A checkpoint written in one reorder row space must NOT be
    resumed under another recipe: the loader refuses (CheckpointError
    on the direct path) and the resilient resume degrades to a fresh
    start with a checkpoint_recovery event — never silently permuted
    factors."""
    from splatt_tpu.cpd import (CheckpointError, load_checkpoint,
                                load_checkpoint_resilient)

    tt = _zipf_tensor(dims=(30, 24, 28), nnz=1500, seed=5)
    init = init_factors(tt.dims, 3, 7)
    ro = _opts(max_iterations=3, tolerance=0.0, val_dtype=np.float64,
               reorder="hgraph")
    ck = str(tmp_path / "ck.npz")
    cpd_als(BlockedSparse.compile(tt, ro, rank=3), 3, opts=ro, init=init,
            checkpoint_path=ck, checkpoint_every=3)
    # same recipe: loads fine; other recipe (incl. identity): refused
    load_checkpoint(ck, expect_reorder="hgraph")
    with pytest.raises(CheckpointError, match="row space"):
        load_checkpoint(ck, expect_reorder="identity")
    resilience.run_report().clear()
    assert load_checkpoint_resilient(ck, expect_reorder="graph") is None
    assert resilience.run_report().events("checkpoint_recovery")
    # end-to-end: an identity-order resume over the stale reordered
    # checkpoint starts fresh and still matches the reference run
    base = _opts(max_iterations=3, tolerance=0.0, val_dtype=np.float64)
    ref = cpd_als(BlockedSparse.compile(tt, base, rank=3), 3, opts=base,
                  init=init)
    res = cpd_als(BlockedSparse.compile(tt, base, rank=3), 3, opts=base,
                  init=init, checkpoint_path=ck)
    assert abs(float(res.fit) - float(ref.fit)) < 1e-6


def test_reordered_checkpoint_resume_roundtrip(tmp_path):
    """Checkpoints written mid-run live in RELABELED space; a resume
    under the same recipe continues them, and the final output is back
    in original row order — equal to the uninterrupted run."""
    tt = _zipf_tensor(dims=(30, 24, 28), nnz=1500, seed=4)
    init = init_factors(tt.dims, 3, 7)

    def opts(iters):
        return _opts(max_iterations=iters, tolerance=0.0,
                     val_dtype=np.float64, reorder="hgraph")

    full = cpd_als(BlockedSparse.compile(tt, opts(6), rank=3), 3,
                   opts=opts(6), init=init)
    ck = str(tmp_path / "ck.npz")
    cpd_als(BlockedSparse.compile(tt, opts(3), rank=3), 3, opts=opts(3),
            init=init, checkpoint_path=ck, checkpoint_every=3)
    resumed = cpd_als(BlockedSparse.compile(tt, opts(6), rank=3), 3,
                      opts=opts(6), init=init, checkpoint_path=ck,
                      checkpoint_every=3)
    assert abs(float(resumed.fit) - float(full.fit)) < 1e-6
    for m in range(tt.nmodes):
        np.testing.assert_allclose(np.asarray(resumed.factors[m]),
                                   np.asarray(full.factors[m]),
                                   rtol=1e-5, atol=1e-6)


# -- tuner integration ------------------------------------------------------

def test_tune_measures_packing_and_reorder():
    tt = gen.fixture_tensor("med")
    res = tune.tune(tt, 3, opts=_opts(autotune=True), blocks=(512,),
                    scan_targets=(1 << 21,), formats=[("i32", "auto")],
                    packings=("fixed", "balanced"),
                    reorders=("identity", "hgraph"), reps=1)
    assert res.measured > 0
    for p in res.plans.values():
        assert p.packing in ("fixed", "balanced")
        assert p.reorder in ("identity", "hgraph")


def test_pinned_packing_and_reorder_measured_alone():
    tt = gen.fixture_tensor("med")
    opts = _opts(autotune=True, fiber_packing="balanced",
                 reorder="identity")
    res = tune.tune(tt, 3, opts=opts, modes=(0,), blocks=(512,),
                    scan_targets=(1 << 21,), formats=[("i32", "auto")],
                    reps=1)
    assert res.plans[0].packing == "balanced"
    assert res.plans[0].reorder == "identity"


def test_plan_strict_match_on_packing_and_reorder():
    """A plan measured under one (packing, reorder) never steers a
    layout built under another."""
    import dataclasses

    tt = gen.fixture_tensor("med")
    facs = init_factors(tt.dims, 4, 0, dtype=jnp.float64)
    plan = tune.TunedPlan(path="sorted_scatter", engine="xla",
                          nnz_block=512, scan_target=1 << 21, sec=0.001,
                          packing="balanced", reorder="identity")
    tune._entry_store(tune.plan_key(tt.dims, tt.nnz, 0, 4, jnp.float64,
                                    skew=tune.skew_of(tt, 0)),
                      {"plan": dataclasses.asdict(plan)})
    fixed = build_layout(tt, 0, block=512, val_dtype=np.float64,
                         record_stats=False)
    bal = build_layout(tt, 0, block=512, val_dtype=np.float64,
                       packing="balanced", record_stats=False)
    assert _tuned_plan_for(fixed, facs, 0, "sorted_scatter",
                           autotune=True) is None
    assert _tuned_plan_for(bal, facs, 0, "sorted_scatter",
                           autotune=True) is not None
    ro = build_layout(tt, 0, block=512, val_dtype=np.float64,
                      packing="balanced", reorder_label="hgraph",
                      record_stats=False)
    assert _tuned_plan_for(ro, facs, 0, "sorted_scatter",
                           autotune=True) is None


def test_compile_reorder_unanimity_and_drop():
    """Mixed tuned reorder verdicts: compile resolves identity and
    drops the non-conforming plans WHOLE with tuner_degraded."""
    import dataclasses

    tt = gen.fixture_tensor("med")
    mk = dict(path="sorted_scatter", engine="xla", scan_target=1 << 21,
              sec=0.001, idx_width="i32", val_storage="auto")
    plans = {0: tune.TunedPlan(nnz_block=512, reorder="hgraph", **mk),
             1: tune.TunedPlan(nnz_block=1024, reorder="identity", **mk),
             2: tune.TunedPlan(nnz_block=1024, reorder="identity", **mk)}
    for m, p in plans.items():
        tune._entry_store(
            tune.plan_key(tt.dims, tt.nnz, m, 4, jnp.float64,
                          skew=tune.skew_of(tt, m)),
            {"plan": dataclasses.asdict(p)})
    from splatt_tpu.config import BlockAlloc

    bs = BlockedSparse.compile(
        tt, _opts(autotune=True, block_alloc=BlockAlloc.ALLMODE), rank=4)
    assert bs.reorder == "identity" and bs.perm is None
    # mode 0's hgraph plan was dropped whole: default block applies
    assert bs.layout_for(0).block != 512
    assert bs.layout_for(1).block == 1024
    assert resilience.run_report().events("tuner_degraded")


def test_compile_pinned_packing_beats_cached_plan():
    """An explicitly pinned fiber_packing wins over a stale cached
    tuned verdict (the val_storage/reorder precedence): disagreeing
    plans are dropped WHOLE with tuner_degraded, and the build honors
    the pin."""
    import dataclasses

    tt = gen.fixture_tensor("med")
    mk = dict(path="sorted_scatter", engine="xla", scan_target=1 << 21,
              sec=0.001, idx_width="i32", val_storage="auto",
              packing="balanced")
    for m in range(tt.nmodes):
        tune._entry_store(
            tune.plan_key(tt.dims, tt.nnz, m, 4, jnp.float64,
                          skew=tune.skew_of(tt, m)),
            {"plan": dataclasses.asdict(
                tune.TunedPlan(nnz_block=512, **mk))})
    from splatt_tpu.config import BlockAlloc

    # unpinned: the cached balanced verdict applies
    bs = BlockedSparse.compile(
        tt, _opts(autotune=True, block_alloc=BlockAlloc.ALLMODE), rank=4)
    assert all(l.packing == "balanced" for l in bs.layouts)
    resilience.run_report().clear()
    # pinned fixed: every balanced plan is dropped whole, build is fixed
    bs = BlockedSparse.compile(
        tt, _opts(autotune=True, block_alloc=BlockAlloc.ALLMODE,
                  fiber_packing="fixed"), rank=4)
    assert all(l.packing == "fixed" for l in bs.layouts)
    assert all(l.block != 512 for l in bs.layouts)
    assert resilience.run_report().events("tuner_degraded")


def test_compile_applies_unanimous_reorder():
    import dataclasses

    tt = gen.fixture_tensor("med")
    mk = dict(path="sorted_scatter", engine="xla", scan_target=1 << 21,
              sec=0.001, idx_width="i32", val_storage="auto",
              packing="balanced", reorder="hgraph")
    for m in range(tt.nmodes):
        tune._entry_store(
            tune.plan_key(tt.dims, tt.nnz, m, 4, jnp.float64,
                          skew=tune.skew_of(tt, m)),
            {"plan": dataclasses.asdict(
                tune.TunedPlan(nnz_block=512, **mk))})
    from splatt_tpu.config import BlockAlloc

    bs = BlockedSparse.compile(
        tt, _opts(autotune=True, block_alloc=BlockAlloc.ALLMODE), rank=4)
    assert bs.reorder == "hgraph" and bs.perm is not None
    assert all(l.packing == "balanced" and l.reorder == "hgraph"
               for l in bs.layouts)


def test_compile_reorder_degrade_drops_measured_plans():
    """When apply_reorder degrades classified to identity inside
    compile, plans MEASURED under the failed recipe are dropped WHOLE
    (tuner_degraded) — never half-built at identity order in a
    configuration the tuner never measured."""
    import dataclasses

    tt = gen.fixture_tensor("med")
    mk = dict(path="sorted_scatter", engine="xla", scan_target=1 << 21,
              sec=0.001, idx_width="i32", val_storage="auto",
              packing="fixed", reorder="hgraph")
    for m in range(tt.nmodes):
        tune._entry_store(
            tune.plan_key(tt.dims, tt.nnz, m, 4, jnp.float64,
                          skew=tune.skew_of(tt, m)),
            {"plan": dataclasses.asdict(
                tune.TunedPlan(nnz_block=512, **mk))})
    from splatt_tpu.config import BlockAlloc

    resilience.run_report().clear()
    with faults.inject("reorder.apply", "runtime", times=1):
        bs = BlockedSparse.compile(
            tt, _opts(autotune=True, block_alloc=BlockAlloc.ALLMODE),
            rank=4)
    assert bs.reorder == "identity" and bs.perm is None
    assert all(l.reorder == "identity" for l in bs.layouts)
    # the hgraph-measured plans went with the recipe: default block
    assert all(l.block != 512 for l in bs.layouts)
    assert resilience.run_report().events("reorder_fallback")
    assert resilience.run_report().events("tuner_degraded")


def test_skew_regime_keys():
    """Uniform buckets collapse ("" — legacy keys byte-identical);
    heavy skew keys its own regime; the bucket is permutation-
    invariant."""
    assert tune.skew_regime("k1") == "" and tune.skew_regime("") == ""
    assert tune.skew_regime("k6") == "k6"
    legacy = tune.plan_key((64, 64, 64), 4096, 0, 8, jnp.float32)
    assert tune.plan_key((64, 64, 64), 4096, 0, 8, jnp.float32,
                         skew="k2") == legacy
    assert tune.plan_key((64, 64, 64), 4096, 0, 8, jnp.float32,
                         skew="k6") != legacy
    tt = _zipf_tensor()
    tt2, perm = apply_reorder(tt, "hgraph")
    assert perm is not None
    for m in range(tt.nmodes):
        assert tune.skew_of(tt, m) == tune.skew_of(tt2, m)
    # and a genuinely skewed tensor classifies above the uniform band
    assert nnz_skew_bucket(tt.mode_histogram(0)) not in ("k0", "k1")


def test_shape_key_suffixes_scope_demotions():
    tt = gen.fixture_tensor("med")
    facs = init_factors(tt.dims, 3, 0, dtype=jnp.float64)
    fixed = build_layout(tt, 0, block=512, val_dtype=np.float64,
                         record_stats=False)
    bal = build_layout(tt, 0, block=512, val_dtype=np.float64,
                       packing="balanced", record_stats=False)
    ro = build_layout(tt, 0, block=512, val_dtype=np.float64,
                      packing="balanced", reorder_label="graph",
                      record_stats=False)
    k_fixed = _engine_shape_key(fixed, facs, 0)
    k_bal = _engine_shape_key(bal, facs, 0)
    k_ro = _engine_shape_key(ro, facs, 0)
    assert ":bal" not in k_fixed and ":ro" not in k_fixed
    assert k_bal == k_fixed + ":bal"
    assert k_ro == k_fixed + ":bal:ro"
    # an OOM-style demotion under the balanced scope never touches the
    # fixed layout's dispatch
    resilience.demote_engine("xla_scan", MemoryError("OOM"),
                             shape_key=k_bal)
    assert resilience.is_demoted("xla_scan", k_bal)
    assert not resilience.is_demoted("xla_scan", k_fixed)


# -- imbalance evidence -----------------------------------------------------

def test_layout_imbalance_event_recorded():
    tt = _zipf_tensor()
    BlockedSparse.from_coo(tt, _opts(fiber_packing="balanced"))
    evs = resilience.run_report().events("layout_imbalance")
    assert evs
    e = evs[0]
    for k in ("packing", "block_nnz_max_mean", "span_max_mean",
              "work_amp", "seg_width", "slice_max_mean"):
        assert k in e, k
    assert e["packing"] == "balanced"


def test_blockedsparse_imbalance_summary():
    tt = _zipf_tensor()
    bs = BlockedSparse.from_coo(tt, _opts(fiber_packing="balanced"))
    imb = bs.imbalance()
    for d in imb.values():
        assert d["packing"] == "balanced"
        assert d["block_nnz_max_mean"] <= 1.15
        assert d["work_amp"] > 0


def test_skew_stats_distinguish_uniform_from_powerlaw():
    from splatt_tpu.stats import skew_stats, skew_stats_text

    rng = np.random.default_rng(0)
    uni = SparseTensor(np.stack([rng.integers(0, d, 4000)
                                 for d in (60, 44, 52)]),
                       rng.random(4000), (60, 44, 52))
    zipf = _zipf_tensor()
    su, sz = skew_stats(uni), skew_stats(zipf)
    for m in ("0", "1", "2"):
        assert sz["modes"][m]["max_mean"] > su["modes"][m]["max_mean"]
        assert sz["modes"][m]["p99_median"] >= su["modes"][m]["p99_median"]
    assert "fiber_max_mean" in sz
    txt = skew_stats_text(zipf)
    assert "max/mean" in txt and "top-slice" in txt


# -- distributed balanced sharding ------------------------------------------

@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs >=4 devices")
@pytest.mark.parametrize("decomp", ["fine", "coarse"])
def test_balanced_rowdist_parity_and_evidence(decomp):
    """row_distribute='balanced' (fine + coarse): same factors as the
    plain run, with layout_imbalance evidence carrying the policy."""
    from splatt_tpu.config import Decomposition
    from splatt_tpu.parallel import distributed_cpd_als

    tt = _zipf_tensor(dims=(64, 48, 56), nnz=3000, seed=2)
    init = init_factors(tt.dims, 3, 7)

    def run(rowdist):
        resilience.run_report().clear()
        o = Options(random_seed=3, max_iterations=4, tolerance=0.0,
                    verbosity=Verbosity.NONE, autotune=False,
                    decomposition=Decomposition(decomp))
        out = distributed_cpd_als(tt, 3, opts=o, init=init,
                                  row_distribute=rowdist)
        return out, resilience.run_report().events("layout_imbalance")

    plain, _ = run(None)
    bal, evs = run("balanced")
    assert evs and any(e.get("policy") == "balanced" for e in evs)
    assert abs(float(plain.fit) - float(bal.fit)) < 1e-4
    for m in range(tt.nmodes):
        np.testing.assert_allclose(np.asarray(bal.factors[m]),
                                   np.asarray(plain.factors[m]),
                                   rtol=5e-3, atol=1e-4)


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs >=4 devices")
def test_balanced_rowdist_improves_fence_balance():
    from splatt_tpu.parallel.common import balanced_relabel

    tt = _zipf_tensor(dims=(64, 48, 56), nnz=4000, seed=0)
    ndev = len(jax.devices())
    for m in range(tt.nmodes):
        dim_pad = -(-tt.dims[m] // ndev) * ndev
        cap = dim_pad // ndev
        hist = tt.mode_histogram(m)

        def fence_ratio(labels):
            w = np.zeros(ndev, dtype=np.int64)
            np.add.at(w, labels // cap, hist)
            return w.max() / max(w.mean(), 1e-12)

        plain = fence_ratio(np.arange(tt.dims[m]))
        bal = fence_ratio(balanced_relabel(hist, ndev, cap))
        assert bal <= plain + 1e-9


# -- bench integration ------------------------------------------------------

def test_bench_balance_gate_leg():
    """The --gate comparison flags a work-amplification inflation on
    the balance:<path> leg exactly like a bytes inflation."""
    import bench

    base = {"metric": "m", "value": 1.0, "unit": "sec/iter",
            "imbalance": {"per_path": {"balanced": {"work_amp": 100.0}}}}
    worse = {"metric": "m", "value": 1.0, "unit": "sec/iter",
             "imbalance": {"per_path": {"balanced": {"work_amp": 130.0}}}}
    regs = bench._bench_regressions(worse, base)
    assert any(r["path"] == "balance:balanced" for r in regs)
    assert not bench._bench_regressions(base, base)


def test_bench_guard_ab_legs():
    """The guard A/B helper measures all four legs (health sentinel
    on/off x donation on/off) on a real cpd_als run."""
    import bench

    tt = _zipf_tensor(dims=(24, 20, 22), nnz=800, seed=1)
    from splatt_tpu.config import BlockAlloc

    legs = bench._guard_ab_legs(tt, 3, 2, jnp.float32, False,
                                BlockAlloc.TWOMODE)
    for retries in ("on", "off"):
        for donate in ("on", "off"):
            key = f"guard_{retries}:donate_{donate}"
            assert key in legs
            assert legs[key] is None or legs[key] >= 0.0


def test_bench_scenarios_generate():
    import bench

    tt, desc, label = bench.scenario_tensor("zipf:1.5", "nell2", 2000, 0)
    assert label == "zipf1.5" and "zipf1.5" in desc
    assert tt.nnz == 2000
    tt2, desc2, label2 = bench.scenario_tensor("amazon-like", "nell2",
                                               2000, 0)
    assert label2 == "amazon-like" and tt2.dims == \
        bench.SCENARIO_SHAPES["amazon-like"]
    tt3, desc3, label3 = bench.scenario_tensor("uniform", "nell2", 2000, 0)
    assert label3 is None and desc3 == "NELL-2-shaped"
    with pytest.raises(ValueError):
        bench.scenario_tensor("zipf:0.5", "nell2", 100, 0)
    with pytest.raises(ValueError):
        bench.scenario_tensor("bogus", "nell2", 100, 0)
    # the zipf generator is genuinely skewed where the uniform one
    # is not (its hash-scatter destroys the head)
    from splatt_tpu.stats import skew_stats

    z = skew_stats(tt)["modes"]["0"]["max_mean"]
    u = skew_stats(tt3)["modes"]["0"]["max_mean"]
    assert z > 4 * u
