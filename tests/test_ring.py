"""Ring (point-to-point) communication variant tests.

≙ the reference testing its POINT2POINT row-exchange variant against
ALL2ALL semantics — both must give identical math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from splatt_tpu.utils.env import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P
from functools import partial

from splatt_tpu.config import CommPattern, Options, Verbosity
from splatt_tpu.cpd import cpd_als, init_factors
from splatt_tpu.parallel.mesh import make_mesh
from splatt_tpu.parallel.ring import blockwise_reduce_rows, ring_gather_rows
from splatt_tpu.parallel.sharded import sharded_cpd_als
from tests import gen


def _opts(**kw):
    kw.setdefault("random_seed", 42)
    kw.setdefault("verbosity", Verbosity.NONE)
    kw.setdefault("val_dtype", np.float64)
    return Options(**kw)


def test_ring_gather_rows_unit():
    """ring gather == plain gather of the full matrix."""
    ndev = 8
    mesh = make_mesh(n_devices=ndev)
    rng = np.random.default_rng(0)
    dim_pad, R, nnz = 40, 6, 64
    U = jnp.asarray(rng.random((dim_pad, R)))
    idx = jnp.asarray(rng.integers(0, dim_pad, size=nnz).astype(np.int32))
    U_s = jax.device_put(U, NamedSharding(mesh, P("nnz", None)))

    @partial(shard_map, mesh=mesh, in_specs=(P("nnz", None), P(None)),
             out_specs=P(None), check_vma=False)
    def run(U_l, idx_rep):
        return ring_gather_rows(U_l, idx_rep, "nnz", ndev)

    got = run(U_s, idx)
    np.testing.assert_allclose(np.asarray(got), np.asarray(U)[np.asarray(idx)],
                               atol=1e-12)


def test_blockwise_reduce_rows_unit():
    """blockwise ring reduce == segment_sum + manual row split."""
    ndev = 4
    mesh = make_mesh(n_devices=ndev)
    rng = np.random.default_rng(1)
    dim_pad, R = 16, 3
    block = dim_pad // ndev
    nnz_per_dev = 32
    prod = rng.random((ndev * nnz_per_dev, R))
    idx = rng.integers(0, dim_pad, size=ndev * nnz_per_dev).astype(np.int32)
    prod_s = jax.device_put(jnp.asarray(prod),
                            NamedSharding(mesh, P("nnz", None)))
    idx_s = jax.device_put(jnp.asarray(idx), NamedSharding(mesh, P("nnz")))

    @partial(shard_map, mesh=mesh, in_specs=(P("nnz", None), P("nnz")),
             out_specs=P("nnz", None), check_vma=False)
    def run(prod_l, idx_l):
        return blockwise_reduce_rows(prod_l, idx_l, "nnz", ndev, block)

    got = np.asarray(run(prod_s, idx_s))
    want = np.zeros((dim_pad, R))
    np.add.at(want, idx, prod)
    np.testing.assert_allclose(got, want, atol=1e-12)


def test_ring_cpd_matches_all2all():
    tt = gen.fixture_tensor("med")
    mesh = make_mesh(n_devices=8)
    init = init_factors(tt.dims, 5, 42, dtype=jnp.float64)
    a = sharded_cpd_als(tt, rank=5, mesh=mesh, init=init,
                        opts=_opts(max_iterations=6,
                                   comm_pattern=CommPattern.ALL2ALL))
    b = sharded_cpd_als(tt, rank=5, mesh=mesh, init=init,
                        opts=_opts(max_iterations=6,
                                   comm_pattern=CommPattern.POINT2POINT))
    assert float(b.fit) == pytest.approx(float(a.fit), abs=1e-9)
    for fa, fb in zip(a.factors, b.factors):
        np.testing.assert_allclose(np.asarray(fa), np.asarray(fb), atol=1e-8)


def test_ring_cpd_matches_single_device():
    tt = gen.fixture_tensor("med4")
    init = init_factors(tt.dims, 4, 42, dtype=jnp.float64)
    single = cpd_als(tt, rank=4, opts=_opts(max_iterations=5), init=init)
    ring = sharded_cpd_als(tt, rank=4, mesh=make_mesh(n_devices=4),
                           init=init,
                           opts=_opts(max_iterations=5,
                                      comm_pattern=CommPattern.POINT2POINT))
    assert float(ring.fit) == pytest.approx(float(single.fit), abs=1e-8)
