"""Command-line interface (≙ src/cmds/: the `splatt` binary).

Verbs mirror splatt_cmds.h:77-92: cpd, bench, check, convert, reorder,
stats.  Invoke as ``python -m splatt_tpu.cli <verb> ...`` or via the
``splatt-tpu`` console entry.

Example (≙ `splatt cpd mytensor.tns -r 16 -v`):

    python -m splatt_tpu.cli cpd mytensor.tns -r 16 -v
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

import numpy as np

from splatt_tpu.reorder import PERM_TYPES
from splatt_tpu.utils.env import apply_compile_cache, apply_env_platform

apply_env_platform()


def _positive_int(s: str) -> int:
    v = int(s)
    if v < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
    return v


def _common_opts(p: argparse.ArgumentParser) -> None:
    p.add_argument("tensor", help="coordinate tensor file (.tns/.bin)")
    p.add_argument("-v", "--verbose", action="count", default=0,
                   help="increase verbosity (repeatable)")


def _trace_opt(p: argparse.ArgumentParser) -> None:
    p.add_argument("--trace", metavar="OUT_JSON",
                   help="record structured spans for this run and "
                        "export a perfetto-loadable Chrome trace-event "
                        "JSON file on exit (docs/observability.md); "
                        "summarize it with `splatt trace OUT_JSON`")


def _build_opts(args) -> "Options":
    from splatt_tpu.config import BlockAlloc, Options, Verbosity

    opts = Options()
    opts.verbosity = Verbosity(min(1 + getattr(args, "verbose", 0), 3))
    if getattr(args, "tol", None) is not None:
        opts.tolerance = args.tol
    if getattr(args, "iters", None) is not None:
        opts.max_iterations = args.iters
    if getattr(args, "reg", None) is not None:
        opts.regularization = args.reg
    if getattr(args, "seed", None) is not None:
        opts.random_seed = args.seed
    if getattr(args, "alloc", None):
        opts.block_alloc = BlockAlloc(args.alloc)
    if getattr(args, "block", None):
        opts.nnz_block = args.block
    if getattr(args, "f64", False):
        opts.val_dtype = np.dtype(np.float64)  # splint: ignore[SPL005] the --f64 flag IS the user-facing dtype contract
    if getattr(args, "mode_order", None):
        from splatt_tpu.config import ModeOrder
        opts.mode_order = ModeOrder(args.mode_order)
    if getattr(args, "engine_fallback", None):
        opts.engine_fallback = args.engine_fallback == "on"
    if getattr(args, "autotune", None):
        opts.autotune = args.autotune == "on"
    return opts


def _resilience_record(report, **extra) -> dict:
    """The machine-readable run summary for --json verbs: final fit,
    every run-report event (health rollbacks included) and every
    engine demotion — the same facts the human summary prints."""
    from splatt_tpu import resilience

    return dict(
        extra,
        degraded=bool(report.events("health_degraded")),
        events=[{k: v for k, v in e.items() if k != "ts"}
                for e in report.events()],
        demotions=[dict(engine=d.engine,
                        failure_class=d.failure_class.value,
                        shape_key=d.shape_key, error=d.error[:120])
                   for d in resilience.demotions()])


def cmd_cpd(args) -> int:
    """≙ splatt_cpd_cmd (src/cmds/cmd_cpd.c:159-243; distributed flags ≙
    the mpirun variant's -d, src/cmds/mpi_cmd_cpd.c:175-338)."""
    import jax

    from splatt_tpu.blocked import BlockedSparse
    from splatt_tpu.config import CommPattern, Decomposition, Verbosity
    from splatt_tpu.cpd import cpd_als
    from splatt_tpu.io import load, read_permutation
    from splatt_tpu.stats import cpd_stats_text, tensor_stats
    from splatt_tpu.utils.timers import timers

    opts = _build_opts(args)
    if getattr(args, "comm", None):
        opts.comm_pattern = CommPattern(args.comm)
    timers.start("total")
    with timers.time("io"):
        if args.mmap:
            from splatt_tpu.io import load_memmap

            tt = load_memmap(args.tensor)
        else:
            tt = load(args.tensor)
    print(tensor_stats(tt, args.tensor))

    distributed = (args.decomp is not None or args.grid is not None
                   or args.partition is not None or args.comm is not None
                   or args.rowdist is not None)
    if distributed:
        from splatt_tpu.parallel import distributed_cpd_als

        if args.decomp:
            opts.decomposition = Decomposition(args.decomp)
        elif args.grid:
            opts.decomposition = Decomposition.MEDIUM
        elif args.comm or args.partition or args.rowdist:
            # comm patterns, partitions and row distribution are
            # fine-decomposition concepts
            opts.decomposition = Decomposition.FINE
        if args.partition and opts.decomposition is not Decomposition.FINE:
            raise ValueError(
                "-p/--partition is a FINE-decomposition input; combine it "
                f"with --decomp fine, not {opts.decomposition.value}")
        if (args.comm in ("point2point", "async_ring")
                and opts.decomposition is not Decomposition.FINE):
            raise ValueError(
                f"--comm {args.comm} (ring) applies to the fine "
                f"decomposition only")
        if args.grid and opts.decomposition is not Decomposition.MEDIUM:
            raise ValueError(
                "--grid applies to the medium decomposition only")
        grid = None
        if args.grid:
            grid = tuple(int(g) for g in args.grid.split("x"))
            if len(grid) != tt.nmodes or any(g < 1 for g in grid):
                raise ValueError(
                    f"--grid must give one positive factor per mode "
                    f"({tt.nmodes} modes), got {args.grid!r}")
        partition = (read_permutation(args.partition)
                     if args.partition else None)
        print(f"DISTRIBUTED decomp={opts.decomposition.value} "
              f"devices={len(jax.devices())}"
              + (f" grid={args.grid}" if args.grid else ""))
        # --json ring runs always carry the achieved-overlap metric
        # (docs/ring.md); otherwise the driver's HIGH-verbosity auto
        # gating applies (the measurement costs extra compiles)
        out = distributed_cpd_als(tt, rank=args.rank, opts=opts, grid=grid,
                                  partition=partition,
                                  row_distribute=args.rowdist,
                                  checkpoint_path=args.checkpoint,
                                  checkpoint_every=args.checkpoint_every,
                                  local_engine=args.local_engine,
                                  out_dir=args.scratch_dir,
                                  measure_overlap=(True if args.json
                                                   else None))
        bs = None
    else:
        if args.scratch_dir:
            # never silently ignore an explicit out-of-core request
            raise ValueError(
                "--scratch-dir applies to distributed runs (--decomp/"
                "--grid/...); the single-chip blocked build "
                "materializes its layouts in RAM")
        with timers.time("blocked_build"):
            # compile (not from_coo): with autotune on, the layouts are
            # built directly at the plan cache's tuned nnz_block
            bs = BlockedSparse.compile(tt, opts, rank=args.rank)
        print(cpd_stats_text(bs, args.rank, opts))
        out = cpd_als(bs, rank=args.rank, opts=opts,
                      checkpoint_path=args.checkpoint,
                      checkpoint_every=args.checkpoint_every)
    print(f"Final fit: {float(out.fit):0.5f}")
    # resilience report: silent degradation (engine demotions,
    # transient retries, health rollbacks, checkpoint recoveries) must
    # be observable in the run log, not only in exit codes — on the
    # single-device AND distributed paths alike
    from splatt_tpu import resilience

    report = resilience.run_report()
    if opts.verbosity >= Verbosity.LOW:
        lines = report.summary()
        if lines:
            print("Resilience events:")
            for line in lines:
                print(line)
    if getattr(args, "json", False):
        import json as _json

        print(_json.dumps(_resilience_record(report, fit=float(out.fit))))
    if bs is not None and opts.verbosity >= Verbosity.HIGH:
        # per-mode MTTKRP profile (≙ the per-mode times of `cpd -v -v`,
        # src/cpd.c:361-366) — at HIGH verbosity cpd_als runs the
        # split-jit profiled sweep, so these are true in-loop totals
        print("Per-mode MTTKRP time (in-loop totals):")
        for m in range(bs.nmodes):
            print(f"  mode {m}: {timers[f'mttkrp_mode{m}']:0.3f}s")
    if not args.nowrite:
        # ≙ the reference's -s file-stem semantics (cmd_cpd.c:209-230):
        # a bare stem writes <stem>.mode<N>.mat / <stem>.lambda.mat (the
        # reference's asprintf inserts the '.'); a directory-like stem
        # writes plain mode<N>.mat inside that directory.
        import os as _os

        stem_arg = args.stem
        if (stem_arg.endswith(_os.sep) or stem_arg in (".", "./")
                or _os.path.isdir(stem_arg)):
            out.save(stem_arg.rstrip(_os.sep) or ".", stem="")
        else:
            d, base = _os.path.split(stem_arg)
            out.save(d or ".", stem=base + ".")
    timers.stop("total")
    if opts.verbosity >= Verbosity.LOW:
        print(timers.report(level=2 if opts.verbosity >= Verbosity.HIGH
                            else 1))
    return 0


def cmd_tune(args) -> int:
    """Pre-tune a tensor offline (docs/autotune.md): measure the
    candidate MTTKRP plans — engine x nnz_block x scan_target — per
    mode and persist the winners in the plan cache, so later `cpd`
    runs (and other tensors in the same shape regime) dispatch straight
    to the measured-fastest configuration with zero measurement cost."""
    from splatt_tpu import tune
    from splatt_tpu.io import load
    from splatt_tpu.stats import tensor_stats

    opts = _build_opts(args)
    tt = load(args.tensor)
    print(tensor_stats(tt, args.tensor))
    res = tune.tune(tt, rank=args.rank, opts=opts, reps=args.reps,
                    force=args.force)
    for m in sorted(res.plans):
        p = res.plans[m]
        print(f"  mode {m}: path={p.path} engine={p.engine} "
              f"nnz_block={p.nnz_block} scan_target={p.scan_target} "
              f"({p.sec:.4f}s/call)")
    print(f"tuned {len(res.plans)}/{tt.nmodes} modes "
          f"({res.measured} measurements, {res.cache_hits} cache hits, "
          f"{res.skipped} skipped) -> {tune.cache_path()}")
    from splatt_tpu import resilience

    lines = resilience.run_report().summary()
    if lines:
        print("Resilience events:")
        for line in lines:
            print(line)
    return 0 if res.plans else 1


def cmd_chaos(args) -> int:
    """Chaos-schedule soak (docs/guarded-als.md): run a small seeded
    CPD under injected NaNs / blown deadlines / transient failures and
    assert the guarded-execution invariant — converged or gracefully
    degraded, zero unhandled exceptions, complete run report.  Exit 0
    iff the invariant held."""
    from splatt_tpu import chaos

    if args.fleet:
        # fleet soak: SIGKILL-and-restart across N replica daemons
        # over one spool under multi-tenant load (docs/fleet.md)
        res = chaos.run_fleet_chaos(seed=args.seed, smoke=args.smoke,
                                    replicas=args.replicas,
                                    verbose=args.verbose > 0)
        for line in chaos.format_fleet_report(res):
            print(line)
        if args.json:
            import json as _json

            print(_json.dumps(res.to_json()))
        return 0 if res.ok else 1
    if args.serve:
        # serve-daemon soak: SIGKILL a real daemon mid-queue, restart,
        # assert no accepted job is lost and one tenant's NaN never
        # demotes a neighbor's engines (docs/serve.md)
        res = chaos.run_serve_chaos(seed=args.seed, smoke=args.smoke,
                                    verbose=args.verbose > 0)
        for line in chaos.format_serve_report(res):
            print(line)
        if args.json:
            import json as _json

            print(_json.dumps(res.to_json()))
        return 0 if res.ok else 1
    if args.ingest:
        # ingest soak: SIGKILL a real `splatt ingest` subprocess
        # mid-stream, restart it, and audit the chunk journal ALONE
        # for the exactly-once invariant (docs/ingest.md)
        res = chaos.run_ingest_chaos(seed=args.seed, smoke=args.smoke,
                                     verbose=args.verbose > 0)
        for line in chaos.format_ingest_report(res):
            print(line)
        if args.json:
            import json as _json

            print(_json.dumps(res.to_json()))
        return 0 if res.ok else 1
    # schedule resolution (--schedule, else $SPLATT_CHAOS_SCHEDULE,
    # else the default recipe) lives in run_chaos — the single owner;
    # the resolved string comes back on the result for reporting
    res = chaos.run_chaos(schedule=args.schedule, seed=args.seed,
                          rank=args.rank, iters=args.iters,
                          deadline_s=args.deadline,
                          smoke=args.smoke,
                          verbose=args.verbose > 0,
                          trace_path=args.trace)
    for line in chaos.format_report(res):
        print(line)
    gate_ok = True
    if args.bench_gate:
        # the PR 6 bench regression gate rides the chaos smoke tier
        # (docs/format.md): a >10% time OR encoded-bytes regression
        # against the newest same-metric prior fails the run loudly
        gate = chaos.run_bench_gate(smoke=args.smoke)
        gate_ok = gate["ok"]
        verdict = "passed" if gate_ok else "FAILED"
        print(f"bench gate: {verdict} (exit {gate['returncode']})")
        if not gate_ok and gate.get("stderr_tail"):
            print(gate["stderr_tail"])
        if gate.get("record"):
            rec = gate["record"]
            print(f"bench gate: value={rec.get('value')} "
                  f"{rec.get('unit')} "
                  f"gb_per_path={rec.get('model_gb_per_path')} "
                  f"format={rec.get('format')}")
    if args.json:
        import json as _json

        print(_json.dumps(res.to_json()))
    return 0 if (res.ok and gate_ok) else 1


def cmd_ingest(args) -> int:
    """`splatt ingest` — stream a raw record file (.tns / CSV /
    JSONL) into a COO tensor under the exactly-once chunk journal
    (docs/ingest.md).  Re-running the same SOURCE into the same DEST
    resumes from the journal watermark: zero lost, zero duplicated
    records.  Exit 0 on a converged (finalized) run, 1 when the
    quarantine budget degraded it or nothing could be committed."""
    import json as _json

    from splatt_tpu import ingest, resilience

    dims = None
    if args.dims:
        try:
            dims = tuple(int(d) for d in args.dims.lower().split("x"))
        except ValueError:
            print(f"splatt ingest: bad --dims {args.dims!r} "
                  f"(want IxJxK)", flush=True)
            return 2
    try:
        summary = ingest.ingest_stream(
            args.source, args.dest, fmt=args.format,
            chunk_records=args.chunk, dims=dims,
            quarantine_max=args.quarantine_max,
            quarantine_rate=args.quarantine_rate)
    except (OSError, ValueError) as e:
        cls = resilience.classify_failure(e)
        print(f"splatt ingest: FAILED ({cls.value}): "
              f"{resilience.failure_message(e)[:200]}", flush=True)
        if args.json:
            print(_json.dumps({"status": "failed",
                               "failure_class": cls.value,
                               "error": str(e)[:200]}))
        return 1
    verb = "resumed and " if summary["resumed"] else ""
    print(f"splatt ingest: {verb}{summary['status']} — "
          f"{summary['chunks']} chunk(s), {summary['nnz']} nnz from "
          f"{summary['records']} record(s) "
          f"({summary['quarantined']} quarantined) at "
          f"{summary['records_per_sec']} rec/s")
    if summary.get("tensor"):
        print(f"splatt ingest: tensor at {summary['tensor']} "
              f"(dims {'x'.join(str(d) for d in summary['dims'])})")
    lines = resilience.run_report().summary()
    if lines:
        print("Resilience events:")
        for line in lines:
            print(line)
    if args.json:
        print(_json.dumps(summary))
    return 0 if summary["status"] == "converged" else 1


def cmd_serve(args) -> int:
    """`splatt serve` — the isolated, crash-resumable multi-tenant
    decomposition daemon (docs/serve.md).  Daemon mode runs the
    journal-backed queue over DIR; --submit/--status are the
    client-side filed-request API."""
    import json as _json

    from splatt_tpu import serve

    if args.submit:
        with open(args.submit) as f:
            spec = _json.load(f)
        jid = serve.file_request(args.dir, spec)
        print(_json.dumps({"job": jid, "filed": True}))
        return 0
    if args.status:
        print(_json.dumps(serve.read_status(args.dir, args.status)))
        return 0
    srv = serve.Server(args.dir, workers=args.workers,
                       queue_max=args.queue_max, poll_s=args.poll,
                       job_deadline_s=args.job_deadline,
                       verbose=args.verbose > 0,
                       fleet=args.fleet, replica=args.replica,
                       lease_s=args.lease, heartbeat_s=args.heartbeat,
                       tenant_quota=args.tenant_quota,
                       batch_min=args.batch_min)
    if args.fleet:
        # fleet observability wiring (docs/observability.md): stamp
        # every span/point with this replica's id (what merged traces
        # key on) and arm the flight recorder — span recording on + a
        # bounded per-replica ring in the spool, so a SIGKILLed
        # replica leaves a readable black box.  SPLATT_FLIGHT=0/off
        # opts out of the ring; done here (the daemon entry) rather
        # than in Server so library/test constructions never flip
        # process-wide tracing state behind the caller's back.
        import os as _os

        from splatt_tpu import trace
        from splatt_tpu.utils.env import read_env

        trace.set_replica(srv.fleet.replica)
        flight = str(read_env("SPLATT_FLIGHT") or "auto").lower()
        trace_off = str(read_env("SPLATT_TRACE") or "").lower() in (
            "0", "off", "false", "no")
        if flight not in ("0", "off", "false", "no") and trace_off:
            # an EXPLICIT SPLATT_TRACE=0 wins over the flight
            # recorder's auto-arm: the documented recording switch
            # must not be silently overridden — say so instead
            print("splatt-serve: flight recorder off — SPLATT_TRACE "
                  "is explicitly disabled (set SPLATT_FLIGHT=0 to "
                  "silence this, or drop SPLATT_TRACE=0 to arm the "
                  "black box)", file=sys.stderr)
        elif flight not in ("0", "off", "false", "no"):
            fdir = _os.path.join(args.dir, "fleet", "flight")
            _os.makedirs(fdir, exist_ok=True)
            trace.set_enabled(True)
            trace.set_flight(_os.path.join(
                fdir, f"{srv.fleet.replica}.jsonl"))
    srv.install_signal_handlers()
    try:
        summary = srv.run_once() if args.once else srv.serve_forever()
        if args.once:
            # batch mode exits without the daemon loop's exit
            # snapshot: force one here — BEFORE the fleet retirement
            # below, so the exit aggregation still sees this replica's
            # heartbeat (docs/observability.md)
            srv.write_metrics_now()
    finally:
        if args.fleet:
            # retire the membership lease on the way out: peers route
            # around this replica immediately (docs/fleet.md), and the
            # black box keeps everything recorded up to this exit
            srv.shutdown()
            from splatt_tpu import trace

            trace.flight_flush()
    from splatt_tpu import resilience

    lines = resilience.run_report().summary()
    if lines and args.verbose > 0:
        print("Resilience events:")
        for line in lines:
            print(line)
    print(_json.dumps(summary if args.json
                      else {"jobs": summary["counts"],
                            "pending": summary["pending"]}))
    # --once is the batch/CI entry: nonzero when any accepted job
    # failed outright (degraded-but-terminal is a success of the
    # guarded contract; interrupted jobs resume next start)
    if args.once and summary["counts"].get(serve.FAILED):
        return 1
    return 0


def cmd_bench(args) -> int:
    """≙ splatt_bench_cmd (src/cmds/cmd_bench.c:198-286)."""
    from splatt_tpu.bench_algs import ALGS, bench_mttkrp, format_bench
    from splatt_tpu.io import load
    from splatt_tpu.reorder import reorder
    from splatt_tpu.stats import tensor_stats

    opts = _build_opts(args)
    tt = load(args.tensor)
    print(tensor_stats(tt, args.tensor))
    if args.permute:
        perm = reorder(tt, args.permute, seed=opts.seed())
        tt = perm.apply(tt)
        print(f"  (reordered: {args.permute})")
    algs = args.alg or list(ALGS)
    results, layouts = bench_mttkrp(tt, rank=args.rank, algs=algs,
                                    opts=opts, reps=args.reps,
                                    return_layouts=True)
    print(f"Benchmarking MTTKRP, rank {args.rank}, {args.reps} reps")
    print(format_bench(results))
    from splatt_tpu.bench_algs import roofline_report
    from splatt_tpu.config import resolve_dtype as _rd

    print("Effective bandwidth (first-order bytes model):")
    for line in roofline_report(
            tt, results, args.rank,
            np.dtype(_rd(opts, tt.vals.dtype)).itemsize, layouts):
        print(line)
    if args.check:
        from splatt_tpu.bench_algs import crosscheck_mttkrp
        from splatt_tpu.config import resolve_dtype

        dev = crosscheck_mttkrp(tt, rank=args.rank, algs=algs, opts=opts)
        print(f"cross-check max relative |alg - stream| = {dev:.3e}")
        # tolerance follows the dtype actually computed in (a float64
        # request degrades to float32 when x64 is off)
        tol = (1e-10 if resolve_dtype(opts, tt.vals.dtype) == np.float64  # splint: ignore[SPL005] crosscheck tolerance selection names the dtype on purpose
               else 9e-3)
        if dev > tol:
            print(f"error: algorithms disagree beyond tolerance {tol}")
            return 1
    return 0


def cmd_trace(args) -> int:
    """`splatt trace <file>...` — summarize (and with multiple inputs,
    MERGE) recorded traces (docs/observability.md): top spans by
    self-time, per-iteration breakdown, guard-overhead share,
    point-event counts, and — for fleet traces — per-replica job
    counts and adoption lineage.  Inputs may be Chrome trace-event
    JSON files (``--trace`` exports), flight-recorder ``.jsonl`` rings
    (a SIGKILLed replica's black box), or a directory holding both;
    multiple sources merge onto one wall-clock timeline with flow
    events linking each adopted job's victim and adopter rows."""
    from splatt_tpu import trace

    files = trace.expand_trace_paths(args.file)
    if not files:
        raise ValueError(f"no trace files under {args.file}")
    if len(files) == 1 and not files[0].endswith(".jsonl"):
        events = trace.load_trace(files[0])
    else:
        events = trace.merge_trace_files(files)
    if args.out:
        from splatt_tpu.utils.durable import publish_json

        publish_json(args.out, {"traceEvents": events,
                                "displayTimeUnit": "ms"})
        # stderr: --json's stdout is a machine-readable contract
        print(f"merged trace ({len(files)} source(s)) written to "
              f"{args.out} — load it in ui.perfetto.dev",
              file=sys.stderr)
    s = trace.summarize(events)
    if args.json:
        import json as _json

        # tuples JSON-serialize as lists; drop the redundant "top"
        # ordering (recoverable from "names") for a stable schema
        print(_json.dumps({k: v for k, v in s.items() if k != "top"}))
        return 0
    for line in trace.format_summary(s, top_n=args.top):
        print(line)
    return 0


def cmd_status(args) -> int:
    """`splatt status DIR` / `splatt top DIR` — the fleet dashboard,
    read ONLY from the shared spool (docs/fleet.md): replicas with
    lease freshness, queue depths, per-tenant usage, running jobs with
    age, recent terminal jobs, SLO verdicts.  ``--metrics-out`` writes
    the merged fleet Prometheus exposition; ``--watch`` refreshes
    (`top` watches by default)."""
    import json as _json
    import time as _time

    from splatt_tpu import fleetobs
    from splatt_tpu.utils.env import read_env_float

    interval = float(args.interval if args.interval is not None
                     else read_env_float("SPLATT_STATUS_WATCH_S"))

    def once(clear: bool = False) -> None:
        # ONE aggregation pass feeds both the status view and the
        # optional merged-exposition write (the spool is scanned once
        # per tick, not twice)
        agg = fleetobs.aggregate(args.dir)
        st = fleetobs.fleet_status(args.dir, agg=agg)
        out = []
        if args.metrics_out:
            path = fleetobs.write_fleet_metrics(agg, args.metrics_out)
            out.append(f"fleet metrics written to {path}")
        if args.json:
            out.append(_json.dumps(st))
        else:
            out.extend(fleetobs.format_status(st))
        if clear:
            print("\x1b[2J\x1b[H", end="")
        print("\n".join(out), flush=True)

    if not args.watch or interval <= 0:
        # SPLATT_STATUS_WATCH_S=0 (or --interval 0) means run-once even
        # for the watch-by-default `splatt top` — what tests and
        # scripted status reads set instead of killing a sleep loop
        once()
        return 0
    try:
        while True:
            once(clear=not args.json)
            _time.sleep(max(interval, 0.1))
    except KeyboardInterrupt:
        return 0


def cmd_predict(args) -> int:
    """`splatt predict DIR` — file one generation-fenced predict
    against a committed model and (optionally) wait for the answer
    (docs/predict.md).  Speaks only the spool filed-request API
    (file_request + read_status), so it works against any replica of
    a live fleet, exactly like `splatt serve --submit`."""
    import json as _json
    import time as _time

    from splatt_tpu import serve

    spec: dict = {"kind": "predict", "model": args.model}
    if args.id:
        spec["id"] = args.id
    if args.tenant:
        spec["tenant"] = args.tenant
    if args.coords:
        spec["coords"] = [[int(x) for x in c.split(",")]
                          for c in args.coords]
    if args.top_k:
        fixed = {}
        for kv in (args.fix or []):
            m, _, i = kv.partition("=")
            fixed[int(m)] = int(i)
        spec["top_k"] = {"mode": args.mode, "k": args.top_k,
                         "fixed": fixed}
    jid = serve.file_request(args.dir, spec)
    if not args.wait:
        print(_json.dumps({"job": jid, "filed": True}))
        return 0
    end = _time.time() + float(args.wait)
    while _time.time() < end:
        st = serve.read_status(args.dir, jid)
        if st.get("state") in serve.TERMINAL:
            out = st.get("result") or {"job": jid,
                                       "state": st.get("state")}
            print(_json.dumps(out))
            return 0 if out.get("status") == "served" else 1
        _time.sleep(0.2)
    print(_json.dumps({"job": jid, "state": "pending",
                       "error": "timed out waiting for the answer"}))
    return 1


def cmd_check(args) -> int:
    """≙ splatt_check_cmd (src/cmds/cmd_check.c:63-116): find (and
    optionally fix) duplicate nonzeros and empty slices."""
    from splatt_tpu.io import load, save

    tt = load(args.tensor)
    # out-of-range first: the histogram-based stats below assume
    # in-range indices (negative ones crash np.bincount)
    noob = sum(int(np.count_nonzero((tt.inds[m] < 0)
                                    | (tt.inds[m] >= tt.dims[m])))
               for m in range(tt.nmodes))
    if noob:
        print(f"out-of-range: {noob}")
        print("error: tensor declares indices outside its dimensions "
              "(corrupt file?)")
        return 1
    ndup = tt.count_duplicates()
    nempty = sum(tt.dims[m] - tt.nslices_nonempty(m)
                 for m in range(tt.nmodes))
    print(f"duplicates: {ndup}  empty slices: {nempty}  "
          f"out-of-range: 0")
    if args.fix:
        fixed = tt.deduplicate().remove_empty_slices()
        save(fixed, args.fix)
        print(f"wrote fixed tensor: {args.fix} "
              f"(nnz {tt.nnz} -> {fixed.nnz}, dims {tt.dims} -> {fixed.dims})")
    return 0 if (ndup == 0 and nempty == 0) else 1


def cmd_convert(args) -> int:
    """≙ splatt_convert_cmd (src/cmds/cmd_convert.c)."""
    from splatt_tpu.convert import convert
    from splatt_tpu.io import load

    if args.type == "bin":
        # streaming text→binary when the native runtime is built:
        # bounded memory, scales past RAM (1.7B-nnz-class ingest)
        with open(args.tensor, "rb") as f:
            is_binary = f.read(4) == b"SPTT"
        if not is_binary:
            from splatt_tpu import native

            if native.stream_to_bin(args.tensor, args.output):
                print(f"wrote bin (streamed): {args.output}")
                return 0
    tt = load(args.tensor)
    convert(tt, args.type, args.output, mode=args.mode)
    print(f"wrote {args.type}: {args.output}")
    return 0


def cmd_reorder(args) -> int:
    """≙ splatt_reorder_cmd (src/cmds/cmd_reorder.c)."""
    from splatt_tpu.io import load, save, write_permutation
    from splatt_tpu.reorder import reorder

    tt = load(args.tensor)
    perm = reorder(tt, args.type, seed=args.seed or 0)
    out = perm.apply(tt)
    save(out, args.output)
    for m, p in enumerate(perm.perms):
        if p is not None and args.write_perms:
            write_permutation(p, f"{args.output}.perm{m}")
    print(f"wrote reordered tensor: {args.output}")
    return 0


def cmd_stats(args) -> int:
    """≙ splatt_stats_cmd (src/cmds/cmd_stats.c; -p gives the hypergraph
    partition-quality stats, src/stats.c:53-170)."""
    from splatt_tpu.io import load, read_permutation
    from splatt_tpu.stats import (density_stats_text,
                                  partition_quality_text, skew_stats_text,
                                  tensor_stats)

    tt = load(args.tensor)
    print(tensor_stats(tt, args.tensor))
    if args.partition:
        print(partition_quality_text(tt, read_permutation(args.partition)))
    for m in range(tt.nmodes):
        hist = tt.mode_histogram(m)
        nz = hist[hist > 0]
        print(f"  mode {m}: dim={tt.dims[m]} nonempty={nz.size} "
              f"nnz/slice min={nz.min() if nz.size else 0} "
              f"avg={tt.nnz / max(nz.size, 1):.1f} "
              f"max={nz.max() if nz.size else 0}")
    # slice/fiber skew (docs/layout-balance.md): uniform vs power-law
    # is the first question the layout/tuner answer depends on
    print(skew_stats_text(tt))
    # per-mode density (docs/dense.md): dense-tile vs sparse-blocked is
    # the other axis the layout/tuner answer depends on
    print(density_stats_text(tt))
    return 0


def build_parser() -> argparse.ArgumentParser:
    from splatt_tpu.version import __version__

    ap = argparse.ArgumentParser(
        prog="splatt-tpu",
        description="Sparse tensor factorization on TPU "
                    "(CPD-ALS over blocked sparse formats)")
    ap.add_argument("-V", "--version", action="version",
                    version=f"splatt-tpu {__version__}")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("cpd", help="compute the CPD of a sparse tensor")
    _common_opts(p)
    p.add_argument("-r", "--rank", type=int, default=10)
    p.add_argument("-t", "--tol", type=float)
    p.add_argument("-i", "--iters", type=int)
    p.add_argument("--reg", type=float)
    p.add_argument("--seed", type=int)
    p.add_argument("--alloc", choices=["onemode", "twomode", "allmode"])
    p.add_argument("--mode-order", dest="mode_order",
                   choices=["smallfirst", "bigfirst", "inorder_minusone",
                            "sorted_minusone"],
                   help="secondary mode ordering within a layout "
                        "(reference csf_find_mode_order policies)")
    p.add_argument("--block", type=int, help="nnz per block")
    p.add_argument("--f64", action="store_true", help="double precision")
    p.add_argument("--nowrite", action="store_true",
                   help="skip writing factor files")
    p.add_argument("-s", "--stem", default="./", metavar="PATH",
                   help="file stem for factor output files (default: ./) "
                        "— reference semantics: <stem>.mode1.mat etc.; a "
                        "trailing / (or an existing directory) writes "
                        "plain mode1.mat into that directory")
    # distributed flags (≙ mpirun splatt cpd -d IxJxK / -d f -p partfile)
    p.add_argument("--decomp", choices=["medium", "coarse", "fine"],
                   help="run distributed over all devices with this "
                        "decomposition")
    p.add_argument("--grid", metavar="IxJxK",
                   help="device grid for the medium decomposition")
    p.add_argument("-p", "--partition", metavar="FILE",
                   help="per-nonzero partition file (fine decomposition)")
    p.add_argument("--comm", choices=["all2all", "point2point",
                                      "async_ring"],
                   help="row-exchange pattern for --decomp fine "
                        "(default: $SPLATT_COMM, else all2all): "
                        "point2point = ppermute ring, memory-lean; "
                        "async_ring = Pallas remote-copy ring that "
                        "overlaps the exchange with compute on TPU "
                        "and degrades classified to point2point then "
                        "all2all on failure (docs/ring.md)")
    p.add_argument("--rowdist", choices=["greedy", "balanced"],
                   help="factor-row distribution: greedy = comm-"
                        "minimizing row claiming for --decomp fine "
                        "(reference mpi_mat_distribute semantics); "
                        "balanced = nnz-weighted fences (chains-on-"
                        "chains LPT, docs/layout-balance.md) for fine "
                        "and coarse, so a device owning hot slices no "
                        "longer gates the exchange")
    p.add_argument("--local-engine", choices=["blocked", "stream"],
                   dest="local_engine",
                   help="per-device MTTKRP engine for distributed runs "
                        "(default auto: blocked sorted layouts; "
                        "memmapped tensors build them via streamed "
                        "chunked passes)")
    p.add_argument("--scratch-dir", dest="scratch_dir", metavar="DIR",
                   help="disk-backed scratch for distributed "
                        "decomposition arrays: with a memmapped tensor "
                        "the whole build is out-of-core (bounded host "
                        "RSS at any scale)")
    p.add_argument("--mmap", action="store_true",
                   help="memory-map a binary tensor instead of loading "
                        "it (O(1) host RAM for the LOAD; pair with a "
                        "distributed --decomp and --scratch-dir for a "
                        "fully out-of-core build — the single-chip "
                        "blocked build still materializes its layouts)")
    p.add_argument("--checkpoint", metavar="FILE",
                   help="write an atomic .npz checkpoint (checksummed; "
                        "previous generation kept as .bak) every "
                        "--checkpoint-every iterations and resume from "
                        "it when present (single-device and "
                        "distributed; checkpoints are device-count-"
                        "independent; a corrupt file degrades to the "
                        ".bak generation instead of crashing the resume)")
    p.add_argument("--checkpoint-every", type=_positive_int, default=10,
                   metavar="N", help="iterations between checkpoints")
    p.add_argument("--engine-fallback", choices=["on", "off"],
                   dest="engine_fallback",
                   help="runtime engine fallback (default on): a "
                        "failing MTTKRP engine is demoted and the next "
                        "engine in the chain runs instead of the "
                        "failure killing the run; 'off' fails loudly "
                        "(docs/resilience.md)")
    p.add_argument("--autotune", choices=["on", "off"],
                   help="consult the autotuner's plan cache for the "
                        "MTTKRP engine/block/scan plan (default on; "
                        "pre-tune with `splatt tune` — docs/autotune.md)")
    p.add_argument("--json", action="store_true",
                   help="also print a machine-readable JSON run "
                        "summary (fit, run-report events including "
                        "health rollbacks, engine demotions)")
    _trace_opt(p)
    p.set_defaults(fn=cmd_cpd)

    p = sub.add_parser(
        "chaos", help="chaos-schedule soak of the guarded ALS layer",
        epilog="Runs a small seeded synthetic CPD under a declarative "
               "fault schedule (same grammar as SPLATT_FAULTS, plus "
               "iter=k / p=x:seed=N / after=t schedule modifiers) and "
               "asserts: converged or gracefully degraded, zero "
               "unhandled exceptions, a complete run report, finite "
               "factors or an explicit degraded verdict "
               "(docs/guarded-als.md).  Exit 0 iff the invariant held.")
    p.add_argument("-v", "--verbose", action="count", default=0)
    p.add_argument("--schedule", metavar="SPEC",
                   help="fault schedule (default: "
                        "$SPLATT_CHAOS_SCHEDULE, else a seeded "
                        "NaN+deadline+transient recipe)")
    p.add_argument("--smoke", action="store_true",
                   help="seconds-scale seeded run on a tiny tensor "
                        "(the tier-1 CI entry)")
    p.add_argument("--bench-gate", action="store_true",
                   help="additionally run `python bench.py --gate` "
                        "(smoke-sized under --smoke): a >10% time or "
                        "encoded-bytes regression vs the newest "
                        "same-metric BENCH_*.json prior fails the run "
                        "(docs/format.md)")
    p.add_argument("--serve", action="store_true",
                   help="soak the serve daemon instead: SIGKILL a "
                        "real daemon mid-queue, restart it, and "
                        "assert no accepted job is lost and one "
                        "tenant's injected NaN never demotes a "
                        "neighbor's engines (docs/serve.md)")
    p.add_argument("--fleet", action="store_true",
                   help="soak a serve FLEET instead: N replica "
                        "daemons over one spool under multi-tenant "
                        "load, SIGKILL-and-restart a replica mid-job, "
                        "and assert no accepted job is lost, the "
                        "single-owner lineage holds, adoptions are "
                        "accounted in metrics, and adopted same-"
                        "regime jobs hit warm caches (docs/fleet.md)")
    p.add_argument("--replicas", type=int, default=None, metavar="N",
                   help="fleet soak: replica count (default 2 under "
                        "--smoke, else 3)")
    p.add_argument("--ingest", action="store_true",
                   help="soak the streaming-ingest plane instead: "
                        "SIGKILL a real `splatt ingest` subprocess "
                        "mid-stream, restart it, and audit the chunk "
                        "journal ALONE for zero lost and zero "
                        "duplicated records with every quarantined "
                        "record accounted (docs/ingest.md)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("-r", "--rank", type=int, default=4)
    p.add_argument("-i", "--iters", type=int, default=8)
    p.add_argument("--deadline", type=float, default=0.5, metavar="S",
                   help="watchdog budget for the run (seconds; the "
                        "slow fault kind blows it deliberately)")
    p.add_argument("--json", action="store_true",
                   help="also print the full ChaosResult as JSON")
    p.add_argument("--trace", metavar="OUT_JSON",
                   help="run the soak with span tracing on, export the "
                        "Chrome trace to OUT_JSON, and additionally "
                        "assert that every fired fault left a matching "
                        "point event ON THE TRACE (the exporter leg of "
                        "the invariant; docs/observability.md)")
    p.set_defaults(fn=cmd_chaos)

    p = sub.add_parser(
        "ingest", help="stream a raw record file into a COO tensor",
        epilog="Chunked, crash-resumable ingest (docs/ingest.md): "
               "SOURCE is cut into chunks of --chunk records; each "
               "chunk parses (malformed records quarantined to "
               "DEST/quarantine.jsonl with classified events), "
               "vocab-maps string keys, publishes its segment "
               "atomically, and journals LAST — so a SIGKILL at any "
               "point resumes from DEST/journal.jsonl with zero lost "
               "and zero duplicated records.  A finalized run lands "
               "DEST/tensor.bin in the binary memmap layout "
               "(`splatt cpd DEST/tensor.bin --mmap ...`).")
    p.add_argument("source", help="record stream: .tns text, CSV, or "
                                  "JSONL arrays [i0, ..., val]")
    p.add_argument("dest", help="ingest state directory (journal, "
                                "seg/, vocab/, quarantine sidecar, "
                                "tensor.bin)")
    p.add_argument("--format", choices=["auto", "tns", "csv", "jsonl"],
                   default="auto",
                   help="record format (default: by file extension)")
    p.add_argument("--chunk", type=_positive_int, metavar="N",
                   help="records per chunk commit (default: "
                        "$SPLATT_INGEST_CHUNK; a resume must match "
                        "the journal's value)")
    p.add_argument("--dims", metavar="IxJxK",
                   help="declared mode sizes: out-of-range indices "
                        "quarantine as bad_index instead of growing "
                        "the tensor (required when chaining updates "
                        "against a served model)")
    p.add_argument("--quarantine-max", type=int, dest="quarantine_max",
                   metavar="N",
                   help="absolute bad-record budget (default: "
                        "$SPLATT_INGEST_QUARANTINE_MAX); past it the "
                        "run degrades classified")
    p.add_argument("--quarantine-rate", type=float,
                   dest="quarantine_rate", metavar="X",
                   help="max quarantined/parsed ratio (default: "
                        "$SPLATT_INGEST_QUARANTINE_RATE)")
    p.add_argument("--json", action="store_true",
                   help="also print the machine-readable run summary")
    p.add_argument("-v", "--verbose", action="count", default=0)
    p.set_defaults(fn=cmd_ingest)

    p = sub.add_parser(
        "serve", help="run the multi-tenant decomposition daemon",
        epilog="A journal-backed job queue over DIR: clients drop job "
               "specs into DIR/requests/ (or --submit them), the "
               "daemon runs each CPD under the guarded drivers with "
               "per-job isolation of demotions/health verdicts, "
               "results appear in DIR/results/<id>.json with the "
               "--json run-report schema.  Crash-resumable: a killed "
               "daemon replays its journal on restart and resumes "
               "every accepted job from its checkpoint; SIGTERM "
               "drains gracefully (docs/serve.md).")
    p.add_argument("dir", help="serve state directory (journal, "
                               "requests/, results/, ckpt/)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    p.add_argument("--workers", type=_positive_int,
                   help="concurrent job-supervisor threads "
                        "(default: $SPLATT_SERVE_WORKERS)")
    p.add_argument("--queue-max", type=int, dest="queue_max",
                   help="bounded pending-queue depth; submissions past "
                        "it are load-shed with an explicit queue_full "
                        "rejection (default: $SPLATT_SERVE_QUEUE_MAX; "
                        "<= 0 unbounded)")
    p.add_argument("--poll", type=float,
                   help="seconds between request-spool scans "
                        "(default: $SPLATT_SERVE_POLL_S)")
    p.add_argument("--job-deadline", type=float, dest="job_deadline",
                   help="default per-job deadline in seconds; a blown "
                        "deadline classifies TIMEOUT and the job is "
                        "marked failed, releasing its worker (default: "
                        "$SPLATT_SERVE_JOB_DEADLINE_S; <= 0 off)")
    p.add_argument("--once", action="store_true",
                   help="process the spool and queue to completion, "
                        "then exit (batch/CI mode; nonzero exit iff "
                        "a job failed outright)")
    p.add_argument("--fleet", action="store_true",
                   help="fleet mode (docs/fleet.md): run as one of N "
                        "replicas over this shared DIR — job "
                        "ownership via leases, heartbeat membership, "
                        "dead-peer adoption, cache-affinity routing")
    p.add_argument("--replica", metavar="ID",
                   help="fleet: this replica's stable id (default: "
                        "$SPLATT_FLEET_REPLICA, else a fresh "
                        "pid+random id)")
    p.add_argument("--lease", type=float, metavar="S",
                   help="fleet: lease duration in seconds — the "
                        "failure-detection horizon (default: "
                        "$SPLATT_FLEET_LEASE_S)")
    p.add_argument("--heartbeat", type=float, metavar="S",
                   help="fleet: heartbeat/renewal cadence (default: "
                        "$SPLATT_FLEET_HEARTBEAT_S, else lease/3)")
    p.add_argument("--tenant-quota", type=int, dest="tenant_quota",
                   help="admission control: max non-terminal jobs per "
                        "tenant, shed past it with a quota_rejected "
                        "event (default: $SPLATT_FLEET_TENANT_QUOTA; "
                        "<= 0 off)")
    p.add_argument("--batch-min", type=int, dest="batch_min",
                   help="auto-coalescing (docs/batched.md): dispatch "
                        ">= this many queued same-regime jobs as ONE "
                        "vmapped batched CPD (default: "
                        "$SPLATT_SERVE_BATCH_MIN; <= 0 off)")
    p.add_argument("--submit", metavar="SPEC_JSON",
                   help="client mode: file this job-spec JSON into "
                        "DIR/requests/ and exit")
    p.add_argument("--status", metavar="JOB_ID",
                   help="client mode: print the job's journal-derived "
                        "state (and result, when terminal) as JSON")
    p.add_argument("--json", action="store_true",
                   help="print the full per-job state map on exit")
    _trace_opt(p)
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "tune", help="pre-tune the MTTKRP plan for a tensor",
        epilog="Times candidate plans (engine x nnz_block x "
               "scan_target) per mode with short warm+timed runs and "
               "persists the winners in the plan cache; later cpd runs "
               "in the same shape regime dispatch straight to the "
               "measured winner (docs/autotune.md)")
    _common_opts(p)
    p.add_argument("-r", "--rank", type=int, default=10)
    p.add_argument("--reps", type=int, default=2,
                   help="timed repetitions per candidate (median wins)")
    p.add_argument("--force", action="store_true",
                   help="re-measure even when the plan cache already "
                        "holds an unexpired winner")
    p.add_argument("--alloc", choices=["onemode", "twomode", "allmode"])
    p.add_argument("--f64", action="store_true")
    _trace_opt(p)
    p.set_defaults(fn=cmd_tune)

    p = sub.add_parser(
        "bench", help="benchmark MTTKRP algorithms",
        epilog="Per-path effective-bandwidth (roofline) lines are "
               "printed with the timings.  For a device-count scaling "
               "sweep (≙ the reference's thread scaling) run the "
               "repo-root bench driver: SPLATT_BENCH_DEVICES=1,2,4,8 "
               "python bench.py")
    _common_opts(p)
    p.add_argument("-r", "--rank", type=int, default=16)
    p.add_argument("-a", "--alg", action="append",
                   help="algorithm (repeatable): stream/blocked/"
                        "blocked_pallas/scatter/ttbox")
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--seed", type=int)
    p.add_argument("--alloc", choices=["onemode", "twomode", "allmode"])
    p.add_argument("--mode-order", dest="mode_order",
                   choices=["smallfirst", "bigfirst", "inorder_minusone",
                            "sorted_minusone"],
                   help="secondary mode ordering within a layout "
                        "(reference csf_find_mode_order policies)")
    p.add_argument("--block", type=int)
    p.add_argument("--f64", action="store_true")
    p.add_argument("--permute", choices=list(PERM_TYPES),
                   help="reorder the tensor first")
    p.add_argument("--check", action="store_true",
                   help="cross-validate algorithm outputs against stream "
                        "(≙ the reference's --write dumps)")
    _trace_opt(p)
    p.set_defaults(fn=cmd_bench)

    p = sub.add_parser(
        "trace", help="summarize (and merge) recorded span traces",
        epilog="Reads Chrome trace-event JSON files (the --trace "
               "<path> export of cpd/tune/bench/serve/chaos), flight-"
               "recorder .jsonl rings (a SIGKILLed replica's black "
               "box), or a directory of both; prints top spans by "
               "self-time, the per-iteration breakdown, the guard-"
               "overhead share, point-event counts, and the fleet "
               "block (per-replica jobs, adoption lineage).  Multiple "
               "inputs merge onto one wall-clock timeline with flow "
               "events linking an adopted job's victim and adopter "
               "(docs/observability.md).  Load the (merged) file in "
               "ui.perfetto.dev for the interactive view.")
    p.add_argument("file", nargs="+",
                   help="trace file(s): Chrome JSON, flight .jsonl, "
                        "or a directory holding them")
    p.add_argument("--out", metavar="OUT_JSON",
                   help="also write the merged Chrome trace-event "
                        "file (atomic) for perfetto")
    p.add_argument("--top", type=int, default=12, metavar="N",
                   help="rows in the top-spans table (default 12)")
    p.add_argument("--json", action="store_true",
                   help="print the aggregate summary as JSON instead")
    p.set_defaults(fn=cmd_trace)

    for verb, watching in (("status", False), ("top", True)):
        p = sub.add_parser(
            verb,
            help=("watch-mode textual fleet dashboard" if watching
                  else "one-shot fleet status from the shared spool"),
            epilog="Reads ONLY the shared serve spool (journal, "
                   "fleet/ heartbeats + leases, per-replica metrics "
                   "snapshots, persisted SLO verdicts) — no daemon "
                   "RPC, so it works on a live fleet, a draining one "
                   "and a post-mortem alike (docs/fleet.md, "
                   "docs/observability.md).  Shows replicas with "
                   "lease freshness, queue depths, per-tenant usage, "
                   "running jobs with age, recent terminal jobs and "
                   "the SLO burn summary.")
        p.add_argument("dir", help="the serve spool directory")
        p.add_argument("--json", action="store_true",
                       help="print the machine-readable status object")
        p.add_argument("--metrics-out", dest="metrics_out",
                       metavar="PROM",
                       help="also write the merged fleet Prometheus "
                            "exposition (counters summed, gauges "
                            "per-replica, histograms bucket-merged, "
                            "dead replicas' gauges dropped) to this "
                            "file, atomically")
        if watching:
            p.add_argument("--once", dest="watch",
                           action="store_false",
                           help="one-shot instead of watching")
        else:
            p.add_argument("--watch", action="store_true",
                           help="refresh continuously (the `splatt "
                                "top` default)")
        p.add_argument("--interval", type=float, metavar="S",
                       help="watch refresh seconds (default: "
                            "$SPLATT_STATUS_WATCH_S)")
        p.set_defaults(fn=cmd_status, watch=watching)

    p = sub.add_parser(
        "predict",
        help="query a served model: reconstruct entries / top-k",
        epilog="Files a generation-fenced predict job into DIR's serve "
               "spool (docs/predict.md): a daemon answers from an "
               "intact model generation or refuses classified — never "
               "stale, never torn.  --coords reconstructs entries "
               "x̂ = Σ_r λ_r Π_m U_m[i_m,r]; --top-k scans one mode "
               "with every other mode pinned by --fix.")
    p.add_argument("dir", help="the serve spool directory")
    p.add_argument("--model", required=True,
                   help="the committed model's job id")
    p.add_argument("--id", help="predict job id (default: generated)")
    p.add_argument("--tenant", help="tenant label for quota accounting")
    p.add_argument("--coords", action="append", metavar="I,J,K",
                   help="an index tuple to reconstruct (repeatable)")
    p.add_argument("--top-k", dest="top_k", type=int, metavar="K",
                   help="return the K best indices along --mode")
    p.add_argument("--mode", type=int, default=0,
                   help="the scanned mode for --top-k (default 0)")
    p.add_argument("--fix", action="append", metavar="MODE=INDEX",
                   help="pin a non-scanned mode for --top-k "
                        "(repeatable; every mode but --mode needs one)")
    p.add_argument("--wait", type=float, default=0.0, metavar="S",
                   help="poll up to S seconds for the answer "
                        "(default: file-and-exit, exit 0 on served)")
    p.set_defaults(fn=cmd_predict)

    p = sub.add_parser("check", help="check for duplicates/empty slices")
    _common_opts(p)
    p.add_argument("--fix", metavar="OUT",
                   help="write a fixed tensor to OUT")
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser("convert", help="convert to other formats")
    _common_opts(p)
    p.add_argument("type", choices=["graph", "fibmat", "fibhgraph",
                                    "nnzhgraph", "bin", "coord"])
    p.add_argument("output")
    p.add_argument("-m", "--mode", type=int, default=0)
    p.set_defaults(fn=cmd_convert)

    p = sub.add_parser("reorder", help="relabel tensor indices")
    _common_opts(p)
    p.add_argument("type", choices=list(PERM_TYPES))
    p.add_argument("output")
    p.add_argument("--seed", type=int)
    p.add_argument("--write-perms", action="store_true")
    p.set_defaults(fn=cmd_reorder)

    p = sub.add_parser("stats", help="print tensor statistics")
    _common_opts(p)
    p.add_argument("-p", "--partition", metavar="FILE",
                   help="also report quality of this nonzero partition")
    p.set_defaults(fn=cmd_stats)

    return ap


def main(argv: Optional[List[str]] = None) -> int:
    # Mirror JAX_PLATFORMS into jax.config before any backend
    # initializes: site plugins may pre-register an accelerator backend
    # programmatically, which ignores the env var (bench.py does the
    # same; ≙ the reference CLI honoring its environment unconditionally).
    # A config-update failure is classified and logged by the helper —
    # it used to be swallowed here, losing the error entirely.
    apply_env_platform()
    # SPLATT_COMPILE_CACHE: share serialized executables across splatt
    # processes (fleet replicas, restarts) — must also precede backend
    # initialization
    apply_compile_cache()
    args = build_parser().parse_args(argv)
    if getattr(args, "rank", 1) < 1:
        print(f"splatt-tpu: error: rank must be >= 1 (got {args.rank})",
              file=sys.stderr)
        return 2
    # --trace <path> (docs/observability.md): enable span recording
    # process-wide for this invocation — timers, build, cpd/serve spans
    # all land in one tree — and export on the way out, success or
    # error (a crash's partial trace is exactly when you want one).
    # The chaos verb owns its own trace leg (run_chaos arms, exports
    # and ASSERTS on the trace), so it is excluded here.
    trace_out = (getattr(args, "trace", None)
                 if getattr(args, "cmd", "") != "chaos" else None)
    if trace_out:
        from splatt_tpu import trace

        trace.set_enabled(True)
    try:
        return args.fn(args)
    except (OSError, ValueError) as e:
        print(f"splatt-tpu: error: {e}", file=sys.stderr)
        return 1
    finally:
        if trace_out:
            ev = trace.write_chrome_trace(trace_out)
            trace.set_enabled(None)
            print(f"splatt-tpu: trace "
                  + (f"written to {trace_out} ({ev.get('spans')} spans, "
                     f"{ev.get('events')} point events); summarize "
                     f"with: splatt trace {trace_out}"
                     if ev.get("ok") else
                     f"export to {trace_out} FAILED "
                     f"({ev.get('failure_class')}: {ev.get('error')})"),
                  file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
