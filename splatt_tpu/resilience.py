"""Resilience layer: failure taxonomy, retries, demotions, run report.

Four consecutive rounds of chip unavailability (VERDICT.md) showed the
system's weakest point is failure HANDLING, not speed: one transient
remote-compile 500 used to be persisted as a permanent "compile_failed"
verdict, demoting the flagship Pallas engine for every future session.
Production tensor-decomposition stacks (GenTen's performance-portable
MTTKRP; the emerging-architectures survey) keep multiple backends live
so one backend's failure degrades, not kills, the run.  This module is
the single place that decides what a failure MEANS:

Failure taxonomy
    :func:`classify_failure` sorts probe/compile/runtime errors into

    - ``DETERMINISTIC`` — a proven kernel-compiler rejection (Mosaic
      signatures).  Safe to persist: the same sources on the same
      device will always fail.
    - ``TRANSIENT``     — the remote-compile relay or service hiccuping
      (HTTP 5xx, bare ``INTERNAL:``, ``UNAVAILABLE``, resets,
      timeouts).  Retried with capped exponential backoff + jitter,
      NEVER persisted.
    - ``RESOURCE``      — capacity, not capability (OOM / VMEM
      exhaustion).  Demotes the engine for this shape only.
    - ``UNKNOWN``       — anything unrecognized.  Treated like
      transient for persistence purposes (rejected this session,
      re-probed next process) but not retried in-place.

Engine demotion registry
    :func:`demote_engine` / :func:`is_demoted` — runtime failures of a
    dispatch engine demote it (process-wide, or per-shape for RESOURCE
    failures) so the ordered fallback chain in
    :func:`splatt_tpu.ops.mttkrp.engine_chain` skips it mid-run instead
    of crashing ``cpd_als``.

Run report
    :func:`run_report` — an append-only event log (demotions, probe
    retries, checkpoint recoveries) the CLI prints at the end of a run,
    so silent degradation is observable (≙ the reference's stats
    reporting philosophy, src/stats.c).

Nothing here imports jax: classification is pure string logic so the
fault-injection tests exercise every branch without a device.
"""

from __future__ import annotations

import dataclasses
import enum
import random
import time
from typing import Callable, Dict, List, Optional


class FailureClass(enum.Enum):
    """What a probe/compile/runtime failure means for future dispatch."""

    DETERMINISTIC = "deterministic"   # persist: will always fail here
    TRANSIENT = "transient"           # retry w/ backoff; never persist
    RESOURCE = "resource"             # demote for this shape only
    UNKNOWN = "unknown"               # unproven; re-probe next process


# Capacity failures first: an OOM message may also mention the kernel
# compiler ("Mosaic ... scoped vmem limit exceeded"), and the right
# verdict there is shape-scoped demotion, not a permanent rejection.
RESOURCE_MARKERS = (
    "RESOURCE_EXHAUSTED", "Out of memory", "out of memory", "OOM",
    "vmem limit", "VMEM limit", "scoped vmem", "exceeds the limit",
    "Attempting to allocate", "Attempting to reserve",
)

# Deterministic Mosaic/kernel-compiler rejection signatures — the ONLY
# class that may be persisted as "compile_failed" (a persisted
# misclassification demotes the flagship engine for every future
# session, so this is a whitelist, not a transient-error blocklist).
# 'HTTP code 500' and bare 'INTERNAL: ' were deliberately REMOVED from
# this set (ADVICE.md round 5): they are classic transient relay
# failures and live in TRANSIENT_MARKERS below.
DETERMINISTIC_MARKERS = (
    "Mosaic", "mosaic", "Internal TPU kernel compiler",
    "Invalid input layout", "Unsupported lowering",
    "not implemented", "NotImplementedError",
)

# Transient remote-compile / relay / service failures: retried with
# backoff, rejected only for this attempt window, never persisted.
TRANSIENT_MARKERS = (
    "HTTP code 500", "HTTP code 502", "HTTP code 503", "HTTP code 504",
    "INTERNAL: ", "UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED",
    "Connection reset", "Connection refused", "Socket closed",
    "Broken pipe", "timed out", "TimeoutError",
    "temporarily unavailable", "Transient",
)


def failure_message(exc) -> str:
    """The string classification runs on: "ExcType: message"."""
    if isinstance(exc, str):
        return exc
    return f"{type(exc).__name__}: {exc}"


def classify_failure(exc) -> FailureClass:
    """Classify a probe/compile/runtime error (exception or message).

    Order matters: RESOURCE outranks DETERMINISTIC (a Mosaic VMEM
    message is capacity, not capability), and DETERMINISTIC outranks
    TRANSIENT — "INTERNAL: Mosaic failed ..." carries a real compiler
    signature, so the transient 'INTERNAL: ' prefix must not launder it
    into a retry loop (ADVICE.md: bare 500/INTERNAL are transient
    UNLESS they co-occur with a Mosaic/kernel-compiler marker).
    """
    msg = failure_message(exc)
    if any(m in msg for m in RESOURCE_MARKERS):
        return FailureClass.RESOURCE
    if any(m in msg for m in DETERMINISTIC_MARKERS):
        return FailureClass.DETERMINISTIC
    if any(m in msg for m in TRANSIENT_MARKERS):
        return FailureClass.TRANSIENT
    return FailureClass.UNKNOWN


# -- transient retry --------------------------------------------------------

#: default retry budget for transient failures.  Small and capped: a
#: wedged relay must degrade the session in bounded time (the probe
#: machinery adds its own 240 s deadline on top).
TRANSIENT_RETRIES = 3
BACKOFF_BASE_S = 0.5
BACKOFF_CAP_S = 8.0


def retry_transient(fn: Callable, attempts: int = None,
                    base: float = BACKOFF_BASE_S,
                    cap: float = BACKOFF_CAP_S,
                    sleep: Optional[Callable] = None,
                    rng: Optional[Callable] = None,
                    label: str = "") -> object:
    """Run `fn`, retrying ONLY transient failures with capped
    exponential backoff + full jitter (delay ~ U(0, min(cap, base·2^a))
    — the decorrelated pattern that avoids thundering-herd re-compiles
    against a shared relay).  Deterministic / resource / unknown
    failures propagate immediately: retrying a proven rejection wastes
    the chip window.  `sleep`/`rng` are injectable for tests.
    """
    if attempts is None:
        attempts = TRANSIENT_RETRIES
    if sleep is None:
        sleep = time.sleep
    if rng is None:
        rng = random.random
    last = None
    for a in range(max(attempts, 1)):
        try:
            return fn()
        except Exception as e:
            last = e
            if (classify_failure(e) is not FailureClass.TRANSIENT
                    or a == attempts - 1):
                raise
            delay = min(cap, base * (2 ** a)) * rng()
            run_report().add("transient_retry", label=label,
                             attempt=a + 1, delay_s=round(delay, 3),
                             error=failure_message(e)[:200])
            sleep(delay)
    raise last  # pragma: no cover — loop always returns or raises


# -- engine demotion registry -----------------------------------------------

@dataclasses.dataclass
class Demotion:
    """One runtime engine demotion: which engine, why, and its scope
    (shape_key=None means process-wide; otherwise this shape only)."""

    engine: str
    failure_class: FailureClass
    error: str
    shape_key: Optional[str] = None
    ts: float = dataclasses.field(default_factory=time.time)


_DEMOTED: Dict[str, Demotion] = {}


def _demotion_key(engine: str, shape_key: Optional[str]) -> str:
    return engine if shape_key is None else f"{engine}@{shape_key}"


def demote_engine(engine: str, error, shape_key: Optional[str] = None
                  ) -> Demotion:
    """Record a runtime demotion of `engine`; the fallback chain skips
    it from now on.  RESOURCE failures demote per-shape (pass the
    shape_key); everything else process-wide.  Never persisted to disk:
    a demotion lasts one process — the probe cache owns cross-process
    verdicts with its own (stricter) persistence rules."""
    cls = classify_failure(error)
    if cls is not FailureClass.RESOURCE:
        shape_key = None
    d = Demotion(engine=engine, failure_class=cls,
                 error=failure_message(error)[:500], shape_key=shape_key)
    _DEMOTED[_demotion_key(engine, shape_key)] = d
    run_report().add("engine_demotion", engine=engine,
                     failure_class=cls.value, shape_key=shape_key,
                     error=d.error[:200])
    return d


def is_demoted(engine: str, shape_key: Optional[str] = None) -> bool:
    """Whether `engine` was demoted process-wide, or for this shape."""
    if engine in _DEMOTED:
        return True
    return (shape_key is not None
            and _demotion_key(engine, shape_key) in _DEMOTED)


def demotions() -> List[Demotion]:
    return list(_DEMOTED.values())


def reset_demotions() -> None:
    """Clear runtime demotions (tests; a fresh run in one process)."""
    _DEMOTED.clear()


# -- last-attempt tracking --------------------------------------------------
#
# Failures on accelerators can surface ASYNCHRONOUSLY — not at the
# mttkrp_blocked call that picked the engine, but at the next host sync
# inside the sweep.  The dispatch layer notes which engine it handed
# work to; the driver-level handler (cpd_als) uses it to demote the
# right engine when an exception arrives with no call-site context.

_LAST_ATTEMPT: Optional[tuple] = None


def note_engine_attempt(engine: str, shape_key: Optional[str] = None
                        ) -> None:
    global _LAST_ATTEMPT
    _LAST_ATTEMPT = (engine, shape_key)


def last_engine_attempt() -> Optional[tuple]:
    """(engine, shape_key) of the most recent dispatch, or None."""
    return _LAST_ATTEMPT


# -- engine fallback switch -------------------------------------------------

_FALLBACK_ENV = "SPLATT_ENGINE_FALLBACK"
_fallback_override: Optional[bool] = None


def fallback_enabled() -> bool:
    """Whether runtime engine fallback is on (default yes).  CLI
    --engine-fallback off / SPLATT_ENGINE_FALLBACK=0 disable it — a
    differential test chasing a kernel bug wants the crash, not the
    silent rescue."""
    if _fallback_override is not None:
        return _fallback_override
    from splatt_tpu.utils.env import read_env

    return str(read_env(_FALLBACK_ENV)).lower() not in (
        "0", "off", "false", "no")


def set_fallback(enabled: Optional[bool]) -> None:
    """Process-wide override (None restores the env default)."""
    global _fallback_override
    _fallback_override = enabled


# -- run report -------------------------------------------------------------

#: Every run-report event kind the code emits, name -> one-line doc —
#: the authoritative documentation of the observability surface,
#: mirroring utils/env.py:ENV_VARS.  `splint` rule SPL012 statically
#: checks every ``run_report().add("<kind>", ...)`` emission site
#: against this registry (both directions: undeclared emissions and
#: declared-but-never-emitted kinds are findings), so the docs and the
#: code cannot drift apart.  Tests may add ad-hoc kinds through a
#: RunReport instance directly; the registry governs production
#: emissions only.
RUN_REPORT_EVENTS = {
    "transient_retry": "a transient failure was retried in place with "
                       "capped backoff+jitter (retry_transient)",
    "engine_demotion": "a dispatch engine was demoted at runtime "
                       "(process-wide, or per-shape for RESOURCE "
                       "failures) and the fallback chain skips it",
    "checkpoint_recovery": "a corrupt/torn checkpoint degraded the "
                           "resume to the .bak generation or a fresh "
                           "start (cpd.load_checkpoint_resilient)",
    "probe_downgrade": "a capability-probe verdict was downgraded to "
                       "unproven for this session (re-probed next "
                       "process)",
    "probe_cache_io_error": "probe-cache IO failed and was degraded "
                            "(cache stays best-effort; verdicts are "
                            "re-earned)",
    "tune_cache_io_error": "plan-cache IO failed and was degraded "
                           "(dispatch falls back to re-tuning or the "
                           "heuristic chain)",
    "tuned_plan": "cpd_als dispatched through autotuned MTTKRP plans "
                  "(docs/autotune.md); carries the per-mode plans",
    "tuner_negative": "an autotuner candidate failed to measure; "
                      "deterministic/resource failures persist as "
                      "negative plan-cache entries",
    "tuner_degraded": "no autotuner candidate was measurable for a "
                      "mode; dispatch keeps the heuristic chain",
    "block_clamp": "build_layout clamped the requested nnz block to "
                   "the tensor's size (blocked.py)",
    "env_platform_error": "JAX_PLATFORMS could not be mirrored into "
                          "jax.config (utils/env.py:"
                          "apply_env_platform); the run continues on "
                          "whatever backend jax picks",
}


class RunReport:
    """Append-only log of resilience events for one run: engine
    demotions, transient retries, probe verdict downgrades, checkpoint
    recoveries.  The CLI prints :meth:`summary` after the run so silent
    degradation is observable; tests assert on :meth:`events`."""

    def __init__(self):
        self._events: List[dict] = []

    def add(self, kind: str, **info) -> dict:
        ev = dict(kind=kind, ts=time.time(), **info)
        self._events.append(ev)
        return ev

    def events(self, kind: Optional[str] = None) -> List[dict]:
        if kind is None:
            return list(self._events)
        return [e for e in self._events if e["kind"] == kind]

    def clear(self) -> None:
        self._events.clear()

    def summary(self) -> List[str]:
        """Human-readable lines, one per noteworthy event (retries are
        aggregated — their details matter for debugging, not reporting)."""
        lines = []
        retries = self.events("transient_retry")
        if retries:
            lines.append(f"  {len(retries)} transient failure(s) retried "
                         f"with backoff")
        for e in self.events("engine_demotion"):
            scope = (f"shape {e['shape_key']}" if e.get("shape_key")
                     else "this process")
            lines.append(f"  engine {e['engine']} demoted for {scope} "
                         f"({e['failure_class']}: {e['error'][:80]})")
        for e in self.events("checkpoint_recovery"):
            lines.append(f"  checkpoint {e['path']} was corrupt "
                         f"({e['error'][:80]}); {e['action']}")
        for e in self.events("probe_downgrade"):
            lines.append(f"  probe {e['state_key']}: {e['verdict']} "
                         f"(unproven — re-probed next process)")
        negatives = self.events("tuner_negative")
        if negatives:
            lines.append(f"  {len(negatives)} autotuner candidate(s) "
                         f"failed to measure (deterministic failures "
                         f"recorded as negative plan-cache entries)")
        for e in self.events("tuner_degraded"):
            lines.append(f"  autotuner: no measurable candidate for "
                         f"mode {e['mode']} — dispatch keeps the "
                         f"heuristic chain")
        return lines


_REPORT = RunReport()


def run_report() -> RunReport:
    """The process-wide resilience event log."""
    return _REPORT
